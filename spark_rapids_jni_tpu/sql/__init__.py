"""SQL front-end: text → ``plan/ir.py`` trees → the whole engine.

A first-party recursive-descent parser (``sql/parser.py`` documents the
grammar) binds against catalog schemas (``sql/binder.py``) and emits the
same IR the hand-built plan trees use, so a SQL-born query flows
unchanged through rule optimization, lowering, the exec scheduler, the
plan cache, AOT artifacts, AQE, and profiling — keyed on the same
structural fingerprint as an equivalently-shaped hand-built tree.

Entry points:

* :func:`parse` — text → AST (:class:`SqlError` with caret on failure).
* :func:`sql_to_plan` — text → **optimized** IR tree, memoized per
  (text, params, schema) under ``SRJT_SQL_CACHE`` so a warm repeat
  submission skips parse+bind+optimize entirely.
* :func:`compile_sql` — text → ``qfn(tables) -> Table`` (the scheduler/
  plan-cache callable shape, fingerprint attached).
* :func:`to_sql` — AST → SQL text (round-trip stable).

Every failed parse/bind on the serving surface records a
``sql_parse_error`` flight incident (ring event + counter) carrying the
line/column, so malformed client queries are diagnosable post-hoc.
"""

from __future__ import annotations

from collections import OrderedDict
from threading import Lock
from typing import Any, Dict, Optional, Sequence

from ..plan import ir, lower, rules
from ..utils import flight, knobs, metrics
from .binder import bind
from .parser import Query, parse, to_sql
from .tokenizer import SqlError

__all__ = ["SqlError", "parse", "to_sql", "bind", "sql_to_plan",
           "compile_sql", "cache_stats", "clear_cache"]


# --- parsed-plan memo -------------------------------------------------------

_memo: "OrderedDict[tuple, ir.Plan]" = OrderedDict()
_memo_lock = Lock()


def _schema_sig(schemas: Dict[str, Sequence[str]]) -> tuple:
    return tuple(sorted((t, tuple(cols)) for t, cols in schemas.items()))


def _params_sig(params: Optional[Dict[str, Any]]) -> tuple:
    if not params:
        return ()
    return tuple(sorted(params.items()))


def clear_cache() -> None:
    with _memo_lock:
        _memo.clear()


def cache_stats() -> dict:
    """Lifetime hit/miss counters of the SQL plan memo (metrics-backed,
    so they survive ``clear_cache``)."""
    return {"hit": metrics.counter_value("sql.cache.hit"),
            "miss": metrics.counter_value("sql.cache.miss"),
            "size": len(_memo)}


def _record_parse_error(e: SqlError, surface: str) -> None:
    flight.incident("sql_parse_error", surface=surface, line=e.line,
                    col=e.col, message=e.message[:200])


def sql_to_plan(text: str, schemas: Dict[str, Sequence[str]],
                params: Optional[Dict[str, Any]] = None, *,
                stats=None, optimize: bool = True) -> ir.Plan:
    """Parse + bind + (by default) rule-optimize ``text``.

    The result is memoized on ``(text, params, schemas)`` when
    ``SRJT_SQL_CACHE`` is on — a warm hit returns the previously
    optimized tree with zero parse work, which is what makes
    ``submit_sql`` amortized-free against pre-built plan trees (the
    plan-cache fingerprint dedupes the compile).  Parse/bind failures
    raise :class:`SqlError` and record a ``sql_parse_error`` incident."""
    if len(text) > knobs.get("SRJT_SQL_MAX_LEN"):
        e = SqlError(f"query text of {len(text)} chars exceeds "
                     f"SRJT_SQL_MAX_LEN", text[:80], 1, 1)
        _record_parse_error(e, "sql_to_plan")
        raise e
    use_memo = bool(knobs.get("SRJT_SQL_CACHE")) and stats is None
    key = None
    if use_memo:
        key = (text, _params_sig(params), _schema_sig(schemas), optimize)
        with _memo_lock:
            got = _memo.get(key)
            if got is not None:
                _memo.move_to_end(key)
                metrics.count("sql.cache.hit")
                return got
        metrics.count("sql.cache.miss")
    try:
        with metrics.span("sql.parse"):
            tree = bind(parse(text), schemas, params, text)
    except SqlError as e:
        _record_parse_error(e, "sql_to_plan")
        raise
    if optimize:
        tree = rules.optimize(tree, schemas, stats=stats).tree
    else:
        ir.schema_of(tree, schemas)      # validate even when not rewriting
    if use_memo:
        with _memo_lock:
            _memo[key] = tree
            _memo.move_to_end(key)
            cap = knobs.get("SRJT_SQL_CACHE_CAP")
            while len(_memo) > cap:
                _memo.popitem(last=False)
    return tree


def compile_sql(text: str, schemas: Dict[str, Sequence[str]],
                params: Optional[Dict[str, Any]] = None, *, stats=None):
    """SQL text → ``qfn(tables: dict[str, Table]) -> Table`` with
    ``.plan_tree`` / ``.plan_fingerprint`` / ``.plan_output_names``
    attached — drop-in wherever a compiled plan tree goes (scheduler
    submission, plan cache, AOT store)."""
    tree = sql_to_plan(text, schemas, params, stats=stats)
    return lower.compile_plan(tree, schemas)
