"""SQL tokenizer: text → positioned tokens.

Small by design — the grammar the parser implements (see ``sql/parser.py``)
needs identifiers, numbers, single-quoted strings, ``:name`` parameters,
a dozen operators, and ``--`` comments.  Every token carries its 1-based
``(line, col)`` so parse- and bind-errors render a caret pointing at the
offending character (:class:`SqlError`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List


class SqlError(ValueError):
    """Malformed SQL: tokenizer/parser/binder errors, with the 1-based
    source position and a rendered caret line for diagnostics."""

    def __init__(self, message: str, text: str = "", line: int = 1,
                 col: int = 1):
        self.message = message
        self.text = text
        self.line = line
        self.col = col
        super().__init__(self._render())

    def _render(self) -> str:
        lines = self.text.splitlines()
        if not self.text or not (1 <= self.line <= len(lines)):
            return f"{self.message} (line {self.line}, column {self.col})"
        src = lines[self.line - 1]
        caret = " " * (self.col - 1) + "^"
        return (f"{self.message}\n"
                f"  line {self.line}, column {self.col}:\n"
                f"    {src}\n"
                f"    {caret}")


# token kinds
IDENT = "IDENT"      # bare word (keywords are IDENTs; the parser matches)
NUMBER = "NUMBER"    # value is the parsed int/float
STRING = "STRING"    # value is the unquoted str
PARAM = "PARAM"      # :name — value is the bare name
OP = "OP"            # punctuation/operator, value is the symbol
EOF = "EOF"


@dataclass(frozen=True)
class Token:
    kind: str
    value: Any
    line: int
    col: int

    @property
    def upper(self) -> str:
        return self.value.upper() if isinstance(self.value, str) else ""


_TWO_CHAR = ("<=", ">=", "<>", "!=")
_ONE_CHAR = set("()[],.;*=<>+-/")


def tokenize(text: str) -> List[Token]:
    """Tokenize ``text``; raises :class:`SqlError` (with caret) on a
    character the grammar has no use for or an unterminated string."""
    toks: List[Token] = []
    i, line, bol = 0, 1, 0          # bol = offset of current line start
    n = len(text)
    while i < n:
        ch = text[i]
        col = i - bol + 1
        if ch == "\n":
            i += 1
            line += 1
            bol = i
            continue
        if ch in " \t\r":
            i += 1
            continue
        if text.startswith("--", i):
            while i < n and text[i] != "\n":
                i += 1
            continue
        if ch.isalpha() or ch == "_":
            j = i
            while j < n and (text[j].isalnum() or text[j] == "_"):
                j += 1
            toks.append(Token(IDENT, text[i:j], line, col))
            i = j
            continue
        if ch.isdigit():
            j = i
            while j < n and (text[j].isdigit() or text[j] == "."):
                j += 1
            raw = text[i:j]
            try:
                value = float(raw) if "." in raw else int(raw)
            except ValueError:
                raise SqlError(f"bad numeric literal {raw!r}", text,
                               line, col)
            toks.append(Token(NUMBER, value, line, col))
            i = j
            continue
        if ch == "'":
            j = i + 1
            while j < n and text[j] != "'":
                if text[j] == "\n":
                    break
                j += 1
            if j >= n or text[j] != "'":
                raise SqlError("unterminated string literal", text,
                               line, col)
            toks.append(Token(STRING, text[i + 1:j], line, col))
            i = j + 1
            continue
        if ch == ":":
            j = i + 1
            while j < n and (text[j].isalnum() or text[j] == "_"):
                j += 1
            if j == i + 1:
                raise SqlError("expected parameter name after ':'", text,
                               line, col)
            toks.append(Token(PARAM, text[i + 1:j], line, col))
            i = j
            continue
        if text[i:i + 2] in _TWO_CHAR:
            toks.append(Token(OP, text[i:i + 2], line, col))
            i += 2
            continue
        if ch in _ONE_CHAR:
            toks.append(Token(OP, ch, line, col))
            i += 1
            continue
        raise SqlError(f"unexpected character {ch!r}", text, line, col)
    toks.append(Token(EOF, None, line, (n - bol) + 1))
    return toks
