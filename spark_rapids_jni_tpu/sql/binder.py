"""Name resolution + plan construction: SQL AST → ``plan/ir.py`` trees.

The binder resolves every column reference against the catalog schemas
(qualified ``alias.col`` refs through the FROM/JOIN alias frames,
unqualified refs by uniqueness — ambiguity is an error), substitutes
named parameters, and emits exactly the IR shapes the hand-built plan
trees use, so a SQL-born tree and its hand-built equivalent share one
structural fingerprint (and therefore one plan-cache/AOT entry).

Logical binding order inside one SELECT (the SQL standard's):
FROM/JOIN → WHERE → GROUP BY/aggregates → HAVING → window functions →
SELECT projection → DISTINCT → ORDER BY → LIMIT.

Deliberate dialect limits (kept loud — each raises :class:`SqlError`):

* plain columns may only be aliased in UNION ALL arms and derived
  tables feeding a UNION (the IR renames positionally at ``Union``);
* aggregates require GROUP BY (no whole-table scalar aggregates);
* ``COUNT(DISTINCT x)`` must be the only aggregate of its SELECT;
* scalar expressions in WHERE/HAVING compare a column against a
  literal/parameter, or (HAVING) an aggregate-of-output-column times an
  optional literal — the ``ir.ScalarAgg`` device-scalar shape.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..plan import ir
from . import parser as ast
from .tokenizer import SqlError

_HOW = {"inner", "left", "semi", "anti"}


class _Frame:
    """One FROM/JOIN input: its alias (may be None) and output names."""

    def __init__(self, alias: Optional[str], names: Sequence[str]):
        self.alias = alias
        self.names = list(names)


class _Binder:
    def __init__(self, schemas: Dict[str, Sequence[str]],
                 params: Optional[Dict[str, Any]], text: str):
        self.schemas = schemas
        self.params = params or {}
        self.text = text

    def _err(self, message: str, pos: Tuple[int, int]):
        raise SqlError(message, self.text, pos[0], pos[1])

    # . reference resolution .................................................

    def resolve(self, c: ast.ColRef, frames: List[_Frame]) -> str:
        if c.qual is not None:
            for f in frames:
                if f.alias == c.qual:
                    if c.name not in f.names:
                        self._err(f"unknown column {c.name!r} in "
                                  f"{c.qual!r} (has {f.names})", c.pos)
                    return c.name
            self._err(f"unknown table alias {c.qual!r}", c.pos)
        hits = sum(f.names.count(c.name) for f in frames)
        if hits == 0:
            have = [n for f in frames for n in f.names]
            self._err(f"unknown column {c.name!r} (have {have})", c.pos)
        if hits > 1:
            self._err(f"ambiguous column {c.name!r}: qualify it with a "
                      f"table alias", c.pos)
        return c.name

    def param_value(self, v: ast.Value):
        if v.param is None:
            return v.value
        if v.param not in self.params:
            self._err(f"unbound parameter :{v.param}", v.pos)
        return self.params[v.param]

    # . predicate binding ....................................................

    def bind_scalar(self, e: ast.Node, frames: List[_Frame]) -> ir.Expr:
        if isinstance(e, ast.Value):
            return ir.Lit(self.param_value(e))
        if isinstance(e, ast.AggFunc):
            if e.fn not in ("mean", "sum"):
                self._err(f"only AVG/SUM usable as scalar aggregates "
                          f"(got {e.fn})", e.pos)
            return ir.ScalarAgg(e.fn,
                                ir.Col(self.resolve(e.arg, frames)))
        if isinstance(e, ast.MulOp):
            return ir.Mul(self.bind_scalar(e.left, frames),
                          self.bind_scalar(e.right, frames))
        raise SqlError(f"unsupported scalar {type(e).__name__}")

    def bind_pred(self, p: ast.Node, frames: List[_Frame]) -> ir.Expr:
        if isinstance(p, ast.AndPred):
            return ir.And(tuple(self.bind_pred(x, frames)
                                for x in p.parts))
        if isinstance(p, ast.OrPred):
            return ir.Or(tuple(self.bind_pred(x, frames)
                               for x in p.parts))
        if isinstance(p, ast.Cmp):
            return ir.Cmp(p.op, ir.Col(self.resolve(p.left, frames)),
                          self.bind_scalar(p.right, frames))
        if isinstance(p, ast.BetweenPred):
            return ir.Between(ir.Col(self.resolve(p.col, frames)),
                              lo=self.param_value(p.lo),
                              hi=self.param_value(p.hi))
        if isinstance(p, ast.InPred):
            return ir.IsIn(ir.Col(self.resolve(p.col, frames)),
                           tuple(self.param_value(v) for v in p.values))
        raise SqlError(f"unsupported predicate {type(p).__name__}")

    # . FROM / JOIN ..........................................................

    def bind_table(self, tr: ast.TableRef) -> Tuple[ir.Plan, List[str]]:
        if tr.subquery is not None:
            return self.bind_query(tr.subquery)
        if tr.name not in self.schemas:
            self._err(f"unknown table {tr.name!r} "
                      f"(catalog: {sorted(self.schemas)})", tr.pos)
        return ir.Scan(tr.name), list(self.schemas[tr.name])

    def _on_sides(self, a: ast.ColRef, b: ast.ColRef,
                  left: List[_Frame], right: _Frame) -> Tuple[str, str]:
        """Resolve one ``ON x = y`` pair to (left key, right key),
        accepting either written order."""
        def side_of(c: ast.ColRef) -> Optional[str]:
            if c.qual is not None:
                if right.alias == c.qual:
                    return "r"
                if any(f.alias == c.qual for f in left):
                    return "l"
                return None
            in_l = any(c.name in f.names for f in left)
            in_r = c.name in right.names
            if in_l and in_r:
                self._err(f"ambiguous join key {c.name!r}: qualify it",
                          c.pos)
            return "l" if in_l else ("r" if in_r else None)

        sa, sb = side_of(a), side_of(b)
        if sa == "l" and sb == "r":
            lref, rref = a, b
        elif sa == "r" and sb == "l":
            lref, rref = b, a
        else:
            bad = a if sa is None else b
            self._err(f"join key {bad.name!r} matches neither side",
                      bad.pos)
        lk = self.resolve(lref, left)
        rk = self.resolve(rref, [right])
        return lk, rk

    # . one SELECT ...........................................................

    def bind_select(self, sel: ast.Select,
                    union_arm: bool = False
                    ) -> Tuple[ir.Plan, List[str], List[str]]:
        """Returns ``(plan, names, aliases)`` — ``aliases`` is the output
        name per position as the SELECT list wrote it (used by UNION ALL
        to name the concatenated columns)."""
        plan, names = self.bind_table(sel.table)
        frames = [_Frame(sel.table.alias, names)]

        for j in sel.joins:
            rplan, rnames = self.bind_table(j.table)
            rframe = _Frame(j.table.alias, rnames)
            lks, rks = [], []
            for a, b in j.on:
                lk, rk = self._on_sides(a, b, frames, rframe)
                lks.append(lk)
                rks.append(rk)
            if j.how not in _HOW:
                self._err(f"unsupported join type {j.how!r}", j.pos)
            plan = ir.Join(plan, rplan, tuple(lks), tuple(rks), how=j.how)
            if j.how in ("semi", "anti"):
                continue             # right side filters; never lands
            dup = set(n for f in frames for n in f.names) & set(rnames)
            if dup:
                self._err(f"join sides share column names {sorted(dup)}",
                          j.pos)
            frames.append(rframe)

        if sel.where is not None:
            plan = ir.Filter(plan, self.bind_pred(sel.where, frames))

        # classify the select list
        plain: List[ast.SelectItem] = []
        aggs: List[ast.SelectItem] = []
        wins: List[ast.SelectItem] = []
        star = None
        for it in sel.items:
            if isinstance(it.expr, ast.Star):
                star = it
            elif isinstance(it.expr, ast.AggFunc):
                aggs.append(it)
            elif isinstance(it.expr, ast.WinFunc):
                wins.append(it)
            elif isinstance(it.expr, ast.ColRef):
                plain.append(it)
            else:
                self._err("unsupported select expression", it.pos)

        plain_resolved: Dict[int, str] = {}
        if sel.group is not None:
            plan, frames, plain_resolved = self._bind_group(
                sel, plain, aggs, frames, plan)
        elif aggs:
            self._err("aggregates require GROUP BY (whole-table scalar "
                      "aggregates are unsupported)", aggs[0].pos)

        if sel.having is not None:
            plan = ir.Filter(plan, self.bind_pred(sel.having, frames))

        for it in wins:
            plan, frames = self._bind_window(it, frames, plan)

        cur = [n for f in frames for n in f.names]

        # final projection, in select-list order
        if star is not None:
            if len(sel.items) != 1:
                self._err("'*' cannot mix with other select items",
                          star.pos)
            out_names, out_aliases = list(cur), list(cur)
        else:
            out_names, out_aliases = [], []
            for it in sel.items:
                if isinstance(it.expr, ast.ColRef):
                    name = (plain_resolved.get(id(it))
                            or self.resolve(it.expr, frames))
                    if (it.alias is not None and it.alias != name
                            and not union_arm):
                        self._err(
                            f"renaming column {name!r} is only supported "
                            f"in UNION ALL arms", it.pos)
                    out_names.append(name)
                    out_aliases.append(it.alias or name)
                else:
                    # agg/window outputs were named when they were bound
                    name = self._out_name(it)
                    out_names.append(name)
                    out_aliases.append(name)
            if out_names != cur:
                plan = ir.Project(plan, tuple(out_names))

        if sel.distinct:
            plan = ir.Distinct(plan)

        if sel.order:
            keys, asc = [], []
            for name, ascending, pos in sel.order:
                if name not in out_names:
                    self._err(f"ORDER BY column {name!r} is not in the "
                              f"select list ({out_names})", pos)
                keys.append(name)
                asc.append(ascending)
            plan = ir.Sort(plan, tuple(keys),
                           None if all(asc) else tuple(asc))

        if sel.limit is not None:
            plan = ir.Limit(plan, sel.limit)
        return plan, out_names, out_aliases

    @staticmethod
    def _out_name(it: ast.SelectItem) -> str:
        if it.alias:
            return it.alias
        e = it.expr
        if isinstance(e, ast.AggFunc):
            return f"{e.fn}_{e.arg.name}"
        return e.fn                      # window fn without alias

    def _bind_group(self, sel: ast.Select, plain, aggs, frames, plan):
        g = sel.group
        keys = tuple(self.resolve(c, frames) for c in g.cols)
        # every plain select item must be a grouping key
        keyset = set(keys) | ({ir.GROUPING_ID} if g.kind != "plain"
                              else set())
        # remember each plain item's pre-aggregate resolution: qualifiers
        # don't survive into the post-aggregate frame, but SELECT
        # i.k ... GROUP BY i.k must still project the key
        resolved: Dict[int, str] = {}
        for it in plain:
            if (g.kind != "plain" and it.expr.qual is None
                    and it.expr.name == ir.GROUPING_ID):
                resolved[id(it)] = ir.GROUPING_ID
                continue     # synthesized by the grouping spec itself
            name = self.resolve(it.expr, frames)
            if name not in keyset:
                self._err(f"column {name!r} must appear in GROUP BY "
                          f"or inside an aggregate", it.pos)
            resolved[id(it)] = name
        agg_specs = []
        for it in aggs:
            e = it.expr
            agg_specs.append((self.resolve(e.arg, frames), e.fn,
                              self._out_name(it)))
        nuniques = [a for a in agg_specs if a[1] == "nunique"]
        if nuniques and len(agg_specs) != 1:
            self._err("COUNT(DISTINCT x) must be the only aggregate",
                      aggs[0].pos)
        grouping = None
        grouping_sets = None
        if g.kind in ("rollup", "cube"):
            grouping = g.kind
        elif g.kind == "sets":
            grouping = "sets"
            index = {k: i for i, k in enumerate(keys)}
            grouping_sets = tuple(
                tuple(index[self.resolve(c, frames)] for c in s)
                for s in g.sets)
        plan = ir.Aggregate(plan, keys, tuple(agg_specs),
                            grouping=grouping, grouping_sets=grouping_sets)
        out = list(keys) + [a[2] for a in agg_specs]
        if grouping is not None:
            out.append(ir.GROUPING_ID)
        return plan, [_Frame(None, out)], resolved

    def _bind_window(self, it: ast.SelectItem, frames, plan):
        e: ast.WinFunc = it.expr
        part = tuple(self.resolve(c, frames) for c in e.partition)
        order = tuple(self.resolve(c, frames) for c, _a in e.order)
        asc = tuple(a for _c, a in e.order)
        value = (None if e.value is None
                 else self.resolve(e.value, frames))
        out = self._out_name(it)
        cur = [n for f in frames for n in f.names]
        if out in cur:
            self._err(f"window output name {out!r} collides with an "
                      f"input column; add AS <name>", it.pos)
        plan = ir.Window(plan, e.fn, part, order, out,
                         ascending=None if all(asc) else asc,
                         value=value)
        return plan, frames + [_Frame(None, [out])]

    # . query (UNION chain) ..................................................

    def bind_query(self, q: ast.Query) -> Tuple[ir.Plan, List[str]]:
        if len(q.selects) == 1:
            plan, names, aliases = self.bind_select(q.selects[0])
            # a lone select exposes alias-free physical names (aliases
            # only rename across a UNION)
            return plan, names
        arms = [self.bind_select(s, union_arm=True) for s in q.selects]
        names = arms[0][2]               # first arm's aliases name the union
        arity = len(names)
        for i, (_p, n, _a) in enumerate(arms):
            if len(n) != arity:
                raise SqlError(
                    f"UNION ALL arm {i} has {len(n)} columns, expected "
                    f"{arity}", self.text)
        return ir.Union(tuple(p for p, _n, _a in arms),
                        tuple(names)), list(names)


def bind(q: ast.Query, schemas: Dict[str, Sequence[str]],
         params: Optional[Dict[str, Any]] = None,
         text: str = "") -> ir.Plan:
    """Bind a parsed query against ``schemas`` (table → column names),
    substituting ``params`` for ``:name`` placeholders.  Returns the IR
    tree; every resolution failure is a :class:`SqlError` whose caret
    points at the offending token in ``text``."""
    plan, _names = _Binder(schemas, params, text).bind_query(q)
    return plan
