"""Recursive-descent SQL parser → a small AST (``sql/binder.py`` binds it
against a catalog schema into ``plan/ir.py`` trees).

Grammar (the supported dialect — see the README "SQL front-end" section
for semantics and limits)::

    query       := select (UNION ALL select)*
    select      := SELECT [DISTINCT] item (',' item)*
                   FROM table_ref join*
                   [WHERE pred] [GROUP BY group_spec] [HAVING pred]
                   [ORDER BY order_key (',' order_key)*] [LIMIT int]
    item        := '*' | column [AS? alias] | agg_fn [AS? alias]
                 | win_fn OVER '(' [PARTITION BY columns]
                                  [ORDER BY order_keys] ')' [AS? alias]
    table_ref   := name [AS? alias] | '(' query ')' [AS? alias]
    join        := [INNER | LEFT [OUTER] | LEFT SEMI | LEFT ANTI] JOIN
                   table_ref ON column '=' column (AND column '=' column)*
    group_spec  := columns | ROLLUP '(' columns ')' | CUBE '(' columns ')'
                 | GROUPING SETS '(' set (',' set)* ')'     set := '(' columns? ')'
    agg_fn      := (SUM|COUNT|AVG|MIN|MAX|STD|STDDEV|FIRST|LAST) '(' column ')'
                 | COUNT '(' DISTINCT column ')'
    win_fn      := (ROW_NUMBER|RANK|DENSE_RANK) '(' ')'
                 | (SUM|LAG|LEAD) '(' column ')'
    pred        := or_pred;  or_pred := and_pred (OR and_pred)*
    and_pred    := term (AND term)*
    term        := '(' pred ')' | column BETWEEN value AND value
                 | column [NOT] IN '(' value (',' value)* ')'
                 | column cmp scalar
    scalar      := scalar_term ('*' scalar_term)*
    scalar_term := value | agg_fn          -- agg only meaningful in HAVING
    value       := number | string | ':' name
    cmp         := '=' | '!=' | '<>' | '<' | '<=' | '>' | '>='

Keywords are case-insensitive; every AST node carries the 1-based
``(line, col)`` of its anchor token so the binder's errors point carets
at the offending name.  :func:`to_sql` renders an AST back to text that
re-parses to an equivalent AST (the round-trip tests pin this).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

from .tokenizer import (EOF, IDENT, NUMBER, OP, PARAM, STRING, SqlError,
                        Token, tokenize)

# words that terminate an implicit alias position
_RESERVED = {
    "SELECT", "DISTINCT", "FROM", "JOIN", "INNER", "LEFT", "OUTER", "SEMI",
    "ANTI", "ON", "WHERE", "GROUP", "BY", "HAVING", "ORDER", "LIMIT",
    "UNION", "ALL", "AND", "OR", "NOT", "IN", "BETWEEN", "AS", "ASC",
    "DESC", "OVER", "PARTITION", "ROLLUP", "CUBE", "GROUPING", "SETS",
}

_AGG_FNS = {"SUM": "sum", "COUNT": "count", "AVG": "mean", "MIN": "min",
            "MAX": "max", "STD": "std", "STDDEV": "std", "FIRST": "first",
            "LAST": "last"}
_WIN_NOARG = {"ROW_NUMBER": "row_number", "RANK": "rank",
              "DENSE_RANK": "dense_rank"}
_WIN_VALUE = {"SUM": "running_sum", "LAG": "lag", "LEAD": "lead"}


# --- AST --------------------------------------------------------------------


@dataclass(frozen=True)
class Node:
    pass


@dataclass(frozen=True)
class ColRef(Node):
    name: str
    qual: Optional[str] = None
    pos: Tuple[int, int] = (1, 1)

    def __str__(self):
        return f"{self.qual}.{self.name}" if self.qual else self.name


@dataclass(frozen=True)
class Value(Node):
    """Literal or named parameter (``param`` set)."""
    value: Any = None
    param: Optional[str] = None
    pos: Tuple[int, int] = (1, 1)


@dataclass(frozen=True)
class AggFunc(Node):
    fn: str                      # ops fn name (sum/mean/count/nunique/...)
    arg: ColRef = None
    pos: Tuple[int, int] = (1, 1)


@dataclass(frozen=True)
class WinFunc(Node):
    fn: str                      # row_number/rank/dense_rank/running_sum/...
    value: Optional[ColRef]
    partition: Tuple[ColRef, ...]
    order: Tuple[Tuple[ColRef, bool], ...]      # (col, ascending)
    pos: Tuple[int, int] = (1, 1)


@dataclass(frozen=True)
class Star(Node):
    pos: Tuple[int, int] = (1, 1)


@dataclass(frozen=True)
class Cmp(Node):
    op: str                      # == != < <= > >=
    left: ColRef = None
    right: Node = None           # Value | AggFunc | MulOp
    pos: Tuple[int, int] = (1, 1)


@dataclass(frozen=True)
class MulOp(Node):
    left: Node = None
    right: Node = None


@dataclass(frozen=True)
class BetweenPred(Node):
    col: ColRef = None
    lo: Value = None
    hi: Value = None


@dataclass(frozen=True)
class InPred(Node):
    col: ColRef = None
    values: Tuple[Value, ...] = ()


@dataclass(frozen=True)
class AndPred(Node):
    parts: Tuple[Node, ...] = ()


@dataclass(frozen=True)
class OrPred(Node):
    parts: Tuple[Node, ...] = ()


@dataclass(frozen=True)
class SelectItem(Node):
    expr: Node
    alias: Optional[str] = None
    pos: Tuple[int, int] = (1, 1)


@dataclass(frozen=True)
class TableRef(Node):
    name: Optional[str] = None          # base table ...
    subquery: Optional["Query"] = None  # ... or derived table
    alias: Optional[str] = None
    pos: Tuple[int, int] = (1, 1)


@dataclass(frozen=True)
class JoinClause(Node):
    how: str                            # inner/left/semi/anti
    table: TableRef = None
    on: Tuple[Tuple[ColRef, ColRef], ...] = ()
    pos: Tuple[int, int] = (1, 1)


@dataclass(frozen=True)
class GroupSpec(Node):
    kind: str                           # plain/rollup/cube/sets
    cols: Tuple[ColRef, ...] = ()
    sets: Optional[Tuple[Tuple[ColRef, ...], ...]] = None


@dataclass(frozen=True)
class Select(Node):
    items: Tuple[SelectItem, ...]
    table: TableRef
    joins: Tuple[JoinClause, ...] = ()
    distinct: bool = False
    where: Optional[Node] = None
    group: Optional[GroupSpec] = None
    having: Optional[Node] = None
    order: Tuple[Tuple[str, bool, Tuple[int, int]], ...] = ()
    limit: Optional[int] = None


@dataclass(frozen=True)
class Query(Node):
    """One SELECT, or a UNION ALL chain of them."""
    selects: Tuple[Select, ...]


# --- parser -----------------------------------------------------------------


class _Parser:
    def __init__(self, text: str):
        self.text = text
        self.toks = tokenize(text)
        self.i = 0

    # . cursor helpers ......................................................

    @property
    def tok(self) -> Token:
        return self.toks[self.i]

    def _err(self, message: str, tok: Optional[Token] = None):
        tok = tok or self.tok
        raise SqlError(message, self.text, tok.line, tok.col)

    def at_kw(self, *words: str) -> bool:
        t = self.tok
        return t.kind == IDENT and t.upper in words

    def take_kw(self, *words: str) -> bool:
        if self.at_kw(*words):
            self.i += 1
            return True
        return False

    def expect_kw(self, word: str) -> Token:
        if not self.at_kw(word):
            self._err(f"expected {word}")
        t = self.tok
        self.i += 1
        return t

    def at_op(self, *syms: str) -> bool:
        t = self.tok
        return t.kind == OP and t.value in syms

    def take_op(self, *syms: str) -> bool:
        if self.at_op(*syms):
            self.i += 1
            return True
        return False

    def expect_op(self, sym: str) -> Token:
        if not self.at_op(sym):
            self._err(f"expected {sym!r}")
        t = self.tok
        self.i += 1
        return t

    def ident(self, what: str = "identifier") -> Token:
        t = self.tok
        if t.kind != IDENT or t.upper in _RESERVED:
            self._err(f"expected {what}")
        self.i += 1
        return t

    # . grammar ..............................................................

    def query(self) -> Query:
        selects = [self.select()]
        while self.take_kw("UNION"):
            self.expect_kw("ALL")      # only UNION ALL (no dedup UNION)
            selects.append(self.select())
        return Query(tuple(selects))

    def select(self) -> Select:
        self.expect_kw("SELECT")
        distinct = self.take_kw("DISTINCT")
        items = [self.select_item()]
        while self.take_op(","):
            items.append(self.select_item())
        self.expect_kw("FROM")
        table = self.table_ref()
        joins = []
        while self.at_kw("JOIN", "INNER", "LEFT"):
            joins.append(self.join_clause())
        where = self.pred() if self.take_kw("WHERE") else None
        group = None
        if self.take_kw("GROUP"):
            self.expect_kw("BY")
            group = self.group_spec()
        having = self.pred() if self.take_kw("HAVING") else None
        order: List[Tuple[str, bool, Tuple[int, int]]] = []
        if self.take_kw("ORDER"):
            self.expect_kw("BY")
            order.append(self.order_key())
            while self.take_op(","):
                order.append(self.order_key())
        limit = None
        if self.take_kw("LIMIT"):
            t = self.tok
            if t.kind != NUMBER or not isinstance(t.value, int):
                self._err("expected integer LIMIT")
            limit = t.value
            self.i += 1
        return Select(tuple(items), table, tuple(joins), distinct, where,
                      group, having, tuple(order), limit)

    def select_item(self) -> SelectItem:
        t = self.tok
        if self.take_op("*"):
            return SelectItem(Star((t.line, t.col)), None, (t.line, t.col))
        expr = self.select_expr()
        alias = None
        if self.take_kw("AS"):
            alias = self.ident("alias").value
        elif self.tok.kind == IDENT and self.tok.upper not in _RESERVED:
            alias = self.ident("alias").value
        return SelectItem(expr, alias, (t.line, t.col))

    def select_expr(self) -> Node:
        t = self.tok
        if t.kind != IDENT:
            self._err("expected column or function")
        up = t.upper
        is_call = (self.toks[self.i + 1].kind == OP
                   and self.toks[self.i + 1].value == "(")
        if is_call and (up in _AGG_FNS or up in _WIN_NOARG
                        or up in _WIN_VALUE):
            return self.func_call()
        if up in _RESERVED:
            self._err("expected column or function")
        return self.colref()

    def func_call(self) -> Node:
        """``FN(...)`` — an aggregate, or (followed by OVER) a window."""
        t = self.tok
        up = t.upper
        self.i += 1
        self.expect_op("(")
        pos = (t.line, t.col)
        arg = None
        distinct_arg = False
        if not self.at_op(")"):
            distinct_arg = self.take_kw("DISTINCT")
            arg = self.colref()
        self.expect_op(")")
        if self.at_kw("OVER"):
            fn = _WIN_NOARG.get(up) or _WIN_VALUE.get(up)
            if fn is None:
                self._err(f"{t.value} is not a window function", t)
            if fn in _WIN_NOARG.values() and arg is not None:
                self._err(f"{t.value}() takes no argument", t)
            if fn in _WIN_VALUE.values() and arg is None:
                self._err(f"{t.value}(...) needs a value column", t)
            self.i += 1
            return self.over_clause(fn, arg, pos)
        if up not in _AGG_FNS:
            self._err(f"{t.value} is not an aggregate function", t)
        if arg is None:
            self._err(f"{t.value}(*) unsupported; name a column", t)
        fn = _AGG_FNS[up]
        if distinct_arg:
            if up != "COUNT":
                self._err("DISTINCT argument only for COUNT", t)
            fn = "nunique"
        return AggFunc(fn, arg, pos)

    def over_clause(self, fn: str, value: Optional[ColRef],
                    pos) -> WinFunc:
        self.expect_op("(")
        partition: List[ColRef] = []
        order: List[Tuple[ColRef, bool]] = []
        if self.take_kw("PARTITION"):
            self.expect_kw("BY")
            partition.append(self.colref())
            while self.take_op(","):
                partition.append(self.colref())
        if self.take_kw("ORDER"):
            self.expect_kw("BY")
            while True:
                c = self.colref()
                asc = True
                if self.take_kw("DESC"):
                    asc = False
                else:
                    self.take_kw("ASC")
                order.append((c, asc))
                if not self.take_op(","):
                    break
        self.expect_op(")")
        return WinFunc(fn, value, tuple(partition), tuple(order), pos)

    def colref(self) -> ColRef:
        t = self.ident("column")
        if self.take_op("."):
            t2 = self.ident("column")
            return ColRef(t2.value, t.value, (t2.line, t2.col))
        return ColRef(t.value, None, (t.line, t.col))

    def table_ref(self) -> TableRef:
        t = self.tok
        if self.take_op("("):
            sub = self.query()
            self.expect_op(")")
            alias = self._opt_alias()
            return TableRef(None, sub, alias, (t.line, t.col))
        name = self.ident("table name")
        return TableRef(name.value, None, self._opt_alias(),
                        (name.line, name.col))

    def _opt_alias(self) -> Optional[str]:
        if self.take_kw("AS"):
            return self.ident("alias").value
        if self.tok.kind == IDENT and self.tok.upper not in _RESERVED:
            return self.ident("alias").value
        return None

    def join_clause(self) -> JoinClause:
        t = self.tok
        how = "inner"
        if self.take_kw("INNER"):
            pass
        elif self.take_kw("LEFT"):
            if self.take_kw("SEMI"):
                how = "semi"
            elif self.take_kw("ANTI"):
                how = "anti"
            else:
                self.take_kw("OUTER")
                how = "left"
        self.expect_kw("JOIN")
        table = self.table_ref()
        self.expect_kw("ON")
        on = [self._on_pair()]
        while self.take_kw("AND"):
            on.append(self._on_pair())
        return JoinClause(how, table, tuple(on), (t.line, t.col))

    def _on_pair(self) -> Tuple[ColRef, ColRef]:
        a = self.colref()
        self.expect_op("=")
        return a, self.colref()

    def group_spec(self) -> GroupSpec:
        if self.take_kw("ROLLUP"):
            return GroupSpec("rollup", self._paren_cols())
        if self.take_kw("CUBE"):
            return GroupSpec("cube", self._paren_cols())
        if self.take_kw("GROUPING"):
            self.expect_kw("SETS")
            self.expect_op("(")
            sets = [self._paren_cols(allow_empty=True)]
            while self.take_op(","):
                sets.append(self._paren_cols(allow_empty=True))
            self.expect_op(")")
            # keys = first appearance order across the sets
            cols: List[ColRef] = []
            seen = set()
            for s in sets:
                for c in s:
                    if str(c) not in seen:
                        seen.add(str(c))
                        cols.append(c)
            return GroupSpec("sets", tuple(cols), tuple(sets))
        cols = [self.colref()]
        while self.take_op(","):
            cols.append(self.colref())
        return GroupSpec("plain", tuple(cols))

    def _paren_cols(self, allow_empty: bool = False) -> Tuple[ColRef, ...]:
        self.expect_op("(")
        cols: List[ColRef] = []
        if not self.at_op(")"):
            cols.append(self.colref())
            while self.take_op(","):
                cols.append(self.colref())
        if not cols and not allow_empty:
            self._err("expected column list")
        self.expect_op(")")
        return tuple(cols)

    def order_key(self) -> Tuple[str, bool, Tuple[int, int]]:
        # a qualifier is accepted but dropped: ORDER BY binds against the
        # select list's output names, which never carry one
        c = self.colref()
        asc = True
        if self.take_kw("DESC"):
            asc = False
        else:
            self.take_kw("ASC")
        return c.name, asc, c.pos

    # . predicates ...........................................................

    def pred(self) -> Node:
        parts = [self.and_pred()]
        while self.take_kw("OR"):
            parts.append(self.and_pred())
        return parts[0] if len(parts) == 1 else OrPred(tuple(parts))

    def and_pred(self) -> Node:
        parts = [self.pred_term()]
        while self.take_kw("AND"):
            parts.append(self.pred_term())
        return parts[0] if len(parts) == 1 else AndPred(tuple(parts))

    def pred_term(self) -> Node:
        if self.take_op("("):
            p = self.pred()
            self.expect_op(")")
            return p
        col = self.colref()
        if self.take_kw("BETWEEN"):
            lo = self.value()
            self.expect_kw("AND")
            return BetweenPred(col, lo, self.value())
        if self.take_kw("IN"):
            self.expect_op("(")
            vals = [self.value()]
            while self.take_op(","):
                vals.append(self.value())
            self.expect_op(")")
            return InPred(col, tuple(vals))
        t = self.tok
        if not self.at_op("=", "!=", "<>", "<", "<=", ">", ">="):
            self._err("expected comparison operator")
        self.i += 1
        op = {"=": "==", "<>": "!="}.get(t.value, t.value)
        return Cmp(op, col, self.scalar(), (t.line, t.col))

    def scalar(self) -> Node:
        left = self.scalar_term()
        while self.take_op("*"):
            left = MulOp(left, self.scalar_term())
        return left

    def scalar_term(self) -> Node:
        t = self.tok
        if t.kind in (NUMBER, STRING, PARAM):
            return self.value()
        if (t.kind == IDENT and t.upper in _AGG_FNS
                and self.toks[self.i + 1].kind == OP
                and self.toks[self.i + 1].value == "("):
            fn = self.func_call()
            if not isinstance(fn, AggFunc):
                self._err("window function not allowed here", t)
            return fn
        self._err("expected literal, :param, or aggregate")

    def value(self) -> Value:
        t = self.tok
        if t.kind == NUMBER or t.kind == STRING:
            self.i += 1
            return Value(t.value, None, (t.line, t.col))
        if t.kind == PARAM:
            self.i += 1
            return Value(None, t.value, (t.line, t.col))
        self._err("expected literal or :param")


def parse(text: str) -> Query:
    """Parse ``text`` into a :class:`Query` AST; :class:`SqlError` (with
    source caret) on any syntax error, including trailing garbage."""
    p = _Parser(text)
    q = p.query()
    p.take_op(";")
    if p.tok.kind != EOF:
        p._err("unexpected trailing input")
    return q


# --- rendering (AST → SQL text) ---------------------------------------------


def _render_value(v: Value) -> str:
    if v.param is not None:
        return f":{v.param}"
    if isinstance(v.value, str):
        return "'" + v.value + "'"
    return repr(v.value)


def _render_scalar(e: Node) -> str:
    if isinstance(e, Value):
        return _render_value(e)
    if isinstance(e, AggFunc):
        if e.fn == "nunique":
            return f"COUNT(DISTINCT {e.arg})"
        up = {v: k for k, v in _AGG_FNS.items()}
        return f"{up[e.fn]}({e.arg})"
    if isinstance(e, MulOp):
        return f"{_render_scalar(e.left)} * {_render_scalar(e.right)}"
    raise SqlError(f"unrenderable scalar {type(e).__name__}")


def _render_pred(p: Node) -> str:
    if isinstance(p, Cmp):
        op = {"==": "=", "!=": "<>"}.get(p.op, p.op)
        return f"{p.left} {op} {_render_scalar(p.right)}"
    if isinstance(p, BetweenPred):
        return (f"{p.col} BETWEEN {_render_value(p.lo)} "
                f"AND {_render_value(p.hi)}")
    if isinstance(p, InPred):
        return (f"{p.col} IN ("
                + ", ".join(_render_value(v) for v in p.values) + ")")
    if isinstance(p, AndPred):
        return " AND ".join(
            f"({_render_pred(x)})" if isinstance(x, OrPred)
            else _render_pred(x) for x in p.parts)
    if isinstance(p, OrPred):
        return "(" + " OR ".join(
            f"({_render_pred(x)})" if isinstance(x, (AndPred, OrPred))
            else _render_pred(x) for x in p.parts) + ")"
    raise SqlError(f"unrenderable predicate {type(p).__name__}")


def _render_item(it: SelectItem) -> str:
    e = it.expr
    if isinstance(e, Star):
        return "*"
    if isinstance(e, ColRef):
        body = str(e)
    elif isinstance(e, AggFunc):
        body = _render_scalar(e)
    elif isinstance(e, WinFunc):
        noarg = {v: k for k, v in _WIN_NOARG.items()}
        if e.fn in noarg:
            head = f"{noarg[e.fn]}()"
        else:
            byval = {v: k for k, v in _WIN_VALUE.items()}
            head = f"{byval[e.fn]}({e.value})"
        inner = []
        if e.partition:
            inner.append("PARTITION BY "
                         + ", ".join(str(c) for c in e.partition))
        if e.order:
            inner.append("ORDER BY " + ", ".join(
                f"{c}" + ("" if asc else " DESC") for c, asc in e.order))
        body = f"{head} OVER ({' '.join(inner)})"
    else:
        raise SqlError(f"unrenderable select item {type(e).__name__}")
    return body + (f" AS {it.alias}" if it.alias else "")


def _render_table(tr: TableRef) -> str:
    body = tr.name if tr.subquery is None else f"({to_sql(tr.subquery)})"
    return body + (f" AS {tr.alias}" if tr.alias else "")


def _render_select(s: Select) -> str:
    parts = ["SELECT " + ("DISTINCT " if s.distinct else "")
             + ", ".join(_render_item(it) for it in s.items),
             "FROM " + _render_table(s.table)]
    for j in s.joins:
        kw = {"inner": "JOIN", "left": "LEFT JOIN",
              "semi": "LEFT SEMI JOIN", "anti": "LEFT ANTI JOIN"}[j.how]
        on = " AND ".join(f"{a} = {b}" for a, b in j.on)
        parts.append(f"{kw} {_render_table(j.table)} ON {on}")
    if s.where is not None:
        parts.append("WHERE " + _render_pred(s.where))
    if s.group is not None:
        g = s.group
        if g.kind == "plain":
            body = ", ".join(str(c) for c in g.cols)
        elif g.kind == "sets":
            body = ("GROUPING SETS ("
                    + ", ".join("(" + ", ".join(str(c) for c in st) + ")"
                                for st in g.sets) + ")")
        else:
            body = (g.kind.upper() + "("
                    + ", ".join(str(c) for c in g.cols) + ")")
        parts.append("GROUP BY " + body)
    if s.having is not None:
        parts.append("HAVING " + _render_pred(s.having))
    if s.order:
        parts.append("ORDER BY " + ", ".join(
            name + ("" if asc else " DESC") for name, asc, _pos in s.order))
    if s.limit is not None:
        parts.append(f"LIMIT {s.limit}")
    return "\n".join(parts)


def to_sql(q: Query) -> str:
    """Render an AST back to SQL text (parse → to_sql → parse is stable:
    the re-parsed AST binds to a fingerprint-identical plan tree)."""
    return "\nUNION ALL\n".join(_render_select(s) for s in q.selects)
