"""Equi-joins (libcudf hash-join analog, join engine v2).

TPU-first design choice: libcudf joins via GPU hash tables (open addressing,
random scatter) — a poor fit for the VPU/MXU.  Engine v2 probes through a
planner-selected build-side index (``ops.join_plan``):

* **dense direct lookup** — for dense integer key ranges (TPC-DS surrogate
  keys) a ``(span,)`` CSR lookup table turns each probe into one gather;
  with unique build keys the pair-expansion step is skipped entirely.
* **sort-probe** — the fallback for sparse/float/string keys: sort the
  build side once, binary-search every probe key (``searchsorted`` lowers
  to a vectorized compare tree).

Both index kinds return identical (lo, counts, row_ids) probe results, so
this module's match-expansion tail — the only dynamically-sized step, its
total resolved with one scalar sync per the two-phase discipline — is
shared, and the engines produce bit-identical join indices.  Build-side
indexes are cached on column-buffer identity (``join_plan.build_index``).

Join keys: any fixed-width column, or a LIST of key columns (multi-column
equi-join — tuple equality, a null in ANY key column never matches).
Multi-column keys are planned by ``join_plan.plan_keys``: dense-eligible
tuples range-compress into one int64 composite riding the single-key
engines unchanged; everything else probes on a 64-bit fingerprint and this
module verifies true lane equality on the candidate pairs.
"""

from __future__ import annotations

from typing import Literal, Sequence, Union

import jax.numpy as jnp
import numpy as np

from ..column import Column, Table
from ..memory import arena
from ..memory.budget import PAIR_EXPANSION_BYTES
from ..utils import metrics, syncs
from .filter import gather, sized_nonzero

JoinKey = Union[Column, Sequence[Column]]
OnKey = Union[int, Sequence[int]]


def _key_with_nulls_last(col: Column):
    """Key lane where null rows are moved past any real key (never match)."""
    if col.dtype.id.name == "FLOAT64":
        # Compare the stored bit pattern, not decoded values: on TPU
        # ``from_bits`` carries ~48 mantissa bits, so two distinct doubles
        # can decode equal.  The canonicalized (-0.0 == 0.0, all NaNs one
        # value — Spark join equality) monotone bits→uint map keeps both
        # order and equality exact with zero f64 arithmetic.
        from ..utils.f64bits import ordered_key_u64
        return ordered_key_u64(col.data), col.validity
    data = col.values()
    if col.validity is None:
        return data, None
    return data, col.validity


def _as_key_cols(key) -> list:
    return list(key) if isinstance(key, (list, tuple)) else [key]


def join_indices(left: JoinKey, right: JoinKey,
                 how: Literal["inner", "left", "semi", "anti"] = "inner"):
    """Compute (left_idx, right_idx) gather maps for an equi-join.

    Each side takes one key Column or an equal-length list of key columns
    (multi-column equi-join).  ``semi``/``anti`` return only left_idx.
    ``left`` outer marks unmatched rows with right_idx == -1 (callers
    null-fill on gather).
    """
    with metrics.span("join.indices", how=how):
        return _join_indices(_as_key_cols(left), _as_key_cols(right), how)


def _join_indices(lcols: list, rcols: list, how: str):
    from . import join_plan

    # plan the probe lanes (string encode / composite pack / fingerprint),
    # then index the build (right) side — planner-selected layout, memoized
    # on the key buffers' identity; null build keys are dropped outright
    plan = join_plan.plan_keys(lcols, rcols)
    ix = join_plan.build_index(plan.rdata, plan.rvalid, plan.dense_ok)
    if metrics.recording() and ix.max_run > 0:
        # hottest build key's row count — the AQE skew signal (free: the
        # dense uniqueness test already synced it)
        metrics.observe("join.build_index.max_run", ix.max_run)
    lo, counts = join_plan.probe_counts(ix, plan.ldata, plan.lvalid)
    nr = ix.row_ids.shape[0]

    if plan.verify:
        # hashed probe lane: counts are CANDIDATE counts — every output
        # below must reject fingerprint collisions first
        return _verified_join(plan, ix, lo, counts, how)
    ldata, lvalid = plan.ldata, plan.lvalid

    if how in ("semi", "anti"):
        # two-phase like every dynamic size (count sync → sized nonzero) so
        # the whole plan stays traceable under capture/replay
        m = (counts > 0) if how == "semi" else (counts == 0)
        k = syncs.scalar(jnp.sum(m))
        return sized_nonzero(m, k)

    if ix.unique and nr > 0:
        # unique build keys: each probe row matches ≤ 1 build row — no pair
        # expansion, the match mask IS the output
        pos = jnp.minimum(lo, nr - 1)
        if how == "inner":
            total = syncs.scalar(jnp.sum(counts))   # scalar sync (pair count)
            if metrics.recording():
                metrics.observe("join.match_rows", total)
            metrics.profile_op("join", engine=ix.kind, how=how,
                               match_rows=total, unique_build=True)
            left_idx = sized_nonzero(counts > 0, total)
            right_idx = ix.row_ids[pos[left_idx]]
            return left_idx, right_idx
        left_idx = jnp.arange(ldata.shape[0], dtype=jnp.int64)
        right_idx = jnp.where(counts > 0, ix.row_ids[pos], -1)
        return left_idx, right_idx

    if how == "left":
        # match count needs its own sync here (total below includes the
        # unmatched keep-one rows); unconditional so capture/replay tapes
        # never depend on metrics state
        matched_rows = syncs.scalar(jnp.sum(counts))
        out_counts = jnp.maximum(counts, 1)   # unmatched keep one row
    else:
        matched_rows = None
        out_counts = counts

    total = syncs.scalar(jnp.sum(out_counts))     # scalar sync (pair count)
    if metrics.recording():
        # the ephemeral pair-expansion buffer (~10× input on skewed keys)
        # is the HBM-arena pressure point — ROADMAP open item
        metrics.count("join.expand.calls")
        metrics.observe("join.expand.pair_elements", total)
        metrics.observe("join.match_rows",
                        total if matched_rows is None else matched_rows)
        metrics.annotate(expand_pairs=total)
    metrics.profile_op(
        "join", engine=ix.kind, how=how, expand_pairs=total,
        match_rows=total if matched_rows is None else matched_rows)
    # admission-control the ephemeral expansion working set (the int64
    # lanes + mask below) before XLA materializes it; under pressure this
    # spills LRU arena residents first (soft: an admitted query completes)
    with arena.reserve(total * PAIR_EXPANSION_BYTES, tag="join.expand"):
        starts = jnp.cumsum(out_counts) - out_counts
        pair_ids = jnp.arange(total, dtype=jnp.int64)
        # row of each output pair: inverse of starts (searchsorted right)
        left_idx = jnp.searchsorted(starts.astype(jnp.int64), pair_ids,
                                    side="right") - 1
        within = pair_ids - starts.astype(jnp.int64)[left_idx]
        matched = within < counts[left_idx]
        if nr == 0:
            right_idx = jnp.full(left_idx.shape, -1, dtype=jnp.int64)
        else:
            r_pos = lo[left_idx] + jnp.where(matched, within, 0)
            right_idx = jnp.where(
                matched, ix.row_ids[jnp.minimum(r_pos, nr - 1)], -1)
        return left_idx, right_idx


def _pair_candidates(ix, lo, counts):
    """Aligned (probe_row, build_row) candidate pairs from probe results —
    the shared inner-pair enumeration: unique-build rows come straight off
    the scatter LUT, everything else runs the arena-admitted searchsorted
    expansion."""
    nr = ix.row_ids.shape[0]
    total = syncs.scalar(jnp.sum(counts))         # scalar sync (pair count)
    if nr == 0 or total == 0:
        z = jnp.zeros(0, jnp.int64)
        return z, z
    if ix.unique:
        left_idx = sized_nonzero(counts > 0, total)
        right_idx = ix.row_ids[jnp.minimum(lo, nr - 1)[left_idx]]
        return left_idx, right_idx
    if metrics.recording():
        metrics.count("join.expand.calls")
        metrics.observe("join.expand.pair_elements", total)
    with arena.reserve(total * PAIR_EXPANSION_BYTES, tag="join.expand"):
        starts = (jnp.cumsum(counts) - counts).astype(jnp.int64)
        pair_ids = jnp.arange(total, dtype=jnp.int64)
        left_idx = jnp.searchsorted(starts, pair_ids, side="right") - 1
        within = pair_ids - starts[left_idx]
        r_pos = lo[left_idx].astype(jnp.int64) + within
        right_idx = ix.row_ids[jnp.minimum(r_pos, nr - 1)]
        return left_idx, right_idx


def _verified_join(plan, ix, lo, counts, how: str):
    """Fingerprint/fallback tail: enumerate candidate pairs on the hashed
    probe lane, then keep only pairs where EVERY true key lane matches —
    fingerprint collisions are rejected before any output is built."""
    li, ri = _pair_candidates(ix, lo, counts)
    eq = jnp.ones(li.shape[0], jnp.bool_)
    for ll, rl in plan.verify:
        eq = eq & (ll[li] == rl[ri])
    kept = syncs.scalar(jnp.sum(eq))         # scalar sync (verified pairs)
    if metrics.recording():
        metrics.count("join.verify.candidates", int(li.shape[0]))
        metrics.count("join.verify.collisions", int(li.shape[0]) - kept)
        if how in ("inner", "left"):
            metrics.observe("join.match_rows", kept)
    metrics.profile_op("join", engine=ix.kind, how=how,
                       candidates=int(li.shape[0]), match_rows=kept)
    sel = sized_nonzero(eq, kept)
    li, ri = li[sel], ri[sel]
    if how == "inner":
        return li, ri
    n = plan.ldata.shape[0]
    has = jnp.zeros(n, jnp.bool_).at[li].set(True)
    if how in ("semi", "anti"):
        m = has if how == "semi" else ~has
        k = syncs.scalar(jnp.sum(m))
        return sized_nonzero(m, k)
    # left outer: verified pairs plus one null-extended row per unmatched
    # probe row, restored to probe-row-major order (the expansion tail's
    # output order) by a stable sort on the left index
    miss = ~has
    nm = syncs.scalar(jnp.sum(miss))
    mi = sized_nonzero(miss, nm)
    left_idx = jnp.concatenate([li, mi])
    right_idx = jnp.concatenate([ri, jnp.full(nm, -1, jnp.int64)])
    order = jnp.argsort(left_idx, stable=True)
    return left_idx[order], right_idx[order]


def _key_of(t: Table, on: OnKey):
    return [t[i] for i in on] if isinstance(on, (list, tuple)) else t[on]


def inner_join(left: Table, right: Table, left_on: OnKey,
               right_on: OnKey) -> Table:
    """Inner equi-join; result columns = left columns ++ right columns.
    ``left_on``/``right_on``: one column index or equal-length lists."""
    li, ri = join_indices(_key_of(left, left_on), _key_of(right, right_on),
                          "inner")
    lt = gather(left, li)
    rt = gather(right, ri)
    return Table(list(lt.columns) + list(rt.columns))


def _empty_column(dt) -> Column:
    from .. import types as T
    if dt.id == T.TypeId.LIST:
        return Column(dt, arena.zeros(0, jnp.uint8), arena.zeros(1, jnp.int32),
                      None, [_empty_column(dt.children[0])])
    if dt.id == T.TypeId.STRUCT:
        return Column(dt, arena.zeros(0, jnp.uint8), None, None,
                      [_empty_column(f) for f in dt.children])
    if dt.is_variable_width:
        return Column(dt, arena.zeros(0, jnp.uint8), arena.zeros(1, jnp.int32))
    if dt.id == T.TypeId.DECIMAL128:
        return Column(dt, arena.zeros((0, 2), jnp.int64))
    if dt.id == T.TypeId.FLOAT64:     # bit-pair storage invariant
        return Column(dt, arena.zeros((0, 2), jnp.uint32))
    return Column(dt, arena.zeros(0, dt.storage))


def _null_column(dt, n: int) -> Column:
    from .. import types as T
    nulls = arena.zeros(n, jnp.bool_)
    if dt.id == T.TypeId.LIST:
        return Column(dt, arena.zeros(0, jnp.uint8),
                      arena.zeros(n + 1, jnp.int32), nulls,
                      [_empty_column(dt.children[0])])
    if dt.id == T.TypeId.STRUCT:
        return Column(dt, arena.zeros(0, jnp.uint8), None, nulls,
                      [_null_column(f, n) for f in dt.children])
    if dt.is_variable_width:
        return Column(dt, arena.zeros(0, jnp.uint8),
                      arena.zeros(n + 1, jnp.int32), nulls)
    if dt.id == T.TypeId.DECIMAL128:
        return Column(dt, arena.zeros((n, 2), jnp.int64), validity=nulls)
    if dt.id == T.TypeId.FLOAT64:     # bit-pair storage invariant
        return Column(dt, arena.zeros((n, 2), jnp.uint32), validity=nulls)
    return Column(dt, arena.zeros(n, dt.storage), validity=nulls)


def left_join(left: Table, right: Table, left_on: OnKey,
              right_on: OnKey) -> Table:
    """Left outer equi-join; unmatched right columns become null."""
    li, ri = join_indices(_key_of(left, left_on), _key_of(right, right_on),
                          "left")
    lt = gather(left, li)
    if right.num_rows == 0:   # nothing to gather — all-null right columns
        right_cols = [_null_column(c.dtype, int(li.shape[0]))
                      for c in right.columns]
        return Table(list(lt.columns) + right_cols)
    matched = ri >= 0
    rt = gather(right, jnp.maximum(ri, 0))

    def _with_matched(c):
        # deferred like the gather itself: the validity AND must not force
        # columns the plan never reads
        from ..column import LazyColumn, force_column

        def thunk(c=c):
            g = force_column(c)
            v = matched if g.validity is None else (g.validity & matched)
            return Column(g.dtype, g.data, g.offsets, v, g.children)
        return LazyColumn(c.dtype, c.num_rows, thunk)

    return Table(list(lt.columns) + [_with_matched(c) for c in rt.columns])


def right_join(left: Table, right: Table, left_on: OnKey,
               right_on: OnKey) -> Table:
    """Right outer equi-join; result columns = left ++ right, with null
    left columns on unmatched right rows."""
    mirrored = left_join(right, left, right_on, left_on)
    cols = list(mirrored.columns)            # right ++ left
    return Table(cols[right.num_columns:] + cols[:right.num_columns])


def full_outer_join(left: Table, right: Table, left_on: OnKey,
                    right_on: OnKey) -> Table:
    """Full outer equi-join: left-join pairs plus unmatched right rows with
    null left columns (Spark FULL OUTER)."""
    from .copying import concat_tables
    lj = left_join(left, right, left_on, right_on)
    extra = anti_join(right, left, right_on, left_on)
    if extra.num_rows == 0:
        return lj
    null_left = [_null_column(c.dtype, extra.num_rows) for c in left.columns]
    return concat_tables([lj, Table(null_left + list(extra.columns))])


def semi_join(left: Table, right: Table, left_on: OnKey,
              right_on: OnKey) -> Table:
    return gather(left, join_indices(_key_of(left, left_on),
                                     _key_of(right, right_on), "semi"))


def anti_join(left: Table, right: Table, left_on: OnKey,
              right_on: OnKey) -> Table:
    return gather(left, join_indices(_key_of(left, left_on),
                                     _key_of(right, right_on), "anti"))
