"""Join planning: build-side indexes, dense-key direct lookup, index
caching, and join→aggregate fusion (join engine v2).

Join-strategy heuristic (the planner)
-------------------------------------
:func:`build_index` inspects the build (right) side once and picks between
two physical index layouts.  Both expose the same probe interface —
``(lo, counts)`` positions into a key-sorted ``row_ids`` array — so the
match-expansion tail in ``ops.join`` is shared and the engines produce
bit-identical indices:

* **dense** — eligible when both key columns are fixed-width integer-kind
  (ints, dates/timestamps, decimal32/64 raw payloads, dictionary codes
  from string keys; NOT float bit-keys, decimal128 limbs, or uint64) and
  the observed build key span ``kmax - kmin + 1`` satisfies
  ``span <= max(DENSE_SPAN_FACTOR * n_valid, DENSE_SPAN_FLOOR)`` and
  ``span <= DENSE_SPAN_CAP``.  A ``(span,)`` CSR lookup table
  (slot → start offset + run length into key-sorted ``row_ids``) is
  materialized once; probing is one subtract + clip + two gathers —
  no ``searchsorted`` compare tree.  TPC-DS surrogate keys are contiguous
  integers, so the star joins all take this path.  When every slot holds
  at most one build row (``unique``) the index is built by direct scatter
  (no sort at all) and ``ops.join`` skips pair expansion entirely.
* **sorted** — the fallback for sparse/float/string/128-bit keys: the
  original sort-probe (stable key sort + two ``searchsorted``).

The span bounds (``kmin``/``kmax``), the valid-row count, and the
uniqueness bit all resolve through ``syncs.scalar``, so the planner's
branch decisions replay identically under ``models/compiled.py``
capture/replay, and the compiled-plan staleness guard re-derives them
against refreshed data (a key-range drift raises ``StaleTapeError``
instead of silently probing the wrong window).

Build-side index cache
----------------------
Indexes are memoized on the key buffers' device-array identity
(weakref'd, entries drop with the arrays, and the cache is automatically
disabled under capture/replay so tapes stay aligned).  A dimension table
is therefore sorted/indexed ONCE per process and reused across every
join of every query in a suite run.

Since the HBM-arena PR the cache is capacity-bounded and evictable: each
entry's device footprint is LRU-tracked against ``SRJT_INDEX_CACHE_CAP``
(cap overflow drops the LRU entry — ``join.build_index.evictions``), and
when the arena is enabled entries register with ``memory.spill`` so
budget pressure moves their lanes to host RAM; a later cache hit faults
them back bit-exactly (``join.build_index.faultback``).
"""

from __future__ import annotations

import contextlib
import os
import threading
import weakref
from collections import OrderedDict
from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..analysis import sanitize
from ..column import Column, Table, as_dict_column, force_column
from ..utils import knobs, metrics, syncs
from .filter import sized_nonzero

DENSE_SPAN_FACTOR = 2
DENSE_SPAN_FLOOR = 4096
DENSE_SPAN_CAP = 1 << 23

# THREAD-LOCAL: the exec runtime's degraded-admission path pins one
# request's joins to the low-footprint sorted engine from its worker
# thread; a process-global flag would leak the degradation into queries
# running concurrently on other workers.
_forced_tls = threading.local()    # .kind: None | "dense" | "sorted"


def forced_engine() -> Optional[str]:
    f = getattr(_forced_tls, "kind", None) \
        or knobs.get("SRJT_JOIN_ENGINE")
    return f if f in ("dense", "sorted") else None


@contextlib.contextmanager
def force_engine(kind: Optional[str]):
    """Pin the physical join engine ("dense" / "sorted"; None restores the
    planner heuristic) for the CURRENT THREAD — benchmark/test hook plus
    the exec runtime's degraded-admission routing (both engines produce
    bit-identical indices, so this only trades footprint for speed)."""
    old = getattr(_forced_tls, "kind", None)
    _forced_tls.kind = kind
    try:
        yield
    finally:
        _forced_tls.kind = old


class BuildIndex(NamedTuple):
    """Physical index over the build side's valid (non-null-key) rows."""
    kind: str                            # "dense" | "sorted"
    n_valid: int                         # valid build rows (static)
    row_ids: jnp.ndarray                 # [n_valid] key-sorted, stable
    sorted_keys: Optional[jnp.ndarray]   # [n_valid] (sorted kind only)
    kmin: int                            # dense: lookup-window base key
    span: int                            # dense: lut length (0 if sorted)
    lut_lo: Optional[jnp.ndarray]        # [span] slot → start into row_ids
    lut_cnt: Optional[jnp.ndarray]       # [span] slot → run length
    unique: bool                         # dense: every slot holds ≤ 1 row
    max_run: int = 0                     # dense: hottest key's row count
    #   (free: the uniqueness test already syncs max(lut_cnt); 0 = unknown
    #   on the sorted path).  The adaptive executor reads this as its skew
    #   signal — see skew_stats().


def _index_nbytes(ix: "BuildIndex") -> int:
    return sum(int(a.nbytes) for a in
               (ix.row_ids, ix.sorted_keys, ix.lut_lo, ix.lut_cnt)
               if a is not None)


class _IndexCache:
    """LRU build-index cache keyed on key-buffer identity, capacity-bound
    and arena-evictable (the fix for the PR 1 unbounded memo).

    * plain LRU over device bytes: inserting past ``SRJT_INDEX_CACHE_CAP``
      drops the least-recently-used entry (``join.build_index.evictions``).
    * arena tier (``SRJT_HBM_ARENA``/``SRJT_HBM_BUDGET`` set): entries
      register as ``memory.spill`` residents; budget pressure spills their
      lanes to host RAM, and the next cache hit faults them back.
    * entries die with their key arrays (weakref callbacks) and the cache
      is bypassed under syncs capture/replay, exactly like the old memo.

    Thread-safety: all map/byte-accounting mutation happens under the
    arena's ``budget._LOCK`` (an RLock).  That lock is deliberately SHARED
    with ``memory.spill``: the spiller closures below run inside
    ``spill.reclaim`` — which ``budget.charge`` invokes while holding the
    lock — so a private cache lock would deadlock ABBA against the
    register/unregister path.  Weakref death callbacks re-enter safely.
    """

    def __init__(self):
        self._d: "OrderedDict[tuple, dict]" = OrderedDict()
        self._device_bytes = 0

    @staticmethod
    def _lock():
        from ..memory import budget as mbudget
        return mbudget._LOCK

    @staticmethod
    def _cap() -> Optional[int]:
        from ..memory import budget as mbudget
        return mbudget.parse_bytes(knobs.get("SRJT_INDEX_CACHE_CAP"))

    def _drop(self, key, *, count_eviction: bool) -> None:
        from ..memory import spill as mspill
        with self._lock():
            e = self._d.pop(key, None)
            if e is None:
                return
            if not e["payload"].spilled:
                self._device_bytes -= e["nbytes"]
                mspill.unregister(("join_index",) + key)
        if count_eviction and metrics.recording():
            metrics.count("join.build_index.evictions")

    def get(self, tag: str, arrays) -> Optional["BuildIndex"]:
        if syncs.mode() != "normal":
            return None
        key = (tag,) + tuple(id(a) for a in arrays)
        from ..memory import spill as mspill
        with self._lock():
            e = self._d.get(key)
            if e is None:
                return None
            for r, a in zip(e["refs"], arrays):
                if r() is not a:
                    return None
            self._d.move_to_end(key)
            if not e["payload"].spilled:
                mspill.touch(("join_index",) + key)
                return e["value"]
            lanes = e["payload"].get()          # fault back (bit-exact)
            kind, n_valid, kmin, span, unique, max_run = e["meta"]
            e["value"] = BuildIndex(kind, n_valid, lanes["row_ids"],
                                    lanes["sorted_keys"], kmin, span,
                                    lanes["lut_lo"], lanes["lut_cnt"],
                                    unique, max_run)
            self._device_bytes += e["nbytes"]
            mspill.register(("join_index",) + key, e["nbytes"],
                            "join.build_index", e["payload"].spill)
            if metrics.recording():
                metrics.count("join.build_index.faultback")
            self._evict_over_cap(keep=key)
            return e["value"]

    def _evict_over_cap(self, keep=None) -> None:
        # caller holds the lock
        cap = self._cap()
        if cap is None:
            return
        while self._device_bytes > cap and len(self._d) > 1:
            lru = next(k for k in self._d if k != keep) \
                if keep is not None else next(iter(self._d))
            self._drop(lru, count_eviction=True)
            if lru == keep:
                break

    def put(self, tag: str, arrays, ix: "BuildIndex") -> None:
        if syncs.mode() != "normal":
            return
        key = (tag,) + tuple(id(a) for a in arrays)
        try:
            refs = tuple(
                weakref.ref(a, lambda _, k=key: self._drop(
                    k, count_eviction=False))
                for a in arrays)
        except TypeError:
            return
        from ..memory import spill as mspill
        payload = mspill.SpillableArrays(
            "join.build_index",
            {"row_ids": ix.row_ids, "sorted_keys": ix.sorted_keys,
             "lut_lo": ix.lut_lo, "lut_cnt": ix.lut_cnt})
        entry = {"refs": refs, "value": ix, "payload": payload,
                 "nbytes": payload.nbytes,
                 "meta": (ix.kind, ix.n_valid, ix.kmin, ix.span,
                          ix.unique, ix.max_run)}

        def _spiller(e=entry):
            with self._lock():                  # reentrant under reclaim
                freed = e["payload"].spill()
                if freed:
                    e["value"] = None           # drop the device refs
                    self._device_bytes -= e["nbytes"]
                return freed

        with self._lock():
            # two threads can miss-then-build the same key concurrently;
            # dropping the loser's entry first keeps the byte ledger exact
            self._drop(key, count_eviction=False)
            self._d[key] = entry
            self._device_bytes += entry["nbytes"]
            mspill.register(("join_index",) + key, entry["nbytes"],
                            "join.build_index", _spiller)
            self._evict_over_cap(keep=key)

    def clear(self) -> None:
        from ..memory import spill as mspill
        with self._lock():
            for key, e in list(self._d.items()):
                if not e["payload"].spilled:
                    mspill.unregister(("join_index",) + key)
            self._d.clear()
            self._device_bytes = 0

    def device_bytes(self) -> int:
        return self._device_bytes


_INDEX_CACHE = _IndexCache()


def dense_eligible(col: Column) -> bool:
    """Key dtypes the direct-lookup window arithmetic is exact for."""
    dt = col.dtype
    if dt.is_variable_width or dt.is_nested:
        return False
    if dt.id in (T.TypeId.FLOAT32, T.TypeId.FLOAT64, T.TypeId.DECIMAL128):
        return False
    sd = np.dtype(dt.storage)
    if sd.kind not in "iu":
        return False
    return not (sd.kind == "u" and sd.itemsize == 8)


def build_index(data: jnp.ndarray, valid, dense_ok: bool) -> BuildIndex:
    """Index the build side, memoized on the key buffers' identity
    (capacity-bound LRU; arena-evictable — see :class:`_IndexCache`)."""
    forced = forced_engine()
    tag = f"join_build_index:{forced or 'auto'}"
    key_arrays = (data,) if valid is None else (data, valid)
    hit = _INDEX_CACHE.get(tag, key_arrays)
    if hit is not None:
        if metrics.recording():
            metrics.count("join.build_index.cache_hit")
            metrics.count(f"join.engine.{hit.kind}")
        return hit
    with metrics.span("join.build_index"):
        ix = _build_index(data, valid, dense_ok and forced != "sorted",
                          forced == "dense")
        if metrics.recording():
            metrics.count("join.build_index.cache_miss")
            metrics.count(f"join.engine.{ix.kind}")
            metrics.annotate(engine=ix.kind, n_valid=ix.n_valid,
                             key_span=ix.span)
    _INDEX_CACHE.put(tag, key_arrays, ix)
    return ix


def _key_sorted_order(data, valid, n_valid: int):
    """Valid build rows in stable key-sorted order (ties keep original row
    order — the exact ``r_order`` the sort-probe engine produces)."""
    order = jnp.argsort(data, stable=True)
    if valid is None:
        return order, data[order]
    skeys = data[order]
    rank = jnp.where(valid, 0, 1)[order]
    rr = jnp.lexsort((skeys, rank))       # valid first, then key, stable
    return order[rr][:n_valid], skeys[rr][:n_valid]


def _build_index(data, valid, try_dense: bool, must_dense: bool):
    n = int(data.shape[0])
    n_valid = n if valid is None else syncs.scalar(jnp.sum(valid))
    kmin = span = 0
    dense = False
    if try_dense and n_valid > 0:
        info = np.iinfo(np.dtype(data.dtype))
        dmin = data if valid is None else jnp.where(valid, data, info.max)
        dmax = data if valid is None else jnp.where(valid, data, info.min)
        kmin = syncs.scalar(jnp.min(dmin))
        span = syncs.scalar(jnp.max(dmax)) - kmin + 1
        limit = DENSE_SPAN_CAP if must_dense else min(
            max(DENSE_SPAN_FACTOR * n_valid, DENSE_SPAN_FLOOR),
            DENSE_SPAN_CAP)
        dense = span <= limit
    if not dense:
        order, skeys = _key_sorted_order(data, valid, n_valid)
        return BuildIndex("sorted", n_valid, order, skeys, 0, 0, None, None,
                          False)
    slot64 = data.astype(jnp.int64) - kmin
    ok = jnp.ones(n, jnp.bool_) if valid is None else valid
    slot = jnp.clip(slot64, 0, span - 1).astype(jnp.int32)
    lut_cnt = jnp.zeros(span, jnp.int32).at[slot].add(ok.astype(jnp.int32))
    lut_lo = (jnp.cumsum(lut_cnt) - lut_cnt).astype(jnp.int32)
    max_run = syncs.scalar(jnp.max(lut_cnt))
    unique = max_run <= 1
    if unique:
        # no sort anywhere: each valid row scatters straight to its slot
        tgt = jnp.where(ok, lut_lo[slot].astype(jnp.int64),
                        jnp.int64(n_valid))
        row_ids = jnp.zeros(n_valid, jnp.int64).at[tgt].set(
            jnp.arange(n, dtype=jnp.int64), mode="drop")
    else:
        row_ids, _ = _key_sorted_order(data, valid, n_valid)
    return BuildIndex("dense", n_valid, row_ids, None, int(kmin), int(span),
                      lut_lo, lut_cnt, bool(unique), int(max_run))


def extend_build_index(ix: BuildIndex, delta_data, delta_valid,
                       base_n: int) -> Optional[BuildIndex]:
    """Append build rows ``[base_n, base_n + len(delta_data))`` to a dense
    index, reusing the existing CSR window instead of invalidate-and-
    rebuild: counts scatter-add into the same ``span`` slots, existing
    ``row_ids`` remap positionally, delta rows append after each slot's
    run.  The result is field-identical to ``_build_index`` over the
    concatenated keys whenever every valid appended key lands inside
    ``[kmin, kmin + span)`` — within a slot, old rows precede delta rows
    in original order, exactly the stable key-sorted order a rebuild
    produces.  Returns None when not applicable (sorted index, or an
    appended key escapes the window): the caller rebuilds."""
    if ix.kind != "dense":
        return None
    m = int(delta_data.shape[0])
    if m == 0:
        return ix
    d = delta_data.astype(jnp.int64) - ix.kmin
    ok = jnp.ones(m, jnp.bool_) if delta_valid is None else delta_valid
    in_win = (d >= 0) & (d < ix.span)
    if syncs.scalar(jnp.sum((~in_win) & ok)) > 0:
        if metrics.recording():
            metrics.count("join.build_index.extend_window_miss")
        return None
    m_valid = m if delta_valid is None else syncs.scalar(jnp.sum(ok))
    if m_valid == 0:
        return ix
    with metrics.span("join.build_index.extend", rows=m):
        slot = jnp.clip(d, 0, ix.span - 1).astype(jnp.int32)
        new_cnt = ix.lut_cnt.at[slot].add(ok.astype(jnp.int32))
        new_lo = (jnp.cumsum(new_cnt) - new_cnt).astype(jnp.int32)
        # remap existing key-sorted positions: position → slot via the old
        # cumulative counts, then shift by the slot's new start
        pos = jnp.arange(ix.n_valid, dtype=jnp.int32)
        cum_old = jnp.cumsum(ix.lut_cnt)
        old_slot = jnp.searchsorted(cum_old, pos, side="right") \
            .astype(jnp.int32)
        old_pos = new_lo[old_slot] + (pos - ix.lut_lo[old_slot])
        # delta rows stable-sorted by slot (invalid rows sort last via the
        # span sentinel and are sliced off), ranked within their run
        sort_key = jnp.where(ok, slot, jnp.int32(ix.span))
        dorder = jnp.argsort(sort_key, stable=True)[:m_valid]
        ds = sort_key[dorder]
        idxs = jnp.arange(m_valid, dtype=jnp.int32)
        head = jnp.concatenate([jnp.ones(1, jnp.bool_), ds[1:] != ds[:-1]])
        run_start = jax.lax.cummax(jnp.where(head, idxs, 0))
        delta_pos = new_lo[ds] + ix.lut_cnt[ds] + (idxs - run_start)
        n_total = ix.n_valid + m_valid
        row_ids = jnp.zeros(n_total, jnp.int64) \
            .at[old_pos].set(ix.row_ids) \
            .at[delta_pos].set(jnp.int64(base_n) + dorder.astype(jnp.int64))
        max_run = syncs.scalar(jnp.max(new_cnt))
        unique = bool(max_run <= 1)
        if metrics.recording():
            metrics.count("join.build_index.extended")
        return BuildIndex("dense", n_total, row_ids, None, ix.kmin, ix.span,
                          new_lo, new_cnt, unique, int(max_run))


def probe_counts(ix: BuildIndex, ldata, lvalid):
    """Per probe row: (first match position into ``ix.row_ids``, match
    count).  ``lo`` is unspecified where ``counts == 0`` (callers guard,
    as the sort-probe tail always has)."""
    if ix.kind == "dense":
        d = ldata.astype(jnp.int64) - ix.kmin
        in_r = (d >= 0) & (d < ix.span)
        if lvalid is not None:
            in_r = in_r & lvalid
        slot = jnp.clip(d, 0, max(ix.span - 1, 0)).astype(jnp.int32)
        counts = jnp.where(in_r, ix.lut_cnt[slot], 0)
        return ix.lut_lo[slot], counts
    lo = jnp.searchsorted(ix.sorted_keys, ldata, side="left")
    hi = jnp.searchsorted(ix.sorted_keys, ldata, side="right")
    counts = hi - lo
    if lvalid is not None:
        counts = jnp.where(lvalid, counts, 0)
    return lo, counts


def skew_stats(ix: BuildIndex) -> Optional[dict]:
    """Hot-key summary from the dense CSR histogram, or None when the
    index carries no histogram (sorted engine, or empty build side).

    ``skew`` is the hottest key's run length over the mean run length —
    the factor by which that key's pair expansion exceeds a uniform
    key's.  Derived entirely from values the build already synced
    (``n_valid`` and ``max_run``), so reading it costs nothing and is
    capture/replay consistent."""
    if ix.kind != "dense" or ix.max_run <= 0 or ix.n_valid <= 0:
        return None
    n_keys = max(1, ix.span)
    mean_run = ix.n_valid / n_keys
    return {"max_run": ix.max_run,
            "n_valid": ix.n_valid,
            "span": ix.span,
            "skew": ix.max_run / max(mean_run, 1.0)}


# --- multi-column key packing ------------------------------------------------


COMPOSITE_BITS = 63     # packed tuples must index as a non-negative int64


class KeyPlan(NamedTuple):
    """Physical probe plan for one (possibly multi-column) equi-join key.

    ``ldata``/``rdata`` are the single fixed-width lanes the engines
    consume; ``verify`` carries ``(left_lane, right_lane)`` pairs that
    candidate matches must additionally satisfy — empty when the probe
    lane alone encodes tuple equality exactly (single keys, composites)."""
    mode: str            # "single" | "composite" | "fingerprint" | "fallback"
    ldata: jnp.ndarray
    lvalid: Optional[jnp.ndarray]
    rdata: jnp.ndarray
    rvalid: Optional[jnp.ndarray]
    verify: tuple
    dense_ok: bool


def _and_valid(a, b):
    if a is None:
        return b
    return a if b is None else (a & b)


def _key_lanes(col: Column):
    """Fixed-width equality lanes for one (already string-encoded) key
    column: one int-kind lane for everything the single-key path probes,
    two int64 limb lanes for decimal128."""
    from .join import _key_with_nulls_last
    c = force_column(col)
    if c.dtype.id == T.TypeId.DECIMAL128:
        return [c.data[:, 0], c.data[:, 1]], c.validity
    data, valid = _key_with_nulls_last(c)
    return [data], valid


class _PlanCache:
    """Tiny LRU memo for multi-key pack plans, keyed on the key columns'
    device-buffer identity.  Without it every repeated multi-key probe
    would re-pack into FRESH composite arrays and the build-index cache
    (also identity-keyed) could never hit; with it the second probe of the
    same key buffers returns the same ``KeyPlan`` object and the index
    cache sees the same ``rdata`` buffer.  Bypassed under capture/replay
    for the same reason the index cache is: a memo hit would skip the
    window ``syncs.scalar`` calls and misalign the tape.

    Mutation is guarded by an RLock (reentrant on purpose: a weakref
    death callback can fire from a GC point inside ``put`` on the same
    thread that already holds the lock)."""

    def __init__(self, cap: int = 8):
        self._d: "OrderedDict[tuple, dict]" = OrderedDict()
        self._cap = cap
        self._mu = sanitize.tracked_rlock("ops.join_plan.index_cache")

    def _evict(self, key) -> None:
        with self._mu:
            self._d.pop(key, None)

    def get(self, key, arrays) -> Optional["KeyPlan"]:
        if syncs.mode() != "normal":
            return None
        with self._mu:
            e = self._d.get(key)
            if e is None:
                return None
            for r, a in zip(e["refs"], arrays):
                if r() is not a:
                    return None
            self._d.move_to_end(key)
            return e["plan"]

    def put(self, key, arrays, plan: "KeyPlan") -> None:
        if syncs.mode() != "normal":
            return
        try:
            refs = tuple(
                weakref.ref(a, lambda _, k=key: self._evict(k))
                for a in arrays)
        except TypeError:
            return
        with self._mu:
            self._d[key] = {"refs": refs, "plan": plan}
            while len(self._d) > self._cap:
                self._d.popitem(last=False)

    def clear(self) -> None:
        with self._mu:
            self._d.clear()


_PLAN_CACHE = _PlanCache()


def plan_keys(left_cols: Sequence[Column],
              right_cols: Sequence[Column]) -> KeyPlan:
    """Plan the physical probe lanes for a k-column equi-join key.

    Single keys pass through untouched (join engine v2 behavior).  String
    columns are dictionary-encoded first against one shared dictionary
    (``strings.encode_shared`` — code equality == string equality), then
    multi-column tuples pack one of three ways:

    * **composite** — every column is :func:`dense_eligible` and the
      product of the build-side windows ``[kmin_i, kmin_i + span_i)`` fits
      in 63 bits: the tuple packs into one non-negative int64
      (mixed-radix over the windows), probe rows falling outside any build
      window are invalidated, and composite equality == tuple equality —
      so the dense LUT, build-index cache, arena admission and
      capture/replay machinery all apply to multi-key joins unchanged.
    * **fingerprint** — the windows overflow 63 bits: probe on a 64-bit
      murmur3 fingerprint of the tuple (``ops.hashing.fingerprint64``) and
      let ``ops.join`` verify true lane equality on the candidate pairs.
    * **fallback** — some column can never pack exactly (f64 bit-keys,
      decimal128 limbs, uint64): same hashed probe + verification, counted
      separately so traces show the tuple never qualified for packing.
    """
    from . import strings
    k = len(left_cols)
    if k != len(right_cols):
        raise ValueError("join keys: left/right lists differ in length")
    if k == 0:
        raise ValueError("join keys: at least one key column required")
    enc_l, enc_r = [], []
    for lc, rc in zip(left_cols, right_cols):
        if lc.dtype.is_variable_width or rc.dtype.is_variable_width:
            # DictColumn sides ride the dictionary-level shared encode —
            # codes out, row bytes never read, and the int32 result keeps
            # the key on the dense lane (see strings.encode_shared)
            if (as_dict_column(lc) is not None
                    or as_dict_column(rc) is not None):
                metrics.count("join.dict_keys")
            lc, rc = strings.encode_shared([lc, rc])
        enc_l.append(lc)
        enc_r.append(rc)
    if k == 1 and not any(force_column(c).dtype.id == T.TypeId.DECIMAL128
                          for c in (enc_l[0], enc_r[0])):
        # decimal128 is excluded: its (n, 2) limb storage has no single
        # probe lane, so it packs below like a 2-lane tuple — hashed
        # fingerprint probe + exact limb verification — instead of
        # handing the sort-probe engine a 2-D array
        from .join import _key_with_nulls_last
        lc, rc = enc_l[0], enc_r[0]
        ldata, lvalid = _key_with_nulls_last(force_column(lc))
        rdata, rvalid = _key_with_nulls_last(force_column(rc))
        return KeyPlan("single", ldata, lvalid, rdata, rvalid, (),
                       dense_eligible(rc) and dense_eligible(lc))
    with metrics.span("join.pack", n_keys=k):
        enc_l = [force_column(c) for c in enc_l]
        enc_r = [force_column(c) for c in enc_r]
        arrays = [a for c in enc_l + enc_r
                  for a in (c.data, c.validity) if a is not None]
        ck = tuple(id(a) for a in arrays)
        hit = _PLAN_CACHE.get(ck, arrays)
        if hit is not None:
            metrics.count("join.pack.cache_hit")
            if metrics.recording():
                metrics.annotate(mode=hit.mode, cached=True)
            return hit
        plan = _pack_keys(enc_l, enc_r)
        _PLAN_CACHE.put(ck, arrays, plan)
        return plan


def _pack_keys(lcols, rcols) -> KeyPlan:
    from .hashing import fingerprint64

    llanes, rlanes = [], []
    lvalid = rvalid = None
    packable = True
    for lc, rc in zip(lcols, rcols):
        ll, lv = _key_lanes(lc)
        rl, rv = _key_lanes(rc)
        llanes += ll
        rlanes += rl
        lvalid = _and_valid(lvalid, lv)
        rvalid = _and_valid(rvalid, rv)
        packable = packable and dense_eligible(lc) and dense_eligible(rc)
    if packable:
        # build-side window per column — unconditional scalar syncs (the
        # capture/replay tape must not depend on metrics state); an
        # all-null build column degenerates to a span-1 window nothing on
        # the probe side can enter, which is exactly "null never matches"
        windows = []
        prod = 1
        for rl in rlanes:
            if rl.shape[0] == 0:
                windows.append((0, 1))
                continue
            info = np.iinfo(np.dtype(rl.dtype))
            vmin = rl if rvalid is None else jnp.where(rvalid, rl, info.max)
            vmax = rl if rvalid is None else jnp.where(rvalid, rl, info.min)
            kmin = syncs.scalar(jnp.min(vmin))
            span = max(syncs.scalar(jnp.max(vmax)) - kmin + 1, 1)
            windows.append((kmin, span))
            prod *= span
        if prod < (1 << COMPOSITE_BITS):
            # mixed-radix pack, last key fastest; per-lane clip keeps the
            # accumulator in [0, prod) so int64 arithmetic never wraps
            comp_l = jnp.zeros(llanes[0].shape[0], jnp.int64)
            comp_r = jnp.zeros(rlanes[0].shape[0], jnp.int64)
            in_win = None
            stride = 1
            for (kmin, span), ll, rl in zip(windows[::-1], llanes[::-1],
                                            rlanes[::-1]):
                dl = ll.astype(jnp.int64) - kmin
                ok = (dl >= 0) & (dl < span)
                in_win = ok if in_win is None else (in_win & ok)
                comp_l = comp_l + jnp.clip(dl, 0, span - 1) * stride
                dr = jnp.clip(rl.astype(jnp.int64) - kmin, 0, span - 1)
                comp_r = comp_r + dr * stride
                stride *= span
            # probe tuples outside any build window cannot match — fold
            # the window test into key validity (the engines' null mask)
            lvalid = _and_valid(lvalid, in_win)
            metrics.count("join.pack.composite")
            if metrics.recording():
                metrics.annotate(mode="composite", span_product=prod)
            return KeyPlan("composite", comp_l, lvalid, comp_r, rvalid,
                           (), True)
        mode = "fingerprint"
    else:
        mode = "fallback"
    metrics.count(f"join.pack.{mode}")
    if metrics.recording():
        metrics.annotate(mode=mode)
    verify = tuple(zip(llanes, rlanes))
    return KeyPlan(mode, fingerprint64(llanes), lvalid,
                   fingerprint64(rlanes), rvalid, verify, False)


# --- join→aggregate fusion ---------------------------------------------------


def _take_col(col: Column, idx) -> Column:
    from .filter import _gather_column
    return _gather_column(force_column(col), idx)


def _null_where(col: Column, keep) -> Column:
    """Gathered build column with validity additionally masked by ``keep``
    — the eager twin of ``ops.join.left_join``'s deferred ``_with_matched``
    (bit-identical null pattern)."""
    g = force_column(col)
    v = keep if g.validity is None else (g.validity & keep)
    return Column(g.dtype, g.data, g.offsets, v, g.children)


def join_aggregate(left: Table, right: Table, left_on, right_on,
                   group_keys: Sequence[int],
                   aggs: Sequence[tuple[int, str]],
                   how: str = "inner") -> Table:
    """``groupby_aggregate(join(left, right, left_on, right_on), group_keys,
    aggs)`` without materializing the join pairs, for ``how`` in
    ``("inner", "left")``.

    ``left_on``/``right_on`` take a single column index or equal-length
    index lists (multi-column keys route through :func:`plan_keys` like
    ``ops.join``).  ``group_keys`` and the agg value indices address the
    joined (left ++ right) schema.  Fused shapes:

    * **unique build side** (the TPC-DS star shape — fact ⋈ dimension on a
      surrogate PK): matched probe rows ARE the joined rows, so only the
      group-key/value columns are gathered (one compaction sync) and fed
      straight into ``ops.groupby``'s segment reductions — no pair
      expansion, no wide joined table.  LEFT OUTER skips even the
      compaction: every probe row is a joined row, left columns pass
      through untouched and build columns null out where unmatched.
    * **probe-side-only columns** over a duplicated build side: each probe
      row's match count becomes a weight (sum/count/mean weight their
      contributions; min/max ignore multiplicity), so the pairs still
      never materialize.  LEFT OUTER keeps unmatched rows at weight 1 —
      their single null-extended joined row.

    Anything else — including fingerprint-probed multi-key tuples, whose
    candidate counts are not true match counts — falls back to the
    materialized join + groupby (identical result either way —
    differentially tested in tests/test_join_v2.py).
    """
    from .groupby import groupby_aggregate
    from .join import inner_join, left_join

    if how not in ("inner", "left"):
        raise ValueError(f"join_aggregate: unsupported how={how!r}")
    nl = left.num_columns
    lon = list(left_on) if isinstance(left_on, (list, tuple)) else [left_on]
    ron = list(right_on) if isinstance(right_on, (list, tuple)) \
        else [right_on]
    plan = plan_keys([left[i] for i in lon], [right[i] for i in ron])
    needed = list(group_keys) + [vi for vi, _ in aggs]

    def _unfused():
        j = (inner_join if how == "inner" else left_join)(
            left, right, left_on, right_on)
        return groupby_aggregate(j, list(group_keys), list(aggs))

    if plan.verify:
        metrics.count("join.fused.fallback_join")
        with metrics.span("join.aggregate", path="fallback_join"):
            return _unfused()

    ix = build_index(plan.rdata, plan.rvalid, plan.dense_ok)
    if ix.unique:
        metrics.count("join.fused.unique_gather")
        with metrics.span("join.aggregate", path="unique_gather"):
            lo, counts = probe_counts(ix, plan.ldata, plan.lvalid)
            pos = jnp.minimum(lo, max(ix.n_valid - 1, 0))
            if how == "inner":
                m = counts > 0
                k = syncs.scalar(jnp.sum(m))
                li = sized_nonzero(m, k)
                ri = ix.row_ids[pos[li]]
                cols = [_take_col(left[ci], li) if ci < nl
                        else _take_col(right[ci - nl], ri) for ci in needed]
            else:
                matched = counts > 0
                ri = jnp.where(matched, ix.row_ids[pos], 0)
                cols = [force_column(left[ci]) if ci < nl
                        else _null_where(_take_col(right[ci - nl], ri),
                                         matched)
                        for ci in needed]
            nk = len(group_keys)
            return groupby_aggregate(
                Table(cols), list(range(nk)),
                [(nk + i, agg) for i, (_, agg) in enumerate(aggs)])

    if (group_keys and all(ci < nl for ci in needed)
            and _weighted_ok([left[ci] for ci in group_keys],
                             [(left[vi], agg) for vi, agg in aggs])):
        metrics.count("join.fused.weighted_groupby")
        with metrics.span("join.aggregate", path="weighted_groupby"):
            lo, counts = probe_counts(ix, plan.ldata, plan.lvalid)
            if how == "inner":
                m = counts > 0
                k = syncs.scalar(jnp.sum(m))
                li = sized_nonzero(m, k)
                w = counts.astype(jnp.int64)[li]
                return _weighted_groupby(
                    [_take_col(left[ci], li) for ci in group_keys],
                    [(_take_col(left[vi], li), agg) for vi, agg in aggs], w)
            w = jnp.maximum(counts, 1).astype(jnp.int64)
            return _weighted_groupby(
                [force_column(left[ci]) for ci in group_keys],
                [(force_column(left[vi]), agg) for vi, agg in aggs], w)

    metrics.count("join.fused.fallback_join")
    with metrics.span("join.aggregate", path="fallback_join"):
        return _unfused()


def _weighted_ok(key_cols, val_aggs) -> bool:
    for c in key_cols:
        dt = c.dtype
        if (dt.is_variable_width or dt.is_nested
                or dt.id in (T.TypeId.FLOAT64, T.TypeId.DECIMAL128)):
            return False
    for c, agg in val_aggs:
        dt = c.dtype
        if dt.is_variable_width or dt.is_nested or dt.id == T.TypeId.DECIMAL128:
            return False
        if agg not in ("sum", "count", "mean", "min", "max"):
            return False
        if dt.id == T.TypeId.FLOAT64 and agg in ("min", "max"):
            return False          # bit-exact selection needs the full path
    return True


def _weighted_groupby(key_cols, val_aggs, w) -> Table:
    """Groupby over matched probe rows where row ``i`` stands for ``w[i]``
    identical joined pairs — mirrors ``ops.groupby`` semantics/dtypes for
    the shapes :func:`_weighted_ok` admits."""
    from .groupby import (_agg_out_dtype, _agg_segment, _cast_res,
                          _empty_result, _segment_ids, _take_rows)
    from .sort import order_by

    nk = len(key_cols)
    sub = Table(key_cols + [c for c, _ in val_aggs])
    if sub.num_rows == 0:
        return _empty_result(sub, list(range(nk)),
                             [(nk + i, a) for i, (_, a) in
                              enumerate(val_aggs)])
    order = order_by(Table(key_cols), list(range(nk)))
    skeys = [_take_rows(c, order) for c in key_cols]
    seg_ids = _segment_ids([c.data for c in skeys],
                           [c.validity for c in skeys])
    ns = syncs.scalar(seg_ids[-1]) + 1
    n = order.shape[0]
    head_pos = jax.ops.segment_min(jnp.arange(n, dtype=jnp.int32), seg_ids,
                                   ns)
    out_cols = [_take_rows(c, head_pos) for c in skeys]
    ws = w[order]
    for col, agg in val_aggs:
        valid = None if col.validity is None else col.validity[order]
        if agg == "count":
            ones = ws if valid is None else jnp.where(valid, ws, 0)
            res = jax.ops.segment_sum(ones, seg_ids, ns)
            dt = _agg_out_dtype(col.dtype, agg)
            out_cols.append(Column(dt, res.astype(dt.storage)))
            continue
        vals = col.values()[order]
        if agg in ("sum", "mean"):
            kind = col.dtype.storage.kind
            acc = vals.astype(jnp.float64 if kind == "f" else jnp.int64)
            acc = acc if valid is None else jnp.where(valid, acc, 0)
            s = jax.ops.segment_sum(acc * ws.astype(acc.dtype), seg_ids, ns)
            if agg == "sum":
                dt = _agg_out_dtype(col.dtype, agg)
                out_cols.append(Column.from_values(dt, _cast_res(s, dt)))
                continue
            cnt = jax.ops.segment_sum(
                ws if valid is None else jnp.where(valid, ws, 0),
                seg_ids, ns)
            res = s.astype(jnp.float64) / jnp.maximum(cnt, 1).astype(
                jnp.float64)
            dt = _agg_out_dtype(col.dtype, agg)
            out_cols.append(Column.from_values(dt, _cast_res(res, dt)))
            continue
        # min/max: pair multiplicity is irrelevant — plain segment select
        res = _agg_segment(vals, valid, seg_ids, agg, ns,
                           col.dtype.storage.kind)
        if valid is not None:
            cnt = _agg_segment(vals, valid, seg_ids, "count", ns,
                               col.dtype.storage.kind)
            out_cols.append(Column.from_values(
                col.dtype, _cast_res(res, col.dtype), validity=cnt > 0))
        else:
            out_cols.append(Column.from_values(col.dtype,
                                               _cast_res(res, col.dtype)))
    return Table(out_cols)
