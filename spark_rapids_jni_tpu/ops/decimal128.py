"""128-bit decimal arithmetic on 64-bit lane pairs.

The reference inherits ``__int128_t`` fixed_point columns from libcudf
(SURVEY §2.9); XLA/TPU has no 128-bit integer lane type, so a DECIMAL128
column stores its payload as ``data[n, 2]`` int64 lanes — lane 0 is the low
64 bits (uint64 bit pattern), lane 1 the sign-carrying high 64 bits
(``types.decimal128``).  All arithmetic here is elementwise limb arithmetic
on 32-bit limbs held in int64 lanes: pure VPU work, fully jittable, no
data-dependent control flow.

Two's-complement throughout: add/mul are computed mod 2^128 on unsigned
limbs, which is exactly correct for signed values.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..column import Column

_MASK32 = jnp.int64(0xFFFFFFFF)
_TOPBIT = jnp.int64(-0x8000000000000000)   # 1 << 63 as int64 bit pattern


# -- host construction -------------------------------------------------------

def from_pyints(values, scale: int = 0) -> Column:
    """Build a DECIMAL128 column from python ints (None ⇒ null)."""
    n = len(values)
    lanes = np.zeros((n, 2), dtype=np.int64)
    valid = np.ones(n, dtype=bool)
    for i, v in enumerate(values):
        if v is None:
            valid[i] = False
            continue
        u = int(v) & ((1 << 128) - 1)          # two's complement mod 2^128
        lanes[i, 0] = np.int64((u & ((1 << 64) - 1)) - (1 << 64)
                               if (u & (1 << 63)) else (u & ((1 << 64) - 1)))
        hi = u >> 64
        lanes[i, 1] = np.int64(hi - (1 << 64) if (hi & (1 << 63)) else hi)
    v = None if valid.all() else jnp.asarray(valid)
    return Column(T.decimal128(scale), jnp.asarray(lanes), validity=v)


# -- limb decomposition ------------------------------------------------------

def _limbs(lanes: jnp.ndarray) -> list[jnp.ndarray]:
    """[n,2] int64 lane pair → four uint32 limbs held in int64 (low first)."""
    lo, hi = lanes[:, 0], lanes[:, 1]
    return [lo & _MASK32, (lo >> 32) & _MASK32,
            hi & _MASK32, (hi >> 32) & _MASK32]


def _from_limbs(l0, l1, l2, l3) -> jnp.ndarray:
    """Carry-propagate int64 limb accumulators → [n,2] lane pair (mod 2^128)."""
    c = l0 >> 32
    l0 = l0 & _MASK32
    l1 = l1 + c
    c = l1 >> 32
    l1 = l1 & _MASK32
    l2 = l2 + c
    c = l2 >> 32
    l2 = l2 & _MASK32
    l3 = (l3 + c) & _MASK32
    lo = l0 | (l1 << 32)
    hi = l2 | (l3 << 32)
    return jnp.stack([lo, hi], axis=1)


def _combine_validity(a: Column, b: Column):
    if a.validity is None:
        return b.validity
    if b.validity is None:
        return a.validity
    return a.validity & b.validity


# -- arithmetic --------------------------------------------------------------

def add(a: Column, b: Column) -> Column:
    """a + b (mod 2^128); scales must match (rescale first)."""
    if a.dtype.scale != b.dtype.scale:
        raise ValueError("decimal128 add requires equal scales")
    la, lb = _limbs(a.data), _limbs(b.data)
    out = _from_limbs(*(x + y for x, y in zip(la, lb)))
    return Column(a.dtype, out, validity=_combine_validity(a, b))


def negate(a: Column) -> Column:
    l0, l1, l2, l3 = [(~x) & _MASK32 for x in _limbs(a.data)]
    return Column(a.dtype, _from_limbs(l0 + 1, l1, l2, l3),
                  validity=a.validity)


def sub(a: Column, b: Column) -> Column:
    return add(a, negate(b))


def _mul_lanes(a_lanes: jnp.ndarray, b_limbs: list[jnp.ndarray]) -> jnp.ndarray:
    """Full 4×4 limb product, keeping the low 4 limbs (mod 2^128).

    Each partial product is uint32×uint32 ≤ 2^64-2^33+1: computed exactly in
    uint64 then split, so int64 accumulators never overflow (≤ 8 summands of
    < 2^32 each per limb before propagation).
    """
    al = _limbs(a_lanes)
    acc = [jnp.zeros_like(al[0]) for _ in range(4)]
    for i in range(4):
        for j in range(4 - i):
            p = (al[i].astype(jnp.uint64) * b_limbs[j].astype(jnp.uint64))
            plo = (p & jnp.uint64(0xFFFFFFFF)).astype(jnp.int64)
            phi = (p >> jnp.uint64(32)).astype(jnp.int64)
            acc[i + j] = acc[i + j] + plo
            if i + j + 1 < 4:
                acc[i + j + 1] = acc[i + j + 1] + phi
            # propagate eagerly so accumulators stay far from 2^63
            carry = acc[i + j] >> 32
            acc[i + j] = acc[i + j] & _MASK32
            if i + j + 1 < 4:
                acc[i + j + 1] = acc[i + j + 1] + carry
    return _from_limbs(*acc)


def _int64_limbs_signext(v: jnp.ndarray) -> list[jnp.ndarray]:
    """int64 vector → four sign-extended uint32 limbs (two's complement)."""
    sign = jnp.where(v < 0, _MASK32, jnp.int64(0))
    return [v & _MASK32, (v >> 32) & _MASK32, sign, sign]


def mul_int(a: Column, b: Column, result_scale: int | None = None) -> Column:
    """decimal128 × integer column (elementwise), mod 2^128."""
    bl = _int64_limbs_signext(b.data.astype(jnp.int64))
    out = _mul_lanes(a.data, bl)
    scale = a.dtype.scale if result_scale is None else result_scale
    return Column(T.decimal128(scale), out, validity=_combine_validity(a, b))


def mul(a: Column, b: Column) -> Column:
    """decimal128 × decimal128 (mod 2^128); result scale = sum of scales."""
    out = _mul_lanes(a.data, _limbs(b.data))
    return Column(T.decimal128(a.dtype.scale + b.dtype.scale), out,
                  validity=_combine_validity(a, b))


def _negate_lanes(lanes: jnp.ndarray) -> jnp.ndarray:
    l0, l1, l2, l3 = [(~x) & _MASK32 for x in _limbs(lanes)]
    return _from_limbs(l0 + 1, l1, l2, l3)


def _add_const(lanes: jnp.ndarray, c: int) -> jnp.ndarray:
    """lanes + python-int constant (mod 2^128)."""
    u = c & ((1 << 128) - 1)
    climbs = [jnp.full_like(lanes[:, 0], (u >> (32 * i)) & 0xFFFFFFFF)
              for i in range(4)]
    return _from_limbs(*(x + y for x, y in zip(_limbs(lanes), climbs)))


def _div_small(lanes: jnp.ndarray, d: int) -> jnp.ndarray:
    """Truncating divide of a NON-NEGATIVE 128-bit value by d < 2^31.

    Schoolbook long division over the four uint32 limbs, high→low; the
    partial dividend r*2^32 + limb stays < 2^62 because r < d < 2^31.
    """
    l = _limbs(lanes)
    q = [None] * 4
    r = jnp.zeros_like(l[0])
    for i in (3, 2, 1, 0):
        cur = (r << 32) | l[i]
        q[i] = cur // d
        r = cur % d
    return _from_limbs(q[0], q[1], q[2], q[3])


def rescale(a: Column, new_scale: int) -> Column:
    """Change scale: ×10^k toward finer scales, ÷10^k (round half away from
    zero, Spark's decimal rescale convention — see ops/cast.py::_rescale)
    toward coarser ones."""
    k = a.dtype.scale - new_scale
    lanes = a.data
    if k >= 0:
        while k > 0:                          # 10^9 < 2^32: limb-safe steps
            step = min(9, k)
            ten = jnp.full_like(a.data[:, 0], 10 ** step)
            lanes = _mul_lanes(lanes, _int64_limbs_signext(ten))
            k -= step
        return Column(T.decimal128(new_scale), lanes, validity=a.validity)
    k = -k
    divisor = 10 ** k
    neg = lanes[:, 1] < 0
    mag = jnp.where(neg[:, None], _negate_lanes(lanes), lanes)
    mag = _add_const(mag, divisor // 2)       # round half away from zero
    while k > 0:   # truncating divide composes: ⌊⌊x/a⌋/b⌋ = ⌊x/(ab)⌋ for x≥0
        step = min(9, k)
        mag = _div_small(mag, 10 ** step)
        k -= step
    out = jnp.where(neg[:, None], _negate_lanes(mag), mag)
    return Column(T.decimal128(new_scale), out, validity=a.validity)


# -- comparison & sort lanes -------------------------------------------------

def sort_key_lanes(a: Column, descending: bool = False) -> list[jnp.ndarray]:
    """Lanes for jnp.lexsort, increasing priority order (lo first, hi last).

    The low lane compares unsigned: flipping the top bit maps uint64 order
    onto int64 order.
    """
    lo = a.data[:, 0] ^ _TOPBIT
    hi = a.data[:, 1]
    if descending:
        lo, hi = ~lo, ~hi
    return [lo, hi]


def less_than(a: Column, b: Column) -> Column:
    hi_lt = a.data[:, 1] < b.data[:, 1]
    hi_eq = a.data[:, 1] == b.data[:, 1]
    lo_lt = (a.data[:, 0] ^ _TOPBIT) < (b.data[:, 0] ^ _TOPBIT)
    out = (hi_lt | (hi_eq & lo_lt)).astype(jnp.uint8)
    return Column(T.bool8, out, validity=_combine_validity(a, b))


def equal_to(a: Column, b: Column) -> Column:
    out = ((a.data[:, 0] == b.data[:, 0]) &
           (a.data[:, 1] == b.data[:, 1])).astype(jnp.uint8)
    return Column(T.bool8, out, validity=_combine_validity(a, b))


# -- reductions --------------------------------------------------------------

def sum_(a: Column) -> Column:
    """Full-column sum (mod 2^128), nulls skipped — returns a 1-row column."""
    limbs = _limbs(a.data)
    if a.validity is not None:
        keep = a.validity.astype(jnp.int64)
        limbs = [x * keep for x in limbs]
    # 32-bit limbs summed in int64: safe for n < 2^31 rows per partial; use
    # a two-level sum for headroom at any realistic column size.
    sums = [jnp.sum(x.reshape(-1)) for x in limbs]
    lanes = _from_limbs(*[s[None] for s in sums])
    return Column(a.dtype, lanes, validity=None)


def segmented_sum(a: Column, segment_ids: jnp.ndarray,
                  num_segments: int) -> Column:
    """Per-group sum (mod 2^128) — the groupby aggregation kernel."""
    limbs = _limbs(a.data)
    if a.validity is not None:
        keep = a.validity.astype(jnp.int64)
        limbs = [x * keep for x in limbs]
    sums = [jax_segment_sum(x, segment_ids, num_segments) for x in limbs]
    lanes = _from_limbs(*sums)
    return Column(a.dtype, lanes, validity=None)


def jax_segment_sum(x: jnp.ndarray, seg: jnp.ndarray, n: int) -> jnp.ndarray:
    return jnp.zeros((n,), x.dtype).at[seg].add(x)


# -- casts -------------------------------------------------------------------

def widen(a: Column, scale: int | None = None) -> Column:
    """decimal32/64 (or integer) column → decimal128.

    Signed sources sign-extend into the high lane; unsigned sources
    zero-extend (a UINT64 ≥ 2^63 keeps its int64 *bit pattern* in the low
    lane but hi stays 0, preserving the value).
    """
    v = a.data.astype(jnp.int64)
    if a.dtype.is_fixed_width and a.dtype.storage.kind == "u":
        hi = jnp.zeros_like(v)
    else:
        hi = jnp.where(v < 0, jnp.int64(-1), jnp.int64(0))
    lanes = jnp.stack([v, hi], axis=1)
    if scale is None:
        scale = a.dtype.scale if a.dtype.is_decimal else 0
    return Column(T.decimal128(scale), lanes, validity=a.validity)


def narrow(a: Column, to: T.DType) -> Column:
    """decimal128 → decimal64/32 (values must fit; truncates like a C cast)."""
    lo = a.data[:, 0]
    return Column(to, lo.astype(jnp.dtype(to.storage)), validity=a.validity)


def to_float64(a: Column) -> Column:
    """decimal128 → float64 (approximate above 2^53, like cudf's cast).

    Converts the two's-complement *magnitude* and reapplies the sign —
    summing hi*2^64 + unsigned(lo) directly would cancel catastrophically
    for small negative values (ulp(2^64) = 4096).
    """
    neg = a.data[:, 1] < 0
    l0, l1, l2, l3 = [(~x) & _MASK32 for x in _limbs(a.data)]
    negated = _from_limbs(l0 + 1, l1, l2, l3)
    mag = jnp.where(neg[:, None], negated, a.data)
    lo, hi = mag[:, 0], mag[:, 1]
    loval = lo.astype(jnp.float64) + jnp.where(lo < 0, 2.0 ** 64, 0.0)
    hival = hi.astype(jnp.float64) + jnp.where(hi < 0, 2.0 ** 64, 0.0)
    val = hival * (2.0 ** 64) + loval
    val = jnp.where(neg, -val, val) * (10.0 ** a.dtype.scale)
    return Column.from_values(T.float64, val, validity=a.validity)
