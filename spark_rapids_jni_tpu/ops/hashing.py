"""Murmur3 x86_32 hashing — Spark-compatible, vectorized for the VPU.

The reference delegates hashing to libcudf (SURVEY §2.9); Spark's shuffle
partitioner uses Murmur3 x86_32 with seed 42 over the row's bytes, treating
ints as one 4-byte block and longs as two 4-byte blocks (low word first).
This is a lane-parallel reimplementation of the public MurmurHash3 algorithm
(Austin Appleby, public domain) in jnp uint32 arithmetic — every row hashes in
registers, no byte loops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_SEED = np.uint32(42)  # Spark's Murmur3Hash seed

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)


def _rotl32(x, r):
    return (x << r) | (x >> (32 - r))


def _mix_k(k):
    k = (k * _C1).astype(jnp.uint32)
    k = _rotl32(k, 15)
    return (k * _C2).astype(jnp.uint32)


def _mix_h(h, k):
    h = h ^ _mix_k(k)
    h = _rotl32(h, 13)
    return (h * np.uint32(5) + np.uint32(0xE6546B64)).astype(jnp.uint32)


def _fmix(h):
    h = h ^ (h >> 16)
    h = (h * np.uint32(0x85EBCA6B)).astype(jnp.uint32)
    h = h ^ (h >> 13)
    h = (h * np.uint32(0xC2B2AE35)).astype(jnp.uint32)
    return h ^ (h >> 16)


def murmur3_32(values: jnp.ndarray,
               seed: np.uint32 = DEFAULT_SEED) -> jnp.ndarray:
    """Hash an integer array per element; returns uint32 [n].

    int8/16/32 hash as one 4-byte block (sign-extended to 32 bits, as Spark
    does); int64/uint64 as two 4-byte blocks, low word first.
    """
    dt = values.dtype
    if dt.kind == "f":
        # Spark hashes floats by their Java floatToIntBits pattern, with
        # -0.0 normalized to 0.0 and NaN canonicalized.  f64 has no device
        # bit access on TPU (see rowconv/convert.py), so only f32 here.
        if dt.itemsize != 4:
            raise TypeError(
                "murmur3_32: float64 keys are not hashable on device "
                "(no f64 bit access on TPU); cast or hash on host")
        v = jnp.where(values == 0.0, jnp.float32(0.0), values)
        bits = jax.lax.bitcast_convert_type(v, jnp.uint32)
        values = jnp.where(jnp.isnan(v), jnp.uint32(0x7FC00000), bits)
        dt = values.dtype
    elif dt.kind == "b":
        values = values.astype(jnp.int32)
        dt = values.dtype
    elif dt.kind not in ("i", "u"):
        raise TypeError(f"murmur3_32: unsupported key dtype {dt}")

    h = jnp.full(values.shape, seed, dtype=jnp.uint32)
    if dt.itemsize <= 4:
        block = values.astype(jnp.int32).view(jnp.uint32) \
            if dt != jnp.uint32 else values
        h = _mix_h(h, block)
        length = np.uint32(4)
    else:
        v = values.view(jnp.uint64) if dt == jnp.int64 else values
        lo = (v & np.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        hi = (v >> np.uint64(32)).astype(jnp.uint32)
        h = _mix_h(h, lo)
        h = _mix_h(h, hi)
        length = np.uint32(8)
    return _fmix(h ^ length)


# second-chain seed for 64-bit fingerprints: an arbitrary constant far from
# Spark's 42 so the two 32-bit chains decorrelate
_FP_SEED_HI = np.uint32(0x9E3779B9)


def fingerprint64(lanes) -> jnp.ndarray:
    """Order-sensitive 64-bit fingerprint of a key tuple → int64 [n].

    Two independent murmur3 chains in Spark's multi-column shape (each
    column's hash seeds the next — ``murmur3_32`` broadcasts array seeds)
    with distinct initial seeds form the low and high words.  Collisions
    are possible: callers MUST verify true lane equality on candidate
    pairs (``ops.join`` does) — the fingerprint is a probe lane, not an
    equality proof.
    """
    if not lanes:
        raise ValueError("fingerprint64: at least one key lane required")
    lo = hi = None
    for lane in lanes:
        lo = murmur3_32(lane, DEFAULT_SEED if lo is None else lo)
        hi = murmur3_32(lane, _FP_SEED_HI if hi is None else hi)
    u = lo.astype(jnp.uint64) | (hi.astype(jnp.uint64) << np.uint64(32))
    # reinterpret as int64: the join engines' key dtype, bit pattern kept
    return jax.lax.bitcast_convert_type(u, jnp.int64)


def hash_partition(hashes: jnp.ndarray, num_partitions: int) -> jnp.ndarray:
    """Spark-style non-negative modulo partitioning → int32 [n] in [0, P)."""
    m = (hashes.astype(jnp.int32) % np.int32(num_partitions)).astype(jnp.int32)
    return jnp.where(m < 0, m + num_partitions, m)
