"""Cumulative (scan) operations — cudf scan / Spark running-aggregate analog.

Null policy matches cudf's ``null_policy::EXCLUDE`` (what Spark's running
aggregates need): null inputs contribute the identity to the running value
and stay null in the output; valid rows see the accumulation over valid
rows so far.  All scans are single XLA ops (``cumsum``/``cummax``/…) —
associative-scan friendly on TPU.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..column import Column


def _identity(kind: str, dtype, op: str):
    if op == "sum":
        return jnp.zeros((), dtype)
    if op == "min":
        return (jnp.asarray(jnp.inf, dtype) if kind == "f"
                else jnp.asarray(np.iinfo(np.dtype(dtype)).max, dtype))
    if op == "max":
        return (jnp.asarray(-jnp.inf, dtype) if kind == "f"
                else jnp.asarray(np.iinfo(np.dtype(dtype)).min, dtype))
    raise ValueError(f"unknown scan op {op!r}")


def _scan(col: Column, op: str) -> Column:
    if (col.dtype.is_variable_width or col.dtype.is_nested
            or col.dtype.id == T.TypeId.DECIMAL128):
        raise TypeError(f"scan not supported on {col.dtype.id.name}")
    data = col.values()   # FLOAT64 bit pairs decode to f64 values
    out_dt = col.dtype
    if op == "sum":
        # accumulate in 64-bit like Spark's running sum; decimals keep
        # their scale but widen to decimal64 (decimal32 would wrap)
        if col.dtype.is_decimal:
            out_dt = T.decimal64(col.dtype.scale)
        else:
            out_dt = T.float64 if col.dtype.storage.kind == "f" else T.int64
        data = data.astype(out_dt.storage)
    if col.validity is not None:
        ident = _identity(col.dtype.storage.kind, data.dtype, op)
        data = jnp.where(col.validity, data, ident)
    if op == "sum":
        res = jnp.cumsum(data)
    elif op == "min":
        res = jax_cummin(data)
    else:
        res = jax_cummax(data)
    if out_dt.id == T.TypeId.FLOAT64:
        return Column.from_values(out_dt, res, validity=col.validity)
    return Column(out_dt, res.astype(out_dt.storage), validity=col.validity)


def jax_cummax(x: jnp.ndarray) -> jnp.ndarray:
    import jax
    return jax.lax.associative_scan(jnp.maximum, x)


def jax_cummin(x: jnp.ndarray) -> jnp.ndarray:
    import jax
    return jax.lax.associative_scan(jnp.minimum, x)


def cumulative_sum(col: Column) -> Column:
    return _scan(col, "sum")


def cumulative_min(col: Column) -> Column:
    return _scan(col, "min")


def cumulative_max(col: Column) -> Column:
    return _scan(col, "max")


def cumulative_count(col: Column) -> Column:
    """Running count of VALID rows (Spark count over an expanding window)."""
    ones = (col.validity.astype(jnp.int64) if col.validity is not None
            else jnp.ones((col.num_rows,), jnp.int64))
    return Column(T.int64, jnp.cumsum(ones))
