from . import hashing  # noqa: F401
from . import strings  # noqa: F401
from . import window  # noqa: F401
from .cast import cast  # noqa: F401
from .filter import (apply_boolean_mask, fill_null, gather,  # noqa: F401
                     isin, mask_table)
from .copying import concat_tables, slice_table  # noqa: F401
from .groupby import (distinct, groupby_aggregate,  # noqa: F401
                      groupby_cube, groupby_grouping_sets, groupby_nunique,
                      groupby_rollup)
from .join import (anti_join, full_outer_join, inner_join,  # noqa: F401
                   join_indices, left_join, right_join, semi_join)
from . import join_plan  # noqa: F401
from .join_plan import join_aggregate  # noqa: F401
from .scan import (cumulative_count, cumulative_max,  # noqa: F401
                   cumulative_min, cumulative_sum)
from .reductions import max_, mean, min_, sum_, valid_count  # noqa: F401
from .sort import order_by, sort_table  # noqa: F401
