from . import hashing  # noqa: F401
