"""Window functions (libcudf rolling/grouped-window analog, Spark
``OVER (PARTITION BY … ORDER BY …)`` semantics).

TPU-first formulation: one lexsort puts rows in (partition, order) order,
then every window primitive is a *segmented scan* — a plain prefix scan
corrected at segment heads — so each costs O(n) fused vector work and no
per-partition loops.  Results are scattered back to the input row order.

Supported: row_number, rank, dense_rank, lag/lead, and partitioned running
sum/min/max/count (the grouped-rolling slice the Spark plugin uses most).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..column import Column, Table
from .groupby import neq_with_null_merge
from .scan import jax_cummax
from .sort import order_by


class WindowSpec:
    """Resolved window: rows pre-sorted by (partition, order), with the
    inverse permutation to scatter results back to input order."""

    def __init__(self, table: Table, partition_by: Sequence[int],
                 order_by_keys: Sequence[int],
                 ascending: Sequence[bool] | None = None):
        self.table = table
        n = table.num_rows
        keys = list(partition_by) + list(order_by_keys)
        asc = ([True] * len(partition_by)
               + (list(ascending) if ascending else
                  [True] * len(order_by_keys)))
        self.order = order_by(table, keys, asc)
        self.inv = jnp.zeros(n, jnp.int32).at[self.order].set(
            jnp.arange(n, dtype=jnp.int32))
        # segment heads: partition-key change between adjacent sorted rows
        head = jnp.zeros(n, dtype=jnp.bool_)
        if n:
            head = head.at[0].set(True)
        for ki in partition_by:
            col = table[ki]
            if col.dtype.id == T.TypeId.FLOAT64:
                # bit pairs canonicalized (-0.0 == 0.0, NaNs equal — Spark
                # grouping equality)
                from ..utils.f64bits import group_key_lanes
                lo, hi = group_key_lanes(col.data)
                k = jnp.stack([lo, hi], axis=1)[self.order]
                neq = (k[1:] != k[:-1]).any(axis=1)
            elif col.dtype.id == T.TypeId.DECIMAL128:
                k = col.data[self.order]
                neq = (k[1:] != k[:-1]).any(axis=1)
            elif col.dtype.is_variable_width:
                from . import strings
                codes, _ = strings.dictionary_encode(col)
                k = codes.data[self.order]
                neq = k[1:] != k[:-1]
            else:
                k = col.data[self.order]
                neq = k[1:] != k[:-1]
            v = col.validity
            if v is not None:
                sv = v[self.order]
                neq = neq_with_null_merge(neq, sv[1:], sv[:-1])
            head = head.at[1:].max(neq)
        self.head = head
        self.seg_id = jnp.cumsum(head.astype(jnp.int32)) - 1

    # -- segmented-scan core ------------------------------------------------
    def _seg_base(self, scanned: jnp.ndarray) -> jnp.ndarray:
        """Per-row value of ``scanned`` at the row's segment head minus one
        step — the correction that turns a global scan into a segmented one.
        ``scanned`` must be an INCLUSIVE global scan."""
        n = scanned.shape[0]
        head_pos = jnp.where(self.head, jnp.arange(n, dtype=jnp.int32), 0)
        head_pos = jax_cummax(head_pos)
        prev = jnp.where(head_pos > 0, scanned[jnp.maximum(head_pos - 1, 0)],
                         jnp.zeros((), scanned.dtype))
        return jnp.where(head_pos > 0, prev, jnp.zeros((), scanned.dtype))

    def _to_input_order(self, sorted_vals: jnp.ndarray,
                        dtype: T.DType, validity=None) -> Column:
        vals = sorted_vals[self.inv]
        v = None if validity is None else validity[self.inv]
        if dtype.id == T.TypeId.FLOAT64:
            if vals.ndim == 2:          # already u32 bit pairs (shift path)
                return Column(dtype, vals, validity=v)
            return Column.from_values(dtype, vals, validity=v)
        return Column(dtype, vals.astype(dtype.storage), validity=v)


def row_number(spec: WindowSpec) -> Column:
    """1-based position within the partition (Spark row_number())."""
    n = spec.table.num_rows
    pos = jnp.arange(n, dtype=jnp.int64) + 1
    base = spec._seg_base(pos)
    return spec._to_input_order(pos - base, T.int64)


def _order_change(spec: WindowSpec, order_keys: Sequence[int]) -> jnp.ndarray:
    """bool [n]: sorted row differs from its predecessor on the ORDER keys
    (or starts a partition) — the tie boundary for rank/dense_rank."""
    n = spec.table.num_rows
    change = spec.head
    for ki in order_keys:
        col = spec.table[ki]
        if col.dtype.is_variable_width:
            from . import strings
            codes, _ = strings.dictionary_encode(col)
            k = codes.data[spec.order]
        elif col.dtype.id == T.TypeId.FLOAT64:
            from ..utils.f64bits import group_key_lanes
            lo, hi = group_key_lanes(col.data)
            k = jnp.stack([lo, hi], axis=1)[spec.order]
        else:
            k = col.data[spec.order]
        if k.ndim == 2:   # decimal128 limbs / canonical f64 bit lanes
            neq = (k[1:] != k[:-1]).any(axis=1)
        else:
            neq = k[1:] != k[:-1]
        if col.validity is not None:
            # NULL is its own rank value (Spark: null sorts distinctly),
            # but all NULLs TIE with each other
            sv = col.validity[spec.order]
            neq = neq_with_null_merge(neq, sv[1:], sv[:-1])
        change = change.at[1:].max(neq)
    return change


def rank(spec: WindowSpec, order_keys: Sequence[int]) -> Column:
    """Spark rank(): ties share a rank, gaps after ties."""
    n = spec.table.num_rows
    change = _order_change(spec, order_keys)
    pos = jnp.arange(n, dtype=jnp.int64) + 1
    # rank = row number of the first row of the tie run, within partition
    run_start = jax_cummax(jnp.where(change, pos, 0))
    base = spec._seg_base(pos)
    return spec._to_input_order(run_start - base, T.int64)


def dense_rank(spec: WindowSpec, order_keys: Sequence[int]) -> Column:
    """Spark dense_rank(): ties share a rank, no gaps."""
    change = _order_change(spec, order_keys)
    distinct = jnp.cumsum(change.astype(jnp.int64))
    base = spec._seg_base(distinct)
    return spec._to_input_order(distinct - base, T.int64)


def lag(spec: WindowSpec, value_col: int, offset: int = 1) -> Column:
    """Value ``offset`` rows earlier in the partition; null at the head."""
    return _shift(spec, value_col, offset)


def lead(spec: WindowSpec, value_col: int, offset: int = 1) -> Column:
    """Value ``offset`` rows later in the partition; null at the tail."""
    return _shift(spec, value_col, -offset)


def _shift(spec: WindowSpec, value_col: int, offset: int) -> Column:
    col = spec.table[value_col]
    if col.dtype.is_variable_width or col.dtype.is_nested:
        raise TypeError(f"lag/lead not supported on {col.dtype.id.name}")
    n = col.num_rows
    idx = jnp.arange(n, dtype=jnp.int32)
    src = idx - offset
    in_bounds = (src >= 0) & (src < n)
    src_c = jnp.clip(src, 0, max(n - 1, 0))
    sorted_vals = col.data[spec.order][src_c]
    # crossing a partition boundary is out-of-window → null
    same_part = spec.seg_id == spec.seg_id[src_c]
    ok = in_bounds & same_part
    sv = col.validity
    if sv is not None:
        ok = ok & sv[spec.order][src_c]
    return spec._to_input_order(sorted_vals, col.dtype, validity=ok)


def _check_scannable(col: Column) -> None:
    if (col.dtype.is_variable_width or col.dtype.is_nested
            or col.dtype.id == T.TypeId.DECIMAL128):
        raise TypeError(
            f"window scans not supported on {col.dtype.id.name}")


def running_sum(spec: WindowSpec, value_col: int) -> Column:
    """Partitioned running sum over the window order (nulls contribute 0,
    stay null — the scan EXCLUDE policy, see ops.scan)."""
    col = spec.table[value_col]
    _check_scannable(col)
    acc_dt = (T.decimal64(col.dtype.scale) if col.dtype.is_decimal
              else T.float64 if col.dtype.storage.kind == "f"
              else T.int64)
    data = col.values()[spec.order].astype(acc_dt.storage)
    sv = None if col.validity is None else col.validity[spec.order]
    if sv is not None:
        data = jnp.where(sv, data, 0)
    scanned = jnp.cumsum(data)
    out = scanned - spec._seg_base(scanned)
    return spec._to_input_order(out, acc_dt, validity=sv)


def running_count(spec: WindowSpec, value_col: int) -> Column:
    col = spec.table[value_col]
    ones = (col.validity[spec.order].astype(jnp.int64)
            if col.validity is not None
            else jnp.ones((col.num_rows,), jnp.int64))
    scanned = jnp.cumsum(ones)
    return spec._to_input_order(scanned - spec._seg_base(scanned), T.int64)


def _running_extreme(spec: WindowSpec, value_col: int, is_max: bool) -> Column:
    """Segmented cummax/cummin: associative scan over (reset, value) pairs
    — max/min has no subtraction trick, so segment heads carry a reset flag
    through the scan instead."""
    col = spec.table[value_col]
    _check_scannable(col)
    data = col.values()[spec.order]   # FLOAT64 bit pairs decode to values
    sv = None if col.validity is None else col.validity[spec.order]
    kind = col.dtype.storage.kind
    if is_max:
        ident = (-jnp.inf if kind == "f"
                 else np.iinfo(np.dtype(col.dtype.storage)).min)
        combine = jnp.maximum
    else:
        ident = (jnp.inf if kind == "f"
                 else np.iinfo(np.dtype(col.dtype.storage)).max)
        combine = jnp.minimum
    if sv is not None:
        data = jnp.where(sv, data, jnp.asarray(ident, data.dtype))

    def op(a, b):
        fa, va = a
        fb, vb = b
        return fa | fb, jnp.where(fb, vb, combine(va, vb))

    _, out = jax.lax.associative_scan(op, (spec.head, data))
    return spec._to_input_order(out, col.dtype, validity=sv)


def running_max(spec: WindowSpec, value_col: int) -> Column:
    """Partitioned running max (nulls skipped, stay null)."""
    return _running_extreme(spec, value_col, True)


def running_min(spec: WindowSpec, value_col: int) -> Column:
    """Partitioned running min (nulls skipped, stay null)."""
    return _running_extreme(spec, value_col, False)
