"""Table copy operations: concatenate and slice.

The reference gets these from libcudf (``cudf::concatenate``,
``cudf::slice`` — SURVEY §2.9); here they are thin, fully device-side
compositions: concatenation is per-column buffer concat with offset
rebasing, slicing is a static-bound buffer slice (XLA wants static shapes,
and Spark partitions give static bounds at plan time).
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from .. import types as T
from ..column import Column, Table


def _concat_validity(cols: Sequence[Column]):
    if all(c.validity is None for c in cols):
        return None
    return jnp.concatenate([c.validity_or_true() for c in cols])


def _concat_columns(cols: Sequence[Column]) -> Column:
    dt = cols[0].dtype
    for c in cols:
        if c.dtype != dt:
            raise TypeError(f"concat dtype mismatch: {c.dtype} vs {dt}")
    v = _concat_validity(cols)
    if dt.id == T.TypeId.STRUCT:
        children = [_concat_columns([c.children[i] for c in cols])
                    for i in range(len(dt.children))]
        return Column(dt, cols[0].data, None, v, children)
    if dt.id == T.TypeId.LIST:
        child = _concat_columns([c.children[0] for c in cols])
        offs = _rebase_offsets(cols)
        return Column(dt, cols[0].data, offs, v, [child])
    if dt.is_variable_width:    # STRING: chars live in .data
        chars = jnp.concatenate([c.data for c in cols])
        return Column(dt, chars, _rebase_offsets(cols), v)
    return Column(dt, jnp.concatenate([c.data for c in cols]), validity=v)


def _rebase_offsets(cols: Sequence[Column]) -> jnp.ndarray:
    parts = [cols[0].offsets]
    base = cols[0].offsets[-1]
    for c in cols[1:]:
        parts.append(c.offsets[1:] + base)
        base = base + c.offsets[-1]
    return jnp.concatenate(parts)


def concat_tables(tables: Sequence[Table]) -> Table:
    """Row-wise concatenation (cudf::concatenate analog).

    Columns are deferred (see ``ops.filter.gather``): concatenating lazy
    join outputs must not force columns the plan never reads.
    """
    from ..column import LazyColumn
    tables = list(tables)
    if not tables:
        raise ValueError("concat_tables needs at least one table")
    ncols = tables[0].num_columns
    for t in tables:
        if t.num_columns != ncols:
            raise ValueError("concat_tables: column count mismatch")
    for i in range(ncols):
        dt = tables[0][i].dtype
        for t in tables[1:]:
            if t[i].dtype != dt:
                # schema errors must surface at the call site, not when a
                # deferred column is eventually forced
                raise TypeError(
                    f"concat dtype mismatch: {t[i].dtype} vs {dt}")
    n_out = sum(t.num_rows for t in tables)
    # capture only the per-index column list: a thunk closing over the
    # full `tables` would pin every column of every input (including
    # already-materialized wide join outputs) until forced or dropped
    cols_by_index = [[t[i] for t in tables] for i in range(ncols)]
    return Table([
        LazyColumn(tables[0][i].dtype, n_out,
                   (lambda cols=cols_by_index[i]: _concat_columns(cols)))
        for i in range(ncols)])


def _slice_column(col: Column, start: int, stop: int) -> Column:
    v = None if col.validity is None else col.validity[start:stop]
    if col.dtype.id == T.TypeId.STRUCT:
        return Column(col.dtype, col.data, None, v,
                      [_slice_column(ch, start, stop) for ch in col.children])
    if col.dtype.id == T.TypeId.LIST:
        offs = col.offsets[start:stop + 1]
        from ..utils import syncs
        c0, c1 = syncs.scalar(offs[0]), syncs.scalar(offs[-1])
        return Column(col.dtype, col.data, offs - offs[0], v,
                      [_slice_column(col.children[0], c0, c1)])
    if col.dtype.is_variable_width:
        offs = col.offsets[start:stop + 1]
        from ..utils import syncs
        c0, c1 = syncs.scalar(offs[0]), syncs.scalar(offs[-1])
        return Column(col.dtype, col.data[c0:c1], offs - offs[0], v)
    return Column(col.dtype, col.data[start:stop], validity=v)


def slice_table(table: Table, start: int, length: int | None = None) -> Table:
    """Zero-based row slice with static host bounds (cudf::slice analog)."""
    n = table.num_rows
    start = max(0, min(start, n))
    stop = n if length is None else max(start, min(start + length, n))
    return Table([_slice_column(c, start, stop) for c in table.columns])
