"""Sort-based groupby-aggregate (libcudf groupby analog).

TPU-first design: hash-based groupby (libcudf's default) wants random
scatter and open addressing — hostile to the VPU.  Sort-based groupby is the
idiomatic XLA formulation: lexsort keys → flag segment heads → segment-id via
inclusive scan → ``jax.ops.segment_*`` reductions, every step a fused vector
pass.  ``num_segments`` must be static under jit, so the public API resolves
the group count with one scalar sync (same two-phase discipline as
strings/filter); ``groupby_aggregate_static`` is the fully-jittable variant
for pipelines that can bound the group count.

Supported aggs mirror the TPC-DS subset need (BASELINE config #3): sum,
count, min, max, mean — all null-aware (Spark semantics: aggregates skip
nulls; count counts valid rows).
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..column import Column, Table
from ..utils import metrics
from .filter import gather
from .sort import order_by

_AGGS = ("sum", "count", "min", "max", "mean", "var", "std",
         "first", "last")

#: aggregates with a merge-closed partial-state decomposition (the
#: streaming/incremental-maintenance subset; see AggStateSpec below)
MERGEABLE_AGGS = ("sum", "count", "min", "max", "mean", "var", "std")


def _segment_ids(sorted_keys: list[jnp.ndarray],
                 sorted_valid: list[jnp.ndarray]) -> jnp.ndarray:
    """Segment id per sorted row: 0-based, increases at each new key tuple."""
    n = sorted_keys[0].shape[0]
    head = jnp.zeros(n, dtype=jnp.int32)
    for k, v in zip(sorted_keys, sorted_valid):
        neq = k[1:] != k[:-1]
        if v is not None:
            # nulls form ONE group regardless of dead payload bytes (a
            # mask_table'd column keeps its stale payload under nulls)
            neq = neq_with_null_merge(neq, v[1:], v[:-1])
        head = head.at[1:].max(neq.astype(jnp.int32))
    return jnp.cumsum(head, dtype=jnp.int32)


def _agg_segment(data, valid, seg_ids, agg, num_segments, storage_kind):
    if agg == "count":
        ones = jnp.ones_like(seg_ids, dtype=jnp.int64) if valid is None \
            else valid.astype(jnp.int64)
        return jax.ops.segment_sum(ones, seg_ids, num_segments)
    if agg in ("sum", "mean"):
        acc = data.astype(jnp.float64 if storage_kind == "f" else jnp.int64)
        acc = acc if valid is None else jnp.where(valid, acc, 0)
        s = jax.ops.segment_sum(acc, seg_ids, num_segments)
        if agg == "sum":
            return s
        cnt = _agg_segment(data, valid, seg_ids, "count", num_segments,
                           storage_kind)
        return s.astype(jnp.float64) / jnp.maximum(cnt, 1).astype(jnp.float64)
    if agg in ("first", "last"):
        # first/last VALID value per group (Spark first/last ignoreNulls):
        # min/max over valid row positions, then gather
        n = data.shape[0]
        pos = jnp.arange(n, dtype=jnp.int32)
        if agg == "first":
            vpos = pos if valid is None else jnp.where(valid, pos, n)
            p = jax.ops.segment_min(vpos, seg_ids, num_segments)
        else:
            vpos = pos if valid is None else jnp.where(valid, pos, -1)
            p = jax.ops.segment_max(vpos, seg_ids, num_segments)
        return data[jnp.clip(p, 0, max(n - 1, 0))]
    if agg == "min":
        ident = np.inf if storage_kind == "f" else np.iinfo(data.dtype).max
        acc = data if valid is None else jnp.where(valid, data, ident)
        return jax.ops.segment_min(acc, seg_ids, num_segments)
    if agg == "max":
        ident = -np.inf if storage_kind == "f" else np.iinfo(data.dtype).min
        acc = data if valid is None else jnp.where(valid, data, ident)
        return jax.ops.segment_max(acc, seg_ids, num_segments)
    raise ValueError(f"unknown aggregation {agg!r} (supported: {_AGGS})")


def _f64_select_pos(col, seg_ids, num_segments, agg):
    """Row position per segment whose FLOAT64 bits the selection aggregate
    returns (see the FLOAT64 branch in :func:`groupby_aggregate`)."""
    n = col.data.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    valid = col.validity
    if agg == "first":
        vpos = pos if valid is None else jnp.where(valid, pos, n)
        return jax.ops.segment_min(vpos, seg_ids, num_segments)
    if agg == "last":
        vpos = pos if valid is None else jnp.where(valid, pos, -1)
        return jax.ops.segment_max(vpos, seg_ids, num_segments)
    from .sort import f64_sort_key_lanes
    lo_k, hi_k = f64_sort_key_lanes(col)
    key = (hi_k.astype(jnp.uint64) << 32) | lo_k.astype(jnp.uint64)
    if agg == "max":
        key = ~key
    if valid is not None:
        key = jnp.where(valid, key, jnp.uint64(0xFFFFFFFFFFFFFFFF))
    best = jax.ops.segment_min(key, seg_ids, num_segments)
    hit = key == best[seg_ids]
    if valid is not None:
        # a valid extreme can tie the invalid sentinel (valid -inf under
        # max, all-NaN under min) — never gather a null row's stale bits
        hit = hit & valid
    vpos = jnp.where(hit, pos, n)
    return jax.ops.segment_min(vpos, seg_ids, num_segments)


def _var_segment(x, valid, seg_ids, num_segments, cnt, std: bool):
    """Sample variance/stddev (ddof=1, Spark var_samp/stddev_samp), two-pass:
    segment mean first, then squared deviations — the one-pass
    sum-of-squares identity cancels catastrophically when the mean
    dominates the spread (e.g. values ~1e8 with variance 1)."""
    x = x.astype(jnp.float64)
    x = x if valid is None else jnp.where(valid, x, 0.0)
    cntf = cnt.astype(jnp.float64)
    mean = (jax.ops.segment_sum(x, seg_ids, num_segments)
            / jnp.maximum(cntf, 1.0))
    dev = x - mean[seg_ids]
    if valid is not None:
        dev = jnp.where(valid, dev, 0.0)
    m2 = jax.ops.segment_sum(dev * dev, seg_ids, num_segments)
    var = m2 / jnp.maximum(cntf - 1.0, 1.0)
    return jnp.sqrt(var) if std else var


def neq_with_null_merge(neq, v1, v0):
    """Adjacent-key inequality honoring nulls-form-one-group: a validity
    flip is a boundary, and two null neighbors are EQUAL regardless of
    their dead payload bytes (shared by groupby segments, window
    partitions, and rank order keys)."""
    return (neq & v1 & v0) | (v1 != v0)


def groupby_aggregate(table: Table, key_indices: Sequence[int],
                      aggs: Sequence[tuple[int, str]]) -> Table:
    """GROUP BY keys, computing (value_column_index, agg_name) pairs.

    Returns a table of [key columns..., agg results...], one row per distinct
    key tuple (sorted by key — a stable, deterministic output order).
    """
    with metrics.span("groupby.aggregate", keys=len(key_indices),
                      aggs=len(aggs), rows=table.num_rows):
        return _groupby_aggregate(table, key_indices, aggs)


def _groupby_aggregate(table: Table, key_indices: Sequence[int],
                       aggs: Sequence[tuple[int, str]]) -> Table:
    n = table.num_rows
    if n == 0:
        if not key_indices:
            # GROUP BY () over an empty relation: Spark still emits ONE
            # grand-total row (count = 0, other aggregates null)
            return _grand_total_empty(table, aggs)
        # keyed GROUP BY over no rows: empty result (Spark semantics)
        return _empty_result(table, key_indices, aggs)
    # string keys: swap in order-preserving dictionary codes (ops.strings) so
    # ordering/segmenting below see plain int32 lanes; the output key columns
    # are decoded from the dictionary at the end
    str_dicts: dict[int, Column] = {}
    work_cols = list(table.columns)
    for ki in key_indices:
        if table[ki].dtype.is_nested:
            raise NotImplementedError(
                f"{table[ki].dtype.id.name} columns cannot be groupby/"
                "distinct keys")
        if table[ki].dtype.is_variable_width:
            from . import strings
            from ..column import as_dict_column
            if as_dict_column(table[ki]) is not None:
                metrics.count("groupby.dict_keys")
            codes, uniq = strings.dictionary_encode(table[ki])
            work_cols[ki] = codes
            str_dicts[ki] = uniq
    table = Table(work_cols)
    if not key_indices:
        # GROUP BY () — the grand-total grouping set: one segment, no sort
        sorted_tbl = table
        seg_ids = jnp.zeros(n, dtype=jnp.int32)
        return _aggregate_sorted(sorted_tbl, [], {}, seg_ids, 1, aggs, n)
    order = order_by(table, list(key_indices))
    sorted_tbl = gather(table, order)

    skeys, svalid = [], []
    for ki in key_indices:
        col = sorted_tbl[ki]
        if col.dtype.id == T.TypeId.FLOAT64:
            # bit-pair lanes canonicalized for Spark grouping equality
            # (-0.0 == 0.0, all NaNs equal)
            from ..utils.f64bits import group_key_lanes
            lo, hi = group_key_lanes(col.data)
            skeys += [lo, hi]
            svalid += [col.validity, col.validity]
        elif col.dtype.id == T.TypeId.DECIMAL128:   # compare both limbs
            skeys += [col.data[:, 0], col.data[:, 1]]
            svalid += [col.validity, col.validity]
        else:
            skeys.append(col.data)
            svalid.append(col.validity)
    seg_ids = _segment_ids(skeys, svalid)
    from ..utils import syncs
    num_segments = syncs.scalar(seg_ids[-1]) + 1   # scalar sync (group count)
    if metrics.recording():
        metrics.observe("groupby.groups", num_segments)
        metrics.annotate(groups=num_segments)
    metrics.profile_op("groupby", rows_in=n, groups=num_segments)
    return _aggregate_sorted(sorted_tbl, list(key_indices), str_dicts,
                             seg_ids, num_segments, aggs, n)


def _aggregate_sorted(sorted_tbl: Table, key_indices, str_dicts,
                      seg_ids, num_segments: int, aggs, n: int) -> Table:
    """Aggregation tail shared by the keyed and grand-total (no-key) paths:
    per-segment key heads + aggregate columns over a key-sorted table."""
    # one representative row per segment for the key columns
    head_pos = jax.ops.segment_min(jnp.arange(n, dtype=jnp.int32), seg_ids,
                                   num_segments)
    out_cols = []
    for ki in key_indices:
        head = _take_rows(sorted_tbl[ki], head_pos)
        if ki in str_dicts:
            # decode: the code IS the dictionary row index
            from .filter import _gather_column
            dec = _gather_column(str_dicts[ki], head.data)
            out_cols.append(Column(dec.dtype, dec.data, dec.offsets,
                                   head.validity))
        else:
            out_cols.append(head)

    for vi, agg in aggs:
        col = sorted_tbl[vi]
        if agg == "count":
            # count never touches the payload — every column type counts
            res = _agg_segment(col.data, col.validity, seg_ids, "count",
                               num_segments, "i")
            dt = _agg_out_dtype(col.dtype, agg)
            out_cols.append(Column(dt, res.astype(dt.storage)))
            continue
        if col.dtype.is_variable_width or col.dtype.is_nested:
            raise NotImplementedError(
                f"{agg!r} aggregation on {col.dtype.id.name} columns")
        if col.dtype.id == T.TypeId.DECIMAL128:
            if agg != "sum":
                raise NotImplementedError(
                    f"decimal128 groupby supports sum/count only, got {agg!r}")
            from . import decimal128 as d128
            out_cols.append(d128.segmented_sum(col, seg_ids, num_segments))
            continue
        if (col.dtype.id == T.TypeId.FLOAT64
                and agg in ("min", "max", "first", "last")):
            # Selection aggregates return an EXISTING row's value, whose
            # exact bits are already resident as u32 pairs — gather them
            # positionally instead of round-tripping through from_bits/
            # to_bits (which perturbs bits on TPU: ~48-mantissa-bit
            # emulation, f32-like exponent window).  min/max select via
            # the monotone bits→uint sort key (NaN largest — Spark order).
            p = _f64_select_pos(col, seg_ids, num_segments, agg)
            bits = col.data[jnp.clip(p, 0, max(n - 1, 0))]
            if col.validity is not None:
                cnt = _agg_segment(col.data[:, 0], col.validity, seg_ids,
                                   "count", num_segments, "i")
                out_cols.append(Column(col.dtype, bits, validity=cnt > 0))
            else:
                out_cols.append(Column(col.dtype, bits))
            continue
        data = col.values()   # FLOAT64 bit pairs decode to f64 values
        if col.dtype.is_decimal and agg in ("mean", "var", "std"):
            # value-domain statistics: apply the decimal scale (the raw
            # payload is unscaled — var over cents would be off by 10^-2s)
            data = data.astype(jnp.float64) * np.float64(10.0) ** col.dtype.scale
        if agg in ("var", "std"):
            cnt = _agg_segment(data, col.validity, seg_ids, "count",
                               num_segments, "i")
            res = _var_segment(data, col.validity, seg_ids, num_segments,
                               cnt, std=(agg == "std"))
            out_cols.append(Column.from_values(
                _agg_out_dtype(col.dtype, agg), res, validity=cnt >= 2))
            continue
        kind = "f" if (col.dtype.is_decimal and agg == "mean") \
            else col.dtype.storage.kind
        res = _agg_segment(data, col.validity, seg_ids, agg,
                           num_segments, kind)
        # min/max/first/last of an all-null group is null
        if agg in ("min", "max", "first", "last") and col.validity is not None:
            cnt = _agg_segment(data, col.validity, seg_ids, "count",
                               num_segments, col.dtype.storage.kind)
            out_cols.append(Column.from_values(
                col.dtype, _cast_res(res, col.dtype), validity=cnt > 0))
        else:
            dt = _agg_out_dtype(col.dtype, agg)
            out_cols.append(Column.from_values(dt, _cast_res(res, dt)))
    return Table(out_cols)


def _cast_res(res, dt):
    """Aggregate result → the dtype's arithmetic value form (FLOAT64 stays a
    f64 value array; ``Column.from_values`` encodes it to bit pairs)."""
    if dt.id == T.TypeId.FLOAT64:
        return res.astype(jnp.float64)
    return res.astype(dt.storage)


def _agg_out_dtype(src, agg):
    """Result dtype of an aggregation — the single source for both the
    populated and the empty-input result paths (schema stability)."""
    from .. import types as T
    if agg in ("min", "max", "first", "last"):
        return src
    if agg in ("mean", "var", "std"):
        return T.float64
    if agg == "count":
        return T.int64
    if src.id == T.TypeId.DECIMAL128:    # limb sum keeps type AND scale
        return src
    if src.is_decimal:                   # sum of decimal keeps the scale
        return T.decimal64(src.scale)
    return T.float64 if src.storage.kind == "f" else T.int64


def _empty_column_of(dt) -> Column:
    if dt.is_variable_width:
        return Column(dt, jnp.zeros(0, jnp.uint8), jnp.zeros(1, jnp.int32))
    if dt.id == T.TypeId.DECIMAL128:
        return Column(dt, jnp.zeros((0, 2), jnp.int64))
    if dt.id == T.TypeId.FLOAT64:
        return Column(dt, jnp.zeros((0, 2), jnp.uint32))
    return Column(dt, jnp.zeros(0, dt.storage))


def _empty_result(table: Table, key_indices, aggs) -> Table:
    cols = [_empty_column_of(table[ki].dtype) for ki in key_indices]
    for vi, agg in aggs:
        cols.append(_empty_column_of(_agg_out_dtype(table[vi].dtype, agg)))
    return Table(cols)


def _grand_total_empty(table: Table, aggs) -> Table:
    """One grand-total row over zero input rows: COUNT = 0 (valid), every
    other aggregate null."""
    cols = []
    for vi, agg in aggs:
        dt = _agg_out_dtype(table[vi].dtype, agg)
        if agg == "count":
            cols.append(Column(dt, jnp.zeros(1, dt.storage)))
            continue
        proto = _empty_column_of(dt)
        shape = (1,) + proto.data.shape[1:]
        cols.append(Column(dt, jnp.zeros(shape, proto.data.dtype),
                           validity=jnp.zeros(1, jnp.bool_)))
    return Table(cols)


def _take_rows(col: Column, idx: jnp.ndarray) -> Column:
    v = None if col.validity is None else col.validity[idx]
    return Column(col.dtype, col.data[idx], validity=v)


def groupby_grouping_sets(table: Table, key_indices: Sequence[int],
                          sets: Sequence[Sequence[int]],
                          aggs: Sequence[tuple[int, str]]) -> Table:
    """GROUP BY GROUPING SETS (Spark/libcudf groupby with grouping sets).

    ``sets`` holds positions INTO ``key_indices`` (e.g. rollup over keys
    [a, b] is ``[[0, 1], [0], []]``).  Output schema: every key column (null
    where the set aggregates it away), then the agg columns, then a
    ``grouping_id`` int64 column (Spark's bigint grouping_id) — bit ``k``
    (MSB = first key) set when key ``k`` is NOT in the set.  One sorted
    ``groupby_aggregate`` per set, results unioned; callers order the
    result (deterministic given a sort, as elsewhere).
    """
    from .copying import concat_tables
    from .join import _null_column
    key_indices = list(key_indices)
    nk = len(key_indices)
    parts = []
    for s in sets:
        included = sorted(s)
        sub = groupby_aggregate(table, [key_indices[i] for i in included],
                                aggs)
        n = sub.num_rows
        gid = 0
        cols: list[Column] = []
        for k in range(nk):
            if k in included:
                cols.append(sub[included.index(k)])
            else:
                gid |= 1 << (nk - 1 - k)
                cols.append(_null_column(table[key_indices[k]].dtype, n))
        for ai in range(len(aggs)):
            cols.append(sub[len(included) + ai])
        cols.append(Column(T.int64, jnp.full((n,), gid, jnp.int64)))
        parts.append(Table(cols))
    return concat_tables(parts)


def groupby_rollup(table: Table, key_indices: Sequence[int],
                   aggs: Sequence[tuple[int, str]]) -> Table:
    """GROUP BY ROLLUP (Spark rollup): grouping sets over every key-list
    prefix, from all keys down to the grand total."""
    nk = len(key_indices)
    sets = [list(range(k)) for k in range(nk, -1, -1)]
    return groupby_grouping_sets(table, key_indices, sets, aggs)


def groupby_cube(table: Table, key_indices: Sequence[int],
                 aggs: Sequence[tuple[int, str]]) -> Table:
    """GROUP BY CUBE (Spark cube): grouping sets over every key subset."""
    import itertools
    nk = len(key_indices)
    sets = []
    for r in range(nk, -1, -1):
        sets.extend(itertools.combinations(range(nk), r))
    return groupby_grouping_sets(table, key_indices, sets, aggs)


def groupby_nunique(table: Table, key_indices: Sequence[int],
                    value_index: int) -> Table:
    """COUNT(DISTINCT value) GROUP BY keys (Spark countDistinct, nulls
    excluded): distinct (keys, value) tuples, then count non-null values
    per key group — two sort passes, both fully vectorized."""
    sub = groupby_aggregate(table, list(key_indices) + [value_index], [])
    k = len(key_indices)
    return groupby_aggregate(sub, list(range(k)), [(k, "count")])


def distinct(table: Table) -> Table:
    """Distinct rows (Spark dropDuplicates over all columns) — a groupby on
    every column with no aggregations; output order is the key sort order."""
    return groupby_aggregate(table, list(range(table.num_columns)), [])


# ---------------------------------------------------------------------------
# Mergeable partial-aggregate states (incremental view maintenance)
# ---------------------------------------------------------------------------
# Every MERGEABLE_AGGS aggregate decomposes into a small set of state
# columns closed under a segment-merge:
#
#   count      -> [count]                   merge: int64 add
#   sum        -> [sum]                     merge: dtype-native segment sum
#   min / max  -> [min] / [max]             merge: selection over states
#   mean       -> [sum, count]   (int)      finalize: sum / count
#              -> [fsum, count]  (f/dec)    fsum = value-domain f64 sum
#   var / std  -> [count, fsum, m2]         merge: Chan's parallel M2 update
#
# so refresh = merge(old_state, partial(delta)).  Exactness contract
# (``merge_exact``): count always; sum over integer-kind storage and
# decimals (associative int/limb adds); min/max over any fixed-width
# (selection — FLOAT64 keeps resident bits, ties resolve to the earliest
# state row, which is the earliest input row because states are merged in
# input order); mean over plain integers (int sum + count, one final
# division).  Float sums/means and merged M2 variance are numerically
# stable but NOT bit-identical to a full recompute (fp addition is not
# associative); callers gate on ``merge_exact`` when they need bit-parity.
# An UNMERGED state finalizes bit-identical for every aggregate — the
# state pass mirrors ``groupby_aggregate``'s formulas operation for
# operation.

class StateCol(NamedTuple):
    kind: str    # "sum" | "count" | "min" | "max" | "fsum" | "m2"
    src: int     # value-column index in the input relation


class OutSpec(NamedTuple):
    agg: str
    mode: str                  # "passthrough" | "mean_int" | "mean_f" | "var" | "std"
    states: tuple[int, ...]    # positions into AggStateSpec.states
    exact: bool                # merge is bit-identical to full recompute


class AggStateSpec(NamedTuple):
    nkeys: int
    states: tuple[StateCol, ...]
    outs: tuple[OutSpec, ...]

    @property
    def exact(self) -> bool:
        return all(o.exact for o in self.outs)


def merge_exact(agg: str, dtype) -> bool:
    """True when merging partial states of ``agg`` over a ``dtype`` column
    reproduces the full recompute bit for bit (see module comment)."""
    if agg == "count":
        return True
    if dtype.is_variable_width or dtype.is_nested:
        return False
    if agg in ("min", "max"):
        return True
    if agg == "sum":
        return (dtype.id == T.TypeId.DECIMAL128
                or dtype.storage.kind in ("i", "u"))
    if agg == "mean":
        return not dtype.is_decimal and dtype.storage.kind in ("i", "u")
    return False     # var/std: merged M2 is stable, not bit-exact


def plan_aggregate_states(aggs: Sequence[tuple[int, str]], dtypes,
                          nkeys: int) -> AggStateSpec:
    """Plan the state layout for ``aggs`` over a relation whose column
    ``i`` has dtype ``dtypes[i]``.  States are deduplicated: mean/var over
    the same column share their sum/count columns."""
    states: list[StateCol] = []

    def pos(kind: str, src: int) -> int:
        sc = StateCol(kind, src)
        if sc in states:
            return states.index(sc)
        states.append(sc)
        return len(states) - 1

    outs: list[OutSpec] = []
    for vi, agg in aggs:
        if agg not in MERGEABLE_AGGS:
            raise ValueError(
                f"aggregate {agg!r} has no mergeable state form "
                f"(supported: {MERGEABLE_AGGS})")
        dt = dtypes[vi]
        if agg != "count" and (dt.is_variable_width or dt.is_nested):
            raise NotImplementedError(
                f"{agg!r} state on {dt.id.name} columns")
        exact = merge_exact(agg, dt)
        if agg in ("sum", "count", "min", "max"):
            outs.append(OutSpec(agg, "passthrough", (pos(agg, vi),), exact))
        elif agg == "mean":
            if dt.is_decimal or dt.storage.kind == "f":
                outs.append(OutSpec(agg, "mean_f",
                                    (pos("fsum", vi), pos("count", vi)),
                                    exact))
            else:
                outs.append(OutSpec(agg, "mean_int",
                                    (pos("sum", vi), pos("count", vi)),
                                    exact))
        else:    # var / std
            outs.append(OutSpec(agg, agg,
                                (pos("count", vi), pos("fsum", vi),
                                 pos("m2", vi)), False))
    return AggStateSpec(nkeys, tuple(states), tuple(outs))


def _state_dtype(src_dt, kind: str):
    if kind == "count":
        return T.int64
    if kind == "sum":
        return _agg_out_dtype(src_dt, "sum")
    if kind in ("min", "max"):
        return src_dt
    return T.float64     # fsum / m2


def _value_f64(col: Column) -> jnp.ndarray:
    """Value-domain f64 payload (decimal scale applied) — the accumulator
    basis shared by the mean/var paths of ``groupby_aggregate``."""
    data = col.values()
    if col.dtype.is_decimal:
        return data.astype(jnp.float64) * np.float64(10.0) ** col.dtype.scale
    return data.astype(jnp.float64)


def _encode_str_keys(table: Table, key_indices):
    """Swap variable-width key columns for order-preserving dictionary
    codes (same move as ``_groupby_aggregate``)."""
    str_dicts: dict[int, Column] = {}
    work = list(table.columns)
    for ki in key_indices:
        if table[ki].dtype.is_nested:
            raise NotImplementedError(
                f"{table[ki].dtype.id.name} columns cannot be state keys")
        if table[ki].dtype.is_variable_width:
            from . import strings
            codes, uniq = strings.dictionary_encode(table[ki])
            work[ki] = codes
            str_dicts[ki] = uniq
    return Table(work), str_dicts


def _sorted_segments(table: Table, key_indices):
    """Key-sort + segment ids + group count (one scalar sync); ``table``
    must already be string-encoded."""
    order = order_by(table, list(key_indices))
    st = gather(table, order)
    skeys, svalid = [], []
    for ki in key_indices:
        col = st[ki]
        if col.dtype.id == T.TypeId.FLOAT64:
            from ..utils.f64bits import group_key_lanes
            lo, hi = group_key_lanes(col.data)
            skeys += [lo, hi]
            svalid += [col.validity, col.validity]
        elif col.dtype.id == T.TypeId.DECIMAL128:
            skeys += [col.data[:, 0], col.data[:, 1]]
            svalid += [col.validity, col.validity]
        else:
            skeys.append(col.data)
            svalid.append(col.validity)
    seg_ids = _segment_ids(skeys, svalid)
    from ..utils import syncs
    num_segments = syncs.scalar(seg_ids[-1]) + 1
    return st, seg_ids, num_segments


def _head_key_cols(st: Table, key_indices, str_dicts, seg_ids,
                   num_segments: int, n: int) -> list[Column]:
    head_pos = jax.ops.segment_min(jnp.arange(n, dtype=jnp.int32), seg_ids,
                                   num_segments)
    cols = []
    for ki in key_indices:
        head = _take_rows(st[ki], head_pos)
        if ki in str_dicts:
            from .filter import _gather_column
            dec = _gather_column(str_dicts[ki], head.data)
            cols.append(Column(dec.dtype, dec.data, dec.offsets,
                               head.validity))
        else:
            cols.append(head)
    return cols


def _state_column(col: Column, kind: str, seg_ids, num_segments: int,
                  n: int) -> Column:
    """One state column over a key-sorted relation — each branch mirrors
    the corresponding ``_aggregate_sorted`` formula exactly so an
    unmerged state finalizes bit-identical to ``groupby_aggregate``."""
    if kind == "count":
        res = _agg_segment(col.data, col.validity, seg_ids, "count",
                           num_segments, "i")
        return Column(T.int64, res.astype(T.int64.storage))
    if col.dtype.is_variable_width or col.dtype.is_nested:
        raise NotImplementedError(
            f"{kind!r} state on {col.dtype.id.name} columns")
    if kind == "sum":
        if col.dtype.id == T.TypeId.DECIMAL128:
            from . import decimal128 as d128
            return d128.segmented_sum(col, seg_ids, num_segments)
        data = col.values()
        res = _agg_segment(data, col.validity, seg_ids, "sum",
                           num_segments, col.dtype.storage.kind)
        dt = _agg_out_dtype(col.dtype, "sum")
        return Column.from_values(dt, _cast_res(res, dt))
    if kind in ("min", "max"):
        if col.dtype.id == T.TypeId.DECIMAL128:
            raise NotImplementedError("decimal128 min/max states")
        if col.dtype.id == T.TypeId.FLOAT64:
            p = _f64_select_pos(col, seg_ids, num_segments, kind)
            bits = col.data[jnp.clip(p, 0, max(n - 1, 0))]
            if col.validity is not None:
                cnt = _agg_segment(col.data[:, 0], col.validity, seg_ids,
                                   "count", num_segments, "i")
                return Column(col.dtype, bits, validity=cnt > 0)
            return Column(col.dtype, bits)
        data = col.values()
        res = _agg_segment(data, col.validity, seg_ids, kind,
                           num_segments, col.dtype.storage.kind)
        if col.validity is not None:
            cnt = _agg_segment(data, col.validity, seg_ids, "count",
                               num_segments, col.dtype.storage.kind)
            return Column.from_values(col.dtype, _cast_res(res, col.dtype),
                                      validity=cnt > 0)
        return Column.from_values(col.dtype, _cast_res(res, col.dtype))
    if kind == "fsum":
        x = _value_f64(col)
        x = x if col.validity is None else jnp.where(col.validity, x, 0.0)
        s = jax.ops.segment_sum(x, seg_ids, num_segments)
        return Column.from_values(T.float64, s)
    if kind == "m2":
        # mirrors _var_segment's two-pass M2 (ddof applied at finalize)
        cnt = _agg_segment(col.data if col.dtype.id != T.TypeId.FLOAT64
                           else col.data[:, 0], col.validity, seg_ids,
                           "count", num_segments, "i")
        x = _value_f64(col)
        x = x if col.validity is None else jnp.where(col.validity, x, 0.0)
        cntf = cnt.astype(jnp.float64)
        mean = (jax.ops.segment_sum(x, seg_ids, num_segments)
                / jnp.maximum(cntf, 1.0))
        dev = x - mean[seg_ids]
        if col.validity is not None:
            dev = jnp.where(col.validity, dev, 0.0)
        m2 = jax.ops.segment_sum(dev * dev, seg_ids, num_segments)
        return Column.from_values(T.float64, m2)
    raise ValueError(f"unknown state kind {kind!r}")


def _empty_states(table: Table, key_indices, spec: AggStateSpec) -> Table:
    cols = [_empty_column_of(table[ki].dtype) for ki in key_indices]
    for sc in spec.states:
        cols.append(_empty_column_of(_state_dtype(table[sc.src].dtype,
                                                  sc.kind)))
    return Table(cols)


def partial_aggregate_states(table: Table, key_indices: Sequence[int],
                             aggs: Sequence[tuple[int, str]],
                             spec: AggStateSpec | None = None) -> Table:
    """Partial-aggregate state table for ``aggs`` GROUP BY ``key_indices``:
    [key columns..., state columns in spec order], one row per distinct
    key tuple, sorted by key.  Keys must be non-empty (grand-total views
    fall back to full recompute — the empty-input grand-total row has
    different null semantics than a merged empty state)."""
    key_indices = list(key_indices)
    if not key_indices:
        raise ValueError("partial aggregate states require group keys")
    if spec is None:
        spec = plan_aggregate_states(aggs, [c.dtype for c in table.columns],
                                     len(key_indices))
    n = table.num_rows
    with metrics.span("groupby.partial_states", keys=len(key_indices),
                      states=len(spec.states), rows=n):
        if n == 0:
            return _empty_states(table, key_indices, spec)
        enc, str_dicts = _encode_str_keys(table, key_indices)
        st, seg_ids, ns = _sorted_segments(enc, key_indices)
        cols = _head_key_cols(st, key_indices, str_dicts, seg_ids, ns, n)
        for sc in spec.states:
            cols.append(_state_column(st[sc.src], sc.kind, seg_ids, ns, n))
        return Table(cols)


def merge_aggregate_states(spec: AggStateSpec, a: Table | None,
                           b: Table | None) -> Table:
    """Merge two state tables (layout per ``partial_aggregate_states``).
    ``a`` rows come first, so for groups present in both the earlier
    partition's representative key row and selection ties win — matching
    a stable full recompute over ``a``-then-``b`` input order."""
    if a is None:
        return b
    if b is None:
        return a
    from .copying import concat_tables
    t = concat_tables([a, b])
    n = t.num_rows
    if n == 0:
        return a
    nk = spec.nkeys
    key_indices = list(range(nk))
    with metrics.span("groupby.merge_states", states=len(spec.states),
                      rows=n):
        enc, str_dicts = _encode_str_keys(t, key_indices)
        st, seg_ids, ns = _sorted_segments(enc, key_indices)
        cols = _head_key_cols(st, key_indices, str_dicts, seg_ids, ns, n)
        for p, sc in enumerate(spec.states):
            col = st[nk + p]
            if sc.kind in ("sum", "count"):
                # counts merge by summing; the int64 state column keeps
                # its dtype through the sum branch
                merged = _state_column(col, "sum", seg_ids, ns, n)
                if sc.kind == "count":
                    merged = Column(T.int64, merged.data)
                cols.append(merged)
            elif sc.kind in ("min", "max", "fsum"):
                cols.append(_state_column(col, sc.kind, seg_ids, ns, n))
            else:    # m2 — Chan's parallel update, generalized to segments:
                # M2 = sum(m2_i) + sum(n_i * (mean_i - mean_comb)^2)
                ci = spec.states.index(StateCol("count", sc.src))
                si = spec.states.index(StateCol("fsum", sc.src))
                n_i = st[nk + ci].values().astype(jnp.float64)
                s_i = st[nk + si].values()
                m_i = col.values()
                big_n = jax.ops.segment_sum(n_i, seg_ids, ns)
                big_s = jax.ops.segment_sum(s_i, seg_ids, ns)
                mean_comb = big_s / jnp.maximum(big_n, 1.0)
                mean_i = s_i / jnp.maximum(n_i, 1.0)
                dev = mean_i - mean_comb[seg_ids]
                m2 = (jax.ops.segment_sum(m_i, seg_ids, ns)
                      + jax.ops.segment_sum(n_i * dev * dev, seg_ids, ns))
                cols.append(Column.from_values(T.float64, m2))
        return Table(cols)


def finalize_aggregate_states(spec: AggStateSpec, state: Table) -> Table:
    """State table → the ``groupby_aggregate`` result it stands for:
    [key columns..., one column per requested aggregate], formulas
    mirroring ``_aggregate_sorted`` bit for bit."""
    nk = spec.nkeys
    cols = [state[i] for i in range(nk)]
    for o in spec.outs:
        if o.mode == "passthrough":
            cols.append(state[nk + o.states[0]])
        elif o.mode in ("mean_int", "mean_f"):
            s = state[nk + o.states[0]].values()
            cnt = state[nk + o.states[1]].values()
            res = (s.astype(jnp.float64)
                   / jnp.maximum(cnt, 1).astype(jnp.float64))
            cols.append(Column.from_values(T.float64, res))
        else:    # var / std
            cnt = state[nk + o.states[0]].values()
            m2 = state[nk + o.states[2]].values()
            cntf = cnt.astype(jnp.float64)
            var = m2 / jnp.maximum(cntf - 1.0, 1.0)
            res = jnp.sqrt(var) if o.mode == "std" else var
            cols.append(Column.from_values(T.float64, res,
                                           validity=cnt >= 2))
    return Table(cols)
