"""Null-aware column reductions (libcudf reduce analog).

Every reduction masks invalid lanes with the operation's identity and runs as
one fused VPU pass; ``count`` is a popcount of the validity lanes.  Spark
semantics: aggregates ignore nulls; min/max of an all-null column is null
(callers check ``valid_count``).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..column import Column


def _masked(col: Column, identity):
    data = col.values()   # FLOAT64 bit pairs decode to f64 values
    if col.validity is None:
        return data
    return jnp.where(col.validity, data, identity)


def valid_count(col: Column) -> jnp.ndarray:
    if col.validity is None:
        return jnp.asarray(col.num_rows, dtype=jnp.int64)
    return jnp.sum(col.validity, dtype=jnp.int64)


def sum_(col: Column) -> jnp.ndarray:
    acc = jnp.float64 if col.dtype.storage.kind == "f" else jnp.int64
    return jnp.sum(_masked(col, 0), dtype=acc)


def min_(col: Column) -> jnp.ndarray:
    if col.dtype.storage.kind == "f":
        ident = np.inf
    else:
        ident = np.iinfo(col.dtype.storage).max
    return jnp.min(_masked(col, ident))


def max_(col: Column) -> jnp.ndarray:
    if col.dtype.storage.kind == "f":
        ident = -np.inf
    else:
        ident = np.iinfo(col.dtype.storage).min
    return jnp.max(_masked(col, ident))


def mean(col: Column) -> jnp.ndarray:
    n = valid_count(col)
    return sum_(col).astype(jnp.float64) / jnp.maximum(n, 1).astype(jnp.float64)
