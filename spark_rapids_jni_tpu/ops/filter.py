"""Row filtering (libcudf apply_boolean_mask / copy_if analog).

Two-phase shape discipline, same as the string path (SURVEY §7 step 4):
dynamic result sizes don't exist under XLA, so filtering is

  phase 1 (device): predicate → bool mask → count (one scalar sync)
  phase 2 (device): statically-shaped gather of the surviving rows

For fully-jitted pipelines that must avoid the sync, ``mask_table`` keeps
the static shape and marks filtered-out rows invalid instead — aggregations
honor validity, so scan→filter→agg plans (TPC-H q6 shape) never compact.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..column import Column, DictColumn, Table, as_dict_column


def _segment_gather(offs: jnp.ndarray, idx: jnp.ndarray):
    """Element indices + new offsets for gathering variable-width segments."""
    lens = (offs[1:] - offs[:-1])[idx]
    new_offs = jnp.concatenate([jnp.zeros(1, lens.dtype), jnp.cumsum(lens)])
    from ..utils import syncs
    total = syncs.scalar(new_offs[-1])   # size resolution (capture/replay)
    starts = offs[:-1][idx]
    # marker-cumsum segment lookup, not a per-char binary search — same
    # cliff fix as DictColumn.materialize (string gathers walk every char)
    from ..rowconv.convert import _segment_of
    elem_ids = jnp.arange(total, dtype=jnp.int64)
    row_of = _segment_of(new_offs.astype(jnp.int32), int(total))
    src = starts.astype(jnp.int64)[row_of] + (
        elem_ids - new_offs.astype(jnp.int64)[row_of])
    return src, new_offs.astype(jnp.int32)


def _gather_column(col: Column, idx: jnp.ndarray) -> Column:
    d = as_dict_column(col)
    if d is not None:
        # codes gather only — the dictionary is shared, bytes stay unread,
        # and (unlike the plain STRING branch) there is no size sync
        from ..utils import metrics
        metrics.count("strings.dict.gather")
        dv = None if d.validity is None else d.validity[idx]
        return DictColumn(d.codes[idx], d.dictionary, dv,
                          sorted_dict=d.sorted_dict)
    v = None if col.validity is None else col.validity[idx]
    if col.dtype.id == T.TypeId.STRUCT:
        return Column(col.dtype, col.data, None, v,
                      [_gather_column(ch, idx) for ch in col.children])
    if col.dtype.id == T.TypeId.LIST:
        src, new_offs = _segment_gather(col.offsets, idx)
        return Column(col.dtype, col.data, new_offs, v,
                      [_gather_column(col.children[0], src)])
    if col.dtype.is_variable_width:   # STRING: chars live in .data
        src, new_offs = _segment_gather(col.offsets, idx)
        return Column(col.dtype, col.data[src], new_offs, v)
    return Column(col.dtype, col.data[idx], validity=v)


def gather(table: Table, idx: jnp.ndarray) -> Table:
    """Gather rows by index (libcudf gather analog).

    Columns come back LAZY (:class:`~..column.LazyColumn`): each
    materializes on first payload access, so plan tails that only read a
    few columns never pay the others' gathers — or, for string columns,
    their size-resolution syncs.  This is the structural projection pass
    that keeps wide joins from materializing (and OOMing on) columns the
    query never references.
    """
    from ..column import LazyColumn
    n_out = int(idx.shape[0])
    # DictColumns gather EAGERLY: a codes gather is one cheap fixed-width
    # take with no size sync, and staying a concrete DictColumn (not a lazy
    # wrapper) keeps the dictionary visible across jit boundaries
    return Table([
        _gather_column(c, idx) if isinstance(c, DictColumn) else
        LazyColumn(c.dtype, n_out,
                   (lambda c=c: _gather_column(c, idx)))
        for c in table.columns])


def sized_nonzero(mask: jnp.ndarray, n_keep: int) -> jnp.ndarray:
    """Ascending indices of the True rows, shaped ``[n_keep]``.

    Every dynamic-size site is two-phase (count sync, then sized
    selection), so by the time this runs the mask is usually concrete —
    and then a host ``np.flatnonzero`` is a single linear pass, where the
    XLA sized-nonzero lowering routes through a full sort (~100ms on a
    2M-row mask on CPU, dwarfing the gathers it feeds).  Under a trace
    (capture/replay) the mask is a tracer and the jittable lowering is
    the only option; parity is preserved — same ascending order, same
    zero padding when the clamped size exceeds the population count.
    """
    if isinstance(mask, jax.core.Tracer):
        return jnp.nonzero(mask, size=n_keep)[0]
    idx = np.flatnonzero(np.asarray(mask))
    if idx.shape[0] >= n_keep:
        idx = idx[:n_keep]
    else:
        idx = np.pad(idx, (0, n_keep - idx.shape[0]))
    # same int64 index dtype the sized device lowering produces (x64 on)
    return jnp.asarray(idx)


def apply_boolean_mask(table: Table, mask: jnp.ndarray) -> Table:
    """Keep rows where mask is True (compacting; one host sync for the count)."""
    from ..utils import metrics, syncs
    n_keep = syncs.scalar(jnp.sum(mask))   # counted host sync (dynamic size)
    metrics.profile_op("filter", rows_in=table.num_rows, rows_kept=n_keep)
    idx = sized_nonzero(mask, n_keep)
    return gather(table, idx)


def mask_table(table: Table, mask: jnp.ndarray) -> Table:
    """Filter without compaction: failing rows become invalid (null).

    Static-shaped, fully jittable; downstream reductions/groupbys honor
    validity so results match the compacting filter.  Deferred per column
    (see ``gather``) so masking a wide table doesn't force unread columns.
    """
    from ..column import LazyColumn, force_column

    def mk(c):
        if isinstance(c, DictColumn):   # eager: validity AND only, no bytes
            v = mask if c.validity is None else (c.validity & mask)
            return DictColumn(c.codes, c.dictionary, v,
                              sorted_dict=c.sorted_dict)

        def thunk(c=c):
            g = force_column(c)
            if isinstance(g, DictColumn):
                v = mask if g.validity is None else (g.validity & mask)
                return DictColumn(g.codes, g.dictionary, v,
                                  sorted_dict=g.sorted_dict)
            v = mask if g.validity is None else (g.validity & mask)
            return Column(g.dtype, g.data, g.offsets, v, g.children)
        return LazyColumn(c.dtype, c.num_rows, thunk)

    return Table([mk(c) for c in table.columns])


def fill_null(col: Column, value) -> Column:
    """Replace nulls with a scalar (Spark ``coalesce(col, lit)`` / cudf
    ``replace_nulls``).  Fixed-width columns only."""
    if (col.dtype.is_variable_width or col.dtype.is_nested
            or col.dtype.id == T.TypeId.DECIMAL128):
        raise TypeError(f"fill_null not supported on {col.dtype.id.name}")
    if col.validity is None:
        return col
    if col.dtype.id == T.TypeId.FLOAT64:   # bit-pair storage: fill with bits
        from ..utils import f64bits
        fill = jnp.asarray(f64bits.np_to_bits(
            np.asarray([value], np.float64))[0])
        data = jnp.where(col.validity[:, None], col.data, fill[None, :])
    else:
        data = jnp.where(col.validity, col.data,
                         jnp.asarray(value, col.data.dtype))
    return Column(col.dtype, data, validity=None)


def isin(col: Column, values) -> jnp.ndarray:
    """Null-safe SQL ``col IN (v1, v2, …)`` mask (Spark semantics: null
    rows yield False).  Fixed-width columns probe a sorted value list with
    one searchsorted; string columns OR a few vectorized equality passes
    (IN-lists are short in practice)."""
    if col.dtype.id == T.TypeId.STRING:
        from . import strings
        d = as_dict_column(col)
        if d is not None:
            # membership once per dictionary entry, then gather by code
            from ..utils import metrics
            metrics.count("strings.dict.predicate")
            nd = d.dictionary.num_rows
            if nd == 0:
                m = jnp.zeros(d.codes.shape, bool)
            else:
                dm = isin(d.dictionary, values)
                m = dm[jnp.clip(d.codes, 0, nd - 1)]
            metrics.count("strings.dict.gather")
            if d.validity is not None:
                m = m & d.validity
            return m
        payloads = [v.encode() if isinstance(v, str) else bytes(v)
                    for v in values if v is not None]
        m = jnp.zeros(col.num_rows, bool)
        if payloads:
            # one shared byte matrix; per-value compare is a masked row-AND
            mat, lens = strings._search_matrix(
                col, max(len(p) for p in payloads))
            for p in payloads:
                eq = jnp.asarray(lens == len(p))
                for k, b in enumerate(p):
                    eq = eq & (mat[:, k] == b)
                m = m | eq
    elif col.dtype.is_nested or col.dtype.id == T.TypeId.DECIMAL128:
        raise NotImplementedError(f"isin on {col.dtype.id.name}")
    elif col.dtype.id == T.TypeId.FLOAT64:
        # Membership on the canonicalized bit lanes, not decoded values: on
        # TPU ``from_bits`` carries ~48 mantissa bits, so two distinct
        # doubles can decode equal and match spuriously.  Probes are
        # bit-converted on host (exact) with the same canonicalization as
        # ``group_key_lanes`` (-0.0 == 0.0, all NaNs one value — Spark
        # equality, under which NaN IN (NaN) is true).
        from ..utils.f64bits import equality_key_u64, np_equality_key_u64
        probes = []
        for v in values:
            if v is None:
                continue
            try:
                fv = np.float64(v)
            except (OverflowError, ValueError, TypeError):
                continue
            if np.isnan(fv) or fv == v or isinstance(v, float):
                probes.append(fv)
        if not probes:
            m = jnp.zeros(col.num_rows, bool)
        else:
            pb = np_equality_key_u64(np.asarray(probes, np.float64))
            key = equality_key_u64(col.data)
            vals = jnp.sort(jnp.asarray(np.unique(pb)))
            pos = jnp.clip(jnp.searchsorted(vals, key), 0, vals.shape[0] - 1)
            m = vals[pos] == key
    else:
        # keep only probes that survive an EXACT round trip into the
        # column's storage dtype — a lossy cast (3.5 → 3 into int32, or an
        # out-of-range literal) must match nothing, not its truncation;
        # None (SQL NULL) literals never match non-null rows
        storage = col.dtype.storage
        kept = []
        for v in values:
            if v is None:
                continue
            try:
                cast_v = storage.type(v)
            except (OverflowError, ValueError, TypeError):
                continue
            if cast_v == v:
                kept.append(cast_v)
        if not kept:
            return jnp.zeros(col.num_rows, bool)
        vals = jnp.sort(jnp.asarray(np.asarray(kept, storage)))
        cdata = col.values()   # FLOAT64 bit pairs decode to f64 values
        pos = jnp.clip(jnp.searchsorted(vals, cdata), 0,
                       vals.shape[0] - 1)
        m = vals[pos] == cdata
    if col.validity is not None:
        m = m & col.validity
    return m
