"""Column casts (numeric, bool, decimal rescale, timestamps).

Replaces the slice of libcudf's cast kernels the Spark plugin leans on
(SURVEY §2.9 / §7 step 6).  TPU-first: every cast is a single fused
elementwise XLA op over the column's lanes; validity rides along untouched.

Decimal semantics follow the reference's representation (scaled integers,
``RowConversion.java:114-118``): DECIMAL(s) holds ``unscaled * 10**s`` with
cudf's negative-scale convention, so rescaling from s1 to s2 multiplies or
divides by ``10**(s1 - s2)`` (round-half-up on divide, matching Spark).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..column import Column


def cast(col: Column, to: T.DType) -> Column:
    """Cast a column to another dtype, preserving validity."""
    src = col.dtype
    if src == to:
        return col
    if src.id == T.TypeId.STRING or to.id == T.TypeId.STRING:
        return _cast_string(col, to)

    if src.id == T.TypeId.DECIMAL128 or to.id == T.TypeId.DECIMAL128:
        return _cast_decimal128(col, to)

    data = col.values()   # FLOAT64 bit pairs decode to f64 values
    if src.is_decimal and to.is_decimal:
        data = _rescale(data, src.scale, to.scale).astype(to.storage)
    elif src.is_decimal:
        # decimal → float/int: apply the scale
        if to.storage.kind == "f":
            data = data.astype(to.storage) * np.float64(10.0) ** src.scale
        else:
            data = _rescale(data, src.scale, 0).astype(to.storage)
    elif to.is_decimal:
        if src.storage.kind == "f":
            scaled = data.astype(jnp.float64) * np.float64(10.0) ** (-to.scale)
            data = jnp.round(scaled).astype(to.storage)
        else:
            data = _rescale(data.astype(jnp.int64), 0, to.scale).astype(to.storage)
    elif src.id == T.TypeId.BOOL8:
        data = (data != 0).astype(to.storage)
    elif to.id == T.TypeId.BOOL8:
        data = (data != 0).astype(jnp.uint8)
    else:
        data = data.astype(to.storage if to.id != T.TypeId.FLOAT64
                           else jnp.float64)
    return Column.from_values(to, data, validity=col.validity)


def _cast_string(col: Column, to: T.DType) -> Column:
    """STRING ↔ numeric casts, dispatching to the ops.strings parse/format
    kernels (Spark CAST semantics: unparseable rows become null)."""
    from . import strings as S
    src = col.dtype
    if src.id == T.TypeId.STRING:
        if to.id == T.TypeId.BOOL8:
            return S.to_bool(col)
        if to.id == T.TypeId.DECIMAL64 or to.id == T.TypeId.DECIMAL32:
            parsed = S.to_decimal(col, to.scale)
            if to.id == T.TypeId.DECIMAL64:
                return parsed
            # narrow with overflow → null (Spark CAST), not int32 wrap
            i32 = np.iinfo(np.int32)
            in_range = (parsed.data >= i32.min) & (parsed.data <= i32.max)
            v = (in_range if parsed.validity is None
                 else (parsed.validity & in_range))
            return Column(to, parsed.data.astype(to.storage), validity=v)
        if to.id == T.TypeId.TIMESTAMP_DAYS:
            return S.to_date(col)
        if to.is_timestamp or (T.TypeId.DURATION_DAYS <= to.id
                               <= T.TypeId.DURATION_NANOSECONDS):
            raise NotImplementedError(f"STRING → {to.id.name}")
        if to.is_fixed_width and to.storage.kind in "iu":
            parsed = S.to_int64(col)
            if to == T.int64:
                return parsed
            if to.id == T.TypeId.UINT64:
                # parse tops out below 2^63 (18-digit guard), so only the
                # sign check matters; iinfo(uint64).max won't trace as an
                # int64 constant
                in_range = parsed.data >= 0
            else:
                info = np.iinfo(to.storage)
                in_range = ((parsed.data >= info.min)
                            & (parsed.data <= info.max))
            v = (in_range if parsed.validity is None
                 else (parsed.validity & in_range))
            return Column(to, parsed.data.astype(to.storage), validity=v)
        raise NotImplementedError(f"STRING → {to.id.name}")
    # numeric → STRING
    if src.id == T.TypeId.BOOL8:
        return S.format_bool(col)
    if src.id == T.TypeId.TIMESTAMP_DAYS:
        return S.format_date(col)
    if src.is_timestamp or (T.TypeId.DURATION_DAYS <= src.id
                            <= T.TypeId.DURATION_NANOSECONDS):
        raise NotImplementedError(f"{src.id.name} → STRING")
    if src.is_decimal and src.id != T.TypeId.DECIMAL128:
        return S.format_decimal(col)
    if src.is_fixed_width and src.storage.kind in "iu":
        return S.format_int64(col)
    raise NotImplementedError(f"{src.id.name} → STRING")


def _cast_decimal128(col: Column, to: T.DType) -> Column:
    """Casts in/out of the [n,2]-lane DECIMAL128 representation."""
    from . import decimal128 as d128
    src = col.dtype
    if src.id == T.TypeId.DECIMAL128:
        if to.id == T.TypeId.DECIMAL128:
            return d128.rescale(col, to.scale)
        if to.id == T.TypeId.FLOAT64:
            return d128.to_float64(col)
        if to.is_decimal or to.is_numeric:
            mid = col if to.scale == src.scale else d128.rescale(col, to.scale)
            return d128.narrow(mid, to)
        raise NotImplementedError(f"decimal128 → {to.id.name}")
    # widening into decimal128
    if src.is_decimal or src.storage.kind in "iu" or src.id == T.TypeId.BOOL8:
        wide = d128.widen(col)
        if wide.dtype.scale != to.scale:
            wide = d128.rescale(wide, to.scale)
        return wide
    if src.storage.kind == "f":
        # float → decimal128 by two-limb split: a float64 mantissa is 53
        # bits, so hi = ⌊x/2^64⌋ and lo = x - hi·2^64 are each exact in f64
        # and together reach the full 128-bit range (an int64 intermediate
        # would silently wrap above 2^63).  Exact on CPU; on TPU, f64
        # div/floor are emulated and may be a few ulp off above 2^64.
        scaled = jnp.round(
            col.values().astype(jnp.float64) * np.float64(10.0) ** (-to.scale))
        neg = scaled < 0
        mag = jnp.abs(scaled)
        hi_f = jnp.floor(mag / (2.0 ** 64))
        lo_f = mag - hi_f * (2.0 ** 64)            # in [0, 2^64)
        lo = jnp.where(lo_f >= 2.0 ** 63,
                       (lo_f - 2.0 ** 64).astype(jnp.int64),
                       lo_f.astype(jnp.int64))
        hi = hi_f.astype(jnp.int64)
        lanes = jnp.stack([lo, hi], axis=1)
        lanes = jnp.where(neg[:, None], d128._negate_lanes(lanes), lanes)
        return Column(T.decimal128(to.scale), lanes, validity=col.validity)
    raise NotImplementedError(f"{src.id.name} → decimal128")


def _rescale(data: jnp.ndarray, from_scale: int, to_scale: int) -> jnp.ndarray:
    """unscaled * 10**from_scale == result * 10**to_scale."""
    diff = from_scale - to_scale
    if diff == 0:
        return data
    if diff > 0:
        return data * np.int64(10) ** diff
    div = np.int64(10) ** (-diff)
    # round half away from zero, like Spark's decimal rescale (floor division
    # on a negative adjusted value would over-round, so work on magnitudes)
    half = div // 2
    mag = (jnp.abs(data) + half) // div
    return jnp.where(data < 0, -mag, mag)
