"""Column casts (numeric, bool, decimal rescale, timestamps).

Replaces the slice of libcudf's cast kernels the Spark plugin leans on
(SURVEY §2.9 / §7 step 6).  TPU-first: every cast is a single fused
elementwise XLA op over the column's lanes; validity rides along untouched.

Decimal semantics follow the reference's representation (scaled integers,
``RowConversion.java:114-118``): DECIMAL(s) holds ``unscaled * 10**s`` with
cudf's negative-scale convention, so rescaling from s1 to s2 multiplies or
divides by ``10**(s1 - s2)`` (round-half-up on divide, matching Spark).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..column import Column


def cast(col: Column, to: T.DType) -> Column:
    """Cast a column to another dtype, preserving validity."""
    src = col.dtype
    if src == to:
        return col
    if src.id == T.TypeId.STRING or to.id == T.TypeId.STRING:
        raise NotImplementedError("string casts live in ops.strings")

    data = col.data
    if src.is_decimal and to.is_decimal:
        data = _rescale(data, src.scale, to.scale).astype(to.storage)
    elif src.is_decimal:
        # decimal → float/int: apply the scale
        if to.storage.kind == "f":
            data = data.astype(to.storage) * np.float64(10.0) ** src.scale
        else:
            data = _rescale(data, src.scale, 0).astype(to.storage)
    elif to.is_decimal:
        if src.storage.kind == "f":
            scaled = data.astype(jnp.float64) * np.float64(10.0) ** (-to.scale)
            data = jnp.round(scaled).astype(to.storage)
        else:
            data = _rescale(data.astype(jnp.int64), 0, to.scale).astype(to.storage)
    elif src.id == T.TypeId.BOOL8:
        data = (data != 0).astype(to.storage)
    elif to.id == T.TypeId.BOOL8:
        data = (data != 0).astype(jnp.uint8)
    else:
        data = data.astype(to.storage)
    return Column(to, data, validity=col.validity)


def _rescale(data: jnp.ndarray, from_scale: int, to_scale: int) -> jnp.ndarray:
    """unscaled * 10**from_scale == result * 10**to_scale."""
    diff = from_scale - to_scale
    if diff == 0:
        return data
    if diff > 0:
        return data * np.int64(10) ** diff
    div = np.int64(10) ** (-diff)
    # round half away from zero, like Spark's decimal rescale (floor division
    # on a negative adjusted value would over-round, so work on magnitudes)
    half = div // 2
    mag = (jnp.abs(data) + half) // div
    return jnp.where(data < 0, -mag, mag)
