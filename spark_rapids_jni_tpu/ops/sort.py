"""Multi-key table sort (libcudf sort/order_by analog).

TPU-first: ``jnp.lexsort`` (XLA's variadic sort) over the key columns —
no comparator kernels.  Nulls order first or last per key via an explicit
null-rank lane prepended to that key, matching Spark's NULLS FIRST/LAST.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from ..column import Table
from ..utils import metrics
from .filter import gather


def order_by(table: Table, keys: Sequence[int],
             ascending: Sequence[bool] | None = None,
             nulls_first: Sequence[bool] | None = None) -> jnp.ndarray:
    """Row ordering by the given key column indices (first key is primary)."""
    with metrics.span("sort.order_by", keys=len(keys),
                      rows=table.num_rows):
        return _order_by(table, keys, ascending, nulls_first)


def _order_by(table: Table, keys: Sequence[int],
              ascending: Sequence[bool] | None = None,
              nulls_first: Sequence[bool] | None = None) -> jnp.ndarray:
    ascending = list(ascending) if ascending else [True] * len(keys)
    nulls_first = list(nulls_first) if nulls_first else [True] * len(keys)

    lanes = []
    # lexsort sorts by the LAST key first → feed keys in reverse priority
    from ..column import as_dict_column
    for ki, asc, nf in reversed(list(zip(keys, ascending, nulls_first))):
        col = table[ki]
        if col.dtype.id.name == "STRING" and as_dict_column(col) is not None:
            # dictionary fast path: one order-preserving rank lane replaces
            # the whole byte-lane stack (equal strings ⇒ equal ranks, so
            # ties — and lexsort stability — match the byte path exactly)
            from . import strings
            rank, _ = strings.dict_rank_codes(as_dict_column(col))
            key_lanes = [~rank if not asc else rank]
        elif col.dtype.id.name == "STRING":
            # u32 byte lanes + length tiebreak (see ops.strings), already in
            # increasing-priority order for lexsort
            from . import strings
            key_lanes = strings.sort_key_lanes(col, descending=not asc)
        elif col.dtype.id.name == "DECIMAL128":
            from . import decimal128 as d128
            key_lanes = d128.sort_key_lanes(col, descending=not asc)
        elif col.dtype.id.name == "FLOAT64":
            # storage is the IEEE bit pattern (u32 [n, 2]); the classic
            # monotone bits->uint mapping (negatives inverted, positives
            # sign-flipped) sorts numerically EXACTLY with no f64
            # arithmetic, and NaN (max exponent, nonzero mantissa) lands
            # above +inf — Spark's NaN-largest order — in both directions.
            key_lanes = f64_sort_key_lanes(col, descending=not asc)
        else:
            data = col.data
            if not asc:
                data = -data if data.dtype.kind == "f" else ~data  # order-reversing
            key_lanes = [data]
            if data.dtype.kind == "f" and not asc:
                # Spark orders NaN as the LARGEST value: ascending sorts
                # place it last natively, but negation keeps NaN last, so
                # descending needs an explicit NaN-first rank lane
                key_lanes.append(jnp.where(jnp.isnan(data), 0, 1))
        if col.validity is not None:
            # null rows must TIE on this key (SQL: all nulls equal under
            # ORDER BY) so lower-priority keys order them — zero the stale
            # payload, else it ranks the null block and splits downstream
            # groupby segments
            key_lanes = [jnp.where(col.validity, lane,
                                   jnp.zeros((), lane.dtype))
                         for lane in key_lanes]
        lanes.extend(key_lanes)
        if col.validity is not None:
            # the rank lane always sorts ascending, independent of the data
            # lane's direction: 0 → nulls first, 2 → nulls last
            null_rank = jnp.where(col.validity, 1, 0 if nf else 2)
            lanes.append(null_rank)   # appended after → higher priority
    return jnp.lexsort(tuple(lanes))


def f64_sort_key_lanes(col, descending: bool = False) -> list[jnp.ndarray]:
    """Order-preserving u32 lanes for a FLOAT64 bit-pair column, in
    increasing lexsort priority (lo lane first, hi lane last).

    All NaNs (either sign, any payload) map to the single maximum key —
    Spark's NaN-largest total order — before the optional descending
    inversion, so NaN sorts last ascending and first descending."""
    from ..utils.f64bits import is_nan_bits, monotone_lanes
    lo = col.data[:, 0]
    hi = col.data[:, 1]
    nan = is_nan_bits(lo, hi)
    lo_m, hi_m = monotone_lanes(lo, hi)   # shared map: joins stay in lockstep
    hi_k = jnp.where(nan, jnp.uint32(0xFFFFFFFF), hi_m)
    lo_k = jnp.where(nan, jnp.uint32(0xFFFFFFFF), lo_m)
    if descending:
        hi_k, lo_k = ~hi_k, ~lo_k
    return [lo_k, hi_k]


def sort_table(table: Table, keys: Sequence[int],
               ascending: Sequence[bool] | None = None,
               nulls_first: Sequence[bool] | None = None) -> Table:
    with metrics.span("sort.table", keys=len(keys), rows=table.num_rows):
        return gather(table, order_by(table, keys, ascending, nulls_first))
