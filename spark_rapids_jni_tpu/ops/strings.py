"""String operations — the libcudf ``strings`` slice the Spark plugin needs
(SURVEY §2.9; the reference gets all of this from the cudf submodule, e.g.
``make_strings_column`` usage at ``row_conversion.cu:2240``).

TPU-first design.  A STRING column is Arrow layout (uint8 chars [total] +
int32 offsets [n+1]) — variable-width data the VPU cannot compare directly.
The central primitive here is the **padded byte matrix**: a [n, L] uint8 view
(L = max length, resolved with one host sync — the same two-phase shape
discipline as the row-conversion strings path), packed big-endian into u32
lanes so that *numeric* lane comparison equals *lexicographic byte*
comparison.  Everything else rides on that:

* ``sort_key_lanes`` — lanes for ``jnp.lexsort`` (unlocks string sort keys);
* ``dictionary_encode`` — order-preserving dense int32 codes + dictionary
  (sort → adjacent-unique → rank), the enabler for string groupby keys;
* ``encode_shared`` — one dictionary across several columns, so equi-joins
  can compare codes instead of bytes;
* ``equal_to`` / ``equal_to_scalar`` — vectorized equality;
* ``upper`` / ``lower`` / ``substring`` / ``concat`` — the elementwise
  minimum for TPC-DS-shaped plans.

Null semantics follow Spark: null compares as null (predicates yield False),
nulls form their own group key, and null join keys never match.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..column import Column, DictColumn, as_dict_column
from ..rowconv.convert import _segment_of  # marker-scatter + cumsum lookup


def _lengths(col: Column) -> jnp.ndarray:
    return col.offsets[1:] - col.offsets[:-1]


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def byte_matrix(col: Column, width: Optional[int] = None):
    """Padded byte view: (uint8 [n, L], lengths int32 [n]).

    ``mat[i, j]`` is the j-th byte of row i, zero beyond the row's length.
    ``width`` pins L (callers comparing two columns share the larger);
    otherwise L = max row length, one host sync, rounded up to 4.
    """
    n = col.num_rows
    lens = _lengths(col)
    if width is None:
        width = _max_len(col)
    L = max(_round_up(width, 4), 4)
    j = jnp.arange(L, dtype=jnp.int32)
    idx = col.offsets[:-1, None] + j[None, :]
    mask = j[None, :] < lens[:, None]
    if col.data.shape[0]:
        mat = jnp.where(mask, col.data[jnp.clip(idx, 0, col.data.shape[0] - 1)],
                        jnp.uint8(0))
    else:
        mat = jnp.zeros((n, L), dtype=jnp.uint8)
    return mat, lens


def _max_len(col: Column) -> int:
    """Max string length: free from the host-mirror offsets when available,
    one counted scalar sync otherwise — memoized on the offsets array (the
    width is a pure function of it, and query plans re-touch the same
    dimension columns constantly)."""
    from ..utils import hostcache, syncs
    if col.num_rows == 0:
        return 0
    hit = syncs.memo_get("strwidth", (col.offsets,))
    if hit is not None:
        return hit
    host = hostcache.peek(col.offsets)
    if host is not None:
        width = int((host[1:] - host[:-1]).max(initial=0))
    else:
        width = syncs.scalar(jnp.max(_lengths(col)))
    syncs.memo_put("strwidth", (col.offsets,), width)
    return width


def _u32_lanes(mat: jnp.ndarray) -> jnp.ndarray:
    """[n, L] bytes → [n, L//4] big-endian u32 lanes (lane compare ==
    lexicographic byte compare)."""
    n, L = mat.shape
    b = mat.reshape(n, L // 4, 4).astype(jnp.uint32)
    return (b[..., 0] << 24) | (b[..., 1] << 16) | (b[..., 2] << 8) | b[..., 3]


def sort_key_lanes(col: Column, descending: bool = False) -> list[jnp.ndarray]:
    """Lanes for ``jnp.lexsort``, in *increasing* priority order (the caller
    appends them in this order; lexsort treats later keys as higher priority).

    Priority within one string key: first 4 bytes > next 4 bytes > … >
    length (the tiebreak that orders a string after its proper prefix —
    zero padding alone cannot distinguish "ab" from "ab\\x00")."""
    mat, lens = byte_matrix(col)
    lanes = _u32_lanes(mat)
    out = [(-lens if descending else lens)]
    for k in range(lanes.shape[1] - 1, -1, -1):
        lane = lanes[:, k]
        out.append(~lane if descending else lane)
    return out


# ---------------------------------------------------------------------------
# dictionary encode
# ---------------------------------------------------------------------------

def dict_rank_codes(dcol: DictColumn) -> tuple[jnp.ndarray, Column]:
    """Order-preserving rank per row of a :class:`DictColumn` + the sorted
    dictionary those ranks index.

    Scan codes are in *parquet-dictionary* order, not lexicographic order;
    sorts and sorted groupbys need ranks.  The re-encode runs over the
    dictionary only (|dict| rows, memoized via the ``dictionary_encode``
    memo), then one gather maps row codes → ranks — row bytes are never
    touched.  Duplicate dictionary entries (merged multi-chunk dictionaries)
    collapse onto one rank, so rank equality == string equality even there.
    """
    if dcol.sorted_dict:
        return dcol.codes, dcol.dictionary
    rank, uniq = dictionary_encode(dcol.dictionary)
    nd = dcol.dictionary.num_rows
    from ..utils import metrics
    metrics.count("strings.dict.gather")
    if nd == 0:
        return jnp.zeros(dcol.codes.shape, jnp.int32), uniq
    rows = rank.data[jnp.clip(dcol.codes, 0, nd - 1)]
    return rows, uniq


def _dict_predicate(col: Column, fn) -> Optional[Column]:
    """Dictionary fast path for per-row string predicates: evaluate ``fn``
    once per dictionary entry (|dict| rows, typically 100-100k× smaller than
    the table), then gather the boolean by code.  Returns None when ``col``
    carries no dictionary (caller falls through to the byte-matrix path)."""
    d = as_dict_column(col)
    if d is None:
        return None
    from ..utils import metrics
    metrics.count("strings.dict.predicate")
    nd = d.dictionary.num_rows
    if nd == 0:
        bits = jnp.zeros(d.codes.shape, bool)
    else:
        dmask = fn(d.dictionary)           # BOOL8 over the dictionary
        bits = (dmask.data != 0)[jnp.clip(d.codes, 0, nd - 1)]
    metrics.count("strings.dict.gather")
    return _as_bool_column(bits, d.validity)


def dictionary_encode(col: Column) -> tuple[Column, Column]:
    """Order-preserving dense codes: (codes int32 column, dictionary column).

    ``codes[i]`` is the rank of row i's string among the distinct strings
    (so code comparison == string comparison), and indexes the returned
    dictionary column directly.  Null rows encode as the zeroed byte string
    (one shared code) with validity carried through — equality on
    (code, validity) pairs equals Spark's null-aware key equality.

    A :class:`DictColumn` input re-encodes through its dictionary (see
    :func:`dict_rank_codes`) — the dense-code consumers (groupby keys,
    string join keys, window partitions) get the fast path with no byte
    materialization and no byte-matrix sort over the full table.
    """
    d = as_dict_column(col)
    if d is not None:
        rows, uniq = dict_rank_codes(d)
        if d.validity is not None:
            # mirror the materialized path: null rows collapse onto the
            # lowest code so sorted-group order can't depend on the stale
            # code a null slot happens to hold
            rows = jnp.where(d.validity, rows, 0)
        return Column(T.int32, rows, validity=d.validity), uniq
    n = col.num_rows
    if n == 0:
        return (Column(T.int32, jnp.zeros(0, jnp.int32)),
                Column(T.string, jnp.zeros(0, jnp.uint8),
                       jnp.zeros(1, jnp.int32)))
    # pure function of the column payload, re-touched by every groupby /
    # window / join over the same dimension column: memoize (the distinct
    # count sync below then happens once per column, not once per query op)
    from ..utils import syncs
    memo_key = (col.data, col.offsets) + (
        (col.validity,) if col.validity is not None else ())
    memo_tag = f"dictenc{'v' if col.validity is not None else ''}"
    hit = syncs.memo_get(memo_tag, memo_key)
    if hit is not None:
        return hit
    mat, lens = byte_matrix(col)
    if col.validity is not None:
        # nulls collapse onto the zeroed key so they share one code
        mat = jnp.where(col.validity[:, None], mat, jnp.uint8(0))
        lens = jnp.where(col.validity, lens, 0)
    lanes = _u32_lanes(mat)

    sort_keys = [lens] + [lanes[:, k] for k in range(lanes.shape[1] - 1, -1, -1)]
    order = jnp.lexsort(tuple(sort_keys))

    s_lanes = lanes[order]
    s_lens = lens[order]
    head = jnp.zeros(n, dtype=jnp.int32)
    neq = jnp.any(s_lanes[1:] != s_lanes[:-1], axis=1) | (s_lens[1:] != s_lens[:-1])
    head = head.at[1:].set(neq.astype(jnp.int32))
    codes_sorted = jnp.cumsum(head, dtype=jnp.int32)

    codes = jnp.zeros(n, dtype=jnp.int32).at[order].set(codes_sorted)

    # dictionary: one representative row per distinct value, gathered from
    # the ORIGINAL column.  Null rows share code 0 with the zeroed key but
    # still carry their original bytes, so a valid row must win the
    # representative slot wherever one exists (otherwise a masked-null row's
    # payload could decode as the empty-string group key): scatter any row
    # first, then overwrite with valid rows (invalid ones routed to a trash
    # slot).
    ndict = syncs.scalar(codes_sorted[-1]) + 1   # scalar sync (distinct count)
    order32 = order.astype(jnp.int32)
    first_pos = jnp.zeros(ndict + 1, dtype=jnp.int32).at[
        jnp.flip(codes_sorted)].set(jnp.flip(order32))
    if col.validity is not None:
        slot = jnp.where(col.validity[order], codes_sorted, ndict)
        first_pos = first_pos.at[jnp.flip(slot)].set(jnp.flip(order32))
    from .filter import _gather_column
    uniq = _gather_column(Column(col.dtype, col.data, col.offsets),
                          first_pos[:ndict])
    out = (Column(T.int32, codes, validity=col.validity), uniq)
    syncs.memo_put(memo_tag, memo_key, out)
    return out


def encode_shared(cols: Sequence[Column]) -> list[Column]:
    """Encode several string columns against ONE shared dictionary, so codes
    compare/equate across columns (the equi-join enabler).

    :class:`DictColumn` inputs contribute their *dictionaries* (small) to
    the shared encode instead of their rows, then translate row codes with
    one gather — a string equi-join between two dict-scanned columns costs
    an encode over the union of dictionaries, not over both tables.  Mixed
    dict/plain inputs compose: the plain side is encoded at full size as
    before, against the same shared dictionary.
    """
    dicts = [as_dict_column(c) for c in cols]
    if any(d is not None for d in dicts):
        from ..utils import metrics
        parts = [d.dictionary if d is not None else c
                 for c, d in zip(cols, dicts)]
        shared = encode_shared(parts)      # all plain now → base path below
        out = []
        for c, d, s in zip(cols, dicts, shared):
            if d is None:
                out.append(s)
                continue
            metrics.count("strings.dict.gather")
            nd = d.dictionary.num_rows
            rows = (s.data[jnp.clip(d.codes, 0, nd - 1)] if nd
                    else jnp.zeros(d.codes.shape, jnp.int32))
            if d.validity is not None:
                rows = jnp.where(d.validity, rows, 0)
            out.append(Column(T.int32, rows, validity=d.validity))
        return out
    sizes = [c.num_rows for c in cols]
    chars = jnp.concatenate([c.data for c in cols]) if any(
        c.data.shape[0] for c in cols) else jnp.zeros(0, jnp.uint8)
    offs_parts, validity_parts = [jnp.zeros(1, jnp.int32)], []
    char_base = 0
    for c in cols:
        offs_parts.append(c.offsets[1:] + char_base)
        char_base += int(c.data.shape[0])
        validity_parts.append(c.validity_or_true())
    combined = Column(
        T.string, chars, jnp.concatenate(offs_parts),
        None if all(c.validity is None for c in cols)
        else jnp.concatenate(validity_parts))
    codes, _ = dictionary_encode(combined)
    out, base = [], 0
    for c, sz in zip(cols, sizes):
        out.append(Column(T.int32, codes.data[base:base + sz],
                          validity=c.validity))
        base += sz
    return out


# ---------------------------------------------------------------------------
# equality
# ---------------------------------------------------------------------------

def equal_to(a: Column, b: Column) -> Column:
    """Row-wise string equality → BOOL8 column (null if either side null)."""
    la, lb = _lengths(a), _lengths(b)
    width = max(_max_len(a), _max_len(b))
    ma, _ = byte_matrix(a, width)
    mb, _ = byte_matrix(b, width)
    eq = (la == lb) & jnp.all(ma == mb, axis=1)
    v = None
    if a.validity is not None or b.validity is not None:
        v = a.validity_or_true() & b.validity_or_true()
    return Column(T.bool8, eq.astype(jnp.uint8), validity=v)


def equal_to_scalar(col: Column, value: str | bytes) -> Column:
    """Column == scalar → BOOL8 column (null rows stay null)."""
    hit = _dict_predicate(col, lambda u: equal_to_scalar(u, value))
    if hit is not None:
        return hit
    payload = value.encode("utf-8") if isinstance(value, str) else bytes(value)
    lens = _lengths(col)
    mat, _ = byte_matrix(col, max(len(payload), 1))
    target = np.zeros(mat.shape[1], dtype=np.uint8)
    target[:len(payload)] = np.frombuffer(payload, dtype=np.uint8)
    eq = (lens == len(payload)) & jnp.all(mat == jnp.asarray(target)[None, :],
                                          axis=1)
    return Column(T.bool8, eq.astype(jnp.uint8), validity=col.validity)


# ---------------------------------------------------------------------------
# elementwise transforms
# ---------------------------------------------------------------------------

def upper(col: Column) -> Column:
    """ASCII uppercase (the reference's unicode_to_lower analog operates
    ASCII-per-byte for pruning too, NativeParquetJni.cpp:45)."""
    d = as_dict_column(col)
    if d is not None:   # elementwise ⇒ transform the dictionary, keep codes
        return DictColumn(d.codes, upper(d.dictionary), d.validity)
    c = col.data
    is_lower = (c >= 97) & (c <= 122)
    return Column(T.string, jnp.where(is_lower, c - 32, c), col.offsets,
                  col.validity)


def lower(col: Column) -> Column:
    """ASCII lowercase."""
    d = as_dict_column(col)
    if d is not None:
        return DictColumn(d.codes, lower(d.dictionary), d.validity)
    c = col.data
    is_upper = (c >= 65) & (c <= 90)
    return Column(T.string, jnp.where(is_upper, c + 32, c), col.offsets,
                  col.validity)


def substring(col: Column, start: int, length: Optional[int] = None) -> Column:
    """0-based byte substring [start, start+length) of every row."""
    if start < 0:
        raise ValueError("substring start must be >= 0")
    d = as_dict_column(col)
    if d is not None:
        return DictColumn(d.codes, substring(d.dictionary, start, length),
                          d.validity)
    lens = _lengths(col)
    new_lens = jnp.maximum(lens - start, 0)
    if length is not None:
        new_lens = jnp.minimum(new_lens, length)
    new_offs = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(new_lens, dtype=jnp.int32)])
    from ..utils import syncs
    total = syncs.scalar(new_offs[-1])         # scalar sync (chars total)
    if total == 0:
        return Column(T.string, jnp.zeros(0, jnp.uint8), new_offs, col.validity)
    row_of = _segment_of(new_offs, total)
    within = jnp.arange(total, dtype=jnp.int32) - new_offs[row_of]
    src = col.offsets[:-1][row_of] + start + within
    return Column(T.string, col.data[src], new_offs, col.validity)


def concat(a: Column, b: Column) -> Column:
    """Row-wise concatenation a[i] + b[i] (null if either side null — Spark
    ``concat`` semantics)."""
    la, lb = _lengths(a), _lengths(b)
    valid = None
    if a.validity is not None or b.validity is not None:
        valid = a.validity_or_true() & b.validity_or_true()
        la = jnp.where(valid, la, 0)
        lb = jnp.where(valid, lb, 0)
    new_lens = la + lb
    new_offs = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(new_lens, dtype=jnp.int32)])
    from ..utils import syncs
    total = syncs.scalar(new_offs[-1])         # scalar sync (chars total)
    if total == 0:
        return Column(T.string, jnp.zeros(0, jnp.uint8), new_offs, valid)
    row_of = _segment_of(new_offs, total)
    within = jnp.arange(total, dtype=jnp.int32) - new_offs[row_of]
    from_a = within < la[row_of]
    src_a = a.offsets[:-1][row_of] + within
    src_b = b.offsets[:-1][row_of] + (within - la[row_of])
    ca = (a.data[jnp.clip(src_a, 0, a.data.shape[0] - 1)]
          if a.data.shape[0] else jnp.zeros_like(row_of, dtype=jnp.uint8))
    cb = (b.data[jnp.clip(src_b, 0, b.data.shape[0] - 1)]
          if b.data.shape[0] else jnp.zeros_like(row_of, dtype=jnp.uint8))
    return Column(T.string, jnp.where(from_a, ca, cb), new_offs, valid)


# ---------------------------------------------------------------------------
# numeric / date parsing (cudf strings::to_integers / to_fixed_point /
# to_timestamps analog — the Mortgage-ETL cast path, BASELINE config #5)
# ---------------------------------------------------------------------------

def _trimmed(mat: jnp.ndarray, lens: jnp.ndarray):
    """Left-justify each row past its leading spaces and drop trailing
    spaces from the length — Spark CAST trims whitespace before parsing
    (UTF8String.trimAll).  One gather, stays vectorized."""
    j = jnp.arange(mat.shape[1], dtype=jnp.int32)
    in_row = j[None, :] < lens[:, None]
    # Spark UTF8String.trimAll strips all ASCII whitespace: space, \t, \n,
    # \v, \f, \r
    is_space = (mat == ord(" ")) | ((mat >= 9) & (mat <= 13))
    lead = jnp.sum(jnp.cumprod((is_space & in_row).astype(jnp.int32),
                               axis=1), axis=1)
    # trailing spaces: contiguous suffix of the row that is all spaces
    tail_space = is_space | ~in_row
    trail = (jnp.sum(jnp.cumprod(tail_space[:, ::-1].astype(jnp.int32),
                                 axis=1), axis=1)
             - (mat.shape[1] - lens))
    new_lens = jnp.maximum(lens - lead - jnp.maximum(trail, 0), 0)
    src = jnp.clip(j[None, :] + lead[:, None], 0, mat.shape[1] - 1)
    shifted = jnp.take_along_axis(mat, src, axis=1)
    shifted = jnp.where(j[None, :] < new_lens[:, None], shifted,
                        jnp.uint8(0))
    return shifted, new_lens.astype(lens.dtype)


def _digit_scan(mat: jnp.ndarray, lens: jnp.ndarray):
    """Per-row digit parse state over the padded byte matrix.

    Returns (digits int64 [n,L] with -1 for non-digit/padding, neg bool [n],
    is_digit bool [n,L]).  Leading '-'/'+' is consumed; all other characters
    are the caller's concern.
    """
    j = jnp.arange(mat.shape[1], dtype=jnp.int32)
    in_row = j[None, :] < lens[:, None]
    neg = (mat[:, 0] == ord("-")) if mat.shape[1] else jnp.zeros(
        (mat.shape[0],), bool)
    signed = neg | (mat[:, 0] == ord("+"))
    consumed = signed[:, None] & (j[None, :] == 0)
    is_digit = in_row & ~consumed & (mat >= ord("0")) & (mat <= ord("9"))
    digits = jnp.where(is_digit, (mat - ord("0")).astype(jnp.int64), -1)
    return digits, neg, is_digit


def to_int64(col: Column) -> Column:
    """Parse decimal integer strings → INT64 (null for empty/malformed rows,
    Spark CAST semantics).  Fully vectorized: one weight per byte position
    (10^(#digits to the right)), one masked dot product per row."""
    mat, lens = byte_matrix(col)
    mat, lens = _trimmed(mat, lens)
    digits, neg, is_digit = _digit_scan(mat, lens)
    # a row is valid iff it has ≥1 digit and nothing but sign+digits
    j = jnp.arange(mat.shape[1], dtype=jnp.int32)
    in_row = j[None, :] < lens[:, None]
    junk = in_row & ~is_digit & ~(
        ((mat == ord("-")) | (mat == ord("+"))) & (j[None, :] == 0))
    ok = is_digit.any(axis=1) & ~junk.any(axis=1)
    # overflow guard: >18 significant digits (leading zeros excluded) can
    # wrap int64 — null, like Spark CAST (conservative at exactly 19)
    ok = ok & (_significant_digits(digits, is_digit) <= 18)
    # digits to the right of each position (inclusive scan from the right)
    right = (jnp.cumsum(is_digit[:, ::-1].astype(jnp.int64), axis=1)[:, ::-1]
             - is_digit.astype(jnp.int64))
    weight = jnp.where(is_digit, 10 ** jnp.clip(right, 0, 18), 0)
    vals = jnp.sum(jnp.where(is_digit, digits, 0) * weight, axis=1)
    vals = jnp.where(neg, -vals, vals)
    valid = ok if col.validity is None else (ok & col.validity)
    return Column(T.int64, vals, validity=valid)


def _significant_digits(digits: jnp.ndarray, which: jnp.ndarray) -> jnp.ndarray:
    """Per-row count of digits in ``which``, excluding leading zeros."""
    nonzero_seen = jnp.cumsum((which & (digits > 0)).astype(jnp.int32),
                              axis=1) > 0
    return jnp.sum(which & nonzero_seen, axis=1)


def to_decimal(col: Column, scale: int) -> Column:
    """Parse "123.45"-style strings → DECIMAL64(scale) with round-half-up
    when the text has more fractional digits than ``scale`` keeps."""
    mat, lens = byte_matrix(col)
    mat, lens = _trimmed(mat, lens)
    digits, neg, is_digit = _digit_scan(mat, lens)
    j = jnp.arange(mat.shape[1], dtype=jnp.int32)
    in_row = j[None, :] < lens[:, None]
    is_dot = in_row & (mat == ord("."))
    junk = in_row & ~is_digit & ~is_dot & ~(
        ((mat == ord("-")) | (mat == ord("+"))) & (j[None, :] == 0))
    ok = (is_digit.any(axis=1) & ~junk.any(axis=1)
          & (is_dot.sum(axis=1) <= 1))
    # fractional digits = digits right of the dot; the digit at distance k
    # right of the dot has decimal exponent -k.  Target exponent is
    # ``scale`` (cudf convention: negative = fractional), so each digit's
    # integer weight is 10^(-scale - k_frac) for kept digits; digits finer
    # than the scale are accumulated separately for rounding.
    after_dot = jnp.cumsum(is_dot.astype(jnp.int32), axis=1) > 0
    frac_pos = jnp.where(is_digit & after_dot,
                         jnp.cumsum((is_digit & after_dot).astype(jnp.int32),
                                    axis=1), 0)      # 1-based frac index
    # integer-part digits: count of integer digits to the right of each
    int_digit = is_digit & ~after_dot
    right_int = (jnp.cumsum(int_digit[:, ::-1].astype(jnp.int64),
                            axis=1)[:, ::-1] - int_digit.astype(jnp.int64))
    keep = -scale                                    # fractional digits kept
    exp = jnp.where(int_digit, right_int + keep,
                    jnp.where(is_digit, keep - frac_pos, -1))
    kept = is_digit & (exp >= 0)
    # overflow guard: significant integer digits + kept fractional digits
    # must fit int64 (≤18 decimal digits) — else null, like Spark CAST
    ok = ok & (_significant_digits(digits, int_digit) + keep <= 18)
    weight = jnp.where(kept, 10 ** jnp.clip(exp, 0, 18), 0)
    vals = jnp.sum(jnp.where(kept, digits, 0) * weight, axis=1)
    # round half up on the first dropped digit — exp == -1 identifies it in
    # both regimes: the (keep+1)-th fractional digit for negative scales,
    # and the most significant dropped INTEGER digit for positive scales
    first_drop = is_digit & (exp == -1)
    roundup = jnp.sum(jnp.where(first_drop, digits, 0), axis=1) >= 5
    vals = vals + roundup.astype(jnp.int64)
    vals = jnp.where(neg, -vals, vals)
    valid = ok if col.validity is None else (ok & col.validity)
    return Column(T.decimal64(scale), vals, validity=valid)


def _days_from_civil(y: jnp.ndarray, m: jnp.ndarray,
                     d: jnp.ndarray) -> jnp.ndarray:
    """Gregorian (y,m,d) → days since 1970-01-01 (Hinnant's civil_from_days
    inverse) — pure integer vector math."""
    y = y - (m <= 2)
    era = jnp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    mp = (m + 9) % 12
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _slice_int(mat: jnp.ndarray, start: int, width: int):
    """(value, all-digits) for a fixed byte slice."""
    raw = mat[:, start:start + width].astype(jnp.int64)
    sub = raw - ord("0")
    digits_ok = ((sub >= 0) & (sub <= 9)).all(axis=1)
    w = 10 ** jnp.arange(width - 1, -1, -1, dtype=jnp.int64)
    return jnp.sum(jnp.clip(sub, 0, 9) * w, axis=1), digits_ok


_DAYS_IN_MONTH = (31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31)


def to_date(col: Column, fmt: str = "%Y-%m-%d") -> Column:
    """Parse fixed-layout date strings → TIMESTAMP_DAYS.

    Supported formats: "%Y-%m-%d" (ISO) and "%m/%d/%Y" (the mortgage raw
    data layout).  Spark CAST semantics: wrong length, wrong separators,
    non-digit fields, and impossible calendar dates (Feb 31) are null."""
    mat, lens = byte_matrix(col, width=10)
    if fmt == "%Y-%m-%d":
        y, oy = _slice_int(mat, 0, 4)
        m, om = _slice_int(mat, 5, 2)
        d, od = _slice_int(mat, 8, 2)
        seps = (mat[:, 4] == ord("-")) & (mat[:, 7] == ord("-"))
    elif fmt == "%m/%d/%Y":
        m, om = _slice_int(mat, 0, 2)
        d, od = _slice_int(mat, 3, 2)
        y, oy = _slice_int(mat, 6, 4)
        seps = (mat[:, 2] == ord("/")) & (mat[:, 5] == ord("/"))
    else:
        raise NotImplementedError(f"unsupported date format {fmt!r}")
    leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
    msafe = jnp.clip(m, 1, 12)
    dim = (jnp.asarray(_DAYS_IN_MONTH, jnp.int64)[msafe - 1]
           + (leap & (msafe == 2)))
    ok = ((lens == 10) & seps & oy & om & od
          & (m >= 1) & (m <= 12) & (d >= 1) & (d <= dim))
    days = _days_from_civil(y, msafe, jnp.clip(d, 1, 31)).astype(jnp.int32)
    valid = ok if col.validity is None else (ok & col.validity)
    return Column(T.timestamp_days, days, validity=valid)


# ---------------------------------------------------------------------------
# substring search (cudf strings::contains / find; Spark LIKE)
# ---------------------------------------------------------------------------

def _match_at(mat: jnp.ndarray, lens: jnp.ndarray, pat: bytes,
              wildcard: int | None = None) -> jnp.ndarray:
    """[n, L] bool: does ``pat`` match starting at byte position s?

    Static unrolled compare per pattern byte (m small, L bounded by the
    byte-matrix width) — each step is one fused VPU compare.  ``wildcard``
    bytes in the pattern (SQL '_') match anything.  A match must fit inside
    the row: positions with s + m > len are False.
    """
    n, L = mat.shape
    m = len(pat)
    s = jnp.arange(L, dtype=jnp.int32)
    ok = (s[None, :] + m) <= lens[:, None]
    for k, pb in enumerate(pat):
        if wildcard is not None and pb == wildcard:
            continue
        shifted = mat[:, k:] if k else mat
        pad = jnp.zeros((n, k), jnp.uint8)
        cmp = jnp.concatenate([shifted, pad], axis=1) == jnp.uint8(pb)
        ok = ok & cmp
    return ok


def _as_bool_column(mask: jnp.ndarray, validity) -> Column:
    return Column(T.bool8, mask.astype(jnp.uint8), validity=validity)


def _search_matrix(col: Column, min_width: int):
    """Byte matrix wide enough for both the column's longest row AND the
    pattern (``byte_matrix(width=…)`` PINS the width — passing only the
    pattern length would truncate longer rows and lose matches)."""
    n = col.num_rows
    wmax = _max_len(col)
    return byte_matrix(col, width=max(wmax, min_width, 1))


def contains(col: Column, pat: str | bytes) -> Column:
    """True where the row contains ``pat`` (Spark ``contains`` / LIKE
    '%pat%'); empty pattern matches everything; null rows stay null."""
    hit = _dict_predicate(col, lambda u: contains(u, pat))
    if hit is not None:
        return hit
    pat = pat.encode() if isinstance(pat, str) else bytes(pat)
    mat, lens = _search_matrix(col, len(pat))
    return _as_bool_column(_match_at(mat, lens, pat).any(axis=1),
                           col.validity)


def starts_with(col: Column, pat: str | bytes) -> Column:
    hit = _dict_predicate(col, lambda u: starts_with(u, pat))
    if hit is not None:
        return hit
    pat = pat.encode() if isinstance(pat, str) else bytes(pat)
    mat, lens = _search_matrix(col, len(pat))
    return _as_bool_column(_match_at(mat, lens, pat)[:, 0], col.validity)


def ends_with(col: Column, pat: str | bytes) -> Column:
    hit = _dict_predicate(col, lambda u: ends_with(u, pat))
    if hit is not None:
        return hit
    pat = pat.encode() if isinstance(pat, str) else bytes(pat)
    mat, lens = _search_matrix(col, len(pat))
    hits = _match_at(mat, lens, pat)
    pos = jnp.clip(lens - len(pat), 0, mat.shape[1] - 1)
    at_end = jnp.take_along_axis(hits, pos[:, None].astype(jnp.int32),
                                 axis=1)[:, 0]
    return _as_bool_column(at_end & (lens >= len(pat)), col.validity)


def like(col: Column, pattern: str) -> Column:
    """SQL LIKE with ``%`` (any run) and ``_`` (any one byte) — the Spark /
    cudf ``strings::like`` subset (no escape character support).

    Pieces between ``%`` are matched left to right with a vectorized
    earliest-match scan per piece; the number of pieces is tiny and static,
    so the whole predicate stays a short chain of fused compares.
    """
    hit = _dict_predicate(col, lambda u: like(u, pattern))
    if hit is not None:
        return hit
    pat = pattern.encode()
    pieces = pat.split(b"%")
    anchored_start = not pattern.startswith("%")
    anchored_end = not pattern.endswith("%")
    mat, lens = _search_matrix(col, max((len(p) for p in pieces),
                                        default=0))
    L = mat.shape[1]
    n = mat.shape[0]
    okv = jnp.ones((n,), bool)
    cur = jnp.zeros((n,), jnp.int32)      # earliest position still usable
    idx = jnp.arange(L, dtype=jnp.int32)
    for pi, piece in enumerate(pieces):
        if not piece:
            continue
        hits = _match_at(mat, lens, piece, wildcard=ord("_"))
        is_first, is_last = pi == 0, pi == len(pieces) - 1
        if is_first and anchored_start:
            okv = okv & hits[:, 0]
            cur = jnp.maximum(cur, len(piece))
            if is_last and anchored_end:
                okv = okv & (lens == len(piece))
            continue
        if is_last and anchored_end:
            pos = jnp.clip(lens - len(piece), 0, L - 1)
            at_end = jnp.take_along_axis(
                hits, pos[:, None].astype(jnp.int32), axis=1)[:, 0]
            okv = okv & at_end & (lens >= len(piece)) & (pos >= cur)
            continue
        # floating piece: earliest match at position >= cur
        usable = hits & (idx[None, :] >= cur[:, None])
        found = usable.any(axis=1)
        first = jnp.argmax(usable, axis=1).astype(jnp.int32)
        okv = okv & found
        cur = first + len(piece)
    if not any(pieces):
        # pattern is all-% (or empty): "%...%" matches everything,
        # "" matches only the empty string
        okv = jnp.ones((n,), bool) if b"%" in pat else (lens == 0)
    return _as_bool_column(okv, col.validity)


# ---------------------------------------------------------------------------
# numeric → string formatting (cudf strings::from_integers / from_fixed_point;
# Spark CAST(x AS STRING))
# ---------------------------------------------------------------------------

_POW10 = [10 ** k for k in range(20)]


def _digit_matrix(mag: jnp.ndarray, width: int) -> jnp.ndarray:
    """uint8 [n, width] ASCII digits of ``mag`` (int64/uint64 ≥ 0),
    right-aligned at column width-1 — one fused divide/mod per position."""
    cols = []
    for p in range(width):
        div = jnp.asarray(10 ** (width - 1 - p), mag.dtype)
        cols.append(((mag // div) % 10).astype(jnp.uint8) + ord("0"))
    return jnp.stack(cols, axis=1)


def _ndigits(mag: jnp.ndarray, up_to: int = 18) -> jnp.ndarray:
    """Decimal digit count of mag ≥ 0 (0 → 1 digit); ``up_to`` is the
    largest power-of-ten exponent compared (18 for int64, 19 for uint64)."""
    n = jnp.ones_like(mag, dtype=jnp.int32)
    for k in range(1, up_to + 1):
        n = n + (mag >= jnp.asarray(_POW10[k], mag.dtype)).astype(jnp.int32)
    return n


def _uint64_magnitude(v: jnp.ndarray):
    """(magnitude as uint64, neg mask) — exact for INT64_MIN, whose
    magnitude has no int64 representation."""
    neg = v < 0
    u = v.astype(jnp.uint64)
    return jnp.where(neg, jnp.uint64(0) - u, u), neg


def _matrix_to_strings(mat: jnp.ndarray, starts: jnp.ndarray,
                       lens: jnp.ndarray, validity) -> Column:
    """Assemble a STRING column from per-row [start, start+len) slices of a
    byte matrix (same two-phase gather as ``substring``)."""
    lens = jnp.where(validity, lens, 0) if validity is not None else lens
    new_offs = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(lens, dtype=jnp.int32)])
    from ..utils import syncs
    total = syncs.scalar(new_offs[-1])        # scalar sync (chars total)
    if total == 0:
        return Column(T.string, jnp.zeros(0, jnp.uint8), new_offs, validity)
    row_of = _segment_of(new_offs, total)
    within = jnp.arange(total, dtype=jnp.int32) - new_offs[row_of]
    chars = mat[row_of, starts[row_of] + within]
    return Column(T.string, chars, new_offs, validity)


def _format_unsigned(mag: jnp.ndarray, neg: jnp.ndarray, validity,
                     trailing_zeros: int = 0) -> Column:
    """uint64 magnitudes + sign mask → decimal strings.

    ``trailing_zeros`` appends literal zero digits (positive decimal
    scales) — except for magnitude 0, which stays "0"."""
    nd = _ndigits(mag, up_to=19)
    W = 21  # '-' + up to 20 digits (2^64-1)
    digits = _digit_matrix(mag, W - 1)
    parts = [jnp.full((mag.shape[0], 1), ord("-"), jnp.uint8), digits]
    if trailing_zeros:
        parts.append(jnp.full((mag.shape[0], trailing_zeros), ord("0"),
                              jnp.uint8))
    mat = jnp.concatenate(parts, axis=1)
    tz = jnp.where(mag == 0, 0, trailing_zeros).astype(jnp.int32)
    lens = nd + tz + neg.astype(jnp.int32)
    starts = jnp.where(neg, (W - 1) - nd, W - nd)
    # '-' sits immediately before the first digit: copy it there
    rows = jnp.arange(mag.shape[0])
    spos = jnp.maximum(starts, 0)
    mat = mat.at[rows, spos].set(
        jnp.where(neg, jnp.uint8(ord("-")), mat[rows, spos]))
    return _matrix_to_strings(mat, starts, lens, validity)


def format_int64(col: Column) -> Column:
    """Integer column → decimal strings (Spark CAST(x AS STRING)).

    All arithmetic runs on the uint64 magnitude, so INT64_MIN and uint64
    values ≥ 2^63 format exactly (no abs() overflow, no wrap)."""
    if col.data.dtype == jnp.uint64:
        mag, neg = col.data, jnp.zeros(col.num_rows, bool)
    else:
        mag, neg = _uint64_magnitude(col.data.astype(jnp.int64))
    return _format_unsigned(mag, neg, col.validity)


def format_decimal(col: Column) -> Column:
    """decimal32/64 column → strings with the scale's fractional digits
    ("123.45" for unscaled 12345 at scale -2); scale 0 formats as integers.

    Runs on the uint64 magnitude (INT64_MIN-safe); positive scales append
    literal zero digits instead of multiplying (which would wrap)."""
    if col.dtype.scale == 0:
        return format_int64(col)
    mag, neg = _uint64_magnitude(col.data.astype(jnp.int64))
    n = col.num_rows
    if col.dtype.scale > 0:
        # value = unscaled * 10^s: digits of |unscaled| + s literal zeros
        # (multiplying would wrap int64)
        return _format_unsigned(mag, neg, col.validity,
                                trailing_zeros=col.dtype.scale)
    k = -col.dtype.scale
    div = jnp.uint64(10 ** k)
    int_part = mag // div
    frac = mag % div
    nd_int = _ndigits(int_part, up_to=19)
    WI = 20
    int_digits = _digit_matrix(int_part, WI)
    frac_digits = _digit_matrix(frac, k)
    dot = jnp.full((n, 1), ord("."), jnp.uint8)
    sign = jnp.full((n, 1), ord("-"), jnp.uint8)
    mat = jnp.concatenate([sign, int_digits, dot, frac_digits], axis=1)
    # layout: [0]='-', [1..WI]=int digits right-aligned, [WI+1]='.',
    # [WI+2..]=frac.  The string starts at the sign (if neg) else at the
    # first significant int digit.
    first_digit = 1 + WI - nd_int
    starts = jnp.where(neg, first_digit - 1, first_digit)
    rows = jnp.arange(n)
    spos = jnp.maximum(starts, 0)
    mat = mat.at[rows, spos].set(
        jnp.where(neg, jnp.uint8(ord("-")), mat[rows, spos]))
    lens = nd_int + 1 + k + neg.astype(jnp.int32)
    return _matrix_to_strings(mat, starts, lens, col.validity)


def _civil_from_days(days: jnp.ndarray):
    """days since 1970-01-01 → (y, m, d), Hinnant's civil_from_days with
    floor-division vector math (the inverse of ``_days_from_civil``)."""
    z = days.astype(jnp.int64) + 719468
    era = z // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y, m, d


def format_date(col: Column) -> Column:
    """TIMESTAMP_DAYS → ISO "YYYY-MM-DD" strings (Spark CAST(date AS
    STRING)); years outside 0000-9999 are null (no expanded-year format)."""
    y, m, d = _civil_from_days(col.data)
    ok = (y >= 0) & (y <= 9999)
    ys = jnp.clip(y, 0, 9999)
    mat = jnp.concatenate([
        _digit_matrix(ys, 4),
        jnp.full((col.num_rows, 1), ord("-"), jnp.uint8),
        _digit_matrix(m, 2),
        jnp.full((col.num_rows, 1), ord("-"), jnp.uint8),
        _digit_matrix(d, 2),
    ], axis=1)
    valid = ok if col.validity is None else (ok & col.validity)
    starts = jnp.zeros(col.num_rows, jnp.int32)
    lens = jnp.full(col.num_rows, 10, jnp.int32)
    return _matrix_to_strings(mat, starts, lens, valid)


_TRUE_WORDS = (b"true", b"t", b"yes", b"y", b"1")
_FALSE_WORDS = (b"false", b"f", b"no", b"n", b"0")


def to_bool(col: Column) -> Column:
    """Spark CAST(string AS BOOLEAN): case-insensitive true/false/t/f/
    yes/no/y/n/1/0; anything else (after trimming) is null."""
    low = lower(col)
    mat, lens = _search_matrix(low, 5)
    mat, lens = _trimmed(mat, lens)

    def word_eq(word: bytes):
        m = jnp.asarray(lens == len(word))
        for k, b in enumerate(word):
            m = m & (mat[:, k] == b)
        return m

    is_true = jnp.zeros(col.num_rows, bool)
    is_false = jnp.zeros(col.num_rows, bool)
    for w in _TRUE_WORDS:
        is_true = is_true | word_eq(w)
    for w in _FALSE_WORDS:
        is_false = is_false | word_eq(w)
    ok = is_true | is_false
    valid = ok if col.validity is None else (ok & col.validity)
    return Column(T.bool8, is_true.astype(jnp.uint8), validity=valid)


def format_bool(col: Column) -> Column:
    """BOOL8 → "true"/"false" strings (Spark CAST(boolean AS STRING))."""
    b = col.data != 0
    lit = jnp.asarray(np.frombuffer(b"falsetrue\x00", np.uint8))
    # one 5-wide matrix per row: "false" or "true\0"
    mat5 = jnp.where(b[:, None], lit[None, 5:10],
                     jnp.broadcast_to(lit[None, :5], (col.num_rows, 5)))
    lens = jnp.where(b, 4, 5).astype(jnp.int32)
    starts = jnp.zeros(col.num_rows, jnp.int32)
    return _matrix_to_strings(mat5, starts, lens, col.validity)
