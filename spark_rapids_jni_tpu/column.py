"""Device columnar table core.

This is the TPU-native replacement for the libcudf column/table ownership model
the reference leans on (SURVEY §2.9: ``make_fixed_width_column`` /
``make_strings_column`` / ``make_lists_column``, ``row_conversion.cu:1264,
2094, 2240``).  Design choices, TPU-first:

* A ``Column`` is a pytree of flat JAX arrays living in HBM — data, optional
  string offsets (Arrow layout: int32 [n+1] offsets + uint8 chars), optional
  validity.  Tables flow through ``jax.jit`` directly; XLA owns placement and
  fusion, so there is no RMM-style manual pool (PJRT's BFC arena is the
  allocator).
* Validity is carried as a *boolean vector* (one lane per row) rather than a
  packed bitmask: on the VPU a bool lane fuses into every elementwise op for
  free, while packed words would need unpack/repack around each op.  Arrow/
  cudf-style little-endian bitmasks are produced on demand via
  ``utils.bitmask`` for interchange (and for the JCUDF validity bytes).
* BOOL8 columns store uint8 0/1 payloads (JCUDF stores bools as one byte,
  ``RowConversion.java:60-67``).
* FLOAT64 columns store their IEEE754 **bit pattern** as uint32 [n, 2]
  (lo, hi half-words), not a float64 array: XLA:TPU cannot bitcast its
  emulated f64, so bit-level storage makes the JCUDF transcode and Parquet
  DOUBLE decode pure byte movement on every backend (``utils.f64bits``).
  Compute ops convert at their boundaries via :meth:`Column.values` /
  :meth:`Column.from_values`.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import types as T
from .utils import bitmask


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Column:
    """A single device column.

    Fixed-width: ``data`` is [n] of ``dtype.storage``; ``offsets`` is None.
    STRING: ``data`` is the uint8 chars buffer [total_bytes]; ``offsets`` is
    int32 [n+1] (Arrow layout, same as cudf's offsets+chars children —
    SURVEY §2.9).
    ``validity``: bool [n], True = valid; None = all rows valid.
    """

    dtype: T.DType
    data: jnp.ndarray
    offsets: Optional[jnp.ndarray] = None
    validity: Optional[jnp.ndarray] = None
    # Child columns for nested types (cudf column hierarchy analog):
    # LIST → [element column]; STRUCT → one per field.  None for leaves.
    children: Optional[list["Column"]] = None

    # -- pytree protocol ----------------------------------------------------
    def tree_flatten(self):
        return (self.data, self.offsets, self.validity, self.children), self.dtype

    @classmethod
    def tree_unflatten(cls, dtype, leaves):
        data, offsets, validity, children = leaves
        return cls(dtype, data, offsets, validity, children)

    # -- basics -------------------------------------------------------------
    @property
    def num_rows(self) -> int:
        if self.dtype.id == T.TypeId.STRUCT:
            return self.children[0].num_rows
        if self.dtype.is_variable_width:
            return self.offsets.shape[0] - 1
        return self.data.shape[0]

    def __len__(self) -> int:
        return self.num_rows

    @property
    def null_count(self) -> int:
        if self.validity is None:
            return 0
        return int(jnp.sum(~self.validity))

    def validity_or_true(self) -> jnp.ndarray:
        if self.validity is None:
            return jnp.ones((self.num_rows,), dtype=jnp.bool_)
        return self.validity

    def validity_bitmask(self) -> jnp.ndarray:
        """Arrow/cudf little-endian packed validity bitmask (uint8)."""
        return bitmask.pack_bits(self.validity_or_true())

    # -- construction -------------------------------------------------------
    @staticmethod
    def from_numpy(arr: np.ndarray, dtype: T.DType | None = None,
                   validity: np.ndarray | None = None) -> "Column":
        arr = np.asarray(arr)
        if dtype is None:
            dtype = T.from_numpy(arr.dtype)
        storage = np.ascontiguousarray(arr, dtype=dtype.storage)
        if dtype.id == T.TypeId.FLOAT64:
            from .utils import f64bits
            storage = f64bits.np_to_bits(storage)   # exact host-side view
        v = None if validity is None else jnp.asarray(np.asarray(validity, dtype=bool))
        return Column(dtype, jnp.asarray(storage), validity=v)

    @staticmethod
    def strings_from_list(strings: Sequence[Optional[str]]) -> "Column":
        """Build a STRING column from host strings (None ⇒ null row)."""
        valid = np.asarray([s is not None for s in strings], dtype=bool)
        payloads = [s.encode("utf-8") if s is not None else b"" for s in strings]
        lengths = np.asarray([len(p) for p in payloads], dtype=np.int32)
        offsets = np.zeros(len(strings) + 1, dtype=np.int32)
        np.cumsum(lengths, out=offsets[1:])
        chars = np.frombuffer(b"".join(payloads), dtype=np.uint8).copy()
        v = None if valid.all() else jnp.asarray(valid)
        joffs = jnp.asarray(offsets)
        from .utils import hostcache
        hostcache.seed(joffs, offsets.astype(np.int64))
        return Column(T.string, jnp.asarray(chars), joffs, v)

    @staticmethod
    def list_from_pylist(values, element_dtype: T.DType | None = None) -> "Column":
        """Build a LIST column from nested host lists (None ⇒ null row).

        Elements may themselves be lists/strings/scalars; the element column
        is built recursively (cudf make_lists_column analog,
        ``row_conversion.cu:1264``).
        """
        valid = np.asarray([v is not None for v in values], dtype=bool)
        flat = []
        lengths = np.zeros(len(values), dtype=np.int32)
        for i, v in enumerate(values):
            if v is not None:
                flat.extend(v)
                lengths[i] = len(v)
        offsets = np.zeros(len(values) + 1, dtype=np.int32)
        np.cumsum(lengths, out=offsets[1:])
        child = _column_from_pylist(flat, element_dtype)
        v = None if valid.all() else jnp.asarray(valid)
        dtype = T.list_(child.dtype)
        return Column(dtype, jnp.zeros((0,), jnp.uint8), jnp.asarray(offsets),
                      v, [child])

    @staticmethod
    def struct_from_columns(fields: Sequence["Column"],
                            validity: np.ndarray | None = None) -> "Column":
        """Build a STRUCT column from equal-length field columns."""
        fields = list(fields)
        n = fields[0].num_rows
        for f in fields:
            if f.num_rows != n:
                raise ValueError("struct fields must have equal length")
        v = None if validity is None else jnp.asarray(np.asarray(validity, bool))
        dtype = T.struct_(*[f.dtype for f in fields])
        return Column(dtype, jnp.zeros((0,), jnp.uint8), None, v, fields)

    # -- value <-> bit-pattern boundary (FLOAT64 storage invariant) ---------
    def values(self) -> jnp.ndarray:
        """Arithmetic payload: FLOAT64 bit pairs decode to f64 values;
        every other dtype returns ``data`` as-is."""
        if self.dtype.id == T.TypeId.FLOAT64:
            from .utils import f64bits
            return f64bits.from_bits(self.data)
        return self.data

    @staticmethod
    def from_values(dtype: T.DType, vals: jnp.ndarray,
                    validity=None) -> "Column":
        """Build a column from arithmetic values, encoding FLOAT64 to its
        uint32 [n, 2] bit-pattern storage."""
        if dtype.id == T.TypeId.FLOAT64:
            from .utils import f64bits
            vals = f64bits.to_bits(vals.astype(jnp.float64))
        return Column(dtype, vals, validity=validity)

    # -- host round-trip (tests / interchange) ------------------------------
    def to_numpy(self) -> np.ndarray:
        """Host copy of the payload (fixed-width columns only)."""
        if self.dtype.id == T.TypeId.FLOAT64:
            from .utils import f64bits
            return f64bits.np_from_bits(np.asarray(self.data))
        return np.asarray(self.data)

    def to_pylist(self):
        """Host list with ``None`` for nulls — test/debug convenience."""
        valid = np.asarray(self.validity_or_true())
        if self.dtype.id == T.TypeId.STRING:
            offsets = np.asarray(self.offsets)
            chars = np.asarray(self.data).tobytes()
            out = []
            for i in range(self.num_rows):
                if not valid[i]:
                    out.append(None)
                else:
                    out.append(chars[offsets[i]:offsets[i + 1]].decode("utf-8"))
            return out
        if self.dtype.id == T.TypeId.LIST:
            offsets = np.asarray(self.offsets)
            elems = self.children[0].to_pylist()
            return [elems[offsets[i]:offsets[i + 1]] if valid[i] else None
                    for i in range(self.num_rows)]
        if self.dtype.id == T.TypeId.STRUCT:
            field_vals = [f.to_pylist() for f in self.children]
            return [tuple(fv[i] for fv in field_vals) if valid[i] else None
                    for i in range(self.num_rows)]
        if self.dtype.id == T.TypeId.DECIMAL128:
            lanes = np.asarray(self.data)
            lo = lanes[:, 0].astype(np.uint64)
            hi = lanes[:, 1].astype(np.int64)
            return [int(hi[i]) * (1 << 64) + int(lo[i]) if valid[i] else None
                    for i in range(self.num_rows)]
        vals = self.to_numpy()
        if self.dtype.id == T.TypeId.BOOL8:
            vals = vals.astype(bool)
        return [vals[i].item() if valid[i] else None for i in range(self.num_rows)]


@jax.tree_util.register_pytree_node_class
class DictColumn(Column):
    """A STRING column stored as dictionary codes + a small dictionary.

    The classic column-store economy (Abadi et al., SIGMOD'06; cudf
    DICTIONARY columns): ``codes`` is int32 [n] indexing into ``dictionary``
    (a plain STRING :class:`Column` of the distinct values, no validity),
    with row validity carried on the codes.  Null rows hold code 0 — the
    payload is never read, mirroring how the scan zero-fills null slots.

    Predicates, joins, groupbys and sorts operate on the codes (see
    ``ops.strings`` / ``ops.join_plan`` / ``ops.groupby`` / ``ops.sort``);
    the byte payload materializes **lazily** on first ``data``/``offsets``
    access — the output boundary (rowconv, host extraction) — matching the
    scan-materialized layout bit-for-bit (null rows are zero-length).

    ``sorted_dict`` marks the dictionary as lexicographically sorted, in
    which case the codes themselves are order-preserving ranks (sorts and
    sorted groupbys can use them directly; otherwise
    ``ops.strings.dict_rank_codes`` re-ranks via the encode memo).
    """

    def __init__(self, codes: jnp.ndarray, dictionary: Column,
                 validity: Optional[jnp.ndarray] = None,
                 sorted_dict: bool = False):
        self.dtype = T.string
        self.codes = codes
        self.dictionary = dictionary
        self.validity = validity
        self.sorted_dict = sorted_dict
        self._mat: Optional[Column] = None

    # -- late materialization ------------------------------------------------
    def materialize(self) -> Column:
        """The equivalent plain STRING column (memoized; one size sync)."""
        if self._mat is not None:
            # Keep the capture/replay size tape aligned: a column
            # materialized BEFORE capture would elide this site's scalar
            # during the capture run, while the traced replay (fresh
            # tracer-leaf columns, cache empty) still resolves it — the
            # positional tape would shift and every later size lands at
            # the wrong site.  Re-recording the cached total restores the
            # one-scalar-per-materialize invariant in both modes.
            from .utils import syncs
            if syncs.mode() != "normal":
                syncs.scalar(self._mat.offsets[-1])
            return self._mat
        if self._mat is None:
            from .utils import metrics, syncs
            with metrics.span("strings.dict_materialize",
                              rows=int(self.codes.shape[0]),
                              dict_rows=self.dictionary.num_rows):
                metrics.count("strings.dict.materialize")
                doffs = self.dictionary.offsets
                nd = self.dictionary.num_rows
                safe = jnp.clip(self.codes, 0, max(nd - 1, 0))
                lens = (doffs[1:] - doffs[:-1])[safe] if nd else jnp.zeros(
                    self.codes.shape, jnp.int32)
                if self.validity is not None:
                    lens = jnp.where(self.validity, lens, 0)  # null ⇒ 0-length
                offs = jnp.concatenate(
                    [jnp.zeros(1, jnp.int32), jnp.cumsum(lens)]).astype(jnp.int32)
                total = syncs.scalar(offs[-1])
                starts = (doffs[:-1][safe] if nd
                          else jnp.zeros(self.codes.shape, jnp.int32))
                # char→row via the marker-cumsum segment trick (one tiny
                # scatter + cumsum) — the per-char binary search it
                # replaces was the dict-string materialization cliff
                # (O(total·log n), ~95% of the scan-bench wall)
                from .rowconv.convert import _segment_of
                elem = jnp.arange(total, dtype=jnp.int64)
                row_of = _segment_of(offs, int(total))
                src = starts.astype(jnp.int64)[row_of] + (
                    elem - offs.astype(jnp.int64)[row_of])
                chars = (self.dictionary.data[src] if nd
                         else jnp.zeros((total,), jnp.uint8))
                self._mat = Column(T.string, chars, offs, self.validity)
        return self._mat

    # payload accessors: touching bytes IS the output boundary
    @property
    def data(self):
        return self.materialize().data

    @property
    def offsets(self):
        return self.materialize().offsets

    @property
    def children(self):
        return None

    @property
    def num_rows(self) -> int:
        return self.codes.shape[0]      # static: no materialization for len()

    # -- pytree protocol: dict structure survives jit boundaries -------------
    def tree_flatten(self):
        return ((self.codes, self.dictionary, self.validity),
                ("dict", self.sorted_dict))

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        codes, dictionary, validity = leaves
        return cls(codes, dictionary, validity, sorted_dict=aux[1])

    # -- host extraction: decode via the dictionary, not the byte payload ----
    def to_pylist(self):
        dvals = self.dictionary.to_pylist()
        codes = np.asarray(self.codes)
        if self.validity is None:
            return [dvals[c] for c in codes]
        valid = np.asarray(self.validity)
        return [dvals[c] if valid[i] else None
                for i, c in enumerate(codes)]


def as_dict_column(col: Column) -> Optional[DictColumn]:
    """``col`` as a :class:`DictColumn` if it is one (forcing a cheap lazy
    wrapper to look), else None — the dispatch point for dict-aware ops."""
    if isinstance(col, DictColumn):
        return col
    if isinstance(col, LazyColumn):
        inner = col._force()
        if isinstance(inner, DictColumn):
            return inner
    return None


@jax.tree_util.register_pytree_node_class
class LazyColumn(Column):
    """A column whose payload materializes on first access.

    This is the package's *planner-level projection pass*, done structurally
    instead of as a rewrite: row-gathering ops (joins, filters, sorts,
    groupbys — everything routed through ``ops.filter.gather``) return
    ``LazyColumn``s, so a column the rest of the plan never reads is never
    gathered — its HBM materialization AND its size-resolution sync (for
    string columns) simply don't happen.  A 16-column join followed by a
    3-column aggregate allocates 3 columns, not 16 — the reference gets the
    same safety from its size-bounded batch machinery
    (``row_conversion.cu:1460-1539``); here oversize is avoided by never
    materializing what isn't referenced.

    Forcing *via attribute access inside an active trace* is well-defined:
    the deferred gather becomes part of the traced program (better fusion
    than the eager form).  Passing a LazyColumn ACROSS a jit boundary does
    NOT fuse it: ``tree_flatten`` runs at the jit argument boundary —
    outside the trace — so the column materializes eagerly there and the
    trace sees a plain :class:`Column` (``tree_unflatten`` rebuilds one).
    """

    def __init__(self, dtype: T.DType, num_rows: int, thunk):
        self.dtype = dtype
        self._n = num_rows
        self._thunk = thunk
        self._col: Optional[Column] = None

    def _force(self) -> Column:
        if self._col is None:
            self._col = self._thunk()
            self._thunk = None
        return self._col

    # payload accessors (dataclass fields on Column are plain instance
    # attributes, so these class-level properties intercept cleanly)
    @property
    def data(self):
        return self._force().data

    @property
    def offsets(self):
        return self._force().offsets

    @property
    def validity(self):
        return self._force().validity

    @property
    def children(self):
        return self._force().children

    @property
    def num_rows(self) -> int:
        return self._n          # static: no forcing to answer len()

    def tree_flatten(self):
        col = self._force()
        if isinstance(col, DictColumn):
            # a LazyColumn flattens with Column's 4-leaf layout; crossing a
            # jit boundary already materializes, so decode here too rather
            # than smuggle dict structure under the wrong unflatten
            col = col.materialize()
        return col.tree_flatten()

    @classmethod
    def tree_unflatten(cls, dtype, leaves):
        return Column.tree_unflatten(dtype, leaves)


def force_column(col: Column) -> Column:
    """The eager form of ``col`` (materializes a :class:`LazyColumn`)."""
    return col._force() if isinstance(col, LazyColumn) else col


def _column_from_pylist(values, dtype: T.DType | None = None) -> Column:
    """Build a column from a flat host list, inferring the type if needed."""
    if dtype is not None and dtype.id == T.TypeId.LIST:
        return Column.list_from_pylist(values, dtype.children[0])
    if dtype is not None and dtype.id == T.TypeId.STRING:
        return Column.strings_from_list(values)
    sample = next((v for v in values if v is not None), None)
    if dtype is None:
        if isinstance(sample, str):
            return Column.strings_from_list(values)
        if isinstance(sample, (list, tuple)):
            return Column.list_from_pylist(values)
    arr = np.asarray([0 if v is None else v for v in values])
    validity = (np.asarray([v is not None for v in values])
                if any(v is None for v in values) else None)
    if dtype is not None:
        arr = arr.astype(dtype.storage)
    elif not values:
        arr = arr.astype(np.int32)
    return Column.from_numpy(arr, dtype, validity)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Table:
    """An ordered collection of equal-length columns (cudf::table_view analog)."""

    columns: list[Column]

    def __post_init__(self):
        if self.columns:
            n = self.columns[0].num_rows
            for i, c in enumerate(self.columns):
                if c.num_rows != n:
                    raise ValueError(
                        f"column {i} has {c.num_rows} rows, expected {n}")

    def tree_flatten(self):
        return (self.columns,), None

    @classmethod
    def tree_unflatten(cls, _, children):
        obj = cls.__new__(cls)
        obj.columns = children[0]
        return obj

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    @property
    def num_rows(self) -> int:
        return self.columns[0].num_rows if self.columns else 0

    @property
    def schema(self) -> list[T.DType]:
        return [c.dtype for c in self.columns]

    def __getitem__(self, i: int) -> Column:
        return self.columns[i]

    def __iter__(self):
        return iter(self.columns)

    @staticmethod
    def from_pydict(data: dict, dtypes: dict | None = None) -> "Table":
        cols = []
        for name, values in data.items():
            dt = (dtypes or {}).get(name)
            if (dt is not None and dt.id == T.TypeId.STRING) or (
                    dt is None and values and isinstance(
                        next((v for v in values if v is not None), None), str)):
                cols.append(Column.strings_from_list(values))
            else:
                arr = np.asarray([0 if v is None else v for v in values])
                validity = (np.asarray([v is not None for v in values])
                            if any(v is None for v in values) else None)
                if dt is not None:
                    arr = arr.astype(dt.storage)
                cols.append(Column.from_numpy(arr, dt, validity))
        return Table(cols)
