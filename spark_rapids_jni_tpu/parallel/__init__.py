from .mesh import make_mesh  # noqa: F401
from .shuffle import bucketize_rows, all_to_all_shuffle  # noqa: F401
