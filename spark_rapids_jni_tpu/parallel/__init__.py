from .mesh import make_mesh  # noqa: F401
from .shuffle import bucketize_rows, all_to_all_shuffle  # noqa: F401
from .repartition_join import (JoinAggSpec, repartition_join_agg)  # noqa: F401
