"""Device-mesh helpers.

The reference's distribution story is host-mediated (SURVEY §2 parallelism
note): it emits shuffle-ready JCUDF blobs and lets Spark's external
UCX/NVLink RapidsShuffle move them.  Here the transport is first-class: a
`jax.sharding.Mesh` over ICI/DCN with XLA collectives (the BASELINE.json
north-star "RapidsShuffle over ICI").
"""

from __future__ import annotations

import jax
import numpy as np


def make_mesh(n_devices: int | None = None,
              axis_name: str = "data") -> jax.sharding.Mesh:
    """1-D mesh over the first ``n_devices`` devices (executor-pool analog)."""
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devs)} "
                "(tests use --xla_force_host_platform_device_count)")
        devs = devs[:n_devices]
    return jax.sharding.Mesh(np.array(devs), (axis_name,))


def make_2d_mesh(n_hosts: int, chips_per_host: int,
                 host_axis: str = "dcn", chip_axis: str = "ici"):
    """2-D mesh (hosts × chips): the multi-host topology, with the slow DCN
    axis outermost and ICI innermost (collectives should reduce over
    ``chip_axis`` first / most often — "How to Scale Your Model" recipe)."""
    devs = jax.devices()
    need = n_hosts * chips_per_host
    if len(devs) < need:
        raise ValueError(f"need {need} devices, have {len(devs)}")
    arr = np.array(devs[:need]).reshape(n_hosts, chips_per_host)
    return jax.sharding.Mesh(arr, (host_axis, chip_axis))
