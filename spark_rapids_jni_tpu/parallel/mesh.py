"""Device-mesh helpers.

The reference's distribution story is host-mediated (SURVEY §2 parallelism
note): it emits shuffle-ready JCUDF blobs and lets Spark's external
UCX/NVLink RapidsShuffle move them.  Here the transport is first-class: a
`jax.sharding.Mesh` over ICI/DCN with XLA collectives (the BASELINE.json
north-star "RapidsShuffle over ICI").
"""

from __future__ import annotations

import jax
import numpy as np


def local_devices(n_devices: int | None = None) -> list:
    """The first ``n_devices`` local device handles (all when None).

    The single source of device handles shared by the mesh builders here
    and the serving layer's replica placement (``exec/placement.py``) —
    both must agree on ordering so a replica index means the same chip
    everywhere.  Raises when the host has fewer devices than asked."""
    devs = list(jax.devices())
    if n_devices is None or n_devices <= 0:
        return devs
    if len(devs) < n_devices:
        raise ValueError(
            f"need {n_devices} devices, have {len(devs)} "
            "(tests use --xla_force_host_platform_device_count)")
    return devs[:n_devices]


def make_mesh(n_devices: int | None = None,
              axis_name: str = "data") -> jax.sharding.Mesh:
    """1-D mesh over the first ``n_devices`` devices (executor-pool analog)."""
    return jax.sharding.Mesh(np.array(local_devices(n_devices)),
                             (axis_name,))


def make_2d_mesh(n_hosts: int, chips_per_host: int,
                 host_axis: str = "dcn", chip_axis: str = "ici"):
    """2-D mesh (hosts × chips): the multi-host topology, with the slow DCN
    axis outermost and ICI innermost (collectives should reduce over
    ``chip_axis`` first / most often — "How to Scale Your Model" recipe)."""
    devs = jax.devices()
    need = n_hosts * chips_per_host
    if len(devs) < need:
        raise ValueError(f"need {need} devices, have {len(devs)}")
    arr = np.array(devs[:need]).reshape(n_hosts, chips_per_host)
    return jax.sharding.Mesh(arr, (host_axis, chip_axis))
