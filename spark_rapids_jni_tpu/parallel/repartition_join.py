"""Repartition (shuffled) hash equi-join over the device mesh.

Round 2 executed only the *star* shape — a replicated dimension probed by a
sharded fact (``dist_query.py``).  This module removes the replication
requirement: BOTH sides arrive sharded, and each is hash-partitioned on its
join key and exchanged with the JCUDF row shuffle so that all rows of a key
land on one chip, where a local static-shaped sort-merge probe joins them.
This is Spark's shuffled hash join for PK-FK equi-joins (the TPC-DS
store_sales ⋈ item shape) executed as ONE jitted SPMD program:

  per chip:  transcode to JCUDF u32 row words  (rowconv crown jewel)
          →  murmur3 key hash → bucketize      (shuffle.py)
          →  lax.all_to_all over ICI           (both sides)
          →  decode received rows → local probe → segment aggregate
  global:    one psum over the mesh axis

TPU-first design notes:
* all shapes static: fixed per-destination bucket capacity with drop
  accounting (callers size with headroom, same two-phase discipline as the
  reference's ≤2GB batches);
* the local join is a segment-run probe over the received build side — the
  TPU formulation of a hash probe (no pointer chasing).  Duplicate build
  keys are first-class (cudf ``inner_join`` semantics): equal-key build
  rows form a run; each fact row's value is aggregated once per run
  (searchsorted + segment-add), then distributed to every build row of the
  run — each (fact, build) pair contributes exactly once without ever
  materializing the expanded pairs;
* dense integer build keys (join engine v2, the TPC-DS surrogate-key case)
  skip the sort entirely: each shard scatter-adds fact values into a
  ``(span,)`` slot accumulator addressed by ``key - key_min`` and build
  rows gather their slot — the auto path detects this from the build key
  range (``JoinAggSpec.key_span``);
* capacities are sized automatically by a count pass
  (:func:`repartition_join_agg_auto`) — the same two-phase discipline as the
  reference's batch sizing (``row_conversion.cu:1460-1539``) — so bucket
  overflow is structurally impossible on the auto path.

Reference parity: the reference emits shuffle-ready blobs and hands them to
Spark's shuffle (SURVEY §5.8); here the shuffle AND the join execute on
device, the BASELINE.json north-star (NDS over ICI) in miniature.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

try:                                    # jax ≥ 0.5 top-level name
    _shard_map = jax.shard_map
except AttributeError:                  # 0.4.x keeps it in experimental
    from jax.experimental.shard_map import shard_map as _shard_map

from ..ops.hashing import murmur3_32, hash_partition
from ..rowconv.convert import (_to_rows_fixed_words, _from_rows_fixed_words)
from ..rowconv.layout import compute_row_layout
from .shuffle import (bucketize_rows, all_to_all_shuffle, received_mask,
                      replicated_partition_ids, salted_partition_ids)


class JoinAggSpec(NamedTuple):
    """Static description of a repartition join + aggregate.

    Column indices address the respective schema.  The probe (fact) side
    aggregates ``value_idx`` grouped by the build side's ``group_idx``
    (dense int32 codes in [0, num_groups) — callers dictionary-encode)."""
    fact_schema: tuple
    build_schema: tuple
    fact_key_idx: "int | tuple"
    build_key_idx: "int | tuple"
    build_group_idx: int
    fact_value_idx: int
    num_groups: int
    fact_capacity: int     # per-destination bucket rows, fact side
    build_capacity: int    # per-destination bucket rows, build side
    # dense-key direct lookup (join engine v2): when key_span > 0 the local
    # probe indexes a (span,) slot accumulator with key - key_min instead of
    # sorting + searchsorted.  0 (the default) keeps the sort-merge probe.
    key_min: int = 0
    key_span: int = 0
    # composite multi-column keys (join engine v2 key packing): when the
    # ``*_key_idx`` fields are equal-length tuples, each side's shuffle and
    # probe lane is the mixed-radix int64 pack of its key tuple over these
    # per-key build windows ``[key_mins[i], key_mins[i] + key_spans[i])``
    # — 0-based, so a dense composite runs with key_min = 0 and
    # key_span = prod(key_spans).  Rows with any null or out-of-window key
    # never match (tuple-null semantics, same as ops/join_plan.py).
    key_mins: tuple = ()
    key_spans: tuple = ()
    # AQE skew split (plan.aqe.skew_split): salt ``S`` must be a power of
    # two dividing the partition count P.  The partition space becomes
    # ``G = P // S`` key groups × S sub-partitions: fact rows of a key
    # round-robin over their group's S destinations while every build row
    # is replicated to all S of them, so each (fact, build) pair still
    # meets exactly once and the psum merge stays bit-identical to
    # salt == 1.  Build capacity is per-GROUP need (replicas are one row
    # per destination each).  1 (the default) is plain hash routing.
    salt: int = 1


def _composite_lane(datas, validm, idxs, mins, spans):
    """Mixed-radix int64 pack of a key tuple (last key fastest) plus the
    combined "all keys valid and in-window" mask — the shard-side twin of
    ``ops/join_plan.py``'s composite pack (identical lane values, so the
    shuffle routing and the local probe agree across chips)."""
    comp = ok = None
    stride = 1
    for i, kmin, span in zip(idxs[::-1], mins[::-1], spans[::-1]):
        d = datas[i].astype(jnp.int64) - kmin
        okk = validm[:, i] & (d >= 0) & (d < span)
        ok = okk if ok is None else (ok & okk)
        t = jnp.clip(d, 0, span - 1) * stride
        comp = t if comp is None else comp + t
        stride *= span
    return comp, ok


def _key_lane(spec: JoinAggSpec, key_idx, datas, validm, mask):
    """(probe lane, live mask) for one side's received rows: the raw key
    column for single keys, the composite pack for tuple keys."""
    if isinstance(key_idx, tuple):
        lane, ok = _composite_lane(datas, validm, key_idx,
                                   spec.key_mins, spec.key_spans)
        return lane, mask & ok
    return datas[key_idx], mask & validm[:, key_idx]


def _shuffle_side(layout, datas, valid, part, axis_name, capacity, P):
    """Local columns → JCUDF words → bucketize by precomputed partition
    ids → all-to-all → decode.

    Returns (datas, validity matrix, live-row mask, dropped count) for the
    rows this chip RECEIVED."""
    W = layout.fixed_row_size // 4
    rows = _to_rows_fixed_words(layout, datas, valid).reshape(-1, W)
    buckets = bucketize_rows(rows, part, P, capacity)
    recv = all_to_all_shuffle(buckets, axis_name)
    mask = received_mask(recv).reshape(-1)
    rdatas, rvalid = _from_rows_fixed_words(layout, recv.rows.reshape(-1))
    return rdatas, rvalid, mask, recv.dropped


def _local_join_agg(spec: JoinAggSpec, axis_name, num_partitions,
                    fact_datas, fact_valid, build_datas, build_valid):
    lf = compute_row_layout(list(spec.fact_schema))
    lb = compute_row_layout(list(spec.build_schema))

    # shuffle routing hashes the same lane the local probe uses — for
    # composite keys both sides pack with the SAME static windows, so all
    # rows of a tuple land on one chip (one SUB-partition of its group
    # when salted — matching build replicas follow)
    fshuf, _ = _key_lane(spec, spec.fact_key_idx, fact_datas, fact_valid,
                         jnp.bool_(True))
    if spec.salt > 1:
        # skew split: replicate the build shard S× (replica-major) so each
        # sub-partition of a key group holds a full copy of the group's
        # build rows; fact rows round-robin over the S sub-partitions
        S = spec.salt
        build_datas = tuple(jnp.tile(d, S) for d in build_datas)
        build_valid = jnp.tile(build_valid, (S, 1))
    bshuf, _ = _key_lane(spec, spec.build_key_idx, build_datas, build_valid,
                         jnp.bool_(True))
    fpart = salted_partition_ids(fshuf, num_partitions, spec.salt)
    bpart = replicated_partition_ids(bshuf, num_partitions, spec.salt)
    fdatas, fvalidm, fmask, fdrop = _shuffle_side(
        lf, fact_datas, fact_valid, fpart,
        axis_name, spec.fact_capacity, num_partitions)
    bdatas, bvalidm, bmask, bdrop = _shuffle_side(
        lb, build_datas, build_valid, bpart,
        axis_name, spec.build_capacity, num_partitions)

    fkey, flive = _key_lane(spec, spec.fact_key_idx, fdatas, fvalidm, fmask)
    bkey, blive = _key_lane(spec, spec.build_key_idx, bdatas, bvalidm, bmask)

    if spec.key_span > 0:
        # dense-key fast path (the ops/join_plan.py heuristic applied per
        # shard): slot = key - key_min addresses a (span,) accumulator
        # directly — no build sort, no searchsorted.  The shuffle already
        # guarantees all rows of a key share a chip, so a slot read by a
        # live build row holds exactly the fact rows with that key.  JAX
        # wraps NEGATIVE scatter indices even under mode="drop" (only
        # OOB-high drops), so bad rows are where()-routed to slot span.
        span = spec.key_span
        fd = fkey.astype(jnp.int64) - spec.key_min
        f_ok = flive & (fd >= 0) & (fd < span)
        fslot = jnp.where(f_ok, fd, jnp.int64(span))
        val = fdatas[spec.fact_value_idx].astype(jnp.int64)
        fval_ok = fvalidm[:, spec.fact_value_idx]
        slot_sums = jnp.zeros(span + 1, jnp.int64).at[fslot].add(
            jnp.where(f_ok & fval_ok, val, 0), mode="drop")[:span]
        slot_cnts = jnp.zeros(span + 1, jnp.int32).at[fslot].add(
            f_ok.astype(jnp.int32), mode="drop")[:span]

        bd = bkey.astype(jnp.int64) - spec.key_min
        b_ok = blive & (bd >= 0) & (bd < span)
        bslot = jnp.clip(bd, 0, span - 1)
        g = jnp.where(b_ok, bdatas[spec.build_group_idx].astype(jnp.int32),
                      jnp.int32(spec.num_groups))
        sums = jnp.zeros(spec.num_groups, jnp.int64).at[g].add(
            jnp.where(b_ok, slot_sums[bslot], 0), mode="drop")
        cnts = jnp.zeros(spec.num_groups, jnp.int32).at[g].add(
            jnp.where(b_ok, slot_cnts[bslot], 0), mode="drop")
        return (jax.lax.psum(sums, axis_name),
                jax.lax.psum(cnts, axis_name),
                jax.lax.psum(fdrop + bdrop, axis_name))

    # build side: dead/null-key slots get a max sentinel AND sort strictly
    # after any live row with the same value (secondary dead-flag lane), so
    # the leftmost-equal searchsorted position always lands on a LIVE row
    # when one exists — a legitimate key equal to the dtype max still joins
    # (composite lanes are < prod(key_spans) < 2^63, so the sentinel can
    # never collide with a live packed tuple)
    sent = jnp.asarray(np.iinfo(np.dtype(bkey.dtype)).max, bkey.dtype)
    bkey = jnp.where(blive, bkey, sent)
    dead = (~blive).astype(jnp.int32)
    order = jnp.lexsort((dead, bkey))     # primary bkey, live before dead
    bkey_s = bkey[order]
    blive_s = blive[order]
    bgroup_s = bdatas[spec.build_group_idx][order]
    nb = bkey_s.shape[0]

    # equal-key runs over the sorted build side (duplicate keys are
    # first-class: every build row of a fact row's run matches it)
    head = jnp.concatenate([jnp.ones(1, jnp.int32),
                            (bkey_s[1:] != bkey_s[:-1]).astype(jnp.int32)])
    run_id = jnp.cumsum(head) - 1                       # int32 [nb]

    pos = jnp.clip(jnp.searchsorted(bkey_s, fkey), 0, max(nb - 1, 0))
    hit = flive & (bkey_s[pos] == fkey) & blive_s[pos]

    # phase 1: aggregate fact rows once per RUN (not per build row) —
    # sentinel run nb absorbs misses via mode="drop"
    rf = jnp.where(hit, run_id[pos], jnp.int32(nb))
    val = fdatas[spec.fact_value_idx].astype(jnp.int64)
    fval_ok = fvalidm[:, spec.fact_value_idx]
    run_sums = jnp.zeros(nb, jnp.int64).at[rf].add(
        jnp.where(hit & fval_ok, val, 0), mode="drop")
    run_cnts = jnp.zeros(nb, jnp.int32).at[rf].add(
        hit.astype(jnp.int32), mode="drop")

    # phase 2: distribute each run's fact aggregate to every live build row
    # of the run — exactly one contribution per (fact, build) pair
    g = jnp.where(blive_s, bgroup_s.astype(jnp.int32),
                  jnp.int32(spec.num_groups))
    sums = jnp.zeros(spec.num_groups, jnp.int64).at[g].add(
        jnp.where(blive_s, run_sums[run_id], 0), mode="drop")
    cnts = jnp.zeros(spec.num_groups, jnp.int32).at[g].add(
        jnp.where(blive_s, run_cnts[run_id], 0), mode="drop")
    return (jax.lax.psum(sums, axis_name), jax.lax.psum(cnts, axis_name),
            jax.lax.psum(fdrop + bdrop, axis_name))


@lru_cache(maxsize=64)
def _compiled_join_agg(mesh, spec: JoinAggSpec, axis_name):
    """jitted SPMD program cached on (mesh, spec, axis)."""
    P = jax.sharding.PartitionSpec
    nf, nb = len(spec.fact_schema), len(spec.build_schema)
    axes = axis_name if isinstance(axis_name, tuple) else (axis_name,)
    num_partitions = int(np.prod([mesh.shape[a] for a in axes]))
    fn = _shard_map(
        partial(_local_join_agg, spec, axis_name, num_partitions),
        mesh=mesh,
        in_specs=(tuple(P(axis_name) for _ in range(nf)), P(axis_name),
                  tuple(P(axis_name) for _ in range(nb)), P(axis_name)),
        out_specs=(P(), P(), P()))
    return jax.jit(fn)


def repartition_join_agg(mesh: jax.sharding.Mesh, spec: JoinAggSpec,
                         fact_datas: Sequence[jnp.ndarray],
                         fact_valid: jnp.ndarray,
                         build_datas: Sequence[jnp.ndarray],
                         build_valid: jnp.ndarray,
                         axis_name: str = "data"):
    """SELECT g, SUM(fact.value), COUNT(*) FROM fact JOIN build USING (key)
    GROUP BY build.group — both sides sharded, repartitioned over ICI.
    Duplicate build keys join every matching fact row (cudf ``inner_join``
    semantics).

    ``*_datas`` are global column arrays (row counts divisible by the mesh
    size), ``*_valid`` the [n, ncols] validity matrices.  Returns
    replicated (sums int64 [num_groups], counts int32 [num_groups],
    dropped int32).  With explicit capacities ``dropped > 0`` reports
    overflow; use :func:`repartition_join_agg_auto` to size capacities by a
    count pass so overflow cannot happen.
    """
    fn = _compiled_join_agg(mesh, spec, axis_name)
    return fn(tuple(fact_datas), fact_valid, tuple(build_datas), build_valid)


def _local_bucket_need(axis_name, num_partitions, salt, fact_key, build_key):
    """Per-chip count pass: the largest per-destination bucket each side
    needs anywhere on the mesh (replicated scalars).

    With ``salt > 1`` the fact side counts against its salted destinations
    and the build side against its ``G = P // S`` key groups — replica
    ``j`` of group ``g`` sends the group's full row count to destination
    ``g·S + j``, so per-group need IS per-destination need."""
    fpart = salted_partition_ids(fact_key, num_partitions, salt)
    fcounts = jnp.zeros(num_partitions, jnp.int32).at[fpart].add(
        1, mode="drop")
    need_f = jax.lax.pmax(jnp.max(fcounts), axis_name)
    groups = num_partitions // salt if salt > 1 else num_partitions
    bpart = hash_partition(murmur3_32(build_key), groups)
    bcounts = jnp.zeros(groups, jnp.int32).at[bpart].add(1, mode="drop")
    need_b = jax.lax.pmax(jnp.max(bcounts), axis_name)
    return need_f, need_b


@lru_cache(maxsize=16)
def _compiled_bucket_need(mesh, axis_name, salt=1):
    P = jax.sharding.PartitionSpec
    axes = axis_name if isinstance(axis_name, tuple) else (axis_name,)
    num_partitions = int(np.prod([mesh.shape[a] for a in axes]))
    fn = _shard_map(
        partial(_local_bucket_need, axis_name, num_partitions, salt),
        mesh=mesh, in_specs=(P(axis_name), P(axis_name)),
        out_specs=(P(), P()))
    return jax.jit(fn)


def _local_bucket_need_multi(axis_name, num_partitions, salts,
                             fact_key, build_key):
    """One-pass count sweep over every candidate salt: the murmur hash is
    computed once per side and each salt's destinations are one extra
    scatter — so the AQE path picks its salt from a SINGLE sync instead
    of measure → decide → re-measure."""
    fh = murmur3_32(fact_key)
    bh = murmur3_32(build_key)
    n = fact_key.shape[0]
    sub = jnp.arange(n, dtype=jnp.int32)
    needs_f, needs_b = [], []
    for S in salts:
        groups = num_partitions // S
        fpart = (hash_partition(fh, groups) * S + sub % jnp.int32(S)
                 if S > 1 else hash_partition(fh, num_partitions))
        fcounts = jnp.zeros(num_partitions, jnp.int32).at[fpart].add(
            1, mode="drop")
        needs_f.append(jax.lax.pmax(jnp.max(fcounts), axis_name))
        bcounts = jnp.zeros(groups, jnp.int32).at[
            hash_partition(bh, groups)].add(1, mode="drop")
        needs_b.append(jax.lax.pmax(jnp.max(bcounts), axis_name))
    return jnp.stack(needs_f), jnp.stack(needs_b)


@lru_cache(maxsize=16)
def _compiled_bucket_need_multi(mesh, axis_name, salts):
    P = jax.sharding.PartitionSpec
    axes = axis_name if isinstance(axis_name, tuple) else (axis_name,)
    num_partitions = int(np.prod([mesh.shape[a] for a in axes]))
    fn = _shard_map(
        partial(_local_bucket_need_multi, axis_name, num_partitions, salts),
        mesh=mesh, in_specs=(P(axis_name), P(axis_name)),
        out_specs=(P(), P()))
    return jax.jit(fn)


def _bucket_capacity(need: int) -> int:
    """Round a measured bucket need up to a shared compile-key bucket
    (≤ ~12.5% growth), multiple of 8."""
    need = max(int(need), 8)
    p = 8
    while p < need:
        p <<= 1
    step = max(8, p // 8)
    return -(-need // step) * step


def repartition_join_agg_auto(mesh: jax.sharding.Mesh,
                              fact_schema, build_schema,
                              fact_key_idx, build_key_idx,
                              build_group_idx: int, fact_value_idx: int,
                              num_groups: int,
                              fact_datas: Sequence[jnp.ndarray],
                              fact_valid: jnp.ndarray,
                              build_datas: Sequence[jnp.ndarray],
                              build_valid: jnp.ndarray,
                              axis_name: str = "data",
                              salt: "int | None" = None):
    """:func:`repartition_join_agg` with automatic two-phase capacity
    sizing: a count pass measures the true per-destination bucket maxima
    (one tiny sync), capacities are bucketed for compile-cache reuse, and
    the sized program runs with overflow structurally impossible.

    ``fact_key_idx``/``build_key_idx`` take one column index or
    equal-length index lists: multi-column keys are planned like
    ``ops/join_plan.py`` — per-key build windows measured once, the tuple
    packed into one int64 composite lane that both the shuffle routing and
    the local probe share.  Composite windows must fit 63 bits (the shard
    path carries no fingerprint fallback; overflow raises).

    The count pass also inspects the build key range and, when it is dense
    (``ops/join_plan.py`` heuristic: span ≤ max(2·n, 4096), capped), sets
    ``key_min``/``key_span`` so every shard probes by direct lookup.
    ``key_min`` is floored and the span bucketed so nearby datasets share a
    compile-cache entry.

    ``salt`` forces a skew-split factor (power of two dividing the
    partition count; see :class:`JoinAggSpec`).  The default ``None``
    auto-detects: with ``SRJT_AQE`` on, a measured hot-bucket need ≥
    ``SRJT_AQE_SKEW_FACTOR`` × the uniform expectation triggers a salted
    sub-join (``plan.aqe.skew_split.fired``) — bit-identical results,
    hot-side capacity (and padded probe work) cut ~salt×."""
    from ..ops import join_plan
    from ..utils import knobs, metrics

    fki = tuple(fact_key_idx) \
        if isinstance(fact_key_idx, (list, tuple)) else fact_key_idx
    bki = tuple(build_key_idx) \
        if isinstance(build_key_idx, (list, tuple)) else build_key_idx
    if isinstance(fki, tuple) != isinstance(bki, tuple) or (
            isinstance(fki, tuple) and len(fki) != len(bki)):
        raise ValueError("fact/build key index lists must match in length")
    if isinstance(fki, tuple) and len(fki) == 1:
        fki, bki = fki[0], bki[0]
    multi = isinstance(fki, tuple)
    key_min = key_span = 0
    key_mins = key_spans = ()
    if multi:
        # per-key build windows, floored/bucketed for compile-cache reuse
        exprs = []
        for i in bki:
            bk = build_datas[i]
            bdt = np.dtype(bk.dtype)
            if bdt.kind not in "iu" or (bdt.kind == "u"
                                        and bdt.itemsize == 8):
                raise ValueError(
                    "composite repartition keys must be int-kind below 64 "
                    "unsigned bits; pre-encode strings/decimals to codes")
            bv = build_valid[:, i]
            info = np.iinfo(bdt)
            exprs += [
                jnp.min(jnp.where(bv, bk, info.max)).astype(jnp.int64),
                jnp.max(jnp.where(bv, bk, info.min)).astype(jnp.int64)]
        allv = None
        for i in bki:
            bv = build_valid[:, i]
            allv = bv if allv is None else (allv & bv)
        exprs.append(jnp.sum(allv).astype(jnp.int64))
        vals = [int(v) for v in np.asarray(jnp.stack(exprs))]  # ONE sync
        nvalid = vals[-1]
        mins, spans, prod = [], [], 1
        for j in range(len(bki)):
            kmin, kmax = vals[2 * j], vals[2 * j + 1]
            if kmax < kmin:            # this key column is all-null
                kmin, span = 0, 1
            else:
                kmin = (kmin // 64) * 64
                span = _bucket_capacity(kmax - kmin + 1)
            mins.append(kmin)
            spans.append(span)
            prod *= span
        if prod >= 1 << 63:
            raise ValueError(
                "composite key windows overflow 63 bits — the distributed "
                "shard path has no fingerprint fallback; narrow the key "
                "ranges or join through ops.join locally")
        key_mins, key_spans = tuple(mins), tuple(spans)
        if nvalid > 0 and prod <= min(
                max(join_plan.DENSE_SPAN_FACTOR * nvalid,
                    join_plan.DENSE_SPAN_FLOOR), join_plan.DENSE_SPAN_CAP):
            key_span = prod            # composite lane is already 0-based
        fact_key_arr, _ = _composite_lane(fact_datas, fact_valid, fki,
                                          key_mins, key_spans)
        build_key_arr, _ = _composite_lane(build_datas, build_valid, bki,
                                           key_mins, key_spans)
    else:
        fact_key_arr = fact_datas[fki]
        build_key_arr = build_datas[bki]
    axes = axis_name if isinstance(axis_name, tuple) else (axis_name,)
    P = int(np.prod([mesh.shape[a] for a in axes]))
    S = 1 if salt is None else max(int(salt), 1)
    if S > 1 and ((S & (S - 1)) or P % S):
        raise ValueError("salt must be a power of two dividing the "
                         "partition count")
    if salt is None and P > 1 and knobs.get("SRJT_AQE"):
        # AQE skew split: a hot key melts one destination bucket; when the
        # measured need beats the uniform expectation by
        # SRJT_AQE_SKEW_FACTOR, re-route through salted sub-partitions —
        # the hot side's capacity (and padded probe work) drops ~S×.  The
        # multi-salt count sweep measures every candidate in ONE sync, so
        # choosing a salt costs no extra round trip.
        cand = [1]
        while cand[-1] * 2 <= P and P % (cand[-1] * 2) == 0:
            cand.append(cand[-1] * 2)
        need_fn = _compiled_bucket_need_multi(mesh, axis_name, tuple(cand))
        nf, nb = need_fn(fact_key_arr, build_key_arr)
        needs_all = np.asarray(jnp.stack([nf, nb]))  # ONE sync, [2, k]
        n_local = max(fact_datas[0].shape[0] // P, 1)
        uniform = max(n_local / P, 1.0)
        ratio = float(needs_all[0, 0]) / uniform
        pick = 0
        if ratio >= float(knobs.get("SRJT_AQE_SKEW_FACTOR")):
            # hot-destination need falls as hot_mass/S, so salt up to the
            # point the uniform tail would dominate (≈ 2·ratio): the
            # measured multi-salt needs size the buckets either way
            while pick + 1 < len(cand) and cand[pick + 1] <= 2 * ratio:
                pick += 1
        S = cand[pick]
        needs = needs_all[:, pick]
        if S > 1 and metrics.recording():
            metrics.count("plan.aqe.skew_split.fired")
            metrics.gauge_max("shuffle.salt", S)
            metrics.annotate(skew_salt=S, skew_ratio=round(ratio, 2))
    else:
        need_fn = _compiled_bucket_need(mesh, axis_name, S)
        nf, nb = need_fn(fact_key_arr, build_key_arr)
        needs = np.asarray(jnp.stack([nf, nb]))  # ONE host sync, two scalars
    if not multi:
        bk = build_datas[bki]
        bdt = np.dtype(bk.dtype)
        if bdt.kind == "i" or (bdt.kind == "u" and bdt.itemsize < 8):
            bv = build_valid[:, bki]
            info = np.iinfo(bdt)
            stats = np.asarray(jnp.stack([      # one more sync, 3 scalars
                jnp.sum(bv).astype(jnp.int64),
                jnp.min(jnp.where(bv, bk, info.max)).astype(jnp.int64),
                jnp.max(jnp.where(bv, bk, info.min)).astype(jnp.int64)]))
            nvalid, kmin, kmax = (int(s) for s in stats)
            if nvalid > 0:
                limit = min(max(join_plan.DENSE_SPAN_FACTOR * nvalid,
                                join_plan.DENSE_SPAN_FLOOR),
                            join_plan.DENSE_SPAN_CAP)
                if kmax - kmin + 1 <= limit:
                    key_min = (kmin // 4096) * 4096
                    key_span = _bucket_capacity(kmax - key_min + 1)
    spec = JoinAggSpec(
        fact_schema=tuple(fact_schema), build_schema=tuple(build_schema),
        fact_key_idx=fki, build_key_idx=bki,
        build_group_idx=build_group_idx, fact_value_idx=fact_value_idx,
        num_groups=num_groups,
        fact_capacity=_bucket_capacity(needs[0]),
        build_capacity=_bucket_capacity(needs[1]),
        key_min=key_min, key_span=key_span,
        key_mins=key_mins, key_spans=key_spans, salt=S)
    if metrics.recording():
        # mesh-wide padded probe slots — the wasted-work proxy the AQE
        # bench compares static vs salted runs on
        metrics.count("shuffle.padded_slots.fact", P * P * spec.fact_capacity)
        metrics.count("shuffle.padded_slots.build",
                      P * P * spec.build_capacity)
    # arena admission for the exchange's padded bucket buffers (both
    # sides), sized from the measured capacities before dispatch
    from .shuffle import bucket_reservation
    row_bytes = [sum(np.dtype(a.dtype).itemsize for a in datas) + len(datas)
                 for datas in (fact_datas, build_datas)]
    with bucket_reservation(P, spec.fact_capacity, row_bytes[0],
                            tag="shuffle.fact"), \
         bucket_reservation(P, spec.build_capacity, row_bytes[1],
                            tag="shuffle.build"):
        return repartition_join_agg(mesh, spec, fact_datas, fact_valid,
                                    build_datas, build_valid, axis_name)
