"""Repartition (shuffled) hash equi-join over the device mesh.

Round 2 executed only the *star* shape — a replicated dimension probed by a
sharded fact (``dist_query.py``).  This module removes the replication
requirement: BOTH sides arrive sharded, and each is hash-partitioned on its
join key and exchanged with the JCUDF row shuffle so that all rows of a key
land on one chip, where a local static-shaped sort-merge probe joins them.
This is Spark's shuffled hash join for PK-FK equi-joins (the TPC-DS
store_sales ⋈ item shape) executed as ONE jitted SPMD program:

  per chip:  transcode to JCUDF u32 row words  (rowconv crown jewel)
          →  murmur3 key hash → bucketize      (shuffle.py)
          →  lax.all_to_all over ICI           (both sides)
          →  decode received rows → local probe → segment aggregate
  global:    one psum over the mesh axis

TPU-first design notes:
* all shapes static: fixed per-destination bucket capacity with drop
  accounting (callers size with headroom, same two-phase discipline as the
  reference's ≤2GB batches);
* the local join is searchsorted over the received build side — the TPU
  formulation of a hash probe (no pointer chasing);
* build keys must be globally unique (PK side).  Hash partitioning
  co-locates every copy of a key, so the probe resolves each fact row to
  at most one build row — exactly cudf's `inner_join` contract for the
  plugin's PK-FK joins.

Reference parity: the reference emits shuffle-ready blobs and hands them to
Spark's shuffle (SURVEY §5.8); here the shuffle AND the join execute on
device, the BASELINE.json north-star (NDS over ICI) in miniature.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.hashing import murmur3_32, hash_partition
from ..rowconv.convert import (_to_rows_fixed_words, _from_rows_fixed_words)
from ..rowconv.layout import compute_row_layout
from .shuffle import bucketize_rows, all_to_all_shuffle, received_mask


class JoinAggSpec(NamedTuple):
    """Static description of a repartition join + aggregate.

    Column indices address the respective schema.  The probe (fact) side
    aggregates ``value_idx`` grouped by the build side's ``group_idx``
    (dense int32 codes in [0, num_groups) — callers dictionary-encode)."""
    fact_schema: tuple
    build_schema: tuple
    fact_key_idx: int
    build_key_idx: int
    build_group_idx: int
    fact_value_idx: int
    num_groups: int
    fact_capacity: int     # per-destination bucket rows, fact side
    build_capacity: int    # per-destination bucket rows, build side


def _shuffle_side(layout, datas, valid, key, axis_name, capacity, P):
    """Local columns → JCUDF words → hash-bucketize → all-to-all → decode.

    Returns (datas, validity matrix, live-row mask, dropped count) for the
    rows this chip RECEIVED."""
    W = layout.fixed_row_size // 4
    rows = _to_rows_fixed_words(layout, datas, valid).reshape(-1, W)
    part = hash_partition(murmur3_32(key), P)
    buckets = bucketize_rows(rows, part, P, capacity)
    recv = all_to_all_shuffle(buckets, axis_name)
    mask = received_mask(recv).reshape(-1)
    rdatas, rvalid = _from_rows_fixed_words(layout, recv.rows.reshape(-1))
    return rdatas, rvalid, mask, recv.dropped


def _local_join_agg(spec: JoinAggSpec, axis_name, num_partitions,
                    fact_datas, fact_valid, build_datas, build_valid):
    lf = compute_row_layout(list(spec.fact_schema))
    lb = compute_row_layout(list(spec.build_schema))

    fdatas, fvalidm, fmask, fdrop = _shuffle_side(
        lf, fact_datas, fact_valid, fact_datas[spec.fact_key_idx],
        axis_name, spec.fact_capacity, num_partitions)
    bdatas, bvalidm, bmask, bdrop = _shuffle_side(
        lb, build_datas, build_valid, build_datas[spec.build_key_idx],
        axis_name, spec.build_capacity, num_partitions)

    # build side: dead/null-key slots get a max sentinel AND sort strictly
    # after any live row with the same value (secondary dead-flag lane), so
    # the leftmost-equal searchsorted position always lands on a LIVE row
    # when one exists — a legitimate key equal to the dtype max still joins
    bkey = bdatas[spec.build_key_idx]
    sent = jnp.asarray(np.iinfo(np.dtype(bkey.dtype)).max, bkey.dtype)
    blive = bmask & bvalidm[:, spec.build_key_idx]
    bkey = jnp.where(blive, bkey, sent)
    dead = (~blive).astype(jnp.int32)
    order = jnp.lexsort((dead, bkey))     # primary bkey, live before dead
    bkey_s = bkey[order]
    blive_s = blive[order]
    bgroup_s = bdatas[spec.build_group_idx][order]

    fkey = fdatas[spec.fact_key_idx]
    flive = fmask & fvalidm[:, spec.fact_key_idx]
    pos = jnp.clip(jnp.searchsorted(bkey_s, fkey), 0, bkey_s.shape[0] - 1)
    hit = flive & (bkey_s[pos] == fkey) & blive_s[pos]

    # sentinel group absorbs misses via mode="drop"
    g = jnp.where(hit, bgroup_s[pos].astype(jnp.int32),
                  jnp.int32(spec.num_groups))
    val = fdatas[spec.fact_value_idx].astype(jnp.int64)
    fval_ok = fvalidm[:, spec.fact_value_idx]
    sums = jnp.zeros(spec.num_groups, jnp.int64).at[g].add(
        jnp.where(hit & fval_ok, val, 0), mode="drop")
    cnts = jnp.zeros(spec.num_groups, jnp.int32).at[g].add(
        hit.astype(jnp.int32), mode="drop")
    return (jax.lax.psum(sums, axis_name), jax.lax.psum(cnts, axis_name),
            jax.lax.psum(fdrop + bdrop, axis_name))


@lru_cache(maxsize=64)
def _compiled_join_agg(mesh, spec: JoinAggSpec, axis_name):
    """jitted SPMD program cached on (mesh, spec, axis)."""
    P = jax.sharding.PartitionSpec
    nf, nb = len(spec.fact_schema), len(spec.build_schema)
    axes = axis_name if isinstance(axis_name, tuple) else (axis_name,)
    num_partitions = int(np.prod([mesh.shape[a] for a in axes]))
    fn = jax.shard_map(
        partial(_local_join_agg, spec, axis_name, num_partitions),
        mesh=mesh,
        in_specs=(tuple(P(axis_name) for _ in range(nf)), P(axis_name),
                  tuple(P(axis_name) for _ in range(nb)), P(axis_name)),
        out_specs=(P(), P(), P()))
    return jax.jit(fn)


def repartition_join_agg(mesh: jax.sharding.Mesh, spec: JoinAggSpec,
                         fact_datas: Sequence[jnp.ndarray],
                         fact_valid: jnp.ndarray,
                         build_datas: Sequence[jnp.ndarray],
                         build_valid: jnp.ndarray,
                         axis_name: str = "data"):
    """SELECT g, SUM(fact.value), COUNT(*) FROM fact JOIN build USING (key)
    GROUP BY build.group — both sides sharded, repartitioned over ICI.

    ``*_datas`` are global column arrays (row counts divisible by the mesh
    size), ``*_valid`` the [n, ncols] validity matrices.  Returns
    replicated (sums int64 [num_groups], counts int32 [num_groups],
    dropped int32) — ``dropped > 0`` means a bucket capacity overflowed and
    the caller must retry with more headroom (two-phase sizing, like the
    reference's batch-size pass).
    """
    fn = _compiled_join_agg(mesh, spec, axis_name)
    return fn(tuple(fact_datas), fact_valid, tuple(build_datas), build_valid)
