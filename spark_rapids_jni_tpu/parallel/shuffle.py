"""Hash shuffle of JCUDF row blobs over the device mesh (ICI all-to-all).

TPU-native replacement for the external RapidsShuffle UCX/NVLink path the
reference feeds (SURVEY §5.8): rows are partitioned by key hash, bucketized
into fixed-capacity per-destination buckets (XLA needs static shapes — the
dynamic part is carried as per-bucket counts), and exchanged with
``lax.all_to_all`` inside ``shard_map`` so XLA rides ICI.

Capacity discipline: like the reference's ≤2GB row batches
(``row_conversion.cu:97-103``), senders bound their per-destination payload;
rows beyond ``capacity`` are counted in ``dropped`` (callers size capacity
with headroom and treat dropped > 0 as an error/retry-with-larger-capacity —
a size pass, same two-phase discipline as the string path).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import metrics


class Buckets(NamedTuple):
    rows: jnp.ndarray      # [P, capacity, row_size]
    counts: jnp.ndarray    # int32 [P] — valid rows per bucket (≤ capacity)
    dropped: jnp.ndarray   # int32 [] — rows that exceeded capacity


def bucketize_rows(rows: jnp.ndarray, part_id: jnp.ndarray,
                   num_partitions: int, capacity: int) -> Buckets:
    """Group local rows by destination partition into padded buckets.

    rows: [n, row_size] (any dtype); part_id: int32 [n] in [0, P).
    Pure static-shape formulation: stable-sort by partition, compute each
    row's rank within its partition, scatter with out-of-range drop.
    """
    n, row_size = rows.shape
    if metrics.recording():
        # static-shape accounting only: this body usually runs under
        # shard_map/jit tracing, so counts here are once-per-trace (the
        # per-execution story is record_shuffle_stats, called eagerly on
        # the exchanged result)
        metrics.count("shuffle.bucketize.calls")
        metrics.count("shuffle.bucketize.payload_bytes",
                      n * row_size * rows.dtype.itemsize)
    # out-of-range destinations (partitioner bugs) are routed to a sentinel
    # partition P and counted in `dropped` — without this, a negative id
    # would wrap via negative indexing into partition P-1
    in_range = (part_id >= 0) & (part_id < num_partitions)
    part_id = jnp.where(in_range, part_id, num_partitions).astype(jnp.int32)

    order = jnp.argsort(part_id, stable=True)
    sorted_rows = rows[order]
    sorted_part = part_id[order]
    counts = jnp.zeros(num_partitions, dtype=jnp.int32).at[part_id].add(
        1, mode="drop")  # sentinel P drops out rather than clipping to P-1
    starts = jnp.cumsum(counts) - counts
    rank = jnp.arange(n, dtype=jnp.int32) - starts.at[sorted_part].get(
        mode="fill", fill_value=0)

    buckets = jnp.zeros((num_partitions, capacity, row_size), dtype=rows.dtype)
    # sentinel partition and ranks ≥ capacity fall outside the scatter
    # domain and are dropped
    buckets = buckets.at[sorted_part, rank].set(sorted_rows, mode="drop")
    clipped = jnp.minimum(counts, capacity)
    dropped = np.int32(n) - clipped.sum()
    return Buckets(buckets, clipped, dropped)


def salted_partition_ids(key: jnp.ndarray, num_partitions: int,
                         salt: int) -> jnp.ndarray:
    """Probe (fact) side destination under salt-``S`` sub-partitioning.

    The partition space splits into ``G = P // S`` key groups × ``S``
    sub-partitions: a key hashes to group ``g`` and each of its rows
    round-robins (by local row index) over the group's ``S`` consecutive
    destinations ``g·S + j``.  With ``salt == 1`` this is exactly the
    plain ``hash_partition`` routing.  A skewed hot key thus spreads over
    ``S`` chips instead of melting one — the AQE skew-split primitive
    (``plan.aqe.skew_split``)."""
    from ..ops.hashing import hash_partition, murmur3_32
    if salt <= 1:
        return hash_partition(murmur3_32(key), num_partitions)
    groups = num_partitions // salt
    g = hash_partition(murmur3_32(key), groups)
    n = key.shape[0]
    sub = jnp.arange(n, dtype=jnp.int32) % jnp.int32(salt)
    return (g.astype(jnp.int32) * salt + sub).astype(jnp.int32)


def replicated_partition_ids(key_tiled: jnp.ndarray, num_partitions: int,
                             salt: int) -> jnp.ndarray:
    """Build side twin of :func:`salted_partition_ids`: ``key_tiled`` is
    the build key lane tiled ``S``× (replica-major — ``jnp.tile(key, S)``)
    and replica ``j`` of a key in group ``g`` routes to destination
    ``g·S + j``.  Every fact row of the key meets exactly ONE replica of
    each matching build row (the one in its own sub-partition), so the
    psum-merged aggregate counts each (fact, build) pair exactly once —
    salting is bit-identical to the unsalted join."""
    from ..ops.hashing import hash_partition, murmur3_32
    if salt <= 1:
        return hash_partition(murmur3_32(key_tiled), num_partitions)
    groups = num_partitions // salt
    n = key_tiled.shape[0] // salt
    g = hash_partition(murmur3_32(key_tiled), groups)
    replica = (jnp.arange(salt * n, dtype=jnp.int32) // jnp.int32(max(n, 1)))
    return (g.astype(jnp.int32) * salt + replica).astype(jnp.int32)


def bucket_reservation(num_partitions: int, capacity: int,
                       row_nbytes: int, sides: int = 1, tag: str = "shuffle"):
    """HBM-arena admission context for a sized exchange's padded bucket
    buffers: every shard materializes a ``[P, capacity, row_size]`` send
    buffer and receives its transpose, so the mesh-wide footprint is
    ``P² · capacity · row_bytes`` per side.  Call around the sized
    dispatch (eager code — never inside shard_map); no-op when the arena
    is off."""
    from ..memory import arena
    nbytes = (int(num_partitions) ** 2 * int(capacity) * int(row_nbytes)
              * int(sides))
    return arena.reserve(nbytes, tag=tag)


def all_to_all_shuffle(buckets: Buckets, axis_name: str) -> Buckets:
    """Exchange buckets across the mesh axis (must run inside shard_map).

    After the exchange, ``rows[p]`` holds the rows device ``p`` addressed to
    this device, with ``counts[p]`` of them valid.
    """
    rows = jax.lax.all_to_all(buckets.rows, axis_name, split_axis=0,
                              concat_axis=0, tiled=True)
    counts = jax.lax.all_to_all(buckets.counts, axis_name, split_axis=0,
                                concat_axis=0, tiled=True)
    return Buckets(rows, counts, buckets.dropped)


def received_mask(buckets: Buckets) -> jnp.ndarray:
    """bool [P, capacity]: which received slots hold real rows."""
    capacity = buckets.rows.shape[1]
    return (jnp.arange(capacity, dtype=jnp.int32)[None, :]
            < buckets.counts[:, None])


def record_shuffle_stats(buckets: Buckets) -> dict:
    """Eager post-exchange accounting (record around dispatch — call on a
    CONCRETE :class:`Buckets`, never inside shard_map): bytes actually
    moved, rows dropped, and partition skew (max/mean bucket fill — the
    straggler predictor for the all-to-all).

    Returns the stats dict and, when metrics are enabled, feeds the
    ``shuffle.*`` counters/gauges."""
    counts = np.asarray(buckets.counts).reshape(-1)
    row_size = buckets.rows.shape[-1] * buckets.rows.dtype.itemsize
    valid_rows = int(counts.sum())
    mean = counts.mean() if counts.size else 0.0
    skew = float(counts.max() / mean) if valid_rows and mean > 0 else 1.0
    stats = {"rows": valid_rows,
             "bytes_moved": valid_rows * row_size,
             "dropped": int(np.asarray(buckets.dropped).reshape(-1).sum()),
             "partition_skew": round(skew, 4)}
    if metrics.recording():
        metrics.count("shuffle.rows_moved", stats["rows"])
        metrics.count("shuffle.bytes_moved", stats["bytes_moved"])
        metrics.count("shuffle.rows_dropped", stats["dropped"])
        metrics.gauge_max("shuffle.partition_skew.max", skew)
        metrics.observe("shuffle.partition_skew", skew)
    return stats
