"""Distributed query execution over the device mesh (SURVEY §7 step 7).

The reference stops at emitting shuffle-ready blobs (Spark executes the
query plan); here multi-chip execution is first-class: the canonical
Spark-on-TPU aggregation — a star-schema join + groupby over a sharded fact
table — runs as ONE jitted SPMD program:

  * fact columns sharded over the mesh axis (rows split across chips)
  * the dimension table replicated and pre-sorted by join key
  * per chip: ``searchsorted`` probe (static-shaped sort-merge lookup — the
    TPU formulation of a hash-probe), sentinel-dropped misses, and a
    fixed-width ``segment_sum`` partial aggregate
  * one ``psum`` over ICI combines the per-chip partials

No host sync anywhere: group count is static (dictionary codes), the probe
is static-shaped, and the collective is a single XLA ``all-reduce`` riding
ICI.  This is the BASELINE.json north-star shape (TPC-DS aggregation over a
sharded executor pool) in miniature.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

try:                                    # jax >= 0.5 top-level name
    _shard_map = jax.shard_map
except AttributeError:                  # 0.4.x keeps it in experimental
    from jax.experimental.shard_map import shard_map as _shard_map

from ..column import Column
from ..ops import strings as S
from ..utils import metrics


class Dimension(NamedTuple):
    """A replicated, probe-ready dimension: keys sorted ascending, one
    int32 group code per key (codes from ``strings.dictionary_encode`` or
    any bounded categorical), and the static group count."""
    keys: jnp.ndarray          # int [m], sorted ascending, unique
    group_codes: jnp.ndarray   # int32 [m] in [0, num_groups)
    num_groups: int


def prepare_dimension(key_col: Column, group_col: Column) -> Dimension:
    """Host-side prep: sort by key; dictionary-encode the group column
    (string or integer) into dense codes."""
    keys = np.asarray(key_col.data)
    if np.unique(keys).shape[0] != keys.shape[0]:
        # searchsorted probes resolve each fact key to ONE dimension row;
        # duplicate keys would silently drop the shadowed rows' groups
        raise ValueError("dimension join keys must be unique")
    order = np.argsort(keys)
    if group_col.dtype.is_variable_width:
        codes_col, uniq = S.dictionary_encode(group_col)
        codes = np.asarray(codes_col.data)
        num_groups = uniq.num_rows
    else:
        vals = np.asarray(group_col.data)
        uniq_vals, codes = np.unique(vals, return_inverse=True)
        num_groups = int(uniq_vals.shape[0])
    return Dimension(jnp.asarray(keys[order]),
                     jnp.asarray(codes[order].astype(np.int32)),
                     num_groups)


def _probe(dim_keys: jnp.ndarray, fact_keys: jnp.ndarray):
    """Static-shaped sort-merge probe: position + hit mask per fact row."""
    pos = jnp.searchsorted(dim_keys, fact_keys)
    pos = jnp.clip(pos, 0, dim_keys.shape[0] - 1)
    return pos, dim_keys[pos] == fact_keys


def _local_star_agg(num_groups: int, axis_name: str, dim_keys, dim_codes,
                    fact_key, fact_value):
    pos, hit = _probe(dim_keys, fact_key)
    # sentinel group `num_groups` absorbs probe misses via mode="drop"
    g = jnp.where(hit, dim_codes[pos], num_groups)
    sums = jnp.zeros(num_groups, fact_value.dtype).at[g].add(
        jnp.where(hit, fact_value, 0), mode="drop")
    cnts = jnp.zeros(num_groups, jnp.int32).at[g].add(
        hit.astype(jnp.int32), mode="drop")
    return (jax.lax.psum(sums, axis_name), jax.lax.psum(cnts, axis_name))


def distributed_star_agg(mesh: jax.sharding.Mesh, dim: Dimension,
                         fact_key: jnp.ndarray, fact_value: jnp.ndarray,
                         axis_name="data"):
    """SELECT group, SUM(value), COUNT(*) FROM fact ⋈ dim GROUP BY group,
    executed SPMD over the mesh.

    ``fact_key``/``fact_value`` are global [n] arrays (n divisible by the
    mesh size); they are sharded over ``axis_name``, the dimension is
    replicated (explicit P() specs — no closure capture under shard_map).
    Returns replicated ([num_groups] sums, [num_groups] counts) — group
    codes index them.

    ``axis_name`` may be a tuple of mesh axes (e.g. ``("dcn", "ici")`` on a
    2-D multi-host mesh): the fact table shards over all of them and the
    final psum reduces over all of them — XLA lowers that to an ICI
    all-reduce per host followed by one DCN all-reduce.
    """
    axis = tuple(axis_name) if isinstance(axis_name, (tuple, list)) \
        else axis_name
    fn = _compiled_star_agg(mesh, dim.num_groups, axis)
    if metrics.recording():
        # record around the SPMD dispatch: sharded fact bytes cross ICI,
        # the partial-aggregate psum is one [num_groups] all-reduce
        metrics.count("dist.star_agg.calls")
        metrics.count("dist.star_agg.fact_bytes",
                      int(fact_key.nbytes) + int(fact_value.nbytes))
        with metrics.span("dist.star_agg", groups=dim.num_groups,
                          devices=len(mesh.devices.flat)):
            return fn(dim.keys, dim.group_codes, fact_key, fact_value)
    return fn(dim.keys, dim.group_codes, fact_key, fact_value)


@lru_cache(maxsize=64)
def _compiled_star_agg(mesh, num_groups: int, axis_name: str):
    """jitted program cached on (mesh, num_groups, axis) — rebuilding the
    shard_map wrapper per call would retrace every invocation."""
    P = jax.sharding.PartitionSpec
    fn = _shard_map(
        partial(_local_star_agg, num_groups, axis_name),
        mesh=mesh,
        in_specs=(P(), P(), P(axis_name), P(axis_name)),
        out_specs=(P(), P()))
    return jax.jit(fn)
