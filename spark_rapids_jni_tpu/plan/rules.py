"""Fixpoint rewrite engine over the plan IR.

Four rules, applied in a loop until a full pass changes nothing:

* **projection_pushdown** — walk required-column sets down the tree and
  narrow every ``Scan`` to the columns actually consumed above it (plus
  its own predicate's columns).  On the file catalog this prunes parquet
  columns *before decode*.
* **filter_pushdown** — sink ``Filter`` predicates through projects and
  joins (splitting conjuncts by side) until they merge into ``Scan``
  predicates, where footer statistics can prune whole row groups before
  decode.
* **join_reorder** — for a left-deep pair of inner joins whose outer key
  lives on the base table, join the smaller dimension first.  Driven by
  :mod:`plan.stats` cardinalities (exact observations, else the
  ``join.match_rows`` metrics prior); rejects — a deliberate no-op —
  when stats are absent.
* **fuse_join_aggregate** — detect ``Aggregate(Join(...))`` with an
  inner/left join and emit the fused ``ops/join_plan.join_aggregate``
  path (``FusedJoinAggregate`` node) instead of a per-query rewire.
* **fuse_join_window** — push a ``Window`` below a left join whose
  build side is provably unique on its keys (an ``Aggregate`` or
  ``Distinct`` on exactly those columns), so the window runs on the
  narrow pre-join table instead of the widened join output.

Metrics (when recording): ``plan.rule.fired.<name>`` /
``plan.rule.rejected.<name>`` counters and a ``plan.optimize`` span that
nests under the active query span.

Env knobs:

* ``SRJT_PLAN_OPT=0`` — disable optimization (``optimize`` returns the
  tree untouched; lowering still works on raw trees).
* ``SRJT_PLAN_RULES=a,b`` — run only the named rules.
* ``SRJT_PLAN_MAX_PASSES`` — fixpoint pass cap (default 10).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field, replace
from typing import Optional, Sequence

from ..utils import knobs, metrics
from . import ir


@dataclass(frozen=True)
class RuleEvent:
    rule: str
    detail: str


@dataclass
class Context:
    """Per-optimize scratch state handed to rules."""
    schemas: dict
    stats: object = None            # CardinalityStats or None
    events: list = field(default_factory=list)
    rejections: list = field(default_factory=list)

    def fire(self, rule: str, detail: str) -> None:
        self.events.append(RuleEvent(rule, detail))

    def reject(self, rule: str, detail: str) -> None:
        self.rejections.append(RuleEvent(rule, detail))

    def schema(self, node: ir.Plan) -> tuple:
        return ir.schema_of(node, self.schemas)


class Rule:
    name = "rule"

    def apply(self, tree: ir.Plan, ctx: Context) -> ir.Plan:
        raise NotImplementedError


# --- projection pushdown ----------------------------------------------------


class ProjectionPushdown(Rule):
    """Narrow every Scan to the columns consumed above it."""

    name = "projection_pushdown"

    def apply(self, tree, ctx):
        return self._push(tree, None, ctx)

    def _push(self, node, need, ctx):
        # need: frozenset of columns required by ancestors, None = all
        if isinstance(node, ir.Scan):
            full = tuple(ctx.schemas[node.table])
            cur = node.columns if node.columns is not None else full
            if need is None:
                return node
            want = set(need) | set(ir.expr_columns(node.predicate))
            new_cols = tuple(c for c in cur if c in want)
            if new_cols == cur:
                return node
            ctx.fire(self.name,
                     f"scan({node.table}): {len(cur)} → {len(new_cols)} "
                     f"columns [{', '.join(new_cols)}]")
            return replace(node, columns=new_cols)
        if isinstance(node, ir.Filter):
            cneed = (None if need is None
                     else need | ir.expr_columns(node.predicate))
            return self._rebuild(node, (self._push(node.child, cneed, ctx),))
        if isinstance(node, ir.Project):
            return self._rebuild(
                node, (self._push(node.child, frozenset(node.columns),
                                  ctx),))
        if isinstance(node, ir.Join):
            if need is None:
                lneed = rneed = None
            else:
                ls = set(ctx.schema(node.left))
                rs = set(ctx.schema(node.right))
                lneed = frozenset((need & ls) | set(node.left_on))
                rneed = frozenset((need & rs) | set(node.right_on))
            return self._rebuild(node,
                                 (self._push(node.left, lneed, ctx),
                                  self._push(node.right, rneed, ctx)))
        if isinstance(node, ir.Aggregate):
            # aggregates reset the requirement to a CONCRETE set no
            # matter what the ancestors ask for
            cneed = frozenset(node.keys) | {a[0] for a in node.aggs}
            return self._rebuild(node, (self._push(node.child, cneed, ctx),))
        if isinstance(node, ir.FusedJoinAggregate):
            used = frozenset(node.keys) | {a[0] for a in node.aggs}
            ls = set(ctx.schema(node.left))
            rs = set(ctx.schema(node.right))
            lneed = frozenset((used & ls) | set(node.left_on))
            rneed = frozenset((used & rs) | set(node.right_on))
            return self._rebuild(node,
                                 (self._push(node.left, lneed, ctx),
                                  self._push(node.right, rneed, ctx)))
        if isinstance(node, ir.Window):
            val = set() if node.value is None else {node.value}
            cneed = (None if need is None
                     else frozenset((need - {node.out})
                                    | set(node.partition_by)
                                    | set(node.order_by) | val))
            return self._rebuild(node, (self._push(node.child, cneed, ctx),))
        if isinstance(node, ir.Union):
            # arms are positional: ancestors' name-based needs don't
            # translate, but each arm's own Projects reset the
            # requirement so scan narrowing still happens below
            return self._rebuild(
                node, tuple(self._push(p, None, ctx) for p in node.parts))
        if isinstance(node, ir.Distinct):
            # distinct is over the child's FULL row — everything is needed
            return self._rebuild(node, (self._push(node.child, None, ctx),))
        if isinstance(node, ir.Sort):
            cneed = None if need is None else need | set(node.keys)
            return self._rebuild(node, (self._push(node.child, cneed, ctx),))
        if isinstance(node, ir.Limit):
            return self._rebuild(node, (self._push(node.child, need, ctx),))
        raise ir.PlanError(f"unknown plan node {type(node).__name__}")

    @staticmethod
    def _rebuild(node, new_kids):
        kids = ir.children(node)
        if all(nk is k for nk, k in zip(new_kids, kids)):
            return node
        return ir.with_children(node, tuple(new_kids))


# --- filter pushdown --------------------------------------------------------


class FilterPushdown(Rule):
    """Sink Filter predicates toward (and into) the scans."""

    name = "filter_pushdown"

    def apply(self, tree, ctx):
        return ir.transform_up(tree, lambda n: self._rewrite(n, ctx))

    def _rewrite(self, node, ctx):
        if not isinstance(node, ir.Filter):
            return None
        child = node.child
        cj = ir.conjuncts(node.predicate)

        if isinstance(child, ir.Filter):
            ctx.fire(self.name, "merged adjacent filters")
            return ir.Filter(child.child,
                             ir.and_(ir.conjuncts(child.predicate) + cj))

        if isinstance(child, ir.Scan):
            merged = ir.conjuncts(child.predicate) + cj
            ctx.fire(self.name,
                     f"{len(cj)} predicate(s) → scan({child.table})")
            return replace(child, predicate=ir.and_(merged))

        if isinstance(child, ir.Project):
            if ir.expr_columns(node.predicate) <= set(child.columns):
                ctx.fire(self.name, "filter below project")
                return ir.Project(ir.Filter(child.child, node.predicate),
                                  child.columns)
            return None

        if isinstance(child, ir.Join):
            ls = set(ctx.schema(child.left))
            rs = set(ctx.schema(child.right))
            lp, rp, keep = [], [], []
            for c in cj:
                cols = ir.expr_columns(c)
                if cols and cols <= ls:
                    lp.append(c)
                elif cols and cols <= rs and child.how == "inner":
                    # right-side predicates must NOT sink below a left
                    # outer join (they'd drop null-extended rows early)
                    rp.append(c)
                else:
                    keep.append(c)
            if not lp and not rp:
                if child.how != "inner" and any(
                        ir.expr_columns(c) and ir.expr_columns(c) <= rs
                        for c in keep):
                    ctx.reject(self.name,
                               f"right-side predicate kept above "
                               f"{child.how} join")
                return None
            nl = (ir.Filter(child.left, ir.and_(lp)) if lp else child.left)
            nr = (ir.Filter(child.right, ir.and_(rp)) if rp else child.right)
            ctx.fire(self.name,
                     f"{len(lp) + len(rp)} conjunct(s) through "
                     f"{child.how} join ({len(keep)} kept above)")
            out = replace(child, left=nl, right=nr)
            return ir.Filter(out, ir.and_(keep)) if keep else out

        if isinstance(child, ir.Union):
            # positional rename per arm, then push into every arm (same
            # rows survive; concat of filtered arms == filtered concat)
            new_parts = []
            for part in child.parts:
                psch = ctx.schema(part)
                mapping = dict(zip(child.names, psch))
                new_parts.append(ir.Filter(
                    part, _rename_expr(node.predicate, mapping)))
            ctx.fire(self.name,
                     f"filter through union ({len(new_parts)} arms)")
            return replace(child, parts=tuple(new_parts))

        if isinstance(child, ir.Distinct):
            # distinct(filter(x)) == filter(distinct(x)): same surviving
            # key set, same key-sorted output order
            ctx.fire(self.name, "filter below distinct")
            return ir.Distinct(ir.Filter(child.child, node.predicate))

        # Sort/Limit/Aggregate/Window: order- or group-sensitive —
        # predicates stay put (HAVING-style filters land here)
        return None


def _rename_expr(e, mapping: dict):
    """Rewrite every Col reference through ``mapping`` (missing = keep)."""
    if e is None:
        return None
    if isinstance(e, ir.Col):
        return ir.Col(mapping.get(e.name, e.name))
    if isinstance(e, ir.Cmp):
        return ir.Cmp(e.op, _rename_expr(e.left, mapping),
                      _rename_expr(e.right, mapping))
    if isinstance(e, ir.Between):
        return replace(e, col=_rename_expr(e.col, mapping))
    if isinstance(e, (ir.And, ir.Or)):
        return type(e)(tuple(_rename_expr(p, mapping) for p in e.parts))
    if isinstance(e, ir.IsIn):
        return replace(e, col=_rename_expr(e.col, mapping))
    if isinstance(e, ir.ScalarAgg):
        return ir.ScalarAgg(e.fn, _rename_expr(e.arg, mapping))
    if isinstance(e, ir.Mul):
        return ir.Mul(_rename_expr(e.left, mapping),
                      _rename_expr(e.right, mapping))
    return e                          # Lit and friends: no columns


# --- join reorder -----------------------------------------------------------


class JoinReorder(Rule):
    """Left-deep inner-join pair: join the smaller dimension first.

    ``Join(Join(base, d1), d2)`` → ``Project(Join(Join(base, d2), d1))``
    when the outer keys come from ``base`` and est(d2) < est(d1); the
    Project restores the original output column order so the rewrite is
    invisible above.  Without stats for BOTH dimensions the rule rejects.
    """

    name = "join_reorder"

    def apply(self, tree, ctx):
        return ir.transform_up(tree, lambda n: self._rewrite(n, ctx))

    def _rewrite(self, node, ctx):
        if not (isinstance(node, ir.Join) and node.how == "inner"
                and isinstance(node.left, ir.Join)
                and node.left.how == "inner"):
            return None
        inner, d2 = node.left, node.right
        base, d1 = inner.left, inner.right
        if not set(node.left_on) <= set(ctx.schema(base)):
            return None           # outer keys come via d1: not commutable
        if ctx.stats is None:
            ctx.reject(self.name, "no cardinality stats provided")
            return None
        e1 = ctx.stats.rows_for(d1)
        e2 = ctx.stats.rows_for(d2)
        if e1 is None or e2 is None:
            ctx.reject(self.name,
                       "missing cardinality estimate for join input")
            return None
        if e2 >= e1:
            return None           # already smallest-first; strict <
        names = ctx.schema(node)  # original left++d1++d2 order
        ctx.fire(self.name,
                 f"swap join inputs (est {e2:.0f} < {e1:.0f} rows)")
        swapped = ir.Join(
            ir.Join(base, d2, node.left_on, node.right_on),
            d1, inner.left_on, inner.right_on)
        return ir.Project(swapped, names)


# --- join→aggregate fusion --------------------------------------------------


class FuseJoinAggregate(Rule):
    """Aggregate directly over an inner/left join → the fused
    ``join_aggregate`` path (covers left-join→groupby too)."""

    name = "fuse_join_aggregate"

    def apply(self, tree, ctx):
        return ir.transform_up(tree, lambda n: self._rewrite(n, ctx))

    def _rewrite(self, node, ctx):
        if not isinstance(node, ir.Aggregate):
            return None
        c = node.child
        if not isinstance(c, ir.Join):
            return None
        if node.grouping is not None or any(a[1] == "nunique"
                                            for a in node.aggs):
            ctx.reject(self.name,
                       "grouping-spec/nunique aggregate is unfusable")
            return None
        if c.how not in ("inner", "left"):
            ctx.reject(self.name, f"unfusable join type {c.how!r}")
            return None
        ctx.fire(self.name,
                 f"aggregate over {c.how} join → ops.join_aggregate")
        return ir.FusedJoinAggregate(c.left, c.right, c.left_on,
                                     c.right_on, node.keys, node.aggs,
                                     c.how)


# --- join→window fusion -----------------------------------------------------


class FuseJoinWindow(Rule):
    """Push a Window below a left join with a provably-unique build side.

    ``Window(Join(left, right, how="left"))`` == ``Join(Window(left),
    right)`` when (a) every window input column lives on ``left`` and
    (b) ``right`` is unique on its join keys, so each left row lands in
    the output exactly once.  Uniqueness is only claimed when it is
    structural: the right child is an ``Aggregate`` grouped exactly on
    the join keys, or a ``Distinct`` whose schema is exactly the join
    keys.  The trailing Project restores the original column order, so
    the rewrite is invisible above — and the window now runs on the
    narrow pre-join table instead of the gather-widened join output."""

    name = "fuse_join_window"

    def apply(self, tree, ctx):
        return ir.transform_up(tree, lambda n: self._rewrite(n, ctx))

    def _rewrite(self, node, ctx):
        if not isinstance(node, ir.Window):
            return None
        c = node.child
        if not isinstance(c, ir.Join):
            return None
        if c.how != "left":
            ctx.reject(self.name,
                       f"{c.how} join can drop/repeat probe rows")
            return None
        ls = ctx.schema(c.left)
        rs = ctx.schema(c.right)
        wcols = set(node.partition_by) | set(node.order_by)
        if node.value is not None:
            wcols.add(node.value)
        if not wcols <= set(ls):
            ctx.reject(self.name, "window keys straddle the join")
            return None
        if not _unique_on(c.right, c.right_on, ctx):
            ctx.reject(self.name,
                       "build side not provably unique on join keys")
            return None
        ctx.fire(self.name, f"window({node.fn}) below {c.how} join")
        win = replace(node, child=c.left)
        return ir.Project(replace(c, left=win), ls + rs + (node.out,))


def _unique_on(node: ir.Plan, keys, ctx: Context) -> bool:
    """True when ``node``'s output is structurally unique on ``keys``."""
    if isinstance(node, ir.Aggregate) and node.grouping is None:
        return set(node.keys) == set(keys)
    if isinstance(node, ir.FusedJoinAggregate):
        return set(node.keys) == set(keys)
    if isinstance(node, ir.Distinct):
        return set(ctx.schema(node)) == set(keys)
    return False


DEFAULT_RULES: tuple[Rule, ...] = (
    ProjectionPushdown(), FilterPushdown(), JoinReorder(),
    FuseJoinAggregate(), FuseJoinWindow(),
)


@dataclass(frozen=True)
class OptimizeResult:
    tree: ir.Plan
    events: tuple
    rejections: tuple
    passes: int
    converged: bool


def optimize(tree: ir.Plan, schemas: dict, stats=None,
             rules: Optional[Sequence[Rule]] = None,
             max_passes: Optional[int] = None) -> OptimizeResult:
    """Rewrite ``tree`` to fixpoint (or ``max_passes``).

    ``schemas`` maps base-table name → column names; ``stats`` is an
    optional :class:`plan.stats.CardinalityStats` for join reordering.
    """
    if not knobs.get("SRJT_PLAN_OPT"):
        return OptimizeResult(tree, (), (), 0, True)
    if stats is not None:
        # warm priors: merge the SRJT_PLAN_STATS_PATH sidecar (once per
        # process) before any rule consults cardinalities
        from . import stats as plan_stats
        plan_stats.ensure_sidecar_loaded()
    active = list(DEFAULT_RULES if rules is None else rules)
    only = knobs.get("SRJT_PLAN_RULES")
    if only:
        wanted = {r.strip() for r in only.split(",") if r.strip()}
        active = [r for r in active if r.name in wanted]
    if max_passes is None:
        max_passes = knobs.get("SRJT_PLAN_MAX_PASSES")

    ir.schema_of(tree, schemas)      # validate before rewriting
    ctx = Context(schemas=schemas, stats=stats)
    recording = metrics.recording()
    converged = False
    passes = 0
    with metrics.span("plan.optimize"):
        while passes < max_passes:
            passes += 1
            before = len(ctx.events)
            for rule in active:
                f0, r0 = len(ctx.events), len(ctx.rejections)
                tree = rule.apply(tree, ctx)
                if recording:
                    fired = len(ctx.events) - f0
                    rejected = len(ctx.rejections) - r0
                    if fired:
                        metrics.count(f"plan.rule.fired.{rule.name}",
                                      fired)
                    if rejected:
                        metrics.count(f"plan.rule.rejected.{rule.name}",
                                      rejected)
            if len(ctx.events) == before:
                converged = True
                break
        if recording:
            metrics.annotate(plan_passes=passes,
                             plan_rules_fired=len(ctx.events))
    ir.schema_of(tree, schemas)      # rewrites must preserve validity
    return OptimizeResult(tree, tuple(ctx.events), tuple(ctx.rejections),
                          passes, converged)


def explain(tree: ir.Plan, schemas: dict, stats=None,
            rules: Optional[Sequence[Rule]] = None,
            adaptive_report=None) -> str:
    """Render the pre-/post-rewrite tree with per-rule annotations.

    ``adaptive_report`` (a ``plan.adaptive.AdaptiveReport``) appends the
    stage-wise runtime decisions of an adaptive execution — the static
    EXPLAIN shows what the optimizer *planned*, the adaptive section what
    observed cardinalities actually *did*."""
    res = optimize(tree, schemas, stats=stats, rules=rules)
    lines = ["== Logical plan ==", ir.render(tree), "",
             f"== Optimized plan ({res.passes} pass(es)"
             f"{'' if res.converged else ', pass cap hit'}) ==",
             ir.render(res.tree), "", "== Rules =="]
    if not res.events and not res.rejections:
        lines.append("(no rules fired)")
    for ev in res.events:
        lines.append(f"fired    {ev.rule}: {ev.detail}")
    for ev in res.rejections:
        lines.append(f"rejected {ev.rule}: {ev.detail}")
    if adaptive_report is not None:
        lines += ["", adaptive_report.render()]
    return "\n".join(lines)
