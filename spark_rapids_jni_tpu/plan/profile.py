"""Per-plan-node runtime profiles: the EXPLAIN ANALYZE subsystem.

PR 2/PR 7 observability stops at the request lifecycle — which *query*
was slow, never which *plan node*.  This module closes that gap the way
Spark SQL's per-operator metrics do, rebuilt on ``plan/ir.py``
fingerprints: while a :class:`QueryProfile` is active, both executors
(``plan/lower.py`` static, ``plan/adaptive.py`` stage-wise) wrap every
node execution in :func:`node_enter` / :func:`node_exit`, producing a
tree of :class:`NodeProfile` records that mirrors what actually ran —
input/output rows, output bytes, validity density, the engine/AQE
decision taken, and wall/device time.  :func:`explain_analyze` renders
the annotated tree (estimated vs observed rows, >2× mispredictions
flagged); artifacts export as JSON under ``SRJT_PROFILE_DIR``; the
flight recorder embeds in-flight partial profiles in incident snapshots.

Discipline (the same three rules as ``utils/metrics.py``):

* **Zero overhead when disabled.**  Every public entry is gated on ONE
  module-level bool (``SRJT_PROFILE``, default off); the compiled steady
  loop (``CompiledQuery.run_unchecked``) is untouched entirely.
* **Capture/replay-safe.**  Profiles derive only from host-visible
  values — ``Table.num_rows`` (free ints under static shapes), buffer
  ``nbytes``, ``perf_counter`` — and recording is skipped under a
  ``syncs.replay`` re-trace.  The one knob that syncs,
  ``SRJT_PROFILE_VALIDITY``, does so UNCONDITIONALLY at the single
  lowering funnel (``lower._apply_node`` → :func:`at_node_output`) so
  capture and replay tapes stay aligned; keep it stable across a
  compiled plan's lifetime.
* **Device time never forces.**  ``block_until_ready`` fencing
  (``SRJT_PROFILE_DEVICE_TIME``) touches only already-concrete buffers —
  an unrealized ``LazyColumn`` is skipped, because forcing it would
  resolve string-size syncs outside their recorded order.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Optional

from ..analysis import sanitize
from ..utils import flight, knobs, metrics, syncs
from . import ir
from . import stats as plan_stats

#: observed rows beyond this factor × the prior estimate flags the node
#: as a misprediction in ``explain_analyze`` (mirrors
#: ``plan/adaptive.REGRESSION_FACTOR``)
MISPREDICT_FACTOR = 2.0

_enabled: bool = bool(knobs.get("SRJT_PROFILE"))
_device_time: bool = bool(knobs.get("SRJT_PROFILE_DEVICE_TIME"))
_validity: bool = bool(knobs.get("SRJT_PROFILE_VALIDITY"))

_lock = sanitize.tracked_lock("plan.profile")
_tls = threading.local()                    # .prof = active QueryProfile
_inflight: dict[int, "QueryProfile"] = {}   # tid → active (flight probe)
_completed: "deque[QueryProfile]" = deque(maxlen=32)
_artifact_seq = 0

#: per-node cap on op-level events (a pathological loop must not grow a
#: profile without bound)
_MAX_OPS_PER_NODE = 64


def enabled() -> bool:
    return _enabled


def set_enabled(on: Optional[bool] = None) -> None:
    """Toggle profiling at runtime; ``None`` re-reads the env knobs.
    Also refreshes the device-time / validity sub-knob gates."""
    global _enabled, _device_time, _validity
    _enabled = bool(knobs.get("SRJT_PROFILE")) if on is None else bool(on)
    _device_time = bool(knobs.get("SRJT_PROFILE_DEVICE_TIME"))
    _validity = bool(knobs.get("SRJT_PROFILE_VALIDITY"))


def active() -> Optional["QueryProfile"]:
    """The calling thread's active profile (None outside :func:`query`)."""
    return getattr(_tls, "prof", None)


# --- records -----------------------------------------------------------------


@dataclass
class NodeProfile:
    """One executed plan node's runtime facts (a tree: ``children`` hold
    the node's executed inputs, mirroring the actual run — an adaptively
    re-ordered spine profiles in its EXECUTED order)."""

    op: str                             # plan node class name
    line: str                           # ir._node_line rendering
    node_id: str                        # ir.fingerprint (structural)
    est_rows: Optional[float] = None    # plan/stats prior at entry
    in_rows: Optional[int] = None       # sum of child output rows
    out_rows: Optional[int] = None
    out_bytes: int = 0                  # realized device buffer bytes
    lazy_cols: int = 0                  # unrealized columns (not forced)
    valid_frac: Optional[float] = None  # SRJT_PROFILE_VALIDITY only
    wall_ms: float = 0.0                # inclusive (children + fence)
    fence_ms: Optional[float] = None    # block_until_ready drain at exit
    engine: Optional[str] = None        # join engine pinned/used
    decisions: list = field(default_factory=list)   # AQE decision strings
    ops: list = field(default_factory=list)         # op-level events
    error: bool = False                 # node raised (partial record)
    children: list = field(default_factory=list)

    def self_ms(self) -> float:
        """Wall time exclusive of profiled children."""
        return max(self.wall_ms - sum(c.wall_ms for c in self.children),
                   0.0)

    def mispredicted(self) -> bool:
        """True when observed rows disagree with the prior by more than
        ``MISPREDICT_FACTOR`` in either direction."""
        if self.est_rows is None or not self.est_rows or \
                self.out_rows is None:
            return False
        ratio = self.out_rows / self.est_rows
        return (ratio > MISPREDICT_FACTOR
                or (self.out_rows and 1 / ratio > MISPREDICT_FACTOR))

    def walk(self):
        yield self
        for c in self.children:
            yield from c.walk()

    def as_dict(self) -> dict:
        d: dict[str, Any] = {"op": self.op, "line": self.line,
                             "node_id": self.node_id,
                             "out_rows": self.out_rows,
                             "out_bytes": self.out_bytes,
                             "wall_ms": round(self.wall_ms, 3),
                             "self_ms": round(self.self_ms(), 3)}
        if self.est_rows is not None:
            d["est_rows"] = self.est_rows
        if self.in_rows is not None:
            d["in_rows"] = self.in_rows
        if self.lazy_cols:
            d["lazy_cols"] = self.lazy_cols
        if self.valid_frac is not None:
            d["valid_frac"] = round(self.valid_frac, 4)
        if self.fence_ms is not None:
            d["fence_ms"] = round(self.fence_ms, 3)
        if self.engine is not None:
            d["engine"] = self.engine
        if self.decisions:
            d["decisions"] = list(self.decisions)
        if self.ops:
            d["ops"] = [dict(o) for o in self.ops]
        if self.mispredicted():
            d["mispredict"] = True
        if self.error:
            d["error"] = True
        if self.children:
            d["children"] = [c.as_dict() for c in self.children]
        return d


class QueryProfile:
    """One query execution's node-profile tree plus identity/timing."""

    def __init__(self, name: str, fingerprint: Optional[str] = None):
        self.name = name
        self.fingerprint = fingerprint
        self.ts = time.time()
        self.wall_ms = 0.0
        self.finished = False
        self.roots: list[NodeProfile] = []
        self._stack: list[NodeProfile] = []
        self._spans: dict[int, Any] = {}
        self._t0 = time.perf_counter()

    def nodes(self):
        for r in self.roots:
            yield from r.walk()

    def mispredictions(self) -> list[NodeProfile]:
        return [n for n in self.nodes() if n.mispredicted()]

    def as_dict(self, partial: bool = False) -> dict:
        d: dict[str, Any] = {
            "name": self.name, "fingerprint": self.fingerprint,
            "ts": round(self.ts, 6), "finished": self.finished,
            "wall_ms": round(self.wall_ms, 3),
            "nodes": [r.as_dict() for r in self.roots]}
        if partial and self._stack:
            # the in-flight path: innermost-open-last — an incident
            # snapshot shows exactly where the request is stuck
            d["open"] = [rec.line for rec in self._stack]
        return d

    def render(self) -> str:
        """Annotated plan-tree rendering (the EXPLAIN ANALYZE body)."""
        lines: list[str] = []

        def emit(rec: NodeProfile, depth: int) -> None:
            est = ("?" if rec.est_rows is None
                   else f"{rec.est_rows:.0f}")
            obs = "?" if rec.out_rows is None else str(rec.out_rows)
            parts = [f"rows est={est} obs={obs}"]
            if rec.out_bytes:
                parts.append(f"bytes={rec.out_bytes}")
            t = f"time={rec.wall_ms:.2f}ms self={rec.self_ms():.2f}ms"
            if rec.fence_ms is not None:
                t += f" fence={rec.fence_ms:.2f}ms"
            parts.append(t)
            if rec.valid_frac is not None:
                parts.append(f"valid={rec.valid_frac:.3f}")
            if rec.engine is not None:
                parts.append(f"engine={rec.engine}")
            if rec.mispredicted():
                parts.append("!!misprediction")
            if rec.error:
                parts.append("!!error")
            lines.append("  " * depth + rec.line
                         + "   | " + " ".join(parts))
            for d in rec.decisions:
                lines.append("  " * depth + f"  fired {d}")
            for c in rec.children:
                emit(c, depth + 1)

        for r in self.roots:
            emit(r, 0)
        return "\n".join(lines) if lines else "(no profiled nodes)"


# --- activation --------------------------------------------------------------


@contextlib.contextmanager
def query(name: str, fingerprint: Optional[str] = None):
    """Activate a :class:`QueryProfile` for the calling thread.  Yields
    None (and records nothing) when profiling is disabled or inside a
    replay re-trace; on exit the profile is finalized, retained (bounded),
    and exported to ``SRJT_PROFILE_DIR`` when set."""
    if not _enabled or syncs.mode() == "replay":
        yield None
        return
    prof = QueryProfile(name, fingerprint)
    prev = getattr(_tls, "prof", None)
    _tls.prof = prof
    tid = threading.get_ident()
    with _lock:
        _inflight[tid] = prof
    try:
        yield prof
    finally:
        prof.wall_ms = (time.perf_counter() - prof._t0) * 1e3
        prof.finished = not prof._stack
        _tls.prof = prev
        with _lock:
            if prev is None:
                _inflight.pop(tid, None)
            else:
                _inflight[tid] = prev
            _completed.append(prof)
        if metrics.recording():
            metrics.count("plan.profile.queries")
            n = len(prof.mispredictions())
            if n:
                metrics.count("plan.profile.mispredict", n)
        _export_artifact(prof)


def completed(last: Optional[int] = None) -> list[QueryProfile]:
    """Finished profiles, oldest → newest (bounded retention)."""
    with _lock:
        profs = list(_completed)
    return profs[-int(last):] if last is not None else profs


def reset() -> None:
    """Drop retained profiles (tests)."""
    with _lock:
        _completed.clear()


# --- executor instrumentation ------------------------------------------------


def node_enter(node: ir.Plan) -> Optional[NodeProfile]:
    """Open a node record under the thread's active profile.  Returns
    None — ONE bool check then out — when profiling is off; also None
    without an active profile or under a replay re-trace."""
    if not _enabled:
        return None
    prof = getattr(_tls, "prof", None)
    if prof is None or syncs.mode() == "replay":
        return None
    rec = NodeProfile(op=type(node).__name__, line=ir._node_line(node),
                      node_id=ir.fingerprint(node),
                      est_rows=plan_stats.GLOBAL.rows_for(node),
                      engine=getattr(node, "engine", None))
    prof._stack.append(rec)
    sp = metrics.span(f"plan.node:{rec.op}", node_id=rec.node_id,
                      line=rec.line)
    sp.__enter__()                      # nullcontext when metrics off
    prof._spans[id(rec)] = sp
    rec._t0 = time.perf_counter()
    return rec


def node_exit(rec: NodeProfile, t, kids=None) -> None:
    """Close ``rec`` with the node's output ``t`` (None on error) and the
    child ``(table, names)`` pairs when the caller has them."""
    prof = getattr(_tls, "prof", None)
    if t is None:
        rec.error = True
    else:
        rec.out_rows = t.num_rows
        rec.out_bytes, rec.lazy_cols = _table_bytes(t)
        if kids:
            rec.in_rows = sum(k[0].num_rows for k in kids)
        if _device_time:
            rec.fence_ms = _fence(t)
    rec.wall_ms = (time.perf_counter() - rec._t0) * 1e3
    sp = None if prof is None else prof._spans.pop(id(rec), None)
    if isinstance(sp, metrics.Span):
        sp.annotate(rows=rec.out_rows, out_bytes=rec.out_bytes,
                    est_rows=rec.est_rows)
        if rec.engine is not None:
            sp.annotate(engine=rec.engine)
    if sp is not None:
        sp.__exit__(None, None, None)
    if prof is None or not prof._stack or prof._stack[-1] is not rec:
        return                          # unbalanced exit: drop, never raise
    prof._stack.pop()
    if prof._stack:
        prof._stack[-1].children.append(rec)
    else:
        prof.roots.append(rec)


def annotate_node(engine: Optional[str] = None,
                  decision: Optional[str] = None, **fields) -> None:
    """Attach an engine choice / AQE decision / extra fields to the
    innermost open node record (``plan/adaptive.py`` calls this at its
    decision sites)."""
    if not _enabled:
        return
    prof = getattr(_tls, "prof", None)
    if prof is None or not prof._stack or syncs.mode() == "replay":
        return
    rec = prof._stack[-1]
    if engine is not None:
        rec.engine = engine
    if decision is not None:
        rec.decisions.append(decision)
    for k, v in fields.items():
        setattr(rec, k, v) if hasattr(rec, k) else rec.ops.append(
            {"op": "annotate", k: v})


@contextlib.contextmanager
def stage(name: str, **fields):
    """Open a synthetic node record for a non-plan stage (ml/ feature
    pack, train-step, predict): the stage gets its own row in EXPLAIN
    ANALYZE / profile_report with wall/self time, and :func:`op_event`s
    fired inside attach to it.  Installed as
    ``metrics._profile_stage_hook`` — ml/ reaches it without importing
    plan/ (same discipline as :func:`op_event`)."""
    if not _enabled:
        yield None
        return
    prof = getattr(_tls, "prof", None)
    if prof is None or syncs.mode() == "replay":
        yield None
        return
    line = name if not fields else name + "(" + ", ".join(
        f"{k}={v}" for k, v in fields.items()) + ")"
    rec = NodeProfile(op=name, line=line, node_id=name)
    prof._stack.append(rec)
    rec._t0 = time.perf_counter()
    try:
        yield rec
    except BaseException:
        rec.error = True
        raise
    finally:
        rec.wall_ms = (time.perf_counter() - rec._t0) * 1e3
        if prof._stack and prof._stack[-1] is rec:
            prof._stack.pop()
            if prof._stack:
                prof._stack[-1].children.append(rec)
            else:
                prof.roots.append(rec)


def op_event(name: str, **fields) -> None:
    """One op-level event (join match counts, filter selectivity, scan
    pruning, rowconv volumes) into the innermost open node record.
    Installed as ``metrics.profile_op``'s hook so ops/ modules report
    without importing plan/.  Fields must already be host values."""
    if not _enabled:
        return
    prof = getattr(_tls, "prof", None)
    if prof is None or not prof._stack or syncs.mode() == "replay":
        return
    rec = prof._stack[-1]
    eng = fields.pop("engine", None)
    if eng is not None and rec.engine is None:
        rec.engine = eng
    if fields and len(rec.ops) < _MAX_OPS_PER_NODE:
        rec.ops.append({"op": name, **fields})


def at_node_output(t) -> None:
    """Hook at the single lowering funnel (``lower._apply_node``), called
    for EVERY applied node: when ``SRJT_PROFILE_VALIDITY`` is on, sync
    the output's validity density — UNCONDITIONALLY on the module gates,
    never on profile/metrics state, so a capture run and its replay
    re-trace resolve the identical sync sequence — and stash it into the
    open node record when one is recording."""
    if not (_enabled and _validity):
        return
    frac = _validity_fraction(t)
    prof = getattr(_tls, "prof", None)
    if (frac is not None and prof is not None and prof._stack
            and syncs.mode() != "replay"):
        prof._stack[-1].valid_frac = frac


# --- table accounting helpers ------------------------------------------------


def _realized(col):
    """The concrete Column behind ``col``, or None when it is an
    unrealized LazyColumn (which must never be forced here)."""
    from ..column import LazyColumn
    if isinstance(col, LazyColumn):
        return col._col                 # None until someone else forces
    return col


def _buffers(col):
    """``col``'s existing device buffers — NO materialization: a
    DictColumn contributes codes + dictionary buffers (touching ``.data``
    would synthesize the flat string bytes), a plain Column its
    data/offsets/validity and children's."""
    from ..column import DictColumn
    if isinstance(col, DictColumn):
        out = [col.codes, col.validity]
        d = _realized(col.dictionary)
        if d is not None:
            out.extend(_buffers(d))
        return out
    out = [col.data, col.offsets, col.validity]
    for ch in (getattr(col, "children", None) or ()):
        sub = _realized(ch)
        if sub is not None:
            out.extend(_buffers(sub))
    return out


def _table_bytes(t) -> tuple[int, int]:
    """(realized device bytes, unrealized column count) for ``t`` —
    buffer ``nbytes`` sums only, no device sync, no forcing."""
    total = 0
    lazy = 0
    for c in t.columns:
        col = _realized(c)
        if col is None:
            lazy += 1
            continue
        for a in _buffers(col):
            total += int(getattr(a, "nbytes", 0) or 0)
    return total, lazy


def _fence(t) -> float:
    """Drain pending device work on ``t``'s realized buffers; the wait
    (ms) is the device time still outstanding when the node's Python
    returned.  Tracers and unrealized lazy columns are skipped."""
    t0 = time.perf_counter()
    for c in t.columns:
        col = _realized(c)
        if col is None:
            continue
        for a in _buffers(col):
            bur = getattr(a, "block_until_ready", None)
            if bur is not None:
                try:
                    bur()
                except Exception:       # tracer / donated buffer: skip
                    pass
    return (time.perf_counter() - t0) * 1e3


def _validity_fraction(t) -> Optional[float]:
    """Valid-row density across nullable realized columns (one scalar
    sync per nullable column — each through ``syncs.scalar`` so the
    capture/replay tape carries it)."""
    import jax.numpy as jnp
    rows = t.num_rows
    if rows == 0:
        return None
    total = 0
    valid = 0
    for c in t.columns:
        col = _realized(c)
        if col is None or col.validity is None:
            continue
        total += rows
        valid += syncs.scalar(jnp.sum(col.validity))
    if total == 0:
        return None
    return valid / total


# --- EXPLAIN ANALYZE ---------------------------------------------------------


def explain_analyze(tree: ir.Plan, schemas: Optional[dict] = None,
                    tables: Optional[dict] = None, *, catalog=None,
                    stats=None) -> str:
    """Optimize ``tree``, execute it under an active profile, and render
    the annotated plan tree: estimated vs observed rows per node (>2×
    mispredictions flagged), output bytes, wall/device time, and the
    engine/AQE decision taken at each join.  Executes with
    ``record_stats=True``, so every observed cardinality feeds
    ``plan/stats.py`` — the misprediction IS corrected for the next
    optimize of the same shape.

    Pass ``tables`` + ``schemas`` (a ``TableCatalog`` is built) or an
    explicit ``catalog``.  Routes through the adaptive executor when
    ``SRJT_AQE`` is on, exactly like ``lower.execute``.  Profiling is
    force-enabled for the duration (this call IS the opt-in)."""
    from . import lower, rules
    if catalog is None:
        if tables is None or schemas is None:
            raise ir.PlanError(
                "explain_analyze needs tables+schemas or a catalog")
        catalog = lower.TableCatalog(tables, schemas)
    opt = tree
    opt_lines: list[str] = []
    if knobs.get("SRJT_PLAN_OPT"):
        res = rules.optimize(tree, schemas if schemas is not None
                             else catalog.schemas, stats=stats)
        opt = res.tree
        opt_lines = [f"applied {e.rule}: {e.detail}" for e in res.events]
    fp = ir.fingerprint(opt)
    prev = _enabled
    set_enabled(True)
    try:
        with metrics.query_span(f"explain_analyze:{fp[5:17]}"):
            with query(f"explain_analyze:{fp[5:17]}", fp) as prof:
                lower.execute(opt, catalog, record_stats=True)
    finally:
        set_enabled(prev)
    mode = "adaptive" if knobs.get("SRJT_AQE") else "static"
    lines = ["== EXPLAIN ANALYZE ==", f"plan: {fp}", f"mode: {mode}"]
    lines += opt_lines
    lines.append(prof.render())
    mis = prof.mispredictions()
    lines.append(f"{sum(1 for _ in prof.nodes())} node(s), "
                 f"wall {prof.wall_ms:.2f} ms, "
                 f"{len(mis)} misprediction(s) >{MISPREDICT_FACTOR:g}x")
    return "\n".join(lines)


# --- artifact pipeline -------------------------------------------------------


def _export_artifact(prof: QueryProfile) -> Optional[str]:
    """Write ``prof`` (plus the plan's compile-cost ledger entry) as one
    JSON file under ``SRJT_PROFILE_DIR``.  Atomic (tmp + replace), never
    raises — export failure is a counter, not a second failure."""
    global _artifact_seq
    try:
        out_dir = knobs.get("SRJT_PROFILE_DIR")
        if not out_dir:
            return None
        with _lock:
            _artifact_seq += 1
            seq = _artifact_seq
        doc = prof.as_dict()
        ledger = metrics.ledger_snapshot()
        if prof.fingerprint and prof.fingerprint in ledger:
            doc["compile_ledger"] = ledger[prof.fingerprint]
        safe = "".join(ch if ch.isalnum() or ch in "-_." else "_"
                       for ch in prof.name)[:64]
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(
            out_dir, f"profile-{safe}-{os.getpid()}-{seq}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, default=repr)
        os.replace(tmp, path)
        return path
    except Exception:
        try:
            if metrics.enabled():
                metrics.count("plan.profile.export_failed")
        except Exception:
            pass
        return None


# --- flight-recorder probe ---------------------------------------------------


def _flight_probe():
    """Partial node profiles of every in-flight profiled query — a
    deadline/SLO incident snapshot shows WHERE each stuck request was."""
    with _lock:
        profs = list(_inflight.items())
    if not profs:
        return None
    return {str(tid): p.as_dict(partial=True) for tid, p in profs}


flight.register_probe("plan.active_profile", _flight_probe)

# ops-layer sites (ops/join.py, ops/filter.py, ops/groupby.py,
# parquet/device_scan.py, rowconv/convert.py) report through
# ``metrics.profile_op`` — installing the hook here keeps plan/ out of
# their import graphs entirely
metrics._profile_op_hook = op_event
metrics._profile_stage_hook = stage
