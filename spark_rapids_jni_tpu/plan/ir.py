"""Logical plan IR: immutable relational plan trees (Catalyst analog).

Queries become small trees of frozen dataclass nodes — ``Scan``,
``Filter``, ``Project``, ``Join``, ``Aggregate``, ``Window``, ``Sort``,
``Limit`` — over typed expressions, instead of hand-written op-layer
Python (``models/tpcds.py``).  The rewrite engine (``plan/rules.py``)
rewrites these trees; ``plan/lower.py`` lowers them onto the existing ops
layer.

Design constraints:

* **Immutability**: every node and expression is a frozen dataclass with
  tuple-valued children, so rewrites share subtrees structurally and a
  node can key caches.
* **Name-based references**: columns are referenced by NAME, not index —
  projection pushdown renumbers physical columns freely without touching
  the tree above.
* **Stable fingerprints**: :func:`fingerprint` hashes the canonical form
  of a tree (conjunct order normalized, literals type-normalized), so two
  semantically-identical trees produced by different construction orders
  share one ``exec/plan_cache.py`` key.
"""

from __future__ import annotations

import functools
import hashlib
from dataclasses import dataclass, field, replace
from typing import Any, Optional, Tuple


class PlanError(ValueError):
    """Malformed plan tree: unknown column/table, ambiguous names, or an
    expression form the lowering does not implement."""


# --- expressions ------------------------------------------------------------


@dataclass(frozen=True)
class Expr:
    pass


@dataclass(frozen=True)
class Col(Expr):
    """Column reference by name."""
    name: str


@dataclass(frozen=True)
class Lit(Expr):
    """Scalar literal (int / float / str / bool)."""
    value: Any


@dataclass(frozen=True)
class Cmp(Expr):
    """``left <op> right`` with op in ``== != < <= > >=``; null rows
    compare False (validity ANDed into the mask, SQL-style)."""
    op: str
    left: Expr
    right: Expr


@dataclass(frozen=True)
class Between(Expr):
    """``lo <= col <= hi`` (either bound optional; ``hi_strict`` makes
    the upper bound exclusive) — the ``tpcds._range_mask`` shape."""
    col: Expr
    lo: Any = None
    hi: Any = None
    hi_strict: bool = False


@dataclass(frozen=True)
class And(Expr):
    parts: Tuple[Expr, ...]

    def __post_init__(self):
        object.__setattr__(self, "parts", tuple(self.parts))


@dataclass(frozen=True)
class Or(Expr):
    parts: Tuple[Expr, ...]

    def __post_init__(self):
        object.__setattr__(self, "parts", tuple(self.parts))


@dataclass(frozen=True)
class IsIn(Expr):
    """Null-safe membership: OR of null-safe equalities."""
    col: Expr
    values: Tuple[Any, ...]

    def __post_init__(self):
        object.__setattr__(self, "values", tuple(self.values))


@dataclass(frozen=True)
class ScalarAgg(Expr):
    """Whole-column scalar aggregate (``mean`` / ``sum``) usable as a
    comparison operand — stays a device scalar through lowering (no host
    pull, composes with capture/replay)."""
    fn: str
    arg: Expr


@dataclass(frozen=True)
class Mul(Expr):
    left: Expr
    right: Expr


def and_(parts) -> Optional[Expr]:
    """Conjunction of ``parts`` (flattened); None for an empty list."""
    flat: list[Expr] = []
    for p in parts:
        flat.extend(conjuncts(p))
    if not flat:
        return None
    return flat[0] if len(flat) == 1 else And(tuple(flat))


def conjuncts(expr: Optional[Expr]) -> list[Expr]:
    """Flatten nested ``And`` into a conjunct list (order-preserving)."""
    if expr is None:
        return []
    if isinstance(expr, And):
        out: list[Expr] = []
        for p in expr.parts:
            out.extend(conjuncts(p))
        return out
    return [expr]


def expr_columns(expr: Optional[Expr]) -> frozenset[str]:
    """All column names an expression references."""
    if expr is None:
        return frozenset()
    if isinstance(expr, Col):
        return frozenset((expr.name,))
    if isinstance(expr, (And, Or)):
        return frozenset().union(*(expr_columns(p) for p in expr.parts))
    if isinstance(expr, Cmp):
        return expr_columns(expr.left) | expr_columns(expr.right)
    if isinstance(expr, (Between, IsIn)):
        return expr_columns(expr.col)
    if isinstance(expr, ScalarAgg):
        return expr_columns(expr.arg)
    if isinstance(expr, Mul):
        return expr_columns(expr.left) | expr_columns(expr.right)
    return frozenset()


# --- plan nodes -------------------------------------------------------------


@dataclass(frozen=True)
class Plan:
    pass


def _tup(v):
    return None if v is None else tuple(v)


@dataclass(frozen=True)
class Scan(Plan):
    """Read a base table.  ``columns=None`` means the full schema;
    ``predicate`` is applied at the scan (and, on the file path, drives
    row-group pruning from footer statistics before decode)."""
    table: str
    columns: Optional[Tuple[str, ...]] = None
    predicate: Optional[Expr] = None

    def __post_init__(self):
        object.__setattr__(self, "columns", _tup(self.columns))


@dataclass(frozen=True)
class Filter(Plan):
    child: Plan
    predicate: Expr


@dataclass(frozen=True)
class Project(Plan):
    child: Plan
    columns: Tuple[str, ...]

    def __post_init__(self):
        object.__setattr__(self, "columns", tuple(self.columns))


@dataclass(frozen=True)
class Join(Plan):
    """Equi-join; output schema = left schema ++ right schema.

    ``engine`` optionally pins the physical build-index engine
    (``"dense"`` / ``"sorted"``) for THIS join — the adaptive executor
    (``plan/adaptive.py``) bakes observed-statistics engine flips into
    the tree through it.  ``None`` (the default, and the only value the
    front-end emits) keeps the ``ops/join_plan.py`` heuristic; both
    engines produce bit-identical results, so a pin only trades
    footprint for speed."""
    left: Plan
    right: Plan
    left_on: Tuple[str, ...]
    right_on: Tuple[str, ...]
    how: str = "inner"
    engine: Optional[str] = None

    def __post_init__(self):
        object.__setattr__(self, "left_on", tuple(self.left_on))
        object.__setattr__(self, "right_on", tuple(self.right_on))


#: name of the synthetic column grouping aggregates append (bit k set when
#: key k was aggregated away; MSB = first key — matches ``ops.groupby``).
GROUPING_ID = "grouping_id"


@dataclass(frozen=True)
class Aggregate(Plan):
    """GROUP BY ``keys``; ``aggs`` are ``(value_column, fn, out_name)``
    with fn from the ops groupby set (sum/mean/count/min/max/... plus
    ``nunique`` = COUNT(DISTINCT), single-agg only).

    ``grouping`` widens plain GROUP BY to multi-level grouping:
    ``"rollup"`` / ``"cube"`` derive their grouping sets from ``keys``;
    ``"sets"`` takes explicit ``grouping_sets`` — tuples of positions
    into ``keys`` (the ``ops.groupby_grouping_sets`` convention).  Any
    grouping spec appends a ``grouping_id`` int64 column to the output
    schema."""
    child: Plan
    keys: Tuple[str, ...]
    aggs: Tuple[Tuple[str, str, str], ...]
    grouping: Optional[str] = None              # None|"rollup"|"cube"|"sets"
    grouping_sets: Optional[Tuple[Tuple[int, ...], ...]] = None

    def __post_init__(self):
        object.__setattr__(self, "keys", tuple(self.keys))
        object.__setattr__(self, "aggs",
                           tuple(tuple(a) for a in self.aggs))
        if self.grouping_sets is not None:
            object.__setattr__(self, "grouping_sets",
                               tuple(tuple(s) for s in self.grouping_sets))
        if self.grouping not in (None, "rollup", "cube", "sets"):
            raise PlanError(f"unknown grouping spec {self.grouping!r}")
        if (self.grouping == "sets") != (self.grouping_sets is not None):
            raise PlanError("grouping_sets requires grouping='sets' "
                            "(and vice versa)")


@dataclass(frozen=True)
class FusedJoinAggregate(Plan):
    """Rule-emitted fusion of ``Aggregate(Join(left, right))`` — lowers to
    ``ops.join_plan.join_aggregate`` (no pair materialization).  Not meant
    to be written by hand: the ``fuse_join_aggregate`` rule detects the
    shape."""
    left: Plan
    right: Plan
    left_on: Tuple[str, ...]
    right_on: Tuple[str, ...]
    keys: Tuple[str, ...]
    aggs: Tuple[Tuple[str, str, str], ...]
    how: str = "inner"
    engine: Optional[str] = None     # see Join.engine

    def __post_init__(self):
        object.__setattr__(self, "left_on", tuple(self.left_on))
        object.__setattr__(self, "right_on", tuple(self.right_on))
        object.__setattr__(self, "keys", tuple(self.keys))
        object.__setattr__(self, "aggs",
                           tuple(tuple(a) for a in self.aggs))


@dataclass(frozen=True)
class Window(Plan):
    """Append one window-function column named ``out``
    (``fn`` in row_number/rank/dense_rank/running_sum/lag over
    ``ops.window``).  ``ascending`` optionally orders each order key
    descending (parallel to ``order_by``); ``value`` names the input
    column for value-carrying fns (running_sum/lag).  Both default to
    None and stay out of the fingerprint when unset, so pre-existing
    rank/row_number trees keep their historical cache keys."""
    child: Plan
    fn: str
    partition_by: Tuple[str, ...]
    order_by: Tuple[str, ...]
    out: str
    ascending: Optional[Tuple[bool, ...]] = None
    value: Optional[str] = None

    def __post_init__(self):
        object.__setattr__(self, "partition_by", tuple(self.partition_by))
        object.__setattr__(self, "order_by", tuple(self.order_by))
        object.__setattr__(self, "ascending", _tup(self.ascending))


@dataclass(frozen=True)
class Union(Plan):
    """UNION ALL: positional concatenation of ``parts`` (each the same
    arity and per-position dtype); output columns are renamed to
    ``names`` (the first arm's aliases, SQL-style)."""
    parts: Tuple[Plan, ...]
    names: Tuple[str, ...]

    def __post_init__(self):
        object.__setattr__(self, "parts", tuple(self.parts))
        object.__setattr__(self, "names", tuple(self.names))
        if len(self.parts) < 2:
            raise PlanError("union needs at least two parts")


@dataclass(frozen=True)
class Distinct(Plan):
    """Row-level DISTINCT over the child's full schema (lowers to the
    grouped-by-all-columns path; output order is the key sort order)."""
    child: Plan


@dataclass(frozen=True)
class Sort(Plan):
    child: Plan
    keys: Tuple[str, ...]
    ascending: Optional[Tuple[bool, ...]] = None

    def __post_init__(self):
        object.__setattr__(self, "keys", tuple(self.keys))
        object.__setattr__(self, "ascending", _tup(self.ascending))


@dataclass(frozen=True)
class Limit(Plan):
    child: Plan
    n: int


# --- tree plumbing ----------------------------------------------------------


def children(node: Plan) -> tuple[Plan, ...]:
    if isinstance(node, (Join, FusedJoinAggregate)):
        return (node.left, node.right)
    if isinstance(node, Union):
        return node.parts
    if isinstance(node, (Filter, Project, Aggregate, Window, Sort, Limit,
                         Distinct)):
        return (node.child,)
    return ()


def with_children(node: Plan, kids: tuple[Plan, ...]) -> Plan:
    if isinstance(node, (Join, FusedJoinAggregate)):
        return replace(node, left=kids[0], right=kids[1])
    if isinstance(node, Union):
        return replace(node, parts=tuple(kids))
    if isinstance(node, (Filter, Project, Aggregate, Window, Sort, Limit,
                         Distinct)):
        return replace(node, child=kids[0])
    return node


def transform_up(node: Plan, fn) -> Plan:
    """Bottom-up rewrite: children first, then ``fn(node)`` (None = keep).
    Shares unchanged subtrees (identity-preserving when nothing fires)."""
    kids = children(node)
    new_kids = tuple(transform_up(k, fn) for k in kids)
    if any(nk is not k for nk, k in zip(new_kids, kids)):
        node = with_children(node, new_kids)
    out = fn(node)
    return node if out is None else out


def walk(node: Plan):
    """Pre-order node iterator."""
    yield node
    for k in children(node):
        yield from walk(k)


# --- schema propagation -----------------------------------------------------


def schema_of(node: Plan, schemas: dict) -> tuple[str, ...]:
    """Output column names of ``node``; ``schemas`` maps base-table name →
    column-name sequence.  Validates column references on the way up."""
    if isinstance(node, Scan):
        try:
            full = tuple(schemas[node.table])
        except (KeyError, TypeError):
            raise PlanError(f"unknown table {node.table!r} "
                            f"(catalog: {sorted(schemas or ())})")
        cols = full if node.columns is None else node.columns
        _need(cols, full, f"scan({node.table})")
        _need(expr_columns(node.predicate), cols,
              f"scan({node.table}) predicate")
        return cols
    if isinstance(node, Filter):
        sch = schema_of(node.child, schemas)
        _need(expr_columns(node.predicate), sch, "filter predicate")
        return sch
    if isinstance(node, Project):
        sch = schema_of(node.child, schemas)
        _need(node.columns, sch, "project")
        return node.columns
    if isinstance(node, Join):
        ls = schema_of(node.left, schemas)
        rs = schema_of(node.right, schemas)
        _need(node.left_on, ls, "join left keys")
        _need(node.right_on, rs, "join right keys")
        if node.how in ("semi", "anti"):
            return ls                       # right side filters, never lands
        dup = set(ls) & set(rs)
        if dup:
            raise PlanError(f"join sides share column names {sorted(dup)}")
        return ls + rs
    if isinstance(node, Aggregate):
        sch = schema_of(node.child, schemas)
        _need(node.keys, sch, "aggregate keys")
        _need([a[0] for a in node.aggs], sch, "aggregate values")
        out = node.keys + tuple(a[2] for a in node.aggs)
        return out + (GROUPING_ID,) if node.grouping else out
    if isinstance(node, FusedJoinAggregate):
        ls = schema_of(node.left, schemas)
        rs = schema_of(node.right, schemas)
        joined = ls + rs
        _need(node.keys, joined, "fused aggregate keys")
        _need([a[0] for a in node.aggs], joined, "fused aggregate values")
        return node.keys + tuple(a[2] for a in node.aggs)
    if isinstance(node, Window):
        sch = schema_of(node.child, schemas)
        _need(node.partition_by + node.order_by, sch, "window keys")
        if node.value is not None:
            _need((node.value,), sch, "window value")
        return sch + (node.out,)
    if isinstance(node, Union):
        arity = len(node.names)
        for i, p in enumerate(node.parts):
            psch = schema_of(p, schemas)
            if len(psch) != arity:
                raise PlanError(
                    f"union arm {i} has {len(psch)} columns, expected "
                    f"{arity} ({list(node.names)})")
        return node.names
    if isinstance(node, Distinct):
        return schema_of(node.child, schemas)
    if isinstance(node, (Sort, Limit)):
        sch = schema_of(node.child, schemas)
        if isinstance(node, Sort):
            _need(node.keys, sch, "sort keys")
        return sch
    raise PlanError(f"unknown plan node {type(node).__name__}")


def _need(names, available, what: str):
    missing = [n for n in names if n not in available]
    if missing:
        raise PlanError(f"{what}: unknown column(s) {missing} "
                        f"(have {list(available)})")


# --- stable structural fingerprint ------------------------------------------


def _canon_lit(v) -> str:
    if isinstance(v, bool):
        return f"b:{v}"
    if isinstance(v, str):
        return f"s:{v}"
    if hasattr(v, "item"):          # numpy scalar → python scalar
        v = v.item()
    if isinstance(v, int):
        return f"i:{v}"
    if isinstance(v, float):
        return f"f:{v!r}"
    return f"x:{v!r}"


def _sexp_expr(e: Optional[Expr]) -> str:
    if e is None:
        return "-"
    if isinstance(e, Col):
        return f"c({e.name})"
    if isinstance(e, Lit):
        return f"l({_canon_lit(e.value)})"
    if isinstance(e, Cmp):
        return f"cmp({e.op},{_sexp_expr(e.left)},{_sexp_expr(e.right)})"
    if isinstance(e, Between):
        return (f"between({_sexp_expr(e.col)},"
                f"{_canon_lit(e.lo) if e.lo is not None else '-'},"
                f"{_canon_lit(e.hi) if e.hi is not None else '-'},"
                f"{int(e.hi_strict)})")
    if isinstance(e, (And, Or)):
        tag = "and" if isinstance(e, And) else "or"
        # conjunct/disjunct order is semantically irrelevant: normalize
        return f"{tag}({','.join(sorted(_sexp_expr(p) for p in e.parts))})"
    if isinstance(e, IsIn):
        vals = ",".join(sorted(_canon_lit(v) for v in e.values))
        return f"isin({_sexp_expr(e.col)},[{vals}])"
    if isinstance(e, ScalarAgg):
        return f"sagg({e.fn},{_sexp_expr(e.arg)})"
    if isinstance(e, Mul):
        return f"mul({_sexp_expr(e.left)},{_sexp_expr(e.right)})"
    raise PlanError(f"unknown expression {type(e).__name__}")


def _sexp(node: Plan) -> str:
    if isinstance(node, Scan):
        cols = "*" if node.columns is None else ",".join(node.columns)
        return (f"scan({node.table},[{cols}],"
                f"{_sexp_expr(node.predicate)})")
    if isinstance(node, Filter):
        return f"filter({_sexp(node.child)},{_sexp_expr(node.predicate)})"
    if isinstance(node, Project):
        return f"project({_sexp(node.child)},[{','.join(node.columns)}])"
    if isinstance(node, Join):
        keys = ",".join(f"{l}={r}"
                        for l, r in zip(node.left_on, node.right_on))
        # engine pin participates only when SET: unpinned trees (every
        # tree the front-end builds) keep their historical fingerprints,
        # while adaptive-decided trees get distinct cache keys for free
        eng = "" if node.engine is None else f",e={node.engine}"
        return (f"join({node.how},{_sexp(node.left)},{_sexp(node.right)},"
                f"[{keys}]{eng})")
    if isinstance(node, Aggregate):
        aggs = ",".join(f"{fn}({c})>{o}" for c, fn, o in node.aggs)
        # grouping spec participates only when SET: plain GROUP BY trees
        # keep their historical fingerprints
        if node.grouping is None:
            grp = ""
        elif node.grouping == "sets":
            sets = ";".join(",".join(map(str, s))
                            for s in node.grouping_sets)
            grp = f",g=sets[{sets}]"
        else:
            grp = f",g={node.grouping}"
        return (f"agg({_sexp(node.child)},[{','.join(node.keys)}],"
                f"[{aggs}]{grp})")
    if isinstance(node, FusedJoinAggregate):
        keys = ",".join(f"{l}={r}"
                        for l, r in zip(node.left_on, node.right_on))
        aggs = ",".join(f"{fn}({c})>{o}" for c, fn, o in node.aggs)
        eng = "" if node.engine is None else f",e={node.engine}"
        return (f"joinagg({node.how},{_sexp(node.left)},"
                f"{_sexp(node.right)},[{keys}],[{','.join(node.keys)}],"
                f"[{aggs}]{eng})")
    if isinstance(node, Window):
        # ascending/value participate only when SET (fingerprint
        # back-compat, same discipline as Join.engine)
        asc = ("" if node.ascending is None
               else ",a=" + "".join("1" if a else "0"
                                    for a in node.ascending))
        val = "" if node.value is None else f",v={node.value}"
        return (f"window({_sexp(node.child)},{node.fn},"
                f"[{','.join(node.partition_by)}],"
                f"[{','.join(node.order_by)}],{node.out}{asc}{val})")
    if isinstance(node, Union):
        parts = ",".join(_sexp(p) for p in node.parts)
        return f"union([{parts}],[{','.join(node.names)}])"
    if isinstance(node, Distinct):
        return f"distinct({_sexp(node.child)})"
    if isinstance(node, Sort):
        asc = ("-" if node.ascending is None
               else "".join("1" if a else "0" for a in node.ascending))
        return f"sort({_sexp(node.child)},[{','.join(node.keys)}],{asc})"
    if isinstance(node, Limit):
        return f"limit({_sexp(node.child)},{node.n})"
    raise PlanError(f"unknown plan node {type(node).__name__}")


@functools.lru_cache(maxsize=4096)
def fingerprint(node: Plan) -> str:
    """Stable structural fingerprint of a plan tree — usable directly as
    an ``exec/plan_cache.py`` / ``exec/scheduler.py`` request name.
    Semantically-identical trees (reordered conjuncts, numpy vs python
    literals) share one fingerprint."""
    return "plan:" + hashlib.sha256(
        _sexp(node).encode()).hexdigest()[:32]


# --- rendering (EXPLAIN) ----------------------------------------------------


def expr_str(e: Optional[Expr]) -> str:
    if e is None:
        return "true"
    if isinstance(e, Col):
        return e.name
    if isinstance(e, Lit):
        return repr(e.value)
    if isinstance(e, Cmp):
        return f"({expr_str(e.left)} {e.op} {expr_str(e.right)})"
    if isinstance(e, Between):
        lo = "" if e.lo is None else f"{e.lo!r} <= "
        hi = "" if e.hi is None else f" {'<' if e.hi_strict else '<='} {e.hi!r}"
        return f"({lo}{expr_str(e.col)}{hi})"
    if isinstance(e, And):
        return " AND ".join(expr_str(p) for p in e.parts)
    if isinstance(e, Or):
        return "(" + " OR ".join(expr_str(p) for p in e.parts) + ")"
    if isinstance(e, IsIn):
        return f"{expr_str(e.col)} IN {list(e.values)!r}"
    if isinstance(e, ScalarAgg):
        return f"{e.fn}({expr_str(e.arg)})"
    if isinstance(e, Mul):
        return f"{expr_str(e.left)} * {expr_str(e.right)}"
    return repr(e)


def _node_line(node: Plan) -> str:
    if isinstance(node, Scan):
        cols = "*" if node.columns is None else f"[{', '.join(node.columns)}]"
        pred = ("" if node.predicate is None
                else f" predicate={expr_str(node.predicate)}")
        return f"Scan {node.table} columns={cols}{pred}"
    if isinstance(node, Filter):
        return f"Filter {expr_str(node.predicate)}"
    if isinstance(node, Project):
        return f"Project [{', '.join(node.columns)}]"
    if isinstance(node, Join):
        keys = ", ".join(f"{l} = {r}"
                         for l, r in zip(node.left_on, node.right_on))
        eng = "" if node.engine is None else f" engine={node.engine}"
        return f"Join {node.how} on ({keys}){eng}"
    if isinstance(node, Aggregate):
        aggs = ", ".join(f"{fn}({c}) AS {o}" for c, fn, o in node.aggs)
        grp = "" if node.grouping is None else f" grouping={node.grouping}"
        return (f"Aggregate keys=[{', '.join(node.keys)}] "
                f"aggs=[{aggs}]{grp}")
    if isinstance(node, FusedJoinAggregate):
        keys = ", ".join(f"{l} = {r}"
                         for l, r in zip(node.left_on, node.right_on))
        aggs = ", ".join(f"{fn}({c}) AS {o}" for c, fn, o in node.aggs)
        eng = "" if node.engine is None else f" engine={node.engine}"
        return (f"FusedJoinAggregate {node.how} on ({keys}) "
                f"keys=[{', '.join(node.keys)}] aggs=[{aggs}]{eng}")
    if isinstance(node, Window):
        val = "" if node.value is None else f" value={node.value}"
        return (f"Window {node.fn}{val}"
                f" partition=[{', '.join(node.partition_by)}]"
                f" order=[{', '.join(node.order_by)}] AS {node.out}")
    if isinstance(node, Union):
        return f"Union [{', '.join(node.names)}]"
    if isinstance(node, Distinct):
        return "Distinct"
    if isinstance(node, Sort):
        return f"Sort keys=[{', '.join(node.keys)}]"
    if isinstance(node, Limit):
        return f"Limit {node.n}"
    return type(node).__name__


def render(node: Plan, indent: int = 0) -> str:
    """Indented one-node-per-line tree rendering (EXPLAIN body)."""
    lines = ["  " * indent + _node_line(node)]
    for k in children(node):
        lines.append(render(k, indent + 1))
    return "\n".join(lines)
