"""Cardinality statistics feeding the join-reorder rule.

Two sources, in priority order:

1. **Exact observations**: the executor (``plan/lower.py``) records every
   plan node's output row count (static shapes make this free) keyed by
   the node's structural fingerprint.  Recurring queries — the serving
   workload — reorder from exact cardinalities on the second sighting.
2. **Metrics priors**: for join-shaped nodes never seen before, fall back
   to the process-wide ``join.match_rows`` histogram that
   ``utils/metrics.py`` already collects on every join — a coarse prior,
   but enough to rank a filtered dimension against an unfiltered one.

When neither source knows a subtree, ``rows_for`` returns ``None`` and
the reorder rule rejects (a deliberate no-op: never reorder blind).
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from ..utils import metrics
from . import ir

_MAX_ENTRIES = 4096


class CardinalityStats:
    """Bounded fingerprint → observed-row-count store (thread-safe)."""

    def __init__(self, max_entries: int = _MAX_ENTRIES):
        self._lock = threading.Lock()
        self._rows: OrderedDict[str, int] = OrderedDict()
        self._max = max_entries

    def observe(self, fp: str, rows: int) -> None:
        with self._lock:
            self._rows[fp] = int(rows)
            self._rows.move_to_end(fp)
            while len(self._rows) > self._max:
                self._rows.popitem(last=False)

    def rows_for(self, node: ir.Plan):
        """Estimated output rows of ``node``, or None when unknowable."""
        with self._lock:
            got = self._rows.get(ir.fingerprint(node))
        if got is not None:
            return float(got)
        if isinstance(node, (ir.Join, ir.FusedJoinAggregate)):
            return self._join_prior()
        return None

    @staticmethod
    def _join_prior():
        # mean of the join.match_rows histogram — the coarse process-wide
        # prior for "how big do joins come out around here"
        snap = metrics.snapshot()
        hist = snap.get("histograms", {}).get("join.match_rows")
        if hist and hist.get("count"):
            return float(hist["total"]) / float(hist["count"])
        return None

    def clear(self) -> None:
        with self._lock:
            self._rows.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)


#: process-wide store the executor feeds; pass to ``rules.optimize`` to
#: let recurring queries reorder from observed cardinalities.
GLOBAL = CardinalityStats()
