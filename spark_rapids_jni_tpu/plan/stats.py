"""Cardinality statistics feeding the join-reorder rule.

Two sources, in priority order:

1. **Exact observations**: the executor (``plan/lower.py``) records every
   plan node's output row count (static shapes make this free) keyed by
   the node's structural fingerprint.  Recurring queries — the serving
   workload — reorder from exact cardinalities on the second sighting.
2. **Metrics priors**: for join-shaped nodes never seen before, fall back
   to the process-wide ``join.match_rows`` histogram that
   ``utils/metrics.py`` already collects on every join — a coarse prior,
   but enough to rank a filtered dimension against an unfiltered one.

When neither source knows a subtree, ``rows_for`` returns ``None`` and
the reorder rule rejects (a deliberate no-op: never reorder blind).
"""

from __future__ import annotations

import os
from collections import OrderedDict
from typing import Optional

from ..analysis import sanitize
from ..utils import knobs, metrics
from . import ir

_MAX_ENTRIES = 4096


def _default_cap() -> int:
    try:
        return max(knobs.get("SRJT_PLAN_STATS_CAP"), 1)
    except ValueError:
        return _MAX_ENTRIES


class CardinalityStats:
    """Bounded fingerprint → observed-row-count LRU (thread-safe).

    Long-running serving processes see an unbounded stream of distinct
    fingerprints; the cap (``SRJT_PLAN_STATS_CAP``, default 4096) bounds
    the store and *reads refresh recency* — the fingerprints recurring
    queries actually reorder on survive one-off churn.  Evictions land on
    the ``plan.stats.evictions`` counter."""

    def __init__(self, max_entries: Optional[int] = None):
        self._lock = sanitize.tracked_lock("plan.stats")
        self._rows: OrderedDict[str, int] = OrderedDict()
        self._max = _default_cap() if max_entries is None else max(
            int(max_entries), 1)
        self._evictions = 0

    def observe(self, fp: str, rows: int) -> None:
        evicted = 0
        with self._lock:
            self._rows[fp] = int(rows)
            self._rows.move_to_end(fp)
            while len(self._rows) > self._max:
                self._rows.popitem(last=False)
                evicted += 1
            self._evictions += evicted
        if evicted and metrics.recording():
            metrics.count("plan.stats.evictions", evicted)

    @property
    def evictions(self) -> int:
        with self._lock:
            return self._evictions

    def rows_for(self, node: ir.Plan):
        """Estimated output rows of ``node``, or None when unknowable."""
        fp = ir.fingerprint(node)
        with self._lock:
            got = self._rows.get(fp)
            if got is not None:
                self._rows.move_to_end(fp)    # a read IS a use (LRU)
        if got is not None:
            return float(got)
        if isinstance(node, (ir.Join, ir.FusedJoinAggregate)):
            return self._join_prior()
        return None

    @staticmethod
    def _join_prior():
        # mean of the join.match_rows histogram — the coarse process-wide
        # prior for "how big do joins come out around here"
        snap = metrics.snapshot()
        hist = snap.get("histograms", {}).get("join.match_rows")
        if hist and hist.get("count"):
            return float(hist["total"]) / float(hist["count"])
        return None

    def clear(self) -> None:
        with self._lock:
            self._rows.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)


#: process-wide store the executor feeds; pass to ``rules.optimize`` to
#: let recurring queries reorder from observed cardinalities.
GLOBAL = CardinalityStats()
