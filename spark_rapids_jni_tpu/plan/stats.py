"""Cardinality statistics feeding the join-reorder rule.

Two sources, in priority order:

1. **Exact observations**: the executor (``plan/lower.py``) records every
   plan node's output row count (static shapes make this free) keyed by
   the node's structural fingerprint.  Recurring queries — the serving
   workload — reorder from exact cardinalities on the second sighting.
2. **Metrics priors**: for join-shaped nodes never seen before, fall back
   to the process-wide ``join.match_rows`` histogram that
   ``utils/metrics.py`` already collects on every join — a coarse prior,
   but enough to rank a filtered dimension against an unfiltered one.

When neither source knows a subtree, ``rows_for`` returns ``None`` and
the reorder rule rejects (a deliberate no-op: never reorder blind).

With ``SRJT_PLAN_STATS_PATH`` set, the process-wide store additionally
persists to a JSON sidecar: loaded lazily on first use (a fresh process
re-optimizes with warm priors instead of cold defaults) and written back
atomically (tmp + ``os.replace``) at interpreter exit.  A corrupt or
missing sidecar is silently treated as empty — stats are advisory.
"""

from __future__ import annotations

import atexit
import json
import os
import tempfile
from collections import OrderedDict
from typing import Optional

from ..analysis import sanitize
from ..utils import knobs, metrics
from . import ir

_MAX_ENTRIES = 4096


def atomic_write_json(path: str, doc) -> bool:
    """Atomically write ``doc`` as JSON to ``path`` (tmp in the target
    directory + ``os.replace``, never a torn file).  Returns False on any
    OS failure — shared by the stats sidecar and the AOT artifact store
    (``exec/artifacts.py``), both of which treat persistence as
    best-effort."""
    try:
        d = os.path.dirname(os.path.abspath(path)) or "."
        fd, tmp = tempfile.mkstemp(prefix=".sidecar.", dir=d)
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(doc, f, separators=(",", ":"))
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    except OSError:
        return False
    return True


def _default_cap() -> int:
    try:
        return max(knobs.get("SRJT_PLAN_STATS_CAP"), 1)
    except ValueError:
        return _MAX_ENTRIES


class CardinalityStats:
    """Bounded fingerprint → observed-row-count LRU (thread-safe).

    Long-running serving processes see an unbounded stream of distinct
    fingerprints; the cap (``SRJT_PLAN_STATS_CAP``, default 4096) bounds
    the store and *reads refresh recency* — the fingerprints recurring
    queries actually reorder on survive one-off churn.  Evictions land on
    the ``plan.stats.evictions`` counter."""

    def __init__(self, max_entries: Optional[int] = None):
        self._lock = sanitize.tracked_lock("plan.stats")
        self._rows: OrderedDict[str, int] = OrderedDict()
        self._max = _default_cap() if max_entries is None else max(
            int(max_entries), 1)
        self._evictions = 0

    def observe(self, fp: str, rows: int) -> None:
        evicted = 0
        with self._lock:
            self._rows[fp] = int(rows)
            self._rows.move_to_end(fp)
            while len(self._rows) > self._max:
                self._rows.popitem(last=False)
                evicted += 1
            self._evictions += evicted
        if evicted and metrics.recording():
            metrics.count("plan.stats.evictions", evicted)

    @property
    def evictions(self) -> int:
        with self._lock:
            return self._evictions

    def rows_for(self, node: ir.Plan):
        """Estimated output rows of ``node``, or None when unknowable."""
        fp = ir.fingerprint(node)
        with self._lock:
            got = self._rows.get(fp)
            if got is not None:
                self._rows.move_to_end(fp)    # a read IS a use (LRU)
        if got is not None:
            return float(got)
        if isinstance(node, (ir.Join, ir.FusedJoinAggregate)):
            return self._join_prior()
        return None

    @staticmethod
    def _join_prior():
        # mean of the join.match_rows histogram — the coarse process-wide
        # prior for "how big do joins come out around here"
        snap = metrics.snapshot()
        hist = snap.get("histograms", {}).get("join.match_rows")
        if hist and hist.get("count"):
            return float(hist["total"]) / float(hist["count"])
        return None

    def clear(self) -> None:
        with self._lock:
            self._rows.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)

    # --- JSON sidecar (SRJT_PLAN_STATS_PATH) -----------------------------

    def load_sidecar(self, path: str) -> int:
        """Merge fingerprint → rows entries from ``path`` (oldest-first,
        so live observations outrank persisted ones in the LRU).  Returns
        the number of entries merged; any read/parse failure counts as an
        empty sidecar."""
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
            entries = doc.get("rows", {})
            if not isinstance(entries, dict):
                return 0
        except (OSError, ValueError):
            return 0
        n = 0
        with self._lock:
            for fp, rows in entries.items():
                if not isinstance(fp, str) or fp in self._rows:
                    continue
                try:
                    rows = int(rows)
                except (TypeError, ValueError):
                    continue
                self._rows[fp] = rows
                self._rows.move_to_end(fp, last=False)
                n += 1
            while len(self._rows) > self._max:
                self._rows.popitem(last=False)
        if n and metrics.recording():
            metrics.count("plan.stats.sidecar_loaded", n)
        return n

    def save_sidecar(self, path: str) -> bool:
        """Atomically write the store to ``path`` (tmp + ``os.replace``,
        never a torn file).  Returns False on any OS failure — persistence
        is best-effort, stats are advisory."""
        with self._lock:
            snap = dict(self._rows)
        return atomic_write_json(path, {"version": 1, "rows": snap})


#: process-wide store the executor feeds; pass to ``rules.optimize`` to
#: let recurring queries reorder from observed cardinalities.
GLOBAL = CardinalityStats()

_sidecar_loaded = False


def ensure_sidecar_loaded() -> None:
    """Lazily merge the ``SRJT_PLAN_STATS_PATH`` sidecar into ``GLOBAL``
    (once per process; callers invoke before reading priors)."""
    global _sidecar_loaded
    if _sidecar_loaded:
        return
    _sidecar_loaded = True
    path = knobs.get("SRJT_PLAN_STATS_PATH")
    if path:
        GLOBAL.load_sidecar(path)


@atexit.register
def _save_sidecar_at_exit() -> None:
    # knob re-read at exit: tests that set the env var mid-process and
    # processes that never touched stats both do the right thing
    path = knobs.get("SRJT_PLAN_STATS_PATH")
    if path and len(GLOBAL):
        GLOBAL.save_sidecar(path)
