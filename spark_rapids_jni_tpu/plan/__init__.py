"""Relational plan IR + rule-based optimizer + lowering.

Queries become immutable plan trees (``plan.ir``), a fixpoint rewrite
engine pushes projections/filters into the parquet scan, reorders joins
from observed cardinalities, and detects join→aggregate fusion
(``plan.rules``), and the lowering (``plan.lower``) emits the exact
hand-fused op sequence — bit-identical results, composing unchanged with
capture/replay and the serving runtime.
"""

from . import adaptive, ir, lower, profile, rules, stats
from .adaptive import (AdaptiveReport, compile_adaptive_plan,
                       execute_adaptive, explain_adaptive)
from .ir import (GROUPING_ID, Aggregate, And, Between, Cmp, Col, Distinct,
                 Filter, FusedJoinAggregate, IsIn, Join, Limit, Lit, Mul, Or,
                 Plan, PlanError, Project, ScalarAgg, Scan, Sort, Union,
                 Window, expr_columns, fingerprint, render, schema_of)
from .lower import (FileCatalog, TableCatalog, compile_plan, execute,
                    rowgroup_conditions)
from .profile import NodeProfile, QueryProfile, explain_analyze
from .rules import DEFAULT_RULES, OptimizeResult, explain, optimize
from .stats import GLOBAL as GLOBAL_STATS
from .stats import CardinalityStats

__all__ = [
    "ir", "lower", "rules", "stats", "adaptive", "profile",
    "NodeProfile", "QueryProfile", "explain_analyze",
    "AdaptiveReport", "compile_adaptive_plan", "execute_adaptive",
    "explain_adaptive",
    "Plan", "PlanError", "Scan", "Filter", "Project", "Join", "Aggregate",
    "FusedJoinAggregate", "Window", "Sort", "Limit", "Union", "Distinct",
    "GROUPING_ID",
    "Col", "Lit", "Cmp", "Between", "And", "Or", "IsIn", "ScalarAgg", "Mul",
    "schema_of", "fingerprint", "render", "expr_columns",
    "optimize", "explain", "DEFAULT_RULES", "OptimizeResult",
    "compile_plan", "execute", "TableCatalog", "FileCatalog",
    "rowgroup_conditions", "CardinalityStats", "GLOBAL_STATS",
]
