"""Adaptive query execution: stage-wise runtime re-optimization.

The static optimizer (``plan/rules.py``) fires once, before execution,
on whatever priors ``plan/stats.py`` has accumulated.  This module
closes Spark-AQE's loop instead: the lowered tree executes **stage by
stage** — a stage boundary at every join / aggregate barrier, exactly
where intermediate tables materialize — and between stages the
*observed* runtime facts feed back into the not-yet-executed remainder:

* **replan** — a left-deep inner-join chain ending in a
  ``FusedJoinAggregate`` re-orders its pending dimension joins on the
  dimensions' *actual post-filter* row counts (static shapes make those
  free) instead of ``CardinalityStats`` priors.  Restricted to shapes
  where the result is provably bit-identical: the aggregated output is
  sorted by group key (order-insensitive) and every aggregate is exact
  (non-float inputs, no first/last), so any join order produces the
  same bytes.
* **engine_flip** — each join pre-probes the *materialized* build side
  (valid-key count, key window) plus the probe-side row count and flips
  the dense↔sorted engine when the observed statistics disagree with
  the lowering-time heuristic.  Executed through the existing
  ``ops/join_plan.force_engine`` seam, so every variant stays
  bit-identical; an ambient force (scheduler degradation,
  ``SRJT_JOIN_ENGINE``) always wins over an adaptive pin.
* **skew** — when the dense window is chosen, the same pass computes the
  build-index CSR histogram's hottest run.  On this local path the
  signal is advisory (``plan.aqe.skew_split.advisory`` + report detail);
  the *acting* consumer is the repartition path
  (``parallel/repartition_join.py``), which salts skewed hot keys into
  sub-joins when the measured per-partition need exceeds
  ``SRJT_AQE_SKEW_FACTOR`` × the mean.

Capture/replay discipline — the load-bearing invariant: every adaptive
decision derives ONLY from (a) intermediate-table ``num_rows`` (static
Python ints, identical under replay because compaction sizes come from
the tape) and (b) ``syncs.scalar`` reads (recorded on capture, popped on
replay).  Capture and replay therefore take the same host branches and
the tape stays aligned — decisions simply execute inline on every run,
no decided-plan state machine.  All probe syncs are unconditional on the
reached path (never gated on metrics state).

Plan-cache composition: ``compile_adaptive_plan`` tags its qfn with
``aqe_variant``, which ``exec/plan_cache.get_or_compile`` folds into the
cache key — adaptive and static compiles of the same tree never share
(or thrash) an entry.

Everything is behind ``SRJT_AQE`` (default off): ``lower.execute`` /
``lower.compile_plan`` route here only when the knob is on, so the off
path is byte-for-byte the static executor.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import NamedTuple, Optional

import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..ops import join_plan
from ..utils import flight, knobs, metrics, syncs
from . import ir, lower, profile
from . import stats as plan_stats

#: observed rows > this factor × the prior estimate, on a stage where a
#: decision fired → flight-recorder ``aqe_regression`` incident
REGRESSION_FACTOR = 2.0

#: exact (order-insensitive) aggregate functions over non-float inputs;
#: first/last are input-order-sensitive by definition and float sums
#: reassociate, so neither may be reordered across
_REORDERABLE_AGGS = ("sum", "count", "min", "max", "mean")


def enabled() -> bool:
    return bool(knobs.get("SRJT_AQE"))


# --- decision / stage records (the EXPLAIN payload) --------------------------


@dataclass(frozen=True)
class Decision:
    kind: str            # "replan" | "engine_flip" | "skew_advisory"
    detail: str


@dataclass
class StageRecord:
    """One barrier-node stage: what the priors predicted, what actually
    came out, and which runtime rules fired in between."""
    index: int
    node: str                          # EXPLAIN line of the barrier node
    est_rows: Optional[float] = None   # prior estimate (None = unknown)
    rows: Optional[int] = None         # observed output rows
    decisions: list = field(default_factory=list)


@dataclass
class AdaptiveReport:
    stages: list = field(default_factory=list)

    def decisions(self) -> list:
        return [d for s in self.stages for d in s.decisions]

    def render(self) -> str:
        lines = ["== Adaptive execution =="]
        if not self.stages:
            lines.append("(no barrier stages)")
        for s in self.stages:
            est = "?" if s.est_rows is None else f"{s.est_rows:.0f}"
            lines.append(f"stage {s.index}: {s.node}")
            lines.append(f"  est={est} rows → observed={s.rows} rows")
            for d in s.decisions:
                lines.append(f"  fired    {d.kind}: {d.detail}")
        n = len(self.decisions())
        lines.append(f"({n} adaptive decision(s))")
        return "\n".join(lines)


# --- engine / skew probe -----------------------------------------------------


class _Probe(NamedTuple):
    engine: Optional[str]   # pin to apply ("dense"/"sorted"), None = agree
    detail: str
    skew: Optional[dict]    # skew_stats-shaped dict when dense + skewed


def _probe_engine(node, kids) -> Optional[_Probe]:
    """Observed-statistics engine choice for one Join/FusedJoinAggregate,
    or None when the key shape never qualifies for the dense engine.

    Syncs the build lane's valid count and key window (3 scalars — the
    same values ``_build_index`` would sync) *before* the join runs, so
    the index is built directly in the decided kind.  The adaptive rule
    widens the static span limit by the observed probe-side row count:
    a dense LUT is worth building whenever the probe side amortizes it,
    even when the build side alone would not
    (``span ≤ max(2·n_valid, FLOOR, probe_rows)``, still capped).
    """
    (lt, ln), (rt, rn) = kids
    try:
        lon = [ln.index(c) for c in node.left_on]
        ron = [rn.index(c) for c in node.right_on]
    except ValueError:
        return None
    plan = join_plan.plan_keys([lt[i] for i in lon], [rt[i] for i in ron])
    if plan.mode not in ("single", "composite") or not plan.dense_ok:
        return None
    n = int(plan.rdata.shape[0])
    if n == 0:
        return None
    # unconditional scalar syncs (capture/replay tape alignment)
    if plan.rvalid is None:
        n_valid = n
        kmin = syncs.scalar(jnp.min(plan.rdata))
        kmax = syncs.scalar(jnp.max(plan.rdata))
    else:
        info = np.iinfo(np.dtype(plan.rdata.dtype))
        n_valid = syncs.scalar(jnp.sum(plan.rvalid))
        kmin = syncs.scalar(jnp.min(jnp.where(plan.rvalid, plan.rdata,
                                              info.max)))
        kmax = syncs.scalar(jnp.max(jnp.where(plan.rvalid, plan.rdata,
                                              info.min)))
    if n_valid == 0:
        return None
    span = kmax - kmin + 1
    probe_rows = int(plan.ldata.shape[0])
    floor = max(join_plan.DENSE_SPAN_FACTOR * n_valid,
                join_plan.DENSE_SPAN_FLOOR)
    static_dense = span <= min(floor, join_plan.DENSE_SPAN_CAP)
    adaptive_dense = span <= min(max(floor, probe_rows),
                                 join_plan.DENSE_SPAN_CAP)

    skew = None
    if adaptive_dense:
        # dense window decided: the CSR histogram is one scatter-add away
        # — compute the hottest run (the skew signal) on the spot
        slot = jnp.clip(plan.rdata.astype(jnp.int64) - kmin, 0,
                        span - 1).astype(jnp.int32)
        ok = (jnp.ones(n, jnp.bool_) if plan.rvalid is None
              else plan.rvalid)
        cnt = jnp.zeros(span, jnp.int32).at[slot].add(ok.astype(jnp.int32))
        max_run = syncs.scalar(jnp.max(cnt))
        mean_run = max(n_valid / max(span, 1), 1.0)
        ratio = max_run / mean_run
        if ratio >= knobs.get("SRJT_AQE_SKEW_FACTOR"):
            skew = {"max_run": max_run, "n_valid": n_valid,
                    "span": span, "skew": ratio}

    if adaptive_dense == static_dense:
        return _Probe(None, "", skew)
    eng = "dense" if adaptive_dense else "sorted"
    detail = (f"{'sorted' if adaptive_dense else 'dense'}→{eng} "
              f"(span={span}, n_valid={n_valid}, probe_rows={probe_rows})")
    return _Probe(eng, detail, skew)


# --- reorderable chain detection ---------------------------------------------


class _ChainDim(NamedTuple):
    plan: ir.Plan
    left_on: tuple
    right_on: tuple


def _collect_chain(fja: ir.FusedJoinAggregate):
    """``(base, dims)`` for a left-deep inner-join spine under an inner
    FusedJoinAggregate, or None.  ``dims[i]`` carries the key pair that
    binds dimension *i*; the FJA's own join is the last element.  Needs
    at least two dims for a reorder to exist."""
    if fja.how != "inner":
        return None
    spine = []
    node = fja.left
    while isinstance(node, ir.Join) and node.how == "inner":
        spine.append(node)
        node = node.left
    if not spine:
        return None
    base = node
    dims = [_ChainDim(j.right, j.left_on, j.right_on)
            for j in reversed(spine)]
    dims.append(_ChainDim(fja.right, fja.left_on, fja.right_on))
    return base, dims


def _aggs_order_insensitive(fja, results) -> bool:
    """True when every aggregate of ``fja`` produces identical bytes
    under any join order: exact fn over a non-float input column.
    ``results`` holds the executed (table, names) of base + dims."""
    for c, fn, _out in fja.aggs:
        if fn not in _REORDERABLE_AGGS:
            return False
        col = None
        for t, names in results:
            if c in names:
                col = t[names.index(c)]
                break
        if col is None:
            return False
        dt = col.dtype
        if dt.is_variable_width or dt.is_nested:
            return False
        if dt.id in (T.TypeId.FLOAT32, T.TypeId.FLOAT64,
                     T.TypeId.DECIMAL128):
            return False
    return True


# --- stage-wise executor -----------------------------------------------------


_BARRIERS = (ir.Join, ir.FusedJoinAggregate, ir.Aggregate)


class _Exec:
    def __init__(self, catalog, record_stats: bool,
                 report: AdaptiveReport):
        self.catalog = catalog
        self.record_stats = record_stats
        self.report = report

    # . generic recursion .....................................................

    def run(self, node: ir.Plan):
        if isinstance(node, ir.FusedJoinAggregate):
            chain = _collect_chain(node)
            if chain is not None and len(chain[1]) >= 2:
                ctx = profile.node_enter(node)
                if ctx is None:
                    return self._run_chain(node, *chain)
                res = None
                try:
                    res = self._run_chain(node, *chain)
                finally:
                    # the chain record is the replan REGION: its
                    # children are the executed base/dim subtrees plus
                    # the synthesized spine in its chosen order
                    profile.node_exit(
                        ctx, None if res is None else res[0])
                return res
        ctx = profile.node_enter(node)
        if ctx is None:
            kids = [self.run(k) for k in ir.children(node)]
            return self._apply(node, kids)
        t = kids = None
        try:
            kids = [self.run(k) for k in ir.children(node)]
            t, names = self._apply(node, kids)
        finally:
            profile.node_exit(ctx, t, kids)
        return t, names

    # . one barrier stage .....................................................

    def _apply(self, node: ir.Plan, kids,
               extra_decisions: Optional[list] = None):
        if not isinstance(node, _BARRIERS):
            return lower._apply_node(node, kids, self.catalog,
                                     self.record_stats)
        stage = StageRecord(index=len(self.report.stages),
                            node=ir._node_line(node),
                            est_rows=plan_stats.GLOBAL.rows_for(node))
        if extra_decisions:
            stage.decisions.extend(extra_decisions)
        self.report.stages.append(stage)

        force = None
        if (isinstance(node, (ir.Join, ir.FusedJoinAggregate))
                and node.engine is None
                and join_plan.forced_engine() is None):
            probe = _probe_engine(node, kids)
            if probe is not None:
                if probe.engine is not None:
                    force = probe.engine
                    stage.decisions.append(
                        Decision("engine_flip", probe.detail))
                    if metrics.recording():
                        metrics.count("plan.aqe.engine_flip.fired")
                        metrics.count(
                            f"plan.aqe.engine_flip.{probe.engine}")
                if probe.skew is not None:
                    s = probe.skew
                    stage.decisions.append(Decision(
                        "skew_advisory",
                        f"hot key ×{s['skew']:.1f} mean "
                        f"(max_run={s['max_run']}, "
                        f"n_valid={s['n_valid']})"))
                    if metrics.recording():
                        metrics.count("plan.aqe.skew_split.advisory")
                        metrics.gauge_max("plan.aqe.skew_split.max_run",
                                          s["max_run"])

        if force is None:
            t, names = lower._apply_node(node, kids, self.catalog,
                                         self.record_stats)
        else:
            # the same force_engine seam the scheduler's degradation
            # uses — stats still observe the UNPINNED fingerprint, so
            # static-optimizer priors and adaptive observations share
            # one keyspace
            with join_plan.force_engine(force):
                t, names = lower._apply_node(node, kids, self.catalog,
                                             self.record_stats)
        stage.rows = t.num_rows
        if force is not None:
            profile.annotate_node(engine=force)
        for d in stage.decisions:
            profile.annotate_node(decision=f"{d.kind}: {d.detail}")
        self._check_regression(stage)
        return t, names

    def _check_regression(self, stage: StageRecord) -> None:
        if (not stage.decisions or stage.est_rows is None
                or stage.rows is None or stage.est_rows <= 0):
            return
        if stage.rows <= REGRESSION_FACTOR * stage.est_rows:
            return
        if metrics.recording():
            metrics.count("plan.aqe.regression")
        if syncs.mode() == "normal":
            # replay would re-report capture's incident; snapshot once
            flight.incident(
                "aqe_regression", stage=stage.index, node=stage.node,
                est_rows=stage.est_rows, observed_rows=stage.rows,
                decisions=[f"{d.kind}: {d.detail}"
                           for d in stage.decisions])

    # . chain replanning ......................................................

    def _run_chain(self, fja: ir.FusedJoinAggregate, base_node, dims):
        base = self.run(base_node)
        dim_res = [self.run(d.plan) for d in dims]

        order = list(range(len(dims)))
        decisions: list = []
        base_names = set(base[1])
        commutable = all(set(d.left_on) <= base_names for d in dims)
        exact = commutable and _aggs_order_insensitive(
            fja, [base] + dim_res)
        rows = [r[0].num_rows for r in dim_res]
        min_rows = knobs.get("SRJT_AQE_REPLAN_MIN_ROWS")
        if exact and max(rows) >= min_rows:
            picked = sorted(order, key=lambda i: (rows[i], i))
            if picked != order:
                before = [rows[i] for i in order]
                after = [rows[i] for i in picked]
                decisions.append(Decision(
                    "replan",
                    f"join order {order} → {picked} "
                    f"(observed dim rows {before} → {after})"))
                if metrics.recording():
                    metrics.count("plan.aqe.replan.fired")
                order = picked
        elif metrics.recording():
            metrics.count("plan.aqe.replan.rejected")

        # rebuild the spine in the chosen order; synthesized nodes are
        # value-equal to the originals when the order is unchanged, so
        # fingerprints, stats, and the op sequence match the static
        # executor exactly
        cur_plan, cur_res = base_node, base
        for j in order[:-1]:
            d = dims[j]
            jn = ir.Join(cur_plan, d.plan, d.left_on, d.right_on, "inner")
            cur_res = self._apply_staged(jn, [cur_res, dim_res[j]],
                                         extra_decisions=decisions)
            decisions = []          # attach replan to the first stage only
            cur_plan = jn
        last = dims[order[-1]]
        fnode = ir.FusedJoinAggregate(
            cur_plan, last.plan, last.left_on, last.right_on,
            fja.keys, fja.aggs, fja.how)
        return self._apply_staged(fnode, [cur_res, dim_res[order[-1]]],
                                  extra_decisions=decisions)

    def _apply_staged(self, node: ir.Plan, kids,
                      extra_decisions: Optional[list] = None):
        """One synthesized spine node (``_run_chain``): profiled like a
        ``run()`` node so the EXECUTED join order shows in the tree."""
        ctx = profile.node_enter(node)
        if ctx is None:
            return self._apply(node, kids, extra_decisions)
        res = None
        try:
            res = self._apply(node, kids, extra_decisions)
        finally:
            profile.node_exit(ctx, None if res is None else res[0], kids)
        return res


# --- entry points ------------------------------------------------------------


def execute_adaptive(tree: ir.Plan, catalog, record_stats: bool = True,
                     report: Optional[AdaptiveReport] = None):
    """Run a plan tree with stage-wise adaptive re-optimization.  Returns
    the result Table; pass ``report`` to collect the decision log."""
    plan_stats.ensure_sidecar_loaded()
    if report is None:
        report = AdaptiveReport()
    with metrics.span("plan.adaptive"):
        t, _names = _Exec(catalog, record_stats, report).run(tree)
    if metrics.recording():
        metrics.annotate(aqe_decisions=len(report.decisions()))
    return t


def compile_adaptive_plan(tree: ir.Plan, schemas: dict):
    """Adaptive twin of ``lower.compile_plan``: same qfn shape, plus an
    ``aqe_variant`` tag the exec plan cache folds into its key and a
    ``last_report`` attribute holding the most recent decision log."""
    ir.schema_of(tree, schemas)

    def qfn(tables):
        report = AdaptiveReport()
        t = execute_adaptive(tree, lower.TableCatalog(tables, schemas),
                             report=report)
        qfn.last_report = report
        return t

    qfn.plan_tree = tree
    qfn.plan_fingerprint = ir.fingerprint(tree)
    qfn.plan_output_names = lower.output_names(tree, schemas)
    qfn.aqe_variant = "aqe"
    qfn.last_report = None
    return qfn


def explain_adaptive(tree: ir.Plan, schemas: dict, tables: dict,
                     stats=None) -> str:
    """EXPLAIN with the adaptive appendix: optimizes ``tree``, executes
    the optimized tree adaptively against ``tables``, and renders the
    static report plus the stage-wise decisions that actually fired."""
    from . import rules
    res = rules.optimize(tree, schemas, stats=stats)
    report = AdaptiveReport()
    execute_adaptive(res.tree, lower.TableCatalog(tables, schemas),
                     record_stats=False, report=report)
    return rules.explain(tree, schemas, stats=stats,
                         adaptive_report=report)
