"""Lowering: optimized plan trees → the existing ops layer.

The executor walks a plan tree bottom-up against a **catalog** and emits
exactly the op calls the hand-fused queries make — same join order, same
mask construction (validity AND placement mirrors
``models/tpcds._eq_scalar_mask`` / ``_range_mask``), same fused
``join_aggregate`` tail — so results are bit-identical to the
hand-written kernels, including float summation order.

Catalogs:

* :class:`TableCatalog` — tables already decoded to device ``Table``
  objects.  Scans select columns by reference (column object identity is
  preserved, so the join build-index cache keeps hitting).
* :class:`FileCatalog` — raw parquet bytes.  Scans call
  ``parquet.device_scan.scan_table`` with the pruned column list and a
  row-group predicate derived from the scan predicate, so pushdown prunes
  *before decode* (``plan.scan.columns_pruned`` / the decoder's
  ``plan.scan.rowgroups_pruned`` counters prove it).

``compile_plan`` wraps execution as a ``qfn(tables) -> Table`` closure —
the exact shape ``models/compiled.compile_query``, the ``exec/`` plan
cache, and the scheduler already consume; ``ir.fingerprint(tree)`` is the
natural request name.
"""

from __future__ import annotations

import contextlib
from typing import Optional

import numpy as np

from .. import types as T
from ..column import Column, Table
from ..ops import (anti_join, apply_boolean_mask, concat_tables, distinct,
                   groupby_aggregate, groupby_cube, groupby_grouping_sets,
                   groupby_nunique, groupby_rollup, inner_join,
                   join_aggregate, left_join, mean, semi_join, slice_table,
                   sort_table, sum_)
from ..ops import strings as S
from ..ops import window as W
from ..utils import metrics
from . import ir
from . import profile
from . import stats as plan_stats


# --- catalogs ---------------------------------------------------------------


class TableCatalog:
    """Catalog over already-decoded device tables."""

    def __init__(self, tables: dict[str, Table],
                 schemas: dict[str, list[str]]):
        self.tables = tables
        self.schemas = {k: list(v) for k, v in schemas.items()}

    def scan(self, node: ir.Scan) -> tuple[Table, list[str]]:
        t = self.tables[node.table]
        names = self.schemas[node.table]
        if node.columns is None:
            return t, list(names)
        # select by reference: column identity preserved → build-index
        # caches keyed on buffer identity still hit
        cols = [t[names.index(c)] for c in node.columns]
        return Table(cols), list(node.columns)


class FileCatalog:
    """Catalog over raw parquet file bytes: scans decode on demand with
    column pruning and statistics-driven row-group pruning."""

    def __init__(self, files: dict[str, bytes]):
        self.files = files
        self._schemas: dict[str, list[str]] = {}

    def schema(self, table: str) -> list[str]:
        got = self._schemas.get(table)
        if got is None:
            from ..parquet import decode as D
            from ..parquet.footer import extract_footer_bytes
            from ..parquet.thrift import parse_struct
            meta = parse_struct(extract_footer_bytes(self.files[table]))
            got = [leaf.name for leaf in D._leaf_schema_elements(meta)]
            self._schemas[table] = got
        return got

    @property
    def schemas(self) -> dict[str, list[str]]:
        return {name: self.schema(name) for name in self.files}

    def scan(self, node: ir.Scan) -> tuple[Table, list[str]]:
        from ..parquet import device_scan
        full = self.schema(node.table)
        cols = list(node.columns) if node.columns is not None else list(full)
        conds = rowgroup_conditions(node.predicate)
        # the same conjunct list drives both pushdown tiers: row groups
        # prune on footer statistics, surviving rows prune on the walked
        # raw pages (parquet.rowfilter) before anything decodes
        t = device_scan.scan_table(
            self.files[node.table], columns=cols,
            rowgroup_predicate=conds, row_predicate=conds)
        if metrics.recording() and len(cols) < len(full):
            metrics.count("plan.scan.columns_pruned",
                          len(full) - len(cols))
        return t, cols


def _rowgroup_literal(v):
    """A literal usable for footer min/max pruning, or None.  Ints prune
    INT32/INT64 (and int-backed decimal) chunks; strings pass as UTF-8
    bytes and prune BYTE_ARRAY chunks (parquet's UTF8 logical order IS
    unsigned byte order, so Python bytes comparison matches)."""
    if hasattr(v, "item"):
        # planning-time literal from the query spec (numpy scalar), never
        # a traced value — rowgroup pruning runs before any jit
        v = v.item()  # srjt-lint: disable=trace-host-sync
    if isinstance(v, bool):
        return None
    if isinstance(v, int):
        return v
    if isinstance(v, str):
        return v.encode("utf-8")
    if isinstance(v, (bytes, bytearray)):
        return bytes(v)
    return None


def rowgroup_conditions(expr: Optional[ir.Expr]):
    """Extract ``(column, op, value)`` conditions the parquet scanner can
    test against footer min/max statistics.  Integer and string
    comparisons qualify (strings travel as UTF-8 bytes); anything else is
    simply not offered for pruning (the full predicate still runs as a
    mask after decode)."""
    conds = []
    for c in ir.conjuncts(expr):
        if (isinstance(c, ir.Cmp) and isinstance(c.left, ir.Col)
                and isinstance(c.right, ir.Lit)
                and c.op in ("==", "<", "<=", ">", ">=")):
            v = _rowgroup_literal(c.right.value)
            if v is not None:
                op = {"==": "eq", "<": "lt", "<=": "le", ">": "gt",
                      ">=": "ge"}[c.op]
                conds.append((c.left.name, op, v))
        elif isinstance(c, ir.Between) and isinstance(c.col, ir.Col):
            lo = _rowgroup_literal(c.lo)
            hi = _rowgroup_literal(c.hi)
            if lo is not None:
                conds.append((c.col.name, "ge", lo))
            if hi is not None:
                conds.append((c.col.name, "lt" if c.hi_strict else "le",
                              hi))
    return conds or None


def _full_pushdown(expr: Optional[ir.Expr]) -> bool:
    """True when ``rowgroup_conditions(expr)`` is EQUIVALENT to the whole
    predicate — every conjunct is a Cmp/Between whose literals made it
    into the condition list — not merely a necessary relaxation.  Only
    then may a scan-side row filter replace the planner's mask."""
    if expr is None:
        return False
    for c in ir.conjuncts(expr):
        if (isinstance(c, ir.Cmp) and isinstance(c.left, ir.Col)
                and isinstance(c.right, ir.Lit)
                and c.op in ("==", "<", "<=", ">", ">=")):
            if _rowgroup_literal(c.right.value) is None:
                return False
        elif isinstance(c, ir.Between) and isinstance(c.col, ir.Col):
            if c.lo is None and c.hi is None:
                return False
            if c.lo is not None and _rowgroup_literal(c.lo) is None:
                return False
            if c.hi is not None and _rowgroup_literal(c.hi) is None:
                return False
        else:
            return False
    return True


# --- expression evaluation --------------------------------------------------


def _column(table: Table, names: list[str], name: str) -> Column:
    try:
        return table[names.index(name)]
    except ValueError:
        raise ir.PlanError(f"column {name!r} not in {names}")


def _scalar(e: ir.Expr, table: Table, names: list[str]):
    """Evaluate a scalar-valued expression (stays a device scalar for
    ScalarAgg so capture/replay sees no host pull)."""
    if isinstance(e, ir.Lit):
        return e.value
    if isinstance(e, ir.ScalarAgg):
        if not isinstance(e.arg, ir.Col):
            raise ir.PlanError("ScalarAgg argument must be a column")
        col = _column(table, names, e.arg.name)
        if e.fn == "mean":
            return mean(col)
        if e.fn == "sum":
            return sum_(col)
        raise ir.PlanError(f"unsupported scalar aggregate {e.fn!r}")
    if isinstance(e, ir.Mul):
        return _scalar(e.left, table, names) * _scalar(e.right, table, names)
    raise ir.PlanError(f"not a scalar expression: {type(e).__name__}")


def _eq_mask(col: Column, value):
    # mirrors models/tpcds._eq_scalar_mask bit-for-bit
    if col.dtype.id == T.TypeId.STRING:
        b = S.equal_to_scalar(col, value)
        m = b.data.astype(bool)
        return m if b.validity is None else (m & b.validity)
    m = col.values() == value
    return m if col.validity is None else (m & col.validity)


def eval_mask(expr: ir.Expr, table: Table, names: list[str]):
    """Boolean row mask for ``expr`` over ``table`` — null rows fail
    (validity ANDed in, matching the hand-written query helpers)."""
    if isinstance(expr, ir.And):
        m = None
        for p in expr.parts:
            pm = eval_mask(p, table, names)
            m = pm if m is None else (m & pm)
        return m
    if isinstance(expr, ir.Or):
        m = None
        for p in expr.parts:
            pm = eval_mask(p, table, names)
            m = pm if m is None else (m | pm)
        return m
    if isinstance(expr, ir.IsIn):
        if not isinstance(expr.col, ir.Col):
            raise ir.PlanError("IsIn operand must be a column")
        col = _column(table, names, expr.col.name)
        m = None
        for v in expr.values:
            vm = _eq_mask(col, v)
            m = vm if m is None else (m | vm)
        if m is None:
            raise ir.PlanError("IsIn with empty value list")
        return m
    if isinstance(expr, ir.Between):
        if not isinstance(expr.col, ir.Col):
            raise ir.PlanError("Between operand must be a column")
        col = _column(table, names, expr.col.name)
        # mirrors models/tpcds._range_mask bit-for-bit
        m = None
        cvals = col.values()
        if expr.lo is not None:
            m = cvals >= expr.lo
        if expr.hi is not None:
            hm = (cvals < expr.hi) if expr.hi_strict else (cvals <= expr.hi)
            m = hm if m is None else (m & hm)
        if col.validity is not None:
            m = col.validity if m is None else (m & col.validity)
        if m is None:
            raise ir.PlanError("Between with no bounds")
        return m
    if isinstance(expr, ir.Cmp):
        if not isinstance(expr.left, ir.Col):
            raise ir.PlanError("comparison left side must be a column")
        col = _column(table, names, expr.left.name)
        rhs = _scalar(expr.right, table, names)
        if expr.op == "==":
            return _eq_mask(col, rhs)
        cvals = col.values()
        if expr.op == "<":
            m = cvals < rhs
        elif expr.op == "<=":
            m = cvals <= rhs
        elif expr.op == ">":
            m = cvals > rhs
        elif expr.op == ">=":
            m = cvals >= rhs
        elif expr.op == "!=":
            m = cvals != rhs
        else:
            raise ir.PlanError(f"unsupported comparison {expr.op!r}")
        return m if col.validity is None else (m & col.validity)
    raise ir.PlanError(f"not a predicate expression: {type(expr).__name__}")


# --- execution --------------------------------------------------------------


def _key_indices(names: list[str], keys) -> list[int]:
    return [names.index(k) for k in keys]


def _on_arg(idxs: list[int]):
    # hand-written queries pass single-key joins as a bare int — match
    # that exactly so the join entry point takes the identical path
    return idxs[0] if len(idxs) == 1 else idxs


@contextlib.contextmanager
def _engine_pin(node: ir.Plan):
    """Honor an adaptive engine pin (``Join.engine`` /
    ``FusedJoinAggregate.engine``) around one join's execution.  An
    ambient force — the scheduler's degraded-admission
    ``force_engine("sorted")`` or the ``SRJT_JOIN_ENGINE`` knob — always
    wins: a pin decided from observed statistics must not override a
    footprint-driven degradation."""
    from ..ops import join_plan
    eng = getattr(node, "engine", None)
    if eng is None or join_plan.forced_engine() is not None:
        yield
        return
    with join_plan.force_engine(eng):
        yield


def _apply_node(node: ir.Plan, kids: list, catalog, record_stats: bool):
    """Apply ONE plan node to its already-computed child results.

    ``kids`` holds one ``(table, names)`` pair per ``ir.children(node)``
    entry.  This is the single place a node becomes op calls —
    :func:`_execute` (the static recursive executor) and
    ``plan/adaptive.py`` (the stage-wise adaptive executor) both route
    through it, so an adaptively re-ordered plan runs the exact op
    sequence the static lowering of the same tree would."""
    t: Table
    names: list[str]
    if isinstance(node, ir.Scan):
        t, names = catalog.scan(node)
        if node.predicate is not None:
            if (getattr(t, "fused_filter_complete", False)
                    and _full_pushdown(node.predicate)):
                # the scan already evaluated every conjunct on the raw
                # pages and pruned the rows — the mask here would be
                # all-True, skip the redundant gather
                if metrics.recording():
                    metrics.count("plan.scan.filter_fused")
            else:
                t = apply_boolean_mask(t, eval_mask(node.predicate, t,
                                                    names))
    elif isinstance(node, ir.Filter):
        t, names = kids[0]
        t = apply_boolean_mask(t, eval_mask(node.predicate, t, names))
    elif isinstance(node, ir.Project):
        ct, cnames = kids[0]
        t = Table([ct[cnames.index(c)] for c in node.columns])
        names = list(node.columns)
    elif isinstance(node, ir.Join):
        (lt, ln), (rt, rn) = kids
        fn = {"inner": inner_join, "left": left_join,
              "semi": semi_join, "anti": anti_join}.get(node.how)
        if fn is None:
            raise ir.PlanError(f"unsupported join type {node.how!r}")
        with _engine_pin(node):
            t = fn(lt, rt, _on_arg(_key_indices(ln, node.left_on)),
                   _on_arg(_key_indices(rn, node.right_on)))
        names = ln if node.how in ("semi", "anti") else ln + rn
    elif isinstance(node, ir.FusedJoinAggregate):
        (lt, ln), (rt, rn) = kids
        joined = ln + rn
        with _engine_pin(node):
            t = join_aggregate(
                lt, rt, _on_arg(_key_indices(ln, node.left_on)),
                _on_arg(_key_indices(rn, node.right_on)),
                _key_indices(joined, node.keys),
                [(joined.index(c), fn) for c, fn, _out in node.aggs],
                how=node.how)
        names = list(node.keys) + [a[2] for a in node.aggs]
    elif isinstance(node, ir.Aggregate):
        ct, cnames = kids[0]
        key_idx = _key_indices(cnames, node.keys)
        agg_arg = [(cnames.index(c), fn) for c, fn, _out in node.aggs]
        names = list(node.keys) + [a[2] for a in node.aggs]
        if node.grouping is not None:
            gfn = {"rollup": groupby_rollup, "cube": groupby_cube}.get(
                node.grouping)
            if gfn is not None:
                t = gfn(ct, key_idx, agg_arg)
            else:
                t = groupby_grouping_sets(ct, key_idx,
                                          node.grouping_sets, agg_arg)
            names = names + [ir.GROUPING_ID]
        elif any(fn == "nunique" for _c, fn, _o in node.aggs):
            if len(node.aggs) != 1:
                raise ir.PlanError(
                    "nunique aggregate must be the only aggregation")
            t = groupby_nunique(ct, key_idx,
                                cnames.index(node.aggs[0][0]))
        else:
            t = groupby_aggregate(ct, key_idx, agg_arg)
    elif isinstance(node, ir.Window):
        ct, cnames = kids[0]
        asc = None if node.ascending is None else list(node.ascending)
        spec = W.WindowSpec(ct, _key_indices(cnames, node.partition_by),
                            _key_indices(cnames, node.order_by),
                            ascending=asc)
        order_idx = _key_indices(cnames, node.order_by)
        if node.fn == "row_number":
            wcol = W.row_number(spec)
        elif node.fn == "rank":
            wcol = W.rank(spec, order_idx)
        elif node.fn == "dense_rank":
            wcol = W.dense_rank(spec, order_idx)
        elif node.fn in ("running_sum", "lag", "lead"):
            if node.value is None:
                raise ir.PlanError(f"window {node.fn} needs a value column")
            vidx = cnames.index(node.value)
            wfn = {"running_sum": W.running_sum, "lag": W.lag,
                   "lead": W.lead}[node.fn]
            wcol = wfn(spec, vidx)
        else:
            raise ir.PlanError(f"unsupported window function {node.fn!r}")
        t = Table(list(ct.columns) + [wcol])
        names = cnames + [node.out]
    elif isinstance(node, ir.Union):
        t = concat_tables([k[0] for k in kids])
        names = list(node.names)
    elif isinstance(node, ir.Distinct):
        ct, cnames = kids[0]
        t = distinct(ct)
        names = cnames
    elif isinstance(node, ir.Sort):
        ct, cnames = kids[0]
        asc = None if node.ascending is None else list(node.ascending)
        t = sort_table(ct, _key_indices(cnames, node.keys), ascending=asc)
        names = cnames
    elif isinstance(node, ir.Limit):
        ct, cnames = kids[0]
        t = slice_table(ct, 0, node.n)
        names = cnames
    else:
        raise ir.PlanError(f"unknown plan node {type(node).__name__}")

    if record_stats:
        # static shapes: num_rows is free — feed the reorder rule's
        # exact-cardinality store for the next optimize of this shape
        plan_stats.GLOBAL.observe(ir.fingerprint(node), t.num_rows)
    # the validity-density sync (SRJT_PROFILE_VALIDITY) lives at this
    # single funnel so capture and replay resolve the identical tape
    profile.at_node_output(t)
    return t, names


def _execute(node: ir.Plan, catalog, record_stats: bool):
    ctx = profile.node_enter(node)
    if ctx is None:
        kids = [_execute(k, catalog, record_stats)
                for k in ir.children(node)]
        return _apply_node(node, kids, catalog, record_stats)
    t = kids = None
    try:
        kids = [_execute(k, catalog, record_stats)
                for k in ir.children(node)]
        t, names = _apply_node(node, kids, catalog, record_stats)
    finally:
        profile.node_exit(ctx, t, kids)
    return t, names


def execute(tree: ir.Plan, catalog, record_stats: bool = True) -> Table:
    """Run a (typically optimized) plan tree against a catalog.  With
    ``SRJT_AQE`` on, routes through the stage-wise adaptive executor
    (``plan/adaptive.py``); off (the default) is the static path,
    byte-for-byte."""
    from ..utils import knobs
    if knobs.get("SRJT_AQE"):
        from . import adaptive
        return adaptive.execute_adaptive(tree, catalog,
                                         record_stats=record_stats)
    t, _names = _execute(tree, catalog, record_stats)
    return t


def output_names(tree: ir.Plan, schemas: dict) -> list[str]:
    return list(ir.schema_of(tree, schemas))


def compile_plan(tree: ir.Plan, schemas: dict):
    """Wrap a plan tree as ``qfn(tables: dict[str, Table]) -> Table`` —
    the exact callable shape ``models/compiled.compile_query``, the
    ``exec/`` plan cache, and the scheduler consume.  Use
    ``ir.fingerprint(tree)`` as the request/cache name.

    With ``SRJT_AQE`` on at build time, returns the adaptive twin
    (``plan/adaptive.compile_adaptive_plan``), tagged ``aqe_variant`` so
    the exec plan cache keys it separately.  Either way the returned qfn
    is PINNED to the mode it was built under — a compiled (and possibly
    plan-cached) query must not change execution strategy when the env
    flips later."""
    from ..utils import knobs
    if knobs.get("SRJT_AQE"):
        from . import adaptive
        return adaptive.compile_adaptive_plan(tree, schemas)
    ir.schema_of(tree, schemas)       # validate once at build time

    def qfn(tables: dict[str, Table]) -> Table:
        t, _names = _execute(tree, TableCatalog(tables, schemas), True)
        return t

    qfn.plan_tree = tree
    qfn.plan_fingerprint = ir.fingerprint(tree)
    # output column names, in order — consumers that bind columns by name
    # (ml/ FeatureSpec packing) read these instead of re-deriving the schema
    qfn.plan_output_names = output_names(tree, schemas)
    return qfn
