"""Ragged↔dense byte movement on TPU — the segmented-copy engine.

This is the TPU-native answer to the reference's variable-width CUDA kernels
(``copy_strings_to_rows`` warp-per-row ``memcpy_async``,
``row_conversion.cu:827-875``, and ``copy_strings_from_rows``,
``:1131-1174``).  Three facts about the hardware/toolchain dictate the
design (all measured on v5e, see BASELINE.md):

* XLA's 1D gather scalarizes (~0.1 Gelem/s) — per-element indexing is not a
  usable primitive for byte movement;
* per-DMA issue rate tops out ~1.4 M/s, so per-row DMAs cap at ~1 GB/s for
  typical row sizes;
* Mosaic DMA slices must be tile-aligned (512B windows), but in-register
  dynamic rolls (``pltpu.roll``) are cheap on 32-bit lanes.

So the kernels here move *aligned bulk windows* with a handful of DMAs per
output block and do the unaligned placement with vector rolls — exactly the
reference's "stage tiles in shared memory, blast out coalesced" pattern
(``row_conversion.cu:575-693``) with VMEM in the role of shmem and a
byte-roll in the role of the per-thread shuffle.

Segments are byte-granular: offsets and sizes need no alignment.  The only
structural requirement is monotonicity (segment k's source lies before
segment k+1's), which holds for every use in this package: JCUDF row
pack/unpack, per-column string extraction, and ordered string gathers.

Public entry points (host-metadata + device-array in, device-array out):

* :func:`pack_rows`   — dense [n, M] (zero-padded rows) → packed flat bytes
* :func:`unpack_rows` — packed flat bytes → dense [n, M] (zero-padded)

Both take the segment offsets as a **host** numpy array (the row geometry is
host-resident everywhere in the JCUDF path — the reference makes the same
host/device split: batch/tile metadata on host, bytes on device).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import knobs

LANE = 128
_WINDOW_ALIGN = 512          # bytes; Mosaic DMA minor-dim tile for u32


def dma_supported() -> bool:
    """The Pallas DMA path runs on real TPU backends only (interpret mode
    does not model the DMA/semaphore pipeline faithfully enough to be worth
    maintaining); elsewhere the XLA fallback is used."""
    if not knobs.get("SRJT_RAGGED_DMA"):
        return False
    return jax.default_backend() == "tpu"


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _x64_off():
    """``jax.enable_x64(False)`` context across jax versions (0.4.x ships
    it as ``jax.experimental.disable_x64``)."""
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(False)
    from jax.experimental import disable_x64
    return disable_x64()


def _pow2_bucket(x: int, lo: int = 8) -> int:
    """Round up to a power of two (≥ lo).

    Every data-dependent static the kernels take (block counts, window
    sublanes, metadata rows, padded segment counts) is bucketed so that
    calls with nearby geometry share one compiled kernel — each unique
    static tuple costs a ~35 s Mosaic compile through the remote helper,
    and e.g. a 50-string-column table would otherwise compile ~50 variants.
    """
    v = lo
    while v < x:
        v <<= 1
    return v


def _soft_bucket(x: int, lo: int = 8) -> int:
    """Bucket with ≤ ~12.5% growth: round up to a multiple of pow2(x)/8.

    Used for sizes where doubling would be wasteful (input paddings, grid
    block counts); still collapses the compile-key space to a few dozen
    values.
    """
    x = max(x, lo)
    p = _pow2_bucket(x, lo)
    step = max(lo, p // 8)
    return _round_up(x, step)


# ---------------------------------------------------------------------------
# padding-safe u8 ↔ u32 reinterpretation
#
# jnp.reshape(x, (-1, 4)) + bitcast materializes a (…, 4)-minor array whose
# TPU tiled layout pads the minor dim to 128 — a 32× HBM blow-up that OOMs
# at GB scale.  These helpers keep every intermediate ≥ 512B-minor.
# ---------------------------------------------------------------------------

@jax.jit
def u8_to_u32(x: jnp.ndarray) -> jnp.ndarray:
    """u8 [4N] → u32 [N] (little-endian), N multiple of 128.

    Jitted: these helpers run between pallas_call invocations in otherwise
    eager host orchestration, and each eager jnp op costs a full dispatch
    round-trip on remote backends.
    """
    x2 = x.reshape(-1, 4 * LANE)
    parts = [x2[:, k::4].astype(jnp.uint32) for k in range(4)]
    w = parts[0] | (parts[1] << 8) | (parts[2] << 16) | (parts[3] << 24)
    return w.reshape(-1)


@jax.jit
def u32_to_u8(w: jnp.ndarray) -> jnp.ndarray:
    """u32 [N] → u8 [4N], N multiple of 128 (jitted, see u8_to_u32)."""
    w2 = w.reshape(-1, LANE)
    out = jnp.zeros((w2.shape[0], 4 * LANE), jnp.uint8)
    for k in range(4):
        out = out.at[:, k::4].set(((w2 >> (8 * k)) & 0xFF).astype(jnp.uint8))
    return out.reshape(-1)


# ---------------------------------------------------------------------------
# in-kernel primitives
# ---------------------------------------------------------------------------

def _flat_roll(x2d, shift_words):
    """Circular roll of a [S, 128] u32 register block in flat row-major
    word order, dynamic (possibly negative) shift."""
    from jax.experimental.pallas import tpu as pltpu
    S = x2d.shape[0]
    T = jnp.int32(S * LANE)
    shift_words = jnp.int32(shift_words)
    shift_words = jax.lax.rem(jax.lax.rem(shift_words, T) + T, T)
    q = jax.lax.div(shift_words, jnp.int32(LANE))
    r = jax.lax.rem(shift_words, jnp.int32(LANE))
    a = pltpu.roll(x2d, q, axis=0)
    b = pltpu.roll(x2d, q + 1, axis=0)
    lane = jax.lax.broadcasted_iota(jnp.int32, (S, LANE), 1)
    return jnp.where(lane >= r, pltpu.roll(a, r, axis=1),
                     pltpu.roll(b, r, axis=1))


def _byte_roll(x2d, shift_bytes):
    """Byte-granular circular roll of [S, 128] u32 words in flat little-
    endian byte order: output byte j = input byte (j - shift) mod 4S·128.

    Word roll for the multiple-of-4 part plus a sub-word splice of each word
    with its flat predecessor for the remainder.
    """
    T4 = jnp.int32(x2d.shape[0] * LANE * 4)
    shift_bytes = jnp.int32(shift_bytes)
    shift_bytes = jax.lax.rem(jax.lax.rem(shift_bytes, T4) + T4, T4)
    wshift = jax.lax.div(shift_bytes, jnp.int32(4))
    rb = jax.lax.rem(shift_bytes, jnp.int32(4))
    a = _flat_roll(x2d, wshift)          # bytes rolled by 4·wshift
    prev = _flat_roll(x2d, wshift + 1)   # each word's flat predecessor
    # little-endian: rolling bytes forward in memory by rb means each output
    # word takes its own low bytes shifted up and the predecessor's high
    # bytes shifted down.  Vector shifts by a traced amount do not legalize
    # in Mosaic, so all four constant-shift variants are computed (cheap VPU
    # ops) and selected by the scalar remainder.
    # NOTE the package runs with jax_enable_x64; bare Python ints trace as
    # i64 and Mosaic cannot legalize mixed-width vector ops, so every
    # constant here is explicitly 32-bit.
    variants = [a]
    for k in (1, 2, 3):
        variants.append((a << jnp.uint32(8 * k))
                        | (prev >> jnp.uint32(32 - 8 * k)))
    out = variants[3]
    for k in (2, 1, 0):
        out = jnp.where(rb == jnp.int32(k), variants[k], out)
    return out


def _byte_keep_mask(word_pos4, start_b, end_b):
    """u32 mask per word for flat byte positions in [start_b, end_b).

    ``word_pos4``: [S, 128] i32, flat byte position of each word's byte 0.
    """
    # built in int32 and bitcast at the end: Mosaic's bool→uint32 convert
    # recurses in its lowering helper, int32 selects are fine
    m = jnp.zeros(word_pos4.shape, jnp.int32)
    for j in range(4):
        pj = word_pos4 + jnp.int32(j)
        inside = (pj >= start_b) & (pj < end_b)
        v = 0xFF << (8 * j)
        v = v - (1 << 32) if v >= (1 << 31) else v   # as signed i32 bits
        m = m | jnp.where(inside, jnp.int32(v), jnp.int32(0))
    return jax.lax.bitcast_convert_type(m, jnp.uint32)


# ---------------------------------------------------------------------------
# pack: dense [n, M] → flat
# ---------------------------------------------------------------------------

def _pack_geometry(offs: np.ndarray, n: int, B: int):
    total = int(offs[-1])
    nblocks = max(1, -(-total // B))
    r_begin = np.searchsorted(offs, np.arange(nblocks, dtype=np.int64) * B,
                              side="right") - 1
    r_begin = np.maximum(r_begin, 0)
    r_end = np.searchsorted(
        offs, np.minimum(np.arange(1, nblocks + 1, dtype=np.int64) * B, total),
        side="left")
    r0 = (r_begin // 8) * 8
    NR = int(np.max(r_end - r0)) if n else 8
    NR = _round_up(max(NR, 8), 8)
    return total, nblocks, r_begin.astype(np.int32), r_end, r0.astype(np.int32), NR


def pack_rows(dense: jnp.ndarray, row_offsets: np.ndarray,
              block_bytes: int = 8192) -> jnp.ndarray:
    """Pack zero-padded dense rows into a flat byte buffer on TPU.

    ``dense``: u8 [n, M]; row r's bytes [0, size_r) are its payload (the
    rest must be zero).  ``row_offsets``: HOST int array [n+1], byte offsets
    into the output; ``size_r = offsets[r+1] - offsets[r] ≤ M``.  Offsets
    and sizes are byte-granular (no alignment requirement).

    Runs under ``jax.enable_x64(False)``: the package globally enables x64
    (int64 columns), but PrefetchScalarGridSpec and ``pltpu.roll`` fail to
    legalize under x64, and everything here is 32-bit anyway.
    """
    with _x64_off():
        return _pack_rows_impl(dense, row_offsets, block_bytes)


def _pack_rows_impl(dense, row_offsets, block_bytes):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, M = dense.shape
    offs = np.asarray(row_offsets, dtype=np.int64)
    total = int(offs[-1])
    if total == 0 or n == 0:
        return jnp.zeros((0,), jnp.uint8)
    B = block_bytes
    assert B % _WINDOW_ALIGN == 0
    Mp = max(_WINDOW_ALIGN, _round_up(M, _WINDOW_ALIGN))
    if Mp > B:
        B = _round_up(Mp, _WINDOW_ALIGN)
    Mw = Mp // 4
    MwS = Mw // LANE
    Bw = B // 4
    SB = Bw // LANE

    total_, nblocks, rb, r_end, r0, NR = _pack_geometry(offs, n, B)
    # bucket every data-dependent static so nearby geometries share one
    # compiled kernel (each unique static tuple costs a full Mosaic compile)
    NR = _pow2_bucket(NR, 8)
    if NR * Mw * 4 > (1 << 21):
        # many tiny rows against a large M: the staged row window would
        # exceed VMEM — ValueError so pack() degrades to the XLA fallback
        raise ValueError("pack_rows: row window exceeds VMEM budget")
    KOFF = _pow2_bucket(NR // LANE + 2, 2)
    nblocks_q = _soft_bucket(nblocks, 1)
    pad_blk = nblocks_q - nblocks
    rb = np.pad(rb, (0, pad_blk))
    r0 = np.pad(r0, (0, pad_blk))
    nr = np.pad((r_end - rb[:nblocks]).astype(np.int32), (0, pad_blk))
    nblocks = nblocks_q

    n_pad = _soft_bucket(_round_up(n, 8) + NR)
    dense_pad = jnp.pad(dense, ((0, n_pad - n), (0, Mp - M)))
    dense32 = u8_to_u32(dense_pad.reshape(-1)).reshape(n_pad, MwS, LANE)

    offs32 = offs.astype(np.int32)
    offs_rows = _soft_bucket(-(-(n_pad + 1) // LANE) + KOFF + 1)
    offs2d = jnp.asarray(
        np.pad(offs32, (0, offs_rows * LANE - offs32.shape[0]))
        .reshape(offs_rows, LANE))

    out = _pack_call(nblocks, SB, MwS, NR, KOFF, B)(
        jnp.asarray(r0), jnp.asarray(rb), jnp.asarray(nr), offs2d, dense32)
    return u32_to_u8(out.reshape(-1))[:total]


@functools.lru_cache(maxsize=512)
def _pack_call(nblocks, SB, MwS, NR, KOFF, B):
    """Cached jitted pallas_call for one pack geometry.

    The kernel closure and pallas_call wrapper MUST be built once per
    static tuple and reused: jax's dispatch cache keys on the callable's
    identity, so a fresh closure per call forces a full Mosaic recompile
    every call (~1 s each — this dominated the round-2 string transcode).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(r0_ref, rb_ref, nr_ref, offs_hbm, dense_hbm, out_ref,
               scratch, soffs, sems):
        b = pl.program_id(0)
        row0 = r0_ref[b]
        dma = pltpu.make_async_copy(dense_hbm.at[pl.ds(row0, NR)], scratch,
                                    sems.at[0])
        dma.start()
        orow0 = row0 // LANE
        for k in range(KOFF):
            pltpu.make_async_copy(offs_hbm.at[orow0 + k], soffs.at[k],
                                  sems.at[1 + k]).start()
        dma.wait()
        for k in range(KOFF):
            pltpu.make_async_copy(offs_hbm.at[orow0 + k], soffs.at[k],
                                  sems.at[1 + k]).wait()

        blk_start = b * B
        pos4 = ((jax.lax.broadcasted_iota(jnp.int32, (SB, LANE), 0) * LANE
                 + jax.lax.broadcasted_iota(jnp.int32, (SB, LANE), 1)) * 4)

        def body(i, acc):
            r = rb_ref[b] + i
            lr = r - row0
            o_lo = soffs[(r // LANE) - orow0, r % LANE]
            o_hi = soffs[((r + 1) // LANE) - orow0, (r + 1) % LANE]
            rowvec = scratch[lr]                 # [MwS, LANE] u32
            if SB > MwS:
                ext = jnp.concatenate(
                    [rowvec, jnp.zeros((SB - MwS, LANE), jnp.uint32)], axis=0)
            else:
                ext = rowvec[:SB]
            p = o_lo - blk_start                 # byte position, may be < 0
            rolled = _byte_roll(ext, p)
            keep = _byte_keep_mask(pos4, p, p + (o_hi - o_lo))
            return acc | (rolled & keep)

        acc = jax.lax.fori_loop(0, nr_ref[b],
                                body, jnp.zeros((SB, LANE), jnp.uint32))
        out_ref[...] = acc[None]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(nblocks,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((1, SB, LANE), lambda b, *_: (b, 0, 0)),
        scratch_shapes=[pltpu.VMEM((NR, MwS, LANE), jnp.uint32),
                        pltpu.SMEM((KOFF, LANE), jnp.int32),
                        pltpu.SemaphoreType.DMA((1 + KOFF,))])
    return jax.jit(pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((nblocks, SB, LANE), jnp.uint32),
        compiler_params=pltpu.CompilerParams(has_side_effects=True)))


# ---------------------------------------------------------------------------
# unpack: flat → dense [n, M]
# ---------------------------------------------------------------------------

def unpack_rows(flat: jnp.ndarray, row_offsets: np.ndarray, M: int,
                rows_per_block: int = 8) -> jnp.ndarray:
    """Inverse of :func:`pack_rows`: split a flat byte buffer into
    zero-padded dense rows u8 [n, M].  Byte-granular offsets.

    Runs under ``jax.enable_x64(False)`` — see :func:`pack_rows`."""
    with _x64_off():
        return _unpack_rows_impl(flat, row_offsets, M, rows_per_block)


def _unpack_rows_impl(flat, row_offsets, M, rows_per_block):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    offs = np.asarray(row_offsets, dtype=np.int64)
    n = offs.shape[0] - 1
    total = int(offs[-1])
    if n == 0:
        return jnp.zeros((0, M), jnp.uint8)
    RB = rows_per_block
    Mp = max(_WINDOW_ALIGN, _round_up(M, _WINDOW_ALIGN))
    Mw = Mp // 4
    MwS = Mw // LANE
    nblocks = _soft_bucket(-(-n // RB), 1)    # bucketed: shared compiles
    n_pad = nblocks * RB
    KOFF = _pow2_bucket(RB // LANE + 2, 2)

    offs_pad = np.pad(offs, (0, n_pad + 1 - offs.shape[0]), mode="edge")
    start_word_row = ((offs_pad[np.arange(nblocks) * RB] // 4) // LANE
                      ).astype(np.int32)
    # window sized from the DATA: rows may be larger than M (extracting a
    # prefix, e.g. the fixed region of full JCUDF rows), so each block's
    # staged window must span its rows' full strides, not RB*M
    spans = (offs_pad[np.minimum(np.arange(1, nblocks + 1) * RB, n_pad)]
             - start_word_row.astype(np.int64) * (LANE * 4))
    KS = _pow2_bucket(int(spans.max(initial=1)) // (LANE * 4) + 2, 8)
    KS = max(KS, _round_up(MwS, 8))
    if KS * LANE * 4 > (1 << 21):
        raise ValueError("unpack_rows: row span exceeds VMEM window budget")
    flat_rows = _soft_bucket(-(-total // (LANE * 4)) + KS)
    flat_pad = jnp.pad(flat, (0, flat_rows * LANE * 4 - total))
    flat32 = u8_to_u32(flat_pad).reshape(flat_rows, LANE)

    offs32 = offs_pad.astype(np.int32)
    offs_rows = _soft_bucket(-(-(n_pad + 1) // LANE) + KOFF + 1)
    offs2d = jnp.asarray(
        np.pad(offs32, (0, offs_rows * LANE - offs32.shape[0]))
        .reshape(offs_rows, LANE))

    out = _unpack_call(nblocks, RB, MwS, KS, KOFF)(
        jnp.asarray(start_word_row), offs2d, flat32)
    dense = u32_to_u8(out.reshape(-1)).reshape(n_pad, Mp)
    return dense[:n, :M]


@functools.lru_cache(maxsize=512)
def _unpack_call(nblocks, RB, MwS, KS, KOFF):
    """Cached jitted pallas_call for one unpack geometry (see _pack_call)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(sw_ref, offs_hbm, flat_hbm, out_ref, win, soffs, sems):
        b = pl.program_id(0)
        dma = pltpu.make_async_copy(flat_hbm.at[pl.ds(sw_ref[b], KS)], win,
                                    sems.at[0])
        dma.start()
        orow0 = (b * RB) // LANE
        for k in range(KOFF):
            pltpu.make_async_copy(offs_hbm.at[orow0 + k], soffs.at[k],
                                  sems.at[1 + k]).start()
        dma.wait()
        for k in range(KOFF):
            pltpu.make_async_copy(offs_hbm.at[orow0 + k], soffs.at[k],
                                  sems.at[1 + k]).wait()
        w = win[...]
        pos4 = ((jax.lax.broadcasted_iota(jnp.int32, (MwS, LANE), 0) * LANE
                 + jax.lax.broadcasted_iota(jnp.int32, (MwS, LANE), 1)) * 4)
        base_b = sw_ref[b] * LANE * 4
        for lr in range(RB):
            r = b * RB + lr
            o_lo = soffs[(r // LANE) - orow0, r % LANE]
            o_hi = soffs[((r + 1) // LANE) - orow0, (r + 1) % LANE]
            q = o_lo - base_b                    # byte pos within window
            rolled = _byte_roll(w, -q)[:MwS]
            keep = _byte_keep_mask(pos4, 0, o_hi - o_lo)
            out_ref[0, lr] = rolled & keep

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nblocks,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((1, RB, MwS, LANE), lambda b, *_: (b, 0, 0, 0)),
        scratch_shapes=[pltpu.VMEM((KS, LANE), jnp.uint32),
                        pltpu.SMEM((KOFF, LANE), jnp.int32),
                        pltpu.SemaphoreType.DMA((1 + KOFF,))])
    return jax.jit(pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((nblocks, RB, MwS, LANE), jnp.uint32),
        compiler_params=pltpu.CompilerParams(has_side_effects=True)))


# ---------------------------------------------------------------------------
# segmented_copy: arbitrary monotone byte segments, src_flat → dst_flat
# ---------------------------------------------------------------------------

def segmented_copy(src: jnp.ndarray, src_offs: np.ndarray,
                   dst_offs: np.ndarray, sizes: np.ndarray,
                   dst_size: int, block_bytes: int = 8192) -> jnp.ndarray:
    """Copy n byte segments ``src[src_offs[k] : +sizes[k]] →
    dst[dst_offs[k] : +sizes[k]]`` on TPU.  Bytes of ``dst`` not covered by
    any segment are zero.

    Requirements: ``dst_offs`` strictly non-decreasing with non-overlapping
    [dst_offs[k], +sizes[k]) ranges, and ``src_offs`` non-decreasing (so
    each destination block's sources fit one contiguous staged window —
    true for every use in this package: JCUDF row pack/unpack, per-column
    string extraction, fixed-region extraction).  Byte-granular, no
    alignment requirements.  Runs under ``jax.enable_x64(False)`` — see
    :func:`pack_rows`.
    """
    with _x64_off():
        return _segmented_copy_impl(src, src_offs, dst_offs, sizes,
                                    dst_size, block_bytes)


def _segmented_copy_impl(src, src_offs, dst_offs, sizes, dst_size, B):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    src_offs = np.asarray(src_offs, dtype=np.int64)
    dst_offs = np.asarray(dst_offs, dtype=np.int64)
    sizes = np.asarray(sizes, dtype=np.int64)
    n = sizes.shape[0]
    if dst_size == 0:
        return jnp.zeros((0,), jnp.uint8)
    if n == 0:
        return jnp.zeros((dst_size,), jnp.uint8)
    if int(sizes.max(initial=0)) > B:
        # ValueError so copy_segments degrades to the XLA fallback (an
        # assert would escape that handler and vanish under python -O)
        raise ValueError("segmented_copy: segment larger than block")

    nblocks = _soft_bucket(-(-dst_size // B), 1)   # bucketed: shared compiles
    Bw = B // 4
    SB = Bw // LANE
    dst_end = dst_offs + sizes
    # segments intersecting each dst block (blocks past dst_size get ns=0)
    s_begin = np.searchsorted(dst_end, np.arange(nblocks, dtype=np.int64) * B,
                              side="right")
    s_end = np.searchsorted(dst_offs,
                            np.minimum(np.arange(1, nblocks + 1,
                                                 dtype=np.int64) * B,
                                       dst_size), side="left")
    # segment count BEFORE the index clamp: blocks past dst_size (from the
    # nblocks bucketing) have s_begin == s_end == n and must get ns=0, or
    # each would pay a window DMA + roll for a fully-masked stale segment
    ns = np.maximum(s_end - s_begin, 0).astype(np.int32)
    s_begin = np.minimum(s_begin, np.maximum(s_end - 1, 0))

    # staged source window per block (512B-aligned)
    w_begin = src_offs[np.minimum(s_begin, n - 1)]
    w0 = (w_begin // _WINDOW_ALIGN) * _WINDOW_ALIGN
    last = np.maximum(s_end - 1, 0)
    span = (src_offs[last] + sizes[last]) - w0
    span = np.where(ns > 0, span, 1)
    KSw = _pow2_bucket(int(np.max(span)) // 4 // LANE + 2, 8)
    KSw = max(KSw, SB)        # rolled window must cover one output block
    if KSw * LANE * 4 > (1 << 21):
        raise ValueError("segmented_copy: source window exceeds VMEM budget")

    S = int(src.shape[0])
    src_rows = _soft_bucket(-(-S // (LANE * 4)) + KSw)
    src_pad = jnp.pad(src, (0, src_rows * LANE * 4 - S))
    src32 = u8_to_u32(src_pad).reshape(src_rows, LANE)

    # max segments per block bounds the meta staging
    NSMAX = int(np.max(ns)) if nblocks else 1
    KMETA = _pow2_bucket(NSMAX // LANE + 2, 2)

    # per-segment metadata staged from HBM: src_off, dst_off, size (rows
    # sized so every staged window m0..m0+KMETA stays in bounds)
    def _meta2d(a):
        rows = _soft_bucket(-(-n // LANE) + KMETA + 1)
        return jnp.asarray(np.pad(a.astype(np.int32), (0, rows * LANE - n))
                           .reshape(rows, LANE))
    srcm, dstm, szm = _meta2d(src_offs), _meta2d(dst_offs), _meta2d(sizes)

    sw = (w0 // 4 // LANE).astype(np.int32)      # window start (sublane rows)
    sb32 = s_begin.astype(np.int32)

    out = _segcopy_call(nblocks, SB, B, KSw, KMETA)(
        jnp.asarray(sw), jnp.asarray(sb32), jnp.asarray(ns),
        srcm, dstm, szm, src32)
    return u32_to_u8(out.reshape(-1))[:dst_size]


@functools.lru_cache(maxsize=512)
def _segcopy_call(nblocks, SB, B, KSw, KMETA):
    """Cached jitted pallas_call for one segmented-copy geometry (see
    _pack_call)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(sw_ref, sb_ref, ns_ref, srcm_hbm, dstm_hbm, szm_hbm, src_hbm,
               out_ref, win, ssrc, sdst, ssz, sems):
        b = pl.program_id(0)
        m0 = sb_ref[b] // LANE

        @pl.when(ns_ref[b] > 0)
        def _stage():
            dma = pltpu.make_async_copy(src_hbm.at[pl.ds(sw_ref[b], KSw)],
                                        win, sems.at[0])
            dma.start()
            for k in range(KMETA):
                pltpu.make_async_copy(srcm_hbm.at[m0 + k], ssrc.at[k],
                                      sems.at[1 + 3 * k]).start()
                pltpu.make_async_copy(dstm_hbm.at[m0 + k], sdst.at[k],
                                      sems.at[2 + 3 * k]).start()
                pltpu.make_async_copy(szm_hbm.at[m0 + k], ssz.at[k],
                                      sems.at[3 + 3 * k]).start()
            dma.wait()
            for k in range(KMETA):
                pltpu.make_async_copy(srcm_hbm.at[m0 + k], ssrc.at[k],
                                      sems.at[1 + 3 * k]).wait()
                pltpu.make_async_copy(dstm_hbm.at[m0 + k], sdst.at[k],
                                      sems.at[2 + 3 * k]).wait()
                pltpu.make_async_copy(szm_hbm.at[m0 + k], ssz.at[k],
                                      sems.at[3 + 3 * k]).wait()

        w = win[...]
        blk_start = b * B
        base_b = sw_ref[b] * jnp.int32(LANE * 4)
        pos4 = ((jax.lax.broadcasted_iota(jnp.int32, (SB, LANE), 0)
                 * jnp.int32(LANE)
                 + jax.lax.broadcasted_iota(jnp.int32, (SB, LANE), 1))
                * jnp.int32(4))

        def body(i, acc):
            s = sb_ref[b] + i
            row = (s // LANE) - m0
            col = s % LANE
            so = ssrc[row, col]
            do = sdst[row, col]
            L = ssz[row, col]
            a = so - base_b                      # src byte pos in window
            p = do - blk_start                   # dst byte pos in block
            rolled = _byte_roll(w, p - a)[:SB]
            keep = _byte_keep_mask(pos4, p, p + L)
            return acc | (rolled & keep)

        acc = jax.lax.fori_loop(0, ns_ref[b], body,
                                jnp.zeros((SB, LANE), jnp.uint32))
        out_ref[...] = acc[None]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(nblocks,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 4,
        out_specs=pl.BlockSpec((1, SB, LANE), lambda b, *_: (b, 0, 0)),
        scratch_shapes=[pltpu.VMEM((KSw, LANE), jnp.uint32),
                        pltpu.SMEM((KMETA, LANE), jnp.int32),
                        pltpu.SMEM((KMETA, LANE), jnp.int32),
                        pltpu.SMEM((KMETA, LANE), jnp.int32),
                        pltpu.SemaphoreType.DMA((1 + 3 * KMETA,))])
    return jax.jit(pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((nblocks, SB, LANE), jnp.uint32),
        compiler_params=pltpu.CompilerParams(has_side_effects=True)))


def segmented_copy_xla(src, src_offs, dst_offs, sizes, dst_size):
    """Gather-formulated fallback for CPU backends."""
    src_offs = np.asarray(src_offs, dtype=np.int64)
    dst_offs = np.asarray(dst_offs, dtype=np.int64)
    sizes = np.asarray(sizes, dtype=np.int64)
    if dst_size == 0 or sizes.shape[0] == 0:
        return jnp.zeros((dst_size,), jnp.uint8)
    # segment of each dst byte (host-side geometry; offsets are host arrays)
    dst_end = dst_offs + sizes
    o = jnp.arange(dst_size, dtype=jnp.int32)
    seg = jnp.asarray(
        np.searchsorted(dst_end, np.arange(dst_size), side="right")
        .astype(np.int32))
    seg = jnp.clip(seg, 0, sizes.shape[0] - 1)
    so = jnp.asarray(src_offs.astype(np.int32))[seg]
    do = jnp.asarray(dst_offs.astype(np.int32))[seg]
    sz = jnp.asarray(sizes.astype(np.int32))[seg]
    within = o - do
    keep = (within >= 0) & (within < sz)
    if src.shape[0] == 0:
        return jnp.zeros((dst_size,), jnp.uint8)
    vals = src[jnp.clip(so + within, 0, src.shape[0] - 1)]
    return jnp.where(keep, vals, 0)


def copy_segments(src, src_offs, dst_offs, sizes, dst_size):
    """Dispatching segmented copy: DMA kernel on TPU, XLA gather elsewhere."""
    if dma_supported():
        try:
            return segmented_copy(src, src_offs, dst_offs, sizes, dst_size)
        except ValueError:   # window exceeds VMEM budget — degrade
            pass
    return segmented_copy_xla(src, src_offs, dst_offs, sizes, dst_size)


# ---------------------------------------------------------------------------
# XLA fallback (CPU backends / SRJT_RAGGED_DMA=0): the gather formulation.
# Correct everywhere; slow on TPU (scalarized gather) — the kernels above
# exist precisely because of that.
# ---------------------------------------------------------------------------

def _segment_of(starts: jnp.ndarray, total: int) -> jnp.ndarray:
    markers = jnp.zeros((total,), dtype=jnp.int32).at[starts[1:-1]].add(1)
    return jnp.cumsum(markers)


def pack_rows_xla(dense: jnp.ndarray, row_offsets: np.ndarray) -> jnp.ndarray:
    n, M = dense.shape
    offs = np.asarray(row_offsets, dtype=np.int64)
    total = int(offs[-1])
    if total == 0 or n == 0:
        return jnp.zeros((0,), jnp.uint8)
    offs_dev = jnp.asarray(offs.astype(np.int32))
    row_of = _segment_of(offs_dev, total)
    w = jnp.arange(total, dtype=jnp.int32) - offs_dev[row_of]
    return dense.reshape(-1)[row_of * M + w]


def unpack_rows_xla(flat: jnp.ndarray, row_offsets: np.ndarray,
                    M: int) -> jnp.ndarray:
    offs = np.asarray(row_offsets, dtype=np.int64)
    n = offs.shape[0] - 1
    if n == 0:
        return jnp.zeros((0, M), jnp.uint8)
    offs_dev = jnp.asarray(offs.astype(np.int32))
    sizes = offs_dev[1:] - offs_dev[:-1]
    j = jnp.arange(M, dtype=jnp.int32)
    idx = offs_dev[:-1, None] + j[None, :]
    keep = j[None, :] < sizes[:, None]
    if flat.shape[0] == 0:
        return jnp.zeros((n, M), jnp.uint8)
    vals = flat[jnp.clip(idx, 0, flat.shape[0] - 1)]
    return jnp.where(keep, vals, 0)


def pack(dense: jnp.ndarray, row_offsets: np.ndarray) -> jnp.ndarray:
    """Dispatching pack: DMA kernels on TPU, XLA gather elsewhere."""
    if dma_supported():
        try:
            return pack_rows(dense, row_offsets)
        except ValueError:   # row window exceeds VMEM budget — degrade
            pass
    return pack_rows_xla(dense, row_offsets)


def unpack(flat: jnp.ndarray, row_offsets: np.ndarray, M: int) -> jnp.ndarray:
    """Dispatching unpack: DMA kernels on TPU, XLA gather elsewhere."""
    if dma_supported():
        try:
            return unpack_rows(flat, row_offsets, M)
        except ValueError:   # row span exceeds VMEM window — degrade
            pass
    return unpack_rows_xla(flat, row_offsets, M)
