"""Device row↔column transcode (JCUDF) — the XLA path.

TPU-native reimplementation of the reference's CUDA engine
(``row_conversion.cu``; public surface ``row_conversion.hpp:27-49``).  Design
translation (see SURVEY §7):

* The reference hand-tiles shared memory and double-buffers
  ``cuda::memcpy_async`` (``row_conversion.cu:575-693,892-993``).  On TPU the
  fixed-width transcode works at u32-word granularity end to end: each row
  word is composed from a statically-planned set of column fragments
  (shift/or tree), and the column->row interleave is one layout-preserving
  3-D permute (or, for wide rows, one 2-D transpose) whose output minor
  dimension is a 128-lane multiple.  Measured on the target chip
  (tools/profile_transcode.py, round 3) these formulations run at 250-750
  GB/s vs ~45-135 GB/s for strided lane writes and ~22 GB/s for a final
  u32->u8 repack — which is why :class:`RowBatch` carries the row bytes AS
  u32 words (JCUDF rows are 8-byte aligned, so the words are exact).
* The warp-ballot validity transpose (``row_conversion.cu:710-810``)
  becomes a weighted-sum bit pack (``utils.bitmask.pack_bool_matrix``).
* Variable-width (string) handling follows the reference's two-phase shape
  discipline (size pass → alloc → copy pass; the reference syncs on the total
  at ``row_conversion.cu:2215``): row sizes and char totals are resolved on
  host, then a statically-shaped jitted scatter/gather does the copies.
* Output is split into ≤2GB batches exactly like ``build_batches``
  (``row_conversion.cu:1460-1539``); ``convert_from_rows`` accepts exactly one
  batch (``row_conversion.cu:2124-2139``).

Dynamic-shape note: everything under ``jit`` here is static-shaped; the only
host syncs are the same ones the reference performs (string totals).
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..column import Column, DictColumn, Table, as_dict_column, force_column
from ..faultinj import fault_site
from ..utils import bitmask, knobs, metrics, syncs
from ..utils.tracing import traced
from .layout import (RowLayout, compute_row_layout, build_batches,
                     row_sizes_with_strings, MAX_ROW_SIZE, MAX_BATCH_BYTES,
                     BATCH_ROW_MULTIPLE)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class RowBatch:
    """One ≤2GB batch of JCUDF rows: the LIST<INT8> column analog
    (``row_conversion.cu:1869-1889``).

    ``data`` is the packed row byte stream, stored either as uint8
    [total_bytes] (variable-width batches, byte-granular DMA engine) or as
    uint32 [total_bytes/4] little-endian words (fixed-width batches — rows
    are 8-byte aligned so the word view is exact, and keeping words avoids
    a ~22 GB/s u32->u8 relayout pass on TPU).  Both views describe the
    identical JCUDF byte stream; :meth:`host_bytes` is the canonical byte
    materialization.
    """

    data: jnp.ndarray      # uint8 [total_bytes] or uint32 [total_bytes/4]
    offsets: jnp.ndarray   # int32 [num_rows + 1] byte offsets

    def tree_flatten(self):
        return (self.data, self.offsets), None

    @classmethod
    def tree_unflatten(cls, _, children):
        return cls(*children)

    @property
    def num_rows(self) -> int:
        return self.offsets.shape[0] - 1

    @property
    def num_bytes(self) -> int:
        return self.data.shape[0] * self.data.dtype.itemsize

    def host_bytes(self) -> np.ndarray:
        """The JCUDF byte stream as host uint8 (exact for either storage)."""
        raw = np.ascontiguousarray(np.asarray(self.data))
        return raw.view(np.uint8)

    def device_u8(self) -> jnp.ndarray:
        """The byte stream as a device uint8 array (converts if u32)."""
        if self.data.dtype == jnp.uint8:
            return self.data
        return _words_to_bytes(self.data)


def _is_f64(storage: np.dtype) -> bool:
    return storage.kind == "f" and storage.itemsize == 8


def _byte_view(data: jnp.ndarray, storage: np.dtype) -> jnp.ndarray:
    """[n] fixed-width payload → uint8 [n, itemsize] (little-endian).

    FLOAT64 payloads are uint32 [n, 2] bit pairs by Column invariant
    (``utils.f64bits`` — XLA:TPU exposes no bit-level access to its emulated
    f64), so the transcode — which only moves bytes, never does arithmetic —
    works on the u32 halves.
    """
    if _is_f64(storage):
        return jax.lax.bitcast_convert_type(data, jnp.uint8).reshape(
            data.shape[0], 8)
    data = data.astype(storage)
    if storage.itemsize == 1:
        return data.view(jnp.uint8).reshape(-1, 1)
    return jax.lax.bitcast_convert_type(data, jnp.uint8)


def _from_bytes(b: jnp.ndarray, storage: np.dtype) -> jnp.ndarray:
    """uint8 [n, itemsize] → [n] payload (f64: uint32 [n,2] bit pairs)."""
    if _is_f64(storage):
        # flat u32 then reshape — the direct 3-D bitcast pays a ~15×
        # narrow-minor layout penalty on TPU (measured round 3)
        return jax.lax.bitcast_convert_type(
            b.reshape(-1, 4), jnp.uint32).reshape(-1, 2)
    if storage.itemsize == 1:
        return b.reshape(-1).view(jnp.dtype(storage))
    return jax.lax.bitcast_convert_type(b, jnp.dtype(storage))


def _byte_view_dt(data: jnp.ndarray, dt) -> jnp.ndarray:
    """DType-aware ``_byte_view``: DECIMAL128 [n, 2] int64 → u8 [n, 16]."""
    if dt.id == T.TypeId.DECIMAL128:
        return jax.lax.bitcast_convert_type(data, jnp.uint8).reshape(
            data.shape[0], 16)
    return _byte_view(data, dt.storage)


def _from_bytes_dt(b: jnp.ndarray, dt) -> jnp.ndarray:
    """DType-aware ``_from_bytes``: u8 [n, 16] → DECIMAL128 [n, 2] int64."""
    if dt.id == T.TypeId.DECIMAL128:
        return jax.lax.bitcast_convert_type(b.reshape(-1, 2, 8), jnp.int64)
    return _from_bytes(b, dt.storage)


# ---------------------------------------------------------------------------
# fixed-width core: [cols…] → uint32 row words [n * W]
# ---------------------------------------------------------------------------

# Row-word counts up to which the layout-preserving 3-D permute beats one
# big 2-D transpose, per direction.  Measured on the target chip
# (tools/profile_transcode.py + crossover sweep, round 3):
#   interleave  perm3/transpose GB/s — W=11: 343/136, W=24: 747/263,
#                                      W=40: 351/323, W=53: 154/375
#   deinterleave                      — W=11: 286/51,  W=24: 469/101,
#                                      W=32: 145/254, W=53: 154/372
_IL_PERM3_MAX_W = 40
_DL_PERM3_MAX_W = 24


def _interleave_words(words: list[jnp.ndarray], W: int) -> jnp.ndarray:
    """[W] u32 vectors of [n_pad] (n_pad % 128 == 0) → flat JCUDF word
    stream u32 [n_pad * W] with out[r*W + w] = words[w][r]."""
    x = jnp.stack(words, axis=0)                        # [W, n_pad]
    n_pad = x.shape[1]
    if W <= _IL_PERM3_MAX_W:
        # layout-preserving permute: every reshape boundary is a 128-lane
        # multiple, so XLA never materializes a padded-minor temporary
        return x.reshape(W, n_pad // 128, 128).transpose(1, 2, 0).reshape(-1)
    return x.T.reshape(-1)


def _deinterleave_words(flat: jnp.ndarray, W: int) -> jnp.ndarray:
    """Inverse of :func:`_interleave_words`: u32 [n_pad*W] → [W, n_pad]."""
    if W <= _DL_PERM3_MAX_W:
        return flat.reshape(-1, 128, W).transpose(2, 0, 1).reshape(W, -1)
    return flat.reshape(-1, W).T


@jax.jit
def _words_to_bytes(w: jnp.ndarray) -> jnp.ndarray:
    """u32 [N] → u8 [4N] little-endian (byte-boundary use only)."""
    return jax.lax.bitcast_convert_type(w, jnp.uint8).reshape(-1)


@jax.jit
def _bytes_to_words(b: jnp.ndarray) -> jnp.ndarray:
    """u8 [4N] → u32 [N] little-endian."""
    return jax.lax.bitcast_convert_type(b.reshape(-1, 4), jnp.uint32)


def _word_plan(layout: RowLayout):
    """For each u32 word of the row, the static list of fragments.

    Fragment = (input_index, kind, arg):
      kind 'full'  — input is u32 [n], the whole word                (size 4)
      kind 'pair'  — input is u32 [n, k], arg selects the lane      (size 8/16)
      kind 'sub'   — input is zero-extended u32 [n], arg = byte shift (size <4)
      kind 'vbyte' — input is the validity byte k, arg = (k, shift)
    Input order: one staged array per column, then the validity bytes.
    Every fixed slot is aligned to its own size (compute_column_information,
    ``row_conversion.cu:1331-1370``), so fragments never straddle words.
    """
    W = layout.fixed_row_size // 4
    plan: list[list[tuple[int, str, object]]] = [[] for _ in range(W)]
    for ci, dt in enumerate(layout.schema):
        start = layout.column_starts[ci]
        size = layout.column_sizes[ci]
        if size == 16:   # DECIMAL128: staged u32 [n, 4], four words
            for j in range(4):
                plan[start // 4 + j].append((ci, "pair", j))
        elif size == 8:
            plan[start // 4].append((ci, "pair", 0))
            plan[start // 4 + 1].append((ci, "pair", 1))
        elif size == 4:
            plan[start // 4].append((ci, "full", None))
        else:  # 1 or 2; alignment keeps it inside one word
            plan[start // 4].append((ci, "sub", start % 4))
    vi = layout.num_columns
    vo = layout.validity_offset
    for k in range(layout.validity_bytes):
        byte = vo + k
        plan[byte // 4].append((vi, "vbyte", (k, byte % 4)))
    return plan


def _stage_column(data: jnp.ndarray, storage: np.dtype) -> jnp.ndarray:
    """Column payload → u32 staged form for the word plan: 8-byte columns as
    u32 [n, 2] halves, 4-byte bitcast, sub-word zero-extended.  FLOAT64 is
    already stored as u32 [n, 2] bit pairs (Column invariant)."""
    if _is_f64(storage):
        return data
    data = data.astype(storage)
    if storage.itemsize == 8:
        return jax.lax.bitcast_convert_type(data, jnp.uint32)   # [n, 2]
    if storage.itemsize == 4:
        return jax.lax.bitcast_convert_type(data, jnp.uint32)   # [n]
    unsigned = np.dtype(f"u{storage.itemsize}")
    return jax.lax.bitcast_convert_type(data, unsigned).astype(jnp.uint32)


def _stage_column_dt(data: jnp.ndarray, dt) -> jnp.ndarray:
    """DType-aware staging: DECIMAL128 [n, 2] int64 lanes → u32 [n, 4]."""
    if dt.id == T.TypeId.DECIMAL128:
        return jax.lax.bitcast_convert_type(
            data, jnp.uint32).reshape(data.shape[0], 4)
    return _stage_column(data, dt.storage)


def _pack_validity_words(layout: RowLayout,
                         valid: jnp.ndarray) -> list[jnp.ndarray]:
    """Per validity byte k: u32 [n] vector with the byte's bits in the low
    8 — shared by both fixed compose engines (the byte-identical invariant
    the differential test pins depends on ONE packing implementation)."""
    n = valid.shape[0]
    out = []
    for k in range(layout.validity_bytes):
        acc = jnp.zeros((n,), jnp.uint32)
        for i in range(min(8, layout.num_columns - k * 8)):
            acc = acc | (valid[:, k * 8 + i].astype(jnp.uint32)
                         << jnp.uint32(i))
        out.append(acc)
    return out


@functools.partial(jax.jit, static_argnums=0)
def _to_rows_fixed_words(layout: RowLayout, datas: tuple[jnp.ndarray, ...],
                         valid: jnp.ndarray) -> jnp.ndarray:
    """Fixed-width columns + validity matrix → flat u32 row words [n*W].

    Compose each row word as a [n]-long u32 vector from statically-planned
    column fragments (one shift/or tree per word — the data transpose and
    the warp-ballot validity pack of ``row_conversion.cu:575-810`` fused
    into one pass), then interleave with a single layout-preserving permute.
    """
    n = valid.shape[0]
    W = layout.fixed_row_size // 4
    n_pad = -(-n // 128) * 128

    def padrows(x):
        return jnp.pad(x, [(0, n_pad - n)] + [(0, 0)] * (x.ndim - 1))

    staged = [padrows(_stage_column_dt(d, dt))
              for d, dt in zip(datas, layout.schema)]
    vbytes_w = [padrows(v) for v in _pack_validity_words(layout, valid)]

    plan = _word_plan(layout)
    words = []
    for w in range(W):
        acc = None
        for ii, kind, arg in plan[w]:
            if kind == "vbyte":
                k, shift = arg
                v = vbytes_w[k] << jnp.uint32(shift * 8)
            else:
                x = staged[ii]
                if kind == "full":
                    v = x
                elif kind == "pair":
                    v = x[:, arg]
                else:
                    v = x << jnp.uint32(arg * 8)
            acc = v if acc is None else acc | v
        words.append(acc if acc is not None
                     else jnp.zeros((n_pad,), jnp.uint32))
    flat = _interleave_words(words, W)
    return flat[:n * W] if n_pad != n else flat


def _decode_row_words(layout: RowLayout, word, n: int):
    """Shared word-level row decoder.

    ``word(w)`` returns the u32 vector (length ≥ n) holding row word ``w``
    for every row — from the fixed-path deinterleave or from the xpack
    dense row-window matrix alike.  Returns ``(datas, valid, slots)`` where
    ``datas`` has ``None`` at variable-width columns and ``slots`` carries
    each variable column's (offset, length) u32 [n, 2] pairs.  Every fixed
    slot is aligned to its own size and string slots to 4
    (compute_column_information, ``row_conversion.cu:1331-1370``), so no
    fragment straddles a word.
    """
    datas = []
    slots = []
    for ci, dt in enumerate(layout.schema):
        start = layout.column_starts[ci]
        size = layout.column_sizes[ci]
        if dt.is_variable_width:
            slots.append(jnp.stack([word(start // 4)[:n],
                                    word(start // 4 + 1)[:n]], axis=1))
            datas.append(None)
            continue
        if size == 16:   # DECIMAL128: four words → [n, 2] int64 lanes
            quad = jnp.stack([word(start // 4 + j) for j in range(4)],
                             axis=1)[:n]
            datas.append(jax.lax.bitcast_convert_type(
                quad.reshape(-1, 2, 2), jnp.int64))
            continue
        st = dt.storage
        if size == 8:
            pair = jnp.stack([word(start // 4), word(start // 4 + 1)],
                             axis=1)[:n]
            if _is_f64(st):
                datas.append(pair)           # u32 [n, 2] IS the f64 storage
            else:
                datas.append(jax.lax.bitcast_convert_type(pair,
                                                          jnp.dtype(st)))
        elif size == 4:
            datas.append(jax.lax.bitcast_convert_type(word(start // 4),
                                                      jnp.dtype(st))[:n])
        else:
            v = ((word(start // 4) >> jnp.uint32(8 * (start % 4)))
                 & jnp.uint32((1 << (8 * size)) - 1))
            unsigned = np.dtype(f"u{size}")
            datas.append(jax.lax.bitcast_convert_type(
                v.astype(jnp.dtype(unsigned)), jnp.dtype(st))[:n])
    vcols = []
    for c in range(layout.num_columns):
        byte = layout.validity_offset + c // 8
        bit = ((word(byte // 4) >> jnp.uint32(8 * (byte % 4) + c % 8))
               & jnp.uint32(1))
        vcols.append(bit.astype(jnp.bool_)[:n])
    valid = jnp.stack(vcols, axis=1)
    return tuple(datas), valid, tuple(slots)


# Concat-based fixed compose (round-5 alternate engine, SRJT_FIXED_CONCAT):
# instead of composing W per-word [n] vectors and permuting them into row
# order, build the [n, W] row-word matrix DIRECTLY as one axis-1
# concatenate of per-column u32 blocks — an 8-byte column's natural
# [n, 2] bitcast IS its two adjacent row words, so the formulation has no
# per-word lane selects and no 3-D permute; alignment gaps become zero
# blocks and co-worded sub-byte columns pre-combine.  The inverse slices
# the same blocks back out.  Chip A/B decides the default; both paths are
# byte-identical (differential-tested).

def _word_blocks(layout: RowLayout):
    """Static [start_word, word_count, members] runs covering the row:
    members = [(col_index | 'valid', kind, arg)] sharing the run."""
    W = layout.fixed_row_size // 4
    owner: list[list] = [[] for _ in range(W)]
    for ci, dt in enumerate(layout.schema):
        start = layout.column_starts[ci]
        size = layout.column_sizes[ci]
        w0 = start // 4
        if size >= 4:
            for j in range(size // 4):
                owner[w0 + j].append((ci, "wide", j))
        else:
            owner[w0].append((ci, "sub", start % 4))
    vo = layout.validity_offset
    for k in range(layout.validity_bytes):
        byte = vo + k
        owner[byte // 4].append(("valid", "vbyte", (k, byte % 4)))
    return owner


@functools.partial(jax.jit, static_argnums=0)
def _to_rows_fixed_concat(layout: RowLayout, datas: tuple[jnp.ndarray, ...],
                          valid: jnp.ndarray) -> jnp.ndarray:
    """Fixed-width columns + validity matrix → flat u32 row words [n*W]
    via ONE axis-1 concatenate of per-column blocks."""
    n = valid.shape[0]
    W = layout.fixed_row_size // 4
    owner = _word_blocks(layout)
    staged = {}

    def stage(ci):
        if ci not in staged:
            staged[ci] = _stage_column_dt(datas[ci], layout.schema[ci])
        return staged[ci]

    vbytes_w = _pack_validity_words(layout, valid)

    blocks = []
    w = 0
    while w < W:
        mem = owner[w]
        if not mem:
            # alignment gap: extend over the whole zero run
            w1 = w
            while w1 < W and not owner[w1]:
                w1 += 1
            blocks.append(jnp.zeros((n, w1 - w), jnp.uint32))
            w = w1
            continue
        if len(mem) == 1 and mem[0][1] == "wide" and mem[0][2] == 0:
            ci = mem[0][0]
            x = stage(ci)
            blocks.append(x[:, None] if x.ndim == 1 else x)
            w += 1 if x.ndim == 1 else x.shape[1]
            continue
        # mixed word: sub-word columns and/or validity bytes combine
        acc = jnp.zeros((n,), jnp.uint32)
        for ci, kind, arg in mem:
            if kind == "vbyte":
                k, shift = arg
                acc = acc | (vbytes_w[k] << jnp.uint32(shift * 8))
            elif kind == "sub":
                acc = acc | (stage(ci) << jnp.uint32(arg * 8))
            else:                      # a wide column's j-th word
                x = stage(ci)
                acc = acc | (x if x.ndim == 1 else x[:, arg])
        blocks.append(acc[:, None])
        w += 1
    return jnp.concatenate(blocks, axis=1).reshape(-1)


@functools.partial(jax.jit, static_argnums=0)
def _from_rows_fixed_concat(layout: RowLayout, flat: jnp.ndarray):
    """Inverse: [n, W] row-word matrix sliced back into column blocks."""
    W = layout.fixed_row_size // 4
    n = flat.shape[0] // W
    m2 = flat.reshape(n, W)
    datas = []
    for ci, dt in enumerate(layout.schema):
        start = layout.column_starts[ci]
        size = layout.column_sizes[ci]
        w0 = start // 4
        if size == 16:
            quad = m2[:, w0:w0 + 4]
            datas.append(jax.lax.bitcast_convert_type(
                quad.reshape(-1, 2, 2), jnp.int64))
            continue
        st = dt.storage
        if size == 8:
            pair = m2[:, w0:w0 + 2]
            datas.append(pair if _is_f64(st)
                         else jax.lax.bitcast_convert_type(pair,
                                                           jnp.dtype(st)))
        elif size == 4:
            datas.append(jax.lax.bitcast_convert_type(m2[:, w0],
                                                      jnp.dtype(st)))
        else:
            v = ((m2[:, w0] >> jnp.uint32(8 * (start % 4)))
                 & jnp.uint32((1 << (8 * size)) - 1))
            unsigned = np.dtype(f"u{size}")
            datas.append(jax.lax.bitcast_convert_type(
                v.astype(jnp.dtype(unsigned)), jnp.dtype(st)))
    vcols = []
    for c in range(layout.num_columns):
        byte = layout.validity_offset + c // 8
        bit = ((m2[:, byte // 4] >> jnp.uint32(8 * (byte % 4) + c % 8))
               & jnp.uint32(1))
        vcols.append(bit.astype(jnp.bool_))
    return tuple(datas), jnp.stack(vcols, axis=1)


def _fixed_engine(direction: str) -> str:
    """Measured round-5 policy (chip A/B, BASELINE.md): compose-to-rows
    keeps the perm3 word engine (39.8/57.2 GB/s vs concat's 28.2 and a
    64x-padding OOM at 212 cols — axis-1 concatenate of narrow blocks
    writes terribly), while decode-from-rows uses the concat engine
    everywhere (contiguous [n, W] slices: 64.1 GB/s at 12 cols, 825 GB/s
    at 212 vs perm's 26.5/192.7).  SRJT_FIXED_CONCAT overrides both
    directions for A/B; read OUTSIDE jit and passed as a static arg."""
    env = knobs.get("SRJT_FIXED_CONCAT")
    if env is not None:
        return "concat" if env.lower() in ("1", "on") else "perm"
    return "perm" if direction == "to" else "concat"


@functools.partial(jax.jit, static_argnums=0)
def _from_rows_fixed_words(layout: RowLayout, flat: jnp.ndarray):
    """Flat u32 row words [n*W] → (datas tuple, valid bool [n, ncols])."""
    W = layout.fixed_row_size // 4
    n = flat.shape[0] // W
    n_pad = -(-n // 128) * 128
    if n_pad != n:
        flat = jnp.pad(flat, (0, (n_pad - n) * W))
    t2 = _deinterleave_words(flat, W)                    # [W, n_pad]
    datas, valid, _ = _decode_row_words(layout, lambda w: t2[w], n)
    return datas, valid


# Fused whole-call cores for the public fixed-width path.  The orchestration
# around the reference's kernels is host code (offset columns built with
# Thrust + D2D copies, row_conversion.cu:1460-1539); on a remote-dispatch TPU
# that host work (and its H2D offset upload) dominates, so the full call —
# validity-matrix build, word compose, interleave, offsets arange — is one
# jit program and the only transfer is the column payloads already in HBM.

@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _to_rows_fixed_full(layout: RowLayout, has_valid: tuple[bool, ...],
                        engine: str,
                        datas: tuple[jnp.ndarray, ...],
                        valids: tuple[jnp.ndarray, ...]):
    """Fixed-width table → (flat u32 row words, int32 row offsets), one
    dispatch.  ``valids`` carries arrays only for columns where ``has_valid``
    is True; all-valid columns get their ones generated (and fused away)
    on device."""
    n = datas[0].shape[0]
    vi = iter(valids)
    cols_valid = [next(vi) if hv else jnp.ones((n,), dtype=jnp.bool_)
                  for hv in has_valid]
    valid = jnp.stack(cols_valid, axis=1)
    flat = (_to_rows_fixed_concat(layout, datas, valid)
            if engine == "concat"
            else _to_rows_fixed_words(layout, datas, valid))
    offsets = jnp.arange(n + 1, dtype=jnp.int32) * layout.fixed_row_size
    return flat, offsets


@functools.partial(jax.jit, static_argnums=(0, 1))
def _from_rows_fixed_full(layout: RowLayout, engine: str,
                          words: jnp.ndarray):
    """Flat u32 row words → (datas, per-column validity vectors)."""
    datas, valid = (_from_rows_fixed_concat(layout, words)
                    if engine == "concat"
                    else _from_rows_fixed_words(layout, words))
    valids = tuple(valid[:, ci] for ci in range(layout.num_columns))
    return datas, valids


# ---------------------------------------------------------------------------
# variable-width core (strings): statically-shaped scatter/gather
# ---------------------------------------------------------------------------

def _segment_of(starts: jnp.ndarray, total: int) -> jnp.ndarray:
    """For each position in [0, total): the index of the sorted segment
    containing it.  ``starts`` is int32 [S+1] inclusive starts with a final
    sentinel == total.

    One tiny scatter-add (S markers) + one cumsum — the TPU-friendly
    replacement for a per-position binary search.  Empty segments (repeated
    starts) accumulate multiple increments at one position, so positions
    correctly skip past them.
    """
    markers = jnp.zeros((total,), dtype=jnp.int32).at[starts[1:-1]].add(1)
    return jnp.cumsum(markers)


# shared outside rowconv (DictColumn.materialize, ops.filter string
# gathers, rle_device run lookup): every per-position binary search in
# the package routes through this one primitive
segment_of = _segment_of


@functools.partial(jax.jit, static_argnums=0)
def _var_fixed_region(layout: RowLayout, datas: tuple[jnp.ndarray, ...],
                      str_offsets: tuple[jnp.ndarray, ...],
                      valid: jnp.ndarray) -> jnp.ndarray:
    """Dense fixed region [n, fixed_plus_validity] for a variable-width
    schema: column slots, string (offset,len) slot pairs, validity bytes.
    Pure vector ops — shared by the DMA and XLA string paths."""
    n = valid.shape[0]
    var_idx = layout.variable_column_indices
    nvar = len(var_idx)
    fpv = layout.fixed_plus_validity
    lens = jnp.stack(
        [str_offsets[vi][1:] - str_offsets[vi][:-1] for vi in range(nvar)],
        axis=1).astype(jnp.int32)                           # [n, nvar]
    prefix = jnp.cumsum(lens, axis=1) - lens
    fixed2d = jnp.zeros((n, fpv), dtype=jnp.uint8)
    vi_of_ci = {ci: vi for vi, ci in enumerate(var_idx)}
    for ci, dt in enumerate(layout.schema):
        start = layout.column_starts[ci]
        if dt.is_variable_width:
            vi = vi_of_ci[ci]
            slot_off = (fpv + prefix[:, vi]).astype(jnp.uint32)
            slot = jnp.stack([slot_off, lens[:, vi].astype(jnp.uint32)], axis=1)
            b = jax.lax.bitcast_convert_type(slot, jnp.uint8).reshape(n, 8)
        else:
            b = _byte_view_dt(datas[ci], dt)
        fixed2d = fixed2d.at[:, start:start + b.shape[1]].set(b)
    vbytes = bitmask.pack_bool_matrix(valid)
    return fixed2d.at[:, layout.validity_offset:
                      layout.validity_offset + layout.validity_bytes].set(vbytes)


# Above this many string columns the per-column segmented-copy passes (each
# touching the full char region) lose to the single-pass XLA gather path.
_DMA_MAX_VAR_COLS = 8

# from_rows DMA geometry needs per-row (offset, len) slots on the HOST; the
# tunnel streams D2H at single-digit MB/s, so above this row count the
# device-side gather path (which syncs only per-column char totals) wins.
_DMA_FROM_ROWS_MAX_N = 1 << 16


def _to_rows_var_dma(layout: RowLayout, sub: "Table", valid: jnp.ndarray,
                     offs_np: np.ndarray) -> Optional[jnp.ndarray]:
    """Strings → JCUDF rows via the ragged DMA engine (TPU).

    The reference stages tiles in shared memory and memcpy_asyncs them out
    (``copy_strings_to_rows``, row_conversion.cu:827-875); here the char
    region is assembled as dense per-row byte matrices — one
    :func:`ragged.unpack` when there is a single string column (its chars
    are already per-row contiguous), else one :func:`ragged.segmented_copy`
    per column — and one :func:`ragged.pack` flattens the dense rows into
    the packed JCUDF buffer.  All heavy byte movement is aligned bulk DMA +
    in-register rolls.

    Returns ``None`` for shapes where the engine loses to the XLA gather
    formulation (> ``_DMA_MAX_VAR_COLS`` string columns): the per-column
    passes each traverse the whole char region, so cost grows with the
    column count while the gather path scales with total bytes only.
    """
    from . import ragged
    n = sub.num_rows
    var_idx = layout.variable_column_indices
    nvar = len(var_idx)
    if nvar > _DMA_MAX_VAR_COLS:
        return None
    fpv = layout.fixed_plus_validity
    offs_np = np.asarray(offs_np, dtype=np.int64)
    sizes_np = offs_np[1:] - offs_np[:-1]
    # bucketed to limit distinct jit/kernel shapes (extra columns are zero)
    M = -(-int(sizes_np.max(initial=8)) // 64) * 64
    Mc = M - fpv

    from ..utils import hostcache
    col_offs_np = [hostcache.host_i64(sub[ci].offsets) for ci in var_idx]
    lens_np = np.stack([o[1:] - o[:-1] for o in col_offs_np], axis=1)
    prefix_np = np.cumsum(lens_np, axis=1) - lens_np

    # var columns' char payloads are unread by _var_fixed_region (slots come
    # from the offsets); zero-size placeholders keep its jit cache keyed on
    # (layout, n) only instead of every distinct char-buffer length
    fixed2d = _var_fixed_region(
        layout,
        tuple(jnp.zeros(0, jnp.uint8) if c.dtype.is_variable_width
              else c.data for c in sub.columns),
        tuple(sub[ci].offsets for ci in var_idx), valid)

    total_chars = int(lens_np.sum())
    if Mc > 0 and total_chars:
        if nvar == 1:
            # single string column: chars are already per-row contiguous
            cr = ragged.unpack(sub[var_idx[0]].data, col_offs_np[0], Mc)
        else:
            acc = None
            row_base_c = np.arange(n, dtype=np.int64) * Mc
            for vi, ci in enumerate(var_idx):
                part = ragged.copy_segments(
                    sub[ci].data, col_offs_np[vi][:-1],
                    row_base_c + prefix_np[:, vi], lens_np[:, vi], n * Mc)
                acc = part if acc is None else (acc | part)
            cr = acc.reshape(n, Mc)
        dense = jnp.concatenate([fixed2d, cr], axis=1)
    elif Mc > 0:
        dense = jnp.concatenate(
            [fixed2d, jnp.zeros((n, Mc), jnp.uint8)], axis=1)
    else:
        dense = fixed2d[:, :M] if fpv >= M else jnp.concatenate(
            [fixed2d, jnp.zeros((n, M - fpv), jnp.uint8)], axis=1)
    return ragged.pack(dense, offs_np)


@functools.partial(jax.jit, static_argnums=0)
def _var_fixed_extract(layout: RowLayout, fixed_dense: jnp.ndarray):
    """Inverse of :func:`_var_fixed_region`: dense [n, fpv] → (fixed column
    payloads, validity matrix, per-var-column (offset,len) u32 slots)."""
    n = fixed_dense.shape[0]
    datas = []
    slots = []
    for ci, dt in enumerate(layout.schema):
        start = layout.column_starts[ci]
        if dt.is_variable_width:
            b = fixed_dense[:, start:start + 8].reshape(n, 2, 4)
            slots.append(jax.lax.bitcast_convert_type(b, jnp.uint32))
            datas.append(None)
        else:
            b = fixed_dense[:, start:start + layout.column_sizes[ci]]
            datas.append(_from_bytes_dt(b, dt))
    vbytes = fixed_dense[:, layout.validity_offset:
                         layout.validity_offset + layout.validity_bytes]
    valid = bitmask.unpack_bool_matrix(vbytes, layout.num_columns)
    return datas, valid, tuple(slots)


@functools.partial(jax.jit, static_argnums=(0, 1))
def _to_rows_var(layout: RowLayout, total_bytes: int,
                 datas: tuple[jnp.ndarray, ...],
                 str_offsets: tuple[jnp.ndarray, ...],
                 valid: jnp.ndarray,
                 row_offsets: jnp.ndarray) -> jnp.ndarray:
    """Strings path: one gather pass over the output bytes.

    The reference's ``copy_to_rows``/``copy_strings_to_rows`` kernels
    (row_conversion.cu:575-693, 827-875) scatter from columns into rows; a
    scatter on TPU serializes, so this inverts the direction — every output
    byte *gathers* its source:

    1. the fixed region (column slots, string (offset,len) slots, validity) is
       built as a dense [n, fixed_plus_validity] matrix with vectorized
       column-slice writes;
    2. each flat output position finds its row via a marker-cumsum (no binary
       search), then either reads the fixed matrix or computes the (column,
       char) source for the string tail and reads the concatenated chars
       buffer.

    All heavy traffic is gathers + cumsums; the only scatters are the tiny
    segment-start markers.  The final assembly runs in fixed-size blocks
    (``lax.map``) so the [total_bytes]-sized int32 index temporaries never
    coexist — at 155-column/1M-row scale the unblocked formulation OOMs HBM.
    """
    n = valid.shape[0]
    var_idx = layout.variable_column_indices
    nvar = len(var_idx)
    fpv = layout.fixed_plus_validity
    if n == 0 or total_bytes == 0:
        return jnp.zeros((total_bytes,), dtype=jnp.uint8)
    row_offsets = row_offsets.astype(jnp.int32)             # batch ≤ 2^31-1
    row_base = row_offsets[:-1]                             # [n]

    # per-row, per-variable-column char lengths and exclusive prefix
    lens = jnp.stack(
        [str_offsets[vi][1:] - str_offsets[vi][:-1] for vi in range(nvar)],
        axis=1).astype(jnp.int32)                           # [n, nvar]
    prefix = jnp.cumsum(lens, axis=1) - lens                # exclusive, [n, nvar]
    row_lens = prefix[:, -1] + lens[:, -1]                  # chars per row [n]
    row_char_prefix = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.cumsum(row_lens, dtype=jnp.int32)])            # [n+1]

    # dense fixed-region matrix [n, fpv]
    fixed2d = jnp.zeros((n, fpv), dtype=jnp.uint8)
    vi_of_ci = {ci: vi for vi, ci in enumerate(var_idx)}
    for ci, dt in enumerate(layout.schema):
        start = layout.column_starts[ci]
        if dt.is_variable_width:
            vi = vi_of_ci[ci]
            slot_off = (fpv + prefix[:, vi]).astype(jnp.uint32)
            slot = jnp.stack([slot_off, lens[:, vi].astype(jnp.uint32)], axis=1)
            b = jax.lax.bitcast_convert_type(slot, jnp.uint8).reshape(n, 8)
        else:
            b = _byte_view_dt(datas[ci], dt)
        fixed2d = fixed2d.at[:, start:start + b.shape[1]].set(b)
    vbytes = bitmask.pack_bool_matrix(valid)
    fixed2d = fixed2d.at[:, layout.validity_offset:
                         layout.validity_offset + layout.validity_bytes].set(vbytes)

    # interleaved chars buffer, ordered (row, var-col) — one segment per
    # (row, col) pair, located with a single segment-cumsum
    total_chars = int(sum(datas[ci].shape[0] for ci in var_idx))
    if total_chars:
        chars_concat = jnp.concatenate([datas[ci] for ci in var_idx])
        col_bases = jnp.asarray(np.concatenate(
            [[0], np.cumsum([datas[ci].shape[0] for ci in var_idx])]
        ).astype(np.int32))
        seg_start = jnp.concatenate([
            (row_char_prefix[:-1, None] + prefix).reshape(-1),
            jnp.full((1,), total_chars, jnp.int32)])        # [n*nvar + 1]
        seg_of = _segment_of(seg_start, total_chars)
        offs_at = jnp.stack([str_offsets[vi][:-1].astype(jnp.int32)
                             for vi in range(nvar)], axis=1).reshape(-1)
        q = jnp.arange(total_chars, dtype=jnp.int32)
        src = (col_bases[seg_of % nvar] + offs_at[seg_of]
               + (q - seg_start[seg_of]))
        ichars = chars_concat[src]
    else:
        ichars = jnp.zeros((1,), dtype=jnp.uint8)           # safe dummy gather

    row_of_all = _segment_of(row_offsets, total_bytes)      # [total_bytes]
    fixed_flat = fixed2d.reshape(-1)

    block = 1 << 22
    nblocks = -(-total_bytes // block)
    row_of_pad = jnp.pad(row_of_all, (0, nblocks * block - total_bytes))

    def assemble(b):
        o = b * block + jnp.arange(block, dtype=jnp.int32)
        ro = jax.lax.dynamic_slice(row_of_pad, (b * block,), (block,))
        w = o - row_base[ro]                                # offset within row
        in_fixed = w < fpv
        fval = fixed_flat[ro * fpv + jnp.clip(w, 0, fpv - 1)]
        u = jnp.maximum(w - fpv, 0)                         # char idx in row
        in_chars = (~in_fixed) & (u < row_lens[ro])         # excludes padding
        cidx = jnp.clip(row_char_prefix[ro] + u, 0, max(total_chars - 1, 0))
        return jnp.where(in_fixed, fval,
                         jnp.where(in_chars, ichars[cidx], jnp.uint8(0)))

    out = jax.lax.map(assemble, jnp.arange(nblocks, dtype=jnp.int32))
    return out.reshape(-1)[:total_bytes]


@functools.partial(jax.jit, static_argnums=0)
def _gather_var_slots(layout: RowLayout, data: jnp.ndarray,
                      row_offsets: jnp.ndarray):
    """Phase 1 of from_rows with strings: pull each row's (offset,len) slots."""
    row_base = row_offsets[:-1].astype(jnp.int64)
    slots = []
    for ci in layout.variable_column_indices:
        start = layout.column_starts[ci]
        pos = row_base[:, None] + start + jnp.arange(8)[None, :]
        b = data[pos.reshape(-1)].reshape(-1, 2, 4)
        slots.append(jax.lax.bitcast_convert_type(b, jnp.uint32))  # [n, 2]
    return tuple(slots)


@functools.partial(jax.jit, static_argnums=(0, 1))
def _from_rows_var(layout: RowLayout, char_totals: tuple[int, ...],
                   data: jnp.ndarray, row_offsets: jnp.ndarray,
                   out_offsets: tuple[jnp.ndarray, ...],
                   slots: tuple[jnp.ndarray, ...]):
    """Phase 2: gather fixed slots, validity, and chars buffers.

    ``slots`` are the phase-1 (offset,len) uint32 pairs from
    ``_gather_var_slots`` — passed through rather than re-read from the row
    bytes."""
    row_base = row_offsets[:-1].astype(jnp.int64)
    n = row_base.shape[0]

    datas = []
    for ci, dt in enumerate(layout.schema):
        if dt.is_variable_width:
            datas.append(None)
            continue
        start = layout.column_starts[ci]
        sz = layout.column_sizes[ci]
        pos = row_base[:, None] + start + jnp.arange(sz)[None, :]
        b = data[pos.reshape(-1)].reshape(n, sz)
        datas.append(_from_bytes_dt(b, dt))

    pos = (row_base[:, None] + layout.validity_offset
           + jnp.arange(layout.validity_bytes)[None, :])
    vbytes = data[pos.reshape(-1)].reshape(n, layout.validity_bytes)
    valid = bitmask.unpack_bool_matrix(vbytes, layout.num_columns)

    chars_out = []
    for vi, ci in enumerate(layout.variable_column_indices):
        total = char_totals[vi]
        offs = out_offsets[vi].astype(jnp.int64)            # [n+1]
        slot = slots[vi]
        src_base = row_base + slot[:, 0].astype(jnp.int64)  # chars start per row
        if total == 0:
            chars_out.append(jnp.zeros((0,), dtype=jnp.uint8))
            continue
        # marker-cumsum segment lookup (see _segment_of) instead of a
        # per-char binary search
        row_of = _segment_of(offs.astype(jnp.int32), total)
        char_ids = jnp.arange(total, dtype=jnp.int64)
        src = src_base[row_of] + (char_ids - offs[row_of])
        chars_out.append(data[src])
    return tuple(datas), valid, tuple(chars_out)


# ---------------------------------------------------------------------------
# public API (row_conversion.hpp:27-49 surface)
# ---------------------------------------------------------------------------

def _table_valid_matrix(table: Table) -> jnp.ndarray:
    return jnp.stack([c.validity_or_true() for c in table.columns], axis=1)


def _check_row_size(layout: RowLayout, row_sizes: np.ndarray | None = None):
    worst = (layout.fixed_row_size if row_sizes is None
             else int(row_sizes.max(initial=0)))
    if worst > MAX_ROW_SIZE:
        raise ValueError(
            f"row size {worst} exceeds JCUDF limit {MAX_ROW_SIZE} "
            "(RowConversion.java:98-99)")


@traced("convert_to_rows")
@fault_site("convert_to_rows")
def convert_to_rows(table: Table,
                    max_batch_bytes: Optional[int] = None) -> list[RowBatch]:
    """Table → JCUDF row batches (``convert_to_rows``, row_conversion.cu:1902-1960).

    Returns one or more :class:`RowBatch` (LIST<INT8> analog), each ≤2GB.
    """
    max_batch_bytes = max_batch_bytes or MAX_BATCH_BYTES
    layout = compute_row_layout(table.schema)
    n = table.num_rows

    if layout.fixed_width_only:
        # Constant row stride ⇒ batch boundaries are pure arithmetic (the
        # reference reaches the same boundaries by scanning a constant-valued
        # row_sizes vector, row_conversion.cu:1460-1539) and offsets are a
        # device-side arange — no host scan, no H2D offset upload.
        _check_row_size(layout)
        stride = layout.fixed_row_size
        if stride > max_batch_bytes:
            raise ValueError("a single row exceeds the maximum batch size")
        # Reference boundary rule (build_batches, row_conversion.cu:1460-1539,
        # mirrored by layout.build_batches): split while the remainder
        # overflows the cap, rounding each split to a 32-row multiple only
        # when more than one multiple fits; the final batch is never rounded.
        boundaries = [0]
        while (n - boundaries[-1]) * stride > max_batch_bytes:
            k = max_batch_bytes // stride
            if k > BATCH_ROW_MULTIPLE:
                k = k // BATCH_ROW_MULTIPLE * BATCH_ROW_MULTIPLE
            boundaries.append(boundaries[-1] + k)
        boundaries.append(n)
        out = []
        has_valid = tuple(c.validity is not None for c in table.columns)
        for lo, hi in zip(boundaries[:-1], boundaries[1:]):
            cols = (table.columns if (lo, hi) == (0, n)
                    else [_slice_column(c, lo, hi) for c in table.columns])
            data, offsets = _to_rows_fixed_full(
                layout, has_valid, _fixed_engine("to"),
                tuple(c.data for c in cols),
                tuple(c.validity for c in cols if c.validity is not None))
            out.append(RowBatch(data, offsets))
        _record_transcode("rowconv.to_rows", n, out)
        return out

    # variable-width (strings) path: row sizes are data-dependent, so the
    # reference's scan + lower_bound batching applies as-is.  Offsets come
    # through the host-mirror cache — a cold 1M-row offsets pull costs
    # seconds through the tunnel and the arrays are host-born anyway.
    from ..utils import hostcache
    total_lens = np.zeros(n, dtype=np.int64)
    for ci in layout.variable_column_indices:
        offs = hostcache.host_i64(table[ci].offsets)
        total_lens += offs[1:] - offs[:-1]
    row_sizes = row_sizes_with_strings(layout, total_lens)
    _check_row_size(layout, row_sizes)

    batches = build_batches(row_sizes, max_batch_bytes)
    from . import ragged, xpack
    use_dma = ragged.dma_supported()
    use_xpack = knobs.get("SRJT_XPACK")
    out = []
    for bi, (lo, hi) in enumerate(zip(batches.row_boundaries[:-1],
                                      batches.row_boundaries[1:])):
        sub = Table([_slice_column(c, lo, hi) for c in table.columns])
        data = None
        if use_xpack:
            # primary engine (round 4): slab-gather + fused-roll program,
            # one jitted dispatch for the whole batch (see rowconv/xpack.py)
            col_offs = [hostcache.host_i64(sub[ci].offsets)
                        for ci in layout.variable_column_indices]
            data = xpack.to_rows_var_x(
                layout, sub, batches.row_offsets_within_batch[bi],
                col_offs)
        valid = None if data is not None else _table_valid_matrix(sub)
        if data is None and use_dma:
            data = _to_rows_var_dma(
                layout, sub, valid, batches.row_offsets_within_batch[bi])
        if data is None:
            row_offs = jnp.asarray(
                batches.row_offsets_within_batch[bi].astype(np.int64))
            data = _to_rows_var(
                layout, batches.batch_bytes[bi],
                tuple(c.data for c in sub.columns),
                # _slice_column already rebases string offsets to zero
                tuple(sub[ci].offsets
                      for ci in layout.variable_column_indices),
                valid, row_offs)
        boffs_np = batches.row_offsets_within_batch[bi]
        boffs = jnp.asarray(boffs_np)
        hostcache.seed(boffs, np.asarray(boffs_np, dtype=np.int64))
        out.append(RowBatch(data, boffs))
    _record_transcode("rowconv.to_rows", n, out)
    return out


def _record_transcode(prefix: str, rows: int, batches) -> None:
    """rows/bytes transcoded counters (shared by both directions)."""
    if metrics.recording():
        nbytes = sum(b.num_bytes for b in batches)
        metrics.count(f"{prefix}.rows", rows)
        metrics.count(f"{prefix}.bytes", nbytes)
        metrics.count(f"{prefix}.batches", len(batches))
        metrics.annotate(rows=rows, row_bytes=nbytes)
    if metrics._profile_op_hook is not None:
        metrics.profile_op(prefix, rows=rows,
                           bytes=sum(b.num_bytes for b in batches),
                           batches=len(batches))


def _slice_column(col: Column, lo: int, hi: int) -> Column:
    if lo == 0 and hi == col.num_rows:
        return col          # full range: keep identity (and host mirrors)
    v = None if col.validity is None else col.validity[lo:hi]
    if col.dtype.is_variable_width:
        from ..utils import hostcache
        host = hostcache.host_i64(col.offsets)   # one pull, reused per batch
        clo, chi = int(host[lo]), int(host[hi])
        rebased = host[lo:hi + 1] - clo
        offs = jnp.asarray(rebased.astype(np.int32))
        hostcache.seed(offs, rebased)
        return Column(col.dtype, col.data[clo:chi], offs, v)
    return Column(col.dtype, col.data[lo:hi], validity=v)


# --- fixed-width rows as a dense feature matrix (ml/ handoff) ---------------


def fixed_rows_to_matrix(batch: RowBatch, layout: RowLayout) -> jnp.ndarray:
    """JCUDF fixed-width rows of an all-FLOAT32 schema → dense f32 [n, k].

    The JCUDF fixed-width row IS a dense feature matrix (PAPER.md §L1): for
    an all-f32 schema the k data slots sit at consecutive 4-byte offsets
    0,4,…,4(k-1), so the matrix is a pure reinterpretation of the row word
    stream — reshape to [n, row_words], slice the k leading words, bitcast
    to f32.  No gather, no arithmetic, no host sync; values are bit-identical
    to the source columns by construction.
    """
    if not layout.fixed_width_only:
        raise ValueError("fixed_rows_to_matrix requires a fixed-width layout")
    if any(dt.id != T.TypeId.FLOAT32 for dt in layout.schema):
        raise ValueError("fixed_rows_to_matrix requires an all-FLOAT32 schema")
    k = layout.num_columns
    W = layout.fixed_row_size // 4
    words = (batch.data if batch.data.dtype == jnp.uint32
             else _bytes_to_words(batch.data))
    m = words.reshape(-1, W)[:, :k]
    return jax.lax.bitcast_convert_type(m, jnp.float32)


# --- dictionary-codes passthrough (dict string fast path) -------------------
#
# A DictColumn reaching convert_to_rows materializes its bytes — correct
# (JCUDF rows must carry the strings) but back on the 0.6 GB/s variable-
# width cliff.  When BOTH endpoints speak this engine (shuffle, spill,
# cache), ship the CODES through the fixed-width path instead and send the
# tiny dictionaries out of band: string columns transcode at int32 speed.

def dict_encode_for_rows(table: Table) -> tuple[Table, dict[int, Column]]:
    """Swap every dict string column for its int32 codes column.

    Returns ``(codes_table, dicts)`` where ``dicts`` maps column index →
    dictionary Column.  With every string column dict-encoded the table
    becomes fixed-width-only and ``convert_to_rows`` takes the constant-
    stride JCUDF path; :func:`restore_dict_columns` re-attaches the
    dictionaries after ``convert_from_rows`` on the far side."""
    dicts: dict[int, Column] = {}
    cols: list[Column] = []
    for i, c in enumerate(table.columns):
        d = as_dict_column(c)
        if d is not None:
            dicts[i] = d.dictionary
            cols.append(Column(T.int32, d.codes, validity=d.validity))
        else:
            cols.append(c)
    if dicts:
        metrics.count("rowconv.dict_cols", len(dicts))
    return Table(cols), dicts


def restore_dict_columns(table: Table, dicts: dict[int, Column]) -> Table:
    """Inverse of :func:`dict_encode_for_rows` after a row round trip."""
    cols = list(table.columns)
    for i, dcol in dicts.items():
        c = force_column(cols[i])
        cols[i] = DictColumn(c.data.astype(jnp.int32), dcol, c.validity)
    return Table(cols)


@traced("convert_from_rows")
@fault_site("convert_from_rows")
def convert_from_rows(batch: RowBatch, schema: Sequence[T.DType]) -> Table:
    """JCUDF rows → Table (``convert_from_rows``, row_conversion.cu:2032-2250).

    Like the reference, accepts exactly one batch (row_conversion.cu:2124-2139).
    """
    schema = list(schema)
    layout = compute_row_layout(schema)
    n = batch.num_rows
    _record_transcode("rowconv.from_rows", n, [batch])

    if layout.fixed_width_only:
        if batch.num_bytes != n * layout.fixed_row_size:
            raise ValueError(
                f"row data holds {batch.num_bytes} bytes but offsets "
                f"describe {n} rows of {layout.fixed_row_size} bytes")
        words = (batch.data if batch.data.dtype == jnp.uint32
                 else _bytes_to_words(batch.data))
        datas, valids = _from_rows_fixed_full(layout, _fixed_engine("from"),
                                              words)
        cols = [Column(dt, datas[ci], validity=valids[ci])
                for ci, dt in enumerate(schema)]
        return Table(cols)

    from . import ragged, xpack
    from ..utils import hostcache
    if knobs.get("SRJT_XPACK"):
        # primary engine (round 5): the inverse xpack — one fused program
        # for the whole batch, one memoized stacked sync for the geometry
        # (copy_strings_from_rows + chars-scan analog,
        # row_conversion.cu:1131-1174, 2201-2246)
        res = xpack.from_rows_var_x(layout, batch)
        if res is not None:
            datas, valid, chars, out_offsets = res
            return _assemble(schema, datas, valid, chars, list(out_offsets))
    bdata = batch.device_u8()   # var path is byte-granular (DMA engine)
    if (ragged.dma_supported()
            and len(layout.variable_column_indices) <= _DMA_MAX_VAR_COLS):
        # DMA path (copy_strings_from_rows analog, row_conversion.cu:
        # 1131-1174): the fixed region of every row is pulled into one
        # dense matrix (aligned-window DMA; the batch offsets' host mirror
        # is cache-seeded by convert_to_rows) and decomposed with static
        # slices.  Chars are then extracted per string column:
        #   * small n — host slot metadata is cheap: one stacked slot sync
        #     + one segmented-copy DMA kernel per column;
        #   * large n — the tunnel streams D2H at single-digit MB/s, so
        #     per-row slots stay on DEVICE: output offsets are a device
        #     cumsum, chars come from the marker-cumsum gather, and the
        #     only sync is the per-column char totals (+ a violation
        #     count), mirroring the reference's sync on the scanned totals
        #     (row_conversion.cu:2215).
        offs_np = hostcache.host_i64(batch.offsets)
        row_base_np = offs_np[:-1]
        fixed_dense = ragged.unpack(bdata, offs_np,
                                    layout.fixed_plus_validity)
        datas, valid, slots = _var_fixed_extract(layout, fixed_dense)
        row_sizes_np = offs_np[1:] - offs_np[:-1]
        nvar = len(layout.variable_column_indices)
        out_offsets = []
        chars = []
        if n <= _DMA_FROM_ROWS_MAX_N:
            # ONE host sync for all columns' slots, counted in the
            # syncs-per-query funnel (eager path, never traced)
            syncs.note_sync()
            slots_np = (np.asarray(jnp.stack(slots), dtype=np.int64)  # srjt-lint: disable=trace-host-sync
                        if slots else np.zeros((0, n, 2), np.int64))
            for vi in range(nvar):
                s = slots_np[vi]
                lens = s[:, 1]
                # rows may be shuffle-received: validate the embedded slots
                # before sizing any allocation (same hardening as the C++
                # host engine, host_table.cpp srjt_from_rows)
                if ((s[:, 0] < layout.fixed_plus_validity)
                        | (s[:, 0] + lens > row_sizes_np)).any():
                    raise ValueError(
                        "corrupt row data: string slot outside its row")
                offs = np.zeros(n + 1, dtype=np.int64)
                np.cumsum(lens, out=offs[1:])
                joffs = jnp.asarray(offs)
                hostcache.seed(joffs, offs)   # host-born: free mirror
                out_offsets.append(joffs)
                chars.append(ragged.copy_segments(
                    bdata, row_base_np + s[:, 0], offs[:-1], lens,
                    int(offs[-1])))
        else:
            from . import xpack
            row_base = batch.offsets[:-1].astype(jnp.int64)
            row_sizes = (batch.offsets[1:]
                         - batch.offsets[:-1]).astype(jnp.int64)
            out_offsets = [
                jnp.concatenate([jnp.zeros((1,), jnp.int64),
                                 jnp.cumsum(s[:, 1].astype(jnp.int64))])
                for s in slots]
            fpv = layout.fixed_plus_validity
            viol = [jnp.sum(((s[:, 0] < fpv)
                             | (s[:, 0].astype(jnp.int64)
                                + s[:, 1] > row_sizes))
                            .astype(jnp.int32)) for s in slots]
            src_starts = [row_base + s[:, 0].astype(jnp.int64)
                          for s in slots]
            # one stacked tiny sync: totals + violation counts + the
            # segmented-gather geometry stats (device-computed maxima)
            syncs.note_sync()
            meta = np.asarray(jnp.stack(  # srjt-lint: disable=trace-host-sync
                [jnp.concatenate([
                    jnp.stack([o[-1], v.astype(jnp.int64)]),
                    xpack._seg_gather_stats(st, s[:, 1], o)])
                 for o, v, st, s in zip(out_offsets, viol, src_starts,
                                        slots)]))
            if meta[:, 1].any():
                raise ValueError(
                    "corrupt row data: string slot outside its row")
            for vi in range(nvar):
                geom = xpack.plan_from_device_stats(meta[vi, 2:], n)
                if geom is not None:
                    # segmented gather: slab/roll engine, ONE program
                    chars.append(xpack.segmented_gather(
                        geom, bdata, src_starts[vi].astype(jnp.int32),
                        slots[vi][:, 1], out_offsets[vi]))
                else:
                    chars.append(_gather_chars(
                        int(meta[vi, 0]), bdata, row_base, slots[vi],
                        out_offsets[vi]))
        return _assemble(schema, datas, valid, tuple(chars),
                         [o.astype(jnp.int32) for o in out_offsets])

    row_offsets = batch.offsets.astype(jnp.int64)

    # XLA gather path (> _DMA_MAX_VAR_COLS string columns, or no DMA
    # backend): slot lengths stay on DEVICE; the only host sync is the
    # per-column char totals.
    slots = _gather_var_slots(layout, bdata, row_offsets)
    out_offsets = [
        jnp.concatenate([jnp.zeros((1,), jnp.int64),
                         jnp.cumsum(s[:, 1].astype(jnp.int64))])
        for s in slots]
    # the documented per-column char-total pull: one stacked sync, counted
    syncs.note_sync()
    totals_np = (np.asarray(jnp.stack([o[-1] for o in out_offsets]))  # srjt-lint: disable=trace-host-sync
                 if out_offsets else np.zeros((0,), np.int64))
    char_totals = [int(t) for t in totals_np]
    datas, valid, chars = _from_rows_var(
        layout, tuple(char_totals), bdata, row_offsets,
        tuple(out_offsets), slots)
    return _assemble(schema, datas, valid, chars,
                     [o.astype(jnp.int32) for o in out_offsets])


def _gather_chars(total: int, data: jnp.ndarray, row_base: jnp.ndarray,
                  slot: jnp.ndarray, out_offs: jnp.ndarray) -> jnp.ndarray:
    """One string column's chars from packed rows, fully on device: char k
    belongs to the row found by the marker-cumsum (no per-char binary
    search) and reads ``data[row_start + slot_off + (k - out_offs[row])]``.

    The jitted body is compiled for a BUCKETED total (≤ ~12.5% over) and the
    result sliced — per-batch/per-column totals otherwise each pay a fresh
    XLA compile (~1 s on the remote backend), which would dominate the very
    path this device-side gather exists to speed up.
    """
    if total == 0:
        return jnp.zeros((0,), jnp.uint8)
    from .ragged import _soft_bucket
    return _gather_chars_jit(_soft_bucket(total, 128), data, row_base,
                             slot, out_offs)[:total]


@functools.partial(jax.jit, static_argnums=0)
def _gather_chars_jit(padded: int, data: jnp.ndarray, row_base: jnp.ndarray,
                      slot: jnp.ndarray, out_offs: jnp.ndarray) -> jnp.ndarray:
    row_of = _segment_of(jnp.clip(out_offs, 0, padded).astype(jnp.int32),
                         padded)
    row_of = jnp.clip(row_of, 0, row_base.shape[0] - 1)
    k = jnp.arange(padded, dtype=jnp.int64)
    src = (row_base[row_of] + slot[row_of, 0].astype(jnp.int64)
           + (k - out_offs[row_of]))
    return data[jnp.clip(src, 0, data.shape[0] - 1)]


def _assemble(schema, datas, valid, chars, out_offsets) -> Table:
    # Validity stays on device: the reference likewise always materializes a
    # null mask on this path ("always add it in", row_conversion.cu:1299-1301);
    # deciding all-valid here would force a D2H sync per conversion.
    cols = []
    vi = 0
    for ci, dt in enumerate(schema):
        v = valid[:, ci]
        if dt.is_variable_width:
            cols.append(Column(dt, chars[vi], out_offsets[vi], v))
            vi += 1
        else:
            cols.append(Column(dt, datas[ci], validity=v))
    return Table(cols)


# Legacy-path parity aliases.  The reference keeps a second, simpler CUDA
# implementation for narrow fixed-width tables (row_conversion.cu:425-551,
# 1962-2030) and uses it as a differential oracle; on TPU there is one XLA
# path (the tiling split is a CUDA shared-memory artifact) and the NumPy
# oracle (reference.py) plays the differential role.

def convert_to_rows_fixed_width_optimized(table: Table) -> list[RowBatch]:
    if not all(c.dtype.is_fixed_width for c in table.columns):
        raise ValueError("fixed-width-optimized path requires fixed-width schema")
    return convert_to_rows(table)


def convert_from_rows_fixed_width_optimized(batch: RowBatch,
                                            schema: Sequence[T.DType]) -> Table:
    if not all(dt.is_fixed_width for dt in schema):
        raise ValueError("fixed-width-optimized path requires fixed-width schema")
    return convert_from_rows(batch, schema)
