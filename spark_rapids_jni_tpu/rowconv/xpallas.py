"""Knob-gated Pallas TPU kernels for the byte-path hot loops.

The round-4 string engine (``xpack``) is pure XLA: the placement rolls are
select trees the compiler fuses well, but every window still round-trips
through HBM between the gather and the roll, and the per-window slab gather
re-reads up to ``P×`` the payload.  These kernels are the Mosaic versions
of the same inner loops, built on the DMA/roll idioms validated on chip by
``rowconv.ragged`` (PALLAS_TPU_CHECK.json): aligned window DMAs into VMEM,
``_byte_roll`` + ``_byte_keep_mask`` placement, scalar-prefetch block
metadata, lru-cached ``pallas_call`` builders (a fresh closure per call
would Mosaic-recompile every call).

Dispatch discipline — each kernel sits behind its own knob and NEVER
becomes the only path:

  SRJT_PALLAS_PACKWIN      pack_windows   (JCUDF var-width row packing)
  SRJT_PALLAS_EXTRACT      extract_rows   (flat bytes → padded row matrix)
  SRJT_PALLAS_DICT_GATHER  gather_rows    (dictionary row gather by code)
  SRJT_PALLAS_TRANSPOSE    u8_to_u32      (byte → word transcode)

Knob values: ``0`` (default) = off, ``1``/``on`` = kernel on real TPU
backends only, ``interpret`` = Pallas interpreter mode on any backend —
the CI parity mode (CPU runs the same kernel logic; no speed claim).
Every ``try_*`` entry point returns ``None`` when the kernel is off or
the geometry falls outside its envelope, and the caller keeps its lax/XLA
formulation as the fallback — counted in ``rowconv.pallas.fallbacks``
against ``rowconv.pallas.hits`` so a run can say which path it measured.

Caveat (same as ragged.py): Mosaic compile errors from an UNVALIDATED
geometry on chip surface inside the outer jit and are not catchable here;
that is why every knob defaults off and the envelope checks reject early
(ValueError → fallback) for everything the plan can see.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import flight, knobs, metrics
from .ragged import (LANE, _byte_keep_mask, _byte_roll, _pow2_bucket,
                     _round_up, _soft_bucket, u8_to_u32, u32_to_u8)

# NOTE on x64: unlike ragged's eager entry points (which flip
# ``enable_x64`` off around their pallas_call), these dispatchers run
# INSIDE outer jit traces (the fused file decode) where toggling the x64
# context mid-trace breaks lowering.  Every array and kernel constant
# here is dtype-explicit instead — nothing weak-typed reaches Mosaic.

_VMEM_CAP = 1 << 21           # per-buffer VMEM budget (same as ragged)

# hit/fallback tallies survive metrics being off: the flight recorder
# samples them into incident snapshots (and ops_report reads the probe)
_counts = {"hits": 0, "fallbacks": 0}
flight.register_probe("rowconv.pallas", lambda: dict(_counts))


def mode(knob: str) -> str:
    """Resolve a Pallas knob: ``off`` | ``on`` | ``interpret``.

    ``1``/``on`` asks for the real kernel and resolves to ``off`` (with a
    fallback tally) on non-TPU backends — requesting Mosaic on CPU is a
    misconfiguration, not a crash."""
    raw = str(knobs.get(knob) or "0").strip().lower()
    if raw in ("interpret", "interp"):
        return "interpret"
    if raw in ("1", "on", "true", "force"):
        # knob resolution is host-side planning, never inside a trace
        if jax.default_backend() == "tpu":  # srjt-lint: disable=trace-branch
            return "on"
        _tally(False)
        return "off"
    return "off"


def _tally(hit: bool) -> None:
    key = "hits" if hit else "fallbacks"
    _counts[key] += 1
    if metrics.recording():
        metrics.count(f"rowconv.pallas.{key}")


def _side_effect_params(pltpu):
    """``has_side_effects`` compiler params across jax versions (0.4.x
    names the class ``TPUCompilerParams`` and has no side-effect field —
    there the default params suffice: every kernel output here is consumed,
    so the DMAs are not dead code)."""
    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    try:
        return cls(has_side_effects=True)
    except TypeError:
        return cls()


# ---------------------------------------------------------------------------
# pack_windows: padded rows [n, Mw] u32 + device dst offsets → flat words
#
# Same job as xpack.pack_windows (output-window-centric OR-accumulate), but
# the P-row shifted-view slab — which re-reads the dense matrix P times
# through HBM — becomes ONE VMEM row-window DMA per 4 KiB output block, and
# the place/mask select trees become in-register byte rolls.  Unlike
# ragged.pack_rows the row offsets are DEVICE values (they come out of the
# fused to_rows cumsum), so the per-block row ranges are computed on device
# and ride in as scalar-prefetch operands.
# ---------------------------------------------------------------------------

_B_PACK = 4096                # output block: 8 × 512 B windows
_SB_PACK = _B_PACK // 4 // LANE


def _first_row_per_boundary(dst_b: jnp.ndarray, n: int, nb: int,
                            win: int) -> jnp.ndarray:
    """fr[k] = last row r with dst_b[r] ≤ k·win, k ∈ [0, nb) — the device
    twin of xpack._first_row_per_window (segment-sum, no searchsorted)."""
    win_of = (dst_b[:n] // jnp.int32(win)).astype(jnp.int32)
    h = jax.ops.segment_sum(jnp.ones(n, jnp.int32), win_of, nb)
    lt = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(h)[:-1]])
    eq = jax.ops.segment_sum(
        ((dst_b[:n] % jnp.int32(win)) == 0).astype(jnp.int32), win_of, nb)
    return lt + eq - 1


def try_pack_windows(dense: jnp.ndarray, dst_w: jnp.ndarray, total_w: int,
                     P: int, nwin: int):
    """Pallas pack_windows, or None (knob off / geometry outside the
    envelope).  ``dense`` u32 [n, Mw] zero-padded rows, ``dst_w`` i32
    [n+1] device word offsets; returns u32 [total_w]."""
    m = mode("SRJT_PALLAS_PACKWIN")
    if m == "off":
        return None
    n, Mw = dense.shape
    if n == 0 or total_w == 0:
        return None
    # rows overlapping one 4 KiB block: ≤ P per 512 B window (the plan's
    # exact bound) × 8 windows, +8 for the sublane-aligned window start
    NR = _pow2_bucket(8 * P + 8, 8)
    MwS = -(-Mw // LANE)
    if MwS > _SB_PACK or NR * MwS * LANE * 4 > _VMEM_CAP:
        _tally(False)
        return None
    try:
        out = _pack_windows_pallas(dense, dst_w, total_w, NR, m == "interpret")
    except Exception:
        if m != "interpret":
            raise
        _tally(False)               # interpreter gap — degrade, count it
        return None
    _tally(True)
    return out


def _pack_windows_pallas(dense, dst_w, total_w, NR, interpret):
    n, Mw = dense.shape
    MwS = -(-Mw // LANE)
    nb = -(-total_w * 4 // _B_PACK)
    dst_b = (dst_w.astype(jnp.int32) * jnp.int32(4))

    frs = _first_row_per_boundary(dst_b, n, nb + 1, _B_PACK)
    rb = jnp.clip(frs[:nb], 0, n - 1)
    nr = jnp.clip(frs[1:] - rb + 1, 0, NR - 8)
    row0 = (rb // 8) * 8

    nblocks = _soft_bucket(nb, 1)
    rb = jnp.pad(rb, (0, nblocks - nb))
    nr = jnp.pad(nr, (0, nblocks - nb))
    row0 = jnp.pad(row0, (0, nblocks - nb))

    KOFF = _pow2_bucket(NR // LANE + 2, 2)
    n_pad = _soft_bucket(_round_up(n, 8) + NR)
    dense32 = jnp.pad(dense, ((0, n_pad - n), (0, MwS * LANE - Mw))
                      ).reshape(n_pad, MwS, LANE)
    offs_rows = _soft_bucket(-(-(n_pad + 1) // LANE) + KOFF + 1)
    offs2d = jnp.pad(dst_b, (0, offs_rows * LANE - (n + 1)),
                     mode="edge").reshape(offs_rows, LANE)

    out = _packwin_call(nblocks, MwS, NR, KOFF, interpret)(
        row0, rb, nr, offs2d, dense32)
    return out.reshape(-1)[:total_w]


@functools.lru_cache(maxsize=256)
def _packwin_call(nblocks, MwS, NR, KOFF, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    SB = _SB_PACK

    def kernel(r0_ref, rb_ref, nr_ref, offs_hbm, dense_hbm, out_ref,
               scratch, soffs, sems):
        b = pl.program_id(0)
        row0 = r0_ref[b]
        dma = pltpu.make_async_copy(dense_hbm.at[pl.ds(row0, NR)], scratch,
                                    sems.at[0])
        dma.start()
        orow0 = row0 // LANE
        for k in range(KOFF):
            pltpu.make_async_copy(offs_hbm.at[orow0 + k], soffs.at[k],
                                  sems.at[1 + k]).start()
        dma.wait()
        for k in range(KOFF):
            pltpu.make_async_copy(offs_hbm.at[orow0 + k], soffs.at[k],
                                  sems.at[1 + k]).wait()

        blk_start = b * _B_PACK
        pos4 = ((jax.lax.broadcasted_iota(jnp.int32, (SB, LANE), 0) * LANE
                 + jax.lax.broadcasted_iota(jnp.int32, (SB, LANE), 1)) * 4)

        def body(i, acc):
            r = rb_ref[b] + i
            lr = r - row0
            o_lo = soffs[(r // LANE) - orow0, r % LANE]
            o_hi = soffs[((r + 1) // LANE) - orow0, (r + 1) % LANE]
            rowvec = scratch[lr]                  # [MwS, LANE] u32
            ext = jnp.concatenate(
                [rowvec, jnp.zeros((SB - MwS, LANE), jnp.uint32)], axis=0) \
                if SB > MwS else rowvec[:SB]
            p = o_lo - blk_start                  # byte position, may be < 0
            rolled = _byte_roll(ext, p)
            keep = _byte_keep_mask(pos4, p, p + (o_hi - o_lo))
            return acc | (rolled & keep)

        acc = jax.lax.fori_loop(0, nr_ref[b], body,
                                jnp.zeros((SB, LANE), jnp.uint32))
        out_ref[...] = acc[None]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(nblocks,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((1, SB, LANE), lambda b, *_: (b, 0, 0)),
        scratch_shapes=[pltpu.VMEM((NR, MwS, LANE), jnp.uint32),
                        pltpu.SMEM((KOFF, LANE), jnp.int32),
                        pltpu.SemaphoreType.DMA((1 + KOFF,))])
    return pl.pallas_call(
        kernel, grid_spec=grid_spec, interpret=interpret,
        out_shape=jax.ShapeDtypeStruct((nblocks, SB, LANE), jnp.uint32),
        compiler_params=_side_effect_params(pltpu))


# ---------------------------------------------------------------------------
# extract_rows: flat bytes + HOST offsets → zero-padded row matrix [n, M]
#
# ragged.unpack_rows with an interpreter switch — used where the row
# geometry is host-resident (dictionary pages, row-group string payloads)
# to build the padded matrices the gather paths index into.
# ---------------------------------------------------------------------------

def try_extract_rows(flat: jnp.ndarray, row_offsets: np.ndarray, M: int):
    """Pallas row extraction, or None.  ``flat`` u8 device, ``row_offsets``
    HOST [n+1]; returns u8 [n, M], row r zero-padded past its length."""
    m = mode("SRJT_PALLAS_EXTRACT")
    if m == "off":
        return None
    offs = np.asarray(row_offsets, dtype=np.int64)
    n = offs.shape[0] - 1
    if n == 0 or int(offs[-1]) == 0:
        return None
    try:
        out = _extract_rows_impl(flat, offs, M, m == "interpret")
    except ValueError:              # span outside the VMEM envelope
        _tally(False)
        return None
    except Exception:
        if m != "interpret":
            raise
        _tally(False)
        return None
    _tally(True)
    return out


def _extract_rows_impl(flat, offs, M, interpret):
    RB = 8
    n = offs.shape[0] - 1
    total = int(offs[-1])
    Mp = max(512, _round_up(M, 512))
    MwS = Mp // 4 // LANE
    nblocks = _soft_bucket(-(-n // RB), 1)
    n_pad = nblocks * RB
    KOFF = _pow2_bucket(RB // LANE + 2, 2)

    offs_pad = np.pad(offs, (0, n_pad + 1 - offs.shape[0]), mode="edge")
    start_word_row = ((offs_pad[np.arange(nblocks) * RB] // 4) // LANE
                      ).astype(np.int32)
    spans = (offs_pad[np.minimum(np.arange(1, nblocks + 1) * RB, n_pad)]
             - start_word_row.astype(np.int64) * (LANE * 4))
    KS = _pow2_bucket(int(spans.max(initial=1)) // (LANE * 4) + 2, 8)
    KS = max(KS, _round_up(MwS, 8))
    if KS * LANE * 4 > _VMEM_CAP:
        raise ValueError("extract_rows: row span exceeds VMEM budget")
    flat_rows = _soft_bucket(-(-total // (LANE * 4)) + KS)
    flat_pad = jnp.pad(flat, (0, flat_rows * LANE * 4 - total))
    flat32 = u8_to_u32(flat_pad).reshape(flat_rows, LANE)

    offs32 = offs_pad.astype(np.int32)
    offs_rows = _soft_bucket(-(-(n_pad + 1) // LANE) + KOFF + 1)
    offs2d = jnp.asarray(
        np.pad(offs32, (0, offs_rows * LANE - offs32.shape[0]))
        .reshape(offs_rows, LANE))

    out = _extract_call(nblocks, RB, MwS, KS, KOFF, interpret)(
        jnp.asarray(start_word_row), offs2d, flat32)
    dense = u32_to_u8(out.reshape(-1)).reshape(n_pad, Mp)
    return dense[:n, :M]


@functools.lru_cache(maxsize=256)
def _extract_call(nblocks, RB, MwS, KS, KOFF, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(sw_ref, offs_hbm, flat_hbm, out_ref, win, soffs, sems):
        b = pl.program_id(0)
        dma = pltpu.make_async_copy(flat_hbm.at[pl.ds(sw_ref[b], KS)], win,
                                    sems.at[0])
        dma.start()
        orow0 = (b * RB) // LANE
        for k in range(KOFF):
            pltpu.make_async_copy(offs_hbm.at[orow0 + k], soffs.at[k],
                                  sems.at[1 + k]).start()
        dma.wait()
        for k in range(KOFF):
            pltpu.make_async_copy(offs_hbm.at[orow0 + k], soffs.at[k],
                                  sems.at[1 + k]).wait()
        w = win[...]
        pos4 = ((jax.lax.broadcasted_iota(jnp.int32, (MwS, LANE), 0) * LANE
                 + jax.lax.broadcasted_iota(jnp.int32, (MwS, LANE), 1)) * 4)
        base_b = sw_ref[b] * LANE * 4
        for lr in range(RB):
            r = b * RB + lr
            o_lo = soffs[(r // LANE) - orow0, r % LANE]
            o_hi = soffs[((r + 1) // LANE) - orow0, (r + 1) % LANE]
            q = o_lo - base_b
            rolled = _byte_roll(w, -q)[:MwS]
            keep = _byte_keep_mask(pos4, 0, o_hi - o_lo)
            out_ref[0, lr] = rolled & keep

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nblocks,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((1, RB, MwS, LANE), lambda b, *_: (b, 0, 0, 0)),
        scratch_shapes=[pltpu.VMEM((KS, LANE), jnp.uint32),
                        pltpu.SMEM((KOFF, LANE), jnp.int32),
                        pltpu.SemaphoreType.DMA((1 + KOFF,))])
    return pl.pallas_call(
        kernel, grid_spec=grid_spec, interpret=interpret,
        out_shape=jax.ShapeDtypeStruct((nblocks, RB, MwS, LANE), jnp.uint32),
        compiler_params=_side_effect_params(pltpu))


# ---------------------------------------------------------------------------
# gather_rows: padded row matrix [D, W] u32 + codes [n] → [n, W]
#
# XLA lowers `mat[idx]` to a row gather (~24 ns/row); on wide dictionaries
# the DMA engine can instead stream each selected row HBM→VMEM directly.
# One block gathers 8 rows with 8 in-flight row DMAs (the per-DMA issue
# rate bounds this at ~1.4 M rows/s — wins when rows are ≥ ~512 B).
# ---------------------------------------------------------------------------

def try_gather_rows(mat: jnp.ndarray, idx: jnp.ndarray):
    """Pallas dictionary row gather, or None.  ``mat`` u32 [D, W] (device),
    ``idx`` i32 [n] with values in [0, D); returns u32 [n, W]."""
    m = mode("SRJT_PALLAS_DICT_GATHER")
    if m == "off":
        return None
    D, W = mat.shape
    n = int(idx.shape[0])
    if D == 0 or n == 0:
        return None
    RB = 8
    MwS = -(-W // LANE)
    if RB * MwS * LANE * 4 > _VMEM_CAP:
        _tally(False)
        return None
    try:
        out = _gather_rows_impl(mat, idx, RB, MwS, m == "interpret")
    except Exception:
        if m != "interpret":
            raise
        _tally(False)
        return None
    _tally(True)
    return out[:n, :W]


def _gather_rows_impl(mat, idx, RB, MwS, interpret):
    D, W = mat.shape
    n = int(idx.shape[0])
    n_pad = _round_up(_soft_bucket(max(n, 1), LANE), LANE)
    nblocks = n_pad // RB
    mat3 = jnp.pad(mat, ((0, 0), (0, MwS * LANE - W))).reshape(D, MwS, LANE)
    idx2d = jnp.pad(idx.astype(jnp.int32), (0, n_pad - n)
                    ).reshape(n_pad // LANE, LANE)
    out = _gather_call(nblocks, RB, MwS, interpret)(idx2d, mat3)
    return out.reshape(n_pad, MwS * LANE)


@functools.lru_cache(maxsize=256)
def _gather_call(nblocks, RB, MwS, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(idx_hbm, mat_hbm, out_ref, scratch, sidx, sems):
        b = pl.program_id(0)
        irow = (b * RB) // LANE          # RB | LANE: one idx row per block
        pltpu.make_async_copy(idx_hbm.at[irow], sidx.at[0],
                              sems.at[RB]).start()
        pltpu.make_async_copy(idx_hbm.at[irow], sidx.at[0],
                              sems.at[RB]).wait()
        for j in range(RB):
            r = b * RB + j
            src = sidx[0, r % LANE]
            pltpu.make_async_copy(mat_hbm.at[src], scratch.at[j],
                                  sems.at[j]).start()
        for j in range(RB):
            r = b * RB + j
            src = sidx[0, r % LANE]
            pltpu.make_async_copy(mat_hbm.at[src], scratch.at[j],
                                  sems.at[j]).wait()
        out_ref[...] = scratch[...][None]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(nblocks,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((1, RB, MwS, LANE), lambda b: (b, 0, 0, 0)),
        scratch_shapes=[pltpu.VMEM((RB, MwS, LANE), jnp.uint32),
                        pltpu.SMEM((1, LANE), jnp.int32),
                        pltpu.SemaphoreType.DMA((RB + 1,))])
    return pl.pallas_call(
        kernel, grid_spec=grid_spec, interpret=interpret,
        out_shape=jax.ShapeDtypeStruct((nblocks, RB, MwS, LANE), jnp.uint32),
        compiler_params=_side_effect_params(pltpu))


# ---------------------------------------------------------------------------
# u8 → u32 transcode: the scan's byte→word transpose, blocked through VMEM
#
# Semantically identical to ragged.u8_to_u32 (strided little-endian
# combine); the Pallas version pins the working set to one VMEM block so
# the transcode streams instead of materializing the four strided
# intermediates in HBM.
# ---------------------------------------------------------------------------

_TR_ROWS = 32                 # u8 block: 32 sublanes × 512 lanes = 16 KiB


def try_u8_to_u32(flat: jnp.ndarray):
    """Pallas byte→word transcode, or None.  ``flat`` u8 [4N] with
    4N % 512 == 0; returns u32 [N] little-endian."""
    m = mode("SRJT_PALLAS_TRANSPOSE")
    if m == "off":
        return None
    n4 = int(flat.shape[0])
    if n4 == 0 or n4 % (4 * LANE) != 0:
        return None
    try:
        out = _u8_to_u32_impl(flat, m == "interpret")
    except Exception:
        if m != "interpret":
            raise
        _tally(False)
        return None
    _tally(True)
    return out


def _u8_to_u32_impl(flat, interpret):
    n4 = int(flat.shape[0])
    R = n4 // (4 * LANE)
    R_pad = _soft_bucket(_round_up(R, _TR_ROWS), _TR_ROWS)
    R_pad = _round_up(R_pad, _TR_ROWS)
    x2 = jnp.pad(flat, (0, R_pad * 4 * LANE - n4)).reshape(R_pad, 4 * LANE)
    out = _transpose_call(R_pad // _TR_ROWS, interpret)(x2)
    return out.reshape(-1)[:n4 // 4]


@functools.lru_cache(maxsize=64)
def _transpose_call(nblocks, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(x_ref, o_ref):
        x = x_ref[...].astype(jnp.uint32)        # [32, 512]
        o_ref[...] = (x[:, 0::4] | (x[:, 1::4] << jnp.uint32(8))
                      | (x[:, 2::4] << jnp.uint32(16))
                      | (x[:, 3::4] << jnp.uint32(24)))

    return pl.pallas_call(
        kernel,
        grid=(nblocks,),
        in_specs=[pl.BlockSpec((_TR_ROWS, 4 * LANE), lambda b: (b, 0))],
        out_specs=pl.BlockSpec((_TR_ROWS, LANE), lambda b: (b, 0)),
        out_shape=jax.ShapeDtypeStruct((nblocks * _TR_ROWS, LANE),
                                       jnp.uint32),
        interpret=interpret)
