"""JCUDF row-layout calculator (pure host).

Re-derives the reference's row layout contract so the produced bytes are
bit-identical to spark-rapids-jni's JCUDF format:

* C-struct-like rows, each fixed-width column aligned to its own size, each
  variable-width (string) column occupying an 8-byte (offset:u32, len:u32)
  slot aligned to 4 — ``row_conversion.cu:1331-1370``
  (``compute_column_information``).
* Validity bytes (1 bit/column, little-endian within the byte) appended
  byte-aligned after the data — ``RowConversion.java:56-58``,
  ``row_conversion.cu:1303-1305``.
* Row padded to 8 bytes (``JCUDF_ROW_ALIGNMENT``, ``row_conversion.cu:62``).
  For string rows, the chars of all variable columns are appended in column
  order starting at the *unaligned* fixed+validity size, and the row is then
  padded to 8 — ``row_conversion.cu:216-261`` (``build_string_row_offsets``),
  ``:852-874`` (``copy_strings_to_rows``).
* Output is split into ≤2GB batches (int32 offset limit) —
  ``row_conversion.cu:64,97-103,1460-1539`` (``build_batches``); batch
  boundaries rounded to 32-row multiples (``:1504-1506``).
* Rows larger than 1KB are rejected (API contract,
  ``RowConversion.java:98-99``).

All of this is static host metadata — on TPU it feeds static shapes /
scalar-prefetch grids instead of runtime kernel args.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

from .. import types as T

JCUDF_ROW_ALIGNMENT = 8
MAX_ROW_SIZE = 1024            # RowConversion.java:98-99
MAX_BATCH_BYTES = 2**31 - 1    # size_type max, row_conversion.cu:64
BATCH_ROW_MULTIPLE = 32        # row_conversion.cu:1504-1506


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@dataclasses.dataclass(frozen=True)
class RowLayout:
    """Static row-layout metadata for one schema."""

    schema: tuple[T.DType, ...]
    column_starts: tuple[int, ...]      # byte offset of each column's slot
    column_sizes: tuple[int, ...]       # slot size in bytes
    validity_offset: int                # == end of last data slot
    validity_bytes: int                 # ceil(ncols / 8)
    fixed_plus_validity: int            # chars region starts here (strings)
    fixed_row_size: int                 # aligned row stride when fixed-only
    variable_column_indices: tuple[int, ...]

    @property
    def num_columns(self) -> int:
        return len(self.schema)

    @property
    def fixed_width_only(self) -> bool:
        return not self.variable_column_indices


def compute_row_layout(schema: Sequence[T.DType]) -> RowLayout:
    """Equivalent of ``compute_column_information`` (row_conversion.cu:1331-1370)."""
    starts: list[int] = []
    sizes: list[int] = []
    variable: list[int] = []
    offset = 0
    for i, dt in enumerate(schema):
        if dt.is_nested:
            # Same contract as the reference: JCUDF rows carry fixed-width
            # (incl. decimal128, fixed-width in libcudf) + string columns;
            # nested types are rejected at entry (row_conversion.cu:1268-1271
            # is_fixed_width || is_compound).
            raise TypeError(
                f"column {i}: {dt.id.name} is not supported in JCUDF rows")
        size = dt.itemsize
        offset = _round_up(offset, dt.row_alignment)
        if dt.is_variable_width:
            variable.append(i)
        starts.append(offset)
        sizes.append(size)
        offset += size

    validity_offset = offset
    validity_bytes = -(-len(schema) // 8)
    fixed_plus_validity = validity_offset + validity_bytes
    fixed_row_size = _round_up(fixed_plus_validity, JCUDF_ROW_ALIGNMENT)

    if fixed_row_size > MAX_ROW_SIZE and not variable:
        raise ValueError(
            f"row size {fixed_row_size} exceeds JCUDF limit of {MAX_ROW_SIZE} "
            "bytes (RowConversion.java:98-99)")

    return RowLayout(
        schema=tuple(schema),
        column_starts=tuple(starts),
        column_sizes=tuple(sizes),
        validity_offset=validity_offset,
        validity_bytes=validity_bytes,
        fixed_plus_validity=fixed_plus_validity,
        fixed_row_size=fixed_row_size,
        variable_column_indices=tuple(variable),
    )


def row_sizes_with_strings(layout: RowLayout,
                           string_lengths: np.ndarray) -> np.ndarray:
    """Per-row total byte size for a table with string columns.

    ``string_lengths``: int array [num_rows] — summed UTF-8 byte lengths of all
    variable-width columns per row.  Equivalent of ``build_string_row_offsets``
    (row_conversion.cu:216-261): fixed+validity + chars, rounded up to 8.
    """
    sizes = layout.fixed_plus_validity + np.asarray(string_lengths, dtype=np.int64)
    return (sizes + JCUDF_ROW_ALIGNMENT - 1) // JCUDF_ROW_ALIGNMENT * JCUDF_ROW_ALIGNMENT


@dataclasses.dataclass(frozen=True)
class BatchInfo:
    """Output batching decision (``build_batches``, row_conversion.cu:1460-1539)."""

    row_boundaries: tuple[int, ...]     # len nbatches+1, in rows
    batch_bytes: tuple[int, ...]        # total bytes per batch
    row_offsets_within_batch: list[np.ndarray]  # int32 [rows_in_batch + 1]

    @property
    def num_batches(self) -> int:
        return len(self.batch_bytes)


def build_batches(row_sizes: np.ndarray,
                  max_batch_bytes: int = MAX_BATCH_BYTES) -> BatchInfo:
    """Split rows into batches whose byte totals fit an int32 offset column.

    Mirrors the reference algorithm (row_conversion.cu:1460-1539): inclusive
    scan of row sizes, binary-search each ≤2GB boundary, round boundaries down
    to a 32-row multiple, then per-batch exclusive-scan offset columns.
    """
    row_sizes = np.asarray(row_sizes, dtype=np.int64)
    num_rows = row_sizes.shape[0]
    cum = np.concatenate([[0], np.cumsum(row_sizes)])
    total = int(cum[-1])

    boundaries = [0]
    while cum[boundaries[-1]] + max_batch_bytes < total:
        target = cum[boundaries[-1]] + max_batch_bytes
        # last row index whose cumulative end fits within the target
        nxt = int(np.searchsorted(cum, target, side="right")) - 1
        if nxt > boundaries[-1] + BATCH_ROW_MULTIPLE:
            nxt = boundaries[-1] + (nxt - boundaries[-1]) // BATCH_ROW_MULTIPLE * BATCH_ROW_MULTIPLE
        if nxt <= boundaries[-1]:
            raise ValueError("a single row exceeds the maximum batch size")
        boundaries.append(nxt)
    boundaries.append(num_rows)

    batch_bytes = []
    offsets = []
    for lo, hi in zip(boundaries[:-1], boundaries[1:]):
        batch_bytes.append(int(cum[hi] - cum[lo]))
        offsets.append((cum[lo:hi + 1] - cum[lo]).astype(np.int32))
    return BatchInfo(tuple(boundaries), tuple(batch_bytes), offsets)
