"""Vectorized host (NumPy) JCUDF engine.

Two roles:
* the **CPU baseline** for the headline benchmark (BASELINE.md config #1
  measures the device path against a host reference), and
* a production host fallback for row conversion when no accelerator is
  attached (the reference has no such fallback — its only engine is CUDA —
  so this is strictly additive capability).

Unlike ``reference.py`` (the deliberately scalar oracle), this module is the
fastest reasonable pure-NumPy implementation: strided views + packbits, no
Python per-row loops on the fixed-width path.
"""

from __future__ import annotations

import numpy as np

from .. import types as T
from ..column import Table
from .layout import compute_row_layout
from .reference import _col_valid


def _valid_matrix(table: Table) -> np.ndarray:
    return np.stack([_col_valid(c) for c in table.columns], axis=1)


def to_rows_fixed_np(table: Table) -> np.ndarray:
    """Fixed-width table → uint8 [n, fixed_row_size] (vectorized)."""
    layout = compute_row_layout(table.schema)
    assert layout.fixed_width_only
    n = table.num_rows
    out = np.zeros((n, layout.fixed_row_size), dtype=np.uint8)
    for ci, col in enumerate(table.columns):
        start = layout.column_starts[ci]
        sz = layout.column_sizes[ci]
        # Column payloads are already in storage form (f64 = u32 bit pairs,
        # decimal128 = int64 lane pairs), so a raw byte view is exact.
        data = np.ascontiguousarray(np.asarray(col.data))
        out[:, start:start + sz] = data.view(np.uint8).reshape(n, sz)
    valid = _valid_matrix(table)
    vbytes = np.packbits(valid, axis=1, bitorder="little")
    out[:, layout.validity_offset:
        layout.validity_offset + layout.validity_bytes] = vbytes
    return out


def from_rows_fixed_np(rows: np.ndarray, schema) -> tuple[list, np.ndarray]:
    """uint8 [n, row_size] → (list of value arrays, valid bool [n, ncols])."""
    layout = compute_row_layout(list(schema))
    assert layout.fixed_width_only
    n = rows.shape[0]
    datas = []
    for ci, dt in enumerate(layout.schema):
        start = layout.column_starts[ci]
        sz = layout.column_sizes[ci]
        b = np.ascontiguousarray(rows[:, start:start + sz])
        if dt.id == T.TypeId.FLOAT64:    # storage form: u32 [n, 2] bit pairs
            datas.append(b.view(np.uint32).reshape(n, 2))
        else:
            datas.append(b.view(dt.storage).reshape(n))
    vb = rows[:, layout.validity_offset:
              layout.validity_offset + layout.validity_bytes]
    valid = np.unpackbits(np.ascontiguousarray(vb), axis=1,
                          bitorder="little")[:, :layout.num_columns].astype(bool)
    return datas, valid
