"""Streaming ragged-composition kernel — variable-width JCUDF rows in ONE pass.

TPU-native replacement for the round-3 string transcode chain (per-column
``segmented_copy`` passes + a final ``pack``), whose cost grew as
``segments-per-block × block-size``: every segment paid a full-output-block
byte-roll, so 12-byte strings amplified VPU traffic ~300×.  This kernel is
the analog of the reference's fused string path — one launch writes fixed
slots, validity, and chars for a whole batch (``copy_strings_to_rows``,
``row_conversion.cu:827-875,1861``) — restructured for the TPU memory system:

* The grid walks **row blocks** (``RB`` rows each), not output blocks.  TPU
  grids execute sequentially, which the kernel exploits for a *streaming*
  output: each block appends its rows' bytes to a VMEM window stash and
  flushes full 512-byte windows to HBM with one dynamic-offset DMA; the
  partial tail window is carried to the next block in a scratch register.
* Per row, each of the K source pieces (the packed fixed+validity region,
  then each string column's chars) is placed with ONE small ``[RSB, 128]``
  byte-roll + mask into a register row buffer, and the finished row is
  OR-ed into the stash at its dynamic 512-aligned position.  Work per row
  is O(K · RSB·512B) — independent of block size, the round-3 amplifier.
* Sources are staged per block with one aligned bulk DMA per stream
  (consecutive rows' pieces are contiguous in every stream), and per-row
  metadata (src offset / length per stream + output offset) is staged into
  SMEM from one interleaved ``[n+1, S]`` i32 array the caller builds on
  device — so the whole conversion, metadata included, runs as one jitted
  program with a single dispatch.

Geometry (window starts per block, buckets) is host-planned from the host
row/char offsets the JCUDF path already owns — the same host/device split
the reference uses (batch/tile metadata on host, bytes on device).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from .ragged import (LANE, _WINDOW_ALIGN, _byte_roll, _byte_keep_mask,
                     _pow2_bucket, _soft_bucket, _round_up, u8_to_u32,
                     u32_to_u8, dma_supported)

_VMEM_BUDGET = 1 << 22          # per-stream staging window cap (4MB)


@dataclasses.dataclass(frozen=True)
class ComposePlan:
    """Static geometry for one compose call (hashable: jit/kernel cache key)."""

    K: int                      # number of source streams
    RB: int                     # rows per grid block
    nblocks: int
    S: int                      # i32 metadata words per row (2K + 1 padded)
    n_rows: int
    total_bytes: int
    win_rows: tuple[int, ...]   # staged window sublane-rows per stream
    meta_rows: int              # staged metadata sublane-rows
    cap_rows: int               # output stash sublane-rows (flush granularity)
    rsb: int                    # roll-buffer sublane-rows (covers max row)
    out_rows: int               # output HBM sublane-rows (incl. slack)
    src_rows: tuple[int, ...]   # padded source HBM sublane-rows per stream
    meta_hbm_rows: int


def plan_compose(src_offs: list[np.ndarray], dst_offs: np.ndarray,
                 src_sizes: list[int]) -> ComposePlan:
    """Host geometry pass.

    ``src_offs[k]``: int64 [n+1] — byte offset of row r's piece in stream k
    (monotone, piece k of row r spans ``[src_offs[k][r], src_offs[k][r+1])``
    ... except the caller may carry explicit lengths in the metadata; the
    offsets here only size the staging windows).  ``dst_offs``: int64 [n+1]
    output byte offsets (row r occupies ``[dst_offs[r], dst_offs[r+1])``).
    ``src_sizes[k]``: total byte length of stream k's device array.

    Raises ValueError when a staging window exceeds the VMEM budget (caller
    degrades to the XLA path) — same contract as the ragged engine.
    """
    n = dst_offs.shape[0] - 1
    K = len(src_offs)
    total = int(dst_offs[-1])
    max_row = int((dst_offs[1:] - dst_offs[:-1]).max(initial=8))
    rsb = _pow2_bucket(max_row // _WINDOW_ALIGN + 2, 8)

    # rows per block: bounded by the output stash budget
    RB = 256
    while RB > 64 and RB * max_row > (1 << 19):
        RB //= 2
    nblocks = max(1, -(-n // RB))

    win_rows = []
    for k in range(K):
        o = src_offs[k]
        spans = []
        for b in range(nblocks):
            lo, hi = b * RB, min((b + 1) * RB, n)
            w0 = (int(o[lo]) // _WINDOW_ALIGN) * _WINDOW_ALIGN
            spans.append(int(o[hi]) - w0)
        wr = _pow2_bucket(max(spans) // _WINDOW_ALIGN + 1 + rsb, 8)
        if wr * _WINDOW_ALIGN > _VMEM_BUDGET:
            raise ValueError("compose: staging window exceeds VMEM budget")
        win_rows.append(wr)
    if sum(win_rows) * _WINDOW_ALIGN > 2 * _VMEM_BUDGET:
        raise ValueError("compose: total staging exceeds VMEM budget")

    S = 2 * K + 1
    meta_rows = _pow2_bucket(((RB + 1) * S) // LANE + 2, 2)
    cap_rows = _pow2_bucket(RB * max_row // _WINDOW_ALIGN + 2, 8)
    if cap_rows * _WINDOW_ALIGN > (1 << 21):
        raise ValueError("compose: output stash exceeds VMEM budget")
    out_rows = _soft_bucket(-(-total // _WINDOW_ALIGN) + cap_rows + 8)
    src_rows = tuple(
        _soft_bucket(-(-max(sz, 1) // _WINDOW_ALIGN) + win_rows[k])
        for k, sz in enumerate(src_sizes))
    meta_hbm_rows = _soft_bucket(
        ((nblocks * RB + 1) * S) // LANE + meta_rows + 1)
    return ComposePlan(K=K, RB=RB, nblocks=nblocks, S=S, n_rows=n,
                       total_bytes=total, win_rows=tuple(win_rows),
                       meta_rows=meta_rows, cap_rows=cap_rows, rsb=rsb,
                       out_rows=out_rows, src_rows=src_rows,
                       meta_hbm_rows=meta_hbm_rows)


def plan_prefetch(plan: ComposePlan,
                  src_offs: list[np.ndarray]) -> list[np.ndarray]:
    """Per-block window start sublane-rows, one int32 [nblocks] per stream."""
    n = plan.n_rows
    outs = []
    for k in range(plan.K):
        o = src_offs[k]
        idx = np.minimum(np.arange(plan.nblocks, dtype=np.int64) * plan.RB, n)
        outs.append((o[idx] // _WINDOW_ALIGN).astype(np.int32))
    return outs


def build_meta(plan: ComposePlan, src_offs_dev: list[jnp.ndarray],
               lens_dev: list[jnp.ndarray],
               dst_offs_dev: jnp.ndarray) -> jnp.ndarray:
    """Interleaved metadata array, built ON DEVICE (traceable, int32):
    row r holds ``[src_0[r], len_0[r], …, src_{K-1}[r], len_{K-1}[r], dst[r]]``
    at flat position ``r*S``; row ``n`` is the terminator (lens 0, dst=total);
    rows beyond are edge-padded.  Returns i32 [meta_hbm_rows, 128].
    """
    n = plan.n_rows
    cols = []
    for k in range(plan.K):
        so = src_offs_dev[k].astype(jnp.int32)
        ln = jnp.concatenate(
            [lens_dev[k].astype(jnp.int32), jnp.zeros((1,), jnp.int32)])
        cols.append(so[:n + 1])
        cols.append(ln[:n + 1])
    cols.append(dst_offs_dev.astype(jnp.int32)[:n + 1])
    m = jnp.stack(cols, axis=1)                     # [n+1, S]
    flat = m.reshape(-1)
    pad = plan.meta_hbm_rows * LANE - flat.shape[0]
    # edge-pad: repeated terminator rows keep every block's end-read valid
    reps = -(-pad // plan.S) + 1
    tail = jnp.tile(m[-1], (reps,))
    flat = jnp.concatenate([flat, tail])[:plan.meta_hbm_rows * LANE]
    return flat.reshape(plan.meta_hbm_rows, LANE)


def _pad_src_u32(plan: ComposePlan, k: int, src: jnp.ndarray) -> jnp.ndarray:
    """Stream k's u8 bytes → padded u32 [src_rows[k], 128] staging view."""
    want = plan.src_rows[k] * LANE * 4
    if src.dtype == jnp.uint32:
        flat = src.reshape(-1)
        w = jnp.pad(flat, (0, plan.src_rows[k] * LANE - flat.shape[0]))
        return w.reshape(plan.src_rows[k], LANE)
    b = jnp.pad(src.reshape(-1), (0, want - src.shape[0]))
    return u8_to_u32(b).reshape(plan.src_rows[k], LANE)


@functools.lru_cache(maxsize=256)
def _compose_call(plan: ComposePlan):
    """Cached jitted pallas_call for one compose geometry."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    K, RB, S = plan.K, plan.RB, plan.S
    RSB = plan.rsb
    CAP = plan.cap_rows
    MR = plan.meta_rows
    NP2 = CAP + RSB + 8          # stash slack for the last row's spill

    def kernel(*args):
        wb_refs = args[:K]                    # [nblocks] i32 each
        mb_ref = args[K]                      # [nblocks] i32
        meta_hbm = args[K + 1]
        src_hbms = args[K + 2:2 * K + 2]
        out_hbm = args[2 * K + 2]
        wins = args[2 * K + 3:3 * K + 3]
        mwin = args[3 * K + 3]                # SMEM [MR, 128] i32
        stash = args[3 * K + 4]               # VMEM [NP2, 128] u32
        carry = args[3 * K + 5]               # VMEM [8, 128] u32
        sems = args[3 * K + 6]

        b = pl.program_id(0)
        mb = mb_ref[b]

        @pl.when(b == 0)
        def _init():
            carry[...] = jnp.zeros((8, LANE), jnp.uint32)

        for k in range(K):
            pltpu.make_async_copy(
                src_hbms[k].at[pl.ds(wb_refs[k][b], plan.win_rows[k])],
                wins[k], sems.at[k]).start()
        pltpu.make_async_copy(meta_hbm.at[pl.ds(mb, MR)], mwin,
                              sems.at[K]).start()
        for k in range(K):
            pltpu.make_async_copy(
                src_hbms[k].at[pl.ds(wb_refs[k][b], plan.win_rows[k])],
                wins[k], sems.at[k]).wait()
        pltpu.make_async_copy(meta_hbm.at[pl.ds(mb, MR)], mwin,
                              sems.at[K]).wait()

        def meta(r, j):
            p = r * jnp.int32(S) + jnp.int32(j)
            return mwin[jax.lax.div(p, jnp.int32(LANE)) - mb,
                        jax.lax.rem(p, jnp.int32(LANE))]

        r0 = b * jnp.int32(RB)
        dst0 = meta(r0, S - 1)
        obase = jax.lax.div(dst0, jnp.int32(_WINDOW_ALIGN))   # window rows

        stash[...] = jnp.zeros((NP2, LANE), jnp.uint32)
        stash[pl.ds(0, 8)] = carry[...]

        pos4_row = ((jax.lax.broadcasted_iota(jnp.int32, (RSB, LANE), 0)
                     * jnp.int32(LANE)
                     + jax.lax.broadcasted_iota(jnp.int32, (RSB, LANE), 1))
                    * jnp.int32(4))

        def body(i, _):
            r = r0 + i
            dst = meta(r, S - 1)
            rowbuf = jnp.zeros((RSB, LANE), jnp.uint32)
            run = jnp.int32(0)
            for k in range(K):
                so = meta(r, 2 * k)
                L = meta(r, 2 * k + 1)
                srel = so - wb_refs[k][b] * jnp.int32(_WINDOW_ALIGN)
                sl = wins[k][pl.ds(jax.lax.div(srel, jnp.int32(_WINDOW_ALIGN)),
                                   RSB)]
                srem = jax.lax.rem(srel, jnp.int32(_WINDOW_ALIGN))
                rolled = _byte_roll(sl, run - srem)
                keep = _byte_keep_mask(pos4_row, run, run + L)
                rowbuf = rowbuf | (rolled & keep)
                run = run + L
            # place the finished row into the stash
            prel = dst - obase * jnp.int32(_WINDOW_ALIGN)
            q = jax.lax.div(prel, jnp.int32(_WINDOW_ALIGN))
            rem = jax.lax.rem(prel, jnp.int32(_WINDOW_ALIGN))
            placed = _byte_roll(rowbuf, rem)
            keep = _byte_keep_mask(pos4_row, rem, rem + run)
            cur = stash[pl.ds(q, RSB)]
            stash[pl.ds(q, RSB)] = cur | (placed & keep)
            return 0

        jax.lax.fori_loop(0, RB, body, 0)

        # flush CAP windows (zero tail is rewritten by later blocks; the
        # sequential grid + per-block wait orders the overlapping writes)
        cp = pltpu.make_async_copy(stash.at[pl.ds(0, CAP)],
                                   out_hbm.at[pl.ds(obase, CAP)],
                                   sems.at[K + 1])
        cp.start()
        # carry = the window holding the next block's first byte
        dst_end = meta(r0 + jnp.int32(RB), S - 1)
        used = jax.lax.div(dst_end, jnp.int32(_WINDOW_ALIGN)) - obase
        carry[...] = stash[pl.ds(used, 8)]
        cp.wait()

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=K + 1,
        grid=(plan.nblocks,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * (K + 1),
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=(
            [pltpu.VMEM((plan.win_rows[k], LANE), jnp.uint32)
             for k in range(K)]
            + [pltpu.SMEM((MR, LANE), jnp.int32),
               pltpu.VMEM((NP2, LANE), jnp.uint32),
               pltpu.VMEM((8, LANE), jnp.uint32),
               pltpu.SemaphoreType.DMA((K + 2,))]))
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((plan.out_rows, LANE), jnp.uint32),
        compiler_params=pltpu.CompilerParams(has_side_effects=True))


def compose(plan: ComposePlan, wb: list[jnp.ndarray], mb: jnp.ndarray,
            meta: jnp.ndarray, srcs: list[jnp.ndarray]) -> jnp.ndarray:
    """Run the composer.  Traceable (jit-safe).  Returns u32
    [total_bytes/4] — JCUDF rows are 8-byte aligned so the word view is
    exact."""
    padded = [_pad_src_u32(plan, k, s) for k, s in enumerate(srcs)]
    with jax.enable_x64(False):
        out = _compose_call(plan)(
            *[w.astype(jnp.int32) for w in wb], mb.astype(jnp.int32),
            meta, *padded)
    return out.reshape(-1)[:plan.total_bytes // 4]


def compose_xla(src_offs: list[np.ndarray], lens: list[np.ndarray],
                dst_offs: np.ndarray, srcs: list[jnp.ndarray],
                total: int) -> jnp.ndarray:
    """Reference formulation (gather; correct everywhere, slow on TPU) for
    differential tests of the kernel."""
    from .ragged import segmented_copy_xla
    acc = None
    n = dst_offs.shape[0] - 1
    run = np.zeros(n, dtype=np.int64)
    for k in range(len(srcs)):
        d = dst_offs[:-1] + run
        part = segmented_copy_xla(srcs[k].reshape(-1).view(jnp.uint8)
                                  if srcs[k].dtype != jnp.uint8 else srcs[k],
                                  src_offs[k][:-1], d, lens[k], total)
        acc = part if acc is None else acc | part
        run += lens[k]
    return acc
