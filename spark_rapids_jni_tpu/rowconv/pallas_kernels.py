"""Pallas TPU kernels for the fixed-width JCUDF transcode hot path.

The reference's hot loops are shared-memory tiled CUDA kernels
(``copy_to_rows`` / ``copy_from_rows`` / ``copy_validity_to_rows``,
``row_conversion.cu:575-693, 892-993, 710-810``): stage a 2-D tile of the
table in shmem in row layout, then blast it to global memory coalesced.

The TPU-native equivalent here works at *word* granularity instead of byte
granularity: a JCUDF row is a sequence of ``W = row_size/4`` little-endian
u32 words, and because every fixed-width column slot is aligned to its own
size (``compute_column_information``, ``row_conversion.cu:1331-1370``), each
word is composed of a *static* set of column fragments — a full int32, half
of an int64, or shifted int8/int16/validity bytes sharing one word.  The
kernel tiles rows through VMEM and materialises each output word with a
statically unrolled shift/or tree, fusing the data transpose and the
validity bit-pack (the ``__ballot_sync`` analog) into one pass: one HBM read
per column, one HBM write of the packed rows.  The tile/batch machinery of
the reference becomes the static grid spec — no runtime tile metadata.

Dispatch: :func:`fixed_pallas_enabled` routes to these kernels ONLY under
``SRJT_PALLAS=1`` — the default is the XLA path in ``convert.py``, which
honest in-jit timing measured ~3× faster (see that function's docstring).
Tests run these kernels in interpret mode on CPU and byte-compare against
the XLA oracle.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .. import types as T
from ..utils import bitmask
from .layout import RowLayout


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _is_f64(storage: np.dtype) -> bool:
    return storage.kind == "f" and storage.itemsize == 8


# ---------------------------------------------------------------------------
# static word-composition plan
# ---------------------------------------------------------------------------

def _word_plan(layout: RowLayout):
    """For each u32 word of the row, the static list of fragments.

    Fragment = (input_index, kind, arg):
      kind 'full'  — input is u32 [n], the whole word                (size 4)
      kind 'pair'  — input is u32 [n, 2], arg selects the half       (size 8)
      kind 'sub'   — input is u8/u16 [n], arg = byte shift in word   (size <4)
      kind 'vbyte' — input is u8 [n, vb], arg = (byte index, shift)
    Input order: one staged array per column, then the validity bytes.
    """
    W = layout.fixed_row_size // 4
    plan: list[list[tuple[int, str, object]]] = [[] for _ in range(W)]
    for ci, dt in enumerate(layout.schema):
        start = layout.column_starts[ci]
        size = layout.column_sizes[ci]
        if size == 16:   # DECIMAL128: staged u32 [n, 4], four words
            for j in range(4):
                plan[start // 4 + j].append((ci, "pair", j))
        elif size == 8:
            plan[start // 4].append((ci, "pair", 0))
            plan[start // 4 + 1].append((ci, "pair", 1))
        elif size == 4:
            plan[start // 4].append((ci, "full", None))
        else:  # 1 or 2; alignment guarantees it stays inside one word
            plan[start // 4].append((ci, "sub", start % 4))
    vi = layout.num_columns
    vo = layout.validity_offset
    for k in range(layout.validity_bytes):
        byte = vo + k
        plan[byte // 4].append((vi, "vbyte", (k, byte % 4)))
    return plan


def _stage_column_dt(data: jnp.ndarray, dt) -> jnp.ndarray:
    """DType-aware staging: DECIMAL128's [n, 2] int64 lanes become u32
    [n, 4] (lo_lo, lo_hi, hi_lo, hi_hi — little-endian word order);
    everything else delegates on the storage dtype."""
    from .. import types as T
    if dt.id == T.TypeId.DECIMAL128:
        return jax.lax.bitcast_convert_type(
            data, jnp.uint32).reshape(data.shape[0], 4)
    return _stage_column(data, dt.storage)


def _stage_column(data: jnp.ndarray, storage: np.dtype) -> jnp.ndarray:
    """Column payload → the kernel's staged form (see :func:`_word_plan`).

    Everything becomes u32 so that every kernel operand shares XLA:TPU's
    u32 tiled layout (Mosaic rejects mixed 1-D tilings): 8-byte columns as
    u32 [n, 2] halves, 4-byte columns bitcast, sub-word columns zero-
    extended (their shift/or placement masks nothing, so no masking is
    needed in-kernel).  FLOAT64 arrives pre-staged as u32 [n, 2] (XLA:TPU
    emulates f64 — see ``convert._stage``).
    """
    if _is_f64(storage):
        return data  # already u32 [n, 2]
    data = data.astype(storage)
    if storage.itemsize == 8:
        return jax.lax.bitcast_convert_type(data, jnp.uint32)   # [n, 2]
    if storage.itemsize == 4:
        return jax.lax.bitcast_convert_type(data, jnp.uint32)   # [n]
    unsigned = np.dtype(f"u{storage.itemsize}")
    return jax.lax.bitcast_convert_type(data, unsigned).astype(jnp.uint32)


def _unstage_column(staged: jnp.ndarray, storage: np.dtype) -> jnp.ndarray:
    if _is_f64(storage):
        return staged  # keep the u32 [n, 2] staging convention
    if storage.itemsize < 4:
        unsigned = np.dtype(f"u{storage.itemsize}")
        return jax.lax.bitcast_convert_type(
            staged.astype(jnp.dtype(unsigned)), jnp.dtype(storage))
    return jax.lax.bitcast_convert_type(staged, jnp.dtype(storage))


def _pad_rows(x: jnp.ndarray, n_pad: int) -> jnp.ndarray:
    n = x.shape[0]
    if n == n_pad:
        return x
    pad = [(0, n_pad - n)] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(x, pad)


def _tile_rows(n: int) -> tuple[int, int]:
    """(rows per grid step, padded row count).

    1024 rows/tile: 1-D u32 operands carry XLA:TPU's {0:T(1024)} tiled
    layout and Mosaic requires the block shape to be a multiple of it
    (2-D operands only need sublane multiples of 32, which 1024 also is).
    """
    tr = 1024
    return tr, _round_up(max(n, 1), tr)


# ---------------------------------------------------------------------------
# pack: columns (+ validity matrix) → JCUDF row words
# ---------------------------------------------------------------------------

def to_rows_fixed(layout: RowLayout, datas: Sequence[jnp.ndarray],
                  valid: jnp.ndarray, *, interpret: bool = False) -> jnp.ndarray:
    """Fixed-width pack on TPU via Pallas → uint8 [n, fixed_row_size].

    Same contract as ``convert._to_rows_fixed`` (the XLA oracle):
    ``datas`` per-column payloads (f64 staged as u32 [n, 2]), ``valid``
    bool [n, ncols].
    """
    n = valid.shape[0]
    W = layout.fixed_row_size // 4
    plan = _word_plan(layout)
    tr, n_pad = _tile_rows(n)

    staged = [_pad_rows(_stage_column(d, dt.storage), n_pad)
              for d, dt in zip(datas, layout.schema)]
    # validity bytes widened to u32: Mosaic mishandles narrow-laned u8
    # blocks (observed: zeroed loads from a (tr, 2) u8 block on v5e)
    vbytes = _pad_rows(
        bitmask.pack_bool_matrix(valid).astype(jnp.uint32), n_pad)
    inputs = staged + [vbytes]

    def kernel(*refs):
        in_refs, out_ref = refs[:-1], refs[-1]
        loaded = [r[...] for r in in_refs]
        words = []
        for w in range(W):
            acc = None
            for ii, kind, arg in plan[w]:
                x = loaded[ii]
                if kind == "full":
                    v = x
                elif kind == "pair":
                    v = x[:, arg]
                elif kind == "sub":
                    # multiply, not <<: Mosaic (v5e, jax 0.8) miscompiles
                    # shl-by-16 of a lane-sliced narrow block to zero
                    v = x * jnp.uint32(1 << (arg * 8))
                else:  # vbyte
                    k, shift = arg
                    v = x[:, k] * jnp.uint32(1 << (shift * 8))
                acc = v if acc is None else acc | v
            words.append(acc if acc is not None
                         else jnp.zeros((tr,), jnp.uint32))
        out_ref[...] = jnp.stack(words, axis=1)

    def spec(a):
        if a.ndim == 1:
            return pl.BlockSpec((tr,), lambda i: (i,))
        return pl.BlockSpec((tr, a.shape[1]), lambda i: (i, jnp.int32(0)))

    out = pl.pallas_call(
        kernel,
        grid=(n_pad // tr,),
        in_specs=[spec(a) for a in inputs],
        out_specs=pl.BlockSpec((tr, W), lambda i: (i, jnp.int32(0))),
        out_shape=jax.ShapeDtypeStruct((n_pad, W), jnp.uint32),
        interpret=interpret,
    )(*inputs)

    rows = jax.lax.bitcast_convert_type(out, jnp.uint8)  # [n_pad, W, 4]
    return rows.reshape(n_pad, layout.fixed_row_size)[:n]


# ---------------------------------------------------------------------------
# unpack: JCUDF row words → columns (+ validity matrix)
# ---------------------------------------------------------------------------

def from_rows_fixed(layout: RowLayout, rows: jnp.ndarray,
                    *, interpret: bool = False):
    """Inverse of :func:`to_rows_fixed`: uint8 [n, row_size] → (datas, valid)."""
    n = rows.shape[0]
    W = layout.fixed_row_size // 4
    tr, n_pad = _tile_rows(n)
    vo, vb = layout.validity_offset, layout.validity_bytes

    rows32 = jax.lax.bitcast_convert_type(
        _pad_rows(rows, n_pad).reshape(n_pad, W, 4), jnp.uint32)

    out_shapes, col_plan = [], []
    for ci, dt in enumerate(layout.schema):
        start, size = layout.column_starts[ci], layout.column_sizes[ci]
        if size == 8:
            out_shapes.append(jax.ShapeDtypeStruct((n_pad, 2), jnp.uint32))
            col_plan.append(("pair", start // 4))
        elif size == 4:
            out_shapes.append(jax.ShapeDtypeStruct((n_pad,), jnp.uint32))
            col_plan.append(("full", start // 4))
        else:
            out_shapes.append(jax.ShapeDtypeStruct((n_pad,), jnp.uint32))
            col_plan.append(("sub", (start // 4, start % 4, size)))
    # u32 lanes for the validity bytes (same Mosaic narrow-u8-block issue
    # as the pack side); narrowed back outside the kernel
    out_shapes.append(jax.ShapeDtypeStruct((n_pad, vb), jnp.uint32))

    def kernel(rows_ref, *out_refs):
        r = rows_ref[...]  # [tr, W] u32
        for (kind, arg), oref in zip(col_plan, out_refs[:-1]):
            if kind == "pair":
                oref[...] = jnp.stack([r[:, arg], r[:, arg + 1]], axis=1)
            elif kind == "full":
                oref[...] = r[:, arg]
            else:
                w, shift, width = arg
                oref[...] = (r[:, w] >> (shift * 8)) & ((1 << (8 * width)) - 1)
        vwords = []
        for k in range(vb):
            byte = vo + k
            vwords.append((r[:, byte // 4] >> ((byte % 4) * 8)) & 0xFF)
        out_refs[-1][...] = jnp.stack(vwords, axis=1)

    def spec(s):
        if len(s.shape) == 1:
            return pl.BlockSpec((tr,), lambda i: (i,))
        return pl.BlockSpec((tr, s.shape[1]), lambda i: (i, jnp.int32(0)))

    outs = pl.pallas_call(
        kernel,
        grid=(n_pad // tr,),
        in_specs=[pl.BlockSpec((tr, W), lambda i: (i, jnp.int32(0)))],
        out_specs=[spec(s) for s in out_shapes],
        out_shape=out_shapes,
        interpret=interpret,
    )(rows32)

    datas = tuple(
        _unstage_column(o[:n], dt.storage)
        for o, dt in zip(outs[:-1], layout.schema))
    valid = bitmask.unpack_bool_matrix(
        outs[-1][:n].astype(jnp.uint8), layout.num_columns)
    return datas, valid


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------

# Widest row (in u32 words) routed to the Pallas kernels.  The statically
# unrolled word tree costs scoped VMEM roughly linearly in W (observed on
# v5e: W=220 → 39.5M scoped vs the 16M limit, i.e. ~180KB/word with the
# 1024-row tile); beyond this bound the XLA path wins anyway because the
# unroll dominates compile time.  Override with SRJT_PALLAS_MAX_WORDS.
_MAX_PLAN_WORDS = 64


def layout_supported(layout: RowLayout) -> bool:
    """Static per-schema gate for the Pallas fixed-width kernels."""
    from .. import types as T
    if any(dt.id == T.TypeId.DECIMAL128 for dt in layout.schema):
        return False   # d128 rides the XLA path only (no 16B kernel plan)
    max_words = int(os.environ.get("SRJT_PALLAS_MAX_WORDS", _MAX_PLAN_WORDS))
    return layout.fixed_row_size // 4 <= max_words


def fixed_pallas_enabled() -> bool:
    """True when the fixed-width transcode should route through Pallas.

    ``SRJT_PALLAS=1`` forces on; anything else (including the default
    ``auto``) is **off**: honest device-side timing (dependency-chained
    in-jit loops with forced materialization — per-call
    ``block_until_ready`` is a no-op on the axon tunnel and round-1's
    "Pallas wins" numbers were dispatch-rate artifacts) measured the XLA
    path at ~6.2 GB/s round-trip vs ~2.2 GB/s for these kernels on a 12-col
    1M-row table: the [rows, W-words] block shape puts only W≈12 of 128
    lanes to work.  The kernels remain for narrow-schema experimentation
    until the lane-major redesign lands.
    """
    env = os.environ.get("SRJT_PALLAS", "auto").lower()
    return env in ("1", "on", "true")
