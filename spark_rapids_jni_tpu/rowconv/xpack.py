"""Variable-width JCUDF composition as ONE fused XLA program (round 4).

The round-3 string path moved bytes with per-(row|segment) machinery whose
per-row cost floor was measured at 0.16-0.8 µs (Pallas per-row rolls) or
24 ns (XLA row-granular gathers) — a 1M-row mixed batch could not beat
~0.2 GB/s wall.  This module rebuilds the path on the two primitives the
round-4 chip shootout (``PROFILE_strings.json``, ``tools/probe_slab.py``)
showed to be fast:

* **slab gathers** — XLA row gathers cost ~24 ns per *gathered row*
  regardless of row width (43.9 GB/s at 512 B rows), so all gathers here
  move WIDE slabs covering many logical rows: per-column char windows are
  gathered per GROUP of ``g`` rows (one slab covers the whole group's
  chars), and the output packing gathers one ``P``-row slab per 512 B
  output window.  Gather count is ``n/g + n_windows``, not ``n × pieces``.
* **log-shift rolls** — per-row dynamic byte placement is a select tree
  (log₂(width) word passes + a 4-variant byte funnel), pure elementwise,
  which XLA fuses into a handful of memory passes.  No scatter, no
  per-element gather, no serialization.

This is the same job as the reference's fused string kernels
(``copy_strings_to_rows``, row_conversion.cu:827-875, 1861: one launch
writes fixed slots, validity, and chars for a batch) — restructured so the
heavy traffic is aligned bulk reads + register shuffles, the TPU-friendly
shape of that computation.  Everything here is shape-static given the
geometry buckets, so the whole conversion runs as ONE jitted program per
(schema, geometry-bucket) with zero host syncs inside.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .layout import RowLayout

LANE = 128
WIN_W = 128                    # output pack window: 128 u32 words = 512 B

# Fallback accounting (VERDICT r4 weak #3): every geometry-plan rejection
# increments a named counter and emits one structured-log event, so a bench
# or query run can say exactly WHY a conversion degraded to a slower path.
fallback_counts: dict[str, int] = {}


def _reject(reason: str, **fields):
    """Record a geometry-cap rejection; returns None (the plan result)."""
    fallback_counts[reason] = fallback_counts.get(reason, 0) + 1
    from ..utils import structured_log
    structured_log.event("xpack_fallback", reason=reason, **fields)
    return None


def _bucket(x: int, lo: int = 8) -> int:
    """≤ ~12.5% growth bucket (pow2/8 multiples) to bound jit variants."""
    if x <= lo:
        return lo
    p = lo
    while p < x:
        p <<= 1
    step = max(p // 8, 1)
    return -(-x // step) * step


def _u8_to_u32_rows(b: jnp.ndarray) -> jnp.ndarray:
    """u8 [n, 4W] → u32 [n, W] little-endian (elementwise, fused)."""
    n, w4 = b.shape
    parts = [b[:, k::4].astype(jnp.uint32) for k in range(4)]
    return (parts[0] | (parts[1] << 8) | (parts[2] << 16)
            | (parts[3] << 24))


def _nbits_for(W: int) -> int:
    b = 0
    while (1 << b) < W + 1:
        b += 1
    return b


def _take_words(m: jnp.ndarray, sh: jnp.ndarray, Wo: int) -> jnp.ndarray:
    """out[r, j] = m[r, sh[r] + j] for j < Wo (zeros beyond the source).

    NARROWING radix-4 select tree: the level handling digit weight 4^k
    works at width ``Wo + 4^k − 1`` — widths shrink geometrically, so the
    total vector traffic is ~(W/3 + Wo·log) per row instead of the naive
    W·log of a fixed-width tree."""
    W = m.shape[1]
    levels = []
    w = 1
    while w < W:
        levels.append(w)
        w *= 4
    cur = m
    for wk in reversed(levels):
        Wn = Wo + wk - 1
        digit = ((sh // wk) % 4).astype(jnp.int32)[:, None]
        vs = []
        for k in range(4):
            s0 = k * wk
            if s0 >= cur.shape[1]:
                vs.append(jnp.zeros((cur.shape[0], Wn), cur.dtype))
                continue
            sl = cur[:, s0:s0 + Wn]
            if sl.shape[1] < Wn:
                sl = jnp.pad(sl, ((0, 0), (0, Wn - sl.shape[1])))
            vs.append(sl)
        cur = jnp.where(digit == 1, vs[1],
                        jnp.where(digit == 2, vs[2],
                                  jnp.where(digit == 3, vs[3], vs[0])))
    return cur[:, :Wo]


def _place_words(m: jnp.ndarray, sh: jnp.ndarray, Wo: int) -> jnp.ndarray:
    """out[r, sh[r] + j] = m[r, j] (zeros elsewhere), out width Wo.

    WIDENING radix-4 tree (inverse of :func:`_take_words`): digits are
    applied low→high at geometrically growing widths, so only the final
    level touches the full output width."""
    cur = m
    wk = 1
    while True:
        last = wk * 4 >= Wo
        Wn = Wo if last else min(cur.shape[1] + 3 * wk, Wo)
        digit = ((sh // wk) % 4).astype(jnp.int32)[:, None]
        vs = []
        for k in range(4):
            keep = max(0, min(cur.shape[1], Wn - k * wk))
            if keep == 0:
                vs.append(jnp.zeros((cur.shape[0], Wn), cur.dtype))
                continue
            vs.append(jnp.pad(cur[:, :keep],
                              ((0, 0), (k * wk, Wn - k * wk - keep))))
        cur = jnp.where(digit == 1, vs[1],
                        jnp.where(digit == 2, vs[2],
                                  jnp.where(digit == 3, vs[3], vs[0])))
        if last:
            return cur
        wk *= 4


def _byte_mask(W: int, start_b: jnp.ndarray, end_b: jnp.ndarray):
    """u32 mask [n, W]: byte positions in [start, end) per row."""
    pos = (jnp.arange(W, dtype=jnp.int32) * 4)[None, :]
    s = start_b[:, None]
    e = end_b[:, None]
    m = jnp.zeros((start_b.shape[0], W), jnp.uint32)
    for k in range(4):
        inside = ((pos + k) >= s) & ((pos + k) < e)
        m = m | jnp.where(inside, jnp.uint32(0xFF << (8 * k)), jnp.uint32(0))
    return m


def _pad_to_blocks(flat_u8: jnp.ndarray, B: int) -> jnp.ndarray:
    """u8 [T] → u32 [nb, 2B/4]: B-byte blocks, each row concatenated with
    its successor so ONE gathered row covers any window of ≤ B bytes."""
    T = flat_u8.shape[0]
    nb = max(-(-T // B), 1)
    pad = nb * B - T
    b2 = jnp.pad(flat_u8, (0, pad)).reshape(nb, B)
    w = _u8_to_u32_rows(b2)                      # [nb, B/4]
    nxt = jnp.concatenate([w[1:], jnp.zeros((1, B // 4), jnp.uint32)])
    return jnp.concatenate([w, nxt], axis=1)     # [nb, B/2]


def extract_group_windows(chars_u8: jnp.ndarray, offs: jnp.ndarray,
                          n: int, g: int, B: int, Lw: int) -> jnp.ndarray:
    """Per-row char windows [n, Lw] u32 from a contiguous chars buffer.

    One slab gather per GROUP of ``g`` rows (the group's chars span ≤ B
    bytes — caller sizes B from the host geometry), then ``g`` fused
    byte-shifts pull each row's window out of its group slab.
    """
    ngroups = -(-n // g)
    v2 = _pad_to_blocks(chars_u8, B)             # [nb, B/2] u32
    gstart = offs[jnp.minimum(
        jnp.arange(ngroups, dtype=jnp.int32) * g, n)]
    blk = gstart // B
    slab = v2[jnp.clip(blk, 0, v2.shape[0] - 1)]  # [ngroups, B/2]
    outs = []
    for j in range(g):
        ridx = jnp.minimum(jnp.arange(ngroups, dtype=jnp.int32) * g + j,
                           n - 1) if n else jnp.zeros(0, jnp.int32)
        amt = offs[ridx] - blk * B               # byte offset, [0, 2B)
        w = _take_words(slab, amt // 4, Lw + 1)
        outs.append(_roll_left_bytes(w, Lw, amt % 4))
    out = jnp.stack(outs, axis=1).reshape(ngroups * g, Lw)
    return out[:n]


def _first_row_per_window(dst: jnp.ndarray, n: int, nwin: int,
                          win: int = WIN_W) -> jnp.ndarray:
    """fr[w] = last row r with dst[r] ≤ w·win (rows cover windows
    contiguously; ``dst``/``win`` share a unit — words or bytes).  Pure
    segment-sum/cumsum — no searchsorted."""
    win_of = (dst[:n] // win).astype(jnp.int32)
    h = jax.ops.segment_sum(jnp.ones(n, jnp.int32), win_of, nwin)
    lt = jnp.concatenate([jnp.zeros(1, jnp.int32),
                          jnp.cumsum(h)[:-1]])   # #rows with dst < w·win
    eq = jax.ops.segment_sum(
        ((dst[:n] % win) == 0).astype(jnp.int32), win_of, nwin)
    return lt + eq - 1


def pack_windows(dense: jnp.ndarray, dst_w: jnp.ndarray, total_w: int,
                 P: int, nwin: int) -> jnp.ndarray:
    """Pack padded rows [n, Mw] into flat words [total_w] (rows are
    8-byte-aligned so packing is word-granular).

    Output-window-centric: window w takes rows fr(w)..fr(w)+P-1 as ONE
    gathered slab from a P-wide shifted view of ``dense``, then places each
    row with a fused word-shift + mask + OR.

    ``SRJT_PALLAS_PACKWIN`` routes the same placement through the Mosaic
    kernel (one VMEM row-window DMA per 4 KiB output block instead of the
    P-wide slab re-read); geometry outside the kernel envelope falls back
    here."""
    from . import xpallas
    xout = xpallas.try_pack_windows(dense, dst_w, total_w, P, nwin)
    if xout is not None:
        return xout
    n, Mw = dense.shape
    # P-row slab view: VP[r] = dense[r] ++ dense[r+1] ++ … ++ dense[r+P-1]
    padded = jnp.pad(dense, ((0, P), (0, 0)))
    vp = jnp.concatenate([padded[p:n + p] for p in range(P)], axis=1)
    fr = _first_row_per_window(dst_w, n, nwin)
    fr = jnp.clip(fr, 0, max(n - 1, 0))
    slab = vp[fr]                                 # [nwin, P·Mw]

    F = WIN_W + 2 * Mw                            # frame with ±Mw slack
    wbase = jnp.arange(nwin, dtype=jnp.int32) * WIN_W
    acc = jnp.zeros((nwin, F), jnp.uint32)
    for p in range(P):
        r = jnp.minimum(fr + p, n - 1)
        d = dst_w[r] - wbase + Mw                 # biased frame offset ≥ 0
        live = (fr + p < n) & (dst_w[r] < wbase + WIN_W) & (d >= 0)
        piece = slab[:, p * Mw:(p + 1) * Mw]
        placed = _place_words(piece, jnp.where(live, d, 0), F)
        rw = dst_w[r + 1] - dst_w[r]
        mask = _byte_mask(F, d * 4, (d + rw) * 4)
        acc = acc | jnp.where(live[:, None], placed & mask, jnp.uint32(0))
    out = acc[:, Mw:Mw + WIN_W].reshape(-1)
    return out[:total_w]


def _roll_left_bytes(w: jnp.ndarray, Lw: int, rb: jnp.ndarray) -> jnp.ndarray:
    """[n, Lw+1] u32 word windows → [n, Lw]: shift each row LEFT by
    rb∈[0,4) bytes (the payload starts ``rb`` bytes into word 0).  The
    shared inner roll of every window-extraction site."""
    a, nxt = w[:, :Lw], w[:, 1:Lw + 1]
    rbc = rb.astype(jnp.uint32)[:, None]
    out = a
    for k in (1, 2, 3):
        v = (a >> jnp.uint32(8 * k)) | (nxt << jnp.uint32(32 - 8 * k))
        out = jnp.where(rbc == k, v, out)
    return out


def _byte_funnel_right(win: jnp.ndarray, rb: jnp.ndarray) -> jnp.ndarray:
    """[n, W] u32 → [n, W+1]: shift each row RIGHT by rb∈[0,4) bytes."""
    a = jnp.pad(win, ((0, 0), (0, 1)))
    prev = jnp.pad(win, ((0, 0), (1, 0)))
    rbc = rb.astype(jnp.uint32)[:, None]
    fun = a
    for k in (1, 2, 3):
        v = (a << jnp.uint32(8 * k)) | (prev >> jnp.uint32(32 - 8 * k))
        fun = jnp.where(rbc == k, v, fun)
    return fun


def _words_to_u8(w: jnp.ndarray) -> jnp.ndarray:
    """u32 [N] → u8 [4N] little-endian (elementwise)."""
    pad = (-w.shape[0]) % LANE
    w2 = jnp.pad(w, (0, pad)).reshape(-1, LANE)
    out = jnp.zeros((w2.shape[0], 4 * LANE), jnp.uint8)
    for k in range(4):
        out = out.at[:, k::4].set(((w2 >> (8 * k)) & 0xFF).astype(jnp.uint8))
    return out.reshape(-1)[:w.shape[0] * 4]


# ---------------------------------------------------------------------------
# segmented gather: ordered byte segments → packed stream (device)
# ---------------------------------------------------------------------------

def plan_segmented_gather(src_starts_np: np.ndarray, lens_np: np.ndarray,
                          dst_offs_np: np.ndarray):
    """Host geometry for :func:`segmented_gather` (bucketed statics), or
    None outside the supported buckets.  Segments must be ordered in the
    source (monotone starts) — true for parquet string payloads and JCUDF
    row streams alike."""
    n = int(lens_np.shape[0])
    total = int(dst_offs_np[-1])
    if n == 0 or total == 0:
        return None
    g = 8
    Lmax = int(lens_np.max(initial=0))
    Lw = _bucket(-(-max(Lmax, 1) // 4) + 1, 4)
    idx = np.minimum(np.arange(0, n + g, g), n)
    ends = src_starts_np + lens_np
    lo, hi = idx[:-1], idx[1:]
    nonempty = hi > lo
    span = int((ends[np.maximum(hi - 1, 0)] - src_starts_np[lo])
               [nonempty].max(initial=0))
    B = _bucket(max(span, 64), 64)
    gd = dst_offs_np[idx]
    Bd = _bucket(-(-int((gd[1:] - gd[:-1]).max(initial=1)) // 4) + 1, 8)
    nwin = -(-total // 512)
    fr = np.searchsorted(gd, np.arange(nwin, dtype=np.int64) * 512,
                         side="right") - 1
    lr = np.searchsorted(gd, np.minimum(
        np.arange(nwin, dtype=np.int64) * 512 + 512, total) - 1,
        side="right") - 1
    P = _bucket(int((lr - fr).max(initial=0)) + 1, 2)
    # the same caps as plan_from_device_stats: short-segment geometries
    # (P explodes with ~64 groups per window) must degrade to the caller's
    # fallback, not compile a P-times-unrolled combine
    if B > (1 << 20) or Lw > 512 or Bd > 512 or P > 64:
        return _reject("seg_gather_caps_host", B=B, Lw=Lw, Bd=Bd, P=int(P))
    return (n, g, B, Lw, Bd, int(P), nwin, total)


def dst_combine_stats(dst_offs: jnp.ndarray, g: int = 8):
    """Traceable destination-side packing stats for the group-accumulate
    + window combine: [total, max group dst span, max groups per 512B
    window].  Shared by every engine that packs ordered segments
    (segmented_gather, the from_rows inverse, dictionary strings)."""
    n = dst_offs.shape[0] - 1
    dst = dst_offs.astype(jnp.int64)
    ngroups = -(-n // g)
    gi = jnp.minimum(jnp.arange(ngroups + 1) * g, n)
    dstg = dst[gi]
    dspan = jnp.max(dstg[1:] - dstg[:-1])
    upto = jnp.searchsorted(dstg[:-1], dstg[:-1] + 512, side="left")
    max_p = jnp.max(upto - jnp.arange(ngroups)) + 1
    return jnp.stack([dst[-1], dspan, max_p])


def plan_combine(total: int, dspan: int, max_p: int, reject_tag: str,
                 final: bool = True):
    """Bucket the combine geometry (Bd, P, nwin) from destination stats;
    None outside the caps (with fallback accounting only when ``final`` —
    adaptive-g retries probe several group sizes before giving up)."""
    Bd = _bucket(-(-max(dspan, 1) // 4) + 1, 8)
    P = _bucket(max_p, 2)
    if Bd > 512 or P > 64:
        if final:
            return _reject(reject_tag, Bd=Bd, P=int(P))
        return None
    return (Bd, int(P), -(-total // 512))


@jax.jit
def _seg_gather_stats(src_starts, lens, dst_offs):
    """Device geometry stats for :func:`plan_from_device_stats`: ONE tiny
    stacked sync instead of pulling per-segment metadata to the host
    (g = 8).  Returns [total, Lmax, max group src span, max group dst
    span, max groups overlapping a 512B output window]."""
    g = 8
    n = lens.shape[0]
    src_starts = src_starts.astype(jnp.int64)
    lens = lens.astype(jnp.int64)
    dst_offs = dst_offs.astype(jnp.int64)
    ngroups = -(-n // g)
    gi = jnp.minimum(jnp.arange(ngroups + 1) * g, n)
    ends = src_starts + lens
    gstart = src_starts[jnp.minimum(gi[:-1], n - 1)]
    gend = ends[jnp.minimum(gi[1:] - 1, n - 1)]
    src_span = jnp.max(gend - gstart)
    dstg = dst_offs[gi]
    dspan = jnp.max(dstg[1:] - dstg[:-1])
    total = dst_offs[-1]
    # max groups overlapping any 512B output window: for each group k,
    # how many group starts fall inside [dstg[k], dstg[k] + 512)
    upto = jnp.searchsorted(dstg[:-1], dstg[:-1] + 512, side="left")
    max_p = jnp.max(upto - jnp.arange(ngroups)) + 1
    return jnp.stack([total, jnp.max(lens), src_span, dspan, max_p])


def plan_from_device_stats(stats, n: int):
    """:func:`segmented_gather` geom from the device-stats sync."""
    total, Lmax, src_span, dspan, max_p = (int(x) for x in stats)
    if n == 0 or total == 0:
        return None
    g = 8
    Lw = _bucket(-(-max(Lmax, 1) // 4) + 1, 4)
    B = _bucket(max(src_span, 64), 64)
    Bd = _bucket(-(-max(dspan, 1) // 4) + 1, 8)
    P = _bucket(max_p, 2)
    if B > (1 << 20) or Lw > 512 or Bd > 512 or P > 64:
        return _reject("seg_gather_caps_dev", B=B, Lw=Lw, Bd=Bd, P=int(P))
    nwin = -(-total // 512)
    return (n, g, B, Lw, Bd, int(P), nwin, total)


@functools.partial(jax.jit, static_argnums=0)
def segmented_gather(geom, src_u8: jnp.ndarray, src_starts: jnp.ndarray,
                     lens: jnp.ndarray, dst_offs: jnp.ndarray):
    """Pack ordered byte segments: out[dst_offs[i]:dst_offs[i]+lens[i]] =
    src[src_starts[i]:+lens[i]], fully on device — group-slab gathers and
    narrow/widening roll trees (same primitives as the to_rows engine).
    Returns u8 [total]."""
    n, g, B, Lw, Bd, P, nwin, total = geom
    src_starts = src_starts.astype(jnp.int32)
    lens = lens.astype(jnp.int32)
    dst_offs = dst_offs.astype(jnp.int32)
    ngroups = -(-n // g)
    v2 = _pad_to_blocks(src_u8, B)
    gidx = jnp.minimum(jnp.arange(ngroups, dtype=jnp.int32) * g, n - 1)
    gsrc0 = src_starts[gidx]
    blk = gsrc0 // B
    slab = v2[jnp.clip(blk, 0, v2.shape[0] - 1)]
    dstg = dst_offs[jnp.minimum(
        jnp.arange(ngroups + 1, dtype=jnp.int32) * g, n)]
    acc = jnp.zeros((ngroups, Bd), jnp.uint32)
    for j in range(g):
        ridx = jnp.minimum(jnp.arange(ngroups, dtype=jnp.int32) * g + j,
                           n - 1)
        live = (jnp.arange(ngroups, dtype=jnp.int32) * g + j) < n
        amt = src_starts[ridx] - blk * B
        w = _take_words(slab, amt // 4, Lw + 1)
        piece = _roll_left_bytes(w, Lw, amt % 4)
        drel = dst_offs[ridx] - dstg[:-1]
        fun = _byte_funnel_right(piece, drel % 4)
        placed = _place_words(fun, drel // 4, Bd)
        mask = _byte_mask(Bd, drel, drel + lens[ridx])
        acc = acc | jnp.where(live[:, None], placed & mask, jnp.uint32(0))

    return _group_windows_combine(acc, dstg, ngroups, Bd, P, nwin, total)


def _group_windows_combine(acc: jnp.ndarray, dstg: jnp.ndarray,
                           ngroups: int, Bd: int, P: int, nwin: int,
                           total: int) -> jnp.ndarray:
    """Window combine: group accumulators [ngroups, Bd] u32 at byte-granular
    group destinations ``dstg`` [ngroups+1] → packed u8 [total]."""
    fr = _first_row_per_window(dstg, ngroups, nwin, 512)
    fr = jnp.clip(fr, 0, ngroups - 1)
    padded = jnp.pad(acc, ((0, P), (0, 0)))
    vp = jnp.concatenate([padded[p:ngroups + p] for p in range(P)], axis=1)
    slab2 = vp[fr]
    F = WIN_W + 2 * Bd
    wbase = jnp.arange(nwin, dtype=jnp.int32) * 512
    out = jnp.zeros((nwin, F), jnp.uint32)
    for p in range(P):
        r = jnp.minimum(fr + p, ngroups - 1)
        d_b = dstg[r] - wbase + Bd * 4            # biased, ≥ 0 when live
        live = (fr + p < ngroups) & (dstg[r] < wbase + 512) & (d_b >= 0)
        piece = slab2[:, p * Bd:(p + 1) * Bd]
        fun = _byte_funnel_right(piece, d_b % 4)
        placed = _place_words(fun, d_b // 4, F)
        glen = dstg[r + 1] - dstg[r]
        mask = _byte_mask(F, d_b, d_b + glen)
        out = out | jnp.where(live[:, None], placed & mask, jnp.uint32(0))
    flat = out[:, Bd:Bd + WIN_W].reshape(-1)
    return _words_to_u8(flat)[:total]


# ---------------------------------------------------------------------------
# to_rows: whole-batch fused program
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(0, 1))
def _to_rows_x_jit(layout: RowLayout, geom, datas, str_offsets, valid):
    """geom: (n, Mw, P, nwin, total_w, g, per-col (B, Lw)) — all static.

    Everything — including the destination row offsets (the 8-byte-aligned
    cumsum the host batching derives the same way) — is computed on device:
    a warm call uploads NOTHING through the tunnel.
    """
    n, Mw, P, nwin, total_w, g, colgeo = geom
    var_idx = layout.variable_column_indices
    fpv = layout.fixed_plus_validity
    fpvw = -(-fpv // 4)
    str_offsets = tuple(o.astype(jnp.int32) for o in str_offsets)
    # valid: per-column bool [n] or None — the matrix builds in-trace (an
    # eager stack of 12 validity vectors costs a dispatch each through the
    # tunnel)
    vmat = jnp.stack([jnp.ones((n,), jnp.bool_) if v is None else v
                      for v in valid], axis=1)

    from .convert import _var_fixed_region
    fixed2d = _var_fixed_region(layout, datas, str_offsets, vmat)
    fixed_w = _u8_to_u32_rows(
        jnp.pad(fixed2d, ((0, 0), (0, fpvw * 4 - fpv))))     # [n, fpvw]

    lens = jnp.stack(
        [str_offsets[vi][1:] - str_offsets[vi][:-1]
         for vi in range(len(var_idx))], axis=1).astype(jnp.int32)
    prefix = jnp.cumsum(lens, axis=1) - lens

    dense = jnp.pad(fixed_w, ((0, 0), (0, Mw - fpvw)))
    for vi in range(len(var_idx)):
        B, Lw = colgeo[vi]
        if Lw == 0:
            continue
        win = extract_group_windows(datas[var_idx[vi]].reshape(-1),
                                    str_offsets[vi], n, g, B, Lw)
        start_b = fpv + prefix[:, vi]
        # byte funnel at the NARROW width, then the widening word place
        a = jnp.pad(win, ((0, 0), (0, 1)))
        prev = jnp.pad(win, ((0, 0), (1, 0)))
        rb = (start_b % 4).astype(jnp.uint32)[:, None]
        fun = a
        for k in (1, 2, 3):
            v = ((a << jnp.uint32(8 * k))
                 | (prev >> jnp.uint32(32 - 8 * k)))
            fun = jnp.where(rb == k, v, fun)
        placed = _place_words(fun, start_b // 4, Mw)
        mask = _byte_mask(Mw, start_b, start_b + lens[:, vi])
        dense = dense | (placed & mask)

    # destination offsets: align8(fpv + Σ lens), cumulative — the same rule
    # as layout.row_sizes_with_strings (row_conversion.cu:216-261), in words
    row_b = fpv + prefix[:, -1] + lens[:, -1]
    rs_w = ((row_b + 7) // 8 * 8) // 4
    dst_w = jnp.concatenate([jnp.zeros(1, jnp.int32),
                             jnp.cumsum(rs_w, dtype=jnp.int32)])
    # (a pair-compaction level before the pack was measured and REJECTED:
    # the strided row split d[0::2]/d[1::2] alone cost ~76 ms at 1M rows —
    # more than the whole frame-combine saving it buys)
    return pack_windows(dense, dst_w, total_w, P, nwin)


def _plan_geometry(layout: RowLayout, n: int, offs_np: np.ndarray,
                   col_offs_np: list[np.ndarray]):
    """Host geometry pass → static ``geom`` tuple (bucketed), or None when
    outside the supported buckets."""
    total = int(offs_np[-1])
    row_sizes = offs_np[1:] - offs_np[:-1]
    Mw = _bucket(-(-int(row_sizes.max()) // 4), 8)
    if Mw > 256:                                  # > 1KB rows: fall back
        return _reject("to_rows_row_width", Mw=Mw)
    nwin = -(-(total // 4) // WIN_W)
    # max rows overlapping one output window
    fr = np.searchsorted(offs_np, np.arange(nwin, dtype=np.int64) * 512,
                         side="right") - 1
    lr = np.searchsorted(offs_np,
                         np.minimum(np.arange(nwin, dtype=np.int64) * 512
                                    + 512, total) - 1, side="right") - 1
    P = _bucket(int((lr - fr).max(initial=0)) + 1, 2)
    g = 8
    colgeo = []
    for vi in range(len(layout.variable_column_indices)):
        co = col_offs_np[vi]
        clens = co[1:] - co[:-1]
        Lmax = int(clens.max(initial=0))
        if Lmax == 0:
            colgeo.append((0, 0))
            continue
        idx = np.minimum(np.arange(0, n + g, g), n)
        span = int((co[idx[1:]] - co[idx[:-1]]).max(initial=1))
        B = _bucket(max(span, 64), 64)
        Lw = _bucket(-(-Lmax // 4), 4)
        if B > (1 << 20) or Lw > 512:
            return _reject("to_rows_col_caps", col=vi, B=B, Lw=Lw)
        colgeo.append((B, Lw))
    return (n, Mw, int(P), nwin, total // 4, g, tuple(colgeo))


def to_rows_var_x(layout: RowLayout, sub, offs_np: np.ndarray,
                  col_offs_np: list[np.ndarray]):
    """Strings → packed JCUDF rows, one jitted dispatch.

    ``offs_np``: host row offsets [n+1] (8-byte-aligned rows).
    ``col_offs_np``: host char offsets per var column (geometry buckets).
    Returns u32 words [total/4] or None when the geometry exceeds the
    supported buckets (caller falls back).

    The host geometry pass is memoized on the string-offset device arrays
    (the analytics steady state re-converts the same tables), so a warm
    call is pure dispatch: no host scans, no device uploads.
    """
    n = sub.num_rows
    var_idx = layout.variable_column_indices
    if n == 0 or int(offs_np[-1]) == 0:
        return None
    from ..utils import syncs
    key_arrays = [sub[ci].offsets for ci in var_idx]
    # the geometry depends on the LAYOUT too (fpv feeds the row sizes), so
    # the memo tag carries it — the same string column objects reused under
    # a different schema must not hit a stale geometry
    tag = f"xpack_geom:{hash(layout)}"
    geom = syncs.memo_get(tag, key_arrays)
    if geom is None:
        geom = _plan_geometry(layout, n, offs_np, col_offs_np)
        if geom is None:
            return None
        syncs.memo_put(tag, key_arrays, geom)
    return _to_rows_x_jit(
        layout, geom,
        tuple(c.data for c in sub.columns),
        tuple(sub[ci].offsets for ci in var_idx),
        tuple(c.validity for c in sub.columns))


# ---------------------------------------------------------------------------
# from_rows: whole-batch fused program (the inverse engine, round 5)
# ---------------------------------------------------------------------------
#
# Inverse of ``to_rows_var_x`` — the same job as the reference's
# ``copy_strings_from_rows`` + chars scan + make_strings_column
# (row_conversion.cu:1131-1174, 2201-2246): packed JCUDF rows → fixed
# column payloads + validity + per-column chars streams, all on device.
# Rows are ordered byte segments, so the to_rows primitives invert:
# row-slab gathers pull per-row word windows (rows are 8-byte aligned →
# word-granular, no byte funnel), the shared word decoder extracts fixed
# slots/validity/(offset,len) string slots, and each string column's chars
# are cut from the dense rows with a narrowing roll tree and re-packed at
# in-trace-cumsum destinations with the same group-accumulate + window
# combine as ``segmented_gather``.  ONE stacked scalar sync resolves the
# per-column char totals (the reference syncs on the same scanned totals,
# row_conversion.cu:2215); it is memoized on the batch arrays, so the
# analytics steady state is pure dispatch.


def _extract_row_windows(words: jnp.ndarray, offs: jnp.ndarray,
                         n: int, g: int, Bw: int, Mw: int) -> jnp.ndarray:
    """Per-row word windows [n, Mw] u32 from the flat row-word stream.

    One slab gather per GROUP of ``g`` rows (the group's rows span ≤ Bw
    words — caller sizes Bw from the host row offsets), then ``g`` fused
    word-shift takes pull each row's window out of its group slab.  Bytes
    beyond a row's true size are unspecified (callers mask by length).
    """
    ngroups = -(-n // g)
    T = words.shape[0]
    nb = max(-(-T // Bw), 1)
    w2 = jnp.pad(words, (0, nb * Bw - T)).reshape(nb, Bw)
    nxt = jnp.concatenate([w2[1:], jnp.zeros((1, Bw), jnp.uint32)])
    v2 = jnp.concatenate([w2, nxt], axis=1)           # [nb, 2Bw]
    offs_w = (offs // 4).astype(jnp.int32)
    gidx = jnp.minimum(jnp.arange(ngroups, dtype=jnp.int32) * g, n - 1)
    gstart = offs_w[gidx]
    blk = gstart // Bw
    slab = v2[jnp.clip(blk, 0, nb - 1)]               # [ngroups, 2Bw]
    outs = []
    for j in range(g):
        ridx = jnp.minimum(jnp.arange(ngroups, dtype=jnp.int32) * g + j,
                           n - 1)
        amt = offs_w[ridx] - blk * Bw
        outs.append(_take_words(slab, amt, Mw))
    out = jnp.stack(outs, axis=1).reshape(ngroups * g, Mw)
    return out[:n]


def _combine_to_stream(piece: jnp.ndarray, lens: jnp.ndarray,
                       dst_offs: jnp.ndarray, n: int, g: int, Bd: int,
                       P: int, nwin: int, total: int) -> jnp.ndarray:
    """Per-row byte pieces [n, Lw] u32 (payload starts at byte 0, ``lens``
    bytes live) → packed u8 [total] at byte destinations ``dst_offs``.
    Group-accumulate then window-combine — the segment-packing half of
    ``segmented_gather`` with the pieces already in hand."""
    ngroups = -(-n // g)
    pad = ngroups * g - n
    piece3 = jnp.pad(piece, ((0, pad), (0, 0))).reshape(
        ngroups, g, piece.shape[1])
    lens2 = jnp.pad(lens, (0, pad)).reshape(ngroups, g)
    dstp = jnp.pad(dst_offs[:-1], (0, pad)).reshape(ngroups, g)
    gi = jnp.minimum(jnp.arange(ngroups + 1, dtype=jnp.int32) * g, n)
    dstg = dst_offs[gi]
    live_base = jnp.arange(ngroups, dtype=jnp.int32) * g
    acc = jnp.zeros((ngroups, Bd), jnp.uint32)
    for j in range(g):
        live = (live_base + j) < n
        drel = dstp[:, j] - dstg[:-1]
        fun = _byte_funnel_right(piece3[:, j], drel % 4)
        placed = _place_words(fun, drel // 4, Bd)
        mask = _byte_mask(Bd, drel, drel + lens2[:, j])
        acc = acc | jnp.where(live[:, None], placed & mask, jnp.uint32(0))
    return _group_windows_combine(acc, dstg, ngroups, Bd, P, nwin, total)


@functools.partial(jax.jit, static_argnums=(0, 1))
def _from_rows_x_stats(layout: RowLayout, geom_a, words, offs):
    """Device geometry stats for the inverse engine: [nvar, 5] int64 rows
    of [char total, slot-violation count, Lmax, max group dst span, max
    groups per 512B window] — resolved with ONE stacked host sync.  XLA
    dead-code-eliminates the fixed-column decode this shares with the main
    program."""
    from .convert import _decode_row_words
    n, Mw, g, Bw = geom_a
    dense = _extract_row_windows(words, offs, n, g, Bw, Mw)
    _, _, slots = _decode_row_words(layout, lambda w: dense[:, w], n)
    fpv = layout.fixed_plus_validity
    row_sizes = (offs[1:] - offs[:-1]).astype(jnp.int64)
    ngroups = -(-n // g)
    gi = jnp.minimum(jnp.arange(ngroups + 1) * g, n)
    rows = []
    for s in slots:
        off = s[:, 0].astype(jnp.int64)
        ln = s[:, 1].astype(jnp.int64)
        viol = jnp.sum(((off < fpv) | (off + ln > row_sizes))
                       .astype(jnp.int64))
        dst = jnp.concatenate([jnp.zeros(1, jnp.int64), jnp.cumsum(ln)])
        dstg = dst[gi]
        dspan = jnp.max(dstg[1:] - dstg[:-1])
        upto = jnp.searchsorted(dstg[:-1], dstg[:-1] + 512, side="left")
        max_p = jnp.max(upto - jnp.arange(ngroups)) + 1
        rows.append(jnp.stack([dst[-1], viol, jnp.max(ln), dspan, max_p]))
    return jnp.stack(rows)


@functools.partial(jax.jit, static_argnums=(0, 1))
def _from_rows_x_jit(layout: RowLayout, geom, words, offs):
    """geom: (n, Mw, g, Bw, colgeo) with per-column (Lw, Bd, P, nwin,
    total) — all static.  Returns (datas — None at var columns, valid
    [n, ncols] bool, chars u8 tuple, out_offsets int32 [n+1] tuple), one
    dispatch, zero internal syncs."""
    from .convert import _decode_row_words
    n, Mw, g, Bw, colgeo = geom
    dense = _extract_row_windows(words, offs, n, g, Bw, Mw)
    datas, valid, slots = _decode_row_words(layout, lambda w: dense[:, w], n)
    chars = []
    out_offs = []
    for vi, s in enumerate(slots):
        Lw, Bd, P, nwin, total = colgeo[vi]
        lens = s[:, 1].astype(jnp.int32)
        dst = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(lens)])
        out_offs.append(dst)
        if total == 0:
            chars.append(jnp.zeros((0,), jnp.uint8))
            continue
        off_b = s[:, 0].astype(jnp.int32)
        w = _take_words(dense, off_b // 4, Lw + 1)
        piece = _roll_left_bytes(w, Lw, off_b % 4)
        chars.append(_combine_to_stream(piece, lens, dst, n, g, Bd, P,
                                        nwin, total))
    return datas, valid, tuple(chars), tuple(out_offs)


def _plan_from_rows_a(n: int, offs_np: np.ndarray, g: int = 8):
    """Row-extraction geometry (n, Mw, g, Bw) from the host row offsets
    alone — needed before the stats program can run.  None (with fallback
    accounting) outside the buckets.

    ``g`` (rows per slab-gather group) adapts to the geometry: short rows
    with tiny char spans need LARGE groups, or ~``512/span`` groups
    overlap each 512B output window and the combine's P-unrolled loop
    blows its cap (the mostly-empty-strings shape)."""
    row_sizes = offs_np[1:] - offs_np[:-1]
    Mw = _bucket(-(-int(row_sizes.max(initial=8)) // 4), 8)
    if Mw > 256:                                  # > 1KB rows
        return _reject("from_rows_row_width", Mw=Mw)
    idx = np.minimum(np.arange(0, n + g, g), n)
    span_w = int(((offs_np[idx[1:]] - offs_np[idx[:-1]]) // 4).max(initial=16))
    Bw = _bucket(max(span_w, 16), 16)
    if Bw * 4 > (1 << 20):
        return _reject("from_rows_slab", Bw=Bw)
    return (n, Mw, g, Bw)


def _plan_from_rows_cols(stats: np.ndarray, final: bool = True):
    """Per-column packing geometry from the device stats sync, or None."""
    colgeo = []
    for vi in range(stats.shape[0]):
        total, _viol, lmax, dspan, max_p = (int(x) for x in stats[vi])
        if total == 0:
            colgeo.append((0, 0, 0, 0, 0))
            continue
        # g-invariant caps reject immediately (retrying with a larger
        # group size cannot change the total or the entry length)
        if total >= (1 << 31):
            return _reject("from_rows_total", col=vi, total=total)
        Lw = _bucket(-(-max(lmax, 1) // 4) + 1, 4)
        if Lw > 512:
            return _reject("from_rows_col_caps", col=vi, Lw=Lw)
        combine = plan_combine(total, dspan, max_p, "from_rows_col_caps",
                               final)
        if combine is None:
            return None
        Bd, P, nwin = combine
        colgeo.append((Lw, Bd, P, nwin, total))
    return tuple(colgeo)


def batch_words(batch) -> jnp.ndarray:
    """The batch's JCUDF stream as u32 words (converts a u8 batch)."""
    from .convert import _bytes_to_words
    return (batch.data if batch.data.dtype == jnp.uint32
            else _bytes_to_words(batch.data))


def plan_from_rows(layout: RowLayout, batch, words: jnp.ndarray):
    """Full static geometry for the inverse engine, or None outside the
    buckets (with fallback accounting).

    Costs ONE stacked scalar sync (char totals + slot-bounds violations +
    packing spans, device-reduced) on a memo miss; memoized on the batch
    arrays, so the analytics steady state re-plans nothing.  Raises
    ``ValueError`` on corrupt embedded slots, same hardening as the host
    engine (rows may be shuffle-received).
    """
    from ..utils import hostcache, syncs
    n = batch.num_rows
    if n == 0:
        return None
    offs_np = hostcache.host_i64(batch.offsets)
    if int(offs_np[-1]) == 0 or int(offs_np[-1]) % 4:
        return None
    tag = f"xunpack_geom:{hash(layout)}"
    geom = syncs.memo_get(tag, [batch.data, batch.offsets])
    if geom is None:
        gs = (8, 32, 128)
        for trial, g in enumerate(gs):
            geom_a = _plan_from_rows_a(n, offs_np, g)
            if geom_a is None:
                break                      # Bw only grows with g: give up
            stats = np.asarray(_from_rows_x_stats(
                layout, geom_a, words, batch.offsets))   # one sync per try
            if trial == 0 and stats[:, 1].any():
                raise ValueError(
                    "corrupt row data: string slot outside its row")
            colgeo = _plan_from_rows_cols(stats, final=(g == gs[-1]))
            if colgeo is not None:
                geom = geom_a + (colgeo,)
                break
            if any(int(r[0]) >= (1 << 31)
                   or _bucket(-(-max(int(r[2]), 1) // 4) + 1, 4) > 512
                   for r in stats):
                break          # g-invariant rejection: retries cannot help
        # rejections memoize too (as "reject"): a repeat conversion of an
        # out-of-cap batch must not re-run the stats program + sync, nor
        # re-increment the fallback counters, on every call
        syncs.memo_put(tag, [batch.data, batch.offsets],
                       geom if geom is not None else "reject")
    return None if geom == "reject" else geom


def from_rows_var_x(layout: RowLayout, batch):
    """Packed JCUDF rows → (datas, valid, chars, out_offsets), one fused
    program; None (caller falls back) outside the geometry buckets."""
    words = batch_words(batch)
    geom = plan_from_rows(layout, batch, words)
    if geom is None:
        return None
    return _from_rows_x_jit(layout, geom, words, batch.offsets)
