from .layout import (  # noqa: F401
    JCUDF_ROW_ALIGNMENT, MAX_ROW_SIZE, MAX_BATCH_BYTES,
    RowLayout, compute_row_layout, build_batches,
)
from .convert import convert_to_rows, convert_from_rows, RowBatch  # noqa: F401
