"""ctypes binding to the native (C++) host JCUDF transcode engine.

Exposes the same ``to_rows_np`` / ``from_rows_np`` surface as the NumPy
oracle (``reference.py``) but backed by ``native/rowconv_engine.cpp`` — the
host-runtime analog of the reference's C++ orchestration layer
(``row_conversion.cu:1718-1890``), and an *independent* second oracle for the
device path (the reference differentially tests two engines against each
other, ``tests/row_conversion.cpp:49-58``; here the pair is C++ vs XLA).
"""

from __future__ import annotations

import ctypes

import numpy as np

from .. import native as native_lib
from .. import types as T
from ..column import Column, Table
from .layout import compute_row_layout


def available() -> bool:
    return native_lib.available()


def _require():
    lib = native_lib.load()
    if lib is None:
        raise RuntimeError("native rowconv engine not available (build failed)")
    return lib


def _ptr_array(arrays: list[np.ndarray | None]):
    """C array of void* from numpy arrays (None → nullptr)."""
    out = (ctypes.c_void_p * len(arrays))()
    for i, a in enumerate(arrays):
        out[i] = None if a is None else a.ctypes.data_as(ctypes.c_void_p).value
    return out


def _i32(arr) -> np.ndarray:
    return np.ascontiguousarray(arr, dtype=np.int32)


def layout_native(schema: list[T.DType]):
    """Row layout computed by the C++ engine (differential check vs layout.py)."""
    lib = _require()
    sizes = _i32([dt.itemsize for dt in schema])
    aligns = _i32([dt.row_alignment for dt in schema])
    n = len(schema)
    starts = np.zeros(n, dtype=np.int32)
    vo = ctypes.c_int32()
    fpv = ctypes.c_int32()
    rs = ctypes.c_int32()
    rc = lib.srjt_layout(
        sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        aligns.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n,
        starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.byref(vo), ctypes.byref(fpv), ctypes.byref(rs))
    if rc != 0:
        raise ValueError("srjt_layout rejected schema")
    return tuple(starts.tolist()), int(vo.value), int(fpv.value), int(rs.value)


def _host_cols(table: Table):
    """(data bytes, validity bytes-or-None, offsets-or-None) per column."""
    datas, valids, offs = [], [], []
    for col in table.columns:
        if col.dtype.is_variable_width:
            datas.append(np.ascontiguousarray(np.asarray(col.data),
                                              dtype=np.uint8))
            offs.append(_i32(np.asarray(col.offsets)))
        else:
            # Payloads are already in storage form (FLOAT64 = u32 [n,2] bit
            # pairs, DECIMAL128 = i64 lane pairs): a raw byte view is exact,
            # while a dtype= conversion would VALUE-cast f64 bit halves.
            datas.append(np.ascontiguousarray(
                np.asarray(col.data)).view(np.uint8).reshape(-1))
            offs.append(None)
        valids.append(None if col.validity is None else
                      np.ascontiguousarray(np.asarray(col.validity),
                                           dtype=np.uint8))
    return datas, valids, offs


def to_rows_np(table: Table) -> tuple[np.ndarray, np.ndarray]:
    """Table → (row_bytes uint8 [total], row_offsets int32 [n+1]) via C++."""
    lib = _require()
    layout = compute_row_layout(table.schema)
    n = table.num_rows
    starts = _i32(layout.column_starts)
    sizes = _i32(layout.column_sizes)
    datas, valids, offs = _host_cols(table)
    p_i32 = ctypes.POINTER(ctypes.c_int32)
    p_i64 = ctypes.POINTER(ctypes.c_int64)
    p_u8 = ctypes.POINTER(ctypes.c_uint8)

    if layout.fixed_width_only:
        out = np.empty(n * layout.fixed_row_size, dtype=np.uint8)
        lib.srjt_pack_fixed(
            _ptr_array(datas), _ptr_array(valids),
            starts.ctypes.data_as(p_i32), sizes.ctypes.data_as(p_i32),
            table.num_columns, n, layout.fixed_row_size,
            layout.validity_offset, out.ctypes.data_as(p_u8))
        row_offsets = (np.arange(n + 1, dtype=np.int64)
                       * layout.fixed_row_size)
        return out, row_offsets.astype(np.int32)

    var_offs = [offs[ci] for ci in layout.variable_column_indices]
    row_offsets = np.zeros(n + 1, dtype=np.int64)
    total = lib.srjt_var_row_offsets(
        _ptr_array(var_offs), len(var_offs), n, layout.fixed_plus_validity,
        row_offsets.ctypes.data_as(p_i64))
    is_var = np.asarray([dt.is_variable_width for dt in table.schema],
                        dtype=np.uint8)
    out = np.empty(int(total), dtype=np.uint8)
    lib.srjt_pack_var(
        _ptr_array(datas), _ptr_array(var_offs), _ptr_array(valids),
        starts.ctypes.data_as(p_i32), sizes.ctypes.data_as(p_i32),
        is_var.ctypes.data_as(p_u8), table.num_columns, n,
        row_offsets.ctypes.data_as(p_i64), layout.validity_offset,
        layout.fixed_plus_validity, out.ctypes.data_as(p_u8))
    return out, row_offsets.astype(np.int32)


def from_rows_np(row_bytes: np.ndarray, row_offsets: np.ndarray,
                 schema: list[T.DType]) -> Table:
    """(row_bytes, row_offsets) + schema → Table via the C++ engine."""
    lib = _require()
    schema = list(schema)
    layout = compute_row_layout(schema)
    row_bytes = np.asarray(row_bytes)
    if row_bytes.dtype != np.uint8:
        # word-form batches (RowBatch.data may be uint32 — the fixed and
        # xpack var engines keep the 8-byte-aligned stream as words): the
        # byte STREAM is wanted, so reinterpret, never value-cast
        row_bytes = np.ascontiguousarray(row_bytes).view(np.uint8)
    row_bytes = np.ascontiguousarray(row_bytes)
    row_offsets64 = np.ascontiguousarray(row_offsets, dtype=np.int64)
    n = row_offsets64.shape[0] - 1
    starts = _i32(layout.column_starts)
    sizes = _i32(layout.column_sizes)
    is_var = np.asarray([dt.is_variable_width for dt in schema],
                        dtype=np.uint8)
    p_i32 = ctypes.POINTER(ctypes.c_int32)
    p_i64 = ctypes.POINTER(ctypes.c_int64)
    p_u8 = ctypes.POINTER(ctypes.c_uint8)

    out_data: list[np.ndarray | None] = []
    out_valid = []
    out_str_offsets = []
    for dt in schema:
        if dt.is_variable_width:
            out_data.append(None)
            out_str_offsets.append(np.zeros(n + 1, dtype=np.int32))
        else:
            out_data.append(np.empty(n * dt.itemsize, dtype=np.uint8))
        out_valid.append(np.empty(n, dtype=np.uint8))

    if layout.fixed_width_only:
        lib.srjt_unpack_fixed(
            row_bytes.ctypes.data_as(p_u8), n, layout.fixed_row_size,
            starts.ctypes.data_as(p_i32), sizes.ctypes.data_as(p_i32),
            len(schema), layout.validity_offset,
            _ptr_array(out_data), _ptr_array(out_valid))
        chars = {}
    else:
        lib.srjt_unpack_var(
            row_bytes.ctypes.data_as(p_u8),
            row_offsets64.ctypes.data_as(p_i64), n,
            starts.ctypes.data_as(p_i32), sizes.ctypes.data_as(p_i32),
            is_var.ctypes.data_as(p_u8), len(schema), layout.validity_offset,
            _ptr_array(out_data),   # indexed by column; var slots stay null
            _ptr_array(out_str_offsets), _ptr_array(out_valid))
        chars = {}
        for vi, ci in enumerate(layout.variable_column_indices):
            offs = out_str_offsets[vi]
            buf = np.empty(int(offs[-1]), dtype=np.uint8)
            lib.srjt_gather_chars(
                row_bytes.ctypes.data_as(p_u8),
                row_offsets64.ctypes.data_as(p_i64), n,
                layout.column_starts[ci], offs.ctypes.data_as(p_i32),
                buf.ctypes.data_as(p_u8))
            chars[ci] = (buf, offs)

    import jax.numpy as jnp
    cols = []
    for ci, dt in enumerate(schema):
        valid = out_valid[ci].astype(bool)
        v = None if valid.all() else jnp.asarray(valid)
        if dt.is_variable_width:
            buf, offs = chars[ci]
            cols.append(Column(dt, jnp.asarray(buf), jnp.asarray(offs), v))
        else:
            arr = out_data[ci].view(dt.storage)
            cols.append(Column.from_numpy(arr, dt,
                                          None if v is None else valid))
    return Table(cols)
