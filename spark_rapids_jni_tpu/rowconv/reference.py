"""Host (NumPy) oracle for the JCUDF row format.

The reference validates its tiled CUDA path differentially against the legacy
``*_fixed_width_optimized`` path (``tests/row_conversion.cpp:49-58,575-584``).
Here the slow-but-obvious NumPy implementation plays the oracle role for the
JAX/Pallas device path: both must produce byte-identical JCUDF rows.

This module is deliberately scalar and readable — it is the specification.
"""

from __future__ import annotations

import numpy as np

from .. import types as T
from ..column import Column, Table
from .layout import (JCUDF_ROW_ALIGNMENT, RowLayout, compute_row_layout,
                     row_sizes_with_strings)


def _col_valid(col: Column) -> np.ndarray:
    if col.validity is None:
        return np.ones(col.num_rows, dtype=bool)
    return np.asarray(col.validity)


def to_rows_np(table: Table) -> tuple[np.ndarray, np.ndarray]:
    """Table → (row_bytes: uint8 [total], row_offsets: int32 [n+1])."""
    layout = compute_row_layout(table.schema)
    n = table.num_rows

    if layout.fixed_width_only:
        row_sizes = np.full(n, layout.fixed_row_size, dtype=np.int64)
    else:
        total_lens = np.zeros(n, dtype=np.int64)
        for ci in layout.variable_column_indices:
            offs = np.asarray(table[ci].offsets, dtype=np.int64)
            total_lens += offs[1:] - offs[:-1]
        row_sizes = row_sizes_with_strings(layout, total_lens)

    row_offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(row_sizes, out=row_offsets[1:])
    out = np.zeros(int(row_offsets[-1]), dtype=np.uint8)

    # Hoist every device payload to host ONCE: per-row ``np.asarray`` on a
    # device array is a full tunnel round-trip (~65-110 ms) on the remote
    # TPU backend — n*cols of them turned this oracle into hours.
    host_data = [np.asarray(c.data) for c in table.columns]
    host_offs = [None if c.offsets is None else np.asarray(c.offsets)
                 for c in table.columns]
    host_valid = [_col_valid(c) for c in table.columns]

    for r in range(n):
        base = int(row_offsets[r])
        # fixed-width slots + string (offset, len) slots
        var_cursor = layout.fixed_plus_validity
        for ci, col in enumerate(table.columns):
            start = base + layout.column_starts[ci]
            if col.dtype.is_variable_width:
                offs = host_offs[ci]
                length = int(offs[r + 1] - offs[r])
                slot = np.asarray([var_cursor, length], dtype=np.uint32)
                out[start:start + 8] = slot.view(np.uint8)
                chars = host_data[ci][offs[r]:offs[r + 1]]
                out[base + var_cursor:base + var_cursor + length] = chars
                var_cursor += length
            elif col.dtype.id.name == "DECIMAL128":
                lanes = np.ascontiguousarray(host_data[ci][r], dtype=np.int64)  # (lo, hi)
                out[start:start + 16] = lanes.view(np.uint8)
            elif col.dtype.id == T.TypeId.FLOAT64:
                # storage is the u32 [n, 2] bit pattern (column.py invariant)
                halves = np.ascontiguousarray(host_data[ci][r], dtype=np.uint32)
                out[start:start + 8] = halves.view(np.uint8)
            else:
                val = np.ascontiguousarray(host_data[ci][r:r + 1],
                                       dtype=col.dtype.storage)
                sz = layout.column_sizes[ci]
                out[start:start + sz] = val.view(np.uint8)
        # validity bytes, bit i of byte b = column b*8+i (RowConversion.java:56-58)
        vbase = base + layout.validity_offset
        for b in range(layout.validity_bytes):
            byte = 0
            for i in range(min(8, table.num_columns - b * 8)):
                if host_valid[b * 8 + i][r]:
                    byte |= 1 << i
            out[vbase + b] = byte

    return out, row_offsets.astype(np.int32)


def from_rows_np(row_bytes: np.ndarray, row_offsets: np.ndarray,
                 schema: list[T.DType]) -> Table:
    """(row_bytes, row_offsets) + schema → Table (inverse of to_rows_np)."""
    layout = compute_row_layout(schema)
    row_bytes = np.asarray(row_bytes, dtype=np.uint8)
    row_offsets = np.asarray(row_offsets, dtype=np.int64)
    n = row_offsets.shape[0] - 1

    datas = []
    validities = np.zeros((n, len(schema)), dtype=bool)
    for ci, dt in enumerate(schema):
        if dt.is_variable_width:
            datas.append([])  # list of per-row bytes
        elif dt.id == T.TypeId.DECIMAL128:
            datas.append(np.zeros((n, 2), dtype=np.int64))
        elif dt.id == T.TypeId.FLOAT64:
            datas.append(np.zeros((n, 2), dtype=np.uint32))  # bit pairs
        else:
            datas.append(np.zeros(n, dtype=dt.storage))

    for r in range(n):
        base = int(row_offsets[r])
        vbase = base + layout.validity_offset
        for ci, dt in enumerate(schema):
            validities[r, ci] = bool(
                (row_bytes[vbase + ci // 8] >> (ci % 8)) & 1)
            start = base + layout.column_starts[ci]
            if dt.is_variable_width:
                slot = row_bytes[start:start + 8].view(np.uint32)
                off, length = int(slot[0]), int(slot[1])
                datas[ci].append(row_bytes[base + off:base + off + length])
            elif dt.id == T.TypeId.DECIMAL128:
                datas[ci][r] = row_bytes[start:start + 16].view(np.int64)
            elif dt.id == T.TypeId.FLOAT64:
                datas[ci][r] = row_bytes[start:start + 8].view(np.uint32)
            else:
                sz = layout.column_sizes[ci]
                datas[ci][r] = row_bytes[start:start + sz].view(dt.storage)[0]

    cols = []
    for ci, dt in enumerate(schema):
        valid = validities[:, ci]
        v = None if valid.all() else valid
        if dt.is_variable_width:
            lengths = np.asarray([len(b) for b in datas[ci]], dtype=np.int32)
            offs = np.zeros(n + 1, dtype=np.int32)
            np.cumsum(lengths, out=offs[1:])
            chars = (np.concatenate(datas[ci]) if n and offs[-1] else
                     np.zeros(0, dtype=np.uint8))
            import jax.numpy as jnp
            cols.append(Column(dt, jnp.asarray(chars), jnp.asarray(offs),
                               None if v is None else jnp.asarray(v)))
        elif dt.id in (T.TypeId.DECIMAL128, T.TypeId.FLOAT64):
            import jax.numpy as jnp
            cols.append(Column(dt, jnp.asarray(datas[ci]),
                               validity=None if v is None
                               else jnp.asarray(v)))
        else:
            cols.append(Column.from_numpy(datas[ci], dt, v))
    return Table(cols)
