"""Concurrent query-serving runtime (default-off, ``SRJT_EXEC=1``).

The single-query engine (scan → ops → compiled replay) answers "how fast
is one query"; this subsystem answers the serving question — many
concurrent requests sharing ONE device, one HBM arena, and one set of
caches, the shape Spark's accelerated executors run in (SURVEY §1).
Parts, each its own module:

* :mod:`.scheduler` — bounded worker pool + priority queue, typed
  backpressure, deadlines (``SRJT_EXEC_WORKERS``,
  ``SRJT_EXEC_QUEUE_DEPTH``), and cross-request coalescing: same-plan
  requests batch into ONE program launch (``SRJT_EXEC_COALESCE_MS``,
  ``SRJT_EXEC_COALESCE_MAX``), bit-identical to serial execution.
* :mod:`.admission` — per-request HBM gate with graceful degradation
  (``SRJT_EXEC_INFLIGHT_BYTES``): defer under pressure, force the
  memory-lean sorted join engine when a request can never fit dense.
* :mod:`.plan_cache` — LRU of compiled (capture/replay) plans keyed on
  (query, input fingerprint) so the warm loop is one dispatch per
  request (``SRJT_EXEC_PLAN_CACHE_CAP``), with size-fingerprint plan
  sharing across refreshed same-shape data
  (``SRJT_EXEC_PLAN_SIZE_FP``) and vmapped batch execution.
* :mod:`.artifacts` — persistent AOT plan-artifact store
  (``SRJT_AOT_DIR``): capture tapes + warm-up manifest + the XLA
  executable cache on disk, so a fresh process rehydrates previously-
  seen plans with ZERO capture runs (zero-compile cold start).
* :mod:`.placement` — per-device replica state (``SRJT_EXEC_DEVICES``):
  each device its own executor lifecycle, admission ledger, and
  identity-keyed placement cache; the scheduler routes whole requests to
  replicas and fails them over across the quarantine → probation →
  recovery lifecycle (``SRJT_EXEC_RECOVERY``).
* :mod:`.prefetch` — double-buffered staging overlapping the next
  request's scan with current execution (``SRJT_EXEC_PREFETCH_DEPTH``).
* :mod:`.slo` — rolling-window SLO watchdog over resolved requests
  (``SRJT_SLO_P95_MS`` and friends); breaches alarm through the
  flight-recorder black box (``utils/flight.py``).

Correctness contract: concurrency, admission degradation, plan caching,
and prefetch NEVER change results — only latency.  The differential
tests (``tests/test_exec_runtime.py``) hold serving-runtime output
bit-identical to serial eager execution.
"""

from __future__ import annotations

import os

from ..utils import knobs

from . import artifacts
from .admission import AdmissionController, AdmissionGrant, request_bytes
from .artifacts import ArtifactStore, get_store
from .errors import (ExecDeadlineExceeded, ExecError, ExecQueueFull,
                     ExecShutdown)
from .placement import Replica, build_replicas, device_name
from .plan_cache import PlanCache
from .prefetch import Prefetcher
from .scheduler import QueryScheduler, QueryTicket
from .slo import SloWatchdog, thresholds_from_env

__all__ = [
    "AdmissionController", "AdmissionGrant", "ArtifactStore",
    "ExecDeadlineExceeded", "ExecError", "ExecQueueFull", "ExecShutdown",
    "PlanCache", "Prefetcher", "QueryScheduler", "QueryTicket", "Replica",
    "SloWatchdog", "artifacts", "build_replicas", "device_name",
    "enabled", "get_store", "request_bytes", "thresholds_from_env",
]


def enabled() -> bool:
    """True when the serving runtime is switched on (``SRJT_EXEC``)."""
    return knobs.get("SRJT_EXEC")
