"""Bounded-concurrency query scheduler: the serving runtime's front door.

Shape follows the Spark side of the reference stack: a bounded task queue
feeding a fixed worker pool over N data-parallel device replicas, with
admission control deciding what may touch device memory when (SURVEY §1's
many-tasks-one-GPU discipline, rebuilt at query granularity).  One
request's life:

    submit ──queue (priority heap, bounded depth)── dequeue (a replica's
      worker) → deadline check → prefetched tables (``exec/prefetch.py``)
      → per-device admission gate (``exec/admission.py``; defer/degrade)
      → placement (``exec/placement.py``: inputs replicated onto the
        replica's device, identity-cached)
      → plan cache (``exec/plan_cache.py``, device-keyed variant) under
        ``memory.budget.query_budget`` + the replica's
        ``faultinj.ResilientExecutor``
      → ticket resolves (result or typed error)

Everything device-touching happens on the WORKER thread that dequeued
the request: capture runs, jit traces, and budget scopes are all
thread-local-safe (``utils.syncs`` tape state and the query-budget stack
are thread-local by construction), so workers never share partial state.

**Multi-device placement** (``devices=N`` / ``SRJT_EXEC_DEVICES``,
default 1): each of the first N local devices gets a
:class:`~.placement.Replica` — its own ``ResilientExecutor`` (fault
lifecycle is per device), its own ``AdmissionController``
(``SRJT_EXEC_INFLIGHT_BYTES`` caps each device's in-flight bytes), and
worker affinity (worker *i* serves replica *i* mod N).  Placement is
least-loaded by construction: free workers pull from the shared priority
heap, so work flows to whichever device has capacity; a non-serving
replica's workers PARK and pull nothing.  Request inputs are replicated
to the target device through an identity-keyed placement cache (small
read-only dimension tables copy once, then every repeat request reuses
the same device-resident buffers — which also keeps plan-cache identity
fingerprints stable), and compiled plans key on a per-device variant
(``d<k>``), so replicas never share traced buffers.

Backpressure is typed, never silent: a full queue raises
:class:`~.errors.ExecQueueFull` at submit, a missed deadline resolves
the ticket with :class:`~.errors.ExecDeadlineExceeded`, shutdown drains
to :class:`~.errors.ExecShutdown`.

**Fault lifecycle — quarantine → probation → recovery → (ejection)**:
transient OOMs retry in place with jittered exponential backoff; a fatal
device fault quarantines THAT replica only.  The admission ladder
generalizes defer → degrade → **relocate**: the quarantined replica's
in-flight-failed and queued requests re-enqueue onto healthy replicas
(bounded by ``SRJT_EXEC_RELOCATE_MAX`` hops, re-admitted on the target
device's ledger, bit-identical results), counted by
``exec.failover.relocated`` with a ``failover`` incident snapshot.  A
background probe (``SRJT_EXEC_RECOVERY``, default on) retries the dead
replica with jittered exponential backoff (``SRJT_EXEC_PROBE_BASE_S`` /
``SRJT_EXEC_PROBE_MAX_S``): each probe moves the executor to probation
and runs a host-validated canary through the real dispatch path —
success re-admits the replica (``exec.failover.recovered`` + a
``recovery`` incident), ``SRJT_EXEC_EJECT_AFTER`` consecutive failures
permanently eject it (``exec.failover.ejected`` + an ``ejected``
incident).  Only when NO replica can ever serve again does submit fail
fast with ``DeviceQuarantined`` — the plugin's "replace the executor"
contract, replacement included.

**Cross-request coalescing** (``SRJT_EXEC_COALESCE_MS``, default 4 ms;
0 disables): workers don't just interleave same-plan requests, they
COALESCE them into one program launch — the paper's few-large-programs
discipline applied across requests instead of across rows.  A dequeued
compiled request first sweeps the queue for requests with the same
coalesce key (query name + qfn + size fingerprint of the tables), then
holds a short window — bounded by every gathered request's deadline —
for more arrivals, and the whole batch executes through
``PlanCache.run_batched``: identical buffers share one dispatch and its
result, distinct same-shape buffers stack onto the plan's vmapped
program.  Admission charges the batch ONCE (shared buffers dedup in the
estimate); a batch whose combined footprint would blow the in-flight cap
splits greedily into cap-sized sub-batches (``exec.batch.split``).
Results are bit-identical to serial execution by construction — the
batched paths are parity-checked, and every fallback is the ordinary
per-request dispatch.

**Request lifecycle tracing**: every request carries a request id
(``<name>#<seq>``, on the ticket as ``rid``) threaded through queue →
admission → coalesce window → batch membership → dispatch →
``block_until_ready`` → resolve.  Each stage records (a) a flight-recorder
event (``utils/flight.py`` — always on, so the black box has the full
lifecycle when an incident snapshot fires) and (b) an exact per-stage
latency attribution histogram: ``exec.stage.queue_ms`` (submit →
dequeue/gather), ``exec.stage.coalesce_ms`` (gather → batch launch),
``exec.stage.admission_ms``, ``exec.stage.dispatch_ms`` (launch → outputs
dispatched), ``exec.stage.ready_ms`` (dispatch → buffers materialized) —
summing to ``exec.e2e_ms`` up to scheduling gaps.  A coalesced launch
records one ``exec.batch.launch`` event linking every member rid, so the
shared program's cost is attributable to the requests that rode it.
Deadline breaches, quarantines, and request failures dump incident
snapshots; resolved outcomes feed the SLO watchdog (``exec/slo.py``).

Knobs: ``SRJT_EXEC_WORKERS`` (default 4; floored at the device count),
``SRJT_EXEC_QUEUE_DEPTH`` (default 32), ``SRJT_EXEC_COALESCE_MS``
(default 4), ``SRJT_EXEC_COALESCE_MAX`` (default 16),
``SRJT_EXEC_DEADLINE`` (default end-to-end timeout in seconds for
requests submitted without one), ``SRJT_EXEC_DEVICES`` (default 1),
``SRJT_EXEC_RECOVERY`` (default 1), ``SRJT_EXEC_PROBE_BASE_S`` /
``SRJT_EXEC_PROBE_MAX_S`` (default 0.05 / 2.0),
``SRJT_EXEC_EJECT_AFTER`` (default 3), ``SRJT_EXEC_RELOCATE_MAX``
(default: device count), ``SRJT_AOT_WARMUP`` (default 8; with
``SRJT_AOT_DIR`` set, a background thread pre-hydrates that many
top-cost artifacts from the AOT store at startup — ``exec/artifacts.py``),
plus the admission/prefetch/plan-cache knobs of the composed parts.
Histograms: ``exec.queue_wait_ms``, ``exec.admission_wait_ms``,
``exec.exec_ms``, ``exec.e2e_ms``, ``exec.batch.size``,
``exec.batch.coalesce_wait_ms``, and the ``exec.stage.*`` attribution
family above.
"""

from __future__ import annotations

import contextlib
import heapq
import itertools
import os
import random
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Optional

from ..analysis import sanitize
from ..faultinj import injector as finj
from ..faultinj.resilience import DeviceQuarantined
from ..memory import budget as mbudget
from ..models import compiled as C
from ..utils import flight, knobs, metrics, structured_log
from . import artifacts
from .admission import request_bytes
from .errors import (ExecDeadlineExceeded, ExecError, ExecQueueFull,
                     ExecShutdown)
from .placement import Replica, build_replicas
from .plan_cache import PlanCache
from .prefetch import Prefetcher
from .slo import SloWatchdog


class QueryTicket:
    """One submitted request's future: resolves to the query result or a
    typed error.  ``result()`` blocks; ``timings`` carries the request's
    per-stage attribution (queue/coalesce/admission/dispatch/ready
    seconds) once resolved; ``rid`` is the request id every flight-
    recorder event and log line for this request carries."""

    __slots__ = ("name", "rid", "_done", "_result", "_exc", "timings",
                 "degraded", "batch_rids", "device", "relocations")

    def __init__(self, name: str, rid: str = ""):
        self.name = name
        self.rid = rid
        self._done = threading.Event()
        self._result: Any = None
        self._exc: Optional[BaseException] = None
        self.timings: dict[str, float] = {}
        self.degraded = False
        self.batch_rids: Optional[list[str]] = None   # coalesced peers
        self.device: Optional[str] = None             # replica that served
        self.relocations = 0                          # failover hops

    def done(self) -> bool:
        return self._done.is_set()

    def exception(self) -> Optional[BaseException]:
        self._done.wait()
        return self._exc

    def result(self, timeout: Optional[float] = None) -> Any:
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.name!r} still pending")
        if self._exc is not None:
            raise self._exc
        return self._result

    def _resolve(self, result=None, exc: Optional[BaseException] = None):
        self._result = result
        self._exc = exc
        self._done.set()


class _Request:
    __slots__ = ("name", "qfn", "tables", "loader", "priority", "deadline",
                 "nbytes", "compiled", "ticket", "t_submit", "seq", "ckey",
                 "rid", "t_gather", "relocations", "relocatable")

    def __init__(self, **kw):
        self.t_gather = None        # set when pulled into a batch
        self.relocations = 0        # failover hops so far
        self.relocatable = True
        for k, v in kw.items():
            setattr(self, k, v)


class QueryScheduler:
    """Bounded worker pool pulling from a priority request queue.

    Lower ``priority`` values run first (0 = default; ties FIFO by
    submission order).  Context-manager use shuts the pool down on exit.
    """

    def __init__(self, workers: Optional[int] = None,
                 queue_depth: Optional[int] = None,
                 inflight_bytes=None,
                 plan_cache: Optional[PlanCache] = None,
                 prefetch: bool = True,
                 max_retries: int = 2,
                 coalesce_ms: Optional[float] = None,
                 max_batch: Optional[int] = None,
                 devices: Optional[int] = None,
                 recovery: Optional[bool] = None,
                 probe_base_s: Optional[float] = None,
                 probe_max_s: Optional[float] = None,
                 eject_after: Optional[int] = None,
                 relocate_max: Optional[int] = None):
        if workers is None:
            workers = knobs.get("SRJT_EXEC_WORKERS")
        if queue_depth is None:
            queue_depth = knobs.get("SRJT_EXEC_QUEUE_DEPTH")
        if coalesce_ms is None:
            coalesce_ms = knobs.get("SRJT_EXEC_COALESCE_MS")
        if max_batch is None:
            max_batch = knobs.get("SRJT_EXEC_COALESCE_MAX")
        if devices is None:
            devices = knobs.get("SRJT_EXEC_DEVICES")
        if recovery is None:
            recovery = knobs.get("SRJT_EXEC_RECOVERY")
        if probe_base_s is None:
            probe_base_s = knobs.get("SRJT_EXEC_PROBE_BASE_S")
        if probe_max_s is None:
            probe_max_s = knobs.get("SRJT_EXEC_PROBE_MAX_S")
        if eject_after is None:
            eject_after = knobs.get("SRJT_EXEC_EJECT_AFTER")
        self.n_devices = max(int(devices), 1)
        if relocate_max is None:
            relocate_max = knobs.get("SRJT_EXEC_RELOCATE_MAX")
            if relocate_max is None:
                relocate_max = self.n_devices
        # every device needs at least one affine worker to serve at all
        self.workers = max(int(workers), 1, self.n_devices)
        self.queue_depth = max(int(queue_depth), 1)
        self.coalesce_ms = max(float(coalesce_ms), 0.0)
        self.max_batch = max(int(max_batch), 1)
        self.recovery = bool(recovery)
        self.probe_base_s = max(float(probe_base_s), 1e-3)
        self.probe_max_s = max(float(probe_max_s), self.probe_base_s)
        self.eject_after = max(int(eject_after), 1)
        self.relocate_max = max(int(relocate_max), 1)
        self.default_timeout_s: Optional[float] = \
            knobs.get("SRJT_EXEC_DEADLINE")
        self.replicas: list[Replica] = build_replicas(
            self.n_devices, inflight_bytes=inflight_bytes,
            max_retries=max_retries)
        # back-compat aliases: single-device callers (and the ops surface)
        # see replica 0's gate and executor under the historical names
        self.admission = self.replicas[0].admission
        self.resilient = self.replicas[0].resilient
        self.plans = plan_cache if plan_cache is not None else PlanCache()
        # SQL qfn memo: plan fingerprint + schema → one stable callable,
        # so repeat submit_sql calls coalesce (ckey uses id(qfn)) and hit
        # the same plan-cache entry as an equivalent hand-built tree
        self._sql_qfns: "OrderedDict[tuple, Callable]" = OrderedDict()
        self._sql_lock = threading.Lock()
        self.prefetcher = Prefetcher() if prefetch else None
        self.slo = SloWatchdog()
        self._heap: list[tuple[int, int, _Request]] = []
        self._cv = threading.Condition(
            sanitize.tracked_lock("exec.scheduler.cv"))
        self._seq = itertools.count()
        self._closed = False
        self._probe_rng = random.Random(0x5e1f)
        self._probe_stop = threading.Event()
        # black-box probes: an incident snapshot from ANY subsystem
        # carries the live serving state (last scheduler wins the names)
        flight.register_probe("scheduler.queue_depth", self.pending)
        flight.register_probe("scheduler.inflight_bytes",
                              self.admission.inflight_bytes)
        flight.register_probe("scheduler.plan_cache", self.plans.stats)
        flight.register_probe("scheduler.slo", self.slo.status)
        flight.register_probe(
            "scheduler.replicas",
            lambda: [rep.snapshot() for rep in self.replicas])
        metrics.start_http_server()    # no-op without SRJT_METRICS_PORT
        self._threads = [
            threading.Thread(target=self._worker, name=f"srjt-exec-{i}",
                             args=(self.replicas[i % self.n_devices],),
                             daemon=True)
            for i in range(self.workers)]
        for t in self._threads:
            t.start()
        self._probe_thread: Optional[threading.Thread] = None
        if self.recovery:
            self._probe_thread = threading.Thread(
                target=self._recovery_loop, name="srjt-exec-probe",
                daemon=True)
            self._probe_thread.start()
        # AOT warm-up (exec/artifacts.py): pre-hydrate the costliest
        # persisted plan artifacts on a low-priority background thread so
        # the first requests' plan-cache lookups are memory hits.  Pure
        # disk reads — never touches the device, never blocks serving.
        self._warmup_thread: Optional[threading.Thread] = None
        warm_n = knobs.get("SRJT_AOT_WARMUP")
        if artifacts.enabled() and warm_n > 0:
            self._warmup_thread = threading.Thread(
                target=self._aot_warmup, args=(int(warm_n),),
                name="srjt-exec-warmup", daemon=True)
            self._warmup_thread.start()

    def pending(self) -> int:
        """Queued-but-undequeued request count (ops probe)."""
        with self._cv:
            return len(self._heap)

    def ops_state(self) -> dict:
        """One dict of live serving state for ``tools/ops_report.py``:
        queue depth, in-flight bytes, plan-cache stats, SLO status."""
        return {"queue_depth": self.pending(),
                "workers": self.workers,
                "devices": self.n_devices,
                "inflight_bytes": self.admission.inflight_bytes(),
                "inflight_cap": self.admission.cap,
                "quarantined": self.resilient.quarantined,
                "replicas": [rep.snapshot() for rep in self.replicas],
                "plan_cache": self.plans.stats(),
                "slo": self.slo.status()}

    # -- submission ----------------------------------------------------------

    def submit(self, name: str, qfn: Callable, tables=None, *,
               loader: Optional[Callable[[], Any]] = None,
               priority: int = 0,
               timeout_s: Optional[float] = None,
               nbytes: Optional[int] = None,
               compiled: bool = True,
               relocatable: bool = True) -> QueryTicket:
        """Enqueue ``qfn`` over ``tables`` (or over ``loader()``'s result,
        staged ahead of execution by the prefetcher).  Raises
        :class:`ExecQueueFull` at depth — the backpressure signal —
        and :class:`DeviceQuarantined` once the pool is quarantined.

        ``timeout_s`` bounds the request END TO END (queue + admission;
        a dispatched execution is never aborted mid-flight).  ``nbytes``
        overrides the admission estimate; ``compiled=False`` bypasses
        the plan cache (eager execution)."""
        if tables is None and loader is None:
            raise ValueError("submit needs tables or a loader")
        # fail fast only when no replica can EVER serve this request:
        # with recovery on, a quarantined (non-ejected) replica still
        # counts — the probe may re-admit it before the deadline
        if not any(r.recoverable() if self.recovery else r.serving()
                   for r in self.replicas):
            raise DeviceQuarantined("every replica is quarantined")
        if timeout_s is None:
            timeout_s = self.default_timeout_s
        seq = next(self._seq)
        rid = f"{name}#{seq}"
        ticket = QueryTicket(name, rid)
        now = time.monotonic()
        ckey = None
        if compiled and tables is not None and self.coalesce_ms > 0:
            # coalesce key: same query + same plan shape ⇒ same compiled
            # program ⇒ batchable into one launch.  Size (not identity)
            # fingerprint, so refreshed same-shape data coalesces too.
            try:
                sfp, _ = C.plan_key(tables, by_size=True)
                ckey = (name, id(qfn), sfp)
            except Exception:
                ckey = None
        req = _Request(
            name=name, qfn=qfn, tables=tables, loader=loader,
            priority=int(priority),
            deadline=(now + timeout_s) if timeout_s is not None else None,
            nbytes=nbytes, compiled=compiled, ticket=ticket,
            t_submit=now, seq=seq, ckey=ckey, rid=rid,
            relocatable=relocatable)
        with self._cv:
            if self._closed:
                raise ExecShutdown("scheduler is shut down")
            if len(self._heap) >= self.queue_depth:
                if metrics.recording():
                    metrics.count("exec.queue.rejected")
                flight.record("exec.reject", rid=rid,
                              depth=self.queue_depth)
                raise ExecQueueFull(self.queue_depth)
            heapq.heappush(self._heap, (req.priority, req.seq, req))
            qdepth = len(self._heap)
            # notify_all: idle workers AND workers holding a coalesce
            # window open both need the arrival signal
            self._cv.notify_all()
        flight.record("exec.submit", rid=rid, priority=int(priority),
                      qdepth=qdepth,
                      timeout_s=timeout_s if timeout_s is not None else 0)
        if metrics.recording():
            metrics.count("exec.submitted")
        if loader is not None and tables is None \
                and self.prefetcher is not None:
            # overlap the next request's scan with current executions.
            # (tables-AND-loader submits must not stage: the serve path
            # uses the tables directly and would orphan the slot)
            self.prefetcher.stage((req.name, req.seq), loader,
                                  deadline=req.deadline)
        return ticket

    def run(self, name: str, qfn: Callable, tables=None, **kw) -> Any:
        """Synchronous convenience: submit + block on the result."""
        return self.submit(name, qfn, tables, **kw).result()

    def submit_refresh(self, registry, view, *, priority: int = 0,
                       timeout_s: Optional[float] = None) -> QueryTicket:
        """Route a materialized-view refresh (``stream.ViewRegistry``)
        through the serving pipeline: same queue, priorities, deadlines,
        and quarantine as queries — but admission charges only the
        NOT-YET-CONSUMED delta bytes (the refresh's actual decode work),
        not the full table, so refreshes of a trickle of appends don't
        stall behind table-sized admission holds.  Runs eager
        (``compiled=False``): the refresh closure consults and mutates
        registry state, so it is never plan-cached or coalesced."""
        v = registry.resolve(view)
        est = registry.delta_bytes(v)

        def _refresh(_tables, _registry=registry, _view=v):
            return _registry.refresh(_view)

        if metrics.recording():
            metrics.count("stream.refresh.submitted")
        flight.record("stream.refresh.submit", view=v.name,
                      view_kind=v.kind, est_bytes=est)
        # relocatable=False: the refresh closure mutates registry state,
        # so a fault mid-refresh must surface, never silently re-run
        return self.submit(f"refresh:{v.name}", _refresh, tables={},
                           priority=priority, timeout_s=timeout_s,
                           nbytes=est, compiled=False, relocatable=False)

    def submit_predict(self, model, tables=None, *,
                       loader: Optional[Callable[[], Any]] = None,
                       priority: int = 0,
                       timeout_s: Optional[float] = None,
                       nbytes: Optional[int] = None) -> QueryTicket:
        """Serve an ML servable (``ml/serve.ServableModel`` or its
        registered name) through the ordinary pipeline: the predict query
        function runs ``plan → features → jitted predict`` as ONE compiled
        request, so admission, coalescing, capture/replay and device
        failover apply exactly as they do to queries.  The result is a
        one-column f32 prediction Table, bit-identical to
        ``ServableModel.predict_table`` (asserted in tests, including
        under injected device faults)."""
        from ..ml import serve as mlserve
        sv = mlserve.resolve(model)
        if metrics.recording():
            metrics.count("ml.predict.submitted")
        flight.record("ml.predict.submit", model=sv.name)
        return self.submit(f"predict:{sv.name}", sv.qfn, tables,
                           loader=loader, priority=priority,
                           timeout_s=timeout_s, nbytes=nbytes)

    def submit_sql(self, text: str, tables=None, *, schemas,
                   params: Optional[dict] = None,
                   loader: Optional[Callable[[], Any]] = None,
                   priority: int = 0,
                   timeout_s: Optional[float] = None,
                   nbytes: Optional[int] = None) -> QueryTicket:
        """Serve a SQL query (``sql/``) through the ordinary pipeline.

        The text is parsed, bound against ``schemas`` (table → column
        names), rule-optimized, and lowered to the same ``qfn`` shape a
        hand-built plan tree compiles to — then submitted under the
        plan's STRUCTURAL FINGERPRINT as the request name, so a SQL-born
        query and an equivalently-shaped hand-built tree share one
        plan-cache/AOT entry and coalesce into one launch.  Warm repeats
        are amortized-free: the SQL memo (``SRJT_SQL_CACHE``) skips
        parse+bind+optimize, the per-scheduler qfn memo returns the same
        callable, and the plan cache returns the compiled program.
        Malformed SQL raises :class:`~..sql.SqlError` (with a source
        caret) at submit time and records a ``sql_parse_error``
        incident — nothing is enqueued."""
        from .. import sql as sql_fe
        from ..plan import ir as plan_ir
        tree = sql_fe.sql_to_plan(text, schemas, params)  # SqlError here
        fp = plan_ir.fingerprint(tree)
        key = (fp, tuple(sorted((t, tuple(c)) for t, c in schemas.items())))
        with self._sql_lock:
            qfn = self._sql_qfns.get(key)
            if qfn is not None:
                self._sql_qfns.move_to_end(key)
        if qfn is None:
            from ..plan import lower as plan_lower
            qfn = plan_lower.compile_plan(tree, schemas)
            with self._sql_lock:
                qfn = self._sql_qfns.setdefault(key, qfn)
                while len(self._sql_qfns) > 256:
                    self._sql_qfns.popitem(last=False)
        if metrics.recording():
            metrics.count("sql.submitted")
        flight.record("sql.submit", fingerprint=fp, chars=len(text))
        return self.submit(fp, qfn, tables, loader=loader,
                           priority=priority, timeout_s=timeout_s,
                           nbytes=nbytes)

    # -- lifecycle -----------------------------------------------------------

    def shutdown(self, wait: bool = True) -> None:
        """Stop accepting work; queued-but-unstarted requests resolve
        with :class:`ExecShutdown`.  ``wait`` joins the workers (each
        finishes its in-flight request first)."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            pending = [r for _, _, r in self._heap]
            self._heap.clear()
            self._cv.notify_all()
        for req in pending:
            flight.record("exec.resolve", rid=req.rid, outcome="shutdown")
            req.ticket._resolve(exc=ExecShutdown(
                f"scheduler shut down before {req.name!r} started"))
        self._probe_stop.set()
        for rep in self.replicas:
            rep.admission.close()
        if self.prefetcher is not None:
            self.prefetcher.close()
        if wait:
            for t in self._threads:
                t.join(timeout=30)
            if self._probe_thread is not None:
                self._probe_thread.join(timeout=5)
            if self._warmup_thread is not None:
                self._warmup_thread.join(timeout=5)
        for probe in ("scheduler.queue_depth", "scheduler.inflight_bytes",
                      "scheduler.plan_cache", "scheduler.slo",
                      "scheduler.replicas"):
            flight.unregister_probe(probe)

    def __enter__(self) -> "QueryScheduler":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # -- worker loop ---------------------------------------------------------

    def _worker(self, rep: Replica) -> None:
        while True:
            req = None
            batch = None
            with self._cv:
                while not self._heap and not self._closed:
                    self._cv.wait()
                if not self._heap:
                    return              # closed and drained
                if not rep.serving():
                    # parked: a quarantined/probation/ejected replica's
                    # workers pull nothing — work flows to the healthy
                    # replicas' workers instead.  Timed wait so recovery
                    # (and close) edges are observed even without a
                    # notify.
                    self._cv.wait(timeout=0.05)
                else:
                    _, _, req = heapq.heappop(self._heap)
                    req.t_gather = time.monotonic()
                    batch = [req]
                    if req.ckey is not None:
                        self._gather_locked(req.ckey, batch)
            if req is None:
                continue
            flight.record("exec.dequeue", rid=req.rid, device=rep.name)
            if req.ckey is not None:
                self._coalesce_wait(req.ckey, batch)
            if len(batch) == 1:
                self._serve(req, rep)
            else:
                self._serve_batch(batch, rep)

    def _aot_warmup(self, top_n: int) -> None:
        """Background pre-hydration of the ``top_n`` costliest artifacts
        in the store's warm-up manifest (``SRJT_AOT_WARMUP``).  Advisory:
        any failure is swallowed — warm-up must never take serving down."""
        try:
            store = artifacts.get_store()
            if store is None:
                return
            n = store.preload(top_n)
            flight.record("exec.aot.warmup", loaded=n, top_n=top_n)
            if n and metrics.recording():
                metrics.count("exec.aot.warmed", n)
        except Exception:
            pass

    # -- fault lifecycle: relocation + recovery probe ------------------------

    def _variant(self, rep: Replica, degrade: bool) -> str:
        """Plan-cache variant key: ambient modes (degraded sort engine)
        composed with the serving device — replicas must never share a
        traced program's captured buffers."""
        parts = []
        if degrade:
            parts.append("sorted")
        if self.n_devices > 1:
            parts.append(f"d{rep.index}")
        return "@".join(parts)

    def _relocate(self, req: "_Request", tables, rep: Replica) -> bool:
        """Fail a dying replica's request OVER instead of failing it:
        re-enqueue (original submission order, so relocated requests stay
        ahead of newer arrivals) for a healthy — or recoverable — replica
        to pick up.  Re-admission naturally charges the target device's
        ledger.  Returns False when the request must fail instead."""
        if not req.relocatable or req.relocations >= self.relocate_max:
            return False
        if req.deadline is not None and time.monotonic() > req.deadline:
            return False
        targets = [r for r in self.replicas if r is not rep
                   and (r.serving() or (self.recovery and r.recoverable()))]
        if not targets and not (self.recovery and rep.recoverable()):
            return False
        req.relocations += 1
        req.ticket.relocations = req.relocations
        if tables is not None:
            # carry the already-loaded working set: the target replica
            # re-places it from the SOURCE buffers (identity cache), so
            # nothing reloads and results stay bit-identical
            req.tables = tables
            req.loader = None
        with self._cv:
            if self._closed:
                return False
            heapq.heappush(self._heap, (req.priority, req.seq, req))
            self._cv.notify_all()
        if metrics.recording():
            metrics.count("exec.failover.relocated")
        flight.incident("failover", request_id=req.rid, query=req.name,
                        device=rep.name, relocations=req.relocations,
                        targets=[r.name for r in targets])
        return True

    def _on_quarantine(self, rep: Replica) -> None:
        """A fatal fault just quarantined ``rep`` (or a submit hit the
        already-quarantined executor): arm its recovery probe, or — when
        nothing can ever recover — drain the queue so no request hangs
        behind a permanently dead pool."""
        if self.recovery and rep.recoverable():
            with self._cv:
                if rep.resilient.quarantined and not rep.probe_armed:
                    rep.probe_armed = True
                    rep.schedule_probe(self.probe_base_s, self.probe_max_s,
                                       self._probe_rng)
        self._drain_if_dead()

    def _drain_if_dead(self) -> None:
        """When NO replica can ever serve again, resolve every queued
        request with ``DeviceQuarantined`` — queued work must fail fast,
        not hang until its deadline behind permanently parked workers."""
        if any(r.recoverable() if self.recovery else r.serving()
               for r in self.replicas):
            return
        with self._cv:
            dead = [r for _, _, r in self._heap]
            self._heap.clear()
            self._cv.notify_all()
        for req in dead:
            if self.prefetcher is not None and req.loader is not None:
                self.prefetcher.discard((req.name, req.seq))
            self._resolve_fail(
                req, DeviceQuarantined("every replica is quarantined"),
                "queue", incident_kind="quarantine")

    def _recovery_loop(self) -> None:
        while not self._probe_stop.wait(0.02):
            now = time.monotonic()
            for rep in self.replicas:
                with self._cv:
                    due = (rep.probe_armed and not rep.ejected
                           and rep.resilient.quarantined
                           and now >= rep.next_probe_at)
                if due:
                    self._probe(rep)

    def _probe(self, rep: Replica) -> None:
        """One recovery attempt: probation + canary.  Success re-admits
        the replica; ``eject_after`` consecutive failures eject it."""
        rep.resilient.recover()
        flight.record("exec.failover.probe", device=rep.name,
                      streak=rep.fail_streak)
        try:
            rep.canary()
        except BaseException as e:
            # still faulting (or the canary miscompared — treat a wrong
            # answer exactly like a fault: the device cannot be trusted)
            rep.resilient.fail_probation()
            streak = rep.note_probe_failed()
            if metrics.recording():
                metrics.count("exec.failover.probe_failed")
            flight.record("exec.failover.probe_failed", device=rep.name,
                          streak=streak, error=type(e).__name__)
            if streak >= self.eject_after:
                rep.eject()
                with self._cv:
                    rep.probe_armed = False
                    self._cv.notify_all()
                self._drain_if_dead()
            else:
                with self._cv:
                    rep.schedule_probe(self.probe_base_s, self.probe_max_s,
                                       self._probe_rng)
            return
        rep.note_probe_ok()
        with self._cv:
            rep.probe_armed = False
            self._cv.notify_all()       # unpark this replica's workers
        if metrics.recording():
            metrics.count("exec.failover.recovered")
        flight.incident("recovery", device=rep.name, canary="ok",
                        recovery_count=rep.resilient.recovery_count)

    # -- coalescing ----------------------------------------------------------

    def _gather_locked(self, ckey, batch: list) -> None:
        """Pull every queued request with coalesce key ``ckey`` out of the
        heap into ``batch`` (up to ``max_batch``).  Caller holds the CV
        lock."""
        room = self.max_batch - len(batch)
        if room <= 0 or not self._heap:
            return
        keep, take = [], []
        for item in self._heap:
            if room > 0 and item[2].ckey == ckey:
                take.append(item[2])
                room -= 1
            else:
                keep.append(item)
        if take:
            self._heap[:] = keep
            heapq.heapify(self._heap)
            take.sort(key=lambda r: (r.priority, r.seq))
            now = time.monotonic()
            for r in take:
                r.t_gather = now
            batch.extend(take)

    def _coalesce_wait(self, ckey, batch: list) -> None:
        """Hold a short window for more same-plan arrivals.  The window is
        bounded by ``coalesce_ms`` AND by every gathered request's
        deadline — coalescing must never be the thing that kills a
        servable request."""
        t0 = time.monotonic()
        t_end = t0 + self.coalesce_ms / 1e3

        def _bound(reqs):
            nonlocal t_end
            for r in reqs:
                if r.deadline is not None:
                    t_end = min(t_end, r.deadline)
        _bound(batch)
        while len(batch) < self.max_batch and not self._closed:
            now = time.monotonic()
            if now >= t_end:
                break
            with self._cv:
                self._cv.wait(timeout=t_end - now)
                n0 = len(batch)
                self._gather_locked(ckey, batch)
            _bound(batch[n0:])
        if len(batch) > 1:
            flight.record("exec.coalesce", rid=batch[0].rid,
                          batch=[r.rid for r in batch],
                          wait_ms=round((time.monotonic() - t0) * 1e3, 3))
        if metrics.recording():
            metrics.observe("exec.batch.coalesce_wait_ms",
                            (time.monotonic() - t0) * 1e3)

    # -- resolution (tracing + SLO fan-in) -----------------------------------

    def _stage_obs(self, tk: "QueryTicket", stage: str,
                   seconds: float) -> None:
        """Record one stage's attribution: ticket timing + histogram."""
        tk.timings[f"{stage}_s"] = seconds
        if metrics.recording():
            metrics.observe(f"exec.stage.{stage}_ms", seconds * 1e3)

    def _resolve_ok(self, req: "_Request", result, *,
                    degraded: bool = False, deferred: bool = False,
                    relocated: bool = False) -> None:
        e2e = req.ticket.timings.get(
            "e2e_s", time.monotonic() - req.t_submit)
        flight.record("exec.resolve", rid=req.rid, outcome="ok",
                      e2e_ms=round(e2e * 1e3, 3), degraded=degraded,
                      device=req.ticket.device,
                      relocations=req.relocations)
        self.slo.observe(req.name, e2e * 1e3, outcome="ok",
                         degraded=degraded, deferred=deferred,
                         relocated=relocated, request_id=req.rid)
        req.ticket._resolve(result=result)

    def _resolve_fail(self, req: "_Request", exc: BaseException,
                      stage: str, *, outcome: str = "error",
                      incident_kind: Optional[str] = None,
                      batch: Optional[list] = None) -> None:
        """Resolve a request with a typed error, recording the outcome in
        the flight ring and (for incident-class failures) dumping the
        black-box snapshot that carries this rid's whole lifecycle."""
        e2e = time.monotonic() - req.t_submit
        req.ticket.timings.setdefault("e2e_s", e2e)
        flight.record("exec.resolve", rid=req.rid, outcome=outcome,
                      stage=stage, error=type(exc).__name__,
                      e2e_ms=round(e2e * 1e3, 3))
        if incident_kind is not None:
            flight.incident(incident_kind, request_id=req.rid,
                            batch=batch, stage=stage, error=repr(exc),
                            query=req.name, e2e_ms=round(e2e * 1e3, 3))
        self.slo.observe(req.name, e2e * 1e3, outcome=outcome,
                         request_id=req.rid)
        req.ticket._resolve(exc=exc)

    def _split_by_cap(self, reqs: list) -> list:
        """Greedily pack ``reqs`` into sub-batches whose combined unique
        input bytes fit the in-flight cap.  Shared buffers count once per
        sub-batch (the estimate is the batch's true working set, not
        N× it); a request that alone exceeds the cap stays a singleton
        and takes the ordinary degraded-admission path."""
        cap = self.admission.cap
        if cap is None:
            return [(reqs, 0)]
        subs: list = []
        cur, seen, total = [], set(), 0
        for r in reqs:
            est = r.nbytes if r.nbytes is not None \
                else request_bytes(r.tables, seen=seen)
            if cur and total + est > cap:
                subs.append((cur, total))
                cur, seen, total = [], set(), 0
                est = r.nbytes if r.nbytes is not None \
                    else request_bytes(r.tables, seen=seen)
            cur.append(r)
            total += est
        subs.append((cur, total))
        if len(subs) > 1 and metrics.recording():
            metrics.count("exec.batch.split", len(subs) - 1)
        return subs

    def _serve_batch(self, batch: list, rep: Replica) -> None:
        """Serve a coalesced same-plan batch: per-request deadline sweep,
        one admission charge per cap-fitting sub-batch, one program
        launch through ``PlanCache.run_batched``."""
        now = time.monotonic()
        rids = [r.rid for r in batch]
        live = []
        for r in batch:
            qw = now - r.t_submit
            r.ticket.timings["queue_wait_s"] = qw
            t_gather = r.t_gather if r.t_gather is not None else now
            self._stage_obs(r.ticket, "queue", t_gather - r.t_submit)
            self._stage_obs(r.ticket, "coalesce", now - t_gather)
            if metrics.recording():
                metrics.observe("exec.queue_wait_ms", qw * 1e3)
            if r.deadline is not None and now > r.deadline:
                if metrics.recording():
                    metrics.count("exec.deadline.queue")
                if self.prefetcher is not None and r.loader is not None:
                    self.prefetcher.discard((r.name, r.seq))
                self._resolve_fail(
                    r, ExecDeadlineExceeded(r.name, "queue", qw),
                    "queue", outcome="deadline", incident_kind="deadline",
                    batch=rids)
            else:
                live.append(r)
        for sub, est in self._split_by_cap(live):
            if len(sub) == 1:
                self._serve(sub[0], rep)
            elif sub:
                self._execute_batch(sub, est, rep)

    def _execute_batch(self, batch: list, est: int, rep: Replica) -> None:
        name = batch[0].name
        rids = [r.rid for r in batch]
        for r in batch:
            r.ticket.batch_rids = rids
        deadlines = [r.deadline for r in batch if r.deadline is not None]
        try:
            t_adm = time.monotonic()
            grant = rep.admission.admit(
                est, name=f"{name}[x{len(batch)}]",
                deadline=min(deadlines) if deadlines else None)
            adm_wait = time.monotonic() - t_adm
            for r in batch:
                r.ticket.timings["admission_wait_s"] = adm_wait
                self._stage_obs(r.ticket, "admission", adm_wait)
            if metrics.recording():
                metrics.observe("exec.admission_wait_ms", adm_wait * 1e3)
        except ExecDeadlineExceeded:
            # only the earliest deadline is binding: resolve the expired
            # members, serve the survivors individually (each re-admits
            # under its own deadline)
            now = time.monotonic()
            for r in batch:
                if r.deadline is not None and now > r.deadline:
                    if metrics.recording():
                        metrics.count("exec.admission.deadline")
                    self._resolve_fail(
                        r, ExecDeadlineExceeded(
                            r.name, "admission", now - r.t_submit),
                        "admission", outcome="deadline",
                        incident_kind="deadline", batch=rids)
                else:
                    self._serve(r, rep)
            return
        except ExecError as e:
            for r in batch:
                self._resolve_fail(r, e, "admission")
            return
        except BaseException as e:
            if metrics.recording():
                metrics.count("exec.failed")
            for r in batch:
                self._resolve_fail(r, e, "admission",
                                   incident_kind="request_failed",
                                   batch=rids)
            return
        if grant.degrade:
            # a multi-request sub-batch always fits the cap by
            # construction; defensive fallback only
            grant.release()
            for r in batch:
                self._serve(r, rep)
            return
        flight.record("exec.batch.launch", rid=batch[0].rid, batch=rids,
                      size=len(batch), est_bytes=est, device=rep.name)
        t0 = time.monotonic()
        retries0 = rep.resilient.retry_count
        variant = self._variant(rep, False)
        rep.note_active(len(batch))
        try:
            with grant, structured_log.bound(batch_rids=",".join(rids)):
                scope = mbudget.query_budget(
                    name, batched=len(batch),
                    device=rep.name if self.n_devices > 1 else None) \
                    if mbudget.enabled() \
                    else metrics.span(f"query:{name}", batched=len(batch))
                with scope, metrics.span("batch", size=len(batch),
                                         members=",".join(rids)), \
                        rep.scope(pin_device=self.n_devices > 1):
                    if self.n_devices > 1:
                        member_tables = [rep.place(r.tables)
                                         for r in batch]
                    else:
                        member_tables = [r.tables for r in batch]

                    def _run():
                        finj.get_injector().check("exec.dispatch")
                        return self.plans.run_batched(
                            name, batch[0].qfn, member_tables,
                            variant=variant)
                    outs = rep.resilient.submit(_run)
                    t_disp = time.monotonic()
                    try:
                        import jax
                        outs = jax.block_until_ready(outs)
                    except Exception:
                        pass
            t_done = time.monotonic()
            dt = t_done - t0
            flight.record("exec.batch.ready", rid=batch[0].rid,
                          batch=rids, exec_ms=round(dt * 1e3, 3))
            if metrics.recording():
                metrics.observe("exec.batch.size", len(batch))
                retried = rep.resilient.retry_count - retries0
                if retried:
                    metrics.count("exec.retries", retried)
            rep.note_completed(len(batch))
            for r, out in zip(batch, outs):
                r.ticket.timings["exec_s"] = dt
                r.ticket.timings["e2e_s"] = t_done - r.t_submit
                r.ticket.device = rep.name
                self._stage_obs(r.ticket, "dispatch", t_disp - t0)
                self._stage_obs(r.ticket, "ready", t_done - t_disp)
                if metrics.recording():
                    metrics.observe("exec.exec_ms", dt * 1e3)
                    metrics.observe("exec.e2e_ms",
                                    (t_done - r.t_submit) * 1e3)
                    metrics.count("exec.completed")
                    metrics.count("exec.device."
                                  + rep.name.replace(":", "")
                                  + ".completed")
                self._resolve_ok(r, out, deferred=grant.deferred,
                                 relocated=r.relocations > 0)
        except DeviceQuarantined as e:
            self._on_quarantine(rep)
            for r in batch:
                if self._relocate(r, r.tables, rep):
                    continue
                if metrics.recording():
                    metrics.count("exec.quarantined")
                self._resolve_fail(r, e, "execute",
                                   incident_kind="quarantine", batch=rids)
        except BaseException as e:
            if metrics.recording():
                metrics.count("exec.failed")
            for r in batch:
                self._resolve_fail(r, e, "execute",
                                   incident_kind="request_failed",
                                   batch=rids)
        finally:
            rep.note_active(-len(batch))

    def _serve(self, req: _Request, rep: Replica) -> None:
        tk = req.ticket
        t_dq = time.monotonic()
        queue_wait = t_dq - req.t_submit
        if "queue_wait_s" not in tk.timings:    # batch sweeps record it
            tk.timings["queue_wait_s"] = queue_wait
            if metrics.recording():
                metrics.observe("exec.queue_wait_ms", queue_wait * 1e3)
        if "queue_s" not in tk.timings:
            t_gather = req.t_gather if req.t_gather is not None else t_dq
            self._stage_obs(tk, "queue", t_gather - req.t_submit)
            if t_dq > t_gather:     # held through a coalesce window
                self._stage_obs(tk, "coalesce", t_dq - t_gather)
        if req.deadline is not None and t_dq > req.deadline:
            if metrics.recording():
                metrics.count("exec.deadline.queue")
            if self.prefetcher is not None and req.loader is not None:
                # a dead request's staged tables must not occupy a slot
                self.prefetcher.discard((req.name, req.seq))
            self._resolve_fail(
                req, ExecDeadlineExceeded(req.name, "queue", queue_wait),
                "queue", outcome="deadline", incident_kind="deadline",
                batch=tk.batch_rids)
            return
        try:
            tables = req.tables
            if tables is None:
                tables = self.prefetcher.take((req.name, req.seq),
                                              req.loader) \
                    if self.prefetcher is not None else req.loader()
            est = req.nbytes if req.nbytes is not None \
                else request_bytes(tables)
            t_adm = time.monotonic()
            grant = rep.admission.admit(est, name=req.rid or req.name,
                                        deadline=req.deadline)
            adm_wait = time.monotonic() - t_adm
            tk.timings["admission_wait_s"] = adm_wait
            self._stage_obs(tk, "admission", adm_wait)
            if metrics.recording():
                metrics.observe("exec.admission_wait_ms", adm_wait * 1e3)
        except ExecDeadlineExceeded as e:
            self._resolve_fail(req, e, "admission", outcome="deadline",
                               incident_kind="deadline",
                               batch=tk.batch_rids)
            return
        except ExecError as e:
            self._resolve_fail(req, e, "admission")
            return
        except BaseException as e:
            if metrics.recording():
                metrics.count("exec.failed")
            self._resolve_fail(req, e, "admission",
                               incident_kind="request_failed")
            return
        tk.degraded = grant.degrade
        t0 = time.monotonic()
        retries0 = rep.resilient.retry_count
        variant = self._variant(rep, grant.degrade)
        rep.note_active()
        try:
            with grant, structured_log.bound(request_id=req.rid):
                # degraded admission: the dense engine's O(key-range)
                # lookup table is exactly the allocation that does not
                # fit — route this request's joins to sort-probe (bit-
                # identical results, O(n) memory)
                if grant.degrade:
                    from ..ops import join_plan
                    ctx = join_plan.force_engine("sorted")
                else:
                    ctx = contextlib.nullcontext()
                # the full query_budget scope opens a query_span with
                # live-array HBM censuses — worth it only when the arena
                # is actually accounting; otherwise a plain span keeps
                # per-request overhead off the serving hot path
                scope = mbudget.query_budget(
                    req.name, queue_wait_ms=round(queue_wait * 1e3, 3),
                    degraded=grant.degrade,
                    device=rep.name if self.n_devices > 1 else None) \
                    if mbudget.enabled() \
                    else metrics.span(f"query:{req.name}",
                                      degraded=grant.degrade)
                with ctx, scope, \
                        rep.scope(pin_device=self.n_devices > 1):
                    # replicate the working set onto the serving device
                    # (identity-cached; single-device serves in place)
                    run_tables = rep.place(tables) \
                        if self.n_devices > 1 else tables

                    def _run():
                        finj.get_injector().check("exec.dispatch")
                        if req.compiled:
                            # degraded/per-device plans cache under their
                            # own variant: a dense-captured tape
                            # misaligns under the forced sorted engine,
                            # and replicas never share traced buffers
                            return self.plans.run(
                                req.name, req.qfn, run_tables,
                                variant=variant)
                        return req.qfn(run_tables)
                    result = rep.resilient.submit(_run)
                    t_disp = time.monotonic()
                    # a response is delivered, not dispatched: JAX
                    # dispatch is async, so resolve tickets only when
                    # the result buffers exist (also forces any lazy
                    # columns while the budget scope is still open)
                    try:
                        import jax
                        result = jax.block_until_ready(result)
                    except Exception:
                        pass
            t_done = time.monotonic()
            tk.timings["exec_s"] = t_done - t0
            tk.timings["e2e_s"] = t_done - req.t_submit
            tk.device = rep.name
            self._stage_obs(tk, "dispatch", t_disp - t0)
            self._stage_obs(tk, "ready", t_done - t_disp)
            if metrics.recording():
                metrics.observe("exec.exec_ms",
                                tk.timings["exec_s"] * 1e3)
                metrics.observe("exec.e2e_ms", tk.timings["e2e_s"] * 1e3)
                metrics.count("exec.completed")
                metrics.count("exec.device." + rep.name.replace(":", "")
                              + ".completed")
                retried = rep.resilient.retry_count - retries0
                if retried:
                    metrics.count("exec.retries", retried)
            rep.note_completed()
            self._resolve_ok(req, result, degraded=grant.degrade,
                             deferred=grant.deferred,
                             relocated=req.relocations > 0)
        except DeviceQuarantined as e:
            self._on_quarantine(rep)
            if not self._relocate(req, tables, rep):
                if metrics.recording():
                    metrics.count("exec.quarantined")
                self._resolve_fail(req, e, "execute",
                                   incident_kind="quarantine",
                                   batch=tk.batch_rids)
        except BaseException as e:
            if metrics.recording():
                metrics.count("exec.failed")
            self._resolve_fail(req, e, "execute",
                               incident_kind="request_failed",
                               batch=tk.batch_rids)
        finally:
            rep.note_active(-1)
