"""Persistent AOT plan-artifact store: compilation as a fleet asset.

Every fresh serving process pays the full capture → trace → XLA-compile
tax per plan before it can serve its first request — the compile ledger
(``models/compiled.py``) shows capture + trace dominating first-request
latency.  The reference design never pays it: its kernels are compiled
once into ``libcudf.so`` and *loaded*.  This module is the JAX-native
equivalent, split across the two halves of our compile cost:

* **the capture tape** — the recorded resolved-size vector that makes a
  plan shape-deterministic.  It is pure data (a tuple of ints), so it
  persists here as a versioned JSON artifact keyed on
  ``(plan fingerprint × input geometry × engine/AQE variant × jax +
  package version)``.  A fresh process *rehydrates* a
  :class:`~..models.compiled.CompiledQuery` from the persisted tape
  (``models/compiled.rehydrate_query``) without the eager capture run;
  the plan's first checked run validates the tape with the existing
  stacked-sync guard, and a mismatch degrades to a live capture — a
  stale artifact is never wrong, only slower.
* **the XLA executable** — JAX's persistent compilation cache already
  deserializes compiled programs from disk, keyed on HLO.  The store
  points ``jax_compilation_cache_dir`` at ``<SRJT_AOT_DIR>/xla`` (unless
  one is already configured — ``tests/conftest.py`` shares the same
  layout), so the re-trace of a rehydrated plan loads its executable
  instead of compiling it.

**Geometry bucketing** (``SRJT_AOT_GEOM_BUCKETS``, default on): artifact
keys bucket every input dimension up to the next power of two, so nearby
dataset sizes (yesterday's 1.9M-row refresh vs today's 2.1M) share one
artifact instead of fragmenting the store.  Different true geometry under
one bucket is safe by construction — the size fingerprint inside the key
still carries dtypes and ranks, and the first checked run's tape guard
rejects any artifact whose resolved sizes don't match the live data.
Inputs whose fingerprint contains process-local identity (opaque objects)
have no stable cross-process key and are never persisted.

**Warm-up manifest**: every write updates ``manifest.json`` with the
plan's compile-ledger cost (the capture wall the artifact saves a future
process).  ``ArtifactStore.preload`` reads the top-N costliest artifacts
into memory; ``exec/scheduler.py`` runs it on a background thread at
startup so cold-start p99 drops before traffic arrives.

All writes are atomic (``plan/stats.atomic_write_json`` — tmp +
``os.replace``); corrupted, stale, or version-skewed artifacts are
ignored with an ``aot_reject`` flight incident, never an error.

Knobs: ``SRJT_AOT_DIR`` (root; unset disables), ``SRJT_AOT_GEOM_BUCKETS``,
``SRJT_AOT_WARMUP``, ``SRJT_AOT_XLA_CACHE``.
Counters: ``aot.{hit,miss,write,reject,unstable_key,preloaded}``.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Optional

import jax

from ..analysis import sanitize
from ..models import compiled as C
from ..plan.stats import atomic_write_json
from ..utils import flight, knobs, metrics

#: bump on any incompatible change to the artifact document layout —
#: readers reject mismatched versions (forward AND backward skew)
STORE_VERSION = 1

# tags the size-fingerprint walker (models/compiled.plan_key) emits for
# structural entries; anything else in first position is a dtype string
# heading a (dtype, shape) leaf
_GEOM_TAGS = frozenset(("key", "table", "col", "lazy", "val", "obj", "seq"))


def enabled() -> bool:
    """True when AOT persistence is configured (``SRJT_AOT_DIR``)."""
    return knobs.get("SRJT_AOT_DIR") is not None


def env_fingerprint() -> str:
    """The version key artifacts are stamped with: store layout + jax +
    package versions.  Any skew rejects the artifact (the tape encodes
    op-library resolution-site order, which is only stable within one
    package version; XLA executables key on jax/XLA internals)."""
    try:
        from .. import __version__ as pkg
    except Exception:                           # pragma: no cover
        pkg = "unknown"
    return f"store{STORE_VERSION};jax{jax.__version__};pkg{pkg}"


def _bucket(n) -> int:
    """Round ``n`` up to the next power of two (0 and 1 stay exact)."""
    n = int(n)
    if n <= 1:
        return n
    return 1 << (n - 1).bit_length()


def geometry_key(tables, buckets: Optional[bool] = None) -> Optional[str]:
    """Stable digest of the inputs' geometry — dtypes, ranks, and
    (bucketed) dimensions, NO buffer identity — usable as a cross-process
    artifact key.  Returns ``None`` when the fingerprint contains
    process-local identity (an opaque object the walker cannot see
    inside): such keys are not stable across processes and must never
    reach the disk store."""
    if buckets is None:
        buckets = knobs.get("SRJT_AOT_GEOM_BUCKETS")
    sfp, _ = C.plan_key(tables, by_size=True)
    parts = []
    for e in sfp:
        if not isinstance(e, tuple) or not e:
            parts.append(repr(e))
            continue
        tag = e[0]
        if tag == "obj":
            if metrics.recording():
                metrics.count("aot.unstable_key")
            return None
        if tag == "lazy" and len(e) == 3:
            n = _bucket(e[2]) if buckets else int(e[2])
            parts.append(f"lazy:{e[1]}:{n}")
        elif (len(e) == 2 and isinstance(e[1], tuple)
                and tag not in _GEOM_TAGS):
            shape = tuple(_bucket(d) for d in e[1]) if buckets \
                else tuple(int(d) for d in e[1])
            parts.append(f"{tag}:{shape}")
        else:
            parts.append(repr(e))
    digest = hashlib.sha256("|".join(parts).encode()).hexdigest()[:20]
    return ("b" if buckets else "x") + digest


class ArtifactStore:
    """One on-disk artifact root: ``plans/<digest>.json`` documents, a
    ``manifest.json`` ranked by compile cost, and the XLA executable
    cache under ``xla/``.  Thread-safe; every disk write is atomic;
    every read failure degrades to a miss."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.plans_dir = os.path.join(self.root, "plans")
        self.manifest_path = os.path.join(self.root, "manifest.json")
        self._mu = sanitize.tracked_lock("exec.artifacts")
        self._mem: dict[str, dict] = {}     # digest → validated document
        self._env = env_fingerprint()

    # -- keys ---------------------------------------------------------------

    def _digest(self, plan: str, variant: str, geom: str) -> str:
        raw = f"{self._env}|{plan}|{variant}|{geom}"
        return hashlib.sha256(raw.encode()).hexdigest()[:24]

    def path_for(self, plan: str, variant: str, geom: str) -> str:
        return os.path.join(self.plans_dir,
                            self._digest(plan, variant, geom) + ".json")

    # -- read side ----------------------------------------------------------

    def _reject(self, digest: str, path: str, reason: str) -> None:
        with self._mu:
            self._mem.pop(digest, None)
        if metrics.recording():
            metrics.count("aot.reject")
        flight.incident("aot_reject", reason=reason,
                        artifact=os.path.basename(path))

    def _validate(self, doc, plan: str, variant: str,
                  geom: str) -> Optional[str]:
        """The reason ``doc`` cannot serve (plan, variant, geom), or
        ``None`` when it can."""
        if not isinstance(doc, dict):
            return "corrupt"
        if doc.get("version") != STORE_VERSION:
            return "version_skew"
        if doc.get("env") != self._env:
            return "env_skew"
        if (doc.get("plan") != plan or doc.get("variant") != variant
                or doc.get("geom") != geom):
            return "key_mismatch"
        tape = doc.get("tape")
        if not isinstance(tape, list) or any(
                not isinstance(v, int) or isinstance(v, bool)
                for v in tape):
            return "corrupt"
        return None

    def lookup(self, plan: str, variant: str,
               geom: str) -> Optional[tuple]:
        """The persisted capture tape for the key, or ``None`` (missing,
        corrupt, version-skewed, or mismatched — all misses, never
        errors)."""
        digest = self._digest(plan, variant, geom)
        path = os.path.join(self.plans_dir, digest + ".json")
        with self._mu:
            doc = self._mem.get(digest)
        if doc is None:
            try:
                with open(path, "r", encoding="utf-8") as f:
                    doc = json.load(f)
            except OSError:
                if metrics.recording():
                    metrics.count("aot.miss")
                return None
            except ValueError:
                self._reject(digest, path, "corrupt")
                return None
        reason = self._validate(doc, plan, variant, geom)
        if reason is not None:
            self._reject(digest, path, reason)
            return None
        with self._mu:
            self._mem[digest] = doc
        if metrics.recording():
            metrics.count("aot.hit")
        return tuple(doc["tape"])

    # -- write side ---------------------------------------------------------

    def put(self, plan: str, variant: str, geom: str, tape, *,
            name: str = "", cost_ms: float = 0.0) -> bool:
        """Persist one plan's capture tape (overwriting any previous
        artifact under the same key — the stale-rewrite path) and rank it
        in the warm-up manifest by ``cost_ms``, the capture wall a future
        process saves by rehydrating.  Best-effort: returns False on any
        OS failure."""
        digest = self._digest(plan, variant, geom)
        doc = {"version": STORE_VERSION, "env": self._env, "plan": plan,
               "variant": variant, "geom": geom, "name": name,
               "tape": [int(v) for v in tape],
               "created": round(time.time(), 3),
               "cost_ms": round(float(cost_ms), 3)}
        try:
            os.makedirs(self.plans_dir, exist_ok=True)
        except OSError:
            return False
        if not atomic_write_json(
                os.path.join(self.plans_dir, digest + ".json"), doc):
            return False
        with self._mu:
            self._mem[digest] = doc
        self._update_manifest(digest, {
            "plan": plan, "name": name, "variant": variant,
            "tape_len": len(doc["tape"]), "cost_ms": doc["cost_ms"],
            "created": doc["created"]})
        if metrics.recording():
            metrics.count("aot.write")
        return True

    def _read_manifest(self) -> dict:
        try:
            with open(self.manifest_path, "r", encoding="utf-8") as f:
                doc = json.load(f)
            if (isinstance(doc, dict) and doc.get("env") == self._env
                    and isinstance(doc.get("entries"), dict)):
                return doc
        except (OSError, ValueError):
            pass
        # missing/corrupt/skewed manifest: start fresh (it is derived
        # data — artifacts themselves still validate individually)
        return {"version": STORE_VERSION, "env": self._env, "entries": {}}

    def _update_manifest(self, digest: str, entry: dict) -> None:
        with self._mu:
            doc = self._read_manifest()
            doc["entries"][digest] = entry
            atomic_write_json(self.manifest_path, doc)

    def manifest_entries(self) -> list[tuple[str, dict]]:
        """(digest, entry) pairs ranked costliest-first — the warm-up
        order."""
        with self._mu:
            doc = self._read_manifest()
        return sorted(doc["entries"].items(),
                      key=lambda kv: -float(kv[1].get("cost_ms", 0)))

    # -- warm-up ------------------------------------------------------------

    def preload(self, top_n: int) -> int:
        """Pre-hydrate the ``top_n`` costliest manifest entries: read and
        validate their artifact documents into the in-memory index so
        the first request's lookup is a memory hit (its re-trace then
        pulls the XLA executable from the on-disk cache).  Returns the
        number resident."""
        n = 0
        for digest, entry in self.manifest_entries()[:max(int(top_n), 0)]:
            with self._mu:
                if digest in self._mem:
                    n += 1
                    continue
            path = os.path.join(self.plans_dir, digest + ".json")
            try:
                with open(path, "r", encoding="utf-8") as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                self._reject(digest, path, "corrupt")
                continue
            reason = self._validate(doc, doc.get("plan"),
                                    doc.get("variant"), doc.get("geom")) \
                if isinstance(doc, dict) else "corrupt"
            if reason is not None:
                self._reject(digest, path, reason)
                continue
            with self._mu:
                self._mem[digest] = doc
            n += 1
        if n and metrics.recording():
            metrics.count("aot.preloaded", n)
        return n

    def stats(self) -> dict:
        """Occupancy + lifetime counters (flight probe / ops surface)."""
        with self._mu:
            resident = len(self._mem)
        try:
            on_disk = sum(1 for f in os.listdir(self.plans_dir)
                          if f.endswith(".json"))
        except OSError:
            on_disk = 0
        out = {"root": self.root, "resident": resident,
               "on_disk": on_disk}
        for c in ("hit", "miss", "write", "reject", "unstable_key",
                  "preloaded"):
            out[c] = metrics.counter_value(f"aot.{c}")
        return out


# --- process-wide access -----------------------------------------------------

_stores: dict[str, ArtifactStore] = {}
_stores_mu = sanitize.tracked_lock("exec.artifacts.stores")


_xla_wired = False


def _init_xla_cache(root: str) -> None:
    """Point JAX's persistent compilation cache at ``<root>/xla`` so the
    XLA executables of rehydrated plans come from disk too.  Respects an
    already-configured cache dir (tests/conftest.py, operator config);
    ``SRJT_AOT_XLA_CACHE=0`` leaves the JAX config untouched entirely."""
    global _xla_wired
    if _xla_wired or not knobs.get("SRJT_AOT_XLA_CACHE"):
        return
    _xla_wired = True
    try:
        if getattr(jax.config, "jax_compilation_cache_dir", None):
            return
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(root, "xla"))
        # cold start is death by a thousand small compiles: cache them all
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        # jax latches the persistent cache ON or OFF at the first compile
        # of the process — any jit dispatched before this point (table
        # loading, warm-up probes) leaves it latched OFF and the config
        # update above silently ignored.  Drop the latched state so the
        # next compile re-initialises against the new directory.
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:                           # pragma: no cover
        pass                # cache wiring is advisory, never fatal


def get_store() -> Optional[ArtifactStore]:
    """The store for the current ``SRJT_AOT_DIR`` (None when unset).
    One instance per root path; first use of a root also wires the XLA
    persistent compilation cache under it."""
    root = knobs.get("SRJT_AOT_DIR")
    if not root:
        return None
    root = os.path.abspath(root)
    with _stores_mu:
        st = _stores.get(root)
        if st is None:
            st = _stores[root] = ArtifactStore(root)
    _init_xla_cache(root)
    return st
