"""Capacity-bound LRU of compiled query plans for the serving runtime.

``models/compiled.py`` gives one query ONE dispatch per execution — after
a capture run and a jit trace that cost ~100× the steady-state dispatch.
A server amortizes that only if compiled plans are REUSED across
requests: this cache keys plans on (query name, input-table fingerprint)
so the steady serving loop is a cache hit straight into raw dispatch.

Key discipline (`models.compiled.plan_key`): the fingerprint covers every
payload buffer's identity (id + dtype + shape), with weakrefs guarding
ids against recycling.  Identity keys make staleness STRUCTURAL — arrays
are immutable, so refreshed data is new buffers is a new key is a fresh
capture; a hit provably presents the very buffers the plan was captured
from.  The checked first run (one stacked sync validating the tape)
backstops the remaining edge, and a :class:`~..models.compiled.StaleTapeError`
there evicts and recompiles instead of surfacing to the client.

**Cross-request plan sharing** (``SRJT_EXEC_PLAN_SIZE_FP``, default on):
an identity miss consults a second index keyed on the SIZE fingerprint
(dtype + shape, no buffer ids).  A hit there reuses the warm
:class:`~..models.compiled.CompiledQuery` — no capture, no re-trace — for
the new buffers, provided the first replay runs the CHECKED path: the
tape's resolved sizes (join cardinalities, group counts) are data-
determined, so refreshed same-shape data must revalidate them
(``exec.plan_cache.revalidate``); a mismatch raises StaleTapeError and
recompiles, never returns wrong rows.  This is what makes cross-request
batching fire on real traffic, where buffers churn between refreshes but
shapes do not.

**Cross-request batching** (:meth:`PlanCache.run_batched`): K requests
that resolved to the same plan execute as ONE device program — requests
over identical buffers share a single dispatch and its result; requests
over distinct same-shape buffers stack on a leading batch axis through
:meth:`~..models.compiled.CompiledQuery.run_vmapped` (parity-probed
bit-exact, falling back to per-request dispatch when a plan cannot
batch).

Entries single-flight: two workers missing on the same key compile once
(the second waits on the first's build event — a duplicate capture would
waste the most expensive step the cache exists to amortize).

**Zero-compile cold start** (``SRJT_AOT_DIR``, ``exec/artifacts.py``): an
identity + size miss consults the persistent artifact store before
capturing.  A hit rehydrates the plan from the persisted capture tape —
no eager capture run — and the entry starts unverified, so the first run
is CHECKED and a stale artifact degrades to a live recapture whose
write-back overwrites it.  Fresh captures write back with their measured
compile cost, which ranks the warm-up manifest.

Knobs: ``SRJT_EXEC_PLAN_CACHE_CAP`` (entries, default 32),
``SRJT_EXEC_PLAN_SIZE_FP`` (size-fingerprint sharing, default on),
``SRJT_AOT_DIR`` (persistent artifact store; unset disables).
Counters: ``exec.plan_cache.{hit,miss,size_hit,aot_hit,revalidate,
evictions,stale,expired}``.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import OrderedDict
from typing import Callable, Optional

from ..analysis import sanitize
from ..models import compiled as C
from ..utils import knobs, metrics
from . import artifacts


class PlanCache:
    """LRU of :class:`~..models.compiled.CompiledQuery` keyed on
    (query name, table fingerprint), with a size-fingerprint side index
    for cross-request plan sharing."""

    def __init__(self, cap: Optional[int] = None,
                 share_by_size: Optional[bool] = None):
        if cap is None:
            cap = knobs.get("SRJT_EXEC_PLAN_CACHE_CAP")
        if share_by_size is None:
            share_by_size = knobs.get("SRJT_EXEC_PLAN_SIZE_FP")
        self.cap = max(int(cap), 1)
        self.share_by_size = bool(share_by_size)
        # RLock: weakref death callbacks can fire at GC points on a
        # thread already inside the cache
        self._mu = sanitize.tracked_rlock("exec.plan_cache")
        self._d: "OrderedDict[tuple, dict]" = OrderedDict()
        # size key → CompiledQuery, STRONG refs by design: the sharing
        # scenario is precisely "old buffers are gone, new same-shape
        # data arrived" — a weakref would die with the old entry and the
        # warm plan with it.  Bounded by the same cap, LRU.
        self._by_size: "OrderedDict[tuple, object]" = OrderedDict()
        self._building: dict[tuple, threading.Event] = {}

    def __len__(self) -> int:
        with self._mu:
            return len(self._d)

    def clear(self) -> None:
        with self._mu:
            self._d.clear()
            self._by_size.clear()

    def stats(self) -> dict:
        """Live occupancy + lifetime hit/miss counters (flight-recorder
        probe and ops-report surface)."""
        with self._mu:
            occ = {"entries": len(self._d),
                   "size_index": len(self._by_size),
                   "cap": self.cap,
                   "share_by_size": self.share_by_size,
                   "building": len(self._building)}
        for c in ("hit", "miss", "size_hit", "aot_hit", "revalidate",
                  "evictions", "stale", "expired"):
            occ[c] = metrics.counter_value(f"exec.plan_cache.{c}")
        return occ

    def _evict(self, key, counter: Optional[str]) -> None:
        with self._mu:
            entry = self._d.pop(key, None)
        # weakref death callbacks fire at GC points — including during
        # interpreter shutdown, after the metrics module's globals are
        # torn down.  The eviction itself already happened above; only
        # the counter is best-effort.
        try:
            if entry is not None and counter and metrics.recording():
                metrics.count(counter)
        except TypeError:
            pass

    def _lookup(self, key) -> Optional[dict]:
        """The live entry for ``key`` (LRU-touched), or None.  A dead
        weakref means a keyed buffer was collected and its id may be
        recycled — the entry is unusable and drops here."""
        with self._mu:
            entry = self._d.get(key)
            if entry is None:
                return None
            if any(r() is None for r in entry["refs"]):
                self._d.pop(key, None)
                if metrics.recording():
                    metrics.count("exec.plan_cache.expired")
                return None
            self._d.move_to_end(key)
            return entry

    def get_or_compile(self, name: str, qfn: Callable, tables,
                       variant: str = "", *,
                       _skip_aot: bool = False) -> dict:
        """The cache entry for (``name``, ``variant``, fingerprint of
        ``tables``), compiling on miss (single-flight per key).

        An identity miss first tries the size-fingerprint index: a warm
        plan for the same (name, variant, shape signature) is adopted
        without recapturing (``exec.plan_cache.size_hit``); the adopted
        entry starts unverified, so its first run takes the checked path
        and revalidates the tape against the new buffers.

        ``variant`` keys any ambient mode that changes the captured
        trace — e.g. the scheduler passes ``"sorted"`` for degraded-
        admission requests running under ``force_engine``: a tape
        recorded on the dense join path would misalign when replayed
        with the engine forced, so the two variants must never share an
        entry.  AQE qfns (``plan.adaptive.compile_adaptive_plan``) carry
        their mode in ``qfn.aqe_variant``; it is folded into the variant
        here so flipping ``SRJT_AQE`` between requests can never adopt a
        tape captured in the other mode."""
        aqe = getattr(qfn, "aqe_variant", "")
        if aqe:
            variant = f"{variant}+{aqe}" if variant else aqe
        fp, arrays = C.plan_key(tables)
        key = (name, variant, fp)
        skey = None
        if self.share_by_size:
            sfp, _ = C.plan_key(tables, by_size=True)
            skey = (name, variant, sfp)
        while True:
            with self._mu:
                entry = self._lookup(key)
                if entry is not None:
                    if metrics.recording():
                        metrics.count("exec.plan_cache.hit")
                        metrics.ledger_add(
                            getattr(qfn, "plan_fingerprint", None) or name,
                            cache_hits=1)
                    return entry
                ev = self._building.get(key)
                if ev is None:
                    ev = self._building[key] = threading.Event()
                    break
            ev.wait()
        try:
            shared = None
            if skey is not None:
                with self._mu:
                    shared = self._by_size.get(skey)
                    if shared is not None:
                        self._by_size.move_to_end(skey)
            if shared is not None:
                if metrics.recording():
                    metrics.count("exec.plan_cache.size_hit")
                    metrics.ledger_add(
                        getattr(qfn, "plan_fingerprint", None) or name,
                        cache_size_hits=1)
                plan, expected = shared, None
            else:
                lkey = getattr(qfn, "plan_fingerprint", None) or name
                if metrics.recording():
                    metrics.count("exec.plan_cache.miss")
                    metrics.ledger_add(lkey, cache_misses=1)
                store = artifacts.get_store()
                geom = artifacts.geometry_key(tables) \
                    if store is not None else None
                plan = expected = None
                if store is not None and geom is not None \
                        and not _skip_aot:
                    tape = store.lookup(lkey, variant, geom)
                    if tape is not None:
                        # zero-compile cold start: adopt the persisted
                        # tape without the eager capture run; the entry
                        # stays unverified so the first run is CHECKED
                        # and a stale artifact degrades to recapture
                        plan = C.rehydrate_query(qfn, tape)
                        if metrics.recording():
                            metrics.count("exec.plan_cache.aot_hit")
                if plan is None:
                    t0 = time.perf_counter()
                    plan = C.compile_query(qfn, tables)
                    cost_ms = (time.perf_counter() - t0) * 1e3
                    # the capture run's result IS this request's answer:
                    # hand it out once instead of re-executing, and drop
                    # the plan's own copy — cached entries must not pin
                    # result-sized memory
                    expected = plan.expected
                    plan.expected = None
                    if store is not None and geom is not None:
                        store.put(lkey, variant, geom, plan.tape,
                                  name=name, cost_ms=cost_ms)
            try:
                refs = tuple(
                    weakref.ref(a, lambda _, k=key: self._evict(
                        k, "exec.plan_cache.expired"))
                    for a in arrays)
            except TypeError:
                refs = ()
            entry = {"plan": plan, "refs": refs, "verified": False,
                     "expected": expected, "key": key, "skey": skey,
                     "shared": shared is not None}
            with self._mu:
                self._d[key] = entry
                self._d.move_to_end(key)
                while len(self._d) > self.cap:
                    old = next(iter(self._d))
                    if old == key:
                        break
                    self._d.pop(old)
                    if metrics.recording():
                        metrics.count("exec.plan_cache.evictions")
                if skey is not None:
                    self._by_size[skey] = plan
                    self._by_size.move_to_end(skey)
                    while len(self._by_size) > self.cap:
                        self._by_size.popitem(last=False)
            return entry
        finally:
            with self._mu:
                self._building.pop(key, None)
            ev.set()

    def invalidate(self, entry: dict) -> None:
        """Drop ``entry``; a stale plan also loses its size-index slot so
        the next same-shape request recompiles instead of re-adopting it."""
        self._evict(entry["key"], None)
        skey = entry.get("skey")
        if skey is not None:
            with self._mu:
                if self._by_size.get(skey) is entry["plan"]:
                    del self._by_size[skey]

    def _run_entry(self, entry: dict, name: str, qfn: Callable, tables,
                   variant: str):
        """Execute ``tables`` through an already-looked-up ``entry`` —
        the tail of :meth:`run` after the cache lookup, shared with
        :meth:`run_batched` so batch members don't double-count hits."""
        expected = entry.pop("expected", None)
        if expected is not None:
            return expected
        plan = entry["plan"]
        if entry["verified"]:
            return plan.run_unchecked(tables)
        try:
            if entry.get("shared") and metrics.recording():
                # first replay of a size-fingerprint-adopted plan over
                # fresh buffers: the checked run below IS the tape
                # revalidation
                metrics.count("exec.plan_cache.revalidate")
            out = plan.run(tables)
            entry["verified"] = True
            return out
        except C.StaleTapeError:
            if metrics.recording():
                metrics.count("exec.plan_cache.stale")
            self.invalidate(entry)
            # the retry must NOT re-adopt a persisted artifact: the tape
            # that just failed validation is exactly what the store holds
            # for this key, so a lookup here would loop stale→rehydrate→
            # stale forever.  Force a live capture — its write-back
            # overwrites the stale artifact for the next process.
            fresh = self.get_or_compile(name, qfn, tables, variant,
                                        _skip_aot=True)
            return self._run_entry(fresh, name, qfn, tables, variant)

    def run(self, name: str, qfn: Callable, tables, variant: str = ""):
        """Execute ``qfn(tables)`` through the cache.

        Miss → capture-compile; the capture run's own (eager) result is
        returned, so a cold request executes the query once, not twice.
        Size-fingerprint hit → adopt the warm plan, checked first run
        revalidates the tape.  First identity hit → checked run (one
        stacked sync validates the tape).  Later hits → raw single
        dispatch (``run_unchecked``).  A stale tape evicts + recompiles —
        clients never see :class:`StaleTapeError`."""
        entry = self.get_or_compile(name, qfn, tables, variant)
        return self._run_entry(entry, name, qfn, tables, variant)

    def run_batched(self, name: str, qfn: Callable, tables_list,
                    variant: str = "") -> list:
        """Execute K coalesced same-plan requests as few device programs
        as possible; returns the K results in request order.

        Requests over IDENTICAL buffers (one identity fingerprint) share
        a single execution and its result — the common serving case,
        where every request reads the same resident tables.  Requests
        over distinct same-shape buffers go through the plan's vmapped
        program (one stacked dispatch), provided their entries are warm
        and verified; cold or unverified members run individually (their
        first run is the capture / tape revalidation, which must stay
        serial) and batch from the next request on.  Every fallback is
        per-request dispatch through the same plans — results are always
        exactly what serial execution would have produced."""
        K = len(tables_list)
        results: list = [None] * K
        groups: "OrderedDict[tuple, list[int]]" = OrderedDict()
        for i, t in enumerate(tables_list):
            fp, _ = C.plan_key(t)
            groups.setdefault(fp, []).append(i)

        def _fan(idxs, res):
            for i in idxs:
                results[i] = res
            # duplicate-identity members logically hit the cache too:
            # keep hit+miss+size_hit == requests served
            if len(idxs) > 1 and metrics.recording():
                metrics.count("exec.plan_cache.hit", len(idxs) - 1)

        reps = list(groups.items())
        if len(reps) == 1:
            _fan(reps[0][1], self.run(name, qfn,
                                      tables_list[reps[0][1][0]], variant))
            return results
        batchable: "OrderedDict[int, list]" = OrderedDict()
        for fp, idxs in reps:
            t = tables_list[idxs[0]]
            entry = self.get_or_compile(name, qfn, t, variant)
            if entry.get("expected") is not None or not entry["verified"]:
                # cold capture or first-replay revalidation: serial path
                _fan(idxs, self._run_entry(entry, name, qfn, t, variant))
                continue
            batchable.setdefault(id(entry["plan"]), []).append((entry, idxs))
        for _, items in batchable.items():
            plan = items[0][0]["plan"]
            outs = None
            if len(items) >= 2:
                outs = plan.run_vmapped(
                    [tables_list[idxs[0]] for _, idxs in items])
            if outs is not None:
                for (entry, idxs), res in zip(items, outs):
                    _fan(idxs, res)
            else:
                for entry, idxs in items:
                    _fan(idxs, plan.run_unchecked(tables_list[idxs[0]]))
        return results
