"""Capacity-bound LRU of compiled query plans for the serving runtime.

``models/compiled.py`` gives one query ONE dispatch per execution — after
a capture run and a jit trace that cost ~100× the steady-state dispatch.
A server amortizes that only if compiled plans are REUSED across
requests: this cache keys plans on (query name, input-table fingerprint)
so the steady serving loop is a cache hit straight into raw dispatch.

Key discipline (`models.compiled.plan_key`): the fingerprint covers every
payload buffer's identity (id + dtype + shape), with weakrefs guarding
ids against recycling.  Identity keys make staleness STRUCTURAL — arrays
are immutable, so refreshed data is new buffers is a new key is a fresh
capture; a hit provably presents the very buffers the plan was captured
from.  The checked first run (one stacked sync validating the tape)
backstops the remaining edge, and a :class:`~..models.compiled.StaleTapeError`
there evicts and recompiles instead of surfacing to the client.

Entries single-flight: two workers missing on the same key compile once
(the second waits on the first's build event — a duplicate capture would
waste the most expensive step the cache exists to amortize).

Knobs: ``SRJT_EXEC_PLAN_CACHE_CAP`` (entries, default 32).  Counters:
``exec.plan_cache.{hit,miss,evictions,stale,expired}``.
"""

from __future__ import annotations

import os
import threading
import weakref
from collections import OrderedDict
from typing import Callable, Optional

from ..models import compiled as C
from ..utils import metrics


class PlanCache:
    """LRU of :class:`~..models.compiled.CompiledQuery` keyed on
    (query name, table fingerprint)."""

    def __init__(self, cap: Optional[int] = None):
        if cap is None:
            cap = int(os.environ.get("SRJT_EXEC_PLAN_CACHE_CAP", "32"))
        self.cap = max(int(cap), 1)
        # RLock: weakref death callbacks can fire at GC points on a
        # thread already inside the cache
        self._mu = threading.RLock()
        self._d: "OrderedDict[tuple, dict]" = OrderedDict()
        self._building: dict[tuple, threading.Event] = {}

    def __len__(self) -> int:
        with self._mu:
            return len(self._d)

    def clear(self) -> None:
        with self._mu:
            self._d.clear()

    def _evict(self, key, counter: Optional[str]) -> None:
        with self._mu:
            entry = self._d.pop(key, None)
        if entry is not None and counter and metrics.recording():
            metrics.count(counter)

    def _lookup(self, key) -> Optional[dict]:
        """The live entry for ``key`` (LRU-touched), or None.  A dead
        weakref means a keyed buffer was collected and its id may be
        recycled — the entry is unusable and drops here."""
        with self._mu:
            entry = self._d.get(key)
            if entry is None:
                return None
            if any(r() is None for r in entry["refs"]):
                self._d.pop(key, None)
                if metrics.recording():
                    metrics.count("exec.plan_cache.expired")
                return None
            self._d.move_to_end(key)
            return entry

    def get_or_compile(self, name: str, qfn: Callable, tables,
                       variant: str = "") -> dict:
        """The cache entry for (``name``, ``variant``, fingerprint of
        ``tables``), compiling on miss (single-flight per key).

        ``variant`` keys any ambient mode that changes the captured
        trace — e.g. the scheduler passes ``"sorted"`` for degraded-
        admission requests running under ``force_engine``: a tape
        recorded on the dense join path would misalign when replayed
        with the engine forced, so the two variants must never share an
        entry."""
        fp, arrays = C.plan_key(tables)
        key = (name, variant, fp)
        while True:
            with self._mu:
                entry = self._lookup(key)
                if entry is not None:
                    if metrics.recording():
                        metrics.count("exec.plan_cache.hit")
                    return entry
                ev = self._building.get(key)
                if ev is None:
                    ev = self._building[key] = threading.Event()
                    break
            ev.wait()
        try:
            if metrics.recording():
                metrics.count("exec.plan_cache.miss")
            plan = C.compile_query(qfn, tables)
            try:
                refs = tuple(
                    weakref.ref(a, lambda _, k=key: self._evict(
                        k, "exec.plan_cache.expired"))
                    for a in arrays)
            except TypeError:
                refs = ()
            # the capture run's result IS this request's answer: hand it
            # out once instead of re-executing, and drop the plan's own
            # copy — cached entries must not pin result-sized memory
            entry = {"plan": plan, "refs": refs, "verified": False,
                     "expected": plan.expected, "key": key}
            plan.expected = None
            with self._mu:
                self._d[key] = entry
                self._d.move_to_end(key)
                while len(self._d) > self.cap:
                    old = next(iter(self._d))
                    if old == key:
                        break
                    self._d.pop(old)
                    if metrics.recording():
                        metrics.count("exec.plan_cache.evictions")
            return entry
        finally:
            with self._mu:
                self._building.pop(key, None)
            ev.set()

    def invalidate(self, entry: dict) -> None:
        self._evict(entry["key"], None)

    def run(self, name: str, qfn: Callable, tables, variant: str = ""):
        """Execute ``qfn(tables)`` through the cache.

        Miss → capture-compile; the capture run's own (eager) result is
        returned, so a cold request executes the query once, not twice.
        First hit → checked run (one stacked sync validates the tape;
        the identity key makes a mismatch near-impossible, the check
        makes it impossible).  Later hits → raw single dispatch
        (``run_unchecked``).  A stale tape evicts + recompiles — clients
        never see :class:`StaleTapeError`."""
        entry = self.get_or_compile(name, qfn, tables, variant)
        expected = entry.pop("expected", None)
        if expected is not None:
            return expected
        plan = entry["plan"]
        if entry["verified"]:
            return plan.run_unchecked(tables)
        try:
            out = plan.run(tables)
            entry["verified"] = True
            return out
        except C.StaleTapeError:
            if metrics.recording():
                metrics.count("exec.plan_cache.stale")
            self.invalidate(entry)
            return self.run(name, qfn, tables, variant)
