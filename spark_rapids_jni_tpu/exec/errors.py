"""Typed failure surface of the exec runtime (the backpressure contract).

Spark's accelerated executor communicates overload through typed,
retryable conditions rather than stalls (task rejection → resubmission;
SURVEY §1's many-tasks-one-device shape).  The serving layer does the
same: a full queue and a missed deadline are DISTINCT, catchable types so
a closed-loop client can tell "back off and resend" from "this request is
dead" — and tests can assert the exact condition.
"""

from __future__ import annotations


class ExecError(RuntimeError):
    """Base of every exec-runtime failure."""


class ExecQueueFull(ExecError):
    """Backpressure: the bounded request queue is at depth; resubmit later.

    Raised by ``QueryScheduler.submit`` — never silently dropped work."""

    def __init__(self, depth: int):
        self.depth = depth
        super().__init__(
            f"exec queue full (depth {depth}) — backpressure: retry later "
            "or raise SRJT_EXEC_QUEUE_DEPTH")


class ExecDeadlineExceeded(ExecError):
    """The request's deadline passed while queued, deferred, or admitted."""

    def __init__(self, name: str, stage: str, waited_s: float):
        self.query = name
        self.stage = stage            # "queue" | "admission"
        self.waited_s = waited_s
        super().__init__(
            f"deadline exceeded for {name!r} in {stage} after "
            f"{waited_s:.3f}s")


class ExecShutdown(ExecError):
    """The scheduler is shut down; the request was not (or will not be)
    executed."""
