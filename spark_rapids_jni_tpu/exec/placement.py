"""Per-device replica state for the multi-device serving runtime.

One :class:`Replica` per local device: the device handle, its own
:class:`~..faultinj.resilience.ResilientExecutor` (fault lifecycle is per
device — one chip's fatal fault must not quarantine the pool), its own
:class:`~.admission.AdmissionController` (``SRJT_EXEC_INFLIGHT_BYTES`` is a
PER-DEVICE arena cap; re-admission after failover charges the *target*
device), and an identity-keyed placement cache.

Placement model (data-parallel replication, ROADMAP item #1): requests are
independent, so the scheduler routes whole requests to distinct devices and
replicates their inputs.  Dimension build-indices and lookup tables are
small, read-only, and identity-cached downstream (``utils.syncs`` memos key
on buffer identity), so replicating them per device is cheap — and the
placement cache here makes it *once* per (source buffer, device): repeat
requests over the same resident tables reuse the same device-resident
copies, which also keeps the plan cache's identity fingerprints stable per
device (same placed buffers ⇒ same fingerprint ⇒ warm plan).

The walker preserves column structure instead of flattening through the
pytree protocol: a ``DictColumn`` is placed as codes + dictionary (its
``tree_flatten`` would materialize the byte payload and defeat the dict
fast path), a ``LazyColumn`` is forced first (placement is an output
boundary for laziness — the copy must exist to move).

JAX mechanics this relies on (verified): ``jax.device_put(x, dev)`` is
bit-exact; computations follow committed inputs onto their device; mixing
devices in one jit raises — hence the walker places a request's ENTIRE
working set or nothing.
"""

from __future__ import annotations

import time

from ..analysis import sanitize
from ..faultinj import injector as finj
from ..faultinj.resilience import ResilientExecutor
from ..utils import flight, metrics
from ..utils.hostcache import WeakIdMemo
from .admission import AdmissionController


def device_name(device) -> str:
    """Canonical device label, e.g. ``"cpu:3"`` — the id the fault
    injector's ``device:`` rules and incident snapshots use."""
    return f"{device.platform}:{device.id}"


class Replica:
    """One device's serving state: executor lifecycle, admission ledger,
    placement cache, and recovery-probe bookkeeping."""

    def __init__(self, index: int, device, *, inflight_bytes=None,
                 max_retries: int = 2, cache_bytes=None):
        self.index = index
        self.device = device
        self.name = device_name(device)
        self.resilient = ResilientExecutor(max_retries=max_retries,
                                           device=self.name)
        self.admission = AdmissionController(inflight_bytes,
                                             device=self.name)
        # source-buffer id → device-resident copy; weak on the source so
        # a dropped table releases both copies
        self._placed = WeakIdMemo(cap_bytes=cache_bytes)
        self.ejected = False            # terminal: probes gave up
        self.fail_streak = 0            # consecutive failed probes
        self.next_probe_at = 0.0        # monotonic instant of next probe
        self.probe_armed = False        # recovery probe owns this replica
        self.active = 0                 # in-flight requests (gauge)
        self.completed = 0              # served ok (per-device QPS)
        # Multiple scheduler workers dispatch to the same replica (worker
        # affinity is i → replica i mod N, and failover relocates across
        # replicas), so the counters above are contended read-modify-
        # writes.  Mutate them only through the note_* methods below
        # (found by srjt_lint conc-mixed-guard; regression:
        # tests/test_analysis.py::test_replica_counters_thread_safe).
        self._mu = sanitize.tracked_lock(f"exec.placement.replica{index}")

    # -- counters (thread-safe: shared across scheduler workers) -------------

    def note_active(self, n: int = 1) -> None:
        """In-flight delta: +n at dispatch, -n when the batch resolves."""
        with self._mu:
            self.active += n

    def note_completed(self, n: int = 1) -> None:
        with self._mu:
            self.completed += n

    def note_probe_failed(self) -> int:
        """Bump and return the consecutive-failure streak."""
        with self._mu:
            self.fail_streak += 1
            return self.fail_streak

    def note_probe_ok(self) -> None:
        with self._mu:
            self.fail_streak = 0

    # -- state ---------------------------------------------------------------

    def state(self) -> str:
        if self.ejected:
            return "ejected"
        return self.resilient.state

    def serving(self) -> bool:
        """True when this replica may pull new work off the queue."""
        return not self.ejected and self.resilient.state == "healthy"

    def recoverable(self) -> bool:
        """True while the recovery probe still owns this replica's fate."""
        return not self.ejected

    def scope(self, pin_device: bool = True):
        """The dispatch context for this replica: JAX default device (so
        uncommitted intermediates land here) + the fault injector's device
        scope (so ``device:``-targeted chaos rules can hit it).

        ``pin_device=False`` sets only the injector scope.  The single-
        device scheduler path uses it: ``jax.default_device`` is part of
        jit's compilation-config context, so entering it around replay
        RETRACES plans that were warmed outside the context — a hot-path
        recompile per program for zero placement benefit when everything
        already lives on the only device.  Multi-device dispatch pins
        (warm-up and replay both run inside the same replica's scope, so
        each per-device plan variant compiles exactly once)."""
        import contextlib
        import jax

        @contextlib.contextmanager
        def _scope():
            with contextlib.ExitStack() as stack:
                if pin_device:
                    stack.enter_context(jax.default_device(self.device))
                stack.enter_context(finj.device_scope(self.name))
                yield
        return _scope()

    # -- placement -----------------------------------------------------------

    def _place_array(self, a):
        if a is None:
            return None
        hit = self._placed.get((a,))
        if hit is not None:
            if metrics.recording():
                metrics.count("exec.place.hit")
            return hit
        import jax
        out = jax.device_put(a, self.device)
        self._placed.put((a,), out)
        if metrics.recording():
            metrics.count("exec.place.copy")
            metrics.count("exec.place.bytes",
                          int(getattr(a, "nbytes", 0) or 0))
        return out

    def _place_column(self, c):
        from ..column import Column, DictColumn, force_column
        c = force_column(c)
        if isinstance(c, DictColumn):
            return DictColumn(self._place_array(c.codes),
                              self._place_column(c.dictionary),
                              self._place_array(c.validity),
                              sorted_dict=c.sorted_dict)
        children = None
        if c.children:
            children = [self._place_column(ch) for ch in c.children]
        return Column(c.dtype, self._place_array(c.data),
                      self._place_array(c.offsets),
                      self._place_array(c.validity), children)

    def place(self, tables):
        """``tables`` (dict / Table / Column / sequence nests) with every
        payload buffer resident on this replica's device.  Identity-cached
        per source buffer: repeat requests over resident tables reuse the
        same device copies (stable plan-cache fingerprints per device)."""
        from ..column import Column, Table
        if tables is None:
            return None
        if isinstance(tables, dict):
            return {k: self.place(v) for k, v in tables.items()}
        if isinstance(tables, Table):
            out = Table.__new__(Table)
            out.columns = [self._place_column(c) for c in tables.columns]
            return out
        if isinstance(tables, Column):
            return self._place_column(tables)
        if isinstance(tables, (list, tuple)):
            return type(tables)(self.place(v) for v in tables)
        return tables

    # -- recovery probe support ----------------------------------------------

    def canary(self) -> None:
        """One tiny device computation through the same dispatch path real
        requests take (fault site + device scope), host-validated.  Raises
        ``DeviceQuarantined`` when the device is still faulting."""
        import jax.numpy as jnp

        def _probe():
            finj.get_injector().check("exec.dispatch")
            n = 64
            got = int(jnp.sum(jnp.arange(n, dtype=jnp.int32)))
            if got != n * (n - 1) // 2:
                raise RuntimeError(
                    f"canary miscompare on {self.name}: {got}")
            return got

        with self.scope():
            self.resilient.submit(_probe)

    def schedule_probe(self, base_s: float, max_s: float, rng) -> None:
        """Set the next probe instant with jittered exponential backoff in
        the consecutive-failure streak."""
        back = min(base_s * (2.0 ** self.fail_streak), max_s)
        self.next_probe_at = time.monotonic() \
            + back * (1.0 + 0.5 * rng.random())

    def eject(self, reason: str = "probe failures") -> None:
        """Terminal ejection: the probe gave up on this device."""
        self.ejected = True
        flight.incident("ejected", device=self.name, reason=reason,
                        fail_streak=self.fail_streak,
                        fatal_count=self.resilient.fatal_count)
        if metrics.recording():
            metrics.count("exec.failover.ejected")

    def snapshot(self) -> dict:
        """Ops-surface view (flight probes, ``ops_state``)."""
        return {"device": self.name, "index": self.index,
                "state": self.state(), "active": self.active,
                "completed": self.completed,
                "fail_streak": self.fail_streak,
                "retries": self.resilient.retry_count,
                "fatal_faults": self.resilient.fatal_count,
                "recoveries": self.resilient.recovery_count,
                "inflight_bytes": self.admission.inflight_bytes()}


def build_replicas(n_devices: int, *, inflight_bytes=None,
                   max_retries: int = 2) -> list[Replica]:
    """Replicas over the first ``n_devices`` local devices (the shared
    handle source ``parallel.mesh.local_devices``, so replica index ↔
    mesh position agree)."""
    from ..parallel.mesh import local_devices
    devs = local_devices(n_devices)
    return [Replica(i, d, inflight_bytes=inflight_bytes,
                    max_retries=max_retries)
            for i, d in enumerate(devs)]
