"""Double-buffered host→device staging for queued requests.

The serving analog of the reference's copy/compute overlap (SURVEY §5.5:
spill and shuffle copies ride side streams so the compute stream never
waits on PCIe): while the workers execute the current requests, one
staging thread runs the NEXT requests' loaders (parquet fused scan +
upload — the dominant cold-request cost), so by the time a worker
dequeues a request its tables are already device-resident.

``depth`` (``SRJT_EXEC_PREFETCH_DEPTH``, default 2) bounds how many
staged working sets exist at once — double buffering, not an unbounded
table heap.  Staged tables are registered with ``memory.spill`` under
the ``exec.prefetch`` tag, so under HBM pressure the arena evicts the
*waiting* request's tables (they fault back implicitly on first touch)
before anything the running request holds.  On ``take`` the registration
is dropped: from that instant the table is a running plan's working set,
which the spill registry must never touch.

Slots are deadline-aware: ``stage`` records the request's deadline, and
a staged table whose request already exceeded it frees its slot instead
of occupying double-buffer capacity — swept when a new ``stage`` finds
the buffer full, and skipped by the staging loop before loading
(``exec.prefetch.deadline_evicted``).  A dead request's tables are the
one thing the double buffer must never hold while a live request loads
inline.

Counters: ``exec.prefetch.{hit,miss,rejected,deadline_evicted,discarded}``.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Optional

from ..analysis import sanitize
from ..utils import flight, knobs, metrics

# staging-tier counters attributed to each prefetch load (delta across
# the loader call — see ``_loop``)
_INGEST_COUNTERS = ("parquet.stage.slab_bytes", "parquet.stage.transfers",
                    "parquet.stage.overlap_ms")


def _register_staged(obj) -> None:
    """Spill-register every Table in a staged loader result (a Table, or
    a dict/sequence of them).  ``register_table`` is idempotent per table
    object, so loaders that already registered their scan outputs are
    not double-charged."""
    from ..column import Table
    from ..memory import spill as mspill
    if isinstance(obj, Table):
        mspill.register_table(obj, "exec.prefetch")
    elif isinstance(obj, dict):
        for v in obj.values():
            _register_staged(v)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            _register_staged(v)


def _unregister_staged(obj) -> None:
    from ..column import Table
    from ..memory import spill as mspill
    if isinstance(obj, Table):
        mspill.unregister(("exec.prefetch", id(obj)))
    elif isinstance(obj, dict):
        for v in obj.values():
            _unregister_staged(v)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            _unregister_staged(v)


class Prefetcher:
    """One staging thread + a bounded slot map of loaded working sets."""

    def __init__(self, depth: Optional[int] = None):
        if depth is None:
            depth = knobs.get("SRJT_EXEC_PREFETCH_DEPTH")
        self.depth = max(int(depth), 1)
        self._cv = threading.Condition(
            sanitize.tracked_lock("exec.prefetch.cv"))
        self._slots: "OrderedDict[object, dict]" = OrderedDict()
        self._todo: deque = deque()
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, name="srjt-exec-prefetch", daemon=True)
        self._thread.start()

    def stage(self, key, loader: Callable[[], object],
              deadline: Optional[float] = None) -> bool:
        """Queue ``loader`` to run on the staging thread.  False (with
        ``exec.prefetch.rejected``) when the buffer is full or the key is
        already staged — the caller's ``take`` then loads inline, which
        is the correct degraded behavior, not an error.

        ``deadline`` is the request's absolute ``time.monotonic()``
        deadline: once it passes, the slot is reclaimable — a full buffer
        evicts expired slots before rejecting the newcomer."""
        with self._cv:
            if self._closed or key in self._slots:
                return False
            if len(self._slots) >= self.depth:
                self._evict_expired_locked()
            if len(self._slots) >= self.depth:
                if metrics.recording():
                    metrics.count("exec.prefetch.rejected")
                return False
            self._slots[key] = {"state": "queued", "done": threading.Event(),
                                "result": None, "exc": None, "loader": loader,
                                "deadline": deadline}
            self._todo.append(key)
            self._cv.notify_all()
        return True

    def _evict_expired_locked(self) -> None:
        """Free every slot whose request's deadline has passed (called
        with the lock held).  Loading slots stay — the staging thread
        owns them mid-flight; they are swept once done."""
        now = time.monotonic()
        for k, slot in list(self._slots.items()):
            dl = slot.get("deadline")
            if dl is None or now <= dl or slot["state"] == "loading":
                continue
            self._slots.pop(k)
            if slot["done"].is_set() and slot["exc"] is None:
                _unregister_staged(slot["result"])
            if metrics.recording():
                metrics.count("exec.prefetch.deadline_evicted")

    def take(self, key, loader: Optional[Callable[[], object]] = None):
        """The staged working set for ``key`` (blocks until staged), or
        ``loader()`` run inline on a miss.  Either way the result leaves
        the prefetch spill registrations behind — it is about to become a
        running plan's working set."""
        with self._cv:
            slot = self._slots.pop(key, None)
            # a still-"queued" slot hasn't been picked up by the staging
            # thread; popping it here makes the staging loop skip it, and
            # THIS thread loads inline — waiting on it would deadlock if
            # the loop saw the pop first and never ran the loader
            queued = slot is not None and slot["state"] == "queued"
        if slot is None or queued:
            if metrics.recording():
                metrics.count("exec.prefetch.miss")
            if loader is None and queued:
                loader = slot["loader"]
            if loader is None:
                raise KeyError(f"prefetch: {key!r} not staged, no loader")
            return loader()
        slot["done"].wait()
        with self._cv:
            self._cv.notify_all()      # a slot freed; staging may resume
        if slot["exc"] is not None:
            raise slot["exc"]
        if metrics.recording():
            metrics.count("exec.prefetch.hit")
        result = slot["result"]
        _unregister_staged(result)
        return result

    def discard(self, key) -> None:
        """Drop a staged slot without delivering it (cancelled, expired,
        or failed-over request).  Every scheduler path that resolves a
        loader-backed request WITHOUT taking its tables must call this —
        an orphaned slot holds double-buffer capacity (and its spill
        registration) until deadline eviction, which a slot staged
        without a deadline never reaches."""
        with self._cv:
            slot = self._slots.pop(key, None)
            if slot is not None:
                self._cv.notify_all()   # a slot freed; staging may resume
        if slot is None:
            return
        if metrics.recording():
            metrics.count("exec.prefetch.discarded")
        if slot["done"].is_set() and slot["exc"] is None:
            _unregister_staged(slot["result"])

    def close(self) -> None:
        from .errors import ExecShutdown
        with self._cv:
            self._closed = True
            for slot in self._slots.values():
                if not slot["done"].is_set():
                    slot["exc"] = ExecShutdown("prefetcher closed")
                    slot["done"].set()
            self._slots.clear()
            self._todo.clear()
            self._cv.notify_all()
        self._thread.join(timeout=5)

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._todo and not self._closed:
                    self._cv.wait()
                if self._closed:
                    return
                key = self._todo.popleft()
                slot = self._slots.get(key)
                if slot is not None and slot.get("deadline") is not None \
                        and time.monotonic() > slot["deadline"]:
                    # the request is already dead: don't spend the
                    # staging thread (or a slot) loading for it
                    self._slots.pop(key, None)
                    if metrics.recording():
                        metrics.count("exec.prefetch.deadline_evicted")
                    slot = None
                if slot is not None:
                    slot["state"] = "loading"
            if slot is None:           # taken inline or discarded
                continue
            try:
                with metrics.span("exec.prefetch.load", key=str(key)):
                    rec = metrics.recording()
                    # ingest attribution: the byte-path staging counters a
                    # loader bumps (slab uploads, walk/stage overlap) are
                    # process-global — deltas across the load credit them
                    # to THIS prefetch, so ops_report can split prefetch
                    # latency into ingest vs everything else
                    base = {k: metrics.counter_value(k)
                            for k in _INGEST_COUNTERS} if rec else {}
                    slot["result"] = slot["loader"]()
                    if rec:
                        delta = {k.rsplit(".", 1)[-1]:
                                 metrics.counter_value(k) - base[k]
                                 for k in _INGEST_COUNTERS}
                        if any(delta.values()):
                            metrics.annotate(**delta)
                            flight.record("exec.prefetch.ingest",
                                          key=str(key), **delta)
                _register_staged(slot["result"])
            except Exception as e:     # delivered to the taker
                slot["exc"] = e
                # black-box breadcrumb: the taker re-raises this on its
                # own thread, where the staging context is already gone
                flight.record("exec.prefetch.fail", key=str(key),
                              error=type(e).__name__)
            finally:
                slot["loader"] = None
                slot["done"].set()
