"""Per-request HBM admission for the serving runtime.

``memory.budget`` answers "can THIS allocation proceed right now" at each
allocation site; a concurrent server needs the question answered once per
REQUEST, before any of its allocations exist — otherwise four admitted
queries can each pass their first small charge and then collectively blow
the arena mid-flight, where nothing can be unwound (an admitted query
must complete; ``budget`` docstring).  This controller is that front
gate: a global in-flight byte ledger (``SRJT_EXEC_INFLIGHT_BYTES``)
composed with the per-query ``budget.query_budget`` scope the worker
enters after admission.

Degradation ladder (pressure NEVER fails a request that can be served):

1. **fits** — estimate ≤ free in-flight room: admit on the requested
   path (dense join engine, full working set).
2. **defer** — estimate > free room but ≤ the cap: wait for in-flight
   requests to drain, then admit (``exec.admission.deferred``).  Queue
   wait is the currency overload is paid in — same as Spark's task
   queue — not errors.
3. **degrade** — estimate > the whole cap, so no amount of draining
   admits it as-is: admit EXCLUSIVELY (wait until in-flight is zero,
   hold the full cap) and tell the worker to route joins to the
   sort-probe engine via ``ops.join_plan.force_engine("sorted")``
   (``exec.admission.degraded``).  The sorted engine allocates O(n)
   lanes instead of a dense O(key-range) lookup table and returns
   bit-identical rows — the engines are differentially tested — so the
   degraded request is slower, never wrong.

Deadlines bound stage 2/3 waits: a request whose deadline passes while
deferred raises :class:`~.errors.ExecDeadlineExceeded` instead of
occupying the gate forever.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from ..analysis import sanitize
from ..memory import budget as mbudget
from ..utils import flight, knobs, metrics
from .errors import ExecDeadlineExceeded, ExecShutdown


def request_bytes(tables, seen: Optional[set] = None) -> int:
    """Byte estimate for one request's input working set: every payload
    array (device- or host-resident — a spilled input re-uploads on first
    touch, so it counts) across the request's tables.  Inputs dominate
    the footprint lower bound; op transients ride the per-site budget
    charges after admission.

    ``seen`` (a set of array ids) carries dedup state ACROSS calls: a
    coalesced batch charges each shared buffer once — N requests over the
    same resident tables cost the ledger one working set, not N — while
    distinct buffers accumulate, which is what the scheduler's greedy
    cap-split walks."""
    total = 0
    if seen is None:
        seen = set()

    def add(a):
        nonlocal total
        if a is not None and id(a) not in seen:
            seen.add(id(a))
            total += int(getattr(a, "nbytes", 0) or 0)

    def col(c):
        from ..column import LazyColumn
        if isinstance(c, LazyColumn):
            if c._col is None:
                return
            c = c._col
        add(c.data)
        add(getattr(c, "offsets", None))
        add(getattr(c, "validity", None))
        for ch in (c.children or ()):
            col(ch)

    def walk(obj):
        from ..column import Column, Table
        if isinstance(obj, dict):
            for v in obj.values():
                walk(v)
        elif isinstance(obj, Table):
            for c in obj.columns:
                col(c)
        elif isinstance(obj, Column):
            col(obj)
        elif isinstance(obj, (list, tuple)):
            for v in obj:
                walk(v)

    walk(tables)
    return total


class AdmissionGrant:
    """One admitted request's hold on the in-flight ledger (context
    manager; exiting releases the bytes and wakes deferred waiters).
    ``degrade`` tells the worker to run under ``force_engine("sorted")``;
    ``deferred`` reports whether the request waited behind the ladder's
    stage-2 gate (per-request attribution for the SLO watchdog)."""

    __slots__ = ("nbytes", "degrade", "deferred", "_ctl", "_released")

    def __init__(self, ctl: "AdmissionController", nbytes: int,
                 degrade: bool, deferred: bool = False):
        self._ctl = ctl
        self.nbytes = nbytes
        self.degrade = degrade
        self.deferred = deferred
        self._released = False

    def __enter__(self) -> "AdmissionGrant":
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._ctl._release(self.nbytes)


class AdmissionController:
    """The serving gate: bounded in-flight bytes with defer/degrade.

    ``device`` labels this gate's ledger with the replica device it
    fronts (multi-device scheduler: one controller per device, so
    ``SRJT_EXEC_INFLIGHT_BYTES`` is a per-device cap and failover
    re-admission charges the target device's ledger)."""

    def __init__(self, cap_bytes=None, device: Optional[str] = None):
        if cap_bytes is None:
            cap_bytes = knobs.get("SRJT_EXEC_INFLIGHT_BYTES")
        self.cap: Optional[int] = mbudget.parse_bytes(cap_bytes)
        self.device = device
        self._cv = threading.Condition(
            sanitize.tracked_lock("exec.admission.cv"))
        self._inflight = 0
        self._closed = False

    def inflight_bytes(self) -> int:
        with self._cv:
            return self._inflight

    def close(self) -> None:
        """Wake every deferred waiter with :class:`ExecShutdown`."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def admit(self, nbytes: int, *, name: str = "request",
              deadline: Optional[float] = None) -> AdmissionGrant:
        """Block until ``nbytes`` fits the in-flight cap (the ladder in
        the module docstring), then return the grant.  ``deadline`` is an
        absolute ``time.monotonic()`` instant bounding the wait."""
        n = max(int(nbytes), 0)
        cap = self.cap
        if cap is None:
            return AdmissionGrant(self, 0, False)
        degrade = n > cap
        hold = cap if degrade else n
        # degraded requests admit exclusively: they hold the entire cap,
        # so their true (over-cap) footprint never overlaps another
        # request's admitted bytes
        t0 = time.monotonic()
        deferred = False
        with self._cv:
            while self._inflight + hold > cap:
                if self._closed:
                    raise ExecShutdown("admission gate closed")
                if not deferred:
                    deferred = True
                    if metrics.recording():
                        metrics.count("exec.admission.deferred")
                    flight.record("exec.admission.defer", rid=name,
                                  nbytes=n, inflight=self._inflight,
                                  cap=cap, device=self.device)
                timeout = None
                if deadline is not None:
                    timeout = deadline - time.monotonic()
                    if timeout <= 0:
                        if metrics.recording():
                            metrics.count("exec.admission.deadline")
                        raise ExecDeadlineExceeded(
                            name, "admission", time.monotonic() - t0)
                self._cv.wait(timeout)
            self._inflight += hold
            if metrics.recording():
                metrics.gauge("exec.inflight_bytes", self._inflight)
                if self.device is not None:
                    metrics.gauge(
                        "exec.inflight_bytes."
                        + self.device.replace(":", ""), self._inflight)
        if degrade:
            if metrics.recording():
                metrics.count("exec.admission.degraded")
            flight.record("exec.admission.degrade", rid=name, nbytes=n,
                          cap=cap, device=self.device)
        return AdmissionGrant(self, hold, degrade, deferred)

    def _release(self, nbytes: int) -> None:
        with self._cv:
            self._inflight = max(self._inflight - int(nbytes), 0)
            if metrics.recording():
                metrics.gauge("exec.inflight_bytes", self._inflight)
                if self.device is not None:
                    metrics.gauge(
                        "exec.inflight_bytes."
                        + self.device.replace(":", ""), self._inflight)
            self._cv.notify_all()
