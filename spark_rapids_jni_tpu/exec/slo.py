"""SLO watchdog: rolling-window latency/error objectives per query class.

The serving runtime's histograms say what latency WAS over the process
lifetime; an operator needs to know when it stops being acceptable NOW.
This watchdog keeps a bounded rolling window of per-request outcomes per
query class (the request's query name) and evaluates configurable
objectives over it: p50/p95/p99 end-to-end latency and error / deadline /
defer / degrade rates.  A breach emits the full alarm chain —
``exec.slo.breach`` counter, a structured log line, and a flight-recorder
incident snapshot (``utils/flight.py``) — so the black box captures the
window in which the objective died, not a later steady state.

Thresholds come from env (unset objectives are simply not evaluated)::

  SRJT_SLO_P50_MS / SRJT_SLO_P95_MS / SRJT_SLO_P99_MS
      latency objectives in milliseconds
  SRJT_SLO_ERROR_RATE / SRJT_SLO_DEADLINE_RATE /
  SRJT_SLO_DEFER_RATE  / SRJT_SLO_DEGRADE_RATE /
  SRJT_SLO_RELOCATE_RATE
      rate objectives in [0, 1] (relocate = requests that failed over
      to another replica after a device fault — a rising relocate rate
      is the first operator signal of a flapping device)
  SRJT_SLO_WINDOW_S    rolling window (default 60 s)
  SRJT_SLO_MIN_N       minimum window population before any verdict
                       (default 8 — two requests must not page anyone)
  SRJT_SLO_COOLDOWN_S  per-(class, objective) re-alarm holdoff
                       (default 30 s — a sustained breach is one page,
                       not one per request)

The scheduler calls :meth:`SloWatchdog.observe` on every ticket
resolution; evaluation happens inline on the observing thread (a few
hundred floats sorted per breach check, bounded by the window cap) —
no extra thread to leak."""

from __future__ import annotations

import collections
import os
import time
from typing import Optional

from ..analysis import sanitize
from ..utils import flight, knobs, metrics, structured_log

_WINDOW_CAP = 4096          # per-class sample bound, whatever the window

_RATE_OUTCOMES = ("error", "deadline", "defer", "degrade")


def thresholds_from_env() -> dict:
    """The configured objectives; empty dict when none are set."""
    th = {
        "p50_ms": knobs.get("SRJT_SLO_P50_MS"),
        "p95_ms": knobs.get("SRJT_SLO_P95_MS"),
        "p99_ms": knobs.get("SRJT_SLO_P99_MS"),
        "error_rate": knobs.get("SRJT_SLO_ERROR_RATE"),
        "deadline_rate": knobs.get("SRJT_SLO_DEADLINE_RATE"),
        "defer_rate": knobs.get("SRJT_SLO_DEFER_RATE"),
        "degrade_rate": knobs.get("SRJT_SLO_DEGRADE_RATE"),
        "relocate_rate": knobs.get("SRJT_SLO_RELOCATE_RATE"),
    }
    return {k: v for k, v in th.items() if v is not None}


class SloWatchdog:
    """Rolling-window SLO evaluation over per-request outcomes."""

    def __init__(self, thresholds: Optional[dict] = None,
                 window_s: Optional[float] = None,
                 min_n: Optional[int] = None,
                 cooldown_s: Optional[float] = None):
        if thresholds is None:
            thresholds = thresholds_from_env()
        if window_s is None:
            window_s = knobs.get("SRJT_SLO_WINDOW_S")
        if min_n is None:
            min_n = knobs.get("SRJT_SLO_MIN_N")
        if cooldown_s is None:
            cooldown_s = knobs.get("SRJT_SLO_COOLDOWN_S")
        self.thresholds = dict(thresholds)
        self.window_s = max(float(window_s), 1e-3)
        self.min_n = max(int(min_n), 1)
        self.cooldown_s = max(float(cooldown_s), 0.0)
        self._mu = sanitize.tracked_lock("exec.slo")
        # class -> deque of (ts, e2e_ms, outcome, degraded, deferred)
        self._obs: dict[str, collections.deque] = {}
        self._last_alarm: dict[tuple, float] = {}
        self.breach_count = 0

    def enabled(self) -> bool:
        """A watchdog with no objectives records nothing and never fires."""
        return bool(self.thresholds)

    # -- recording -----------------------------------------------------------

    def observe(self, qclass: str, e2e_ms: float, outcome: str = "ok", *,
                degraded: bool = False, deferred: bool = False,
                relocated: bool = False,
                request_id: Optional[str] = None) -> list[dict]:
        """Record one resolved request and evaluate its class.  Returns
        the breaches fired (empty in the steady state).  ``outcome`` is
        ``ok`` | ``error`` | ``deadline``; ``relocated`` marks a request
        that failed over to another replica before resolving."""
        if not self.enabled():
            return []
        now = time.monotonic()
        with self._mu:
            dq = self._obs.get(qclass)
            if dq is None:
                dq = self._obs[qclass] = collections.deque(
                    maxlen=_WINDOW_CAP)
            dq.append((now, float(e2e_ms), outcome, bool(degraded),
                       bool(deferred), bool(relocated)))
        return self._evaluate(qclass, now, request_id=request_id)

    # -- evaluation ----------------------------------------------------------

    def _window(self, qclass: str, now: float) -> list[tuple]:
        with self._mu:
            dq = self._obs.get(qclass)
            if not dq:
                return []
            cutoff = now - self.window_s
            return [o for o in dq if o[0] >= cutoff]

    def class_status(self, qclass: str,
                     now: Optional[float] = None) -> Optional[dict]:
        """The rolling-window stats + per-objective verdicts for one
        class, or None below the minimum population."""
        now = time.monotonic() if now is None else now
        win = self._window(qclass, now)
        if len(win) < self.min_n:
            return None
        lat = sorted(o[1] for o in win)
        n = len(lat)

        def pct(q):
            rank = max(int(-(-n * q // 100)), 1)
            return lat[min(rank, n) - 1]

        stats = {
            "n": n,
            "window_s": self.window_s,
            "p50_ms": round(pct(50), 3),
            "p95_ms": round(pct(95), 3),
            "p99_ms": round(pct(99), 3),
            "error_rate": sum(o[2] == "error" for o in win) / n,
            "deadline_rate": sum(o[2] == "deadline" for o in win) / n,
            "defer_rate": sum(o[4] for o in win) / n,
            "degrade_rate": sum(o[3] for o in win) / n,
            "relocate_rate": sum(o[5] for o in win) / n,
        }
        verdicts = {}
        for obj, limit in self.thresholds.items():
            observed = stats.get(obj)
            if observed is not None:
                verdicts[obj] = {"limit": limit,
                                 "observed": round(observed, 6),
                                 "breached": observed > limit}
        stats["objectives"] = verdicts
        stats["breached"] = any(v["breached"] for v in verdicts.values())
        return stats

    def status(self) -> dict:
        """Every observed class's :meth:`class_status` (ops surface)."""
        with self._mu:
            classes = list(self._obs)
        now = time.monotonic()
        return {"thresholds": dict(self.thresholds),
                "window_s": self.window_s,
                "classes": {c: self.class_status(c, now) for c in classes}}

    def _evaluate(self, qclass: str, now: float, *,
                  request_id: Optional[str] = None) -> list[dict]:
        stats = self.class_status(qclass, now)
        if stats is None or not stats["breached"]:
            return []
        fired = []
        for obj, v in stats["objectives"].items():
            if not v["breached"]:
                continue
            key = (qclass, obj)
            with self._mu:
                last = self._last_alarm.get(key)
                if last is not None and now - last < self.cooldown_s:
                    continue
                self._last_alarm[key] = now
                self.breach_count += 1
            breach = {"class": qclass, "objective": obj,
                      "limit": v["limit"], "observed": v["observed"],
                      "window_n": stats["n"]}
            fired.append(breach)
            if metrics.enabled():
                metrics.count("exec.slo.breach", in_trace=True)
                metrics.count(f"exec.slo.breach.{obj}", in_trace=True)
            structured_log.event("slo.breach", **breach)
            flight.incident("slo_breach", request_id=request_id, **breach)
        return fired
