"""Single source of the base version string.

Imported by the package ``__init__`` (fallback when no build-provenance
stamp exists) and read by ``ci/build_info.py`` when stamping — keeping the
two from drifting.
"""

BASE_VERSION = "0.2.0.dev0"
