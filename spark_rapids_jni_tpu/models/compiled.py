"""Whole-query compilation: one jitted XLA program per (query, data) plan.

VERDICT r3 weak #2: the eager query path pays 4-10 device→host syncs and
~30 eager dispatches per query (~12 ms each through the tunnel), so SF1
queries lose to single-threaded pandas on wall clock.  The reference's
engine has no such overhead — each libcudf call is a handful of kernel
launches on-stream.

The TPU-native answer is to compile the WHOLE query to one XLA program.
Every dynamic size in the op library (join match totals, group counts,
string widths, compaction counts) already resolves through the
``utils.syncs.scalar`` funnel, so a query plan is *shape-deterministic
given its sizes*:

1. **capture** — run the query eagerly once, recording each resolved size
   in order (``syncs.capture``).  This is the reference's two-phase
   discipline (size pass → sized pass, ``row_conversion.cu:2205-2215``)
   lifted to the whole plan.
2. **replay** — re-trace the same Python under ``jax.jit`` with
   ``syncs.replay``: ``scalar()`` pops the recorded sizes instead of
   syncing, so the trace never touches the host and every shape is static.
   The result is ONE dispatch per query execution, syncs only for the
   final result pull.

The compiled program is exact for any table data with the same resolved
sizes; re-running against data whose sizes differ requires re-capture
(callers hold a :class:`CompiledQuery` per dataset — the analytics
steady-state, where plans are re-executed over refreshed same-shape data).

Join engine v2 (``ops/join_plan.py``) routes its planner decisions —
build-key min/max/uniqueness, which pick dense-lookup vs sort-probe —
through the same ``syncs.scalar`` funnel, so they are recorded on the tape
and re-checked by the staleness guard: a replay against data whose key
range flips the dense/sorted choice raises :class:`StaleTapeError` instead
of silently probing with the wrong engine.  (The identity-keyed build-index
memo is disabled under capture/replay so tapes stay aligned.)
"""

from __future__ import annotations

import itertools
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis import sanitize
from ..utils import flight, metrics, syncs

# Retrace-tripwire identity: a process-wide serial, not id(self) — ids
# recycle, and a dead plan's warmup must not mask a live plan's retrace.
_plan_serial = itertools.count()


class StaleTapeError(ValueError):
    """The compiled plan's recorded sizes no longer match the data."""


def _materialized(result):
    """Force every deferred column in the result WHILE the capture/replay
    context is still active: a ``LazyColumn`` forced later (e.g. by jit's
    own output flattening) would resolve its string-size syncs outside the
    context and desynchronize the tape."""
    return jax.tree_util.tree_map(lambda x: x, result)


class CompiledQuery:
    """A query function compiled to one jitted program over its tables.

    ``run(tables)`` executes the single-dispatch program, first verifying
    — with ONE stacked scalar sync — that the data's true resolved sizes
    still match the recorded tape (the reference re-measures its sizes on
    every call, ``row_conversion.cu:2205-2215``; a replay against
    refreshed data with different join cardinalities would otherwise
    return wrong rows without any error).  ``run_unchecked`` skips the
    check for the steady loop over data already verified once.  ``tape``
    is the recorded size vector (its length is the eager sync count).
    """

    def __init__(self, qfn: Callable, tables: Any, *,
                 tape: Optional[tuple] = None):
        qname = self.name = getattr(qfn, "__name__", "query")
        # the compile-cost ledger keys on the plan fingerprint when the
        # qfn carries one (plan/lower.compile_plan does), else the name —
        # the ROADMAP cold-start item's attribution unit
        self._ledger_key = getattr(qfn, "plan_fingerprint", None) or qname
        if tape is None:
            rec: list[int] = []
            metrics.count("compiled.capture")
            t0 = time.perf_counter()
            with metrics.span(f"compiled.capture:{qname}"):
                with syncs.capture(rec):
                    # eager capture run (and oracle)
                    self.expected = _materialized(qfn(tables))
            metrics.ledger_add(self._ledger_key, captures=1,
                               capture_ms=(time.perf_counter() - t0) * 1e3)
            self.tape = tuple(rec)
        else:
            # rehydration (exec/artifacts.py): adopt a persisted capture
            # tape WITHOUT the eager capture run.  There is no oracle
            # result and the tape is unverified — the caller's first
            # execution must be the CHECKED path, whose stacked-sync
            # guard validates the tape against the live data (a mismatch
            # raises StaleTapeError and falls back to live capture).
            metrics.count("compiled.rehydrate")
            self.expected = None
            self.tape = tuple(int(v) for v in tape)
            metrics.ledger_add(self._ledger_key, rehydrates=1)
        self.rehydrated = tape is not None
        metrics.observe("compiled.tape_len", len(self.tape))
        self._trace_key = f"{qname}#{next(_plan_serial)}"
        self._dispatched = False

        def _traced(tbls):
            # counted at trace time on purpose: each execution of this
            # body IS one (re)trace → XLA recompile of the query program
            metrics.count("compiled.recompile", in_trace=True)
            sanitize.note_trace(self._trace_key)
            tt0 = time.perf_counter()
            with syncs.replay(list(self.tape)):
                out = _materialized(qfn(tbls))
            # traces-1 == recompiles of this plan; trace_ms is the Python
            # re-trace cost (XLA compile itself lands in the surrounding
            # first_dispatch_ms)
            metrics.ledger_add(self._ledger_key, traces=1, in_trace=True,
                               trace_ms=(time.perf_counter() - tt0) * 1e3)
            return out
        _traced.__name__ = f"compiled_{qname}"
        self._traced_fn = _traced
        self._prog = jax.jit(_traced)

        # batched (vmapped) variant, built lazily on first cross-request
        # batch (exec/plan_cache.py run_batched): None = not yet probed,
        # True = parity-verified, False = rejected (trace failure or a
        # parity mismatch) — once False the plan never batches again
        self._vlock = sanitize.tracked_lock("models.compiled.vmap")
        self._vprog = None
        self._vtreedef = None
        self._batchable: Optional[bool] = None

        def _sizes(tbls):
            seen: list = []
            with syncs.replay(list(self.tape), collect=seen):
                _materialized(qfn(tbls))
            if not seen:
                return jnp.zeros((0,), jnp.int64)
            return jnp.stack([jnp.asarray(x).astype(jnp.int64).reshape(())
                              for x in seen])
        _sizes.__name__ = f"sizes_{qname}"
        # everything not feeding a resolution site is dead code, so this
        # program is the PREFIX of the query that produces its sizes
        self._sizes_prog = jax.jit(_sizes)

    def run(self, tables):
        """Checked execution: one stacked sync validates the tape, then
        one dispatch runs the plan.  Raises :class:`StaleTapeError` when
        the data's resolved sizes differ from the capture run's."""
        with metrics.span(f"compiled.run:{self.name}", tape_len=len(self.tape)):
            # a rehydrated plan checks even an EMPTY tape: the persisted
            # tape being empty while the live plan resolves sizes is
            # itself a divergence the sizes program must surface
            if self.tape or self.rehydrated:
                with metrics.span("compiled.tape_check"):
                    syncs.note_sync()    # the guard's one stacked D2H pull
                    try:
                        actual = np.asarray(self._sizes_prog(tables))
                    except RuntimeError as e:
                        # replay divergence (tape too short/long for the
                        # plan's resolution sites) — for a persisted tape
                        # this is the stale-artifact case: degrade to a
                        # live capture, never fail the request
                        metrics.count("compiled.tape_mismatch")
                        flight.incident("stale_tape", query=self.name,
                                        tape_len=len(self.tape),
                                        rehydrated=self.rehydrated,
                                        error=str(e)[:200])
                        raise StaleTapeError(
                            f"compiled plan is stale: {e}") from e
                if tuple(int(v) for v in actual) != self.tape:
                    diffs = [i for i, (a, b) in
                             enumerate(zip(actual, self.tape)) if int(a) != b]
                    metrics.count("compiled.tape_mismatch")
                    flight.incident("stale_tape", query=self.name,
                                    tape_len=len(self.tape),
                                    positions=diffs[:8])
                    raise StaleTapeError(
                        f"compiled plan is stale: resolved sizes differ from "
                        f"the capture run at tape positions {diffs[:8]} "
                        f"(of {len(self.tape)}) — re-run compile_query on "
                        "the refreshed tables")
            metrics.count("compiled.replay_run")
            with metrics.span("compiled.dispatch"):
                return self._ledger_dispatch(tables)

    def _ledger_dispatch(self, tables):
        """Dispatch with compile-ledger attribution (metrics-enabled
        paths only — the disabled steady loop calls ``_prog`` directly).
        The first dispatch of the jitted program carries the XLA compile,
        so its wall time is the plan's compile cost."""
        if self._dispatched:
            metrics.ledger_add(self._ledger_key, runs=1)
            return self._prog(tables)
        t0 = time.perf_counter()
        out = self._prog(tables)
        self._dispatched = True
        metrics.ledger_add(
            self._ledger_key, runs=1, first_dispatches=1,
            first_dispatch_ms=(time.perf_counter() - t0) * 1e3)
        return out

    def run_unchecked(self, tables):
        """Steady-loop execution: no staleness check, one dispatch.

        The disabled-metrics path is ONE bool check away from the raw
        dispatch — this is the steady loop the <1% overhead guarantee
        covers."""
        if not metrics.enabled():
            return self._prog(tables)
        metrics.count("compiled.replay_run")
        with metrics.span(f"compiled.run_unchecked:{self.name}"):
            return self._ledger_dispatch(tables)

    def run_vmapped(self, tables_list) -> Optional[list]:
        """Execute K same-shaped table sets as ONE vmapped dispatch of the
        compiled tape: array leaves stack on a leading batch axis,
        non-array leaves (static config values, equal across the batch by
        the size fingerprint that grouped it) ride as closure constants,
        and the per-element body is exactly :attr:`_traced_fn` — the same
        replay the serial program runs, so every recorded size stays
        static under ``jax.vmap``.

        Returns the K per-element results (unstacked), or ``None`` when
        the caller must fall back to per-request dispatch: mismatched
        structures/shapes within the batch (transient — the batch was
        mis-grouped), a failed vmap trace, or a failed parity probe (both
        permanent for this plan).

        Bit-exactness is enforced, not assumed: the first batched run
        re-executes element 0 through the serial program and compares
        every output buffer byte-for-byte (``compiled.batch_parity_check``);
        a mismatch rejects batching for this plan forever
        (``compiled.batch_parity_reject``) rather than ever serving a
        response that differs from serial execution."""
        if self._batchable is False:
            return None
        try:
            flat = [jax.tree_util.tree_flatten(t) for t in tables_list]
            leaves0, treedef = flat[0]
            is_arr = [hasattr(l, "dtype") and hasattr(l, "shape")
                      for l in leaves0]
            if any(td != treedef or len(ls) != len(leaves0)
                   for ls, td in flat[1:]):
                return None
            stacked = [jnp.stack([ls[i] for ls, _ in flat])
                       for i, a in enumerate(is_arr) if a]
        except Exception:
            return None             # shape skew within the batch: fall back
        with self._vlock:
            if self._vprog is None:
                consts = [l for l, a in zip(leaves0, is_arr) if not a]

                def _elem(arrs, _c=tuple(consts), _ia=tuple(is_arr),
                          _td=treedef):
                    ai, ci = iter(arrs), iter(_c)
                    full = [next(ai) if a else next(ci) for a in _ia]
                    return self._traced_fn(
                        jax.tree_util.tree_unflatten(_td, full))
                self._vtreedef = treedef
                self._vprog = jax.jit(jax.vmap(_elem))
            elif self._vtreedef != treedef:
                return None
        try:
            with metrics.span(f"compiled.batch:{self.name}",
                              size=len(tables_list)):
                # a vmap build (or a new batch size) re-traces the tape
                # body on purpose — not the silent-recompile bug class
                with sanitize.allow_retrace():
                    out = self._vprog(stacked)
            metrics.count("compiled.batch_replay")
        except Exception:
            metrics.count("compiled.batch_unsupported")
            self._batchable = False
            return None
        outs = [jax.tree_util.tree_map(lambda l, _i=i: l[_i], out)
                for i in range(len(tables_list))]
        if self._batchable is None:
            metrics.count("compiled.batch_parity_check")
            ref = jax.tree_util.tree_leaves(
                self.run_unchecked(tables_list[0]))
            got = jax.tree_util.tree_leaves(outs[0])

            def _bits(a):
                a = np.ascontiguousarray(np.asarray(a))
                return (a.dtype.str, a.shape, a.tobytes())
            if len(ref) != len(got) or any(
                    _bits(r) != _bits(g) for r, g in zip(ref, got)):
                metrics.count("compiled.batch_parity_reject")
                flight.incident("vmap_parity_reject", query=self.name,
                                batch_size=len(tables_list))
                self._batchable = False
                return None
            self._batchable = True
        return outs

    def lower_text(self, tables) -> str:
        """StableHLO of the whole-query program (diagnostics)."""
        return self._prog.lower(tables).as_text()


def compile_query(qfn: Callable, tables) -> CompiledQuery:
    """Capture ``qfn(tables)`` and return its single-program form."""
    return CompiledQuery(qfn, tables)


def rehydrate_query(qfn: Callable, tape) -> CompiledQuery:
    """A :class:`CompiledQuery` over a PERSISTED capture tape — no eager
    capture run (the zero-compile cold-start path, ``exec/artifacts.py``).
    The plan is unverified until its first checked :meth:`CompiledQuery.run`
    validates the tape against live data; callers must route a
    :class:`StaleTapeError` there into a live re-capture."""
    return CompiledQuery(qfn, None, tape=tuple(tape))


def plan_key(tables, *, by_size: bool = False) -> tuple[tuple, list]:
    """Fingerprint of a query's input tables, for plan caching.

    Returns ``(key, arrays)``: a hashable key plus the list of keyed
    arrays so a cache can hold weakrefs guarding ids against recycling.

    **Identity mode** (default): every payload array keys on
    ``(id, dtype, shape)``.  Arrays are immutable, so two lookups that
    produce the SAME key (with all refs live) provably present the same
    buffers — a plan verified once against them (:meth:`CompiledQuery.run`)
    may take the unchecked raw-dispatch path on later hits, and refreshed
    data (new buffers) changes the key instead of silently replaying a
    stale tape.

    **Size mode** (``by_size=True``): arrays key on ``(dtype, shape)``
    only — the *shape* of the request, not its buffers.  Two requests
    with equal size keys trace to the same XLA program, so a warm plan
    can be shared across refreshed same-shape data — PROVIDED the tape is
    revalidated on first replay against the new buffers (the resolved
    sizes, e.g. join cardinalities, are data- not shape-determined; the
    checked :meth:`CompiledQuery.run` is that revalidation).  Objects the
    walker cannot see inside (the ``obj`` arm) still key by identity in
    size mode: sharing across unknown state is never safe.

    Unforced lazy columns are keyed by identity (size mode: dtype +
    length) of the LazyColumn itself, NOT forced: fingerprinting must
    never materialize device memory.
    """
    from ..column import Column, LazyColumn, Table
    key: list = []
    arrays: list = []

    def leaf(a):
        if a is None:
            key.append(None)
        else:
            if by_size:
                key.append((str(getattr(a, "dtype", "?")),
                            tuple(getattr(a, "shape", ()))))
            else:
                key.append((id(a), str(getattr(a, "dtype", "?")),
                            tuple(getattr(a, "shape", ()))))
            arrays.append(a)

    def col(c):
        if isinstance(c, LazyColumn) and c._col is not None:
            c = c._col
        if isinstance(c, LazyColumn):
            if by_size:
                key.append(("lazy", c.dtype.id.value, len(c)))
            else:
                key.append(("lazy", id(c), c.dtype.id.value, len(c)))
            arrays.append(c)
            return
        key.append(("col", c.dtype.id.value))
        leaf(c.data)
        leaf(c.offsets)
        leaf(c.validity)
        for ch in (c.children or ()):
            col(ch)

    def walk(obj):
        if isinstance(obj, dict):
            for k in sorted(obj, key=repr):
                key.append(("key", k))
                walk(obj[k])
        elif isinstance(obj, Table):
            key.append(("table", len(obj.columns)))
            for c in obj.columns:
                col(c)
        elif isinstance(obj, Column):
            col(obj)
        elif isinstance(obj, (list, tuple)):
            key.append(("seq", len(obj)))
            for v in obj:
                walk(v)
        elif isinstance(obj, (int, float, str, bool, bytes, type(None))):
            key.append(("val", obj))
        else:
            key.append(("obj", id(obj)))
            arrays.append(obj)

    walk(tables)
    return tuple(key), arrays
