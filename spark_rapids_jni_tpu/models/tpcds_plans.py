"""TPC-DS queries as logical plan trees (planner port of ``tpcds.py``).

Each builder returns the **unoptimized**, SQL-shaped tree: scans of full
tables, joins, one ``Filter`` with the whole WHERE clause sitting *above*
the joins, a plain ``Aggregate``, then ``Sort``.  No hand-placed
projections, no pre-filtered dimensions, no fused-aggregate calls — the
optimizer has to earn all of that:

* filter pushdown splits the WHERE conjuncts through the joins into the
  scans (where footer stats prune row groups before decode),
* projection pushdown narrows every scan to consumed columns,
* fuse_join_aggregate detects the ``Aggregate(Join(...))`` tail and emits
  the ``ops.join_aggregate`` fused path.

The optimized trees lower to the *exact* op sequence of the hand-fused
``tpcds.py`` queries (same join order, same mask order, same fused tail),
so results are bit-identical — ``tests/test_tpcds.py`` asserts it.
"""

from __future__ import annotations

from ..plan import ir, lower, rules
from . import tpcds

#: base-table name → column names, as ``tpcds.load_tables`` decodes them
TABLE_SCHEMAS: dict[str, list[str]] = {
    "store_sales": list(tpcds.SS_COLS),
    "item": list(tpcds.ITEM_COLS),
    "date_dim": list(tpcds.DATE_COLS),
    "store": list(tpcds.STORE_COLS),
    "web_sales": list(tpcds.WS_COLS),
}

_SUM_EXT = ("ss_ext_sales_price", "sum", "sum_ss_ext_sales_price")


def _eq(col: str, value) -> ir.Cmp:
    return ir.Cmp("==", ir.Col(col), ir.Lit(value))


def q3_plan(manufact_id: int = 436, moy: int = 11) -> ir.Plan:
    j = ir.Join(ir.Join(ir.Scan("store_sales"), ir.Scan("item"),
                        ("ss_item_sk",), ("i_item_sk",)),
                ir.Scan("date_dim"),
                ("ss_sold_date_sk",), ("d_date_sk",))
    f = ir.Filter(j, ir.And((_eq("i_manufact_id", manufact_id),
                             _eq("d_moy", moy))))
    keys = ("d_year", "i_brand_id", "i_brand")
    return ir.Sort(ir.Aggregate(f, keys, (_SUM_EXT,)), keys)


def q42_plan(manager_id: int = 1, year: int = 2000,
             moy: int = 11) -> ir.Plan:
    j = ir.Join(ir.Join(ir.Scan("store_sales"), ir.Scan("item"),
                        ("ss_item_sk",), ("i_item_sk",)),
                ir.Scan("date_dim"),
                ("ss_sold_date_sk",), ("d_date_sk",))
    # conjunct order mirrors the hand query's mask order (moy, then year)
    f = ir.Filter(j, ir.And((_eq("i_manager_id", manager_id),
                             _eq("d_moy", moy), _eq("d_year", year))))
    keys = ("d_year", "i_category_id", "i_category")
    return ir.Sort(ir.Aggregate(f, keys, (_SUM_EXT,)), keys)


def q52_plan(moy: int = 12, year: int = 2001) -> ir.Plan:
    j = ir.Join(ir.Join(ir.Scan("store_sales"), ir.Scan("date_dim"),
                        ("ss_sold_date_sk",), ("d_date_sk",)),
                ir.Scan("item"), ("ss_item_sk",), ("i_item_sk",))
    f = ir.Filter(j, ir.And((_eq("d_moy", moy), _eq("d_year", year))))
    keys = ("d_year", "i_brand_id", "i_brand")
    return ir.Sort(ir.Aggregate(f, keys, (_SUM_EXT,)), keys)


def q55_plan(manager_id: int = 28) -> ir.Plan:
    j = ir.Join(ir.Scan("store_sales"), ir.Scan("item"),
                ("ss_item_sk",), ("i_item_sk",))
    f = ir.Filter(j, _eq("i_manager_id", manager_id))
    keys = ("i_brand_id", "i_brand")
    return ir.Sort(ir.Aggregate(f, keys, (_SUM_EXT,)), keys)


def q7_plan(year: int = 2000) -> ir.Plan:
    j = ir.Join(ir.Join(ir.Scan("store_sales"), ir.Scan("date_dim"),
                        ("ss_sold_date_sk",), ("d_date_sk",)),
                ir.Scan("item"), ("ss_item_sk",), ("i_item_sk",))
    f = ir.Filter(j, _eq("d_year", year))
    aggs = (("ss_quantity", "mean", "avg_quantity"),
            ("ss_list_price_cents", "mean", "avg_list_price"),
            ("ss_sales_price_cents", "mean", "avg_sales_price"))
    return ir.Sort(ir.Aggregate(f, ("i_item_id",), aggs), ("i_item_id",))


def q19_plan(year: int = 1999, moy: int = 11, manager_lo: int = 1,
             manager_hi: int = 50) -> ir.Plan:
    j = ir.Join(ir.Join(ir.Scan("store_sales"), ir.Scan("item"),
                        ("ss_item_sk",), ("i_item_sk",)),
                ir.Scan("date_dim"),
                ("ss_sold_date_sk",), ("d_date_sk",))
    f = ir.Filter(j, ir.And((
        ir.Between(ir.Col("i_manager_id"), manager_lo, manager_hi),
        _eq("d_moy", moy), _eq("d_year", year))))
    keys = ("i_brand_id", "i_brand", "i_manufact_id")
    return ir.Sort(ir.Aggregate(f, keys, (_SUM_EXT,)), keys)


def q65_plan(frac: float = 0.9) -> ir.Plan:
    j = ir.Join(ir.Scan("store_sales"), ir.Scan("item"),
                ("ss_item_sk",), ("i_item_sk",))
    agg = ir.Aggregate(j, ("i_brand_id",), (_SUM_EXT,))
    # HAVING against a global aggregate-of-the-aggregate: stays a device
    # scalar through lowering, exactly like the hand query's threshold
    having = ir.Cmp("<", ir.Col(_SUM_EXT[2]),
                    ir.Mul(ir.ScalarAgg("mean", ir.Col(_SUM_EXT[2])),
                           ir.Lit(frac)))
    return ir.Sort(ir.Filter(agg, having), ("i_brand_id",))


def q_having_plan(min_total: float = 1000.0) -> ir.Plan:
    j = ir.Join(ir.Scan("store_sales"), ir.Scan("item"),
                ("ss_item_sk",), ("i_item_sk",))
    agg = ir.Aggregate(j, ("i_brand_id",), (_SUM_EXT,))
    having = ir.Cmp(">", ir.Col(_SUM_EXT[2]), ir.Lit(min_total))
    return ir.Sort(ir.Filter(agg, having), ("i_brand_id",))


#: name → unoptimized-tree builder (same names/params as ``tpcds.QUERIES``)
PLANS = {
    "q3": q3_plan, "q7": q7_plan, "q19": q19_plan, "q42": q42_plan,
    "q52": q52_plan, "q55": q55_plan, "q65": q65_plan,
    "q_having": q_having_plan,
}


def optimized(name: str, stats=None, **params) -> rules.OptimizeResult:
    """Build + optimize one named query's plan tree."""
    return rules.optimize(PLANS[name](**params), TABLE_SCHEMAS,
                          stats=stats)


def plan_fn(name: str, stats=None, **params):
    """``(qfn, optimized_tree)`` for a named query: ``qfn(tables)`` is
    drop-in for the hand-fused ``tpcds.QUERIES[name]`` — same tables
    dict in, bit-identical Table out — and carries
    ``qfn.plan_fingerprint`` for the exec plan cache."""
    res = optimized(name, stats=stats, **params)
    return lower.compile_plan(res.tree, TABLE_SCHEMAS), res.tree
