"""TPC-DS queries as SQL text, paired with hand-built plan trees.

The SQL front-end's differential corpus: every entry carries (a) the
query as SQL text — the form a client would POST at the serving layer —
and (b) a hand-built **unoptimized** ``plan/ir.py`` tree shaped exactly
as the binder emits it.  ``tests/test_sql.py`` asserts, per query, that

* the SQL-born optimized tree and the hand-built optimized tree share
  one structural fingerprint (the plan-cache/AOT identity), and
* executing both over the synthetic TPC-DS dataset produces
  bit-identical Tables.

Fingerprint equality is the strong claim: it means a SQL submission
dedupes against a pre-existing hand-built plan-cache entry and reuses
its compiled program and AOT artifact outright.

The corpus intentionally sweeps the whole grammar: star joins,
BETWEEN/IN predicates, HAVING (literal and scalar-aggregate thresholds),
ROLLUP/CUBE/GROUPING SETS, COUNT(DISTINCT), MIN/MAX/FIRST/LAST/STDDEV,
window functions (rank over aggregates, row_number dedupe, running
sums), derived tables, LEFT SEMI/ANTI joins, UNION ALL, DISTINCT,
ORDER BY ... DESC, LIMIT, and ``:name`` parameters.
"""

from __future__ import annotations

from ..plan import ir
from . import tpcds_plans
from .tpcds_plans import TABLE_SCHEMAS  # noqa: F401  (re-export)

_SS_ITEM = ir.Join(ir.Scan("store_sales"), ir.Scan("item"),
                   ("ss_item_sk",), ("i_item_sk",))
_SS_DATE = ir.Join(ir.Scan("store_sales"), ir.Scan("date_dim"),
                   ("ss_sold_date_sk",), ("d_date_sk",))
_SUM_EXT = ("ss_ext_sales_price", "sum", "sum_ss_ext_sales_price")


def _eq(col: str, value) -> ir.Cmp:
    return ir.Cmp("==", ir.Col(col), ir.Lit(value))


# --- hand trees for the queries tpcds_plans does not already build ----------

def q62_range_plan(year: int = 2000, qty_lo: int = 10,
                   qty_hi: int = 80) -> ir.Plan:
    j = ir.Join(_SS_ITEM, ir.Scan("date_dim"),
                ("ss_sold_date_sk",), ("d_date_sk",))
    f = ir.Filter(j, ir.And((
        _eq("d_year", year),
        ir.Between(ir.Col("ss_quantity"), lo=qty_lo, hi=qty_hi))))
    return ir.Sort(ir.Aggregate(f, ("i_item_id",),
                                (("ss_ext_sales_price", "sum", "total"),)),
                   ("i_item_id",))


def q52_topn_plan(moy: int = 12, year: int = 2001, n: int = 10) -> ir.Plan:
    return ir.Limit(tpcds_plans.q52_plan(moy=moy, year=year), n)


def q_store_counts_plan() -> ir.Plan:
    j = ir.Join(ir.Scan("store_sales"), ir.Scan("store"),
                ("ss_store_sk",), ("s_store_sk",))
    return ir.Sort(ir.Aggregate(j, ("s_state",),
                                (("ss_item_sk", "count", "n_sales"),)),
                   ("s_state",))


def q_isin_states_plan(states=("TN", "GA", "SD")) -> ir.Plan:
    j = ir.Join(ir.Scan("store_sales"), ir.Scan("store"),
                ("ss_store_sk",), ("s_store_sk",))
    f = ir.Filter(j, ir.IsIn(ir.Col("s_state"), tuple(states)))
    return ir.Sort(ir.Aggregate(f, ("s_state",), (_SUM_EXT,)), ("s_state",))


def q36_rollup_plan() -> ir.Plan:
    return ir.Aggregate(_SS_ITEM, ("i_category_id", "i_brand_id"),
                        (("ss_ext_sales_price", "sum", "total"),),
                        grouping="rollup")


def q27_cube_plan() -> ir.Plan:
    j = ir.Join(_SS_DATE, ir.Scan("item"), ("ss_item_sk",), ("i_item_sk",))
    return ir.Aggregate(j, ("d_year", "i_manager_id"),
                        (("ss_ext_sales_price", "sum", "total"),),
                        grouping="cube")


def q5_grouping_sets_plan() -> ir.Plan:
    j = ir.Join(_SS_DATE, ir.Scan("item"), ("ss_item_sk",), ("i_item_sk",))
    return ir.Aggregate(j, ("d_year", "i_category_id"),
                        (("ss_ext_sales_price", "sum", "total"),),
                        grouping="sets",
                        grouping_sets=((0, 1), (0,), ()))


def q_minmax_price_plan() -> ir.Plan:
    agg = ir.Aggregate(ir.Scan("item"), ("i_category_id",),
                       (("i_current_price", "min", "min_price"),
                        ("i_current_price", "max", "max_price")))
    return ir.Sort(agg, ("i_category_id",))


def q_first_last_plan() -> ir.Plan:
    agg = ir.Aggregate(ir.Scan("item"), ("i_brand_id",),
                       (("i_item_sk", "first", "first_sk"),
                        ("i_item_sk", "last", "last_sk")))
    return ir.Sort(agg, ("i_brand_id",))


def q17_stats_plan() -> ir.Plan:
    agg = ir.Aggregate(_SS_ITEM, ("i_category_id",),
                       (("ss_quantity", "mean", "avg_qty"),
                        ("ss_quantity", "std", "std_qty")))
    return ir.Sort(agg, ("i_category_id",))


def q_nunique_items_plan() -> ir.Plan:
    agg = ir.Aggregate(_SS_DATE, ("d_year",),
                       (("ss_item_sk", "nunique", "n_items"),))
    return ir.Sort(agg, ("d_year",))


def q_distinct_pairs_plan() -> ir.Plan:
    return ir.Distinct(ir.Project(ir.Scan("store_sales"),
                                  ("ss_store_sk", "ss_item_sk")))


def q67_rank_plan(top_n: int = 3) -> ir.Plan:
    agg = ir.Aggregate(_SS_ITEM, ("i_category_id", "i_brand_id"),
                       (("ss_ext_sales_price", "sum", "total"),))
    w = ir.Window(agg, "rank", ("i_category_id",), ("total",), "rk",
                  ascending=(False,))
    return ir.Filter(w, ir.Cmp("<=", ir.Col("rk"), ir.Lit(top_n)))


def q_rownum_dedup_plan(keep: int = 2) -> ir.Plan:
    w = ir.Window(ir.Scan("store_sales"), "row_number",
                  ("ss_item_sk",), ("ss_store_sk",), "rn")
    p = ir.Project(w, ("ss_item_sk", "ss_store_sk", "rn"))
    return ir.Filter(p, ir.Cmp("<=", ir.Col("rn"), ir.Lit(keep)))


def q_running_share_plan() -> ir.Plan:
    agg = ir.Aggregate(_SS_DATE, ("d_year", "d_moy"),
                       (("ss_ext_sales_price", "sum", "m_total"),))
    return ir.Window(agg, "running_sum", ("d_year",), ("d_moy",),
                     "running", value="m_total")


def q_lag_growth_plan() -> ir.Plan:
    agg = ir.Aggregate(_SS_DATE, ("d_year", "d_moy"),
                       (("ss_ext_sales_price", "sum", "m_total"),))
    return ir.Window(agg, "lag", ("d_year",), ("d_moy",), "prev",
                     value="m_total")


def q_union_channels_plan() -> ir.Plan:
    store = ir.Aggregate(_SS_DATE, ("d_year",),
                         (("ss_ext_sales_price", "sum", "total"),))
    web = ir.Aggregate(
        ir.Join(ir.Scan("web_sales"), ir.Scan("date_dim"),
                ("ws_sold_date_sk",), ("d_date_sk",)),
        ("d_year",), (("ws_ext_sales_price", "sum", "total"),))
    return ir.Union((store, web), ("d_year", "total"))


def q16_anti_plan() -> ir.Plan:
    j = ir.Join(ir.Scan("store_sales"), ir.Scan("web_sales"),
                ("ss_item_sk",), ("ws_item_sk",), how="anti")
    return ir.Sort(ir.Aggregate(j, ("ss_store_sk",),
                                (("ss_ext_sales_price", "sum", "total"),)),
                   ("ss_store_sk",))


def q23_semi_plan() -> ir.Plan:
    j = ir.Join(ir.Scan("store_sales"), ir.Scan("web_sales"),
                ("ss_item_sk",), ("ws_item_sk",), how="semi")
    return ir.Sort(ir.Aggregate(j, ("ss_store_sk",),
                                (("ss_ext_sales_price", "sum", "total"),)),
                   ("ss_store_sk",))


def q34_baskets_plan(min_cnt: int = 100) -> ir.Plan:
    agg = ir.Aggregate(ir.Scan("store_sales"), ("ss_store_sk",),
                       (("ss_item_sk", "count", "cnt"),))
    f = ir.Filter(agg, ir.Cmp(">", ir.Col("cnt"), ir.Lit(min_cnt)))
    return ir.Sort(f, ("ss_store_sk",))


# --- the corpus: name → (sql, hand-tree builder, default params) ------------

SQL: dict[str, str] = {
    "q3": """
        SELECT d_year, i_brand_id, i_brand,
               SUM(ss_ext_sales_price) AS sum_ss_ext_sales_price
        FROM store_sales
        JOIN item ON ss_item_sk = i_item_sk
        JOIN date_dim ON ss_sold_date_sk = d_date_sk
        WHERE i_manufact_id = :manufact_id AND d_moy = :moy
        GROUP BY d_year, i_brand_id, i_brand
        ORDER BY d_year, i_brand_id, i_brand
    """,
    "q7": """
        SELECT i_item_id, AVG(ss_quantity) AS avg_quantity,
               AVG(ss_list_price_cents) AS avg_list_price,
               AVG(ss_sales_price_cents) AS avg_sales_price
        FROM store_sales
        JOIN date_dim ON ss_sold_date_sk = d_date_sk
        JOIN item ON ss_item_sk = i_item_sk
        WHERE d_year = :year
        GROUP BY i_item_id ORDER BY i_item_id
    """,
    "q19": """
        SELECT i_brand_id, i_brand, i_manufact_id,
               SUM(ss_ext_sales_price) AS sum_ss_ext_sales_price
        FROM store_sales
        JOIN item ON ss_item_sk = i_item_sk
        JOIN date_dim ON ss_sold_date_sk = d_date_sk
        WHERE i_manager_id BETWEEN :manager_lo AND :manager_hi
          AND d_moy = :moy AND d_year = :year
        GROUP BY i_brand_id, i_brand, i_manufact_id
        ORDER BY i_brand_id, i_brand, i_manufact_id
    """,
    "q42": """
        SELECT d_year, i_category_id, i_category,
               SUM(ss_ext_sales_price) AS sum_ss_ext_sales_price
        FROM store_sales
        JOIN item ON ss_item_sk = i_item_sk
        JOIN date_dim ON ss_sold_date_sk = d_date_sk
        WHERE i_manager_id = :manager_id AND d_moy = :moy
          AND d_year = :year
        GROUP BY d_year, i_category_id, i_category
        ORDER BY d_year, i_category_id, i_category
    """,
    "q52": """
        SELECT d_year, i_brand_id, i_brand,
               SUM(ss_ext_sales_price) AS sum_ss_ext_sales_price
        FROM store_sales
        JOIN date_dim ON ss_sold_date_sk = d_date_sk
        JOIN item ON ss_item_sk = i_item_sk
        WHERE d_moy = :moy AND d_year = :year
        GROUP BY d_year, i_brand_id, i_brand
        ORDER BY d_year, i_brand_id, i_brand
    """,
    "q55": """
        SELECT i_brand_id, i_brand,
               SUM(ss_ext_sales_price) AS sum_ss_ext_sales_price
        FROM store_sales
        JOIN item ON ss_item_sk = i_item_sk
        WHERE i_manager_id = :manager_id
        GROUP BY i_brand_id, i_brand ORDER BY i_brand_id, i_brand
    """,
    "q65": """
        SELECT i_brand_id,
               SUM(ss_ext_sales_price) AS sum_ss_ext_sales_price
        FROM store_sales
        JOIN item ON ss_item_sk = i_item_sk
        GROUP BY i_brand_id
        HAVING sum_ss_ext_sales_price
             < AVG(sum_ss_ext_sales_price) * :frac
        ORDER BY i_brand_id
    """,
    "q_having": """
        SELECT i_brand_id,
               SUM(ss_ext_sales_price) AS sum_ss_ext_sales_price
        FROM store_sales
        JOIN item ON ss_item_sk = i_item_sk
        GROUP BY i_brand_id
        HAVING sum_ss_ext_sales_price > :min_total
        ORDER BY i_brand_id
    """,
    "q62_range": """
        SELECT i_item_id, SUM(ss_ext_sales_price) AS total
        FROM store_sales
        JOIN item ON ss_item_sk = i_item_sk
        JOIN date_dim ON ss_sold_date_sk = d_date_sk
        WHERE d_year = :year AND ss_quantity BETWEEN :qty_lo AND :qty_hi
        GROUP BY i_item_id ORDER BY i_item_id
    """,
    "q52_topn": """
        SELECT d_year, i_brand_id, i_brand,
               SUM(ss_ext_sales_price) AS sum_ss_ext_sales_price
        FROM store_sales
        JOIN date_dim ON ss_sold_date_sk = d_date_sk
        JOIN item ON ss_item_sk = i_item_sk
        WHERE d_moy = :moy AND d_year = :year
        GROUP BY d_year, i_brand_id, i_brand
        ORDER BY d_year, i_brand_id, i_brand
        LIMIT 10
    """,
    "q_store_counts": """
        SELECT s_state, COUNT(ss_item_sk) AS n_sales
        FROM store_sales
        JOIN store ON ss_store_sk = s_store_sk
        GROUP BY s_state ORDER BY s_state
    """,
    "q_isin_states": """
        SELECT s_state, SUM(ss_ext_sales_price) AS sum_ss_ext_sales_price
        FROM store_sales
        JOIN store ON ss_store_sk = s_store_sk
        WHERE s_state IN ('TN', 'GA', 'SD')
        GROUP BY s_state ORDER BY s_state
    """,
    "q36_rollup": """
        SELECT i_category_id, i_brand_id,
               SUM(ss_ext_sales_price) AS total, grouping_id
        FROM store_sales
        JOIN item ON ss_item_sk = i_item_sk
        GROUP BY ROLLUP (i_category_id, i_brand_id)
    """,
    "q27_cube": """
        SELECT d_year, i_manager_id,
               SUM(ss_ext_sales_price) AS total, grouping_id
        FROM store_sales
        JOIN date_dim ON ss_sold_date_sk = d_date_sk
        JOIN item ON ss_item_sk = i_item_sk
        GROUP BY CUBE (d_year, i_manager_id)
    """,
    "q5_grouping_sets": """
        SELECT d_year, i_category_id,
               SUM(ss_ext_sales_price) AS total, grouping_id
        FROM store_sales
        JOIN date_dim ON ss_sold_date_sk = d_date_sk
        JOIN item ON ss_item_sk = i_item_sk
        GROUP BY GROUPING SETS ((d_year, i_category_id), (d_year), ())
    """,
    "q_minmax_price": """
        SELECT i_category_id, MIN(i_current_price) AS min_price,
               MAX(i_current_price) AS max_price
        FROM item GROUP BY i_category_id ORDER BY i_category_id
    """,
    "q_first_last": """
        SELECT i_brand_id, FIRST(i_item_sk) AS first_sk,
               LAST(i_item_sk) AS last_sk
        FROM item GROUP BY i_brand_id ORDER BY i_brand_id
    """,
    "q17_stats": """
        SELECT i_category_id, AVG(ss_quantity) AS avg_qty,
               STDDEV(ss_quantity) AS std_qty
        FROM store_sales
        JOIN item ON ss_item_sk = i_item_sk
        GROUP BY i_category_id ORDER BY i_category_id
    """,
    "q_nunique_items": """
        SELECT d_year, COUNT(DISTINCT ss_item_sk) AS n_items
        FROM store_sales
        JOIN date_dim ON ss_sold_date_sk = d_date_sk
        GROUP BY d_year ORDER BY d_year
    """,
    "q_distinct_pairs": """
        SELECT DISTINCT ss_store_sk, ss_item_sk FROM store_sales
    """,
    "q67_rank": """
        SELECT i_category_id, i_brand_id, total, rk
        FROM (SELECT i_category_id, i_brand_id,
                     SUM(ss_ext_sales_price) AS total,
                     RANK() OVER (PARTITION BY i_category_id
                                  ORDER BY total DESC) AS rk
              FROM store_sales
              JOIN item ON ss_item_sk = i_item_sk
              GROUP BY i_category_id, i_brand_id)
        WHERE rk <= :top_n
    """,
    "q_rownum_dedup": """
        SELECT ss_item_sk, ss_store_sk, rn
        FROM (SELECT ss_item_sk, ss_store_sk,
                     ROW_NUMBER() OVER (PARTITION BY ss_item_sk
                                        ORDER BY ss_store_sk) AS rn
              FROM store_sales)
        WHERE rn <= :keep
    """,
    "q_running_share": """
        SELECT d_year, d_moy, SUM(ss_ext_sales_price) AS m_total,
               SUM(m_total) OVER (PARTITION BY d_year
                                  ORDER BY d_moy) AS running
        FROM store_sales
        JOIN date_dim ON ss_sold_date_sk = d_date_sk
        GROUP BY d_year, d_moy
    """,
    "q_lag_growth": """
        SELECT d_year, d_moy, SUM(ss_ext_sales_price) AS m_total,
               LAG(m_total) OVER (PARTITION BY d_year
                                  ORDER BY d_moy) AS prev
        FROM store_sales
        JOIN date_dim ON ss_sold_date_sk = d_date_sk
        GROUP BY d_year, d_moy
    """,
    "q_union_channels": """
        SELECT d_year, SUM(ss_ext_sales_price) AS total
        FROM store_sales
        JOIN date_dim ON ss_sold_date_sk = d_date_sk
        GROUP BY d_year
        UNION ALL
        SELECT d_year, SUM(ws_ext_sales_price) AS total
        FROM web_sales
        JOIN date_dim ON ws_sold_date_sk = d_date_sk
        GROUP BY d_year
    """,
    "q16_anti": """
        SELECT ss_store_sk, SUM(ss_ext_sales_price) AS total
        FROM store_sales
        LEFT ANTI JOIN web_sales ON ss_item_sk = ws_item_sk
        GROUP BY ss_store_sk ORDER BY ss_store_sk
    """,
    "q23_semi": """
        SELECT ss_store_sk, SUM(ss_ext_sales_price) AS total
        FROM store_sales
        LEFT SEMI JOIN web_sales ON ss_item_sk = ws_item_sk
        GROUP BY ss_store_sk ORDER BY ss_store_sk
    """,
    "q34_baskets": """
        SELECT ss_store_sk, COUNT(ss_item_sk) AS cnt
        FROM store_sales
        GROUP BY ss_store_sk
        HAVING cnt > :min_cnt
        ORDER BY ss_store_sk
    """,
}

#: name → hand-built unoptimized tree builder (binder-shaped)
HAND = {
    "q3": tpcds_plans.q3_plan, "q7": tpcds_plans.q7_plan,
    "q19": tpcds_plans.q19_plan, "q42": tpcds_plans.q42_plan,
    "q52": tpcds_plans.q52_plan, "q55": tpcds_plans.q55_plan,
    "q65": tpcds_plans.q65_plan, "q_having": tpcds_plans.q_having_plan,
    "q62_range": q62_range_plan, "q52_topn": q52_topn_plan,
    "q_store_counts": q_store_counts_plan,
    "q_isin_states": q_isin_states_plan,
    "q36_rollup": q36_rollup_plan, "q27_cube": q27_cube_plan,
    "q5_grouping_sets": q5_grouping_sets_plan,
    "q_minmax_price": q_minmax_price_plan,
    "q_first_last": q_first_last_plan, "q17_stats": q17_stats_plan,
    "q_nunique_items": q_nunique_items_plan,
    "q_distinct_pairs": q_distinct_pairs_plan,
    "q67_rank": q67_rank_plan, "q_rownum_dedup": q_rownum_dedup_plan,
    "q_running_share": q_running_share_plan,
    "q_lag_growth": q_lag_growth_plan,
    "q_union_channels": q_union_channels_plan,
    "q16_anti": q16_anti_plan, "q23_semi": q23_semi_plan,
    "q34_baskets": q34_baskets_plan,
}

#: default ``:name`` bindings per query (empty dict = no parameters)
PARAMS: dict[str, dict] = {
    "q3": {"manufact_id": 436, "moy": 11},
    "q7": {"year": 2000},
    "q19": {"manager_lo": 1, "manager_hi": 50, "moy": 11, "year": 1999},
    "q42": {"manager_id": 1, "moy": 11, "year": 2000},
    "q52": {"moy": 12, "year": 2001},
    "q55": {"manager_id": 28},
    "q65": {"frac": 0.9},
    "q_having": {"min_total": 1000.0},
    "q62_range": {"year": 2000, "qty_lo": 10, "qty_hi": 80},
    "q52_topn": {"moy": 12, "year": 2001},
    "q67_rank": {"top_n": 3},
    "q_rownum_dedup": {"keep": 2},
    "q34_baskets": {"min_cnt": 100},
}

QUERY_NAMES = tuple(SQL)
assert set(SQL) == set(HAND)


def hand_tree(name: str) -> ir.Plan:
    """The hand-built unoptimized tree with the corpus-default params."""
    params = PARAMS.get(name, {})
    return HAND[name](**params)
