"""TPC-DS query subset (BASELINE config #3): join + groupby + strings +
decimals end-to-end on the framework's op library.

Five queries shaped after the spec's reporting family (Q3 / Q42 / Q52 /
Q55, plus a store-state rollup exercising decimal aggregation) run against
the mini generator in ``benchmarks/tpcds_data.py``.  Every query is scan
(``parquet.decode`` incl. the Snappy path) → compacting filters → sort-probe
equi-joins → sort-based groupby with string keys (dictionary-encoded,
``ops.strings``) → deterministic key-ordered output, differentially tested
against pandas running the same plan (tests/test_tpcds.py).

The reference reaches this tier through libcudf's join/groupby/strings
(SURVEY §2.9); the TPU formulation is the op library's: no hash tables, no
dynamic shapes outside the two-phase sync points.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..column import Column, Table
from ..ops import (apply_boolean_mask, concat_tables, distinct,
                   groupby_aggregate, groupby_nunique, inner_join, isin,
                   left_join, mean, slice_table, sort_table)
from ..ops import strings as S
from ..ops import window as W
from ..parquet import device_scan as decode  # device fast path, host fallback

SS_COLS = ["ss_sold_date_sk", "ss_item_sk", "ss_store_sk", "ss_quantity",
           "ss_sales_price_cents", "ss_list_price_cents",
           "ss_ext_sales_price"]
WS_COLS = ["ws_sold_date_sk", "ws_item_sk", "ws_quantity",
           "ws_ext_sales_price"]
ITEM_COLS = ["i_item_sk", "i_item_id", "i_current_price", "i_brand_id",
             "i_brand", "i_category_id", "i_category", "i_manufact_id",
             "i_manager_id"]
DATE_COLS = ["d_date_sk", "d_year", "d_moy"]
STORE_COLS = ["s_store_sk", "s_state"]


def load_tables(files: dict[str, bytes]) -> dict[str, Table]:
    return {
        "store_sales": decode.read_table(files["store_sales"],
                                         columns=SS_COLS),
        "item": decode.read_table(files["item"], columns=ITEM_COLS),
        "date_dim": decode.read_table(files["date_dim"], columns=DATE_COLS),
        "store": decode.read_table(files["store"], columns=STORE_COLS),
        **({"web_sales": decode.read_table(files["web_sales"],
                                           columns=WS_COLS)}
           if "web_sales" in files else {}),
    }


def _eq_scalar_mask(col: Column, value) -> "np.ndarray":
    if col.dtype.id == T.TypeId.STRING:
        b = S.equal_to_scalar(col, value)
        m = b.data.astype(bool)
        return m if b.validity is None else (m & b.validity)
    m = col.values() == value
    return m if col.validity is None else (m & col.validity)


def _col(cols: list[str], name: str) -> int:
    return cols.index(name)


def _range_mask(col: Column, lo=None, hi=None, hi_strict: bool = False):
    """lo <= col <= hi (either bound optional; ``hi_strict`` makes the
    upper bound exclusive), null-safe like ``_eq_scalar_mask`` — keeps the
    validity AND in one place."""
    m = None
    cvals = col.values()
    if lo is not None:
        m = cvals >= lo
    if hi is not None:
        hm = (cvals < hi) if hi_strict else (cvals <= hi)
        m = hm if m is None else (m & hm)
    if col.validity is not None:
        m = col.validity if m is None else (m & col.validity)
    return m


def _group_sum(joined: Table, cols: list[str], key_names: list[str],
               value_name: str) -> Table:
    """Shared tail of the reporting queries: GROUP BY keys, SUM(value),
    deterministic key order.  ``cols`` is the joined column-name list
    (inner_join's left ++ right contract)."""
    out = groupby_aggregate(
        joined, [cols.index(k) for k in key_names],
        [(cols.index(value_name), "sum")])
    return sort_table(out, list(range(len(key_names))))


def q3(tables: dict[str, Table], manufact_id: int = 436,
       moy: int = 11) -> Table:
    """SELECT d_year, i_brand_id, i_brand, sum(ss_ext_sales_price)
    FROM store_sales ⋈ item ⋈ date_dim
    WHERE i_manufact_id = ? AND d_moy = ?
    GROUP BY d_year, i_brand_id, i_brand ORDER BY keys."""
    ss, item, dd = tables["store_sales"], tables["item"], tables["date_dim"]
    item_f = apply_boolean_mask(
        item, _eq_scalar_mask(item[_col(ITEM_COLS, "i_manufact_id")],
                              manufact_id))
    dd_f = apply_boolean_mask(
        dd, _eq_scalar_mask(dd[_col(DATE_COLS, "d_moy")], moy))
    j1 = inner_join(ss, item_f, _col(SS_COLS, "ss_item_sk"),
                    _col(ITEM_COLS, "i_item_sk"))
    # j1 columns: SS_COLS ++ ITEM_COLS
    j2 = inner_join(j1, dd_f, _col(SS_COLS, "ss_sold_date_sk"),
                    _col(DATE_COLS, "d_date_sk"))
    return _group_sum(j2, SS_COLS + ITEM_COLS + DATE_COLS,
                      ["d_year", "i_brand_id", "i_brand"],
                      "ss_ext_sales_price")


def q42(tables: dict[str, Table], manager_id: int = 1, year: int = 2000,
        moy: int = 11) -> Table:
    """GROUP BY d_year, i_category_id, i_category with manager/date
    predicates (Q42 shape)."""
    ss, item, dd = tables["store_sales"], tables["item"], tables["date_dim"]
    item_f = apply_boolean_mask(
        item, _eq_scalar_mask(item[_col(ITEM_COLS, "i_manager_id")],
                              manager_id))
    dd_mask = (_eq_scalar_mask(dd[_col(DATE_COLS, "d_moy")], moy)
               & _eq_scalar_mask(dd[_col(DATE_COLS, "d_year")], year))
    dd_f = apply_boolean_mask(dd, dd_mask)
    j1 = inner_join(ss, item_f, _col(SS_COLS, "ss_item_sk"),
                    _col(ITEM_COLS, "i_item_sk"))
    j2 = inner_join(j1, dd_f, _col(SS_COLS, "ss_sold_date_sk"),
                    _col(DATE_COLS, "d_date_sk"))
    return _group_sum(j2, SS_COLS + ITEM_COLS + DATE_COLS,
                      ["d_year", "i_category_id", "i_category"],
                      "ss_ext_sales_price")


def q52(tables: dict[str, Table], moy: int = 12, year: int = 2001) -> Table:
    """GROUP BY d_year, i_brand_id, i_brand for one month (Q52 shape)."""
    ss, item, dd = tables["store_sales"], tables["item"], tables["date_dim"]
    dd_mask = (_eq_scalar_mask(dd[_col(DATE_COLS, "d_moy")], moy)
               & _eq_scalar_mask(dd[_col(DATE_COLS, "d_year")], year))
    dd_f = apply_boolean_mask(dd, dd_mask)
    j1 = inner_join(ss, dd_f, _col(SS_COLS, "ss_sold_date_sk"),
                    _col(DATE_COLS, "d_date_sk"))
    cols1 = SS_COLS + DATE_COLS
    j2 = inner_join(j1, tables["item"], cols1.index("ss_item_sk"),
                    _col(ITEM_COLS, "i_item_sk"))
    return _group_sum(j2, cols1 + ITEM_COLS,
                      ["d_year", "i_brand_id", "i_brand"],
                      "ss_ext_sales_price")


def q55(tables: dict[str, Table], manager_id: int = 28) -> Table:
    """GROUP BY i_brand_id, i_brand for one manager (Q55 shape)."""
    ss, item = tables["store_sales"], tables["item"]
    item_f = apply_boolean_mask(
        item, _eq_scalar_mask(item[_col(ITEM_COLS, "i_manager_id")],
                              manager_id))
    j1 = inner_join(ss, item_f, _col(SS_COLS, "ss_item_sk"),
                    _col(ITEM_COLS, "i_item_sk"))
    return _group_sum(j1, SS_COLS + ITEM_COLS,
                      ["i_brand_id", "i_brand"], "ss_ext_sales_price")


def q_state_rollup(tables: dict[str, Table], state: str = "TN") -> Table:
    """Store-state rollup with decimal aggregation: the s_state string
    predicate + decimal64(-2) sales-price sum and quantity mean."""
    ss, store = tables["store_sales"], tables["store"]
    store_f = apply_boolean_mask(
        store, _eq_scalar_mask(store[_col(STORE_COLS, "s_state")],
                               state))
    j1 = inner_join(ss, store_f, _col(SS_COLS, "ss_store_sk"),
                    _col(STORE_COLS, "s_store_sk"))
    cols = SS_COLS + STORE_COLS
    # the cents column IS the unscaled decimal payload — reinterpret as
    # decimal64(scale -2) (RowConversion.java:114-118 representation);
    # sum keeps the scale
    price_i = cols.index("ss_sales_price_cents")
    work = list(j1.columns)
    work[price_i] = Column(T.decimal64(-2), j1[price_i].data,
                           validity=j1[price_i].validity)
    out = groupby_aggregate(
        Table(work), [cols.index("s_state")],
        [(price_i, "sum"), (cols.index("ss_quantity"), "mean"),
         (cols.index("ss_quantity"), "count")])
    return sort_table(out, [0])


def q7(tables: dict[str, Table], year: int = 2000) -> Table:
    """SELECT i_item_id, avg(ss_quantity), avg(ss_list_price),
    avg(ss_sales_price) FROM ss ⋈ item ⋈ date WHERE d_year = ?
    GROUP BY i_item_id ORDER BY i_item_id (Q7 shape: multi-mean)."""
    ss, item, dd = tables["store_sales"], tables["item"], tables["date_dim"]
    dd_f = apply_boolean_mask(
        dd, _eq_scalar_mask(dd[_col(DATE_COLS, "d_year")], year))
    j1 = inner_join(ss, dd_f, _col(SS_COLS, "ss_sold_date_sk"),
                    _col(DATE_COLS, "d_date_sk"))
    cols1 = SS_COLS + DATE_COLS
    j2 = inner_join(j1, item, cols1.index("ss_item_sk"),
                    _col(ITEM_COLS, "i_item_sk"))
    cols = cols1 + ITEM_COLS
    out = groupby_aggregate(
        j2, [cols.index("i_item_id")],
        [(cols.index("ss_quantity"), "mean"),
         (cols.index("ss_list_price_cents"), "mean"),
         (cols.index("ss_sales_price_cents"), "mean")])
    return sort_table(out, [0])


def q19(tables: dict[str, Table], year: int = 1999, moy: int = 11,
        manager_lo: int = 1, manager_hi: int = 50) -> Table:
    """Brand revenue for a manager-id RANGE in one month (Q19 shape:
    range predicate + 3-key groupby)."""
    ss, item, dd = tables["store_sales"], tables["item"], tables["date_dim"]
    item_f = apply_boolean_mask(
        item, _range_mask(item[_col(ITEM_COLS, "i_manager_id")],
                          manager_lo, manager_hi))
    dd_mask = (_eq_scalar_mask(dd[_col(DATE_COLS, "d_moy")], moy)
               & _eq_scalar_mask(dd[_col(DATE_COLS, "d_year")], year))
    dd_f = apply_boolean_mask(dd, dd_mask)
    j1 = inner_join(ss, item_f, _col(SS_COLS, "ss_item_sk"),
                    _col(ITEM_COLS, "i_item_sk"))
    j2 = inner_join(j1, dd_f, _col(SS_COLS, "ss_sold_date_sk"),
                    _col(DATE_COLS, "d_date_sk"))
    return _group_sum(j2, SS_COLS + ITEM_COLS + DATE_COLS,
                      ["i_brand_id", "i_brand", "i_manufact_id"],
                      "ss_ext_sales_price")


def q62(tables: dict[str, Table], year: int = 2000, qty_lo: int = 10,
        qty_hi: int = 60) -> Table:
    """Sales counts per month for a quantity band (Q62/Q96 count shape)."""
    ss, dd = tables["store_sales"], tables["date_dim"]
    ss_f = apply_boolean_mask(
        ss, _range_mask(ss[_col(SS_COLS, "ss_quantity")], qty_lo, qty_hi))
    dd_f = apply_boolean_mask(
        dd, _eq_scalar_mask(dd[_col(DATE_COLS, "d_year")], year))
    j = inner_join(ss_f, dd_f, _col(SS_COLS, "ss_sold_date_sk"),
                   _col(DATE_COLS, "d_date_sk"))
    cols = SS_COLS + DATE_COLS
    out = groupby_aggregate(j, [cols.index("d_moy")],
                            [(cols.index("ss_quantity"), "count")])
    return sort_table(out, [0])


def q52_topn(tables: dict[str, Table], moy: int = 12, year: int = 2001,
             n: int = 10) -> Table:
    """Q52 with its ORDER BY sum DESC LIMIT: descending sort on the
    aggregate + slice (the op library's cudf::slice analog)."""
    out = q52(tables, moy=moy, year=year)
    # columns: d_year, i_brand_id, i_brand, sum — order by sum desc then
    # brand id asc for a deterministic tie-break
    ranked = sort_table(out, [3, 1], ascending=[False, True])
    return slice_table(ranked, 0, n)


def q65(tables: dict[str, Table], frac: float = 0.9) -> Table:
    """Brands whose revenue is below ``frac`` × the mean brand revenue
    (Q65 shape: aggregate, then compare each group against a global
    aggregate of the aggregate)."""
    ss, item = tables["store_sales"], tables["item"]
    j = inner_join(ss, item, _col(SS_COLS, "ss_item_sk"),
                   _col(ITEM_COLS, "i_item_sk"))
    cols = SS_COLS + ITEM_COLS
    rev = groupby_aggregate(j, [cols.index("i_brand_id")],
                            [(cols.index("ss_ext_sales_price"), "sum")])
    # device scalar — a host pull here would both cost a sync and break
    # whole-query tracing (models/compiled.py); the comparison broadcasts
    threshold = mean(rev[1]) * frac
    return sort_table(
        apply_boolean_mask(rev, _range_mask(rev[1], hi=threshold,
                                            hi_strict=True)), [0])


def q_store_counts(tables: dict[str, Table]) -> Table:
    """Per-store sale counts INCLUDING stores with no sales (left join →
    count over a nullable column; Spark's LEFT OUTER + COUNT semantics)."""
    ss, store = tables["store_sales"], tables["store"]
    j = left_join(store, ss, _col(STORE_COLS, "s_store_sk"),
                  _col(SS_COLS, "ss_store_sk"))
    cols = STORE_COLS + SS_COLS
    out = groupby_aggregate(
        j, [cols.index("s_store_sk"), cols.index("s_state")],
        [(cols.index("ss_item_sk"), "count")])
    return sort_table(out, [0])


def q67_rank(tables: dict[str, Table], top_n: int = 3) -> Table:
    """Top-N brands per category by revenue — the Q67 window shape:
    aggregate, then RANK() OVER (PARTITION BY category ORDER BY sum DESC)
    and keep rank <= N."""
    ss, item = tables["store_sales"], tables["item"]
    j = inner_join(ss, item, _col(SS_COLS, "ss_item_sk"),
                   _col(ITEM_COLS, "i_item_sk"))
    cols = SS_COLS + ITEM_COLS
    rev = groupby_aggregate(
        j, [cols.index("i_category"), cols.index("i_brand_id")],
        [(cols.index("ss_ext_sales_price"), "sum")])
    # rev: [i_category, i_brand_id, sum]
    spec = W.WindowSpec(rev, partition_by=[0], order_by_keys=[2, 1],
                        ascending=[False, True])
    rk = W.rank(spec, [2, 1])
    keep = rk.values() <= top_n
    out = apply_boolean_mask(Table(list(rev.columns) + [rk]), keep)
    return sort_table(out, [0, 3, 1])


def q_like_brands(tables: dict[str, Table], pat: str = "#1",
                  cat_prefix: str = "S") -> Table:
    """LIKE/substring-heavy predicate family (Q45/Q23 spirit): revenue of
    items whose brand CONTAINS ``pat`` and whose category STARTS WITH
    ``cat_prefix`` (via substring equality), grouped by category."""
    ss, item = tables["store_sales"], tables["item"]
    brand_has = S.contains(item[_col(ITEM_COLS, "i_brand")], pat)
    cat_ok = S.starts_with(item[_col(ITEM_COLS, "i_category")], cat_prefix)
    m = (brand_has.data.astype(bool) & cat_ok.data.astype(bool))
    item_f = apply_boolean_mask(item, m)
    j = inner_join(ss, item_f, _col(SS_COLS, "ss_item_sk"),
                   _col(ITEM_COLS, "i_item_sk"))
    return _group_sum(j, SS_COLS + ITEM_COLS, ["i_category"],
                      "ss_ext_sales_price")


def q_union_channels(tables: dict[str, Table]) -> Table:
    """Multi-fact UNION ALL (Q71/Q76 shape): store + web revenue per
    category — both facts projected to a common (item_sk, price) schema,
    concatenated, then joined and grouped."""
    ss, ws, item = (tables["store_sales"], tables["web_sales"],
                    tables["item"])
    common = ["item_sk", "price"]
    part_s = Table([ss[_col(SS_COLS, "ss_item_sk")],
                    ss[_col(SS_COLS, "ss_ext_sales_price")]])
    part_w = Table([ws[_col(WS_COLS, "ws_item_sk")],
                    ws[_col(WS_COLS, "ws_ext_sales_price")]])
    both = concat_tables([part_s, part_w])
    j = inner_join(both, item, 0, _col(ITEM_COLS, "i_item_sk"))
    return _group_sum(j, common + ITEM_COLS, ["i_category"], "price")


def q_lag_growth(tables: dict[str, Table]) -> Table:
    """Month-over-month revenue delta per store (window LAG shape):
    aggregate per (store, year, month), then value - LAG(value) within the
    store partition ordered by (year, month)."""
    ss, dd = tables["store_sales"], tables["date_dim"]
    j = inner_join(ss, dd, _col(SS_COLS, "ss_sold_date_sk"),
                   _col(DATE_COLS, "d_date_sk"))
    cols = SS_COLS + DATE_COLS
    rev = groupby_aggregate(
        j, [cols.index("ss_store_sk"), cols.index("d_year"),
            cols.index("d_moy")],
        [(cols.index("ss_ext_sales_price"), "sum")])
    # rev: [store, year, moy, sum]
    spec = W.WindowSpec(rev, partition_by=[0], order_by_keys=[1, 2])
    prev = W.lag(spec, 3, 1)
    pv = jnp.where(prev.validity_or_true(), prev.values(), 0.0)
    delta = Column.from_values(T.float64, rev[3].values() - pv,
                               validity=prev.validity)
    out = Table(list(rev.columns) + [delta])
    return sort_table(out, [0, 1, 2])


def q_running_share(tables: dict[str, Table], year: int = 2000) -> Table:
    """Cumulative revenue per store across months (window running-sum
    shape, Q47 spirit)."""
    ss, dd = tables["store_sales"], tables["date_dim"]
    dd_f = apply_boolean_mask(
        dd, _eq_scalar_mask(dd[_col(DATE_COLS, "d_year")], year))
    j = inner_join(ss, dd_f, _col(SS_COLS, "ss_sold_date_sk"),
                   _col(DATE_COLS, "d_date_sk"))
    cols = SS_COLS + DATE_COLS
    rev = groupby_aggregate(
        j, [cols.index("ss_store_sk"), cols.index("d_moy")],
        [(cols.index("ss_ext_sales_price"), "sum")])
    spec = W.WindowSpec(rev, partition_by=[0], order_by_keys=[1])
    cum = W.running_sum(spec, 2)
    return sort_table(Table(list(rev.columns) + [cum]), [0, 1])


def q_nunique_items(tables: dict[str, Table]) -> Table:
    """COUNT(DISTINCT item) per store (Q14-family distinct-count shape)."""
    ss = tables["store_sales"]
    out = groupby_nunique(ss, [_col(SS_COLS, "ss_store_sk")],
                          _col(SS_COLS, "ss_item_sk"))
    return sort_table(out, [0])


def q_having(tables: dict[str, Table], min_total: float = 1000.0) -> Table:
    """GROUP BY brand HAVING SUM(price) > threshold (Q23 HAVING shape):
    aggregate, then filter on the aggregate.

    Projection pushdown (what Spark's optimizer does before the exchange):
    this is an UNFILTERED full-fact join, so only the join key, the measure,
    and the group column enter it — materializing all 16 joined columns at
    SF1 allocates multiple GB of string gathers for columns the query never
    reads (measured: it OOM-crashed the chip at 10M rows).
    """
    ss, item = tables["store_sales"], tables["item"]
    ssp = Table([ss[_col(SS_COLS, "ss_item_sk")],
                 ss[_col(SS_COLS, "ss_ext_sales_price")]])
    itp = Table([item[_col(ITEM_COLS, "i_item_sk")],
                 item[_col(ITEM_COLS, "i_brand_id")]])
    j = inner_join(ssp, itp, 0, 0)
    # j columns: [ss_item_sk, price, i_item_sk, i_brand_id]
    rev = groupby_aggregate(j, [3], [(1, "sum")])
    keep = rev[1].values() > min_total
    return sort_table(apply_boolean_mask(rev, keep), [0])


def q_case_when(tables: dict[str, Table], qty_cut: int = 50) -> Table:
    """Conditional aggregation (Q34/CASE WHEN shape): per category, revenue
    from bulk rows (qty > cut) vs retail rows, in one pass via two masked
    value columns."""
    ss, item = tables["store_sales"], tables["item"]
    j = inner_join(ss, item, _col(SS_COLS, "ss_item_sk"),
                   _col(ITEM_COLS, "i_item_sk"))
    cols = SS_COLS + ITEM_COLS
    qcol = j[cols.index("ss_quantity")]
    pcol = j[cols.index("ss_ext_sales_price")]
    # SQL semantics: NULL qty fails the WHEN (ELSE branch); SUM skips NULL
    # prices (they contribute 0 to either branch)
    price = jnp.where(pcol.validity_or_true(), pcol.values(), 0.0)
    bulk = qcol.validity_or_true() & (qcol.data > qty_cut)
    cb = Column.from_values(T.float64, jnp.where(bulk, price, 0.0))
    cr = Column.from_values(T.float64, jnp.where(bulk, 0.0, price))
    work = Table(list(j.columns) + [cb, cr])
    out = groupby_aggregate(
        work, [cols.index("i_category")],
        [(len(cols), "sum"), (len(cols) + 1, "sum")])
    return sort_table(out, [0])


def q_distinct_pairs(tables: dict[str, Table]) -> Table:
    """DISTINCT (brand_id, category_id) pairs (dropDuplicates shape)."""
    item = tables["item"]
    pairs = Table([item[_col(ITEM_COLS, "i_brand_id")],
                   item[_col(ITEM_COLS, "i_category_id")]])
    return sort_table(distinct(pairs), [0, 1])


def q_isin_states(tables: dict[str, Table],
                  states: tuple = ("TN", "CA")) -> Table:
    """Revenue for stores in an IN-list of states (SQL IN shape)."""
    ss, store = tables["store_sales"], tables["store"]
    m = isin(store[_col(STORE_COLS, "s_state")], list(states))
    store_f = apply_boolean_mask(store, m)
    j = inner_join(ss, store_f, _col(SS_COLS, "ss_store_sk"),
                   _col(STORE_COLS, "s_store_sk"))
    return _group_sum(j, SS_COLS + STORE_COLS, ["s_state"],
                      "ss_ext_sales_price")


QUERIES = {"q3": q3, "q42": q42, "q52": q52, "q55": q55,
           "q_state_rollup": q_state_rollup, "q7": q7, "q19": q19,
           "q62": q62, "q52_topn": q52_topn, "q65": q65,
           "q_store_counts": q_store_counts,
           "q67_rank": q67_rank, "q_like_brands": q_like_brands,
           "q_union_channels": q_union_channels, "q_lag_growth": q_lag_growth,
           "q_running_share": q_running_share,
           "q_nunique_items": q_nunique_items, "q_having": q_having,
           "q_case_when": q_case_when, "q_distinct_pairs": q_distinct_pairs,
           "q_isin_states": q_isin_states}


def run_all(files: dict[str, bytes]) -> dict[str, Table]:
    tables = load_tables(files)
    return {name: fn(tables) for name, fn in QUERIES.items()
            if name != "q_union_channels" or "web_sales" in tables}
