"""TPC-DS query subset (BASELINE config #3): join + groupby + strings +
decimals end-to-end on the framework's op library.

Five queries shaped after the spec's reporting family (Q3 / Q42 / Q52 /
Q55, plus a store-state rollup exercising decimal aggregation) run against
the mini generator in ``benchmarks/tpcds_data.py``.  Every query is scan
(``parquet.decode`` incl. the Snappy path) → compacting filters → sort-probe
equi-joins → sort-based groupby with string keys (dictionary-encoded,
``ops.strings``) → deterministic key-ordered output, differentially tested
against pandas running the same plan (tests/test_tpcds.py).

The reference reaches this tier through libcudf's join/groupby/strings
(SURVEY §2.9); the TPU formulation is the op library's: no hash tables, no
dynamic shapes outside the two-phase sync points.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..column import Column, Table
from ..ops import (anti_join, apply_boolean_mask, concat_tables, distinct,
                   fill_null, full_outer_join, groupby_aggregate,
                   groupby_cube, groupby_grouping_sets, groupby_nunique,
                   groupby_rollup, inner_join, isin, join_aggregate,
                   left_join, mean, semi_join, slice_table, sort_table, sum_)
from ..ops import strings as S
from ..ops import window as W
from ..parquet import device_scan as decode  # device fast path, host fallback

SS_COLS = ["ss_sold_date_sk", "ss_item_sk", "ss_store_sk", "ss_quantity",
           "ss_sales_price_cents", "ss_list_price_cents",
           "ss_ext_sales_price"]
WS_COLS = ["ws_sold_date_sk", "ws_item_sk", "ws_quantity",
           "ws_ext_sales_price"]
ITEM_COLS = ["i_item_sk", "i_item_id", "i_current_price", "i_brand_id",
             "i_brand", "i_category_id", "i_category", "i_manufact_id",
             "i_manager_id"]
DATE_COLS = ["d_date_sk", "d_year", "d_moy"]
STORE_COLS = ["s_store_sk", "s_state"]


def load_tables(files: dict[str, bytes]) -> dict[str, Table]:
    return {
        "store_sales": decode.read_table(files["store_sales"],
                                         columns=SS_COLS),
        "item": decode.read_table(files["item"], columns=ITEM_COLS),
        "date_dim": decode.read_table(files["date_dim"], columns=DATE_COLS),
        "store": decode.read_table(files["store"], columns=STORE_COLS),
        **({"web_sales": decode.read_table(files["web_sales"],
                                           columns=WS_COLS)}
           if "web_sales" in files else {}),
    }


def _eq_scalar_mask(col: Column, value) -> "np.ndarray":
    if col.dtype.id == T.TypeId.STRING:
        b = S.equal_to_scalar(col, value)
        m = b.data.astype(bool)
        return m if b.validity is None else (m & b.validity)
    m = col.values() == value
    return m if col.validity is None else (m & col.validity)


def _col(cols: list[str], name: str) -> int:
    return cols.index(name)


def _range_mask(col: Column, lo=None, hi=None, hi_strict: bool = False):
    """lo <= col <= hi (either bound optional; ``hi_strict`` makes the
    upper bound exclusive), null-safe like ``_eq_scalar_mask`` — keeps the
    validity AND in one place."""
    m = None
    cvals = col.values()
    if lo is not None:
        m = cvals >= lo
    if hi is not None:
        hm = (cvals < hi) if hi_strict else (cvals <= hi)
        m = hm if m is None else (m & hm)
    if col.validity is not None:
        m = col.validity if m is None else (m & col.validity)
    return m


def _group_sum(joined: Table, cols: list[str], key_names: list[str],
               value_name: str) -> Table:
    """Shared tail of the reporting queries: GROUP BY keys, SUM(value),
    deterministic key order.  ``cols`` is the joined column-name list
    (inner_join's left ++ right contract)."""
    out = groupby_aggregate(
        joined, [cols.index(k) for k in key_names],
        [(cols.index(value_name), "sum")])
    return sort_table(out, list(range(len(key_names))))


def _join_group_sum(lt: Table, rt: Table, left_on: int, right_on: int,
                    cols: list[str], key_names: list[str],
                    value_name: str) -> Table:
    """Fused final join + GROUP BY keys, SUM(value) — the
    ``join(...).groupby(...)`` tail executed through
    ``ops.join_aggregate`` (no pair materialization).  ``cols`` names the
    joined (left ++ right) schema, same contract as :func:`_group_sum`."""
    out = join_aggregate(
        lt, rt, left_on, right_on, [cols.index(k) for k in key_names],
        [(cols.index(value_name), "sum")])
    return sort_table(out, list(range(len(key_names))))


def q3(tables: dict[str, Table], manufact_id: int = 436,
       moy: int = 11) -> Table:
    """SELECT d_year, i_brand_id, i_brand, sum(ss_ext_sales_price)
    FROM store_sales ⋈ item ⋈ date_dim
    WHERE i_manufact_id = ? AND d_moy = ?
    GROUP BY d_year, i_brand_id, i_brand ORDER BY keys."""
    ss, item, dd = tables["store_sales"], tables["item"], tables["date_dim"]
    item_f = apply_boolean_mask(
        item, _eq_scalar_mask(item[_col(ITEM_COLS, "i_manufact_id")],
                              manufact_id))
    dd_f = apply_boolean_mask(
        dd, _eq_scalar_mask(dd[_col(DATE_COLS, "d_moy")], moy))
    j1 = inner_join(ss, item_f, _col(SS_COLS, "ss_item_sk"),
                    _col(ITEM_COLS, "i_item_sk"))
    # j1 columns: SS_COLS ++ ITEM_COLS; the final join + groupby fuse
    return _join_group_sum(j1, dd_f, _col(SS_COLS, "ss_sold_date_sk"),
                           _col(DATE_COLS, "d_date_sk"),
                           SS_COLS + ITEM_COLS + DATE_COLS,
                           ["d_year", "i_brand_id", "i_brand"],
                           "ss_ext_sales_price")


def q42(tables: dict[str, Table], manager_id: int = 1, year: int = 2000,
        moy: int = 11) -> Table:
    """GROUP BY d_year, i_category_id, i_category with manager/date
    predicates (Q42 shape)."""
    ss, item, dd = tables["store_sales"], tables["item"], tables["date_dim"]
    item_f = apply_boolean_mask(
        item, _eq_scalar_mask(item[_col(ITEM_COLS, "i_manager_id")],
                              manager_id))
    dd_mask = (_eq_scalar_mask(dd[_col(DATE_COLS, "d_moy")], moy)
               & _eq_scalar_mask(dd[_col(DATE_COLS, "d_year")], year))
    dd_f = apply_boolean_mask(dd, dd_mask)
    j1 = inner_join(ss, item_f, _col(SS_COLS, "ss_item_sk"),
                    _col(ITEM_COLS, "i_item_sk"))
    return _join_group_sum(j1, dd_f, _col(SS_COLS, "ss_sold_date_sk"),
                           _col(DATE_COLS, "d_date_sk"),
                           SS_COLS + ITEM_COLS + DATE_COLS,
                           ["d_year", "i_category_id", "i_category"],
                           "ss_ext_sales_price")


def q52(tables: dict[str, Table], moy: int = 12, year: int = 2001) -> Table:
    """GROUP BY d_year, i_brand_id, i_brand for one month (Q52 shape)."""
    ss, item, dd = tables["store_sales"], tables["item"], tables["date_dim"]
    dd_mask = (_eq_scalar_mask(dd[_col(DATE_COLS, "d_moy")], moy)
               & _eq_scalar_mask(dd[_col(DATE_COLS, "d_year")], year))
    dd_f = apply_boolean_mask(dd, dd_mask)
    j1 = inner_join(ss, dd_f, _col(SS_COLS, "ss_sold_date_sk"),
                    _col(DATE_COLS, "d_date_sk"))
    cols1 = SS_COLS + DATE_COLS
    return _join_group_sum(j1, tables["item"], cols1.index("ss_item_sk"),
                           _col(ITEM_COLS, "i_item_sk"), cols1 + ITEM_COLS,
                           ["d_year", "i_brand_id", "i_brand"],
                           "ss_ext_sales_price")


def q55(tables: dict[str, Table], manager_id: int = 28) -> Table:
    """GROUP BY i_brand_id, i_brand for one manager (Q55 shape)."""
    ss, item = tables["store_sales"], tables["item"]
    item_f = apply_boolean_mask(
        item, _eq_scalar_mask(item[_col(ITEM_COLS, "i_manager_id")],
                              manager_id))
    return _join_group_sum(ss, item_f, _col(SS_COLS, "ss_item_sk"),
                           _col(ITEM_COLS, "i_item_sk"),
                           SS_COLS + ITEM_COLS,
                           ["i_brand_id", "i_brand"], "ss_ext_sales_price")


def q_state_rollup(tables: dict[str, Table], state: str = "TN") -> Table:
    """Store-state rollup with decimal aggregation: the s_state string
    predicate + decimal64(-2) sales-price sum and quantity mean."""
    ss, store = tables["store_sales"], tables["store"]
    store_f = apply_boolean_mask(
        store, _eq_scalar_mask(store[_col(STORE_COLS, "s_state")],
                               state))
    j1 = inner_join(ss, store_f, _col(SS_COLS, "ss_store_sk"),
                    _col(STORE_COLS, "s_store_sk"))
    cols = SS_COLS + STORE_COLS
    # the cents column IS the unscaled decimal payload — reinterpret as
    # decimal64(scale -2) (RowConversion.java:114-118 representation);
    # sum keeps the scale
    price_i = cols.index("ss_sales_price_cents")
    work = list(j1.columns)
    work[price_i] = Column(T.decimal64(-2), j1[price_i].data,
                           validity=j1[price_i].validity)
    out = groupby_aggregate(
        Table(work), [cols.index("s_state")],
        [(price_i, "sum"), (cols.index("ss_quantity"), "mean"),
         (cols.index("ss_quantity"), "count")])
    return sort_table(out, [0])


def q7(tables: dict[str, Table], year: int = 2000) -> Table:
    """SELECT i_item_id, avg(ss_quantity), avg(ss_list_price),
    avg(ss_sales_price) FROM ss ⋈ item ⋈ date WHERE d_year = ?
    GROUP BY i_item_id ORDER BY i_item_id (Q7 shape: multi-mean)."""
    ss, item, dd = tables["store_sales"], tables["item"], tables["date_dim"]
    dd_f = apply_boolean_mask(
        dd, _eq_scalar_mask(dd[_col(DATE_COLS, "d_year")], year))
    j1 = inner_join(ss, dd_f, _col(SS_COLS, "ss_sold_date_sk"),
                    _col(DATE_COLS, "d_date_sk"))
    cols1 = SS_COLS + DATE_COLS
    cols = cols1 + ITEM_COLS
    out = join_aggregate(
        j1, item, cols1.index("ss_item_sk"), _col(ITEM_COLS, "i_item_sk"),
        [cols.index("i_item_id")],
        [(cols.index("ss_quantity"), "mean"),
         (cols.index("ss_list_price_cents"), "mean"),
         (cols.index("ss_sales_price_cents"), "mean")])
    return sort_table(out, [0])


def q19(tables: dict[str, Table], year: int = 1999, moy: int = 11,
        manager_lo: int = 1, manager_hi: int = 50) -> Table:
    """Brand revenue for a manager-id RANGE in one month (Q19 shape:
    range predicate + 3-key groupby)."""
    ss, item, dd = tables["store_sales"], tables["item"], tables["date_dim"]
    item_f = apply_boolean_mask(
        item, _range_mask(item[_col(ITEM_COLS, "i_manager_id")],
                          manager_lo, manager_hi))
    dd_mask = (_eq_scalar_mask(dd[_col(DATE_COLS, "d_moy")], moy)
               & _eq_scalar_mask(dd[_col(DATE_COLS, "d_year")], year))
    dd_f = apply_boolean_mask(dd, dd_mask)
    j1 = inner_join(ss, item_f, _col(SS_COLS, "ss_item_sk"),
                    _col(ITEM_COLS, "i_item_sk"))
    return _join_group_sum(j1, dd_f, _col(SS_COLS, "ss_sold_date_sk"),
                           _col(DATE_COLS, "d_date_sk"),
                           SS_COLS + ITEM_COLS + DATE_COLS,
                           ["i_brand_id", "i_brand", "i_manufact_id"],
                           "ss_ext_sales_price")


def q62(tables: dict[str, Table], year: int = 2000, qty_lo: int = 10,
        qty_hi: int = 60) -> Table:
    """Sales counts per month for a quantity band (Q62/Q96 count shape)."""
    ss, dd = tables["store_sales"], tables["date_dim"]
    ss_f = apply_boolean_mask(
        ss, _range_mask(ss[_col(SS_COLS, "ss_quantity")], qty_lo, qty_hi))
    dd_f = apply_boolean_mask(
        dd, _eq_scalar_mask(dd[_col(DATE_COLS, "d_year")], year))
    cols = SS_COLS + DATE_COLS
    out = join_aggregate(ss_f, dd_f, _col(SS_COLS, "ss_sold_date_sk"),
                         _col(DATE_COLS, "d_date_sk"), [cols.index("d_moy")],
                         [(cols.index("ss_quantity"), "count")])
    return sort_table(out, [0])


def q52_topn(tables: dict[str, Table], moy: int = 12, year: int = 2001,
             n: int = 10) -> Table:
    """Q52 with its ORDER BY sum DESC LIMIT: descending sort on the
    aggregate + slice (the op library's cudf::slice analog)."""
    out = q52(tables, moy=moy, year=year)
    # columns: d_year, i_brand_id, i_brand, sum — order by sum desc then
    # brand id asc for a deterministic tie-break
    ranked = sort_table(out, [3, 1], ascending=[False, True])
    return slice_table(ranked, 0, n)


def q65(tables: dict[str, Table], frac: float = 0.9) -> Table:
    """Brands whose revenue is below ``frac`` × the mean brand revenue
    (Q65 shape: aggregate, then compare each group against a global
    aggregate of the aggregate)."""
    ss, item = tables["store_sales"], tables["item"]
    cols = SS_COLS + ITEM_COLS
    rev = join_aggregate(ss, item, _col(SS_COLS, "ss_item_sk"),
                         _col(ITEM_COLS, "i_item_sk"),
                         [cols.index("i_brand_id")],
                         [(cols.index("ss_ext_sales_price"), "sum")])
    # device scalar — a host pull here would both cost a sync and break
    # whole-query tracing (models/compiled.py); the comparison broadcasts
    threshold = mean(rev[1]) * frac
    return sort_table(
        apply_boolean_mask(rev, _range_mask(rev[1], hi=threshold,
                                            hi_strict=True)), [0])


def q_store_counts(tables: dict[str, Table]) -> Table:
    """Per-store sale counts INCLUDING stores with no sales (left join →
    count over a nullable column; Spark's LEFT OUTER + COUNT semantics) —
    the left-join→groupby tail runs fused through ``ops.join_aggregate``."""
    ss, store = tables["store_sales"], tables["store"]
    cols = STORE_COLS + SS_COLS
    out = join_aggregate(
        store, ss, _col(STORE_COLS, "s_store_sk"),
        _col(SS_COLS, "ss_store_sk"),
        [cols.index("s_store_sk"), cols.index("s_state")],
        [(cols.index("ss_item_sk"), "count")], how="left")
    return sort_table(out, [0])


def q67_rank(tables: dict[str, Table], top_n: int = 3) -> Table:
    """Top-N brands per category by revenue — the Q67 window shape:
    aggregate, then RANK() OVER (PARTITION BY category ORDER BY sum DESC)
    and keep rank <= N."""
    ss, item = tables["store_sales"], tables["item"]
    j = inner_join(ss, item, _col(SS_COLS, "ss_item_sk"),
                   _col(ITEM_COLS, "i_item_sk"))
    cols = SS_COLS + ITEM_COLS
    rev = groupby_aggregate(
        j, [cols.index("i_category"), cols.index("i_brand_id")],
        [(cols.index("ss_ext_sales_price"), "sum")])
    # rev: [i_category, i_brand_id, sum]
    spec = W.WindowSpec(rev, partition_by=[0], order_by_keys=[2, 1],
                        ascending=[False, True])
    rk = W.rank(spec, [2, 1])
    keep = rk.values() <= top_n
    out = apply_boolean_mask(Table(list(rev.columns) + [rk]), keep)
    return sort_table(out, [0, 3, 1])


def q_like_brands(tables: dict[str, Table], pat: str = "#1",
                  cat_prefix: str = "S") -> Table:
    """LIKE/substring-heavy predicate family (Q45/Q23 spirit): revenue of
    items whose brand CONTAINS ``pat`` and whose category STARTS WITH
    ``cat_prefix`` (via substring equality), grouped by category."""
    ss, item = tables["store_sales"], tables["item"]
    brand_has = S.contains(item[_col(ITEM_COLS, "i_brand")], pat)
    cat_ok = S.starts_with(item[_col(ITEM_COLS, "i_category")], cat_prefix)
    m = (brand_has.data.astype(bool) & cat_ok.data.astype(bool))
    item_f = apply_boolean_mask(item, m)
    return _join_group_sum(ss, item_f, _col(SS_COLS, "ss_item_sk"),
                           _col(ITEM_COLS, "i_item_sk"),
                           SS_COLS + ITEM_COLS, ["i_category"],
                           "ss_ext_sales_price")


def q_union_channels(tables: dict[str, Table]) -> Table:
    """Multi-fact UNION ALL (Q71/Q76 shape): store + web revenue per
    category — both facts projected to a common (item_sk, price) schema,
    concatenated, then joined and grouped."""
    ss, ws, item = (tables["store_sales"], tables["web_sales"],
                    tables["item"])
    common = ["item_sk", "price"]
    part_s = Table([ss[_col(SS_COLS, "ss_item_sk")],
                    ss[_col(SS_COLS, "ss_ext_sales_price")]])
    part_w = Table([ws[_col(WS_COLS, "ws_item_sk")],
                    ws[_col(WS_COLS, "ws_ext_sales_price")]])
    both = concat_tables([part_s, part_w])
    return _join_group_sum(both, item, 0, _col(ITEM_COLS, "i_item_sk"),
                           common + ITEM_COLS, ["i_category"], "price")


def q_lag_growth(tables: dict[str, Table]) -> Table:
    """Month-over-month revenue delta per store (window LAG shape):
    aggregate per (store, year, month), then value - LAG(value) within the
    store partition ordered by (year, month)."""
    ss, dd = tables["store_sales"], tables["date_dim"]
    j = inner_join(ss, dd, _col(SS_COLS, "ss_sold_date_sk"),
                   _col(DATE_COLS, "d_date_sk"))
    cols = SS_COLS + DATE_COLS
    rev = groupby_aggregate(
        j, [cols.index("ss_store_sk"), cols.index("d_year"),
            cols.index("d_moy")],
        [(cols.index("ss_ext_sales_price"), "sum")])
    # rev: [store, year, moy, sum]
    spec = W.WindowSpec(rev, partition_by=[0], order_by_keys=[1, 2])
    prev = W.lag(spec, 3, 1)
    pv = jnp.where(prev.validity_or_true(), prev.values(), 0.0)
    delta = Column.from_values(T.float64, rev[3].values() - pv,
                               validity=prev.validity)
    out = Table(list(rev.columns) + [delta])
    return sort_table(out, [0, 1, 2])


def q_running_share(tables: dict[str, Table], year: int = 2000) -> Table:
    """Cumulative revenue per store across months (window running-sum
    shape, Q47 spirit)."""
    ss, dd = tables["store_sales"], tables["date_dim"]
    dd_f = apply_boolean_mask(
        dd, _eq_scalar_mask(dd[_col(DATE_COLS, "d_year")], year))
    j = inner_join(ss, dd_f, _col(SS_COLS, "ss_sold_date_sk"),
                   _col(DATE_COLS, "d_date_sk"))
    cols = SS_COLS + DATE_COLS
    rev = groupby_aggregate(
        j, [cols.index("ss_store_sk"), cols.index("d_moy")],
        [(cols.index("ss_ext_sales_price"), "sum")])
    spec = W.WindowSpec(rev, partition_by=[0], order_by_keys=[1])
    cum = W.running_sum(spec, 2)
    return sort_table(Table(list(rev.columns) + [cum]), [0, 1])


def q_nunique_items(tables: dict[str, Table]) -> Table:
    """COUNT(DISTINCT item) per store (Q14-family distinct-count shape)."""
    ss = tables["store_sales"]
    out = groupby_nunique(ss, [_col(SS_COLS, "ss_store_sk")],
                          _col(SS_COLS, "ss_item_sk"))
    return sort_table(out, [0])


def q_having(tables: dict[str, Table], min_total: float = 1000.0) -> Table:
    """GROUP BY brand HAVING SUM(price) > threshold (Q23 HAVING shape):
    aggregate, then filter on the aggregate.

    Deliberately UN-projected: the fused join+aggregate sees all 16 joined
    columns by index but touches only the two the aggregate reads — with
    ``ops.join_aggregate`` the join pairs themselves never materialize
    (pre-fusion, projection happened structurally via ``LazyColumn``
    deferral; the multi-GB string gathers that used to OOM the worker at
    SF1 are likewise never issued).
    """
    ss, item = tables["store_sales"], tables["item"]
    cols = SS_COLS + ITEM_COLS
    rev = join_aggregate(ss, item, _col(SS_COLS, "ss_item_sk"),
                         _col(ITEM_COLS, "i_item_sk"),
                         [cols.index("i_brand_id")],
                         [(cols.index("ss_ext_sales_price"), "sum")])
    keep = rev[1].values() > min_total
    return sort_table(apply_boolean_mask(rev, keep), [0])


def q_case_when(tables: dict[str, Table], qty_cut: int = 50) -> Table:
    """Conditional aggregation (Q34/CASE WHEN shape): per category, revenue
    from bulk rows (qty > cut) vs retail rows, in one pass via two masked
    value columns."""
    ss, item = tables["store_sales"], tables["item"]
    j = inner_join(ss, item, _col(SS_COLS, "ss_item_sk"),
                   _col(ITEM_COLS, "i_item_sk"))
    cols = SS_COLS + ITEM_COLS
    qcol = j[cols.index("ss_quantity")]
    pcol = j[cols.index("ss_ext_sales_price")]
    # SQL semantics: NULL qty fails the WHEN (ELSE branch); SUM skips NULL
    # prices (they contribute 0 to either branch)
    price = jnp.where(pcol.validity_or_true(), pcol.values(), 0.0)
    bulk = qcol.validity_or_true() & (qcol.data > qty_cut)
    cb = Column.from_values(T.float64, jnp.where(bulk, price, 0.0))
    cr = Column.from_values(T.float64, jnp.where(bulk, 0.0, price))
    work = Table(list(j.columns) + [cb, cr])
    out = groupby_aggregate(
        work, [cols.index("i_category")],
        [(len(cols), "sum"), (len(cols) + 1, "sum")])
    return sort_table(out, [0])


def q_distinct_pairs(tables: dict[str, Table]) -> Table:
    """DISTINCT (brand_id, category_id) pairs (dropDuplicates shape)."""
    item = tables["item"]
    pairs = Table([item[_col(ITEM_COLS, "i_brand_id")],
                   item[_col(ITEM_COLS, "i_category_id")]])
    return sort_table(distinct(pairs), [0, 1])


def q_isin_states(tables: dict[str, Table],
                  states: tuple = ("TN", "CA")) -> Table:
    """Revenue for stores in an IN-list of states (SQL IN shape)."""
    ss, store = tables["store_sales"], tables["store"]
    m = isin(store[_col(STORE_COLS, "s_state")], list(states))
    store_f = apply_boolean_mask(store, m)
    return _join_group_sum(ss, store_f, _col(SS_COLS, "ss_store_sk"),
                           _col(STORE_COLS, "s_store_sk"),
                           SS_COLS + STORE_COLS, ["s_state"],
                           "ss_ext_sales_price")


# ---------------------------------------------------------------------------
# round-4 breadth: rollup / grouping sets / cube, multi-fact outer joins,
# disjunctive bands, semi/anti, selection aggregates, window dedup
# ---------------------------------------------------------------------------

def q36_rollup(tables: dict[str, Table]) -> Table:
    """ROLLUP(i_category, i_brand) revenue (Q36 shape): per-(category,
    brand) sums, per-category subtotals, and the grand total, with Spark's
    grouping_id in the last column."""
    ss, item = tables["store_sales"], tables["item"]
    j = inner_join(ss, item, _col(SS_COLS, "ss_item_sk"),
                   _col(ITEM_COLS, "i_item_sk"))
    cols = SS_COLS + ITEM_COLS
    out = groupby_rollup(
        j, [cols.index("i_category"), cols.index("i_brand")],
        [(cols.index("ss_ext_sales_price"), "sum")])
    # [i_category, i_brand, sum, grouping_id] — detail rows first, then
    # subtotals, then the grand total, keys ordered within each level
    return sort_table(out, [3, 0, 1])


def q86_rollup(tables: dict[str, Table]) -> Table:
    """ROLLUP(d_year, d_moy) revenue (Q86 shape: time-hierarchy rollup)."""
    ss, dd = tables["store_sales"], tables["date_dim"]
    j = inner_join(ss, dd, _col(SS_COLS, "ss_sold_date_sk"),
                   _col(DATE_COLS, "d_date_sk"))
    cols = SS_COLS + DATE_COLS
    out = groupby_rollup(
        j, [cols.index("d_year"), cols.index("d_moy")],
        [(cols.index("ss_ext_sales_price"), "sum")])
    return sort_table(out, [3, 0, 1])


def q27_cube(tables: dict[str, Table]) -> Table:
    """CUBE(i_category, s_state) average quantity (Q27 shape: cube over
    item × store geography)."""
    ss, item, store = (tables["store_sales"], tables["item"],
                       tables["store"])
    j1 = inner_join(ss, item, _col(SS_COLS, "ss_item_sk"),
                    _col(ITEM_COLS, "i_item_sk"))
    cols1 = SS_COLS + ITEM_COLS
    j2 = inner_join(j1, store, cols1.index("ss_store_sk"),
                    _col(STORE_COLS, "s_store_sk"))
    cols = cols1 + STORE_COLS
    out = groupby_cube(
        j2, [cols.index("i_category"), cols.index("s_state")],
        [(cols.index("ss_quantity"), "mean"),
         (cols.index("ss_ext_sales_price"), "sum")])
    return sort_table(out, [4, 0, 1])


def q5_grouping_sets(tables: dict[str, Table]) -> Table:
    """Channel roll-report (Q5 shape): store + web revenue unioned with a
    channel tag, GROUPING SETS ((channel, category), (channel), ())."""
    ss, ws, item = (tables["store_sales"], tables["web_sales"],
                    tables["item"])
    part_s = Table([ss[_col(SS_COLS, "ss_item_sk")],
                    ss[_col(SS_COLS, "ss_ext_sales_price")],
                    Column(T.int32,
                           jnp.zeros(ss.num_rows, jnp.int32))])
    part_w = Table([ws[_col(WS_COLS, "ws_item_sk")],
                    ws[_col(WS_COLS, "ws_ext_sales_price")],
                    Column(T.int32,
                           jnp.ones(ws.num_rows, jnp.int32))])
    both = concat_tables([part_s, part_w])
    j = inner_join(both, item, 0, _col(ITEM_COLS, "i_item_sk"))
    cols = ["item_sk", "price", "channel"] + ITEM_COLS
    out = groupby_grouping_sets(
        j, [cols.index("channel"), cols.index("i_category")],
        [[0, 1], [0], []], [(cols.index("price"), "sum")])
    return sort_table(out, [3, 0, 1])


def q78_outer(tables: dict[str, Table]) -> Table:
    """Multi-fact FULL OUTER join (Q78 shape): per-item store revenue vs
    web revenue, keeping items that sold in either channel; missing-side
    revenue coalesces to 0."""
    ss, ws = tables["store_sales"], tables["web_sales"]
    s_rev = groupby_aggregate(ss, [_col(SS_COLS, "ss_item_sk")],
                              [(_col(SS_COLS, "ss_ext_sales_price"), "sum")])
    w_rev = groupby_aggregate(ws, [_col(WS_COLS, "ws_item_sk")],
                              [(_col(WS_COLS, "ws_ext_sales_price"), "sum")])
    j = full_outer_join(s_rev, w_rev, 0, 0)
    # [s_item, s_sum, w_item, w_sum] — coalesce(s_item, w_item), zero-fill
    # revenue; the validity must be read BEFORE any fill
    left_valid = j[0].validity_or_true()
    key = Column(j[0].dtype,
                 jnp.where(left_valid, j[0].data, j[2].data))
    out = Table([key, fill_null(j[1], 0.0), fill_null(j[3], 0.0)])
    return sort_table(out, [0])


def q25_two_fact(tables: dict[str, Table], year: int = 2000) -> Table:
    """Two-fact inner join (Q25 shape): items sold in BOTH channels in one
    year, with each channel's revenue."""
    ss, ws, dd = (tables["store_sales"], tables["web_sales"],
                  tables["date_dim"])
    dd_f = apply_boolean_mask(
        dd, _eq_scalar_mask(dd[_col(DATE_COLS, "d_year")], year))
    js = inner_join(ss, dd_f, _col(SS_COLS, "ss_sold_date_sk"),
                    _col(DATE_COLS, "d_date_sk"))
    jw = inner_join(ws, dd_f, _col(WS_COLS, "ws_sold_date_sk"),
                    _col(DATE_COLS, "d_date_sk"))
    s_rev = groupby_aggregate(
        js, [_col(SS_COLS, "ss_item_sk")],
        [(SS_COLS.index("ss_ext_sales_price"), "sum")])
    w_rev = groupby_aggregate(
        jw, [_col(WS_COLS, "ws_item_sk")],
        [(WS_COLS.index("ws_ext_sales_price"), "sum")])
    j = inner_join(s_rev, w_rev, 0, 0)
    return sort_table(Table([j[0], j[1], j[3]]), [0])


def q_channel_day(tables: dict[str, Table]) -> Table:
    """Per-category store vs web revenue on (item, day) tuples sold in
    BOTH channels — the Q72-style j1→j2 chain: each channel aggregates on
    the (item_sk, sold_date_sk) tuple, the channels join on the 2-column
    key (packed onto the composite dense path by ``join_plan.plan_keys``),
    and the result chains into a fused join+aggregate against item."""
    ss, ws, item = (tables["store_sales"], tables["web_sales"],
                    tables["item"])
    s_rev = groupby_aggregate(
        ss, [_col(SS_COLS, "ss_item_sk"), _col(SS_COLS, "ss_sold_date_sk")],
        [(_col(SS_COLS, "ss_ext_sales_price"), "sum")])
    w_rev = groupby_aggregate(
        ws, [_col(WS_COLS, "ws_item_sk"), _col(WS_COLS, "ws_sold_date_sk")],
        [(_col(WS_COLS, "ws_ext_sales_price"), "sum")])
    j1 = inner_join(s_rev, w_rev, [0, 1], [0, 1])   # 2-key tuple join
    # j1 schema: [item, day, s_sum] ++ [item, day, w_sum]
    work = Table([j1[0], j1[2], j1[5]])
    cols = ["item_sk", "s_sum", "w_sum"] + ITEM_COLS
    out = join_aggregate(
        work, item, 0, _col(ITEM_COLS, "i_item_sk"),
        [cols.index("i_category")],
        [(cols.index("s_sum"), "sum"), (cols.index("w_sum"), "sum")])
    return sort_table(out, [0])


def q_web_also_qty(tables: dict[str, Table]) -> Table:
    """Store quantity per store restricted to (item, day) tuples that ALSO
    sold on the web — a 2-key composite join whose fused weighted-groupby
    tail never materializes the pairs (the build side is the distinct
    tuple set, so each probe row matches at most once)."""
    ss, ws = tables["store_sales"], tables["web_sales"]
    pairs = distinct(Table([ws[_col(WS_COLS, "ws_item_sk")],
                            ws[_col(WS_COLS, "ws_sold_date_sk")]]))
    cols = SS_COLS + ["wi_item_sk", "wd_date_sk"]
    out = join_aggregate(
        ss, pairs,
        [_col(SS_COLS, "ss_item_sk"), _col(SS_COLS, "ss_sold_date_sk")],
        [0, 1],
        [cols.index("ss_store_sk")], [(cols.index("ss_quantity"), "sum")])
    return sort_table(out, [0])


def q_brand_rev_left(tables: dict[str, Table], manager_id: int = 28) -> Table:
    """Revenue per brand for one manager's items, KEEPING sales of every
    other item as the null-brand group (LEFT OUTER → GROUP BY — Q55's
    left-outer twin) — runs fused through ``ops.join_aggregate`` with
    ``how="left"``: the unique filtered build side means no pair expansion
    and no compaction, unmatched rows just null their brand."""
    ss, item = tables["store_sales"], tables["item"]
    item_f = apply_boolean_mask(
        item, _eq_scalar_mask(item[_col(ITEM_COLS, "i_manager_id")],
                              manager_id))
    cols = SS_COLS + ITEM_COLS
    out = join_aggregate(ss, item_f, _col(SS_COLS, "ss_item_sk"),
                         _col(ITEM_COLS, "i_item_sk"),
                         [cols.index("i_brand_id")],
                         [(cols.index("ss_ext_sales_price"), "sum"),
                          (cols.index("ss_item_sk"), "count")], how="left")
    return sort_table(out, [0])


def q88_counts(tables: dict[str, Table]) -> Table:
    """Multi-band count report (Q88 shape): one row of sale counts in four
    quantity bands — four masked counts in one pass."""
    ss = tables["store_sales"]
    q = ss[_col(SS_COLS, "ss_quantity")]
    qv, val = q.data, q.validity_or_true()
    cols = []
    for lo, hi in [(1, 25), (26, 50), (51, 75), (76, 100)]:
        m = val & (qv >= lo) & (qv <= hi)
        cols.append(Column(T.int64,
                           jnp.sum(m.astype(jnp.int64))[None]))
    return Table(cols)


def q90_ratio(tables: dict[str, Table]) -> Table:
    """Count-ratio report (Q90 shape): first-half vs second-half-of-year
    sale counts and their ratio, one output row."""
    ss, dd = tables["store_sales"], tables["date_dim"]
    j = inner_join(ss, dd, _col(SS_COLS, "ss_sold_date_sk"),
                   _col(DATE_COLS, "d_date_sk"))
    cols = SS_COLS + DATE_COLS
    moy = j[cols.index("d_moy")]
    mv, val = moy.data, moy.validity_or_true()
    am = jnp.sum((val & (mv <= 6)).astype(jnp.int64))
    pm = jnp.sum((val & (mv > 6)).astype(jnp.int64))
    ratio = am.astype(jnp.float64) / jnp.maximum(pm, 1).astype(jnp.float64)
    return Table([Column(T.int64, am[None]), Column(T.int64, pm[None]),
                  Column.from_values(T.float64, ratio[None])])


def q29_minmax(tables: dict[str, Table]) -> Table:
    """Selection-aggregate profile (Q29 shape): min/max/mean quantity per
    brand."""
    ss, item = tables["store_sales"], tables["item"]
    cols = SS_COLS + ITEM_COLS
    qi = cols.index("ss_quantity")
    out = join_aggregate(ss, item, _col(SS_COLS, "ss_item_sk"),
                         _col(ITEM_COLS, "i_item_sk"),
                         [cols.index("i_brand_id")],
                         [(qi, "min"), (qi, "max"), (qi, "mean")])
    return sort_table(out, [0])


def q48_bands(tables: dict[str, Table]) -> Table:
    """Disjunctive band predicate (Q48/Q13 shape): (qty in [1,20] AND
    price < $50) OR (qty in [41,60] AND price > $150), total quantity per
    state."""
    ss, store = tables["store_sales"], tables["store"]
    q = ss[_col(SS_COLS, "ss_quantity")]
    p = ss[_col(SS_COLS, "ss_sales_price_cents")]
    qv, pv = q.data, p.data
    val = q.validity_or_true() & p.validity_or_true()
    m = val & (((qv >= 1) & (qv <= 20) & (pv < 50_00))
               | ((qv >= 41) & (qv <= 60) & (pv > 150_00)))
    ss_f = apply_boolean_mask(ss, m)
    j = inner_join(ss_f, store, _col(SS_COLS, "ss_store_sk"),
                   _col(STORE_COLS, "s_store_sk"))
    cols = SS_COLS + STORE_COLS
    out = groupby_aggregate(j, [cols.index("s_state")],
                            [(cols.index("ss_quantity"), "sum")])
    return sort_table(out, [0])


def q13_avg_bands(tables: dict[str, Table]) -> Table:
    """Per-band averages in one pass (Q13 shape): average sales price in
    three disjoint quantity bands, one output row."""
    ss = tables["store_sales"]
    q = ss[_col(SS_COLS, "ss_quantity")]
    p = ss[_col(SS_COLS, "ss_sales_price_cents")]
    qv = q.data
    val = q.validity_or_true() & p.validity_or_true()
    pc = p.data.astype(jnp.float64)
    cols = []
    for lo, hi in [(1, 33), (34, 66), (67, 100)]:
        m = val & (qv >= lo) & (qv <= hi)
        cnt = jnp.maximum(jnp.sum(m.astype(jnp.int64)), 1)
        avg = jnp.sum(jnp.where(m, pc, 0.0)) / cnt.astype(jnp.float64)
        cols.append(Column.from_values(T.float64, (avg / 100.0)[None]))
    return Table(cols)


def q96_count(tables: dict[str, Table], year: int = 2000,
              qty_min: int = 80) -> Table:
    """Plain filtered count (Q96 shape): high-quantity sales in one year."""
    ss, dd = tables["store_sales"], tables["date_dim"]
    ss_f = apply_boolean_mask(
        ss, _range_mask(ss[_col(SS_COLS, "ss_quantity")], qty_min))
    dd_f = apply_boolean_mask(
        dd, _eq_scalar_mask(dd[_col(DATE_COLS, "d_year")], year))
    j = inner_join(ss_f, dd_f, _col(SS_COLS, "ss_sold_date_sk"),
                   _col(DATE_COLS, "d_date_sk"))
    cols = SS_COLS + DATE_COLS
    qsum = sum_(j[cols.index("ss_quantity")])
    return Table([Column(T.int64, jnp.asarray([j.num_rows], jnp.int64)),
                  Column(T.int64, qsum[None].astype(jnp.int64))])


def q23_semi(tables: dict[str, Table], min_sales: int = 30) -> Table:
    """Frequent-item semi join (Q23 shape): revenue from sales of items
    with more than ``min_sales`` transactions."""
    ss = tables["store_sales"]
    freq = groupby_aggregate(ss, [_col(SS_COLS, "ss_item_sk")],
                             [(_col(SS_COLS, "ss_item_sk"), "count")])
    freq_f = apply_boolean_mask(freq, freq[1].data > min_sales)
    hits = semi_join(ss, freq_f, _col(SS_COLS, "ss_item_sk"), 0)
    total = sum_(hits[_col(SS_COLS, "ss_ext_sales_price")])
    return Table([Column.from_values(T.float64, total[None]),
                  Column(T.int64, jnp.asarray([hits.num_rows], jnp.int64))])


def q16_anti(tables: dict[str, Table]) -> Table:
    """Never-sold anti join (Q16/Q87 shape): items with zero store sales."""
    ss, item = tables["store_sales"], tables["item"]
    unsold = anti_join(item, ss, _col(ITEM_COLS, "i_item_sk"),
                       _col(SS_COLS, "ss_item_sk"))
    return sort_table(
        Table([unsold[_col(ITEM_COLS, "i_item_sk")],
               unsold[_col(ITEM_COLS, "i_manufact_id")]]), [0])


def q_minmax_price(tables: dict[str, Table]) -> Table:
    """Decimal selection aggregates: min/max i_current_price (decimal32)
    per category."""
    item = tables["item"]
    pi = _col(ITEM_COLS, "i_current_price")
    out = groupby_aggregate(item, [_col(ITEM_COLS, "i_category")],
                            [(pi, "min"), (pi, "max")])
    return sort_table(out, [0])


def q_multi_measure(tables: dict[str, Table]) -> Table:
    """Wide measure set per store: quantity sum, decimal sales sum, mean
    list price — one groupby, three measure types."""
    ss = tables["store_sales"]
    price_i = _col(SS_COLS, "ss_sales_price_cents")
    work = list(ss.columns)
    work[price_i] = Column(T.decimal64(-2), ss[price_i].data,
                           validity=ss[price_i].validity)
    out = groupby_aggregate(
        Table(work), [_col(SS_COLS, "ss_store_sk")],
        [(_col(SS_COLS, "ss_quantity"), "sum"), (price_i, "sum"),
         (_col(SS_COLS, "ss_list_price_cents"), "mean")])
    return sort_table(out, [0])


def q_rollup3(tables: dict[str, Table]) -> Table:
    """Three-level ROLLUP(d_year, d_moy, s_state) revenue — the deep
    hierarchy variant."""
    ss, dd, store = (tables["store_sales"], tables["date_dim"],
                     tables["store"])
    j1 = inner_join(ss, dd, _col(SS_COLS, "ss_sold_date_sk"),
                    _col(DATE_COLS, "d_date_sk"))
    cols1 = SS_COLS + DATE_COLS
    j2 = inner_join(j1, store, cols1.index("ss_store_sk"),
                    _col(STORE_COLS, "s_store_sk"))
    cols = cols1 + STORE_COLS
    out = groupby_rollup(
        j2, [cols.index("d_year"), cols.index("d_moy"),
             cols.index("s_state")],
        [(cols.index("ss_ext_sales_price"), "sum")])
    return sort_table(out, [4, 0, 1, 2])


def q_first_last(tables: dict[str, Table]) -> Table:
    """FIRST/LAST by time per item (Q64-family shape): each item's first
    and last sale price when ordered by date."""
    ss = tables["store_sales"]
    srt = sort_table(ss, [_col(SS_COLS, "ss_sold_date_sk")])
    pi = _col(SS_COLS, "ss_sales_price_cents")
    out = groupby_aggregate(srt, [_col(SS_COLS, "ss_item_sk")],
                            [(pi, "first"), (pi, "last")])
    return sort_table(out, [0])


def q_rownum_dedup(tables: dict[str, Table], keep: int = 2) -> Table:
    """ROW_NUMBER dedup (Q67-family): keep each store's ``keep``
    highest-revenue months."""
    ss, dd = tables["store_sales"], tables["date_dim"]
    j = inner_join(ss, dd, _col(SS_COLS, "ss_sold_date_sk"),
                   _col(DATE_COLS, "d_date_sk"))
    cols = SS_COLS + DATE_COLS
    rev = groupby_aggregate(
        j, [cols.index("ss_store_sk"), cols.index("d_moy")],
        [(cols.index("ss_ext_sales_price"), "sum")])
    spec = W.WindowSpec(rev, partition_by=[0], order_by_keys=[2, 1],
                        ascending=[False, True])
    rn = W.row_number(spec)
    out = apply_boolean_mask(Table(list(rev.columns) + [rn]),
                             rn.values() <= keep)
    return sort_table(out, [0, 3])


def q_cross_ratio(tables: dict[str, Table]) -> Table:
    """Channel revenue ratio per category: web revenue / store revenue
    where both channels sold (aggregate-join-aggregate shape)."""
    ss, ws, item = (tables["store_sales"], tables["web_sales"],
                    tables["item"])
    js = inner_join(ss, item, _col(SS_COLS, "ss_item_sk"),
                    _col(ITEM_COLS, "i_item_sk"))
    jw = inner_join(ws, item, _col(WS_COLS, "ws_item_sk"),
                    _col(ITEM_COLS, "i_item_sk"))
    cs = SS_COLS + ITEM_COLS
    cw = WS_COLS + ITEM_COLS
    s_rev = groupby_aggregate(js, [cs.index("i_category")],
                              [(cs.index("ss_ext_sales_price"), "sum")])
    w_rev = groupby_aggregate(jw, [cw.index("i_category")],
                              [(cw.index("ws_ext_sales_price"), "sum")])
    j = inner_join(s_rev, w_rev, 0, 0)
    ratio = Column.from_values(
        T.float64, j[3].values() / j[1].values())
    return sort_table(Table([j[0], j[1], j[3], ratio]), [0])


def q_null_share(tables: dict[str, Table]) -> Table:
    """Null accounting per category (COUNT(*) vs COUNT(col) semantics):
    web sales row count vs non-null price count."""
    ws, item = tables["web_sales"], tables["item"]
    j = inner_join(ws, item, _col(WS_COLS, "ws_item_sk"),
                   _col(ITEM_COLS, "i_item_sk"))
    cols = WS_COLS + ITEM_COLS
    out = groupby_aggregate(
        j, [cols.index("i_category")],
        [(cols.index("ws_item_sk"), "count"),
         (cols.index("ws_ext_sales_price"), "count"),
         (cols.index("ws_ext_sales_price"), "sum")])
    return sort_table(out, [0])


# ---------------------------------------------------------------------------
# round-5 breadth: stddev aggregate, INTERSECT/EXCEPT, dense_rank,
# two-level (aggregate-of-aggregate) groupby
# ---------------------------------------------------------------------------

def q17_stats(tables: dict[str, Table]) -> Table:
    """Quantity dispersion per state (Q17 shape: mean + stddev + count of
    the same measure in one pass)."""
    ss, store = tables["store_sales"], tables["store"]
    j = inner_join(ss, store, _col(SS_COLS, "ss_store_sk"),
                   _col(STORE_COLS, "s_store_sk"))
    cols = SS_COLS + STORE_COLS
    qi = cols.index("ss_quantity")
    out = groupby_aggregate(j, [cols.index("s_state")],
                            [(qi, "mean"), (qi, "std"), (qi, "count")])
    return sort_table(out, [0])


def q8_intersect(tables: dict[str, Table]) -> Table:
    """Categories sold in BOTH channels (INTERSECT shape, Q8/Q38 spirit):
    distinct store categories ∩ distinct web categories via semi join."""
    ss, ws, item = (tables["store_sales"], tables["web_sales"],
                    tables["item"])
    js = inner_join(ss, item, _col(SS_COLS, "ss_item_sk"),
                    _col(ITEM_COLS, "i_item_sk"))
    jw = inner_join(ws, item, _col(WS_COLS, "ws_item_sk"),
                    _col(ITEM_COLS, "i_item_sk"))
    cs = SS_COLS + ITEM_COLS
    cw = WS_COLS + ITEM_COLS
    s_cat = distinct(Table([js[cs.index("i_category_id")]]))
    w_cat = distinct(Table([jw[cw.index("i_category_id")]]))
    both = semi_join(s_cat, w_cat, 0, 0)
    return sort_table(both, [0])


def q87_except(tables: dict[str, Table]) -> Table:
    """Brands sold in store but NEVER on the web (EXCEPT shape, Q87):
    distinct store brands ∖ distinct web brands via anti join."""
    ss, ws, item = (tables["store_sales"], tables["web_sales"],
                    tables["item"])
    js = inner_join(ss, item, _col(SS_COLS, "ss_item_sk"),
                    _col(ITEM_COLS, "i_item_sk"))
    jw = inner_join(ws, item, _col(WS_COLS, "ws_item_sk"),
                    _col(ITEM_COLS, "i_item_sk"))
    cs = SS_COLS + ITEM_COLS
    cw = WS_COLS + ITEM_COLS
    s_b = distinct(Table([js[cs.index("i_brand_id")]]))
    w_b = distinct(Table([jw[cw.index("i_brand_id")]]))
    only = anti_join(s_b, w_b, 0, 0)
    return sort_table(only, [0])


def q_dense_rank_cat(tables: dict[str, Table], top_n: int = 2) -> Table:
    """DENSE_RANK window (Q70 shape): top-N revenue months per category,
    ties share a rank without gaps."""
    ss, item, dd = tables["store_sales"], tables["item"], tables["date_dim"]
    j1 = inner_join(ss, item, _col(SS_COLS, "ss_item_sk"),
                    _col(ITEM_COLS, "i_item_sk"))
    cols1 = SS_COLS + ITEM_COLS
    j2 = inner_join(j1, dd, cols1.index("ss_sold_date_sk"),
                    _col(DATE_COLS, "d_date_sk"))
    cols = cols1 + DATE_COLS
    rev = groupby_aggregate(
        j2, [cols.index("i_category"), cols.index("d_moy")],
        [(cols.index("ss_ext_sales_price"), "sum")])
    spec = W.WindowSpec(rev, partition_by=[0], order_by_keys=[2, 1],
                        ascending=[False, True])
    dr = W.dense_rank(spec, [2])
    out = apply_boolean_mask(Table(list(rev.columns) + [dr]),
                             dr.values() <= top_n)
    return sort_table(out, [0, 3, 1])


def q34_baskets(tables: dict[str, Table], qty_min: int = 60) -> Table:
    """Two-level aggregation (Q34 shape): per store, how many ITEMS have
    a high-quantity sale total — a groupby over a groupby's output."""
    ss = tables["store_sales"]
    per_item = groupby_aggregate(
        ss, [_col(SS_COLS, "ss_store_sk"), _col(SS_COLS, "ss_item_sk")],
        [(_col(SS_COLS, "ss_quantity"), "sum")])
    big = apply_boolean_mask(per_item, per_item[2].data >= qty_min)
    out = groupby_aggregate(big, [0], [(1, "count")])
    return sort_table(out, [0])


QUERIES = {"q3": q3, "q42": q42, "q52": q52, "q55": q55,
           "q_state_rollup": q_state_rollup, "q7": q7, "q19": q19,
           "q62": q62, "q52_topn": q52_topn, "q65": q65,
           "q_store_counts": q_store_counts,
           "q67_rank": q67_rank, "q_like_brands": q_like_brands,
           "q_union_channels": q_union_channels, "q_lag_growth": q_lag_growth,
           "q_running_share": q_running_share,
           "q_nunique_items": q_nunique_items, "q_having": q_having,
           "q_case_when": q_case_when, "q_distinct_pairs": q_distinct_pairs,
           "q_isin_states": q_isin_states,
           # round-4 breadth
           "q36_rollup": q36_rollup, "q86_rollup": q86_rollup,
           "q27_cube": q27_cube, "q5_grouping_sets": q5_grouping_sets,
           "q78_outer": q78_outer, "q25_two_fact": q25_two_fact,
           "q88_counts": q88_counts, "q90_ratio": q90_ratio,
           "q29_minmax": q29_minmax, "q48_bands": q48_bands,
           "q13_avg_bands": q13_avg_bands, "q96_count": q96_count,
           "q23_semi": q23_semi, "q16_anti": q16_anti,
           "q_minmax_price": q_minmax_price,
           "q_multi_measure": q_multi_measure, "q_rollup3": q_rollup3,
           "q_first_last": q_first_last, "q_rownum_dedup": q_rownum_dedup,
           "q_cross_ratio": q_cross_ratio, "q_null_share": q_null_share,
           # round-5 breadth
           "q17_stats": q17_stats, "q8_intersect": q8_intersect,
           "q87_except": q87_except, "q_dense_rank_cat": q_dense_rank_cat,
           "q34_baskets": q34_baskets,
           # round-6: composite multi-key joins + left-outer fusion
           "q_channel_day": q_channel_day, "q_web_also_qty": q_web_also_qty,
           "q_brand_rev_left": q_brand_rev_left}

# queries that read the second fact table (skipped when absent)
_NEEDS_WEB = {"q_union_channels", "q5_grouping_sets", "q78_outer",
              "q25_two_fact", "q_cross_ratio", "q_null_share",
              "q8_intersect", "q87_except", "q_channel_day",
              "q_web_also_qty"}


def run_all(files: dict[str, bytes]) -> dict[str, Table]:
    tables = load_tables(files)
    return {name: fn(tables) for name, fn in QUERIES.items()
            if name not in _NEEDS_WEB or "web_sales" in tables}
