"""TPC-DS query subset (BASELINE config #3): join + groupby + strings +
decimals end-to-end on the framework's op library.

Five queries shaped after the spec's reporting family (Q3 / Q42 / Q52 /
Q55, plus a store-state rollup exercising decimal aggregation) run against
the mini generator in ``benchmarks/tpcds_data.py``.  Every query is scan
(``parquet.decode`` incl. the Snappy path) → compacting filters → sort-probe
equi-joins → sort-based groupby with string keys (dictionary-encoded,
``ops.strings``) → deterministic key-ordered output, differentially tested
against pandas running the same plan (tests/test_tpcds.py).

The reference reaches this tier through libcudf's join/groupby/strings
(SURVEY §2.9); the TPU formulation is the op library's: no hash tables, no
dynamic shapes outside the two-phase sync points.
"""

from __future__ import annotations

import numpy as np

from .. import types as T
from ..column import Column, Table
from ..ops import (apply_boolean_mask, groupby_aggregate, inner_join,
                   sort_table)
from ..ops import strings as S
from ..parquet import decode

SS_COLS = ["ss_sold_date_sk", "ss_item_sk", "ss_store_sk", "ss_quantity",
           "ss_sales_price_cents", "ss_ext_sales_price"]
ITEM_COLS = ["i_item_sk", "i_brand_id", "i_brand", "i_category_id",
             "i_category", "i_manufact_id", "i_manager_id"]
DATE_COLS = ["d_date_sk", "d_year", "d_moy"]
STORE_COLS = ["s_store_sk", "s_state"]


def load_tables(files: dict[str, bytes]) -> dict[str, Table]:
    return {
        "store_sales": decode.read_table(files["store_sales"],
                                         columns=SS_COLS),
        "item": decode.read_table(files["item"], columns=ITEM_COLS),
        "date_dim": decode.read_table(files["date_dim"], columns=DATE_COLS),
        "store": decode.read_table(files["store"], columns=STORE_COLS),
    }


def _eq_scalar_mask(col: Column, value) -> "np.ndarray":
    if col.dtype.id == T.TypeId.STRING:
        b = S.equal_to_scalar(col, value)
        m = b.data.astype(bool)
        return m if b.validity is None else (m & b.validity)
    m = col.data == value
    return m if col.validity is None else (m & col.validity)


def _col(cols: list[str], name: str) -> int:
    return cols.index(name)


def _group_sum(joined: Table, cols: list[str], key_names: list[str],
               value_name: str) -> Table:
    """Shared tail of the reporting queries: GROUP BY keys, SUM(value),
    deterministic key order.  ``cols`` is the joined column-name list
    (inner_join's left ++ right contract)."""
    out = groupby_aggregate(
        joined, [cols.index(k) for k in key_names],
        [(cols.index(value_name), "sum")])
    return sort_table(out, list(range(len(key_names))))


def q3(tables: dict[str, Table], manufact_id: int = 436,
       moy: int = 11) -> Table:
    """SELECT d_year, i_brand_id, i_brand, sum(ss_ext_sales_price)
    FROM store_sales ⋈ item ⋈ date_dim
    WHERE i_manufact_id = ? AND d_moy = ?
    GROUP BY d_year, i_brand_id, i_brand ORDER BY keys."""
    ss, item, dd = tables["store_sales"], tables["item"], tables["date_dim"]
    item_f = apply_boolean_mask(
        item, _eq_scalar_mask(item[_col(ITEM_COLS, "i_manufact_id")],
                              manufact_id))
    dd_f = apply_boolean_mask(
        dd, _eq_scalar_mask(dd[_col(DATE_COLS, "d_moy")], moy))
    j1 = inner_join(ss, item_f, _col(SS_COLS, "ss_item_sk"),
                    _col(ITEM_COLS, "i_item_sk"))
    # j1 columns: SS_COLS ++ ITEM_COLS
    j2 = inner_join(j1, dd_f, _col(SS_COLS, "ss_sold_date_sk"),
                    _col(DATE_COLS, "d_date_sk"))
    return _group_sum(j2, SS_COLS + ITEM_COLS + DATE_COLS,
                      ["d_year", "i_brand_id", "i_brand"],
                      "ss_ext_sales_price")


def q42(tables: dict[str, Table], manager_id: int = 1, year: int = 2000,
        moy: int = 11) -> Table:
    """GROUP BY d_year, i_category_id, i_category with manager/date
    predicates (Q42 shape)."""
    ss, item, dd = tables["store_sales"], tables["item"], tables["date_dim"]
    item_f = apply_boolean_mask(
        item, _eq_scalar_mask(item[_col(ITEM_COLS, "i_manager_id")],
                              manager_id))
    dd_mask = (_eq_scalar_mask(dd[_col(DATE_COLS, "d_moy")], moy)
               & _eq_scalar_mask(dd[_col(DATE_COLS, "d_year")], year))
    dd_f = apply_boolean_mask(dd, dd_mask)
    j1 = inner_join(ss, item_f, _col(SS_COLS, "ss_item_sk"),
                    _col(ITEM_COLS, "i_item_sk"))
    j2 = inner_join(j1, dd_f, _col(SS_COLS, "ss_sold_date_sk"),
                    _col(DATE_COLS, "d_date_sk"))
    return _group_sum(j2, SS_COLS + ITEM_COLS + DATE_COLS,
                      ["d_year", "i_category_id", "i_category"],
                      "ss_ext_sales_price")


def q52(tables: dict[str, Table], moy: int = 12, year: int = 2001) -> Table:
    """GROUP BY d_year, i_brand_id, i_brand for one month (Q52 shape)."""
    ss, item, dd = tables["store_sales"], tables["item"], tables["date_dim"]
    dd_mask = (_eq_scalar_mask(dd[_col(DATE_COLS, "d_moy")], moy)
               & _eq_scalar_mask(dd[_col(DATE_COLS, "d_year")], year))
    dd_f = apply_boolean_mask(dd, dd_mask)
    j1 = inner_join(ss, dd_f, _col(SS_COLS, "ss_sold_date_sk"),
                    _col(DATE_COLS, "d_date_sk"))
    cols1 = SS_COLS + DATE_COLS
    j2 = inner_join(j1, tables["item"], cols1.index("ss_item_sk"),
                    _col(ITEM_COLS, "i_item_sk"))
    return _group_sum(j2, cols1 + ITEM_COLS,
                      ["d_year", "i_brand_id", "i_brand"],
                      "ss_ext_sales_price")


def q55(tables: dict[str, Table], manager_id: int = 28) -> Table:
    """GROUP BY i_brand_id, i_brand for one manager (Q55 shape)."""
    ss, item = tables["store_sales"], tables["item"]
    item_f = apply_boolean_mask(
        item, _eq_scalar_mask(item[_col(ITEM_COLS, "i_manager_id")],
                              manager_id))
    j1 = inner_join(ss, item_f, _col(SS_COLS, "ss_item_sk"),
                    _col(ITEM_COLS, "i_item_sk"))
    return _group_sum(j1, SS_COLS + ITEM_COLS,
                      ["i_brand_id", "i_brand"], "ss_ext_sales_price")


def q_state_rollup(tables: dict[str, Table], state: str = "TN") -> Table:
    """Store-state rollup with decimal aggregation: the s_state string
    predicate + decimal64(-2) sales-price sum and quantity mean."""
    ss, store = tables["store_sales"], tables["store"]
    store_f = apply_boolean_mask(
        store, _eq_scalar_mask(store[_col(STORE_COLS, "s_state")],
                               state))
    j1 = inner_join(ss, store_f, _col(SS_COLS, "ss_store_sk"),
                    _col(STORE_COLS, "s_store_sk"))
    cols = SS_COLS + STORE_COLS
    # the cents column IS the unscaled decimal payload — reinterpret as
    # decimal64(scale -2) (RowConversion.java:114-118 representation);
    # sum keeps the scale
    price_i = cols.index("ss_sales_price_cents")
    work = list(j1.columns)
    work[price_i] = Column(T.decimal64(-2), j1[price_i].data,
                           validity=j1[price_i].validity)
    out = groupby_aggregate(
        Table(work), [cols.index("s_state")],
        [(price_i, "sum"), (cols.index("ss_quantity"), "mean"),
         (cols.index("ss_quantity"), "count")])
    return sort_table(out, [0])


QUERIES = {"q3": q3, "q42": q42, "q52": q52, "q55": q55,
           "q_state_rollup": q_state_rollup}


def run_all(files: dict[str, bytes]) -> dict[str, Table]:
    tables = load_tables(files)
    return {name: fn(tables) for name, fn in QUERIES.items()}
