"""Mortgage ETL pipeline (BASELINE config #5): the string/decimal-cast-heavy
feature-engineering stage of the RAPIDS Spark Mortgage demo, on this
framework's op library.

The reference accelerates this workload through libcudf's string-cast +
join + groupby kernels (SURVEY §2.9; config #5 "string/decimal cast
heavy").  Pipeline, all device-side after decode:

  1. scan raw perf/acq parquet (STRING-typed raw columns)
  2. parse: dates (``strings.to_date``), decimals (``to_decimal``),
     integers (``to_int64``), delinquency codes with unparseable "X" → -1
  3. dictionary-encode the categorical dimensions (seller/state)
  4. per-loan aggregation over performance records: max delinquency, mean
     UPB, record count, first reporting period
  5. join the loan features onto the parsed acquisition table → one
     all-numeric feature row per loan (the XGBoost input shape)
"""

from __future__ import annotations

from .. import types as T
from ..column import Column, Table
from ..ops import (cast, fill_null, groupby_aggregate, inner_join,
                   sort_table)
from ..ops import strings as S
from ..parquet import device_scan as decode  # device fast path, host fallback

PERF_COLS = ["loan_id", "monthly_reporting_period", "current_actual_upb",
             "current_loan_delinquency_status", "servicer_name"]
ACQ_COLS = ["loan_id", "orig_interest_rate", "orig_upb", "orig_date",
            "state", "seller_name"]

# feature-table column order produced by etl()
FEATURE_COLS = ["loan_id", "orig_rate_e4", "orig_upb", "orig_date_days",
                "state_code", "seller_code", "max_delinquency", "mean_upb",
                "num_records", "first_period_days"]


def load_tables(files: dict[str, bytes]) -> dict[str, Table]:
    return {"perf": decode.read_table(files["perf"], columns=PERF_COLS),
            "acq": decode.read_table(files["acq"], columns=ACQ_COLS)}


def _parse_perf(perf: Table) -> Table:
    """Raw performance strings → typed columns (loan_id, period_days,
    upb_cents, delinq)."""
    loan = perf[PERF_COLS.index("loan_id")]
    period = S.to_date(perf[PERF_COLS.index("monthly_reporting_period")],
                       "%m/%d/%Y")
    upb = S.to_decimal(perf[PERF_COLS.index("current_actual_upb")], -2)
    # "X" (unknown) parses to null; the demo maps it to -1 before the max
    delinq = fill_null(
        S.to_int64(perf[PERF_COLS.index("current_loan_delinquency_status")]),
        -1)
    return Table([loan, period, upb, delinq])


def _parse_acq(acq: Table) -> Table:
    """Raw acquisition strings → typed columns + categorical codes."""
    loan = acq[ACQ_COLS.index("loan_id")]
    rate = S.to_decimal(acq[ACQ_COLS.index("orig_interest_rate")], -4)
    upb = S.to_int64(acq[ACQ_COLS.index("orig_upb")])
    odate = S.to_date(acq[ACQ_COLS.index("orig_date")], "%Y-%m-%d")
    state_codes, _ = S.dictionary_encode(acq[ACQ_COLS.index("state")])
    seller = acq[ACQ_COLS.index("seller_name")]
    seller_codes, _ = S.dictionary_encode(seller)
    # null seller → code -1 (the demo's "OTHER/unknown" bucket)
    seller_codes = fill_null(
        Column(seller_codes.dtype, seller_codes.data,
               validity=seller.validity), -1)
    return Table([loan, rate, upb, odate, state_codes, seller_codes])


def etl(files: dict[str, bytes]) -> Table:
    """Full pipeline → feature table (FEATURE_COLS order, sorted by loan)."""
    return etl_tables(load_tables(files))


def etl_tables(tables: dict[str, Table]) -> Table:
    """The decode-free plan over loaded tables — separable so the whole
    string-parse/aggregate/join pipeline compiles to ONE program through
    ``models.compiled.compile_query`` (the per-loan parse syncs that made
    the eager pipeline ~300 s at toy scale collapse into the capture
    tape)."""
    perf = _parse_perf(tables["perf"])
    acq = _parse_acq(tables["acq"])

    # per-loan aggregates over the performance records
    agg = groupby_aggregate(
        perf, [0],
        [(3, "max"),     # max delinquency
         (2, "mean"),    # mean UPB (decimal64(-2) → float64 mean of cents)
         (0, "count"),   # record count
         (1, "min")])    # first reporting period
    # columns: loan_id, max_delinq, mean_upb_cents, count, min_period

    joined = inner_join(acq, agg, 0, 0)
    # acq(6) ++ agg(5): drop the duplicate right-side loan_id.  The mean
    # over the decimal64(-2) UPB column is already value-domain dollars
    # (groupby applies the decimal scale to mean/var/std).
    feats = [joined[i] for i in range(6)] + [joined[i] for i in range(7, 11)]
    out = sort_table(Table(feats), [0])
    return out


def feature_spec():
    """The demo's ETL→ML handoff: every numeric ETL output except the loan
    id feeds the model; the label is "severely delinquent"
    (max_delinquency > 2 — the synthetic generator emits delinquency
    grades 2/3, so >2 is the class split that actually separates).
    The returned spec packs ``etl_tables`` output straight into the
    on-device feature matrix — see ``tools/mortgage_bench.py`` for the
    full parquet→trained-model path."""
    from ..ml.features import Feature, FeatureSpec
    feats = [c for c in FEATURE_COLS
             if c not in ("loan_id", "max_delinquency")]
    return FeatureSpec.of([Feature(c, impute="mean") for c in feats],
                          label="max_delinquency",
                          label_transform=("gt", 2.0))


def feature_matrix(files: dict[str, bytes]):
    """Feature table → dense float32 [n_loans, n_features-1] + loan ids —
    the XGBoost handoff (everything numeric, nulls already absorbed)."""
    import jax.numpy as jnp
    t = etl(files)
    lanes = []
    for c in t.columns[1:]:
        data = cast(c, T.float64).values() if c.dtype.is_decimal else c.values()
        lanes.append(data.astype(jnp.float32))
    return t[0].data, jnp.stack(lanes, axis=1)
