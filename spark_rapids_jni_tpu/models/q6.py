"""TPC-H Q6: the scan → filter → sum revenue pipeline (BASELINE config #2).

    SELECT sum(l_extendedprice * l_discount) AS revenue
    FROM lineitem
    WHERE l_shipdate >= DATE '1994-01-01'
      AND l_shipdate <  DATE '1995-01-01'
      AND l_discount BETWEEN 0.05 AND 0.07
      AND l_quantity < 24

TPU-first shape: the Parquet scan decodes ON DEVICE for the fast-path
column shapes (``parquet.device_scan``: PLAIN bitcast / dictionary gather /
def-level expansion as jitted ops over the raw page bytes; host fallback
otherwise), and the predicate + multiply + masked sum is ONE fused jitted
program — the filter never compacts (``ops.filter.mask_table`` discipline),
so the whole query is a single static-shaped VPU pass over the four columns.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..column import Table

COLUMNS = ["l_quantity", "l_extendedprice", "l_discount", "l_shipdate"]


@jax.jit
def q6_kernel(quantity, extendedprice, discount, shipdate,
              date_lo, date_hi):
    """The fused predicate+aggregate; dates as int32 days since epoch."""
    mask = ((shipdate >= date_lo) & (shipdate < date_hi)
            & (discount >= 0.05 - 1e-9) & (discount <= 0.07 + 1e-9)
            & (quantity < 24))
    revenue = jnp.where(mask, extendedprice * discount, 0.0)
    return jnp.sum(revenue, dtype=jnp.float64), jnp.sum(mask, dtype=jnp.int64)


def run(file_bytes: bytes, date_lo_days: int, date_hi_days: int):
    """Scan a lineitem parquet file and compute Q6 revenue on device."""
    from ..parquet import device_scan
    table = device_scan.scan_table(file_bytes, columns=COLUMNS)
    q, ep, disc, ship = (table[i].values() for i in range(4))
    revenue, matched = q6_kernel(q, ep, disc, ship,
                                 jnp.int32(date_lo_days),
                                 jnp.int32(date_hi_days))
    return float(revenue), int(matched)
