"""TPC-H Q1 pricing summary — the decimal-arithmetic aggregation query.

    SELECT l_returnflag, l_linestatus,
           sum(l_quantity), sum(l_extendedprice),
           sum(l_extendedprice*(1-l_discount))            AS sum_disc_price,
           sum(l_extendedprice*(1-l_discount)*(1+l_tax))  AS sum_charge,
           avg(l_quantity), avg(l_extendedprice), avg(l_discount),
           count(*)
    FROM lineitem WHERE l_shipdate <= ? GROUP BY 1,2 ORDER BY 1,2

Exercises the full decimal path end-to-end: FLBA decimal decode →
decimal64 columns → widening to DECIMAL128 lane pairs → exact 128-bit
products (scale -4 / -6, Spark's result-scale rule) → decimal128
groupby-SUM with two-string-key grouping — all device-side limb
arithmetic, no floats anywhere near the money columns.
"""

from __future__ import annotations

import jax.numpy as jnp

from .. import types as T
from ..column import Column, Table
from ..ops import apply_boolean_mask, decimal128 as d128
from ..ops import groupby_aggregate
from ..parquet import device_scan as decode  # device fast path, host fallback

COLUMNS = ["l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
           "l_discount", "l_tax", "l_shipdate"]


def run(file_bytes: bytes, cutoff_days: int) -> Table:
    """Returns [returnflag, linestatus, sum_qty, sum_base_price,
    sum_disc_price(d128,-4), sum_charge(d128,-6), avg_qty, avg_price,
    avg_disc, count], sorted by the two flags."""
    t = decode.read_table(file_bytes, columns=COLUMNS)
    mask = t.columns[6].data <= cutoff_days
    if t.columns[6].validity is not None:
        mask = mask & t.columns[6].validity
    t = apply_boolean_mask(t, mask)   # WHERE removes rows (Spark semantics)
    flag, status, qty, price, disc, tax, _ = t.columns

    # 1 - discount and 1 + tax as unscaled decimal64 at scale -2
    one_minus_disc = Column(T.decimal64(-2),
                            100 - disc.data.astype(jnp.int64),
                            validity=disc.validity)
    one_plus_tax = Column(T.decimal64(-2),
                          100 + tax.data.astype(jnp.int64),
                          validity=tax.validity)

    # exact decimal products on 128-bit lanes (scales add: -2 + -2 = -4 …)
    price_w = d128.widen(price)
    disc_price = d128.mul(price_w, d128.widen(one_minus_disc))     # scale -4
    charge = d128.mul(disc_price, d128.widen(one_plus_tax))        # scale -6

    work = Table([flag, status, qty, price, disc_price, charge, disc])
    # groupby output is already key-ordered (order-preserving dictionary
    # codes for the string keys) — no final sort needed
    return groupby_aggregate(
        work, [0, 1],
        [(2, "sum"),      # sum_qty
         (3, "sum"),      # sum_base_price  (decimal64, scale kept)
         (4, "sum"),      # sum_disc_price  (decimal128 limb sum)
         (5, "sum"),      # sum_charge      (decimal128 limb sum)
         (2, "mean"),     # avg_qty
         (3, "mean"),     # avg_price (value domain)
         (6, "mean"),     # avg_disc  (value domain)
         (2, "count")])   # count(*)
