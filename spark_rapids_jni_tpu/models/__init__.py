"""Query pipelines — the framework's "model zoo".

The reference framework's unit of deployment is a Spark query plan; these
modules are end-to-end pipelines matching BASELINE.md's staged configs
(q6 = config #2), each a jittable scan→filter→aggregate program over the
columnar op library.
"""

from . import q6  # noqa: F401
