// Native raw-Snappy block decompressor (no framing — the format Parquet
// data pages use).  The reference gets Snappy through libcudf's nvcomp
// integration (SURVEY §2.9; nvcomp ships in the reference jar,
// pom.xml:462-469); here the host staging step runs native so SF-scale
// page decompression is not Python-rate-bound (the pure-Python fallback in
// parquet/snappy.py decodes ~1-5 MB/s; this runs at memcpy-class rates).
//
// Format: little-endian varint uncompressed length, then tagged elements —
// low two tag bits select literal / 1-byte-offset / 2-byte-offset /
// 4-byte-offset copy (public snappy format_description.txt).
//
// Implemented from the format description, hardened for untrusted input:
// every read and write is bounds-checked; overlapping copies advance one
// byte at a time (the format allows offset < length for RLE-style runs).

#include <cstdint>
#include <cstring>

extern "C" {

// Returns the number of bytes written into dst, or a negative error code:
//   -1 truncated/garbled input, -2 dst_len does not match the stream's own
//   uncompressed-length varint, -3 copy offset out of range.
long srjt_snappy_decompress(const unsigned char* src, long src_len,
                            unsigned char* dst, long dst_len) {
  long ip = 0;
  // uncompressed-length varint
  uint64_t expect = 0;
  int shift = 0;
  while (true) {
    if (ip >= src_len || shift > 35) return -1;
    unsigned char b = src[ip++];
    expect |= static_cast<uint64_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) break;
    shift += 7;
  }
  if (static_cast<uint64_t>(dst_len) != expect) return -2;

  long op = 0;
  while (ip < src_len) {
    unsigned char tag = src[ip++];
    unsigned kind = tag & 3u;
    if (kind == 0) {                       // literal
      long len = (tag >> 2) + 1;
      if (len > 60) {
        int extra = len - 60;              // 1..4 length bytes follow
        if (ip + extra > src_len) return -1;
        uint32_t l = 0;
        for (int k = 0; k < extra; ++k) l |= uint32_t(src[ip + k]) << (8 * k);
        ip += extra;
        len = long(l) + 1;
      }
      if (ip + len > src_len || op + len > dst_len) return -1;
      std::memcpy(dst + op, src + ip, size_t(len));
      ip += len;
      op += len;
      continue;
    }
    long len, off;
    if (kind == 1) {                       // copy, 1-byte offset
      if (ip >= src_len) return -1;
      len = ((tag >> 2) & 7) + 4;
      off = (long(tag >> 5) << 8) | src[ip++];
    } else if (kind == 2) {                // copy, 2-byte offset
      if (ip + 2 > src_len) return -1;
      len = (tag >> 2) + 1;
      off = long(src[ip]) | (long(src[ip + 1]) << 8);
      ip += 2;
    } else {                               // copy, 4-byte offset
      if (ip + 4 > src_len) return -1;
      len = (tag >> 2) + 1;
      off = long(src[ip]) | (long(src[ip + 1]) << 8)
          | (long(src[ip + 2]) << 16) | (long(src[ip + 3]) << 24);
      ip += 4;
    }
    if (off <= 0 || off > op) return -3;
    if (op + len > dst_len) return -1;
    if (off >= len) {
      std::memcpy(dst + op, dst + op - off, size_t(len));
      op += len;
    } else {
      // overlapping run: byte-at-a-time (source window re-reads output)
      for (long k = 0; k < len; ++k, ++op) dst[op] = dst[op - off];
    }
  }
  return (op == dst_len) ? op : -1;
}

// PLAIN BYTE_ARRAY page walk: the (4-byte LE length, bytes)* stream's
// offsets are an inherently sequential recurrence (offset[i+1] depends on
// length[offset[i]]), so the walk runs native — the role libcudf's string
// decode plays for the reference (SURVEY §2.9).  Writes n+1 int32 Arrow
// offsets (char positions, length prefixes excluded) and returns the char
// total, or -1 on truncation/overflow.
long srjt_byte_array_offsets(const unsigned char* payload, long size,
                             long n, int32_t* out_offs) {
  // the memcpy below reinterprets the 4-byte little-endian length prefix
  // as a host u32 — refuse to build on a big-endian target rather than
  // silently mis-walking the payload
#if defined(__BYTE_ORDER__) && defined(__ORDER_LITTLE_ENDIAN__)
  static_assert(__BYTE_ORDER__ == __ORDER_LITTLE_ENDIAN__,
                "srjt_byte_array_offsets assumes a little-endian host");
#endif
  long pos = 0;
  long total = 0;
  out_offs[0] = 0;
  for (long i = 0; i < n; ++i) {
    if (pos + 4 > size) return -1;
    uint32_t len;
    std::memcpy(&len, payload + pos, 4);
    pos += 4;
    if (len > static_cast<uint64_t>(size - pos)) return -1;
    pos += len;
    total += len;
    if (total > INT32_MAX) return -1;
    out_offs[i + 1] = static_cast<int32_t>(total);
  }
  return total;
}

}  // extern "C"
