// Host column/table ownership model with a C-ABI handle surface.
//
// The reference gets its column/table object model, handle passing, and
// release protocol from libcudf + its Java bindings (SURVEY §2.9: handles
// unwrapped in RowConversionJni.cpp:27-38, released one by one into a
// jlongArray).  This is the TPU framework's native equivalent: plain host
// (pinned-stageable) buffers with single ownership per handle, the staging
// side of the PJRT device path.
//
// Handle discipline mirrors the reference's: a handle is a raw pointer
// returned as int64; the creator owns it until it is explicitly freed or
// ownership is transferred to a container that documents it.  Tables hold
// shared references so a column handle may outlive the table that used it
// (cudf Java's ColumnVector refcounting analog).

#include <cstdint>
#include <cstring>
#include <memory>
#include <new>
#include <vector>

namespace {

constexpr int32_t kRowAlignment = 8;
constexpr int64_t kMaxBatchBytes = (1LL << 31) - 1;  // row_conversion.cu:64
constexpr int64_t kBatchRowMultiple = 32;            // row_conversion.cu:1504
// Test seam: srjt_debug_set_max_batch_bytes shrinks the limit so the
// oversized-row failure path is exercisable without 2GB allocations.
int64_t g_max_batch_bytes = kMaxBatchBytes;
constexpr int32_t kTypeString = 24;                  // TypeId.STRING (types.py)

inline int64_t round_up(int64_t x, int64_t m) { return (x + m - 1) / m * m; }

// Fixed-width byte size per TypeId (types.py _STORAGE); 0 = variable width,
// -1 = unsupported in a host table.
int32_t type_size(int32_t type_id) {
  switch (type_id) {
    case 1: case 5: case 11: return 1;              // INT8, UINT8, BOOL8
    case 2: case 6: return 2;                       // INT16, UINT16
    case 3: case 7: case 9: case 12: case 17:       // INT32, UINT32, FLOAT32,
    case 22: return 4;                              //  TS_DAYS, DUR_DAYS, DEC32
    case 4: case 8: case 10: return 8;              // INT64, UINT64, FLOAT64
    case 13: case 14: case 15: case 16: return 8;   // timestamps
    case 18: case 19: case 20: case 21: return 8;   // durations
    case 23: return 8;                              // DECIMAL64
    case kTypeString: return 0;
    default: return -1;
  }
}

struct Column {
  int32_t type_id = 0;
  int32_t scale = 0;
  int64_t n_rows = 0;
  std::vector<uint8_t> data;        // fixed payload, or string chars
  std::vector<int32_t> offsets;     // string columns: n_rows+1 Arrow offsets
  std::vector<uint8_t> valid;       // empty = all valid, else n_rows bools

  bool is_string() const { return type_id == kTypeString; }
  int32_t slot_size() const { return is_string() ? 8 : type_size(type_id); }
  int32_t slot_align() const { return is_string() ? 4 : type_size(type_id); }
};

struct Table {
  std::vector<std::shared_ptr<Column>> cols;
  int64_t n_rows = 0;
};

// One ≤2GB JCUDF row batch — the LIST<INT8> column analog
// (row_conversion.cu:1869-1889).
struct RowBatch {
  std::vector<uint8_t> data;
  std::vector<int32_t> offsets;  // per-row, rebased to the batch start
};

struct RowBatches {
  std::vector<RowBatch> batches;
};

struct Layout {
  std::vector<int32_t> starts, sizes;
  std::vector<uint8_t> is_var;
  int32_t validity_offset = 0, fixed_plus_validity = 0, row_size = 0;
  bool fixed_only = true;
};

Layout compute_layout(const Table& t) {
  Layout L;
  int64_t off = 0;
  for (const auto& c : t.cols) {
    off = round_up(off, c->slot_align());
    L.starts.push_back(static_cast<int32_t>(off));
    L.sizes.push_back(c->slot_size());
    L.is_var.push_back(c->is_string() ? 1 : 0);
    if (c->is_string()) L.fixed_only = false;
    off += c->slot_size();
  }
  L.validity_offset = static_cast<int32_t>(off);
  L.fixed_plus_validity =
      L.validity_offset + static_cast<int32_t>((t.cols.size() + 7) / 8);
  L.row_size =
      static_cast<int32_t>(round_up(L.fixed_plus_validity, kRowAlignment));
  return L;
}

void pack_validity(const Table& t, int64_t row, uint8_t* dst) {
  int32_t ncols = static_cast<int32_t>(t.cols.size());
  for (int32_t b = 0; b * 8 < ncols; ++b) {
    uint8_t byte = 0;
    for (int32_t i = 0; i < 8 && b * 8 + i < ncols; ++i) {
      const auto& v = t.cols[b * 8 + i]->valid;
      if (v.empty() || v[row]) byte |= static_cast<uint8_t>(1u << i);
    }
    dst[b] = byte;
  }
}

// Per-row byte size (fixed layouts: constant; strings: data-dependent,
// build_string_row_offsets semantics, row_conversion.cu:216-261).
int64_t row_byte_size(const Table& t, const Layout& L, int64_t r) {
  if (L.fixed_only) return L.row_size;
  int64_t chars = 0;
  for (const auto& c : t.cols) {
    if (c->is_string()) chars += c->offsets[r + 1] - c->offsets[r];
  }
  return round_up(L.fixed_plus_validity + chars, kRowAlignment);
}

// Batch boundaries: scan row sizes, cut before 2GB, boundaries at 32-row
// multiples except the tail (build_batches, row_conversion.cu:1460-1539).
// Returns {} when any single row exceeds the batch limit — same contract as
// the Python engine (layout.build_batches raises ValueError); callers must
// treat an empty result as a failed conversion.
std::vector<int64_t> batch_bounds(const Table& t, const Layout& L) {
  std::vector<int64_t> bounds{0};
  int64_t acc = 0, r = 0;
  while (r < t.n_rows) {
    int64_t size = row_byte_size(t, L, r);
    if (acc + size > g_max_batch_bytes) {
      if (acc == 0) return {};  // one row alone blows the limit: fail
      int64_t cut = r - (r % kBatchRowMultiple);
      if (cut <= bounds.back()) cut = r;
      bounds.push_back(cut);
      acc = 0;
      r = cut;
      continue;
    }
    acc += size;
    ++r;
  }
  bounds.push_back(t.n_rows);
  return bounds;
}

void pack_rows(const Table& t, const Layout& L, int64_t row0, int64_t row1,
               RowBatch* out) {
  int64_t n = row1 - row0;
  out->offsets.resize(n + 1);
  int64_t total = 0;
  for (int64_t r = 0; r < n; ++r) {
    out->offsets[r] = static_cast<int32_t>(total);
    total += row_byte_size(t, L, row0 + r);
  }
  out->offsets[n] = static_cast<int32_t>(total);
  out->data.assign(total, 0);
  int32_t ncols = static_cast<int32_t>(t.cols.size());
  for (int64_t r = 0; r < n; ++r) {
    uint8_t* row = out->data.data() + out->offsets[r];
    uint32_t cursor = static_cast<uint32_t>(L.fixed_plus_validity);
    for (int32_t c = 0; c < ncols; ++c) {
      const Column& col = *t.cols[c];
      if (col.is_string()) {
        uint32_t len =
            static_cast<uint32_t>(col.offsets[row0 + r + 1] -
                                  col.offsets[row0 + r]);
        uint32_t slot[2] = {cursor, len};
        std::memcpy(row + L.starts[c], slot, 8);
        std::memcpy(row + cursor, col.data.data() + col.offsets[row0 + r],
                    len);
        cursor += len;
      } else {
        std::memcpy(row + L.starts[c],
                    col.data.data() + (row0 + r) * L.sizes[c], L.sizes[c]);
      }
    }
    pack_validity(t, row0 + r, row + L.validity_offset);
  }
}

}  // namespace

extern "C" {

// ---- column handles -------------------------------------------------------

void* srjt_column_fixed(int32_t type_id, int32_t scale, int64_t n_rows,
                        const uint8_t* data, const uint8_t* valid) {
  int32_t size = type_size(type_id);
  if (size <= 0 || n_rows < 0) return nullptr;
  auto c = new (std::nothrow) std::shared_ptr<Column>(new Column());
  if (!c) return nullptr;
  (*c)->type_id = type_id;
  (*c)->scale = scale;
  (*c)->n_rows = n_rows;
  (*c)->data.assign(data, data + n_rows * size);
  if (valid) (*c)->valid.assign(valid, valid + n_rows);
  return c;
}

void* srjt_column_string(int64_t n_rows, const int32_t* offsets,
                         const uint8_t* chars, const uint8_t* valid) {
  if (n_rows < 0 || !offsets) return nullptr;
  auto c = new (std::nothrow) std::shared_ptr<Column>(new Column());
  if (!c) return nullptr;
  (*c)->type_id = kTypeString;
  (*c)->n_rows = n_rows;
  (*c)->offsets.assign(offsets, offsets + n_rows + 1);
  (*c)->data.assign(chars, chars + offsets[n_rows]);
  if (valid) (*c)->valid.assign(valid, valid + n_rows);
  return c;
}

int32_t srjt_column_type(void* h) {
  return (*static_cast<std::shared_ptr<Column>*>(h))->type_id;
}
int32_t srjt_column_scale(void* h) {
  return (*static_cast<std::shared_ptr<Column>*>(h))->scale;
}
int64_t srjt_column_rows(void* h) {
  return (*static_cast<std::shared_ptr<Column>*>(h))->n_rows;
}
const uint8_t* srjt_column_data(void* h) {
  return (*static_cast<std::shared_ptr<Column>*>(h))->data.data();
}
int64_t srjt_column_data_size(void* h) {
  return static_cast<int64_t>(
      (*static_cast<std::shared_ptr<Column>*>(h))->data.size());
}
const int32_t* srjt_column_offsets(void* h) {
  auto& c = *static_cast<std::shared_ptr<Column>*>(h);
  return c->offsets.empty() ? nullptr : c->offsets.data();
}
const uint8_t* srjt_column_valid(void* h) {
  auto& c = *static_cast<std::shared_ptr<Column>*>(h);
  return c->valid.empty() ? nullptr : c->valid.data();
}
void srjt_column_free(void* h) {
  delete static_cast<std::shared_ptr<Column>*>(h);
}

// ---- table handles --------------------------------------------------------

// Builds a table sharing the given columns (they remain independently owned
// by their handles — the cudf Java refcount discipline).
void* srjt_table(void* const* col_handles, int32_t ncols) {
  if (ncols <= 0) return nullptr;
  auto t = new (std::nothrow) Table();
  if (!t) return nullptr;
  for (int32_t i = 0; i < ncols; ++i) {
    auto& c = *static_cast<std::shared_ptr<Column>*>(col_handles[i]);
    if (i == 0) t->n_rows = c->n_rows;
    if (c->n_rows != t->n_rows) { delete t; return nullptr; }
    t->cols.push_back(c);
  }
  return t;
}

int64_t srjt_table_rows(void* h) { return static_cast<Table*>(h)->n_rows; }
int32_t srjt_table_cols(void* h) {
  return static_cast<int32_t>(static_cast<Table*>(h)->cols.size());
}
void* srjt_table_column(void* h, int32_t i) {
  // returns a NEW shared handle; caller frees it independently
  return new std::shared_ptr<Column>(static_cast<Table*>(h)->cols[i]);
}
void srjt_table_free(void* h) { delete static_cast<Table*>(h); }

// ---- table-level transcode (the convertToRows/convertFromRows surface) ----

// Table → ≤2GB JCUDF row batches.  Returns a RowBatches handle, null on
// unsupported schema or >1KB fixed rows (RowConversion.java:98-99).
void* srjt_to_rows(void* table_handle) {
  Table& t = *static_cast<Table*>(table_handle);
  Layout L = compute_layout(t);
  if (L.fixed_only && L.row_size > 1024) return nullptr;
  auto out = new (std::nothrow) RowBatches();
  if (!out) return nullptr;
  auto bounds = batch_bounds(t, L);
  if (bounds.size() < 2) {  // oversized single row
    delete out;
    return nullptr;
  }
  for (size_t b = 0; b + 1 < bounds.size(); ++b) {
    out->batches.emplace_back();
    pack_rows(t, L, bounds[b], bounds[b + 1], &out->batches.back());
  }
  return out;
}

int32_t srjt_rows_num_batches(void* h) {
  return static_cast<int32_t>(static_cast<RowBatches*>(h)->batches.size());
}
int64_t srjt_rows_batch_rows(void* h, int32_t b) {
  return static_cast<int64_t>(
      static_cast<RowBatches*>(h)->batches[b].offsets.size()) - 1;
}
const uint8_t* srjt_rows_batch_data(void* h, int32_t b) {
  return static_cast<RowBatches*>(h)->batches[b].data.data();
}
int64_t srjt_rows_batch_size(void* h, int32_t b) {
  return static_cast<int64_t>(
      static_cast<RowBatches*>(h)->batches[b].data.size());
}
const int32_t* srjt_rows_batch_offsets(void* h, int32_t b) {
  return static_cast<RowBatches*>(h)->batches[b].offsets.data();
}
void srjt_rows_free(void* h) { delete static_cast<RowBatches*>(h); }

// Test-only: shrink the batch byte limit (0 restores the default).
void srjt_debug_set_max_batch_bytes(int64_t v) {
  g_max_batch_bytes = v > 0 ? v : kMaxBatchBytes;
}

// Builds a RowBatches handle around caller-provided row bytes (the
// convertFromRows input path: Java hands a LIST<INT8> column's buffers).
void* srjt_rows_import(const uint8_t* data, int64_t data_size,
                       const int32_t* offsets, int64_t n_rows) {
  // Shuffle-received bytes are untrusted: reject non-monotonic / negative /
  // out-of-range offsets before they can drive reads or allocations.
  if (!data || !offsets || n_rows < 0 || data_size < 0) return nullptr;
  if (offsets[0] != 0) return nullptr;
  for (int64_t r = 0; r < n_rows; ++r) {
    if (offsets[r + 1] < offsets[r]) return nullptr;
  }
  if (offsets[n_rows] != data_size) return nullptr;
  auto rb = new (std::nothrow) RowBatches();
  if (!rb) return nullptr;
  rb->batches.emplace_back();
  rb->batches[0].data.assign(data, data + data_size);
  rb->batches[0].offsets.assign(offsets, offsets + n_rows + 1);
  return rb;
}

// Append one more ≤2GB batch to an imported RowBatches handle (the device
// bridge marshals multi-batch conversions back one batch at a time).
// Same untrusted-offset validation as srjt_rows_import; returns 0 on
// rejection.
int32_t srjt_rows_import_append(void* rows_handle, const uint8_t* data,
                                int64_t data_size, const int32_t* offsets,
                                int64_t n_rows) {
  if (!rows_handle || !data || !offsets || n_rows < 0 || data_size < 0)
    return 0;
  if (offsets[0] != 0) return 0;
  for (int64_t r = 0; r < n_rows; ++r) {
    if (offsets[r + 1] < offsets[r]) return 0;
  }
  if (offsets[n_rows] != data_size) return 0;
  RowBatches& rb = *static_cast<RowBatches*>(rows_handle);
  rb.batches.emplace_back();
  rb.batches.back().data.assign(data, data + data_size);
  rb.batches.back().offsets.assign(offsets, offsets + n_rows + 1);
  return 1;
}

// One batch of JCUDF rows → table (exactly one input batch, matching
// convert_from_rows' contract, row_conversion.cu:2124-2139).
void* srjt_from_rows(void* rows_handle, int32_t batch,
                     const int32_t* type_ids, const int32_t* scales,
                     int32_t ncols) {
  RowBatches& rb = *static_cast<RowBatches*>(rows_handle);
  if (batch < 0 || batch >= static_cast<int32_t>(rb.batches.size()))
    return nullptr;
  const RowBatch& B = rb.batches[batch];
  int64_t n = static_cast<int64_t>(B.offsets.size()) - 1;

  auto t = new (std::nothrow) Table();
  if (!t) return nullptr;
  t->n_rows = n;
  for (int32_t c = 0; c < ncols; ++c) {
    auto col = std::make_shared<Column>();
    col->type_id = type_ids[c];
    col->scale = scales ? scales[c] : 0;
    col->n_rows = n;
    if (type_ids[c] != kTypeString && type_size(type_ids[c]) <= 0) {
      delete t;
      return nullptr;
    }
    t->cols.push_back(std::move(col));
  }
  Layout L = compute_layout(*t);

  for (int32_t c = 0; c < ncols; ++c) {
    Column& col = *t->cols[c];
    col.valid.assign(n, 1);
    if (col.is_string()) {
      col.offsets.assign(n + 1, 0);
    } else {
      col.data.resize(n * L.sizes[c]);
    }
  }
  for (int64_t r = 0; r < n; ++r) {
    const uint8_t* row = B.data.data() + B.offsets[r];
    int64_t span = B.offsets[r + 1] - B.offsets[r];
    // Row bytes may be shuffle-received (srjt_rows_import): every row must
    // cover the fixed+validity area, and string slots must stay in-row.
    if (span < L.fixed_plus_validity) {
      delete t;
      return nullptr;
    }
    // JCUDF packs all variable-width chars contiguously after the validity
    // bytes, in column order; enforcing that exact invariant (not just
    // per-slot in-row bounds) rejects overlapping slots, which would
    // otherwise let one crafted row claim its full tail for EVERY string
    // column and amplify the phase-2 allocation ncols-fold.
    int64_t chars_cursor = L.fixed_plus_validity;
    for (int32_t c = 0; c < ncols; ++c) {
      Column& col = *t->cols[c];
      if (col.is_string()) {
        uint32_t slot[2];
        std::memcpy(slot, row + L.starts[c], 8);
        if (slot[0] != chars_cursor ||
            static_cast<int64_t>(slot[0]) + slot[1] > span) {
          delete t;
          return nullptr;
        }
        chars_cursor += slot[1];
        int64_t next = static_cast<int64_t>(col.offsets[r]) + slot[1];
        if (next > INT32_MAX) {  // offsets are int32 (2GB column contract)
          delete t;
          return nullptr;
        }
        col.offsets[r + 1] = static_cast<int32_t>(next);
      } else {
        std::memcpy(col.data.data() + r * L.sizes[c], row + L.starts[c],
                    L.sizes[c]);
      }
      col.valid[r] = (row[L.validity_offset + c / 8] >> (c % 8)) & 1;
    }
  }
  // phase 2: gather string chars now that offsets are complete (slots were
  // bounds-checked in phase 1)
  for (int32_t c = 0; c < ncols; ++c) {
    Column& col = *t->cols[c];
    if (!col.is_string()) continue;
    col.data.resize(col.offsets[n]);
    for (int64_t r = 0; r < n; ++r) {
      const uint8_t* row = B.data.data() + B.offsets[r];
      uint32_t slot[2];
      std::memcpy(slot, row + L.starts[c], 8);
      std::memcpy(col.data.data() + col.offsets[r], row + slot[0], slot[1]);
    }
  }
  return t;
}

}  // extern "C"
