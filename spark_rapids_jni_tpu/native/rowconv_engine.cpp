// Host JCUDF row<->column transcode engine (C ABI).
//
// Native-runtime counterpart of the device path in rowconv/convert.py: the
// reference implements this transpose as CUDA kernels orchestrated by host
// C++ (src/main/cpp/src/row_conversion.cu: compute_column_information
// :1331-1370, copy_to_rows :575-693, copy_from_rows :892-993,
// copy_validity_to_rows :710-810, copy_strings_to_rows :827-875); on TPU the
// device engine is XLA, and this C++ engine provides (a) the host staging /
// interchange path a JVM-side caller binds to, and (b) an independent
// differential oracle for the device path (SURVEY §4 differential strategy).
//
// Layout contract (must stay bit-identical to rowconv/layout.py and the
// JCUDF spec in RowConversion.java:40-99):
//   - each fixed-width column slot aligned to its own size; string columns
//     occupy an 8-byte (offset:u32, len:u32) slot aligned to 4
//   - validity bytes appended after the data slots, bit i of byte b = column
//     b*8+i (little-endian within the byte)
//   - string chars appended at the unaligned fixed+validity cursor, in
//     column order; row padded to 8 bytes (JCUDF_ROW_ALIGNMENT)

#include <cstdint>
#include <cstring>

namespace {

constexpr int32_t kRowAlignment = 8;

inline int64_t round_up(int64_t x, int64_t m) { return (x + m - 1) / m * m; }

inline void pack_validity(const uint8_t* const* col_valid, int32_t ncols,
                          int64_t row, uint8_t* dst) {
  for (int32_t b = 0; b * 8 < ncols; ++b) {
    uint8_t byte = 0;
    for (int32_t i = 0; i < 8 && b * 8 + i < ncols; ++i) {
      const uint8_t* v = col_valid[b * 8 + i];
      if (v == nullptr || v[row]) byte |= static_cast<uint8_t>(1u << i);
    }
    dst[b] = byte;
  }
}

}  // namespace

extern "C" {

// Row layout from per-column (slot size, slot alignment).  Returns 0 on
// success, -1 on bad input.  Mirrors compute_row_layout (layout.py) /
// compute_column_information (row_conversion.cu:1331-1370).
int32_t srjt_layout(const int32_t* sizes, const int32_t* aligns, int32_t ncols,
                    int32_t* out_starts, int32_t* out_validity_offset,
                    int32_t* out_fixed_plus_validity, int32_t* out_row_size) {
  if (ncols < 0) return -1;
  int64_t offset = 0;
  for (int32_t i = 0; i < ncols; ++i) {
    if (sizes[i] <= 0 || aligns[i] <= 0) return -1;
    offset = round_up(offset, aligns[i]);
    out_starts[i] = static_cast<int32_t>(offset);
    offset += sizes[i];
  }
  int32_t validity_bytes = (ncols + 7) / 8;
  *out_validity_offset = static_cast<int32_t>(offset);
  *out_fixed_plus_validity = static_cast<int32_t>(offset) + validity_bytes;
  *out_row_size =
      static_cast<int32_t>(round_up(*out_fixed_plus_validity, kRowAlignment));
  return 0;
}

// Fixed-width pack: col_data[i] is n_rows*sizes[i] little-endian bytes;
// col_valid[i] is n_rows bool bytes or null (all valid).  out must hold
// n_rows*row_size bytes; padding bytes are zeroed.
void srjt_pack_fixed(const uint8_t* const* col_data,
                     const uint8_t* const* col_valid, const int32_t* starts,
                     const int32_t* sizes, int32_t ncols, int64_t n_rows,
                     int32_t row_size, int32_t validity_offset, uint8_t* out) {
  std::memset(out, 0, static_cast<size_t>(n_rows) * row_size);
  for (int64_t r = 0; r < n_rows; ++r) {
    uint8_t* row = out + r * row_size;
    for (int32_t c = 0; c < ncols; ++c) {
      std::memcpy(row + starts[c], col_data[c] + r * sizes[c],
                  static_cast<size_t>(sizes[c]));
    }
    pack_validity(col_valid, ncols, r, row + validity_offset);
  }
}

// Inverse of srjt_pack_fixed.  out_data[i] must hold n_rows*sizes[i] bytes;
// out_valid[i] must hold n_rows bool bytes (never null on output).
void srjt_unpack_fixed(const uint8_t* rows, int64_t n_rows, int32_t row_size,
                       const int32_t* starts, const int32_t* sizes,
                       int32_t ncols, int32_t validity_offset,
                       uint8_t* const* out_data, uint8_t* const* out_valid) {
  for (int64_t r = 0; r < n_rows; ++r) {
    const uint8_t* row = rows + r * row_size;
    for (int32_t c = 0; c < ncols; ++c) {
      std::memcpy(out_data[c] + r * sizes[c], row + starts[c],
                  static_cast<size_t>(sizes[c]));
      out_valid[c][r] = (row[validity_offset + c / 8] >> (c % 8)) & 1;
    }
  }
}

// Per-row byte offsets for a table with string columns: fixed+validity plus
// the row's total chars, rounded up to 8 (build_string_row_offsets,
// row_conversion.cu:216-261).  str_offsets[v] is the Arrow int32 [n+1]
// offsets array of variable column v.  Fills out_row_offsets [n+1]; returns
// the total byte size.
int64_t srjt_var_row_offsets(const int32_t* const* str_offsets, int32_t nvar,
                             int64_t n_rows, int32_t fixed_plus_validity,
                             int64_t* out_row_offsets) {
  out_row_offsets[0] = 0;
  for (int64_t r = 0; r < n_rows; ++r) {
    int64_t chars = 0;
    for (int32_t v = 0; v < nvar; ++v) {
      chars += str_offsets[v][r + 1] - str_offsets[v][r];
    }
    int64_t size = round_up(fixed_plus_validity + chars, kRowAlignment);
    out_row_offsets[r + 1] = out_row_offsets[r] + size;
  }
  return out_row_offsets[n_rows];
}

// Variable-width pack (copy_strings_to_rows semantics,
// row_conversion.cu:852-874).  For variable columns, col_data[c] is the
// chars buffer and var_offsets[var_index(c)] its Arrow offsets; is_var[c]
// selects the interpretation.  out must hold row_offsets[n_rows] bytes.
void srjt_pack_var(const uint8_t* const* col_data,
                   const int32_t* const* var_offsets,
                   const uint8_t* const* col_valid, const int32_t* starts,
                   const int32_t* sizes, const uint8_t* is_var, int32_t ncols,
                   int64_t n_rows, const int64_t* row_offsets,
                   int32_t validity_offset, int32_t fixed_plus_validity,
                   uint8_t* out) {
  std::memset(out, 0, static_cast<size_t>(row_offsets[n_rows]));
  for (int64_t r = 0; r < n_rows; ++r) {
    uint8_t* row = out + row_offsets[r];
    uint32_t var_cursor = static_cast<uint32_t>(fixed_plus_validity);
    int32_t vi = 0;
    for (int32_t c = 0; c < ncols; ++c) {
      if (is_var[c]) {
        const int32_t* offs = var_offsets[vi++];
        uint32_t len = static_cast<uint32_t>(offs[r + 1] - offs[r]);
        uint32_t slot[2] = {var_cursor, len};
        std::memcpy(row + starts[c], slot, 8);
        std::memcpy(row + var_cursor, col_data[c] + offs[r], len);
        var_cursor += len;
      } else {
        std::memcpy(row + starts[c], col_data[c] + r * sizes[c],
                    static_cast<size_t>(sizes[c]));
      }
    }
    pack_validity(col_valid, ncols, r, row + validity_offset);
  }
}

// Variable-width unpack, phase 1: fixed slots, validity, and per-string-
// column lengths (written as Arrow offsets after an exclusive scan).
// out_str_offsets[v] must hold n_rows+1 int32s.
void srjt_unpack_var(const uint8_t* rows, const int64_t* row_offsets,
                     int64_t n_rows, const int32_t* starts,
                     const int32_t* sizes, const uint8_t* is_var,
                     int32_t ncols, int32_t validity_offset,
                     uint8_t* const* out_data, int32_t* const* out_str_offsets,
                     uint8_t* const* out_valid) {
  for (int32_t c = 0, vi = 0; c < ncols; ++c) {
    if (is_var[c]) out_str_offsets[vi++][0] = 0;
  }
  for (int64_t r = 0; r < n_rows; ++r) {
    const uint8_t* row = rows + row_offsets[r];
    int32_t vi = 0;
    for (int32_t c = 0; c < ncols; ++c) {
      if (is_var[c]) {
        uint32_t slot[2];
        std::memcpy(slot, row + starts[c], 8);
        int32_t* offs = out_str_offsets[vi++];
        offs[r + 1] = offs[r] + static_cast<int32_t>(slot[1]);
      } else {
        std::memcpy(out_data[c] + r * sizes[c], row + starts[c],
                    static_cast<size_t>(sizes[c]));
      }
      out_valid[c][r] = (row[validity_offset + c / 8] >> (c % 8)) & 1;
    }
  }
}

// Variable-width unpack, phase 2: gather one string column's chars into the
// buffer sized by phase 1's offsets (copy_strings_from_rows,
// row_conversion.cu:1131-1174).  slot_start is the column's (offset,len)
// slot position within the row.
void srjt_gather_chars(const uint8_t* rows, const int64_t* row_offsets,
                       int64_t n_rows, int32_t slot_start,
                       const int32_t* out_offsets, uint8_t* out_chars) {
  for (int64_t r = 0; r < n_rows; ++r) {
    const uint8_t* row = rows + row_offsets[r];
    uint32_t slot[2];
    std::memcpy(slot, row + slot_start, 8);
    std::memcpy(out_chars + out_offsets[r], row + slot[0], slot[1]);
  }
}

}  // extern "C"
