// Intentionally empty: libsrjt_parquet.so is a stub that links libsrjt.so,
// kept so earlier loaders of the footer-only soname keep working — the same
// trick the reference plays with libcudfjni.so (CMakeLists.txt:203-208,
// src/emptyfile.cpp).
