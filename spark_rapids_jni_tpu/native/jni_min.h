// Minimal JNI compile shim.
//
// The build prefers a real <jni.h> (set JAVA_HOME); this header exists so
// the JNI bridge compiles and is unit-testable in images without a JDK.
// Types and the JNINativeInterface slot numbering follow the JNI 6 spec
// (the same table the reference's JNIEXPORT surface is loaded against);
// only the slots this bridge uses are named, the rest are reserved padding
// so the named slots sit at their specification offsets.

#ifndef SRJT_JNI_MIN_H
#define SRJT_JNI_MIN_H

#if defined(__has_include)
#if __has_include(<jni.h>)
#define SRJT_HAVE_REAL_JNI 1
#include <jni.h>
#endif
#endif

#ifndef SRJT_HAVE_REAL_JNI

#include <cstdarg>
#include <cstdint>

extern "C" {

typedef int32_t jint;
typedef int64_t jlong;
typedef int8_t jbyte;
typedef uint8_t jboolean;
typedef uint16_t jchar;
typedef int16_t jshort;
typedef float jfloat;
typedef double jdouble;
typedef jint jsize;

class _jobject {};
typedef _jobject* jobject;
typedef jobject jclass;
typedef jobject jstring;
typedef jobject jarray;
typedef jarray jobjectArray;
typedef jarray jbooleanArray;
typedef jarray jbyteArray;
typedef jarray jintArray;
typedef jarray jlongArray;
typedef jobject jthrowable;

#define JNIEXPORT __attribute__((visibility("default")))
#define JNICALL
#define JNI_TRUE 1
#define JNI_FALSE 0

struct JNINativeInterface_;
typedef const struct JNINativeInterface_* JNIEnv;

// JNI 6 function table.  Named members are at their spec slot numbers
// (comments); padding keeps the layout.
struct JNINativeInterface_ {
  void* reserved0;                                              // 0
  void* reserved1;                                              // 1
  void* reserved2;                                              // 2
  void* reserved3;                                              // 3
  void* pad4_5[2];                                              // 4-5
  jclass (*FindClass)(JNIEnv*, const char*);                    // 6
  void* pad7_13[7];                                             // 7-13
  jint (*ThrowNew)(JNIEnv*, jclass, const char*);               // 14
  jthrowable (*ExceptionOccurred)(JNIEnv*);                     // 15
  void* pad16;                                                  // 16
  void (*ExceptionClear)(JNIEnv*);                              // 17
  void* pad18_168[151];                                         // 18-168
  const char* (*GetStringUTFChars)(JNIEnv*, jstring, jboolean*);   // 169
  void (*ReleaseStringUTFChars)(JNIEnv*, jstring, const char*);    // 170
  jsize (*GetArrayLength)(JNIEnv*, jarray);                        // 171
  void* pad172;                                                    // 172
  jobject (*GetObjectArrayElement)(JNIEnv*, jobjectArray, jsize);  // 173
  void* pad174;                                                    // 174
  void* pad175_178[4];                                             // 175-178
  jintArray (*NewIntArray)(JNIEnv*, jsize);                        // 179
  jlongArray (*NewLongArray)(JNIEnv*, jsize);                      // 180
  void* pad181_182[2];                                             // 181-182
  void* pad183_198[16];                                            // 183-198
  void* pad199_202[4];                                             // 199-202
  void (*GetIntArrayRegion)(JNIEnv*, jintArray, jsize, jsize, jint*);   // 203
  void (*GetLongArrayRegion)(JNIEnv*, jlongArray, jsize, jsize, jlong*);// 204
  void* pad205_210[6];                                             // 205-210
  void (*SetIntArrayRegion)(JNIEnv*, jintArray, jsize, jsize, const jint*);   // 211
  void (*SetLongArrayRegion)(JNIEnv*, jlongArray, jsize, jsize, const jlong*);// 212
  void* pad213_228[16];                                            // 213-228
  jobject (*NewDirectByteBuffer)(JNIEnv*, void*, jlong);           // 229
  void* (*GetDirectBufferAddress)(JNIEnv*, jobject);               // 230
  jlong (*GetDirectBufferCapacity)(JNIEnv*, jobject);              // 231
  void* pad232;                                                    // 232
};

}  // extern "C"

#endif  // !SRJT_HAVE_REAL_JNI
#endif  // SRJT_JNI_MIN_H
