// Device-engine bridge: routes the C/JNI surface onto the TPU path.
//
// The reference's JNI surface drives the CUDA engine directly
// (RowConversionJni.cpp:24-45 → spark_rapids_jni::convert_to_rows).  The
// TPU analog chosen here (SURVEY §7: "C++ core ... or an embedded-runtime
// bridge") is an embedded-Python trampoline: when the process hosts a
// CPython runtime (a PySpark executor, a JVM that initialized one, or the
// test harness), libsrjt forwards a host table handle to
// spark_rapids_jni_tpu.bridge, which reads the table through this same
// library's C accessors, runs the JAX/TPU engine, and imports the packed
// JCUDF bytes back through srjt_rows_import — so bytes entering the JNI
// surface are transcoded by the device engine, with the host C++ engine as
// the fallback tier.
//
// No link-time libpython dependency: the CPython C API is resolved with
// dlsym(RTLD_DEFAULT) at first use, so the .so still loads into a plain
// JVM (srjt_device_available() then reports 0 and callers stay on the
// host engine).

#include <cstdint>
#include <cstdlib>
#include <dlfcn.h>
#include <mutex>

namespace {

// minimal CPython C API surface, resolved dynamically
using PyGILState_Ensure_t = int (*)();
using PyGILState_Release_t = void (*)(int);
using PyImport_ImportModule_t = void* (*)(const char*);
using PyObject_GetAttrString_t = void* (*)(void*, const char*);
using PyObject_CallFunction_t = void* (*)(void*, const char*, ...);
using PyLong_AsLongLong_t = long long (*)(void*);
using PyErr_Occurred_t = void* (*)();
using PyErr_Clear_t = void (*)();
using Py_DecRef_t = void (*)(void*);
using Py_IsInitialized_t = int (*)();

struct PyApi {
  PyGILState_Ensure_t gil_ensure = nullptr;
  PyGILState_Release_t gil_release = nullptr;
  PyImport_ImportModule_t import_module = nullptr;
  PyObject_GetAttrString_t getattr = nullptr;
  PyObject_CallFunction_t call = nullptr;
  PyLong_AsLongLong_t as_longlong = nullptr;
  PyErr_Occurred_t err_occurred = nullptr;
  PyErr_Clear_t err_clear = nullptr;
  Py_DecRef_t decref = nullptr;
  Py_IsInitialized_t is_initialized = nullptr;
  bool ok = false;
};

const PyApi& py_api() {
  static PyApi api;
  static std::once_flag once;
  std::call_once(once, [] {
    void* self = RTLD_DEFAULT;
    api.gil_ensure = reinterpret_cast<PyGILState_Ensure_t>(
        dlsym(self, "PyGILState_Ensure"));
    api.gil_release = reinterpret_cast<PyGILState_Release_t>(
        dlsym(self, "PyGILState_Release"));
    api.import_module = reinterpret_cast<PyImport_ImportModule_t>(
        dlsym(self, "PyImport_ImportModule"));
    api.getattr = reinterpret_cast<PyObject_GetAttrString_t>(
        dlsym(self, "PyObject_GetAttrString"));
    api.call = reinterpret_cast<PyObject_CallFunction_t>(
        dlsym(self, "PyObject_CallFunction"));
    api.as_longlong = reinterpret_cast<PyLong_AsLongLong_t>(
        dlsym(self, "PyLong_AsLongLong"));
    api.err_occurred = reinterpret_cast<PyErr_Occurred_t>(
        dlsym(self, "PyErr_Occurred"));
    api.err_clear = reinterpret_cast<PyErr_Clear_t>(dlsym(self, "PyErr_Clear"));
    api.decref = reinterpret_cast<Py_DecRef_t>(dlsym(self, "Py_DecRef"));
    api.is_initialized = reinterpret_cast<Py_IsInitialized_t>(
        dlsym(self, "Py_IsInitialized"));
    api.ok = api.gil_ensure && api.gil_release && api.import_module
             && api.getattr && api.call && api.as_longlong
             && api.err_occurred && api.err_clear && api.decref
             && api.is_initialized;
  });
  return api;
}

// Runtime kill switch (same convention as the Pallas dispatch's
// SRJT_PALLAS toggle): SRJT_DEVICE=0 forces the host C++ engine even when
// an embedded runtime is reachable — the operator escape hatch for
// non-TPU executors where the "device" path is just slower.
bool device_disabled() {
  const char* v = std::getenv("SRJT_DEVICE");
  return v && v[0] == '0' && v[1] == '\0';
}

// call spark_rapids_jni_tpu.bridge.<fn>(handle) → int64 result handle
void* call_bridge(const char* fn, void* handle, const int32_t* type_ids,
                  const int32_t* scales, int32_t ncols) {
  if (device_disabled()) return nullptr;
  const PyApi& py = py_api();
  if (!py.ok || !py.is_initialized()) return nullptr;
  int gil = py.gil_ensure();
  void* result_handle = nullptr;
  void* mod = py.import_module("spark_rapids_jni_tpu.bridge");
  if (mod) {
    void* f = py.getattr(mod, fn);
    if (f) {
      void* res = type_ids
          ? py.call(f, "LLLl", static_cast<long long>(
                        reinterpret_cast<intptr_t>(handle)),
                    static_cast<long long>(
                        reinterpret_cast<intptr_t>(type_ids)),
                    static_cast<long long>(
                        reinterpret_cast<intptr_t>(scales)),
                    static_cast<long>(ncols))
          : py.call(f, "L", static_cast<long long>(
                        reinterpret_cast<intptr_t>(handle)));
      if (res) {
        long long v = py.as_longlong(res);
        if (!py.err_occurred()) {
          result_handle = reinterpret_cast<void*>(static_cast<intptr_t>(v));
        }
        py.decref(res);
      }
      py.decref(f);
    }
    py.decref(mod);
  }
  if (py.err_occurred()) py.err_clear();
  py.gil_release(gil);
  return result_handle;
}

}  // namespace

extern "C" {

// 1 when an initialized CPython runtime (and thus the JAX device engine)
// is reachable from this process.
int32_t srjt_device_available() {
  if (device_disabled()) return 0;
  const PyApi& py = py_api();
  return (py.ok && py.is_initialized()) ? 1 : 0;
}

// Host table handle → JCUDF RowBatches handle, transcoded by the DEVICE
// engine (JAX/TPU).  Returns nullptr when no runtime is available or the
// engine failed — callers fall back to srjt_to_rows (host engine).
void* srjt_to_rows_device(void* table_handle) {
  return call_bridge("to_rows_from_handle", table_handle, nullptr, nullptr, 0);
}

// JCUDF RowBatches handle (+ schema arrays) → host table handle via the
// device engine.  nullptr on failure — callers fall back to srjt_from_rows.
void* srjt_from_rows_device(void* rows_handle, const int32_t* type_ids,
                            const int32_t* scales, int32_t ncols) {
  return call_bridge("from_rows_from_handle", rows_handle, type_ids, scales,
                     ncols);
}

}  // extern "C"
