"""Loader for the single native artifact ``libsrjt.so``.

All C++ components (Parquet footer engine, host JCUDF transcode engine) are
compiled into one shared library, preserving the reference's packaging
invariant of a single JVM-loadable artifact (``CMakeLists.txt:199-208``).
Built lazily with ``make`` on first use; callers degrade gracefully when no
toolchain is present.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

from ..analysis import sanitize

_NATIVE_DIR = os.path.dirname(__file__)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libsrjt.so")
_lock = sanitize.tracked_lock("native.load")
_lib: Optional[ctypes.CDLL] = None
_tried = False

_c = ctypes


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR, "-s"], check=True,
                       capture_output=True, timeout=300)
        return True
    except (subprocess.SubprocessError, FileNotFoundError):
        return False


def _sig(lib, name, restype, argtypes):
    fn = getattr(lib, name)
    fn.restype = restype
    fn.argtypes = argtypes
    return fn


def _bind(lib: ctypes.CDLL) -> None:
    i32, i64, u64 = _c.c_int32, _c.c_int64, _c.c_uint64
    p_i32 = _c.POINTER(i32)
    p_i64 = _c.POINTER(i64)
    p_u8 = _c.POINTER(_c.c_uint8)
    pp = _c.POINTER(_c.c_void_p)   # generic pointer-array

    # footer engine (parquet/native/footer_engine.cpp)
    _sig(lib, "srjt_footer_read_and_filter", _c.c_void_p,
         [_c.c_char_p, u64, i64, i64, _c.POINTER(_c.c_char_p), p_i32, p_i32,
          i32, i32, i32, _c.c_char_p, u64])
    _sig(lib, "srjt_footer_num_rows", i64, [_c.c_void_p])
    _sig(lib, "srjt_footer_num_columns", i64, [_c.c_void_p])
    _sig(lib, "srjt_footer_serialize", i64,
         [_c.c_void_p, _c.c_char_p, u64, _c.c_char_p, u64])
    _sig(lib, "srjt_footer_free", None, [_c.c_void_p])

    # rowconv engine (native/rowconv_engine.cpp)
    _sig(lib, "srjt_layout", i32,
         [p_i32, p_i32, i32, p_i32, p_i32, p_i32, p_i32])
    _sig(lib, "srjt_pack_fixed", None,
         [pp, pp, p_i32, p_i32, i32, i64, i32, i32, p_u8])
    _sig(lib, "srjt_unpack_fixed", None,
         [p_u8, i64, i32, p_i32, p_i32, i32, i32, pp, pp])
    _sig(lib, "srjt_var_row_offsets", i64, [pp, i32, i64, i32, p_i64])
    _sig(lib, "srjt_pack_var", None,
         [pp, pp, pp, p_i32, p_i32, p_u8, i32, i64, p_i64, i32, i32, p_u8])
    _sig(lib, "srjt_unpack_var", None,
         [p_u8, p_i64, i64, p_i32, p_i32, p_u8, i32, i32, pp, pp, pp])
    _sig(lib, "srjt_gather_chars", None,
         [p_u8, p_i64, i64, i32, p_i32, p_u8])

    # host table / column ABI (native/host_table.cpp) — the single binding
    # site shared by bridge.py and the test suites; keep in sync with
    # cpp declarations in jni_min.h/host_table.cpp
    vp = _c.c_void_p
    _sig(lib, "srjt_column_fixed", vp, [i32, i32, i64, vp, vp])
    _sig(lib, "srjt_column_string", vp, [i64, vp, vp, vp])
    _sig(lib, "srjt_column_free", None, [vp])
    _sig(lib, "srjt_column_type", i32, [vp])
    _sig(lib, "srjt_column_scale", i32, [vp])
    _sig(lib, "srjt_column_rows", i64, [vp])
    _sig(lib, "srjt_column_data", p_u8, [vp])
    _sig(lib, "srjt_column_data_size", i64, [vp])
    _sig(lib, "srjt_column_offsets", p_i32, [vp])
    _sig(lib, "srjt_column_valid", p_u8, [vp])
    _sig(lib, "srjt_table", vp, [pp, i32])
    _sig(lib, "srjt_table_free", None, [vp])
    _sig(lib, "srjt_table_rows", i64, [vp])
    _sig(lib, "srjt_table_cols", i32, [vp])
    _sig(lib, "srjt_table_column", vp, [vp, i32])
    _sig(lib, "srjt_to_rows", vp, [vp])
    # pointer args typed c_void_p: call sites pass numpy .ctypes pointers
    _sig(lib, "srjt_from_rows", vp, [vp, i32, vp, vp, i32])
    _sig(lib, "srjt_debug_set_max_batch_bytes", None, [i64])
    _sig(lib, "srjt_rows_import", vp, [vp, i64, vp, i64])
    _sig(lib, "srjt_rows_import_append", i32, [vp, vp, i64, vp, i64])
    _sig(lib, "srjt_rows_free", None, [vp])
    _sig(lib, "srjt_rows_num_batches", i32, [vp])
    _sig(lib, "srjt_rows_batch_rows", i64, [vp, i32])
    _sig(lib, "srjt_rows_batch_data", p_u8, [vp, i32])
    _sig(lib, "srjt_rows_batch_size", i64, [vp, i32])
    _sig(lib, "srjt_rows_batch_offsets", p_i32, [vp, i32])

    # device bridge (native/device_bridge.cpp)
    _sig(lib, "srjt_device_available", i32, [])
    _sig(lib, "srjt_to_rows_device", vp, [vp])
    _sig(lib, "srjt_from_rows_device", vp, [vp, vp, vp, i32])

    # snappy (native/snappy_native.cpp)
    _sig(lib, "srjt_snappy_decompress", _c.c_long,
         [_c.c_char_p, _c.c_long, _c.c_char_p, _c.c_long])
    _sig(lib, "srjt_byte_array_offsets", _c.c_long,
         [_c.c_char_p, _c.c_long, _c.c_long, vp])


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) libsrjt.so; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH) and not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        try:
            _bind(lib)
        except AttributeError:
            # stale .so predating a newly-bound symbol: rebuild once and
            # retry — crashing every native consumer is not an option.
            # dlopen caches by pathname, so the stale mapping must be
            # dlclosed first or the retry would rebind the old object.
            try:
                ctypes.CDLL(None).dlclose(ctypes.c_void_p(lib._handle))
            except (OSError, AttributeError):
                return None          # cannot unload — stay unavailable
            del lib
            if not _build():
                return None
            try:
                lib = ctypes.CDLL(_LIB_PATH)
                _bind(lib)
            except (OSError, AttributeError):
                return None
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None
