// JNI bridge: the JVM-loadable surface of libsrjt.so.
//
// Equivalent of the reference's L2 bridge (RowConversionJni.cpp:24-66,
// NativeParquetJni.cpp:568-666): unwrap jlong handles, marshal schema
// arrays, translate native failures to Java exceptions, return handles.
// The engines underneath are host_table.cpp (column/table model + JCUDF
// transcode) and footer_engine.cpp (thrift parse/prune/serialize).
//
// Compiles against a real <jni.h> when present, else the jni_min.h shim;
// tests drive these entry points through a ctypes-built mock JNIEnv
// (tests/test_jni_bridge.py), standing in for the reference's JUnit tier.

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

#include "jni_min.h"

#ifdef SRJT_HAVE_REAL_JNI
#define ENV(fn, ...) env->fn(__VA_ARGS__)
#else
#define ENV(fn, ...) (*env)->fn(env, ##__VA_ARGS__)
#endif

extern "C" {

// host_table.cpp
void* srjt_table(void* const* cols, int32_t ncols);
void srjt_table_free(void* h);
int64_t srjt_table_rows(void* h);
int32_t srjt_table_cols(void* h);
void* srjt_table_column(void* h, int32_t i);
void* srjt_column_fixed(int32_t type_id, int32_t scale, int64_t n_rows,
                        const uint8_t* data, const uint8_t* valid);
void* srjt_column_string(int64_t n_rows, const int32_t* offsets,
                         const uint8_t* chars, const uint8_t* valid);
void srjt_column_free(void* h);
int64_t srjt_column_rows(void* h);
const uint8_t* srjt_column_data(void* h);
int64_t srjt_column_data_size(void* h);
const int32_t* srjt_column_offsets(void* h);
const uint8_t* srjt_column_valid(void* h);
void* srjt_to_rows(void* table);
void* srjt_rows_import(const uint8_t* data, int64_t size,
                       const int32_t* offsets, int64_t n_rows);
void* srjt_from_rows(void* rows, int32_t batch, const int32_t* type_ids,
                     const int32_t* scales, int32_t ncols);
void srjt_rows_free(void* h);

// device_bridge.cpp
int32_t srjt_device_available();
void* srjt_to_rows_device(void* table);
void* srjt_from_rows_device(void* rows, const int32_t* type_ids,
                            const int32_t* scales, int32_t ncols);

// footer_engine.cpp
void* srjt_footer_read_and_filter(const uint8_t* buf, uint64_t len,
                                  int64_t part_offset, int64_t part_length,
                                  const char** names,
                                  const int32_t* num_children,
                                  const int32_t* tags, int32_t n,
                                  int32_t parent_num_children,
                                  int32_t ignore_case, char* err,
                                  uint64_t err_len);
int64_t srjt_footer_num_rows(void* h);
int64_t srjt_footer_num_columns(void* h);
int64_t srjt_footer_serialize(void* h, uint8_t* out, uint64_t cap, char* err,
                              uint64_t err_len);
void srjt_footer_free(void* h);

namespace {

void throw_java(JNIEnv* env, const char* cls, const char* msg) {
  jclass c = ENV(FindClass, cls);
  if (c) ENV(ThrowNew, c, msg);
}

#define THROW_ILLEGAL(env, msg)                                  \
  do {                                                           \
    throw_java(env, "java/lang/IllegalArgumentException", msg);  \
    return 0;                                                    \
  } while (0)

}  // namespace

// ---- com.tpu.rapids.jni.HostColumn ---------------------------------------

JNIEXPORT jlong JNICALL Java_com_tpu_rapids_jni_HostColumn_makeFixed(
    JNIEnv* env, jclass, jint type_id, jint scale, jlong n_rows,
    jlong data_addr, jlong valid_addr) {
  void* h = srjt_column_fixed(type_id, scale, n_rows,
                              reinterpret_cast<const uint8_t*>(data_addr),
                              reinterpret_cast<const uint8_t*>(valid_addr));
  if (!h) THROW_ILLEGAL(env, "unsupported fixed-width column");
  return reinterpret_cast<jlong>(h);
}

JNIEXPORT jlong JNICALL Java_com_tpu_rapids_jni_HostColumn_makeString(
    JNIEnv* env, jclass, jlong n_rows, jlong offsets_addr, jlong chars_addr,
    jlong valid_addr) {
  void* h = srjt_column_string(
      n_rows, reinterpret_cast<const int32_t*>(offsets_addr),
      reinterpret_cast<const uint8_t*>(chars_addr),
      reinterpret_cast<const uint8_t*>(valid_addr));
  if (!h) THROW_ILLEGAL(env, "bad string column buffers");
  return reinterpret_cast<jlong>(h);
}

JNIEXPORT void JNICALL Java_com_tpu_rapids_jni_HostColumn_close(
    JNIEnv*, jclass, jlong handle) {
  srjt_column_free(reinterpret_cast<void*>(handle));
}

// Readback surface (the reference verifies through cudf's copy-to-host
// accessors; these expose the same via the srjt C API).

JNIEXPORT jlong JNICALL Java_com_tpu_rapids_jni_HostColumn_rows(
    JNIEnv*, jclass, jlong handle) {
  return srjt_column_rows(reinterpret_cast<void*>(handle));
}

JNIEXPORT jlong JNICALL Java_com_tpu_rapids_jni_HostColumn_dataSize(
    JNIEnv*, jclass, jlong handle) {
  return srjt_column_data_size(reinterpret_cast<void*>(handle));
}

JNIEXPORT jlong JNICALL Java_com_tpu_rapids_jni_HostColumn_dataAddress(
    JNIEnv*, jclass, jlong handle) {
  return reinterpret_cast<jlong>(
      srjt_column_data(reinterpret_cast<void*>(handle)));
}

JNIEXPORT jlong JNICALL Java_com_tpu_rapids_jni_HostColumn_offsetsAddress(
    JNIEnv*, jclass, jlong handle) {
  return reinterpret_cast<jlong>(
      srjt_column_offsets(reinterpret_cast<void*>(handle)));
}

JNIEXPORT jlong JNICALL Java_com_tpu_rapids_jni_HostColumn_validAddress(
    JNIEnv*, jclass, jlong handle) {
  return reinterpret_cast<jlong>(
      srjt_column_valid(reinterpret_cast<void*>(handle)));
}

// ---- com.tpu.rapids.jni.HostTable ----------------------------------------

JNIEXPORT jlong JNICALL Java_com_tpu_rapids_jni_HostTable_makeTable(
    JNIEnv* env, jclass, jlongArray col_handles) {
  jsize n = ENV(GetArrayLength, col_handles);
  std::vector<jlong> handles(n);
  ENV(GetLongArrayRegion, col_handles, 0, n, handles.data());
  std::vector<void*> cols;
  cols.reserve(n);
  for (jlong h : handles) cols.push_back(reinterpret_cast<void*>(h));
  void* t = srjt_table(cols.data(), n);
  if (!t) THROW_ILLEGAL(env, "mismatched column row counts");
  return reinterpret_cast<jlong>(t);
}

JNIEXPORT jlong JNICALL Java_com_tpu_rapids_jni_HostTable_rowCount(
    JNIEnv*, jclass, jlong handle) {
  return srjt_table_rows(reinterpret_cast<void*>(handle));
}

JNIEXPORT jlongArray JNICALL Java_com_tpu_rapids_jni_HostTable_columns(
    JNIEnv* env, jclass, jlong handle) {
  // release each column as its own handle into a jlongArray — the
  // convert_table_for_return protocol (RowConversionJni.cpp:33-38)
  void* t = reinterpret_cast<void*>(handle);
  int32_t n = srjt_table_cols(t);
  std::vector<jlong> out(n);
  for (int32_t i = 0; i < n; ++i) {
    out[i] = reinterpret_cast<jlong>(srjt_table_column(t, i));
  }
  jlongArray arr = ENV(NewLongArray, n);
  if (arr) ENV(SetLongArrayRegion, arr, 0, n, out.data());
  return arr;
}

JNIEXPORT void JNICALL Java_com_tpu_rapids_jni_HostTable_close(
    JNIEnv*, jclass, jlong handle) {
  srjt_table_free(reinterpret_cast<void*>(handle));
}

// ---- com.tpu.rapids.jni.RowConversion ------------------------------------

JNIEXPORT jlong JNICALL Java_com_tpu_rapids_jni_RowConversion_convertToRows(
    JNIEnv* env, jclass, jlong table_handle) {
  // device engine first (the reference's JNI drives its device engine
  // directly, RowConversionJni.cpp:24-45); host C++ engine is the
  // staging/fallback tier when no runtime (or the device path fails)
  void* rows = srjt_to_rows_device(reinterpret_cast<void*>(table_handle));
  if (!rows) rows = srjt_to_rows(reinterpret_cast<void*>(table_handle));
  if (!rows)
    THROW_ILLEGAL(env,
                  "Row size exceeds JCUDF 1KB limit or unsupported schema "
                  "(RowConversion.java:98-99)");
  return reinterpret_cast<jlong>(rows);
}

JNIEXPORT jlong JNICALL Java_com_tpu_rapids_jni_RowConversion_importRows(
    JNIEnv*, jclass, jlong data_addr, jlong data_size, jlong offsets_addr,
    jlong n_rows) {
  return reinterpret_cast<jlong>(
      srjt_rows_import(reinterpret_cast<const uint8_t*>(data_addr), data_size,
                       reinterpret_cast<const int32_t*>(offsets_addr),
                       n_rows));
}

JNIEXPORT jlong JNICALL Java_com_tpu_rapids_jni_RowConversion_convertFromRows(
    JNIEnv* env, jclass, jlong rows_handle, jint batch, jintArray type_ids,
    jintArray scales) {
  jsize n = ENV(GetArrayLength, type_ids);
  std::vector<jint> types(n), scl(n);
  ENV(GetIntArrayRegion, type_ids, 0, n, types.data());
  if (scales) ENV(GetIntArrayRegion, scales, 0, n, scl.data());
  void* t = nullptr;
  if (batch == 0) {   // device engine decodes batch 0 (one-batch contract)
    t = srjt_from_rows_device(reinterpret_cast<void*>(rows_handle),
                              types.data(), scales ? scl.data() : nullptr, n);
  }
  if (!t)
    t = srjt_from_rows(reinterpret_cast<void*>(rows_handle), batch,
                       types.data(), scales ? scl.data() : nullptr, n);
  if (!t) THROW_ILLEGAL(env, "bad batch index or unsupported schema");
  return reinterpret_cast<jlong>(t);
}

JNIEXPORT void JNICALL Java_com_tpu_rapids_jni_RowConversion_freeRows(
    JNIEnv*, jclass, jlong rows_handle) {
  srjt_rows_free(reinterpret_cast<void*>(rows_handle));
}

// ---- com.tpu.rapids.jni.ParquetFooter ------------------------------------

JNIEXPORT jlong JNICALL Java_com_tpu_rapids_jni_ParquetFooter_readAndFilter(
    JNIEnv* env, jclass, jlong buffer_addr, jlong buffer_len,
    jlong part_offset, jlong part_length, jobjectArray names,
    jintArray num_children, jintArray tags, jint parent_num_children,
    jboolean ignore_case) {
  jsize n = ENV(GetArrayLength, names);
  std::vector<std::string> name_strs;
  name_strs.reserve(n);
  for (jsize i = 0; i < n; ++i) {
    jstring s = static_cast<jstring>(ENV(GetObjectArrayElement, names, i));
    const char* c = ENV(GetStringUTFChars, s, nullptr);
    name_strs.emplace_back(c ? c : "");
    if (c) ENV(ReleaseStringUTFChars, s, c);
  }
  std::vector<const char*> name_ptrs;
  for (const auto& s : name_strs) name_ptrs.push_back(s.c_str());
  std::vector<jint> nc(n), tg(n);
  ENV(GetIntArrayRegion, num_children, 0, n, nc.data());
  ENV(GetIntArrayRegion, tags, 0, n, tg.data());

  char err[512] = {0};
  void* h = srjt_footer_read_and_filter(
      reinterpret_cast<const uint8_t*>(buffer_addr),
      static_cast<uint64_t>(buffer_len), part_offset, part_length,
      name_ptrs.data(), nc.data(), tg.data(), n, parent_num_children,
      ignore_case ? 1 : 0, err, sizeof(err));
  if (!h) {
    throw_java(env, "java/lang/RuntimeException",
               err[0] ? err : "failed to parse parquet footer");
    return 0;
  }
  return reinterpret_cast<jlong>(h);
}

JNIEXPORT jlong JNICALL Java_com_tpu_rapids_jni_ParquetFooter_getNumRows(
    JNIEnv*, jclass, jlong handle) {
  return srjt_footer_num_rows(reinterpret_cast<void*>(handle));
}

JNIEXPORT jlong JNICALL Java_com_tpu_rapids_jni_ParquetFooter_getNumColumns(
    JNIEnv*, jclass, jlong handle) {
  return srjt_footer_num_columns(reinterpret_cast<void*>(handle));
}

JNIEXPORT jlong JNICALL
Java_com_tpu_rapids_jni_ParquetFooter_serializeThriftFile(
    JNIEnv* env, jclass, jlong handle, jlong out_addr, jlong out_cap) {
  char err[512] = {0};
  int64_t written = srjt_footer_serialize(
      reinterpret_cast<void*>(handle), reinterpret_cast<uint8_t*>(out_addr),
      static_cast<uint64_t>(out_cap), err, sizeof(err));
  if (written < 0) {
    throw_java(env, "java/lang/RuntimeException",
               err[0] ? err : "failed to serialize footer");
    return 0;
  }
  return written;
}

JNIEXPORT void JNICALL Java_com_tpu_rapids_jni_ParquetFooter_close(
    JNIEnv*, jclass, jlong handle) {
  srjt_footer_free(reinterpret_cast<void*>(handle));
}

}  // extern "C"
