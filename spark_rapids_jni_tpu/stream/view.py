"""Materialized-view registry: incremental maintenance over append-only facts.

Views are keyed on the optimized plan's structural fingerprint
(``plan/ir.fingerprint``).  At registration the optimized tree is
classified:

* **incremental** — a (Sort/Limit/Filter)* tail over ONE keyed
  Aggregate/FusedJoinAggregate whose pre-aggregate tree is *linear in the
  fact table*: built from Scan/Filter/Project/Join only, references the
  fact exactly once, every other scanned table is a registered
  epoch-stable static (dimension), and joins are inner (fact on either
  side) or left with the fact on the left.  Linearity means the
  pre-aggregate relation of (base + delta) is the base relation plus the
  pre-aggregate relation of the delta alone — so a refresh executes the
  pre-tree over ONLY the appended row groups and merges partial aggregate
  states (``ops.groupby.merge_aggregate_states``).  By default every
  aggregate must also be merge-*exact* (``ops.groupby.merge_exact``) so
  refreshed results stay bit-identical to a full recompute;
  ``SRJT_STREAM_ALLOW_APPROX=1`` admits float sums/means and M2-merged
  var/std (numerically stable, not bit-exact).

* **full** — anything else (window/rollup shapes, grand totals,
  non-mergeable or non-exact aggregates, outer joins the delta algebra
  cannot split).  Refresh recomputes from scratch; the classifier reason
  lands on the ``stream.view.fallback`` counter and flight-recorder
  stream so ops can see *why* a view is not O(delta).

Running states live as ordinary device tables registered with the HBM
arena's spill layer (``memory/spill.register_table``): under budget
pressure a cold view's state host-spills and faults back bit-exactly on
its next refresh.
"""

from __future__ import annotations

import os
from typing import Optional

from ..analysis import sanitize
from ..column import Table
from ..memory import spill as mspill
from ..ops import apply_boolean_mask, slice_table, sort_table
from ..ops import groupby as G
from ..plan import ir, lower, rules
from ..plan import stats as plan_stats
from ..utils import flight, knobs, metrics
from .delta import DeltaTable, Watermark

_PRE_NODES = (ir.Scan, ir.Filter, ir.Project, ir.Join)
_POST_NODES = (ir.Sort, ir.Limit, ir.Filter)


def _allow_approx_default() -> bool:
    return knobs.get("SRJT_STREAM_ALLOW_APPROX")


class MaterializedView:
    """One registered view: optimized tree + (for incremental views) the
    running aggregate state and its fact watermark."""

    __slots__ = ("name", "tree", "fingerprint", "kind", "reason", "post",
                 "pre", "keys", "aggs", "names", "key_idx", "agg_pairs",
                 "spec", "state", "watermark", "epoch", "lock",
                 "refreshes", "exact")

    def __init__(self, name: str, tree: ir.Plan, fingerprint: str):
        self.name = name
        self.tree = tree
        self.fingerprint = fingerprint
        self.kind = "full"
        self.reason: Optional[str] = None
        self.post: tuple = ()
        self.pre: Optional[ir.Plan] = None
        self.keys: tuple = ()
        self.aggs: tuple = ()
        self.names: list[str] = []
        self.key_idx: list[int] = []
        self.agg_pairs: list[tuple[int, str]] = []
        self.spec = None
        self.state: Optional[Table] = None
        self.watermark: Optional[Watermark] = None
        self.epoch = 0
        self.lock = sanitize.tracked_lock("stream.view")
        self.refreshes = 0
        self.exact = False


class ViewRegistry:
    """Registry of materialized views over ONE append-only fact table plus
    epoch-stable static (dimension) tables."""

    def __init__(self, delta: DeltaTable, statics: dict[str, Table],
                 schemas: dict[str, list[str]],
                 allow_approx: Optional[bool] = None):
        self.delta = delta
        self.statics = dict(statics)
        self.schemas = {k: list(v) for k, v in schemas.items()
                        if k in self.statics}
        self.schemas[delta.name] = delta.schema()
        self.allow_approx = (_allow_approx_default() if allow_approx is None
                             else bool(allow_approx))
        self._mu = sanitize.tracked_lock("stream.view_registry")
        self._by_fp: dict[str, MaterializedView] = {}
        self._by_name: dict[str, MaterializedView] = {}
        self._listeners: list = []
        self._fallbacks = 0
        self._probe = f"stream.views:{delta.name}"
        flight.register_probe(self._probe, self.stats)

    def close(self) -> None:
        flight.unregister_probe(self._probe)

    def stats(self) -> dict:
        with self._mu:
            views = list(self._by_fp.values())
            fallbacks = self._fallbacks
        return {
            "views": len(views),
            "incremental": sum(1 for v in views if v.kind == "incremental"),
            "full": sum(1 for v in views if v.kind == "full"),
            "fallbacks": fallbacks,
            "refreshes": sum(v.refreshes for v in views),
            "epoch": self.delta.epoch,
        }

    # -- registration -------------------------------------------------------

    def register_view(self, plan: ir.Plan,
                      name: Optional[str] = None) -> MaterializedView:
        res = rules.optimize(plan, self.schemas, stats=plan_stats.GLOBAL)
        tree = res.tree
        fp = ir.fingerprint(tree)
        with self._mu:
            got = self._by_fp.get(fp)
        if got is not None:
            return got
        v = MaterializedView(name or f"view:{fp[:12]}", tree, fp)
        self._classify(v)
        if v.kind == "incremental":
            self._rebuild_state(v)
        else:
            self._fallback(v, at="register")
        if metrics.recording():
            metrics.count("stream.view.registered")
        with self._mu:
            # registration raced: first one in wins, state and all
            prior = self._by_fp.get(fp)
            if prior is not None:
                return prior
            self._by_fp[fp] = v
            self._by_name[v.name] = v
        return v

    def resolve(self, view) -> MaterializedView:
        if isinstance(view, MaterializedView):
            return view
        with self._mu:
            got = self._by_name.get(view) or self._by_fp.get(view)
        if got is None:
            raise KeyError(f"unknown view {view!r}")
        return got

    def views(self) -> list[MaterializedView]:
        with self._mu:
            return list(self._by_fp.values())

    def delta_bytes(self, view) -> int:
        """Admission estimate for a refresh: compressed bytes of the
        not-yet-consumed row groups (incremental) or the whole fact table
        (full recompute)."""
        v = self.resolve(view)
        since = v.watermark if v.kind == "incremental" else None
        return max(int(self.delta.delta_bytes(since)), 1)

    # -- classification -----------------------------------------------------

    def _classify(self, v: MaterializedView) -> None:
        node, post = v.tree, []
        while isinstance(node, _POST_NODES) and not isinstance(node, ir.Scan):
            post.append(node)
            node = node.child
        if isinstance(node, ir.Aggregate):
            pre = node.child
        elif isinstance(node, ir.FusedJoinAggregate):
            pre = ir.Join(node.left, node.right, node.left_on,
                          node.right_on, how=node.how)
        else:
            v.reason = f"shape:{type(node).__name__}"
            return
        if not node.keys:
            v.reason = "grand_total"     # empty-input null semantics differ
            return
        fact = self.delta.name
        fact_scans = 0
        for sub in ir.walk(pre):
            if not isinstance(sub, _PRE_NODES):
                v.reason = f"pre_node:{type(sub).__name__}"
                return
            if isinstance(sub, ir.Scan):
                if sub.table == fact:
                    fact_scans += 1
                elif sub.table not in self.statics:
                    v.reason = f"non_static:{sub.table}"
                    return
            elif isinstance(sub, ir.Join):
                if sub.how == "inner":
                    continue
                if sub.how == "left":
                    # delta algebra needs the fact (the only growing
                    # input) on the preserved side
                    if any(isinstance(s, ir.Scan) and s.table == fact
                           for s in ir.walk(sub.right)):
                        v.reason = "left_join_fact_on_right"
                        return
                else:
                    v.reason = f"join:{sub.how}"
                    return
        if fact_scans != 1:
            v.reason = f"fact_scans:{fact_scans}"
            return
        names = list(ir.schema_of(pre, self.schemas))
        dtypes = {}
        try:
            for col, fn, _out in node.aggs:
                if fn not in G.MERGEABLE_AGGS:
                    v.reason = f"agg:{fn}"
                    return
                vi = names.index(col)
                dtypes[vi] = self._dtype_of(col)
                if not self.allow_approx and not G.merge_exact(fn,
                                                               dtypes[vi]):
                    v.reason = f"approx:{fn}({col})"
                    return
            spec = G.plan_aggregate_states(
                [(names.index(c), fn) for c, fn, _ in node.aggs],
                dtypes, len(node.keys))
        except (NotImplementedError, ValueError, KeyError) as e:
            v.reason = f"state_plan:{e}"
            return
        v.kind = "incremental"
        v.post = tuple(post)
        v.pre = pre
        v.keys = tuple(node.keys)
        v.aggs = tuple(node.aggs)
        v.names = names
        v.key_idx = [names.index(k) for k in node.keys]
        v.agg_pairs = [(names.index(c), fn) for c, fn, _ in node.aggs]
        v.spec = spec
        v.exact = spec.exact

    def _dtype_of(self, col: str):
        for tname, cols in self.schemas.items():
            if col in cols:
                if tname == self.delta.name:
                    return self.delta.column_dtype(col)
                return self.statics[tname][cols.index(col)].dtype
        raise KeyError(col)

    # -- refresh ------------------------------------------------------------

    def add_refresh_listener(self, fn) -> None:
        """Register ``fn(view, table)`` to run after every successful
        refresh, OUTSIDE the view's refresh lock (the online-feature-store
        hook — ``ml/serve.FeatureView`` re-packs here).  Listener errors
        are recorded to the flight buffer, never propagated into refresh."""
        with self._mu:
            self._listeners.append(fn)

    def remove_refresh_listener(self, fn) -> None:
        with self._mu:
            if fn in self._listeners:
                self._listeners.remove(fn)

    def _notify_refresh(self, v: MaterializedView, table: Table) -> None:
        with self._mu:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(v, table)
            except Exception as e:                     # noqa: BLE001
                flight.record("stream.refresh.listener_error",
                              view=v.name, error=repr(e))

    def refresh(self, view) -> Table:
        """Bring the view up to the fact table's current epoch and return
        its result (post-aggregate Sort/Filter/Limit applied)."""
        v = self.resolve(view)
        out = self._refresh_locked(v)
        self._notify_refresh(v, out)
        return out

    def _refresh_locked(self, v: MaterializedView) -> Table:
        with v.lock:
            with metrics.span("stream.refresh", view=v.name, kind=v.kind):
                v.refreshes += 1
                if v.kind != "incremental":
                    if metrics.recording():
                        metrics.count("stream.refresh.full")
                    return self._execute_full(v)
                cur = self.delta.watermark()
                wm = v.watermark
                if wm is None or len(cur) < len(wm) \
                        or any(c < w for c, w in zip(cur, wm)):
                    # watermark no longer a prefix of the file layout —
                    # should be impossible through the DeltaTable API;
                    # recover by rebuilding rather than serving wrong rows
                    flight.incident("stream_watermark_regression",
                                    view=v.name, watermark=list(wm or ()),
                                    current=list(cur))
                    self._fallback(v, at="refresh")
                    self._rebuild_state(v)
                elif cur != wm:
                    delta_rel = lower.execute(
                        v.pre, _StreamCatalog(self, since=wm, until=cur),
                        record_stats=False)
                    dstate = G.partial_aggregate_states(
                        delta_rel, v.key_idx, v.agg_pairs, spec=v.spec)
                    v.state = G.merge_aggregate_states(v.spec, v.state,
                                                       dstate)
                    mspill.register_table(v.state, "stream.view_state")
                    v.watermark = cur
                    v.epoch = self.delta.epoch
                    if metrics.recording():
                        metrics.count("stream.refresh.incremental")
                        metrics.annotate(delta_rows=delta_rel.num_rows,
                                         state_rows=v.state.num_rows)
                else:
                    if metrics.recording():
                        metrics.count("stream.refresh.noop")
                out = G.finalize_aggregate_states(v.spec, v.state)
                return self._apply_post(v, out)

    def _rebuild_state(self, v: MaterializedView) -> None:
        cur = self.delta.watermark()
        rel = lower.execute(v.pre, _StreamCatalog(self, since=None,
                                                  until=cur),
                            record_stats=False)
        v.state = G.partial_aggregate_states(rel, v.key_idx, v.agg_pairs,
                                             spec=v.spec)
        mspill.register_table(v.state, "stream.view_state")
        v.watermark = cur
        v.epoch = self.delta.epoch

    def _execute_full(self, v: MaterializedView) -> Table:
        return lower.execute(v.tree, _StreamCatalog(self, since=None,
                                                    until=None),
                             record_stats=False)

    def _apply_post(self, v: MaterializedView, t: Table) -> Table:
        # mirrors lower._execute's Sort/Filter/Limit lowering exactly so
        # the refreshed result is bit-identical to executing the tree
        names = list(v.keys) + [a[2] for a in v.aggs]
        for node in reversed(v.post):
            if isinstance(node, ir.Filter):
                t = apply_boolean_mask(
                    t, lower.eval_mask(node.predicate, t, names))
            elif isinstance(node, ir.Sort):
                asc = None if node.ascending is None else list(node.ascending)
                t = sort_table(t, [names.index(k) for k in node.keys],
                               ascending=asc)
            elif isinstance(node, ir.Limit):
                t = slice_table(t, 0, node.n)
        return t

    def _fallback(self, v: MaterializedView, at: str) -> None:
        with self._mu:
            self._fallbacks += 1
        if metrics.recording():
            metrics.count("stream.view.fallback")
        flight.record("stream.view.fallback", view=v.name, at=at,
                      reason=v.reason)


class _StreamCatalog:
    """Catalog routing fact scans through the DeltaTable's row-group
    window and static scans through identity-preserving column selection
    (so dimension build-index caches keep hitting across refreshes)."""

    def __init__(self, registry: ViewRegistry, since: Optional[Watermark],
                 until: Optional[Watermark]):
        self._r = registry
        self._since = since
        self._until = until

    @property
    def schemas(self) -> dict[str, list[str]]:
        return self._r.schemas

    def scan(self, node: ir.Scan) -> tuple[Table, list[str]]:
        r = self._r
        if node.table == r.delta.name:
            full = r.schemas[node.table]
            cols = list(node.columns) if node.columns is not None \
                else list(full)
            t = r.delta.scan(
                columns=cols,
                rowgroup_predicate=lower.rowgroup_conditions(node.predicate),
                since=self._since, until=self._until)
            if metrics.recording() and len(cols) < len(full):
                metrics.count("plan.scan.columns_pruned",
                              len(full) - len(cols))
            return t, cols
        t = r.statics[node.table]
        names = r.schemas[node.table]
        if node.columns is None:
            return t, list(names)
        return (Table([t[names.index(c)] for c in node.columns]),
                list(node.columns))
